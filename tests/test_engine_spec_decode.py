# Speculative decode path: the prompt-lookup draft index (host-side,
# fast) and the engine's multi-token verify dispatch (CPU e2e, slow
# lane) — greedy speculation must be bit-identical to the vanilla
# decode path, and the copy-heavy fixture must clear >= 2 tokens per
# weight pass.
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from copilot_for_consensus_tpu.engine.tokenizer import NgramDraftIndex


# ---------------------------------------------------------------------------
# draft index (pure host state, no jax)
# ---------------------------------------------------------------------------


def test_draft_returns_continuation_of_matched_ngram():
    idx = NgramDraftIndex([1, 2, 3, 4, 5, 1, 2, 3])
    assert idx.draft(4) == [4, 5, 1, 2]


def test_draft_prefers_longest_ngram():
    # tail (8, 2, 3): the 3-gram occurred once (followed by 9); the
    # 2-gram (2, 3) also occurred earlier followed by 4 — the 3-gram
    # match must win.
    idx = NgramDraftIndex([1, 2, 3, 4, 8, 2, 3, 9, 7, 8, 2, 3])
    assert idx.draft(1) == [9]


def test_draft_falls_back_to_min_ngram():
    idx = NgramDraftIndex([1, 2, 3, 4, 9, 9, 2, 3])
    assert idx.draft(2) == [4, 9]      # only the 2-gram (2, 3) matches


def test_draft_earliest_occurrence_wins_for_longest_span():
    # (1, 2) occurs at the start and at the tail; the earliest
    # continuation remembers the longer copyable span.
    idx = NgramDraftIndex([1, 2, 7, 8, 9, 1, 2], min_ngram=2, ngram=2)
    assert idx.draft(3) == [7, 8, 9]


def test_tail_never_matches_itself():
    # the context's own final n-gram has no continuation and must not
    # be indexed (a self-match would return an empty draft forever)
    idx = NgramDraftIndex([5, 6, 7])
    assert idx.draft(4) == []
    idx.extend([8])
    assert idx.draft(4) == []          # still no repeated n-gram


def test_incremental_extend_equals_bulk_build():
    toks = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 1, 4, 1, 9, 2, 6]
    bulk = NgramDraftIndex(toks)
    inc = NgramDraftIndex(toks[:5])
    for t in toks[5:]:
        inc.extend([t])
    assert bulk.draft(8) == inc.draft(8)
    assert len(bulk) == len(inc)


def test_draft_truncates_to_max_tokens():
    idx = NgramDraftIndex([1, 2, 3, 4, 5, 6, 7, 1, 2])
    assert idx.draft(2) == [3, 4]
    assert idx.draft(0) == []


def test_rejects_bad_ngram_bounds():
    with pytest.raises(ValueError):
        NgramDraftIndex([], ngram=1, min_ngram=2)


# ---------------------------------------------------------------------------
# engine end-to-end (CPU, slow lane)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestSpecDecodeEndToEnd:
    """The verify dispatch against the real engine on CPU.

    Two fixtures: random tiny weights (mixed accept/reject traffic —
    exercises rewind) and a crafted copy-cycle model whose greedy
    continuation is exactly periodic, so prompt-lookup drafts are
    always right and the weight-pass amortization is measurable
    deterministically (no reliance on what random weights happen to
    generate)."""

    def _engines(self, params, cfg, **spec_kw):
        from copilot_for_consensus_tpu.engine.generation import (
            GenerationEngine,
        )

        kw = dict(num_slots=4, max_len=256, prefill_buckets=(32, 64),
                  dtype=jnp.float32, attn_impl="xla", decode_window=4)
        kw.update(spec_kw.pop("engine_kw", {}))
        return (GenerationEngine(cfg, params, **kw),
                GenerationEngine(cfg, params, spec_decode=True,
                                 spec_draft_lens=(0, 4, 8), **kw,
                                 **spec_kw))

    def _random_setup(self):
        from copilot_for_consensus_tpu.models import decoder
        from copilot_for_consensus_tpu.models.configs import decoder_config

        cfg = decoder_config("tiny")
        params = decoder.init_params(jax.random.PRNGKey(7), cfg,
                                     dtype=jnp.float32)
        return cfg, params

    def _copy_cycle_setup(self, period=7):
        """Zero the attention/FFN outputs and craft one-hot embeddings
        + lm_head so greedy generation is the deterministic cycle
        t -> 3 + ((t - 3 + 1) % period): the model 'copies' forever,
        which is the best case prompt-lookup drafting targets."""
        from copilot_for_consensus_tpu.models import decoder
        from copilot_for_consensus_tpu.models.configs import decoder_config

        cfg = decoder_config("tiny")
        params = decoder.init_params(jax.random.PRNGKey(7), cfg,
                                     dtype=jnp.float32)
        params["layers"]["wo"] = jnp.zeros_like(params["layers"]["wo"])
        params["layers"]["w_down"] = jnp.zeros_like(
            params["layers"]["w_down"])
        emb = np.zeros((cfg.vocab_size, cfg.d_model), np.float32)
        head = np.zeros((cfg.d_model, cfg.vocab_size), np.float32)
        for i in range(period):
            emb[3 + i, i] = 1.0
            head[i, 3 + (i + 1) % period] = 1.0
        params["tok_emb"] = jnp.asarray(emb)
        params["lm_head"] = jnp.asarray(head)
        prompt = [3 + (i % period) for i in range(2 * period)]
        return cfg, params, prompt

    def test_greedy_bit_identical_on_random_weights(self):
        cfg, params = self._random_setup()
        base, spec = self._engines(params, cfg)
        prompts = [[5, 9, 13, 5, 9, 13, 5, 9],
                   [40, 41, 42, 43, 44, 45, 46],
                   list(np.arange(20) % 7 + 3)]
        want = base.generate(prompts, max_new_tokens=24)
        got = spec.generate(prompts, max_new_tokens=24)
        for w, g in zip(want, got):
            assert g.tokens == w.tokens
            assert g.finish_reason == w.finish_reason

    def test_copy_heavy_fixture_bit_identical_and_amortized(self):
        """The acceptance fixture: greedy speculation-on output equals
        speculation-off bit for bit, AND the measured per-stream
        tokens_per_weight_pass clears 2.0 — the decode bandwidth wall
        actually moved."""
        cfg, params, prompt = self._copy_cycle_setup()
        base, spec = self._engines(params, cfg)
        want = base.generate([prompt], max_new_tokens=64)[0]
        got = spec.generate([prompt], max_new_tokens=64)[0]
        assert got.tokens == want.tokens
        assert len(got.tokens) == 64
        st = spec.spec_stats()
        assert st["enabled"]
        assert st["draft_hit_rate"] > 0.9
        assert st["verify_dispatches"] > 0
        assert st["mean_accepted_per_step"] >= 2.0
        assert st["tokens_per_weight_pass"] >= 2.0, st

    def test_mixed_wave_hit_and_miss_slots_stay_exact(self):
        """Streams with and without draft hits share verify dispatches
        (the k=0 lane); nobody's tokens may change."""
        cfg, params, prompt = self._copy_cycle_setup()
        base, spec = self._engines(params, cfg)
        prompts = [prompt, [200, 201, 202, 203]]   # cycle + no-repeat
        want = base.generate(prompts, max_new_tokens=32)
        got = spec.generate(prompts, max_new_tokens=32)
        for w, g in zip(want, got):
            assert g.tokens == w.tokens

    def test_sampled_speculation_reproducible_and_in_vocab(self):
        """The sampled verify path (rejection rule) is seed-stable and
        emits valid tokens; distribution-exactness itself is proven at
        the verify_draft level (test_engine_sampling.py)."""
        from copilot_for_consensus_tpu.engine.sampling import (
            SamplingConfig,
        )

        cfg, params, prompt = self._copy_cycle_setup()
        outs = []
        for _ in range(2):
            _, spec = self._engines(
                params, cfg,
                engine_kw=dict(
                    num_slots=4, max_len=256, prefill_buckets=(32, 64),
                    dtype=jnp.float32, attn_impl="xla", decode_window=4,
                    sampling=SamplingConfig(temperature=0.8, top_k=20),
                    seed=3))
            outs.append(spec.generate([prompt],
                                      max_new_tokens=24)[0].tokens)
        assert outs[0] == outs[1]
        assert all(0 <= t < cfg.vocab_size for t in outs[0])
        assert len(outs[0]) == 24

    def test_rewind_after_rejection_keeps_later_steps_exact(self):
        """Force heavy rejection: prompts whose repeated n-grams draft
        the WRONG continuation for a random-weights model. Every
        rejected draft rewinds the slot length pointer; subsequent
        tokens must still match the vanilla engine exactly."""
        cfg, params = self._random_setup()
        base, spec = self._engines(params, cfg)
        rng = np.random.default_rng(5)
        span = rng.integers(3, cfg.vocab_size, size=6).tolist()
        prompts = [span * 4, (span + [7]) * 3]
        want = base.generate(prompts, max_new_tokens=32)
        got = spec.generate(prompts, max_new_tokens=32)
        for w, g in zip(want, got):
            assert g.tokens == w.tokens
        st = spec.spec_stats()
        assert st["hits"] > 0                 # drafts were attempted
