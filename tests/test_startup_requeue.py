from copilot_for_consensus_tpu.bus.inproc import InProcBroker, InProcPublisher, InProcSubscriber
from copilot_for_consensus_tpu.core.events import JSONParsed
from copilot_for_consensus_tpu.core.startup import StartupRequeue
from copilot_for_consensus_tpu.obs.logging import MemoryLogger
from copilot_for_consensus_tpu.storage.memory import InMemoryDocumentStore


def test_requeue_incomplete_republishes_events():
    broker = InProcBroker("requeue.test")
    store = InMemoryDocumentStore()
    store.insert_document("messages", {
        "message_doc_id": "m1", "archive_id": "a1", "thread_id": "t1",
        "chunked": False})
    store.insert_document("messages", {
        "message_doc_id": "m2", "archive_id": "a1", "thread_id": "t1",
        "chunked": True})

    requeue = StartupRequeue(store, InProcPublisher(broker=broker),
                             MemoryLogger())
    n = requeue.requeue_incomplete(
        "messages", {"chunked": False},
        lambda doc: JSONParsed(message_doc_id=doc["message_doc_id"],
                               archive_id=doc["archive_id"],
                               thread_id=doc["thread_id"]))
    assert n == 1
    sub = InProcSubscriber(broker=broker)
    seen = []
    sub.subscribe(["json.parsed"], lambda env: seen.append(env))
    sub.drain()
    assert [e["data"]["message_doc_id"] for e in seen] == ["m1"]
