"""hlocheck fixture: hlo-collective-budget — a shard_map psum whose
compiled all-reduce is missing from the declared budget (the GSPMD-
reshard-regression shape: the program communicates more than its
declaration admits), plus the correctly budgeted case."""

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    HloSpec,
    contract,
    require_devices,
)


def _case(budget):
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map
    except ImportError:   # jax < 0.5 exports it under experimental only
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from copilot_for_consensus_tpu.parallel.mesh import (
        MeshConfig,
        build_mesh,
    )

    require_devices(8)
    mesh = build_mesh(MeshConfig(sp=4), devices=jax.devices()[:8])

    def body(x):
        return jax.lax.psum(x, "sp")

    fn = shard_map(body, mesh=mesh, in_specs=(P("sp"),), out_specs=P())
    return ContractCase(
        fn=fn, args=(jax.ShapeDtypeStruct((8,), jnp.float32),),
        mesh=mesh,
        hlo=HloSpec(collectives=budget))


def bad_budget():
    return _case({})              # the psum's all-reduce is undeclared


def good_budget():
    return _case({"all-reduce": 1})


SHARDCHECK_CONTRACTS = [
    contract("bad_budget", bad_budget),
    contract("good_budget", good_budget),
]
