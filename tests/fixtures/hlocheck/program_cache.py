"""hlocheck fixture: hlo-program-cache — a bucket-table declaration
that has drifted from the programs it actually lowers to (three
distinct shapes against a declared cardinality of two: a program-cache
explosion waiting for production traffic), plus the honest
declaration including a deliberate duplicate variant proving the
digest sees programs, not labels."""

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    HloSpec,
    contract,
)


def _variants(widths):
    import jax
    import jax.numpy as jnp

    def step(x):
        return x * 2.0

    S = jax.ShapeDtypeStruct
    return tuple((f"bucket@{w}", step, (S((4, w), jnp.float32),))
                 for w in widths)


def bad_cache():
    # widths (8, 16, 32) lower to 3 distinct programs — the declared
    # cardinality of 2 is the stale pre-widening declaration
    return ContractCase(
        hlo=HloSpec(variants=_variants((8, 16, 32)),
                    expected_programs=2))


def good_cache():
    # the duplicate width 8 shares a program with the first variant:
    # 4 declared variants, 3 distinct programs, honestly declared
    return ContractCase(
        hlo=HloSpec(variants=_variants((8, 16, 32, 8)),
                    expected_programs=3))


SHARDCHECK_CONTRACTS = [
    contract("bad_cache", bad_cache),
    contract("good_cache", good_cache),
]
