"""hlocheck fixture: hlo-materialize — a lowered program that gathers
a working set at/above the declared element threshold (the
paged_gather_kv failure shape), plus the clean small-index gather that
stays under it."""

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    HloSpec,
    contract,
)


def bad_materialize():
    import jax
    import jax.numpy as jnp

    def step(pool, idx):
        # advanced indexing over 32 of 64 rows: a [32, 64] = 2048-
        # element stablehlo.gather in the lowering — the working set
        # materializes instead of being read in place
        return pool[idx].sum()

    S = jax.ShapeDtypeStruct
    return ContractCase(
        fn=jax.jit(step),
        args=(S((64, 64), jnp.float32), S((32,), jnp.int32)),
        hlo=HloSpec(forbid_ops=(("gather", 1024),)))


def good_materialize():
    import jax
    import jax.numpy as jnp

    def step(pool, idx):
        # 4 rows → a [4, 64] = 256-element gather, under the 1024
        # threshold: small per-step indexing is the tolerated shape
        return pool[idx].sum()

    S = jax.ShapeDtypeStruct
    return ContractCase(
        fn=jax.jit(step),
        args=(S((64, 64), jnp.float32), S((4,), jnp.int32)),
        hlo=HloSpec(forbid_ops=(("gather", 1024),)))


SHARDCHECK_CONTRACTS = [
    contract("bad_materialize", bad_materialize),
    contract("good_materialize", good_materialize),
]
