"""hlocheck fixture: hlo-donation-alias — a donated buffer whose
output dtype mismatch makes XLA silently drop the input_output_alias
(the donation survives tracing, dies at compilation), plus the clean
in-place update whose alias survives into the compiled artifact."""

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    HloSpec,
    contract,
)


def bad_alias():
    import jax
    import jax.numpy as jnp

    def step(cache, x):
        # output is f32 — no dtype-matching output for the donated
        # bf16 buffer, so the compiled program carries zero aliases
        return (cache + x).astype(jnp.float32)

    S = jax.ShapeDtypeStruct
    return ContractCase(
        fn=jax.jit(step, donate_argnums=(0,)),
        args=(S((4, 8), jnp.bfloat16), S((4, 8), jnp.bfloat16)),
        donate_argnums=(0,),
        hlo=HloSpec())


def good_alias():
    import jax
    import jax.numpy as jnp

    def step(cache, x):
        return cache.at[0].set(x[0])

    S = jax.ShapeDtypeStruct
    return ContractCase(
        fn=jax.jit(step, donate_argnums=(0,)),
        args=(S((4, 8), jnp.bfloat16), S((1, 8), jnp.bfloat16)),
        donate_argnums=(0,),
        hlo=HloSpec())


SHARDCHECK_CONTRACTS = [
    contract("bad_alias", bad_alias),
    contract("good_alias", good_alias),
]
