"""hlocheck fixture: hlo-peak-memory — a dispatch whose compiled peak
(argument + output + temp − aliased bytes) blows through its declared
HBM budget (the working-set-blowup shape that OOMs at production
scale), plus the same program under an honest budget."""

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    HloSpec,
    contract,
)


def _case(budget_bytes):
    import jax
    import jax.numpy as jnp

    def step(x):
        return (x @ x.T).sum(axis=1)

    # [256, 256] f32 argument alone is 262144 bytes
    return ContractCase(
        fn=jax.jit(step),
        args=(jax.ShapeDtypeStruct((256, 256), jnp.float32),),
        hlo=HloSpec(peak_bytes=budget_bytes))


def bad_peak():
    return _case(1024)


def good_peak():
    return _case(4 << 20)


SHARDCHECK_CONTRACTS = [
    contract("bad_peak", bad_peak),
    contract("good_peak", good_peak),
]
