"""shardcheck fixture: shard-divisibility — a spec'd dimension that the
mesh axis does not divide evenly (silent per-shard padding), plus the
clean divisible shape."""

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    contract,
    require_devices,
)

RULES = {"heads": "tp", "embed": None}


def _case(head_dim_total):
    import jax
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.parallel.mesh import (
        MeshConfig,
        build_mesh,
    )

    require_devices(8)
    mesh = build_mesh(MeshConfig(dp=2, tp=4), devices=jax.devices()[:8])
    w = jax.ShapeDtypeStruct((32, head_dim_total), jnp.bfloat16)
    return ContractCase(
        mesh=mesh, rules=RULES,
        logical=(("weights", {"wq": w},
                  {"wq": ("embed", "heads")}),))


def bad_divisibility():
    return _case(6)        # 6 heads-width over tp=4: 2 ranks pad


def good_divisibility():
    return _case(8)


SHARDCHECK_CONTRACTS = [
    contract("bad_divisibility", bad_divisibility),
    contract("good_divisibility", good_divisibility),
]
