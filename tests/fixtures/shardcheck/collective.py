"""shardcheck fixture: shard-collective — a shard_map body whose psum
names an axis the mesh it runs under does not have (caught at trace
time by eval_shape), plus the correctly bound body."""

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    contract,
    require_devices,
)


def _case(axis_name):
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map
    except ImportError:   # jax < 0.5 exports it under experimental only
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from copilot_for_consensus_tpu.parallel.mesh import (
        MeshConfig,
        build_mesh,
    )

    require_devices(8)
    mesh = build_mesh(MeshConfig(sp=4), devices=jax.devices()[:8])

    def body(x):
        return jax.lax.psum(x, axis_name)

    fn = shard_map(body, mesh=mesh, in_specs=(P("sp"),), out_specs=P())
    return ContractCase(
        fn=fn, args=(jax.ShapeDtypeStruct((8,), jnp.float32),),
        mesh=mesh)


def bad_collective():
    return _case("model")       # no such axis on the sp mesh


def good_collective():
    return _case("sp")


SHARDCHECK_CONTRACTS = [
    contract("bad_collective", bad_collective),
    contract("good_collective", good_collective),
]
