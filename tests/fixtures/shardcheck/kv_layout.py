"""shardcheck fixture: shard-kv-layout — two programs in one kv group
declaring caches with different layouts (here: dtype), plus a group
that agrees."""

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    contract,
)


def _cache(dtype_name):
    import jax
    import jax.numpy as jnp

    dt = getattr(jnp, dtype_name)
    leaf = jax.ShapeDtypeStruct((2, 4, 2, 16, 8), dt)
    return {"k": leaf, "v": leaf}


def bad_kv_layout():
    return [
        ContractCase(label="writer", kv_group="fixture-kv-bad",
                     kv_caches=(("cache", _cache("bfloat16")),)),
        ContractCase(label="reader", kv_group="fixture-kv-bad",
                     kv_caches=(("cache", _cache("float32")),)),
    ]


def good_kv_layout():
    return [
        ContractCase(label="writer", kv_group="fixture-kv-good",
                     kv_caches=(("cache", _cache("bfloat16")),)),
        ContractCase(label="reader", kv_group="fixture-kv-good",
                     kv_caches=(("cache", _cache("bfloat16")),)),
    ]


SHARDCHECK_CONTRACTS = [
    contract("bad_kv_layout", bad_kv_layout),
    contract("good_kv_layout", good_kv_layout),
]
