"""shardcheck fixture: shard-rule-axis — a logical-axis rule whose
target names a mesh axis the mesh does not have (the weight would
silently replicate), plus the clean spelling."""

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    contract,
    require_devices,
)


def _mesh():
    import jax

    from copilot_for_consensus_tpu.parallel.mesh import (
        MeshConfig,
        build_mesh,
    )

    require_devices(8)
    return build_mesh(MeshConfig(dp=2, tp=4), devices=jax.devices()[:8])


def bad_rule_axis():
    # "model" is the megatron spelling; this mesh calls the axis "tp"
    return ContractCase(mesh=_mesh(),
                        rules={"heads": "model", "batch": "dp"})


def good_rule_axis():
    return ContractCase(mesh=_mesh(),
                        rules={"heads": "tp", "batch": "dp",
                               "embed": None})


SHARDCHECK_CONTRACTS = [
    contract("bad_rule_axis", bad_rule_axis),
    contract("good_rule_axis", good_rule_axis),
]
