"""shardcheck fixture: shard-bucket — a declared input length the
padding-bucket table does not cover (unbounded retrace / silent
truncation), plus a covering table."""

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    contract,
)


def bad_bucket():
    return ContractCase(buckets=(64, 128), bucket_covers=(256,))


def good_bucket():
    return ContractCase(buckets=(64, 128, 256), bucket_covers=(256, 96))


SHARDCHECK_CONTRACTS = [
    contract("bad_bucket", bad_bucket),
    contract("good_bucket", good_bucket),
]
