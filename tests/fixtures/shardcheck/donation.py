"""shardcheck fixture: shard-donation — a donated buffer with no
shape/dtype-matching output (XLA drops the alias; the buffer
double-allocates), plus the clean in-place update shape."""

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    contract,
)


def bad_donation():
    import jax
    import jax.numpy as jnp

    def step(cache, x):
        # output is [1, 8] f32 — nothing matches the donated [4, 8] bf16
        return (cache[:1] + x).astype(jnp.float32)

    S = jax.ShapeDtypeStruct
    return ContractCase(
        fn=jax.jit(step, donate_argnums=(0,)),
        args=(S((4, 8), jnp.bfloat16), S((1, 8), jnp.bfloat16)),
        donate_argnums=(0,))


def good_donation():
    import jax
    import jax.numpy as jnp

    def step(cache, x):
        return cache.at[0].set(x[0])

    S = jax.ShapeDtypeStruct
    return ContractCase(
        fn=jax.jit(step, donate_argnums=(0,)),
        args=(S((4, 8), jnp.bfloat16), S((1, 8), jnp.bfloat16)),
        donate_argnums=(0,))


SHARDCHECK_CONTRACTS = [
    contract("bad_donation", bad_donation),
    contract("good_donation", good_donation),
]
