# racecheck fixture: race-lock-order — the "held while acquiring"
# relation must stay acyclic (lockdep's invariant).
import threading


class BadOrder:
    """``admit`` holds _alpha while taking _beta; ``drain`` holds _beta
    while taking _alpha — the classic ABBA deadlock pair."""

    def __init__(self):
        self._alpha = threading.Lock()
        self._beta = threading.Lock()
        self._items = []

    def admit(self, item):
        with self._alpha:
            with self._beta:
                self._items.append(item)

    def drain(self):
        with self._beta:
            with self._alpha:
                return list(self._items)


class BadSelfDeadlock:
    """A non-reentrant lock re-acquired through an internal call while
    already held — guaranteed, not just potential."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def bump(self):
        with self._lock:
            self._count += 1

    def bump_twice(self):
        with self._lock:
            self.bump()


class BadAliasBeforeSource:
    """The Condition is declared BEFORE the lock it wraps: provenance
    must still see one identity (deferred alias binding), so holding
    the condition while taking the 'other' lock is a self-deadlock."""

    def __init__(self):
        self._work = threading.Condition(self._lock)
        self._lock = threading.Lock()
        self._jobs = []

    def drain(self):
        with self._work:
            with self._lock:
                return list(self._jobs)


class GoodOrder:
    """Same two locks, ONE documented order everywhere: no cycle."""

    def __init__(self):
        self._alpha = threading.Lock()
        self._beta = threading.Lock()
        self._items = []

    def admit(self, item):
        with self._alpha:
            with self._beta:
                self._items.append(item)

    def drain(self):
        with self._alpha:
            with self._beta:
                return list(self._items)


class GoodReentrant:
    """An RLock may be re-acquired on the same thread by design."""

    def __init__(self):
        self._lock = threading.RLock()
        self._count = 0

    def bump(self):
        with self._lock:
            self._count += 1

    def bump_twice(self):
        with self._lock:
            self.bump()
