# racecheck fixture: race-thread-lifecycle over the telemetry-shipper
# pump shape (obs/ship.py TelemetryShipper) — the background flush
# thread must poll a stop Event and be joined by its owner; a
# daemon-and-forget pump keeps flushing into a spool its owner already
# closed (sqlite on a closed handle) at interpreter teardown.
import threading
import time


class BadShipPump:
    """Fire-and-forget: the flush loop never polls a stop Event and
    the thread is never joined — close() can yank the spool out from
    under a live flush."""

    def __init__(self, spool):
        self._spool = spool
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _pump(self):
        while True:
            time.sleep(0.25)  # jaxlint: disable=blocking-call
            self._spool.append([])

    def close(self):
        self._spool.close()            # the pump races this


class GoodShipPump:
    """The shipped shape: stop-aware wait loop + owner-joined stop()
    before the spool closes."""

    def __init__(self, spool):
        self._spool = spool
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _pump(self):
        while not self._stop.is_set():
            self._stop.wait(0.25)
            self._spool.append([])

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._spool.close()
