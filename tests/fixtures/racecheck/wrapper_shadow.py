# racecheck fixture: race-wrapper-shadow — __getattr__ only fires for
# MISSING attributes, so a concrete trivial base-class default
# silently defeats delegation (the shipped ValidatingPublisher.
# saturation() bug, as a lint rule). Same-module base resolution here;
# the cross-module pass covers the real bus/ wrapper against its ABC.


class DriverBase:
    """Concrete do-nothing defaults that exist to be overridden."""

    def connect(self):
        pass

    def saturation(self):
        return {}

    def publish(self, envelope):
        raise NotImplementedError


class BadWrapper(DriverBase):
    """Relies on __getattr__ for everything it doesn't define: the
    base's concrete ``connect``/``saturation`` defaults shadow the
    delegation, so the wrapped driver's implementations never run."""

    def __init__(self, inner):
        self.inner = inner

    def publish(self, envelope):
        return self.inner.publish(envelope)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class GoodWrapper(DriverBase):
    """Explicit forwarders for every concrete base default;
    __getattr__ only covers names the base does NOT define."""

    def __init__(self, inner):
        self.inner = inner

    def connect(self):
        return self.inner.connect()

    def saturation(self):
        return self.inner.saturation()

    def publish(self, envelope):
        return self.inner.publish(envelope)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class GoodPlainWrapper:
    """No concrete-default base at all: delegation is sound."""

    def __init__(self, inner):
        self.inner = inner

    def __getattr__(self, name):
        return getattr(self.inner, name)
