# racecheck fixture: race-thread-lifecycle — every Thread needs a
# reachable stop path (a stop-Event-polling target, or a join in its
# owner); daemon-and-forget loops race teardown.
import threading
import time


class BadPump:
    """Daemon-and-forget: the loop never polls a stop Event and the
    thread is never joined."""

    def __init__(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            time.sleep(0.1)  # jaxlint: disable=blocking-call


class BadSecondThread:
    """Joining thread ``_a`` must not excuse forgetting thread ``_b``
    — only a provenance-free join (a list loop) may excuse anything."""

    def __init__(self):
        self._a = threading.Thread(target=self._drain)
        self._b = threading.Thread(target=self._pump, daemon=True)
        self._a.start()
        self._b.start()

    def _drain(self):
        return None

    def _pump(self):
        while True:
            time.sleep(0.1)  # jaxlint: disable=blocking-call

    def stop(self):
        self._a.join(timeout=5.0)      # _b is never joined or stopped


class GoodPump:
    """Stop-aware loop plus a bounded join in ``stop()``."""

    def __init__(self):
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            self._stop.wait(0.1)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)


_PUMP = None


def good_module_start():
    """A module-level thread joined by a SIBLING module function: the
    owning scope is the module, not just the creating function."""
    global _PUMP
    _PUMP = threading.Thread(target=_module_loop, daemon=True)
    _PUMP.start()


def _module_loop():
    while True:
        time.sleep(0.1)  # jaxlint: disable=blocking-call


def good_module_stop():
    _PUMP.join(timeout=5.0)


class GoodJoinOnly:
    """No stop Event, but the owner joins the (bounded) worker — the
    scatter/gather fan-out idiom."""

    def run(self, jobs):
        threads = []
        for job in jobs:
            t = threading.Thread(target=job)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
