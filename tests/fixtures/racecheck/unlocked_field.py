# racecheck fixture: race-unlocked-field — RacerD-style lock
# consistency: a field written under its lock in one method must not
# be accessed bare in another.
import threading


class BadLedger:
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0

    def record(self, n):
        with self._lock:
            self._total += n

    def snapshot(self):
        return self._total               # bare read of a guarded field


class BadContainer:
    """Element mutations of a plain shared dict are writes OF the
    field: the bare ``_stats[key] += 1`` races the locked reader."""

    def __init__(self):
        self._stats_lock = threading.Lock()
        self._stats = {"confirmed": 0}

    def bump(self, key):
        self._stats[key] += 1            # bare element write

    def counts(self):
        with self._stats_lock:
            return dict(self._stats)


class GoodLedger:
    """Every cross-thread access holds the guard."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0

    def record(self, n):
        with self._lock:
            self._total += n

    def snapshot(self):
        with self._lock:
            return self._total


class BadTwoGuards:
    """Writes under one lock, reads under ANOTHER: holding different
    locks does not synchronize — the lockset intersection is empty."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._count = 0

    def record(self, n):
        with self._a:
            self._count += n

    def snapshot(self):
        with self._b:
            return self._count


class _CrossHandle:
    """``_mark_done`` is called under the lock from its own class —
    but also LOCK-FREE from another class below, so the 'caller holds
    the lock' inference must not apply and the bare write must flag."""

    def __init__(self):
        self._lk = threading.Lock()
        self._state = 0

    def finish(self):
        with self._lk:
            self._mark_done()

    def _mark_done(self):
        self._state = 1

    def snapshot(self):
        with self._lk:
            return self._state


class BadCrossClassCaller:
    def drop(self, handle):
        handle._mark_done()          # no lock held at this call site


class GoodInjectedLock:
    """A lock field that is ALSO assignable from a parameter (test
    injection): it must stay a lock, never become a 'callback', and
    the scan must not crash on the dual provenance."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0

    def use_lock(self, lock):
        self._lock = lock

    def record(self, n):
        with self._lock:
            self._total += n

    def snapshot(self):
        with self._lock:
            return self._total


class GoodPrivateHelper:
    """``_bump_locked`` is only ever called with the lock held — the
    inferred '# caller holds the lock' idiom must NOT flag it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0

    def record(self, n):
        with self._lock:
            self._bump_locked(n)

    def also_record(self, n):
        with self._lock:
            self._bump_locked(n)

    def _bump_locked(self, n):
        self._total += n

    def snapshot(self):
        with self._lock:
            return self._total
