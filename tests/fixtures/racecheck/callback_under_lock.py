# racecheck fixture: race-callback-under-lock — user-supplied
# callables must fire OUTSIDE the critical section (a done-callback
# may re-enter submit() and deadlock; the PR-7 dispatcher class).
import threading


class BadNotifier:
    def __init__(self, on_done):
        self._lock = threading.Lock()
        self._on_done = on_done          # constructor-supplied callable
        self._pending = []

    def submit(self, item):
        with self._lock:
            self._pending.append(item)

    def complete(self, result):
        with self._lock:
            self._pending.pop()
            self._on_done(result)        # fires INSIDE the lock


class BadIndirect:
    """The invocation is one call away: ``_finish`` fires the
    registered callbacks, and ``complete`` calls it under the lock —
    call-graph propagation must still flag the call site."""

    def __init__(self):
        self._lock = threading.Lock()
        self._callbacks = []
        self._done = False

    def add_done_callback(self, fn):
        with self._lock:
            self._callbacks.append(fn)

    def _finish(self, result):
        for fn in self._callbacks:
            fn(result)

    def complete(self, result):
        with self._lock:
            self._done = True
            self._finish(result)         # fires callbacks under lock


class BadSubscriptDispatch:
    """The handler is invoked straight out of its container —
    ``self._handlers[key](env)`` — while the registry lock is held."""

    def __init__(self):
        self._lock = threading.Lock()
        self._handlers = {}

    def register(self, key, fn):
        with self._lock:
            self._handlers[key] = fn

    def dispatch(self, key, env):
        with self._lock:
            self._handlers[key](env)     # element call under the lock


class GoodNotifier:
    """Mutate ledgers under the lock, fire the callback after."""

    def __init__(self, on_done):
        self._lock = threading.Lock()
        self._on_done = on_done
        self._pending = []

    def submit(self, item):
        with self._lock:
            self._pending.append(item)

    def complete(self, result):
        with self._lock:
            self._pending.pop()
        self._on_done(result)            # outside the critical section
