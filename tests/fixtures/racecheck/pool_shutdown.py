# racecheck fixture: race-thread-lifecycle over the stage-worker-pool
# shape (services/pool.py) — a pool that spawns per-worker consume
# threads must give every worker a reachable stop path: a stop-Event-
# polling loop AND/OR an owner join at shutdown. A fire-and-forget pool
# races teardown: the broker connection closes under a worker mid-fetch.
import threading
import time


class BadPool:
    """Fire-and-forget worker pool: targets spin forever (no stop Event
    polled) and shutdown() forgets to join — the pool-shutdown bug the
    StageWorkerPool contract exists to prevent."""

    def __init__(self, subscribers):
        self.subscribers = list(subscribers)
        self._threads = []

    def start(self):
        for sub in self.subscribers:
            t = threading.Thread(target=self._consume, args=(sub,),
                                 daemon=True)
            self._threads.append(t)
            t.start()

    def _consume(self, sub):
        while True:
            time.sleep(0.05)  # jaxlint: disable=blocking-call

    def shutdown(self):
        self.subscribers.clear()     # workers still running!


class GoodPool:
    """The StageWorkerPool discipline: stop-aware worker loops plus a
    bounded owner join over the thread list at shutdown."""

    def __init__(self, subscribers):
        self.subscribers = list(subscribers)
        self._stop = threading.Event()
        self._threads = []

    def start(self):
        for sub in self.subscribers:
            t = threading.Thread(target=self._consume, args=(sub,),
                                 daemon=True)
            self._threads.append(t)
            t.start()

    def _consume(self, sub):
        while not self._stop.is_set():
            self._stop.wait(0.05)

    def shutdown(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
