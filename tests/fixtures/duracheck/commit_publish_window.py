"""duracheck fixture: dura-commit-publish-window.

The PR-11 crash-window class: a handler commits a store insert, then
publishes only the rows that were ABSENT from its existence read. On
redelivery after a crash between commit and publish, those rows are
filtered out as duplicates and their downstream events are never
published — the rows are stranded forever.
"""


class BadFreshOnlyPublisher:
    """Publishes only the fresh (not-yet-stored) rows: a crash between
    the insert commit and the publish loop strands the committed rows —
    redelivery recomputes ``fresh`` as empty and republishes nothing."""

    def __init__(self, publisher, store):
        self.publisher = publisher
        self.store = store

    def on_RowsArrived(self, event):
        rows = event.rows
        existing = self.store.get_documents(
            "rows", [r["id"] for r in rows])
        fresh = [r for r in rows if r["id"] not in existing]
        self.store.insert_many("rows", fresh, ignore_duplicates=True)
        for r in fresh:
            self.publisher.publish(("RowStored", r["id"]))


class GoodRepublishStored:
    """The redelivery-republish discipline: already-stored rows whose
    downstream work is unfinished are published too (the
    ``stored_unchunked`` pattern), so a redelivered envelope closes
    the window instead of silently acking it."""

    def __init__(self, publisher, store):
        self.publisher = publisher
        self.store = store

    def on_RowsArrived(self, event):
        rows = event.rows
        existing = self.store.get_documents(
            "rows", [r["id"] for r in rows])
        fresh = [r for r in rows if r["id"] not in existing]
        stored_unfinished = [
            r for r in rows
            if (cur := existing.get(r["id"])) is not None
            and not cur.get("finished")
        ]
        self.store.insert_many("rows", fresh, ignore_duplicates=True)
        for r in fresh + stored_unfinished:
            self.publisher.publish(("RowStored", r["id"]))
