"""duracheck fixture: dura-ack-swallow.

Under at-least-once dispatch, a handler that catches ``RetryableError``
or broad ``Exception`` and falls through normally converts a transient
failure into a silent ack: the envelope is consumed and the work never
happened. Handlers must re-raise, return the exception for
classification, or publish a ``*Failed`` event.
"""


class BadSwallowingHandler:
    """Counts the failure and falls through — the dispatcher sees a
    normal return and acks the envelope; the work is gone."""

    def on_JobReady(self, event):
        try:
            self.run(event)
        except RetryableError:
            self.skipped += 1


class GoodClassifyingHandler:
    """The three legitimate exits: re-raise for the nack/redeliver
    path, return the exception for per-envelope classification, or
    publish a ``*Failed`` event as the terminal record."""

    def on_JobReady(self, event):
        try:
            self.run(event)
        except RetryableError:
            raise

    def on_wave_JobReady(self, events):
        try:
            self.run_wave(events)
        except Exception as exc:
            return exc

    def on_JobCancelled(self, event):
        try:
            self.run(event)
        except Exception as exc:
            self.publisher.publish(JobFailed(error=str(exc)))
