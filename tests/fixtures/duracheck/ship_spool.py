"""duracheck fixture: dura-sqlite-ledger over the telemetry-spool
shape (obs/ship.py TelemetrySpool).

The spool's whole crash-safety claim is WAL + one transaction per
flush: committed rows survive SIGKILL, and a kill mid-flush loses the
WHOLE in-flight batch, never a partial one. A spool without those is a
telemetry ledger that lies to the recovery reader.
"""

import sqlite3


class BadSpool:
    """All three violations: rollback-journal mode (a SIGKILL mid-write
    can corrupt the spool), per-row autocommit in the flush loop (a
    kill mid-flush commits a TORN batch — the recovery gate would see
    a metrics delta without its spans), and no close."""

    def __init__(self, path):
        self._db = sqlite3.connect(path)

    def append(self, rows):
        for kind, payload in rows:
            self._db.execute(
                "INSERT INTO rows (kind, payload) VALUES (?, ?)",
                (kind, payload))
        self._db.commit()


class GoodSpool:
    """The shipped shape: WAL on open, the whole flush in ONE
    transaction, owner-joined close via the local-alias idiom."""

    def __init__(self, path):
        self._db = sqlite3.connect(path)
        self._db.execute("PRAGMA journal_mode=WAL")

    def append(self, rows):
        with self._db:
            for kind, payload in rows:
                self._db.execute(
                    "INSERT INTO rows (kind, payload) VALUES (?, ?)",
                    (kind, payload))

    def close(self):
        db = self._db
        db.close()
