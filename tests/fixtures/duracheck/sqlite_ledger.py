"""duracheck fixture: dura-sqlite-ledger.

First-party sqlite ledgers (journal, outbox, broker queue store, DLQ)
must open WAL, scope multi-row write loops in one transaction, and
have an owner-joined close.
"""

import sqlite3


class BadLedger:
    """All three violations: rollback-journal mode (a crash mid-write
    can corrupt it), a per-row autocommit loop (a crash mid-loop
    commits a partial batch), and no close (the WAL/SHM sidecars
    outlive the process)."""

    def __init__(self, path):
        self._db = sqlite3.connect(path)

    def add_all(self, rows):
        for r in rows:
            self._db.execute("INSERT INTO t (v) VALUES (?)", (r,))
        self._db.commit()


class GoodLedger:
    """WAL on open, the write loop scoped in one transaction, and a
    close the owning lifecycle joins on shutdown (via a local alias,
    the EngineJournal.close idiom)."""

    def __init__(self, path):
        self._db = sqlite3.connect(path)
        self._db.execute("PRAGMA journal_mode=WAL")

    def add_all(self, rows):
        with self._db:
            for r in rows:
                self._db.execute("INSERT INTO t (v) VALUES (?)", (r,))

    def close(self):
        db = self._db
        db.close()
