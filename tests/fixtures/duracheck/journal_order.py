"""duracheck fixture: dura-journal-order.

The PR-12 contract: submit paths journal (``record_submit``) BEFORE
any queue/scheduler insertion — a crash in the window otherwise admits
work that restart-replay doesn't know about — and ``record_retire``
runs only AFTER the harvested result is used, so a crash can't delete
the journal row before the completion is emitted.
"""


class BadSubmitAfterEnqueue:
    """Enqueues first: a crash between the enqueue and the journal
    write admits a request the journal never heard of."""

    def __init__(self, journal):
        self.journal = journal
        self._queue = []

    def submit(self, rid, prompt):
        req = (rid, prompt)
        self._queue.append(req)
        self.journal.record_submit(rid, prompt)
        return rid


class BadRetireBeforeHarvest:
    """Deletes the journal row before the result is used — a crash in
    between silently loses the completion."""

    def __init__(self, journal):
        self.journal = journal
        self._done = []

    def harvest(self, req):
        self.journal.record_retire(req.request_id)
        self._done.append(req)


class GoodJournalOrder:
    """Journal-before-admit and retire-at-harvest, in order."""

    def __init__(self, journal):
        self.journal = journal
        self._queue = []
        self._done = []

    def submit(self, rid, prompt):
        self.journal.record_submit(rid, prompt)
        req = (rid, prompt)
        self._queue.append(req)
        return rid

    def harvest(self, req):
        self._done.append(req)
        self.journal.record_retire(req.request_id)
