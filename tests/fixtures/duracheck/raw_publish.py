"""duracheck fixture: dura-raw-publish.

``publish_envelope`` and raw broker ``pub`` ops belong inside the bus
package; everywhere else must publish typed events through
``.publish()`` so schema validation, identity stamping, and the
outbox/publish_window discipline apply.
"""


class BadRawEnvelopePublisher:
    """Hands a hand-rolled envelope straight to ``publish_envelope``,
    skipping the typed-event validation and the outbox path."""

    def __init__(self, publisher):
        self.publisher = publisher

    def on_ThingHappened(self, event):
        self.publisher.publish_envelope(event.to_envelope(), "things")


class BadRawBrokerOp:
    """Speaks the broker wire protocol directly — a raw ``pub`` op is
    invisible to the outbox, so a crash here loses the message."""

    def on_FlushRequested(self, event):
        self.client.request({"op": "pub", "body": event.payload})


class GoodTypedPublisher:
    """Publishes the typed event; EventPublisher.publish owns the
    envelope construction and the durability discipline."""

    def __init__(self, publisher):
        self.publisher = publisher

    def on_ThingHappened(self, event):
        self.publisher.publish(event)
