"""duracheck fixture: dura-idempotent-write.

Handlers run under at-least-once delivery: a redelivered envelope
re-runs the handler, so every insert must tolerate the second run —
``ignore_duplicates=True`` or an existence-read dedup guard.
"""


class BadBlindInsert:
    """Redelivery re-runs this handler and the second insert raises a
    duplicate-key error (or worse, duplicates the rows)."""

    def __init__(self, store, publisher):
        self.store = store
        self.publisher = publisher

    def on_RowsArrived(self, event):
        self.store.insert_many("rows", event.rows)


class GoodDupTolerantInsert:
    """Both redelivery-safe shapes: dup-tolerant insert, and an insert
    guarded by an existence read in the same handler."""

    def __init__(self, store):
        self.store = store

    def on_RowsArrived(self, event):
        self.store.insert_many("rows", event.rows,
                               ignore_duplicates=True)

    def on_RowChanged(self, event):
        existing = self.store.get_documents("rows", [event.row_id])
        if event.row_id not in existing:
            self.store.insert_document("rows", event.row)
