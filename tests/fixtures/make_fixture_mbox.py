#!/usr/bin/env python3
"""Generate the deterministic synthetic IETF-style fixture mbox.

Exercises: reply chains, an orphan reply (parent absent), subject-prefix
variants, RFC-2047 headers, a multipart text+html message, signatures,
quoted replies, forward markers, draft mentions, a missing Message-ID.

Run: python tests/fixtures/make_fixture_mbox.py
"""

import pathlib

OUT = pathlib.Path(__file__).parent / "ietf-sample.mbox"

MESSAGES = [
    # Thread 1: QUIC retransmission — root + 2 replies + 1 orphan reply.
    """From alice@example.org Mon Jan  5 10:00:00 2026
From: Alice Example <alice@example.org>
To: quic@ietf.example.org
Subject: Retransmission timers in draft-ietf-quic-recovery-29
Message-ID: <qr-root-1@example.org>
Date: Mon, 5 Jan 2026 10:00:00 +0000
Content-Type: text/plain; charset=utf-8

I believe the PTO computation in draft-ietf-quic-recovery-29 section 5.2
underestimates RTT variance on lossy paths. We measured a 12% spurious
retransmission rate in our testbed. Proposal: clamp the variance floor to
kGranularity * 2.

Alice
""",
    """From bob@example.net Mon Jan  5 11:30:00 2026
From: Bob Builder <bob@example.net>
To: quic@ietf.example.org
Subject: Re: Retransmission timers in draft-ietf-quic-recovery-29
Message-ID: <qr-reply-1@example.net>
In-Reply-To: <qr-root-1@example.org>
References: <qr-root-1@example.org>
Date: Mon, 5 Jan 2026 11:30:00 +0000
Content-Type: text/plain; charset=utf-8

On Mon, 5 Jan 2026 at 10:00, Alice Example wrote:
> I believe the PTO computation in draft-ietf-quic-recovery-29 section 5.2
> underestimates RTT variance on lossy paths.

+1, we've seen the same in production. The clamp looks right to me.
I support adopting this change.

--
Bob Builder
Distinguished Engineer, Example Networks
""",
    """From carol@example.com Mon Jan  5 14:45:00 2026
From: =?utf-8?b?Q2Fyb2wgTcO8bGxlcg==?= <carol@example.com>
To: quic@ietf.example.org
Cc: bob@example.net
Subject: RE: Retransmission timers in draft-ietf-quic-recovery-29
Message-ID: <qr-reply-2@example.com>
In-Reply-To: <qr-reply-1@example.net>
References: <qr-root-1@example.org> <qr-reply-1@example.net>
Date: Mon, 5 Jan 2026 14:45:00 +0000
Content-Type: text/plain; charset=utf-8

I disagree with the blanket clamp; it penalizes clean paths. Could we
gate it on observed loss rate instead? See also draft-mueller-quic-var-01
for an alternative formulation.

Best regards,
Carol
""",
    """From dave@example.io Tue Jan  6 09:15:00 2026
From: Dave Ops <dave@example.io>
To: quic@ietf.example.org
Subject: Re: Retransmission timers in draft-ietf-quic-recovery-29
Message-ID: <qr-reply-3@example.io>
In-Reply-To: <qr-missing-parent@nowhere.org>
Date: Tue, 6 Jan 2026 09:15:00 +0000
Content-Type: text/plain; charset=utf-8

(replying to a message my archive never received)

Agreed with the loss-rate gating idea. Strong concerns about the clamp
as-is; it doubled tail latency in our CDN simulation.
""",
    # Thread 2: HTTP/3 priorities — root (multipart html) + 1 reply.
    """From erin@example.org Wed Jan  7 08:00:00 2026
From: Erin Web <erin@example.org>
To: httpbis@ietf.example.org
Subject: Consensus call: priority signal defaults
Message-ID: <h3-root-1@example.org>
Date: Wed, 7 Jan 2026 08:00:00 +0000
Content-Type: multipart/alternative; boundary="b1"

--b1
Content-Type: text/plain; charset=utf-8

This is a consensus call on the default urgency level in
draft-ietf-httpbis-priority. Please respond by Jan 21.

--b1
Content-Type: text/html; charset=utf-8

<html><head><style>p{color:red}</style></head><body>
<p>This is a <b>consensus call</b> on the default urgency level in
draft-ietf-httpbis-priority. Please respond by Jan 21.</p>
</body></html>

--b1--
""",
    """From frank@example.net Wed Jan  7 16:20:00 2026
From: frank@example.net
To: httpbis@ietf.example.org
Subject: Fwd: Re: Consensus call: priority signal defaults
Message-ID: <h3-reply-1@example.net>
In-Reply-To: <h3-root-1@example.org>
References: <h3-root-1@example.org>
Date: Wed, 7 Jan 2026 16:20:00 +0000
Content-Type: text/plain; charset=utf-8

No objection to urgency=3 as default. Ship it.

---- Original Message ----
From: someone@example.org
This forwarded tail should be stripped by the normalizer.
""",
    # Thread 3: lone announcement, no Message-ID.
    """From zoe@example.org Thu Jan  8 12:00:00 2026
From: Zoe Chair <zoe@example.org>
To: quic@ietf.example.org
Subject: Interim meeting agenda posted
Date: Thu, 8 Jan 2026 12:00:00 +0000
Content-Type: text/plain; charset=utf-8

The agenda for the interim is up. We will discuss draft-ietf-quic-http-34
and the multipath extension. Remote participation links to follow.

Thanks,
Zoe
""",
]


def main() -> None:
    body = "\n".join(m.replace("\r\n", "\n") for m in MESSAGES)
    OUT.write_text(body)
    print(f"wrote {OUT} ({len(MESSAGES)} messages, {len(body)} bytes)")


if __name__ == "__main__":
    main()
