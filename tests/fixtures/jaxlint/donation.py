# jaxlint fixture: donation — a jitted hot-path function taking a KV
# cache without donate_argnums (positive) and with it (negative).
import jax


def _step_bad(params, cache, tok):
    cache = cache.at[:, 0].set(tok)
    return tok + 1, cache


def _step_good(params, cache, tok):
    cache = cache.at[:, 0].set(tok)
    return tok + 1, cache


bad_fn = jax.jit(_step_bad)                       # cache not donated
good_fn = jax.jit(_step_good, donate_argnums=(1,))
