# jaxlint fixture: prng-reuse — key reuse positives and the split
# discipline negative.
import jax


def bad_double_use(key):
    a = jax.random.normal(key, (2,))
    b = jax.random.uniform(key, (2,))     # same key, second draw
    return a + b


def bad_use_after_split(key):
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(key, (2,))      # parent key reused after split
    return x, k1, k2


def bad_loop_reuse(key):
    out = 0.0
    for _ in range(3):
        out = out + jax.random.normal(key, ())   # no per-iter split
    return out


def good_split_discipline(key):
    key, sub = jax.random.split(key)
    a = jax.random.normal(sub, (2,))
    key, sub = jax.random.split(key)
    b = jax.random.uniform(sub, (2,))
    return a + b


def good_exclusive_branches(key, flag):
    if flag:
        return jax.random.normal(key, (2,))
    return jax.random.uniform(key, (2,))   # other branch: not a reuse
