# jaxlint fixture: collective-axis — axis literals vs the module's mesh
# declarations.
import jax
import numpy as np
from jax.sharding import Mesh

mesh = Mesh(np.array(jax.devices()), ("dp",))


def bad_body(x):
    return jax.lax.psum(x, "tp")          # 'tp' not on any mesh here


def bad_permute(x):
    return jax.lax.ppermute(x, axis_name="model", perm=[(0, 1)])


def good_body(x):
    idx = jax.lax.axis_index("dp")
    return jax.lax.psum(x, "dp") + idx
