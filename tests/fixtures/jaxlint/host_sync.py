# jaxlint fixture: host-sync-in-jit — one positive, one negative.
# Never imported; the analyzer reads it as text.
import jax
import numpy as np


@jax.jit
def bad_sync(x):
    n = x.sum().item()            # device→host sync inside jit
    arr = np.asarray(x)           # host materialization inside jit
    jax.device_get(x)             # explicit host fetch inside jit
    x.block_until_ready()         # sync barrier inside jit
    return n + float(x[0]) + arr.sum()   # float() on a tracer


def good_sync(x):
    """The same operations OUTSIDE the traced program are the normal
    harvest path — no findings."""
    y = jax.jit(lambda t: t * 2)(x)
    return float(np.asarray(jax.device_get(y))[0])
