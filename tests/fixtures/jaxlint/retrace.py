# jaxlint fixture: retrace-hazard — positives and negatives.
import functools

import jax


@jax.jit
def bad_branch(x):
    if x > 0:                     # branches on a tracer
        return x + 1
    return x - 1


@jax.jit
def bad_loop(x):
    while x < 10:                 # loops on a tracer
        x = x + 1
    return x


@functools.partial(jax.jit, static_argnames=("sizes",))
def bad_static_default(x, sizes=[64, 128]):   # unhashable static default
    return x[: sizes[0]]


@functools.partial(jax.jit, static_argnames=("n",))
def good_branch(x, n):
    if n > 2:                     # static arg: resolved at trace time
        return x * n
    if x.shape[0] > 1:            # shape: static on a tracer
        return x
    if x is None:                 # structure check: trace-time
        return x
    return x + 1
