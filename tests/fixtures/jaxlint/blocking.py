# jaxlint fixture: blocking-call — handler-thread hygiene.
import threading
import time


class BadConsumer:
    def __init__(self, bus):
        self.bus = bus
        self._lock = threading.Lock()
        self._stop = threading.Event()

    def run(self):
        while True:
            time.sleep(0.5)                    # uninterruptible poll
            with self._lock:
                self.bus.publish_envelope({})  # broker RTT under lock

    def run_suppressed(self):
        # deliberate one-off pause with a written justification
        # jaxlint: disable=blocking-call
        time.sleep(0.01)


class BadConditionConsumer:
    """``_work`` has no 'lock' in its name: only assignment provenance
    (bound from ``threading.Condition``, aliasing ``self._lock``)
    identifies it as a lock — the async_runner dispatcher shape the
    old name-token heuristic missed."""

    def __init__(self, bus):
        self.bus = bus
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._stop = threading.Event()

    def run(self):
        with self._work:
            self.bus.publish_envelope({})      # broker RTT under lock


class GoodConditionConsumer:
    def __init__(self, bus):
        self.bus = bus
        self._work = threading.Condition()
        self._stop = threading.Event()

    def run(self):
        with self._work:
            batch = list(self.bus.queue)
        for env in batch:                      # publish OUTSIDE the lock
            self.bus.publish_envelope(env)


class GoodConsumer:
    def __init__(self, bus):
        self.bus = bus
        self._lock = threading.Lock()
        self._stop = threading.Event()

    def run(self):
        while not self._stop.is_set():
            self._stop.wait(0.5)               # stop-aware pause
            with self._lock:
                batch = list(self.bus.queue)
            for env in batch:                  # publish OUTSIDE the lock
                self.bus.publish_envelope(env)
