import pytest

from copilot_for_consensus_tpu.text.chunkers import (
    FixedSizeChunker,
    SemanticChunker,
    TokenWindowChunker,
    create_chunker,
    estimate_tokens,
)
from copilot_for_consensus_tpu.text.drafts import detect_draft_mentions


def test_estimate_tokens():
    assert estimate_tokens("") == 0
    assert estimate_tokens("one two three four") == int(4 * 1.3)


def test_token_window_respects_bounds():
    text = " ".join(f"word{i}" for i in range(2000))
    chunks = TokenWindowChunker().chunk(text)
    assert len(chunks) > 1
    for c in chunks:
        assert c.token_count <= 512
    assert all(c.seq == i for i, c in enumerate(chunks))
    # overlap: consecutive chunks share words
    first_words = chunks[0].text.split()
    second_words = chunks[1].text.split()
    assert set(first_words[-10:]) & set(second_words[:50])


def test_token_window_small_tail_merged():
    words_per_chunk = int(384 / 1.3)
    text = " ".join(f"w{i}" for i in range(words_per_chunk + 5))
    chunks = TokenWindowChunker().chunk(text)
    assert len(chunks) == 1 or chunks[-1].token_count >= 100


def test_token_window_empty_and_tiny():
    assert TokenWindowChunker().chunk("") == []
    tiny = TokenWindowChunker().chunk("just a few words")
    assert len(tiny) == 1
    assert tiny[0].text == "just a few words"


def test_fixed_size_chunker():
    text = "x" * 4000
    chunks = FixedSizeChunker(chunk_chars=1500, overlap_chars=200).chunk(text)
    assert len(chunks) == 3
    assert all(len(c.text) <= 1500 for c in chunks)


def test_semantic_chunker_paragraph_packing():
    paras = [f"Paragraph {i}. " + "Sentence filler here. " * 10
             for i in range(10)]
    text = "\n\n".join(paras)
    chunks = SemanticChunker(chunk_size=100).chunk(text)
    assert len(chunks) > 1
    # paragraphs are not split mid-way when under budget
    assert all("Paragraph" in c.text for c in chunks)


def test_semantic_chunker_splits_giant_paragraph():
    text = "This is a sentence. " * 200  # one huge paragraph
    chunks = SemanticChunker(chunk_size=100).chunk(text)
    assert len(chunks) > 1


def test_create_chunker_factory():
    assert create_chunker({"driver": "token_window"}).name == "token_window"
    assert create_chunker({"driver": "semantic"}).name == "semantic"
    assert create_chunker({"driver": "fixed_size"}).name == "fixed_size"
    with pytest.raises(ValueError):
        create_chunker({"driver": "bert"})
    with pytest.raises(ValueError):
        create_chunker({"driver": "token_window", "chunk_size": 10,
                        "overlap": 20})


def test_draft_detection():
    text = ("See draft-ietf-quic-recovery-29 and draft-mueller-quic-var-01; "
            "also draft-ietf-quic-recovery-30 is out. Not-a-draft: "
            "draftsman, re-draft.")
    assert detect_draft_mentions(text) == [
        "draft-ietf-quic-recovery", "draft-mueller-quic-var"]
    assert detect_draft_mentions("") == []


def test_token_window_small_windows_drop_no_words():
    """Regression (fuzz-found): with min_chunk_tokens > chunk_size every
    window is 'small'; the tail-merge must still only fire on the true
    final piece — a mid-stream merge used to stop chunking and drop the
    rest of the text."""
    from copilot_for_consensus_tpu.text.chunkers import (
        _WORD_RE,
        TokenWindowChunker,
    )

    text = "0 0 0 0 0 0 0 0 1"
    chunks = TokenWindowChunker(chunk_size=8, overlap=6).chunk(text)
    got = [w for c in chunks for w in _WORD_RE.findall(c.text)]
    assert got.count("1") >= 1
    for w in set(_WORD_RE.findall(text)):
        assert got.count(w) >= _WORD_RE.findall(text).count(w)
