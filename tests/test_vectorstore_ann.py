# ANN (IVF) retrieval vs the flat exact-scan oracle (ISSUE 19).
#
# The tier-1 lane carries the RECALL GATE the bench preset claims at
# million scale — same clustered geometry, 10k vectors so the fast lane
# stays fast — plus the index invariants (locator coverage across
# retrain/upsert/delete, filtered parity, persistence). The
# million-vector arm lives behind @slow next to the full bench preset.
import numpy as np
import pytest

from copilot_for_consensus_tpu.vectorstore.ivf import (
    IVFParams,
    ListShardAllocator,
    next_pow2,
)
from copilot_for_consensus_tpu.vectorstore.tpu import TPUVectorStore

DIM = 32


def _clustered(n, clusters, dim=DIM, seed=0, noise=0.15):
    """Same corpus geometry as BENCH_PRESET=ann_retrieval: cluster
    centers on the unit sphere, members center + gaussian noise."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((clusters, dim)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    which = rng.integers(0, clusters, size=n)
    pts = centers[which] + noise * rng.standard_normal(
        (n, dim), dtype=np.float32)
    return pts, centers, rng


def _fill(store, vecs, meta=None):
    store.add_embeddings(
        (f"v{i}", vecs[i], (meta(i) if meta else None))
        for i in range(len(vecs)))


def _ids(hits):
    return [h.id for h in hits]


def _recall(store_ivf, store_flat, queries, top_k=10):
    approx = store_ivf.query_batch(list(queries), top_k=top_k)
    exact = store_flat.query_batch(list(queries), top_k=top_k)
    return float(np.mean([
        len(set(_ids(a)) & set(_ids(e))) / max(len(e), 1)
        for a, e in zip(approx, exact) if e]))


# -- jax-free unit surface ----------------------------------------------

def test_next_pow2():
    assert [next_pow2(n) for n in (0, 1, 2, 3, 8, 9, 1000)] == \
        [1, 1, 2, 4, 8, 16, 1024]


def test_ivf_params_from_config():
    p = IVFParams.from_config({"ivf_nlist": 64, "ivf_nprobe": 4,
                               "ivf_min_train": 16})
    assert (p.nlist, p.nprobe, p.min_train) == (64, 4, 16)
    d = IVFParams.from_config({})
    assert d.nlist == 0 and d.nprobe >= 1 and d.min_train > 0


def test_allocator_balances_and_places_every_list():
    """LPT placement: every list gets exactly one slot inside its
    shard's slot range, and the heaviest/lightest shard row totals stay
    within one max-list of each other (greedy LPT bound)."""
    rng = np.random.default_rng(3)
    sizes = rng.integers(1, 1000, size=37)
    alloc = ListShardAllocator(num_shards=8, nlist=37)
    slot_of_list = alloc.assign(sizes)
    assert sorted(set(slot_of_list)) == sorted(slot_of_list)  # unique
    sps = alloc.slots_per_shard
    assert sps * 8 >= 37
    load = np.zeros(8)
    for l, slot in enumerate(slot_of_list):
        shard = slot // sps
        assert 0 <= slot - shard * sps < sps
        load[shard] += sizes[l]
    assert load.max() - load.min() <= sizes.max()


def test_retrain_policy():
    from copilot_for_consensus_tpu.vectorstore.ivf import IVFIndex
    idx = IVFIndex(DIM, IVFParams(min_train=100, spill_fraction=0.25,
                                  growth_factor=2.0))
    assert not idx.needs_retrain(99)        # untrained, too small
    assert idx.needs_retrain(100)           # untrained, enough rows
    idx.trained = True
    idx.trained_at_n = 100
    idx._indexed_live = 100
    assert not idx.needs_retrain(110)       # no drift
    assert idx.needs_retrain(200)           # corpus doubled
    idx._spill_live = 50                    # spill_frac 1/3 > 0.25
    assert idx.needs_retrain(150)


# -- recall gate (the bench preset's claim, tier-1 scale) ---------------

def _pair(n, clusters, seed=0, *, nprobe, nlist=0, min_train=256):
    vecs, centers, rng = _clustered(n, clusters, seed=seed)
    flat = TPUVectorStore({"dimension": DIM})
    ivf = TPUVectorStore({"dimension": DIM, "index": "ivf",
                          "ivf_nprobe": nprobe, "ivf_nlist": nlist,
                          "ivf_min_train": min_train})
    _fill(flat, vecs)
    _fill(ivf, vecs)
    return flat, ivf, centers, rng


def test_recall_gate_clustered_10k():
    """The tentpole gate at tier-1 scale: recall@10 >= 0.95 against
    the exact oracle while scanning <= 15% of the posting lists."""
    flat, ivf, centers, rng = _pair(10_000, 64, nprobe=16)
    queries = (centers[rng.integers(0, 64, size=32)]
               + 0.15 * rng.standard_normal((32, DIM), dtype=np.float32))
    recall = _recall(ivf, flat, queries)
    stats = ivf.last_query_stats
    assert stats["route"] == "ivf"
    assert recall >= 0.95, recall
    assert stats["lists_scanned_frac"] <= 0.15, stats


def test_uniform_corpus_full_probe_is_exact():
    """Adversarial uniform corpus (no cluster structure to exploit):
    probing EVERY list must reproduce the exact scan identically —
    the approximation error comes only from skipped lists, never from
    the fused gather/rescore path itself."""
    rng = np.random.default_rng(7)
    vecs = rng.standard_normal((1500, DIM), dtype=np.float32)
    flat = TPUVectorStore({"dimension": DIM})
    ivf = TPUVectorStore({"dimension": DIM, "index": "ivf",
                          "ivf_nlist": 16, "ivf_nprobe": 16,
                          "ivf_min_train": 64})
    _fill(flat, vecs)
    _fill(ivf, vecs)
    queries = rng.standard_normal((16, DIM), dtype=np.float32)
    assert _recall(ivf, flat, queries) == 1.0


def test_filtered_query_parity():
    """Metadata-filtered retrieval must agree between routes — the ivf
    route falls back rather than return an under-filled filtered set."""
    vecs, _, rng = _clustered(2000, 16, seed=11)
    meta = lambda i: {"thread_id": f"t{i % 7}"}          # noqa: E731
    flat = TPUVectorStore({"dimension": DIM})
    ivf = TPUVectorStore({"dimension": DIM, "index": "ivf",
                          "ivf_nprobe": 4, "ivf_min_train": 128})
    _fill(flat, vecs, meta)
    _fill(ivf, vecs, meta)
    q = vecs[42]
    ivf.query(q, top_k=5)                  # trigger training
    got = ivf.query(q, top_k=5, flt={"thread_id": "t3"})
    want = flat.query(q, top_k=5, flt={"thread_id": "t3"})
    assert _ids(got) == _ids(want)
    assert all(h.metadata["thread_id"] == "t3" for h in got)


# -- index invariants ---------------------------------------------------

def test_upsert_delete_retrain_invariants():
    """Across train → upsert → delete, the index locator must cover
    every live row EXACTLY once (posting lists + spill, no dupes, no
    orphans), and queries must see upserts/deletes immediately."""
    vecs, _, rng = _clustered(600, 8, seed=5)
    store = TPUVectorStore({"dimension": DIM, "index": "ivf",
                            "ivf_nlist": 8, "ivf_nprobe": 8,
                            "ivf_min_train": 64})
    _fill(store, vecs)
    store.query(vecs[0], top_k=1)          # train
    ivf = store._ivf
    assert ivf.trained

    def live_rows():
        return {r for r in range(len(store._ids))
                if r not in store._deleted_rows}

    assert set(ivf._locator) == live_rows()
    assert ivf.live_count == len(live_rows())

    # upsert an existing id with a brand-new direction: the spill
    # catches it without retraining, and search finds it first
    probe = np.zeros(DIM, dtype=np.float32)
    probe[DIM - 1] = 1.0
    store.add_embedding("v7", probe, None)
    assert _ids(store.query(probe, top_k=1)) == ["v7"]
    assert set(ivf._locator) == live_rows()

    # batched delete drops the rows from the index and from results
    store.delete([f"v{i}" for i in range(20)])
    assert store.count() == 580
    assert set(ivf._locator) == live_rows()
    hits = store.query(vecs[3], top_k=10)
    assert not set(_ids(hits)) & {f"v{i}" for i in range(20)}


def test_persistence_roundtrip_preserves_trained_index(tmp_path):
    vecs, _, rng = _clustered(400, 8, seed=9)
    path = str(tmp_path / "store.npz")
    store = TPUVectorStore({"dimension": DIM, "index": "ivf",
                            "ivf_nlist": 8, "ivf_nprobe": 8,
                            "ivf_min_train": 64, "persist_path": path})
    _fill(store, vecs)
    want = _ids(store.query(vecs[5], top_k=5))   # trains + answers
    gen = store._ivf.generation
    store.save()

    again = TPUVectorStore({"dimension": DIM, "index": "ivf",
                            "ivf_nlist": 8, "ivf_nprobe": 8,
                            "ivf_min_train": 64, "persist_path": path})
    assert again.load() == 400
    # restored index is ALREADY trained from the saved centroids — the
    # first query must answer from it, not kick off a k-means fit
    assert again._ivf is not None and again._ivf.trained
    assert _ids(again.query(vecs[5], top_k=5)) == want
    assert again._ivf.generation == gen
    assert again.last_query_stats["route"] == "ivf"


def test_bulk_load_does_not_reingest_per_row(tmp_path, monkeypatch):
    """load() restores via ONE device upload; a per-row add_embedding
    loop (the old path) would re-pay normalization + device sync per
    vector at million scale."""
    vecs, _, _ = _clustered(100, 4, seed=13)
    path = str(tmp_path / "store.npz")
    store = TPUVectorStore({"dimension": DIM, "persist_path": path})
    _fill(store, vecs)
    store.save()

    again = TPUVectorStore({"dimension": DIM, "persist_path": path})
    def boom(*a, **k):
        raise AssertionError("load() must not ingest row-by-row")
    monkeypatch.setattr(again, "add_embedding", boom)
    monkeypatch.setattr(again, "add_embeddings", boom)
    assert again.load() == 100
    assert _ids(again.query(vecs[17], top_k=1)) == ["v17"]


def test_topk_bucketing_stays_correct_at_odd_k():
    """query top_k values between pow2 buckets share device programs
    (the hlo program-cache contract); correctness must not depend on
    the requested k landing on a bucket boundary."""
    vecs, _, rng = _clustered(500, 8, seed=17)
    flat = TPUVectorStore({"dimension": DIM})
    _fill(flat, vecs)
    q = rng.standard_normal(DIM).astype(np.float32)
    full = _ids(flat.query(q, top_k=16))
    for k in (1, 3, 7, 11, 13):
        assert _ids(flat.query(q, top_k=k)) == full[:k]


# -- million-vector arm (bench-preset scale) ----------------------------

@pytest.mark.slow
def test_recall_gate_clustered_1m():
    flat, ivf, centers, rng = _pair(
        1_000_000, 1024, nprobe=16, min_train=65536)
    queries = (centers[rng.integers(0, 1024, size=64)]
               + 0.15 * rng.standard_normal((64, DIM),
                                            dtype=np.float32))
    recall = _recall(ivf, flat, queries)
    stats = ivf.last_query_stats
    assert recall >= 0.95, recall
    assert stats["lists_scanned_frac"] <= 0.15, stats
