from copilot_for_consensus_tpu.text.normalizer import (
    NormalizerConfig,
    TextNormalizer,
    html_to_text,
)


def test_html_to_text_strips_tags_and_style():
    html = ("<html><head><style>p{color:red}</style></head><body>"
            "<p>Hello <b>world</b></p><p>Second</p></body></html>")
    text = html_to_text(html)
    assert "Hello world" in text
    assert "Second" in text
    assert "color" not in text
    assert "<" not in text


def test_signature_stripped():
    body = "Real content here.\n\n--\nBob Builder\nExample Networks\n"
    out = TextNormalizer().normalize(body)
    assert "Real content" in out
    assert "Bob Builder" not in out


def test_best_regards_stripped():
    body = "I disagree with the clamp.\n\nBest regards,\nCarol\n"
    out = TextNormalizer().normalize(body)
    assert "disagree" in out
    assert "Carol" not in out


def test_quoted_reply_removed():
    body = ("On Mon, 5 Jan 2026 at 10:00, Alice wrote:\n"
            "> original text line one\n"
            "> original text line two\n"
            "\n"
            "My actual reply.\n")
    out = TextNormalizer().normalize(body)
    assert "My actual reply." in out
    assert "original text" not in out
    assert "Alice wrote" not in out


def test_forward_marker_truncates():
    body = "Ship it.\n\n---- Original Message ----\nold forwarded stuff\n"
    out = TextNormalizer().normalize(body)
    assert "Ship it." in out
    assert "forwarded stuff" not in out


def test_blank_collapse_and_config_gates():
    body = "a\n\n\n\n\nb\n"
    assert TextNormalizer().normalize(body) == "a\n\nb"
    keep = TextNormalizer(NormalizerConfig(strip_signatures=False))
    assert "Cheers," in keep.normalize("hi\n\nCheers,\nme")


def test_html_message_end_to_end():
    html = "<p>This is a <b>consensus call</b>.</p>"
    out = TextNormalizer().normalize(html, is_html=True)
    assert out == "This is a consensus call."
