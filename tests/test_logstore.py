# Log shipping end-to-end: ShippingLogger → TCP ingest → query by
# correlation id over the HTTP API (the Loki/Promtail-role contract).
import json
import time
import urllib.request

from copilot_for_consensus_tpu.obs.logging import (
    MemoryLogger,
    ShippingLogger,
    create_logger,
)
from copilot_for_consensus_tpu.tools.logstore import (
    LogStore,
    LogStoreServer,
)


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
        return json.loads(resp.read())


def _wait(cond, timeout=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_ship_and_query_by_correlation_id():
    srv = LogStoreServer(LogStore(), port=0, http_port=0).start()
    try:
        log = ShippingLogger(MemoryLogger(), "127.0.0.1", srv.port)
        bound = log.bind(service="parsing", correlation_id="corr-42")
        bound.info("archive parsed", archive_id="a1")
        bound.error("downstream failed", error="boom")
        log.bind(service="chunking",
                 correlation_id="corr-99").info("chunked")
        assert _wait(lambda: srv.store.count() >= 3)
        got = _get(srv.http_port, "/logs?correlation_id=corr-42")["logs"]
        assert len(got) == 2
        assert {g["message"] for g in got} == {"archive parsed",
                                               "downstream failed"}
        # level + service filters compose
        errs = _get(srv.http_port,
                    "/logs?correlation_id=corr-42&level=error")["logs"]
        assert len(errs) == 1 and errs[0]["error"] == "boom"
        assert _get(srv.http_port,
                    "/logs?service=chunking")["logs"][0][
                        "correlation_id"] == "corr-99"
        # health + metrics endpoints serve the deployment contract
        assert _get(srv.http_port, "/health")["records"] == 3
    finally:
        srv.stop()


def test_shipping_survives_sink_down_and_recovers():
    """The pipeline must not crash or block when the logstore is down;
    records buffered within the queue bound arrive after it returns."""
    mem = MemoryLogger()
    # port 1 is never listening
    log = ShippingLogger(mem, "127.0.0.1", 1)
    for i in range(5):
        log.info(f"m{i}")
    assert len(mem.records) == 5            # tee side never blocked
    log.close()
    # now point a fresh shipper at a real store mid-life
    srv = LogStoreServer(LogStore(), port=0, http_port=0).start()
    try:
        log2 = ShippingLogger(MemoryLogger(), "127.0.0.1", srv.port)
        log2.info("after recovery", correlation_id="c1")
        assert _wait(lambda: srv.store.count() >= 1)
    finally:
        srv.stop()


def test_shipping_close_is_stop_aware():
    """close() must interrupt the shipper's reconnect backoff, not wait
    it out — the jaxlint blocking-call rule exists because bare sleeps
    on background threads make shutdown hang (docs/STATIC_ANALYSIS.md)."""
    import time as _time

    log = ShippingLogger(MemoryLogger(), "127.0.0.1", 1)  # sink down
    log.info("m")
    # give the pump time to pop the record, fail the connect (port 1
    # refuses instantly), and enter its backoff wait — the record goes
    # straight back on the queue, so polling the queue can't observe it
    _time.sleep(0.3)
    t0 = _time.monotonic()
    log.close()
    took = _time.monotonic() - t0
    assert not log._thread.is_alive(), "shipper thread survived close()"
    assert took < 1.0, f"close() waited out the backoff ({took:.2f}s)"


def test_hostile_ingest_line_does_not_kill_sink():
    import socket

    srv = LogStoreServer(LogStore(), port=0, http_port=0).start()
    try:
        with socket.create_connection(("127.0.0.1", srv.port)) as s:
            s.sendall(b"not json at all\n")
            # valid JSON but not an object: must hit the fallback
            # record, not AttributeError the connection handler
            s.sendall(b"42\n")
            s.sendall(b'["also", "valid", "json"]\n')
            s.sendall(b'{"message": "fine", "service": "x"}\n')
        assert _wait(lambda: srv.store.count() >= 4)
        ok = srv.store.query(service="x")
        assert ok and ok[0]["message"] == "fine"
        junk = srv.store.query(service="logstore")
        assert len(junk) == 3
        assert all(r["message"] == "unparseable log line" for r in junk)
    finally:
        srv.stop()


def test_create_logger_shipping_driver_and_retention():
    srv = LogStoreServer(LogStore(), port=0, http_port=0).start()
    try:
        log = create_logger({"driver": "shipping", "service": "svc",
                             "host": "127.0.0.1", "port": srv.port})
        log.info("hello", correlation_id="c9")
        assert _wait(lambda: srv.store.count() >= 1)
        rec = srv.store.query(correlation_id="c9")[0]
        assert rec["service"] == "svc"
        # retention prunes old records
        srv.store.add({"ts": time.time() - 10_000, "message": "old"})
        assert srv.store.prune(3600) == 1
        assert srv.store.query(text="old") == []
    finally:
        srv.stop()


def test_ledger_discipline_wal_and_owner_joined_close(tmp_path):
    """duracheck regression (dura-sqlite-ledger): the log ledger opens
    WAL like every first-party sqlite ledger, and LogStoreServer.stop
    closes the store so the WAL/SHM sidecars don't outlive the
    process (and the final checkpoint folds them into the db)."""
    db = tmp_path / "logs.sqlite3"
    srv = LogStoreServer(LogStore(str(db)), port=0, http_port=0).start()
    try:
        mode = srv.store._conn.execute(
            "PRAGMA journal_mode").fetchone()[0]
        assert mode == "wal"
        srv.store.add({"message": "persisted", "service": "svc"})
        assert srv.store.count() == 1
    finally:
        srv.stop()
    # stop() closed the connection (owner-joined close) ...
    import sqlite3

    import pytest as _pytest
    with _pytest.raises(sqlite3.ProgrammingError):
        srv.store._conn.execute("SELECT 1")
    # ... the WAL checkpointed into the main db, and a fresh open
    # sees the committed record
    reopened = LogStore(str(db))
    try:
        assert reopened.count() == 1
    finally:
        reopened.close()
