# Adapter packages: consensus, draft diff, fetchers, archive stores.
import pytest

from copilot_for_consensus_tpu.archive.base import (
    ArchiveStoreError,
    DocumentArchiveStore,
    InMemoryArchiveStore,
    LocalVolumeArchiveStore,
)
from copilot_for_consensus_tpu.consensus.base import (
    ConsensusLevel,
    EmbeddingConsensusDetector,
    HeuristicConsensusDetector,
)
from copilot_for_consensus_tpu.draftdiff.base import LocalDiffProvider
from copilot_for_consensus_tpu.embedding.base import MockEmbeddingProvider
from copilot_for_consensus_tpu.fetch.base import (
    FetchError,
    LocalFetcher,
    SourceConfig,
)
from copilot_for_consensus_tpu.storage.factory import create_document_store


def _msgs(*bodies):
    return [{"body": b, "from_addr": f"u{i}@x"} for i, b in enumerate(bodies)]


class TestConsensus:
    def test_strong_consensus(self):
        det = HeuristicConsensusDetector()
        sig = det.detect(_msgs("+1 from me", "I agree with the draft",
                               "LGTM, ship it", "sounds good"))
        assert sig.level == ConsensusLevel.STRONG_CONSENSUS
        assert sig.score > 0.5
        assert sig.agree_count == 4

    def test_contested(self):
        det = HeuristicConsensusDetector()
        sig = det.detect(_msgs("+1", "I object strongly", "-1 broken",
                               "agree", "this is problematic"))
        assert sig.level == ConsensusLevel.CONTESTED

    def test_no_signal_below_min(self):
        det = HeuristicConsensusDetector()
        sig = det.detect(_msgs("what time is the meeting?"))
        assert sig.level == ConsensusLevel.NO_SIGNAL

    def test_embedding_detector_runs(self):
        det = EmbeddingConsensusDetector(MockEmbeddingProvider(64))
        sig = det.detect(_msgs("I agree, sounds good, +1",
                               "I agree, support the proposal",
                               "objection, this is problematic"))
        assert sig.agree_count + sig.disagree_count >= 2


class TestDraftDiff:
    def test_local_unified_diff(self):
        p = LocalDiffProvider()
        p.register("draft-ietf-quic-recovery", "28", "line a\nline b\n")
        p.register("draft-ietf-quic-recovery", "29", "line a\nline c\n")
        d = p.get_diff("draft-ietf-quic-recovery", "28", "29")
        assert d.added_lines == 1 and d.removed_lines == 1
        assert "+line c" in d.diff_text

    def test_document_store_backed(self):
        store = create_document_store({"driver": "memory"}, validate=False)
        store.upsert_document("drafts", {"_id": "d-01", "text": "v1\n"})
        store.upsert_document("drafts", {"_id": "d-02", "text": "v2\n"})
        p = LocalDiffProvider(document_store=store)
        d = p.get_diff("d", "01", "02")
        assert "+v2" in d.diff_text


class TestFetch:
    def test_local_fetcher_missing_path(self):
        with pytest.raises(FetchError):
            list(LocalFetcher().fetch(SourceConfig(name="x",
                                                   location="/nope/nothing")))

    def test_local_fetcher_reads_file(self, fixtures_dir):
        out = list(LocalFetcher().fetch(SourceConfig(
            name="x", location=str(fixtures_dir / "ietf-sample.mbox"))))
        assert len(out) == 1
        assert out[0].content.startswith(b"From ")


class TestArchiveStore:
    def test_memory_roundtrip(self):
        s = InMemoryArchiveStore()
        s.save("abc", b"data")
        assert s.exists("abc") and s.load("abc") == b"data"
        assert s.delete("abc") and not s.exists("abc")
        with pytest.raises(ArchiveStoreError):
            s.load("abc")

    def test_local_volume_roundtrip(self, tmp_path):
        s = LocalVolumeArchiveStore(str(tmp_path))
        uri = s.save("abc123", b"mbox bytes")
        assert uri.startswith("file://")
        assert s.load("abc123") == b"mbox bytes"
        with pytest.raises(ArchiveStoreError):
            s._path("../evil")

    def test_document_backed(self):
        store = create_document_store({"driver": "memory"}, validate=False)
        s = DocumentArchiveStore(store)
        s.save("a1", b"\x00\xffbinary")
        assert s.load("a1") == b"\x00\xffbinary"
        assert s.delete("a1") and not s.exists("a1")
