# Azure Key Vault JWT signer against a wire-contract mock: the mock
# holds the RSA private key (like the real vault — the client only ever
# sees the public JWK and sign results), AAD client-credentials, JWKS
# publication, end-to-end JWT mint/verify, and the circuit breaker.
import base64
import hashlib
import json as _json

import pytest

# the mock vault holds the RSA private key server-side, so the whole
# module needs the optional dependency — skip cleanly without it
pytest.importorskip(
    "cryptography",
    reason="optional 'cryptography' package not installed (RSA "
           "primitives for the mock vault and local verification)")

from copilot_for_consensus_tpu.security.jwt import (
    JWTError,
    JWTManager,
    create_jwt_signer,
)
from copilot_for_consensus_tpu.security.keyvault_signer import (
    AzureKeyVaultSigner,
    CircuitBreaker,
)
from copilot_for_consensus_tpu.services.http import (
    HTTPServer,
    Response,
    Router,
)


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


@pytest.fixture(scope="module")
def vault_key():
    from cryptography.hazmat.primitives.asymmetric import rsa

    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


@pytest.fixture()
def mock_vault(vault_key):
    """AAD token endpoint + Key Vault keys endpoint; private key stays
    server-side."""
    router = Router()
    state = {"token_calls": 0, "sign_calls": 0, "get_calls": 0,
             "fail_signs": 0}
    pub = vault_key.public_key().public_numbers()

    def _n_bytes(n):
        return n.to_bytes((n.bit_length() + 7) // 8, "big")

    @router.post("/tenant-1/oauth2/v2.0/token")
    def token(req):
        import urllib.parse as up

        state["token_calls"] += 1
        form = dict(up.parse_qsl(req.body.decode()))
        if form.get("client_secret") != "app-secret":
            return Response({"error": "invalid_client"}, status=401)
        return {"access_token": "tok-kv", "expires_in": 3600}

    def _jwk():
        return {"kid": "https://vault/keys/signing/v77", "kty": "RSA",
                "n": _b64url(_n_bytes(pub.n)),
                "e": _b64url(_n_bytes(pub.e)),
                "key_ops": ["sign", "verify"]}

    @router.get("/keys/{name}")
    def get_key(req):
        state["get_calls"] += 1
        if req.headers.get("Authorization") != "Bearer tok-kv":
            return Response({"error": "unauthorized"}, status=401)
        return {"key": _jwk()}

    @router.post("/keys/{name}/sign")
    def sign(req):
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding
        from cryptography.hazmat.primitives.asymmetric.utils import (
            Prehashed,
        )

        state["sign_calls"] += 1
        if state["fail_signs"] > 0:
            state["fail_signs"] -= 1
            return Response({"error": "throttled"}, status=429)
        if req.headers.get("Authorization") != "Bearer tok-kv":
            return Response({"error": "unauthorized"}, status=401)
        body = _json.loads(req.body)
        assert body["alg"] == "RS256"
        digest = base64.urlsafe_b64decode(
            body["value"] + "=" * (-len(body["value"]) % 4))
        sig = vault_key.sign(digest, padding.PKCS1v15(),
                             Prehashed(hashes.SHA256()))
        return {"kid": _jwk()["kid"], "value": _b64url(sig)}

    srv = HTTPServer(router)
    srv.start()
    yield srv, state
    srv.stop()


def _signer(srv, **kw):
    base = f"http://127.0.0.1:{srv.port}"
    kw.setdefault("retry_attempts", 0)
    return AzureKeyVaultSigner(base, "signing", "tenant-1", "app-1",
                               "app-secret", authority=base, **kw)


def test_jwt_mint_and_verify_via_vault(mock_vault):
    """Full path: the JWT's signature is produced by the vault's sign
    operation and verifies against the published JWK — the private key
    never crossed the wire."""
    srv, state = mock_vault
    signer = _signer(srv)
    manager = JWTManager(signer, issuer="iss", audience="aud")
    token = manager.mint("user@example.org", roles=["admin"])
    claims = manager.verify(token)
    assert claims["sub"] == "user@example.org"
    assert claims["roles"] == ["admin"]
    assert state["sign_calls"] == 1
    # header kid is the vault key version
    header = _json.loads(base64.urlsafe_b64decode(
        token.split(".")[0] + "=="))
    assert header["kid"] == "v77"
    # verify is local: no extra vault round-trips
    manager.verify(token)
    assert state["sign_calls"] == 1 and state["get_calls"] == 1


def test_jwks_publication_matches_vault_key(mock_vault, vault_key):
    srv, _ = mock_vault
    jwk = _signer(srv).public_jwk()
    assert jwk["kty"] == "RSA" and jwk["alg"] == "RS256"
    pub = vault_key.public_key().public_numbers()
    n = int.from_bytes(base64.urlsafe_b64decode(
        jwk["n"] + "=" * (-len(jwk["n"]) % 4)), "big")
    assert n == pub.n


def test_tampered_signature_rejected(mock_vault):
    srv, _ = mock_vault
    manager = JWTManager(_signer(srv), issuer="i", audience="a")
    token = manager.mint("u")
    head, payload, sig = token.split(".")
    forged = payload[:-2] + ("AA" if payload[-2:] != "AA" else "BB")
    with pytest.raises(JWTError):
        manager.verify(f"{head}.{forged}.{sig}")


def test_bad_credentials_surface_as_jwt_error(mock_vault):
    srv, _ = mock_vault
    base = f"http://127.0.0.1:{srv.port}"
    bad = AzureKeyVaultSigner(base, "signing", "tenant-1", "app-1",
                              "wrong-secret", authority=base,
                              retry_attempts=0)
    with pytest.raises(Exception, match="401|invalid_client"):
        bad.sign(b"payload")


def test_transient_sign_errors_retry_then_succeed(mock_vault):
    srv, state = mock_vault
    signer = _signer(srv, retry_attempts=2, retry_backoff_s=0.01)
    state["fail_signs"] = 2          # two 429s, then success
    assert signer.sign(b"data")
    assert state["sign_calls"] >= 3


def test_circuit_breaker_opens_and_cools_down(mock_vault):
    srv, state = mock_vault
    signer = _signer(srv, breaker_threshold=2, breaker_cooldown_s=30.0)
    signer._load_public()            # prime key fetch
    state["fail_signs"] = 10**6      # hard-down vault
    for _ in range(2):
        with pytest.raises(JWTError, match="429"):
            signer.sign(b"x")
    hits = state["sign_calls"]
    with pytest.raises(JWTError, match="circuit open"):
        signer.sign(b"x")
    assert state["sign_calls"] == hits     # failed fast, no wire call


def test_circuit_breaker_unit():
    br = CircuitBreaker(threshold=2, cooldown_s=60)
    calls = []

    def boom():
        calls.append(1)
        raise RuntimeError("down")

    for _ in range(2):
        with pytest.raises(RuntimeError):
            br.call(boom)
    with pytest.raises(JWTError, match="circuit open"):
        br.call(boom)
    assert len(calls) == 2


def test_factory_and_validation(mock_vault):
    srv, _ = mock_vault
    base = f"http://127.0.0.1:{srv.port}"
    with pytest.raises(ValueError, match="vault_url"):
        create_jwt_signer({"driver": "azure_keyvault"})
    signer = create_jwt_signer({
        "driver": "azure_keyvault", "vault_url": base,
        "key_name": "signing", "tenant_id": "tenant-1",
        "client_id": "app-1", "client_secret": "app-secret",
        "authority": base})
    assert isinstance(signer, AzureKeyVaultSigner)
    assert signer.alg == "RS256"


def test_non_rsa_key_rejected(mock_vault):
    srv, _ = mock_vault
    router = srv.router

    @router.get("/ec/keys/{name}")
    def ec_key(req):
        return {"key": {"kty": "EC", "kid": "k", "n": "", "e": ""}}

    base = f"http://127.0.0.1:{srv.port}"
    signer = AzureKeyVaultSigner(
        f"{base}/ec", "p256", "tenant-1", "app-1", "app-secret",
        authority=base, retry_attempts=0)
    with pytest.raises(JWTError, match="EC"):
        signer.sign(b"x")
