# SLO-aware scheduler (engine/scheduler.py): DRR fairness properties,
# priority-lane ordering, closed-loop load shedding (shed BEFORE the
# EngineQueueBacklogGrowing alert threshold), the HTTP 429 mapping —
# all host-only and fast — plus slow-lane CPU e2e tests proving the
# chunked-prefill path is bit-identical to the monolithic wave and the
# shed path never trips the engine-failure machinery.
import pathlib
import re
import time

import pytest

from copilot_for_consensus_tpu.engine.scheduler import (
    PRIORITIES,
    EngineOverloaded,
    Scheduler,
    SchedulerConfig,
    jain_index,
    resolve_scheduler,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


class FakeReq:
    def __init__(self, tenant="", priority="interactive", n=64, tag=None):
        self.tenant = tenant
        self.priority = priority
        self.prompt = list(range(n))
        self.tag = tag


def _fill(sched, tenant, lane, count, n=64):
    for _ in range(count):
        sched.enqueue(FakeReq(tenant, lane, n))


# ---------------------------------------------------------------------------
# jain index
# ---------------------------------------------------------------------------


def test_jain_index_bounds():
    assert jain_index([]) == 1.0
    assert jain_index([5, 5, 5, 5]) == pytest.approx(1.0)
    # one tenant takes everything: 1/n
    assert jain_index([100, 0, 0, 0]) == pytest.approx(0.25)
    assert 0.0 < jain_index([10, 1]) < 1.0


# ---------------------------------------------------------------------------
# weighted-DRR fairness properties
# ---------------------------------------------------------------------------


def test_drr_fairness_jain_under_skewed_tenants():
    """The ISSUE-6 property: three tenants, one offering 10x the work
    of the others, equal weights — the admitted-token shares under
    sustained contention must reach Jain >= 0.9 (FIFO would give the
    heavy tenant nearly everything: Jain -> 1/3)."""
    sched = Scheduler(SchedulerConfig(
        quantum_tokens=64, max_queue_depth=10**9,
        batch_shed_depth=10**9))
    _fill(sched, "heavy", "interactive", 200, n=64)
    _fill(sched, "light-1", "interactive", 20, n=64)
    _fill(sched, "light-2", "interactive", 20, n=64)
    # drain in waves while every tenant still has queued work — the
    # contention window fairness is defined over
    while all(sched.queued_for(t) for t in ("heavy", "light-1",
                                            "light-2")):
        got = sched.select(max_requests=8, token_budget=512)
        assert got, "scheduler stopped releasing work under backlog"
    fair = sched.fairness_snapshot()
    assert jain_index(fair.values()) >= 0.9, fair


def test_drr_weights_shape_the_shares():
    """A 3x-weighted tenant gets ~3x the admitted tokens of an equal
    competitor under sustained contention."""
    sched = Scheduler(SchedulerConfig(
        quantum_tokens=64,
        tenant_weights={"gold": 3.0, "bronze": 1.0},
        max_queue_depth=10**9, batch_shed_depth=10**9))
    _fill(sched, "gold", "interactive", 100, n=64)
    _fill(sched, "bronze", "interactive", 100, n=64)
    while sched.queued_for("gold") and sched.queued_for("bronze"):
        assert sched.select(max_requests=8, token_budget=512)
    got_gold = sched._tenants["gold"].admitted_tokens
    got_bronze = sched._tenants["bronze"].admitted_tokens
    assert got_gold / got_bronze == pytest.approx(3.0, rel=0.25)
    # and the WEIGHTED shares are what Jain sees as fair
    assert jain_index(sched.fairness_snapshot().values()) >= 0.9


def test_drr_oversized_request_not_starved():
    """A request bigger than the whole wave budget must eventually be
    released alone, not starve behind the budget forever."""
    sched = Scheduler(SchedulerConfig(quantum_tokens=64))
    sched.enqueue(FakeReq("big", "interactive", n=4096))
    for _ in range(200):
        got = sched.select(max_requests=4, token_budget=256)
        if got:
            assert len(got[0].prompt) == 4096
            return
    pytest.fail("oversized request starved")


def test_priority_lane_preemption_ordering():
    """Interactive requests submitted AFTER a pile of batch work must
    still be released first — strict lane priority."""
    sched = Scheduler(SchedulerConfig(
        quantum_tokens=10**6, max_queue_depth=10**9,
        batch_shed_depth=10**9))
    _fill(sched, "t", "batch", 6, n=32)
    _fill(sched, "t", "interactive", 3, n=32)
    got = sched.select(max_requests=6, token_budget=10**9)
    lanes = [r.priority for r in got]
    assert lanes[:3] == ["interactive"] * 3, lanes
    assert set(lanes[3:]) == {"batch"}


def test_prefix_placement_groups_same_key_into_one_wave():
    """Requests sharing a radix-prefix placement key ride the same
    wave even across tenants (each charged to its own tenant)."""
    sched = Scheduler(SchedulerConfig(
        quantum_tokens=10**6, max_queue_depth=10**9,
        batch_shed_depth=10**9))
    sched.enqueue(FakeReq("a", "interactive", 32, tag="tmpl-X"))
    sched.enqueue(FakeReq("a", "interactive", 32, tag="other"))
    sched.enqueue(FakeReq("b", "interactive", 32, tag="tmpl-X"))
    sched.enqueue(FakeReq("b", "interactive", 32, tag="tmpl-X"))
    got = sched.select(max_requests=3, token_budget=10**9,
                       placement_key=lambda r: r.tag)
    assert [r.tag for r in got] == ["tmpl-X"] * 3


# ---------------------------------------------------------------------------
# load shedding: closed loop + thresholds
# ---------------------------------------------------------------------------


def _backlog_alert_threshold() -> int:
    """Read the EngineQueueBacklogGrowing depth out of the alert pack —
    the shed-before-alert contract is against the REAL rule, not a
    hard-coded copy that could drift."""
    text = (REPO / "infra" / "prometheus" / "alerts" /
            "serving.yml").read_text()
    m = re.search(r"copilot_engine_queue_depth\s*>\s*(\d+)", text)
    assert m, "EngineQueueBacklogGrowing expr not found"
    return int(m.group(1))


def test_default_shed_thresholds_sit_below_backlog_alert():
    cfg = SchedulerConfig()
    alert_depth = _backlog_alert_threshold()
    assert cfg.max_queue_depth < alert_depth
    assert cfg.batch_shed_depth < cfg.max_queue_depth


def test_shed_fires_before_backlog_alert_depth():
    """Submit storm: every request is admission-checked then enqueued;
    the hard-cap shed must kick in strictly below the alert depth, so
    EngineLoadShedding (429s) fires before EngineQueueBacklogGrowing
    ever can."""
    sched = Scheduler(SchedulerConfig())
    alert_depth = _backlog_alert_threshold()
    shed = 0
    for i in range(3 * alert_depth):
        try:
            sched.check_admission(tenant="storm",
                                  priority="interactive",
                                  prompt_tokens=64)
            sched.enqueue(FakeReq("storm", "interactive", 64))
        except EngineOverloaded as exc:
            shed += 1
            assert exc.retry_after_s >= 1.0
            assert exc.reason == "queue-full"
    assert shed > 0
    assert sched.queued < alert_depth


def test_batch_sheds_before_interactive():
    sched = Scheduler(SchedulerConfig(batch_shed_depth=8,
                                      max_queue_depth=16))
    for _ in range(8):
        sched.check_admission(tenant="t", priority="batch",
                              prompt_tokens=8)
        sched.enqueue(FakeReq("t", "batch", 8))
    # batch lane now sheds...
    with pytest.raises(EngineOverloaded) as ei:
        sched.check_admission(tenant="t", priority="batch",
                              prompt_tokens=8)
    assert ei.value.reason == "slo-pressure"
    assert ei.value.priority == "batch"
    # ...but interactive still admits until the hard cap
    sched.check_admission(tenant="t", priority="interactive",
                          prompt_tokens=8)


def test_tenant_quota_sheds_only_the_offender():
    sched = Scheduler(SchedulerConfig(
        tenant_quota_tokens={"greedy": 100}))
    sched.check_admission(tenant="greedy", priority="interactive",
                          prompt_tokens=80)
    sched.enqueue(FakeReq("greedy", "interactive", 80))
    with pytest.raises(EngineOverloaded) as ei:
        sched.check_admission(tenant="greedy", priority="interactive",
                              prompt_tokens=80)
    assert ei.value.reason == "tenant-quota"
    # other tenants unaffected
    sched.check_admission(tenant="polite", priority="interactive",
                          prompt_tokens=80)


def test_closed_loop_slo_violation_sheds_batch_lane():
    """Synthetic telemetry spans violating the queue-wait SLO while
    the slots are saturated flip the loop to level 1: batch sheds,
    interactive still admits."""

    class Trace:
        def __init__(self, qw, ttft, fin):
            self.queue_wait_s = qw
            self.ttft_s = ttft
            self.finished_at = fin

    class Tele:
        completed = [Trace(30.0, 31.0, time.monotonic())
                     for _ in range(16)]

    sched = Scheduler(SchedulerConfig(queue_wait_p95_slo_s=20.0,
                                      ttft_p99_slo_s=30.0))
    sig = sched.observe(queued=2, active=8, num_slots=8,
                        telemetry=Tele())
    assert sig["overload_level"] == 1
    with pytest.raises(EngineOverloaded):
        sched.check_admission(tenant="t", priority="batch",
                              prompt_tokens=8)
    sched.check_admission(tenant="t", priority="interactive",
                          prompt_tokens=8)
    # idle slots = hysteresis, not overload: same latencies, no shed
    sched2 = Scheduler(SchedulerConfig(queue_wait_p95_slo_s=20.0))
    sig2 = sched2.observe(queued=2, active=1, num_slots=8,
                          telemetry=Tele())
    assert sig2["overload_level"] == 0


def test_retry_after_tracks_drain_rate_and_clamps():
    class Trace:
        def __init__(self, fin):
            self.queue_wait_s = 0.1
            self.ttft_s = 0.2
            self.finished_at = fin

    class Tele:
        # 16 completions over the last ~4s -> ~4 req/s
        completed = [Trace(time.monotonic() - 4.0 + 0.25 * i)
                     for i in range(16)]

    sched = Scheduler(SchedulerConfig(min_retry_after_s=1.0,
                                      max_retry_after_s=60.0))
    sig = sched.observe(queued=16, active=4, num_slots=4,
                        telemetry=Tele())
    # 16 queued at ~4/s -> ~4s, within clamps
    assert 1.0 <= sig["retry_after_s"] <= 60.0
    assert sig["retry_after_s"] == pytest.approx(4.0, rel=0.5)
    # zero rate, deep queue: clamped to the max, never infinity
    sched2 = Scheduler(SchedulerConfig(max_retry_after_s=60.0))
    sig2 = sched2.observe(queued=1000, active=0, num_slots=4)
    assert sig2["retry_after_s"] == 60.0


# ---------------------------------------------------------------------------
# structured rejection -> HTTP 429 + Retry-After
# ---------------------------------------------------------------------------


def test_engine_overloaded_event_fields():
    exc = EngineOverloaded("nope", retry_after_s=7.25, tenant="t",
                           priority="batch", reason="queue-full",
                           correlation_id="corr-9")
    f = exc.as_event_fields()
    assert f["retry_after_s"] == 7.25
    assert f["tenant"] == "t"
    assert f["correlation_id"] == "corr-9"
    assert f["reason"] == "queue-full"


def test_router_maps_engine_overloaded_to_429_with_retry_after():
    from copilot_for_consensus_tpu.services.http import Router

    router = Router()

    @router.post("/api/generate")
    def gen(req):
        raise EngineOverloaded(
            "engine overloaded", retry_after_s=12.4, tenant="chat",
            priority="interactive", correlation_id="corr-42")

    resp = router.dispatch("POST", "/api/generate", {}, b"{}")
    assert resp.status == 429
    assert resp.headers["Retry-After"] == "13"      # ceil(12.4)
    import json

    body = json.loads(resp.raw)
    assert body["correlation_id"] == "corr-42"
    assert body["retry_after_s"] == 12.4
    assert body["tenant"] == "chat"


# ---------------------------------------------------------------------------
# telemetry export + resolve semantics
# ---------------------------------------------------------------------------


def test_scheduler_metrics_export():
    from copilot_for_consensus_tpu.engine.telemetry import EngineTelemetry
    from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics

    m = InMemoryMetrics(namespace="copilot")
    tele = EngineTelemetry(engine="generation", num_slots=4, metrics=m)
    sched = Scheduler(SchedulerConfig(max_queue_depth=4),
                      telemetry=tele)
    sched.enqueue(FakeReq("a", "interactive", 8))
    for _ in range(8):
        try:
            sched.check_admission(tenant="a", priority="interactive",
                                  prompt_tokens=8)
            sched.enqueue(FakeReq("a", "interactive", 8))
        except EngineOverloaded:
            pass
    body = m.render_prometheus()
    assert "copilot_engine_sched_tenant_queue_depth" in body
    assert "copilot_engine_sched_shed_total" in body
    assert 'tenant="a"' in body


def test_resolve_scheduler_semantics():
    assert resolve_scheduler(None) is None
    assert resolve_scheduler(False) is None
    s = resolve_scheduler(True)
    assert isinstance(s, Scheduler)
    cfg = SchedulerConfig(chunk_tokens=99)
    s2 = resolve_scheduler(cfg)
    assert s2.cfg.chunk_tokens == 99
    assert resolve_scheduler(s2) is s2      # shared instance
    with pytest.raises(ValueError):
        resolve_scheduler("nope")
    with pytest.raises(ValueError):
        Scheduler().check_admission(priority="urgent")


def test_embed_admit_sizes_and_sheds():
    sched = Scheduler(SchedulerConfig(embed_wave_rows=16,
                                      embed_max_burst_texts=100))
    assert sched.embed_admit(50, batch_size=64) == 16
    with pytest.raises(EngineOverloaded) as ei:
        sched.embed_admit(500, batch_size=64)
    assert ei.value.reason == "embed-burst"
    # under overload the tile halves
    sched.overload_level = 1
    assert sched.embed_admit(50, batch_size=64) == 8


def test_priorities_constant():
    assert PRIORITIES == ("interactive", "batch")


# ---------------------------------------------------------------------------
# CPU e2e (slow lane): chunked prefill bit-identity, engine-level
# shedding, async-runner containment
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_engine_parts():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.models import decoder
    from copilot_for_consensus_tpu.models.configs import decoder_config

    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(7), cfg,
                                 dtype=jnp.float32)
    return cfg, params


def _engine(tiny_engine_parts, **kw):
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.engine.generation import (
        GenerationEngine,
    )

    cfg, params = tiny_engine_parts
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 128)
    kw.setdefault("prefill_buckets", (16, 32, 96))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("decode_window", 4)
    return GenerationEngine(cfg, params, **kw)


@pytest.mark.slow
def test_chunked_prefill_bit_identical_to_monolithic(tiny_engine_parts):
    """The tentpole exactness gate: greedy completions with chunked
    prefill ON (scheduler, chunk_tokens far below the prompt lengths)
    must be token-identical to the monolithic-wave FIFO engine —
    chunked prefill is a scheduling change, not a numerics change."""
    import numpy as np

    cfg, _ = tiny_engine_parts
    rng = np.random.default_rng(3)
    prompts = [rng.integers(3, cfg.vocab_size, size=n).tolist()
               for n in (60, 25, 80, 10, 33, 71)]
    want = _engine(tiny_engine_parts).generate(prompts,
                                               max_new_tokens=6)
    eng = _engine(tiny_engine_parts,
                  scheduler=SchedulerConfig(chunk_tokens=16,
                                            prefill_wave_tokens=64))
    got = eng.generate(prompts, max_new_tokens=6)
    for w, g in zip(want, got):
        assert g.tokens == w.tokens
        assert g.prompt_len == w.prompt_len
    # the long prompts actually took the chunked path
    assert eng.chunk_dispatches > 0
    assert eng.chunk_prefill_tokens > 0


@pytest.mark.slow
def test_chunked_prefill_interleaves_with_decode(tiny_engine_parts):
    """A long prompt joining mid-decode must not perturb the stream
    already decoding (chunk dispatches park active rows OOB), and its
    own output must match the solo run."""
    import numpy as np

    cfg, _ = tiny_engine_parts
    rng = np.random.default_rng(5)
    short = rng.integers(3, cfg.vocab_size, size=12).tolist()
    long_p = rng.integers(3, cfg.vocab_size, size=90).tolist()
    solo = _engine(tiny_engine_parts).generate(
        [short, long_p], max_new_tokens=10)
    eng = _engine(tiny_engine_parts,
                  scheduler=SchedulerConfig(chunk_tokens=16))
    done = {}
    rid1 = eng.submit(short, 10)
    for _ in range(2):
        for c in eng.step():
            done[c.request_id] = c
    rid2 = eng.submit(long_p, 10, tenant="late", priority="batch")
    for _ in range(100):
        for c in eng.step():
            done[c.request_id] = c
        if len(done) == 2:
            break
    assert done[rid1].tokens == solo[0].tokens
    assert done[rid2].tokens == solo[1].tokens


@pytest.mark.slow
def test_engine_submit_sheds_with_structured_rejection(
        tiny_engine_parts):
    """Engine-level closed loop: a submit storm against a tiny queue
    cap sheds with EngineOverloaded at the door, queue depth never
    reaches the cap x2, and the admitted requests all complete."""
    import numpy as np

    cfg, _ = tiny_engine_parts
    rng = np.random.default_rng(7)
    eng = _engine(tiny_engine_parts,
                  scheduler=SchedulerConfig(max_queue_depth=6,
                                            batch_shed_depth=4))
    admitted, shed = [], 0
    for i in range(24):
        p = rng.integers(3, cfg.vocab_size, size=10).tolist()
        try:
            admitted.append(eng.submit(p, 3, tenant=f"t{i % 2}"))
        except EngineOverloaded as exc:
            shed += 1
            assert exc.retry_after_s >= 1.0
        assert eng.queue_depth <= 12
    assert shed > 0 and admitted
    done = {}
    for _ in range(200):
        for c in eng.step():
            done[c.request_id] = c
        if len(done) == len(admitted):
            break
    assert set(done) == set(admitted)
    stats = eng.sched_stats()
    assert stats["shed"] == shed
    assert 0.0 < stats["shed_rate"] < 1.0


@pytest.mark.slow
def test_async_runner_propagates_shed_without_error_reports(
        tiny_engine_parts):
    """ISSUE-6 satellite: a shed is an ADMISSION outcome — the async
    runner must surface it to the caller synchronously and must NOT
    treat it as an engine failure (no error_reporter report, no
    flight-recorder error dump)."""
    from copilot_for_consensus_tpu.engine.async_runner import (
        AsyncEngineRunner,
    )
    from copilot_for_consensus_tpu.obs.errors import (
        CollectingErrorReporter,
    )

    eng = _engine(tiny_engine_parts,
                  scheduler=SchedulerConfig(max_queue_depth=2,
                                            batch_shed_depth=1))
    rep = CollectingErrorReporter()
    runner = AsyncEngineRunner(eng, error_reporter=rep).start()
    try:
        handles, shed = [], 0
        # long generations keep all 4 slots busy, so the burst piles
        # up and trips the 2-deep cap. A shed can surface either
        # synchronously (runner.submit precheck, once the scheduler
        # queue is visibly deep) or on the HANDLE (the dispatcher-side
        # engine.submit shed fails that handle, not the dispatcher) —
        # both are admission outcomes, neither is an engine failure.
        for i in range(16):
            try:
                handles.append(runner.submit([5, 6, 7, 8], 48))
            except EngineOverloaded:
                shed += 1
        ok = 0
        for h in handles:
            try:
                assert h.result(timeout=120.0).tokens
                ok += 1
            except EngineOverloaded as exc:
                shed += 1
                assert exc.retry_after_s >= 1.0
        assert shed > 0, "burst never shed"
        assert ok > 0, "nothing completed"
    finally:
        runner.stop()
    assert rep.reports == []
    assert eng.telemetry.errors == 0
