# Stage scale-out (ISSUE 11): StageWorkerPool lifecycle, batched wave
# dispatch through BaseService.handle_envelopes (per-envelope outcomes,
# amortized stage spans, fallback isolation), the chunking/parsing
# batched hot paths, occupancy-aware embed waves, the service-level
# saturation-snapshot cache, and the runner's services-config wiring.
import threading
import time

import pytest

from copilot_for_consensus_tpu.archive.base import InMemoryArchiveStore
from copilot_for_consensus_tpu.bus.base import PoisonEnvelope
from copilot_for_consensus_tpu.core import events as ev
from copilot_for_consensus_tpu.core.retry import RetryConfig, RetryPolicy
from copilot_for_consensus_tpu.obs import trace
from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics
from copilot_for_consensus_tpu.services.chunking import ChunkingService
from copilot_for_consensus_tpu.services.embedding import EmbeddingService
from copilot_for_consensus_tpu.services.parsing import ParsingService
from copilot_for_consensus_tpu.services.pool import StageWorkerPool
from copilot_for_consensus_tpu.storage.memory import InMemoryDocumentStore


class CapturePublisher:
    def __init__(self):
        self.events = []

    def publish(self, event, routing_key=None):
        # stamp the trace block like real publishers do, so the wave
        # span-DAG assertions see the publish spans
        trace.inject(event.to_envelope(), type(event).routing_key)
        self.events.append(event)

    def publish_envelope(self, envelope, routing_key=None):
        self.events.append(envelope)

    def of(self, cls):
        return [e for e in self.events if isinstance(e, cls)]


def fast_retry():
    return RetryPolicy(RetryConfig(max_attempts=2, base_delay=0.001,
                                   max_delay=0.001))


def make_chunking(store=None):
    store = store or InMemoryDocumentStore()
    pub = CapturePublisher()
    svc = ChunkingService(pub, store, retry=fast_retry(),
                          metrics=InMemoryMetrics())
    return svc, store, pub


def seed_messages(store, n, prefix="m"):
    ids = []
    for i in range(n):
        mid = f"{prefix}{i}"
        store.insert_document("messages", {
            "message_doc_id": mid, "archive_id": "a0",
            "source_id": "s0", "thread_id": f"t{i % 2}",
            "body": "alpha beta gamma delta " * 12,
            "chunked": False})
        ids.append(mid)
    return ids


def parsed_envelopes(ids):
    return [ev.JSONParsed(message_doc_id=m, archive_id="a0",
                          thread_id="t0").to_envelope() for m in ids]


# -- wave dispatch: chunking ------------------------------------------------


def test_chunking_wave_batches_roundtrips_and_publishes_per_message():
    svc, store, pub = make_chunking()
    ids = seed_messages(store, 6)

    calls = {"get": 0, "multi": 0}
    orig_get = store.get_document
    orig_multi = store.get_documents
    store.get_document = lambda *a: (calls.__setitem__(
        "get", calls["get"] + 1) or orig_get(*a))
    store.get_documents = lambda *a: (calls.__setitem__(
        "multi", calls["multi"] + 1) or orig_multi(*a))

    outcomes = svc.handle_envelopes(parsed_envelopes(ids))
    assert outcomes == [None] * 6
    # ONE multi-get for the wave, zero per-message reads
    assert calls == {"get": 0, "multi": 1}
    assert store.count_documents("chunks", {}) >= 6
    assert all(store.get_document("messages", m)["chunked"]
               for m in ids)
    prepared = pub.of(ev.ChunksPrepared)
    assert sorted(e.message_doc_id for e in prepared) == sorted(ids)
    for e in prepared:
        assert e.chunk_ids
        assert all(store.get_document("chunks", c) for c in e.chunk_ids)


def test_chunking_wave_replay_is_idempotent():
    svc, store, pub = make_chunking()
    ids = seed_messages(store, 3)
    envs = parsed_envelopes(ids)
    assert svc.handle_envelopes(envs) == [None] * 3
    n_chunks = store.count_documents("chunks", {})
    # redelivered wave (at-least-once): no duplicate chunks, events
    # re-publish (downstream embedding skips already-embedded chunks)
    assert svc.handle_envelopes(envs) == [None] * 3
    assert store.count_documents("chunks", {}) == n_chunks


def test_chunking_wave_missing_message_isolates_to_single_dispatch():
    """One message missing from the store fails the WAVE, which falls
    back to per-envelope dispatch: present messages chunk + publish,
    only the missing one takes the retry/failure path."""
    svc, store, pub = make_chunking()
    ids = seed_messages(store, 2)
    envs = parsed_envelopes(ids + ["ghost"])
    outcomes = svc.handle_envelopes(envs)
    assert outcomes[0] is None and outcomes[1] is None
    assert outcomes[2] is None   # retries exhausted → failure event+ack
    assert all(store.get_document("messages", m)["chunked"]
               for m in ids)
    assert sorted(e.message_doc_id for e in pub.of(ev.ChunksPrepared)) \
        == sorted(ids)
    failed = pub.of(ev.ChunkingFailed)
    assert len(failed) == 1 and failed[0].message_doc_id == "ghost"
    assert svc.metrics.counter_value(
        "chunking_wave_fallback_total", {"event": "JSONParsed"}) == 1


def test_wave_spans_amortized_per_envelope_with_worker_label():
    collector = trace.configure(capacity=10_000)
    svc, store, pub = make_chunking()
    ids = seed_messages(store, 4)
    trace.set_worker_label("chunking-w2")
    try:
        svc.handle_envelopes(parsed_envelopes(ids))
    finally:
        trace.set_worker_label("")
    stage = [s for s in collector.spans()
             if s.kind == "stage" and s.service == "chunking"]
    assert len(stage) == 4
    for s in stage:
        assert s.attrs.get("wave") == 4
        assert s.attrs.get("worker") == "chunking-w2"
        assert s.duration_s > 0          # amortized share included
        assert s.status == "ok"
    # follow-up publishes parent under THEIR envelope's stage span
    pubs = [s for s in collector.spans() if s.kind == "publish"]
    stage_ids = {(s.trace_id, s.span_id) for s in stage}
    assert pubs and all(
        (p.trace_id, p.parent_span_id) in stage_ids for p in pubs)


def test_wave_outcomes_cover_mixed_event_types():
    """Envelopes of a type without a wave handler ride the single path
    inside handle_envelopes; outcomes stay positionally aligned."""
    svc, store, pub = make_chunking()
    ids = seed_messages(store, 2)
    envs = parsed_envelopes(ids)
    deletion = ev.SourceDeletionRequested(
        source_id="s0", requested_by="ops").to_envelope()
    outcomes = svc.handle_envelopes([envs[0], deletion, envs[1]])
    assert outcomes == [None, None, None]
    assert pub.of(ev.SourceCleanupProgress)


# -- wave dispatch: parsing -------------------------------------------------


def _tiny_mbox(n, prefix):
    out = []
    for i in range(n):
        out.append(
            f"From x@y Thu Jan  1 00:00:00 2026\n"
            f"From: P{i} <p{i}@example.org>\n"
            f"Message-ID: <{prefix}-{i}@t>\n"
            f"Subject: Draft {prefix}\n"
            f"Date: Thu, 1 Jan 2026 00:00:00 +0000\n"
            f"\nbody {prefix} {i}\n\n")
    return "".join(out).encode()


def make_parsing():
    store = InMemoryDocumentStore()
    archive_store = InMemoryArchiveStore()
    pub = CapturePublisher()
    svc = ParsingService(pub, store, archive_store, retry=fast_retry(),
                         metrics=InMemoryMetrics())
    return svc, store, archive_store, pub


def seed_archives(store, archive_store, n_archives=2, msgs=3):
    ids = []
    for a in range(n_archives):
        aid = f"arch{a}"
        store.insert_document("archives", {
            "archive_id": aid, "source_id": "s0", "parsed": False})
        archive_store.save(aid, _tiny_mbox(msgs, f"a{a}"))
        ids.append(aid)
    return ids


def test_parsing_wave_bulk_inserts_and_publishes_per_archive():
    svc, store, archive_store, pub = make_parsing()
    ids = seed_archives(store, archive_store, 2, 3)
    envs = [ev.ArchiveIngested(archive_id=a, source_id="s0",
                               archive_uri="u").to_envelope()
            for a in ids]
    outcomes = svc.handle_envelopes(envs)
    assert outcomes == [None, None]
    assert store.count_documents("messages", {}) == 6
    assert store.count_documents("threads", {}) >= 2
    parsed = pub.of(ev.JSONParsed)
    assert len(parsed) == 6
    assert {e.archive_id for e in parsed} == set(ids)
    for a in ids:
        assert store.get_document("archives", a)["parsed"] is True
    # redelivered wave: no new inserts; stored-but-unchunked messages
    # republish (the crash-window cover — duplicates are idempotent
    # downstream), fully processed ones would stay quiet
    pub.events.clear()
    assert svc.handle_envelopes(envs) == [None, None]
    assert store.count_documents("messages", {}) == 6
    assert len(pub.of(ev.JSONParsed)) == 6


def test_parsing_single_path_uses_bulk_writes():
    """process_archive (the non-wave path) rides the same batched
    storing phase: one existing-ids multi-get + one insert_many
    instead of insert_or_ignore per message."""
    svc, store, archive_store, pub = make_parsing()
    (aid,) = seed_archives(store, archive_store, 1, 5)
    calls = {"ins": 0, "many": 0}
    orig_ins = store.insert_document
    orig_many = store.insert_many
    store.insert_document = lambda *a, **k: (calls.__setitem__(
        "ins", calls["ins"] + 1) or orig_ins(*a, **k))
    store.insert_many = lambda *a, **k: (calls.__setitem__(
        "many", calls["many"] + 1) or orig_many(*a, **k))
    assert svc.process_archive(aid) == 5
    assert calls["many"] == 1
    assert len(pub.of(ev.JSONParsed)) == 5


# -- occupancy-aware embed waves -------------------------------------------


class VecStore:
    def __init__(self):
        self.items = []

    def add_embeddings(self, items):
        self.items.extend(items)


class Provider:
    dimension = 4
    model_name = "stub"

    def embed_batch(self, texts):
        return [[0.0] * 4 for _ in texts]


def make_embedding(occ, batch_size=64):
    store = InMemoryDocumentStore()
    pub = CapturePublisher()
    svc = EmbeddingService(pub, store, Provider(), VecStore(),
                           batch_size=batch_size,
                           occupancy_fn=lambda: occ,
                           retry=fast_retry(),
                           metrics=InMemoryMetrics())
    return svc, store, pub


@pytest.mark.parametrize("occ,expected", [
    (None, 64),      # no telemetry → fixed base (mock drivers)
    (0.0, 128),      # idle engine → double wave (fill the tile)
    (1.0, 32),       # saturated → half wave (protect interactive)
    (1.5, 32),       # clamped occupancy
    (2.0 / 3.0, 64)  # the neutral point: base size
])
def test_effective_batch_size_tracks_engine_headroom(occ, expected):
    svc, _store, _pub = make_embedding(occ)
    assert svc.effective_batch_size() == expected


def test_embed_wave_uses_dynamic_size_and_bulk_flag_flip():
    svc, store, pub = make_embedding(1.0, batch_size=4)   # wave = 2
    chunk_ids = []
    for i in range(5):
        cid = f"c{i}"
        store.insert_document("chunks", {
            "chunk_id": cid, "thread_id": "t0", "message_doc_id": "m0",
            "source_id": "s0", "text": "hello",
            "embedding_generated": False})
        chunk_ids.append(cid)
    waves = []
    orig = svc.provider.embed_batch
    svc.provider.embed_batch = lambda texts: (
        waves.append(len(texts)) or orig(texts))
    bulk = {"n": 0}
    orig_bulk = store.update_documents
    store.update_documents = lambda *a, **k: (bulk.__setitem__(
        "n", bulk["n"] + 1) or orig_bulk(*a, **k))
    assert svc.process_chunks(chunk_ids) == 5
    assert waves == [2, 2, 1]            # occupancy-sized waves
    assert bulk["n"] == 3                # one bulk flip per wave
    docs = store.query_documents("chunks", {})
    assert all(d["embedding_generated"] for d in docs)
    assert len(pub.of(ev.EmbeddingsGenerated)) == 1


# -- service-level saturation snapshot cache --------------------------------


def test_saturation_snapshot_shared_across_pool_workers():
    class CountingPublisher:
        saturation_refresh_s = 30.0

        def __init__(self):
            self.polls = 0

        def saturation(self):
            self.polls += 1
            return {"json.parsed": 99}

        def publish(self, *a, **k):
            pass

    from copilot_for_consensus_tpu.services.base import BaseService

    pub = CountingPublisher()
    svc = BaseService(pub, InMemoryDocumentStore(),
                      metrics=InMemoryMetrics(),
                      throttle_pause_s=0.0)
    for _ in range(20):
        svc._bus_throttle()
    # N events (across N workers) share ONE poll per refresh window
    assert pub.polls == 1
    # every event still throttled off the shared snapshot
    assert svc.metrics.counter_value("bus_throttle_total",
                                     {"service": "base"}) == 20


def test_saturation_snapshot_refreshes_after_ttl():
    class CountingPublisher:
        saturation_refresh_s = 0.02

        def __init__(self):
            self.polls = 0

        def saturation(self):
            self.polls += 1
            return {}

    from copilot_for_consensus_tpu.services.base import BaseService

    pub = CountingPublisher()
    svc = BaseService(pub, InMemoryDocumentStore(),
                      metrics=InMemoryMetrics())
    svc._bus_throttle()
    time.sleep(0.04)
    svc._bus_throttle()
    assert pub.polls == 2


# -- StageWorkerPool lifecycle ---------------------------------------------


class StubSubscriber:
    def __init__(self):
        self._stop = threading.Event()
        self.started = threading.Event()
        self.label_seen = ""
        self.closed = False

    def start_consuming(self):
        self.label_seen = trace.worker_label()
        self.started.set()
        while not self._stop.wait(0.01):
            pass

    def stop(self):
        self._stop.set()

    def close(self):
        self.closed = True


def test_stage_worker_pool_lifecycle_and_labels():
    subs = [StubSubscriber() for _ in range(3)]
    pool = StageWorkerPool("chunking", subs)
    assert pool.workers == 3
    pool.start()
    assert all(s.started.wait(2) for s in subs)
    # idempotent start: no thread leak while workers live
    pool.start()
    assert len(pool._threads) == 3
    assert sorted(s.label_seen for s in subs) == [
        "chunking-w0", "chunking-w1", "chunking-w2"]
    pool.stop()
    assert pool.join(timeout=5)
    assert not any(t.is_alive() for t in pool._threads)
    # the worker label never leaks onto the pool owner's thread
    assert trace.worker_label() == ""
    pool.close()
    assert all(s.closed for s in subs)


# -- runner wiring ----------------------------------------------------------


def test_build_pipeline_rejects_unknown_services_key():
    from copilot_for_consensus_tpu.services.runner import build_pipeline

    with pytest.raises(ValueError, match="unknown services"):
        build_pipeline({"services": {"chunker": {"workers": 4}}})


def test_build_pipeline_inproc_ignores_worker_pools():
    from copilot_for_consensus_tpu.services.runner import build_pipeline

    p = build_pipeline({"services": {"chunking": {"workers": 4}}})
    assert p.worker_pools == []          # pools are an ext-bus feature
    assert len(p.subscribers) == 7


def test_embedding_wave_merges_events_and_publishes_per_envelope():
    svc, store, pub = make_embedding(None, batch_size=64)
    for i in range(6):
        store.insert_document("chunks", {
            "chunk_id": f"c{i}", "thread_id": f"t{i % 2}",
            "message_doc_id": f"m{i}", "source_id": "s0",
            "text": "hello", "embedding_generated": False})
    events = [ev.ChunksPrepared(message_doc_id=f"m{i}", thread_id="",
                                archive_id="a0",
                                chunk_ids=[f"c{2 * i}", f"c{2 * i + 1}"]
                                ).to_envelope() for i in range(3)]
    provider_calls = []
    orig = svc.provider.embed_batch
    svc.provider.embed_batch = lambda texts: (
        provider_calls.append(len(texts)) or orig(texts))
    assert svc.handle_envelopes(events) == [None, None, None]
    # whole wave in ONE provider call (6 ≤ effective batch)
    assert provider_calls == [6]
    assert all(d["embedding_generated"]
               for d in store.query_documents("chunks", {}))
    gen = pub.of(ev.EmbeddingsGenerated)
    assert len(gen) == 3                    # one per envelope
    assert sorted(c for e in gen for c in e.chunk_ids) == [
        f"c{i}" for i in range(6)]
    # replayed wave: nothing re-embedded, nothing re-published
    pub.events.clear()
    provider_calls.clear()
    assert svc.handle_envelopes(events) == [None, None, None]
    assert provider_calls == []
    assert pub.of(ev.EmbeddingsGenerated) == []


def test_orchestrator_wave_dedupes_threads_to_last_event():
    from copilot_for_consensus_tpu.services.orchestrator import (
        OrchestrationService,
    )

    store = InMemoryDocumentStore()
    pub = CapturePublisher()
    svc = OrchestrationService(pub, store, retry=fast_retry(),
                               metrics=InMemoryMetrics())
    orchestrated = []
    svc.orchestrate_thread = lambda tid, corr="": orchestrated.append(
        (tid, corr))
    events = [
        ev.EmbeddingsGenerated(chunk_ids=["c1"], thread_ids=["t1"],
                               correlation_id="e0").to_envelope(),
        ev.EmbeddingsGenerated(chunk_ids=["c2"],
                               thread_ids=["t1", "t2"],
                               correlation_id="e1").to_envelope(),
        ev.EmbeddingsGenerated(chunk_ids=["c3"], thread_ids=["t1"],
                               correlation_id="e2").to_envelope(),
    ]
    assert svc.handle_envelopes(events) == [None, None, None]
    # each unique thread orchestrated ONCE, owned by its LAST event
    assert sorted(orchestrated) == [("t1", "e2"), ("t2", "e1")]


# -- review-pass regressions ------------------------------------------------


def test_embedding_wave_unknown_event_nacks_not_acks():
    """An event whose chunks are ALL invisible (store-visibility race)
    must come back as a retryable outcome — never a silent ack that
    strands its thread behind the orchestrator debounce — while the
    rest of the wave proceeds."""
    from copilot_for_consensus_tpu.core.retry import RetryableError

    svc, store, pub = make_embedding(None)
    store.insert_document("chunks", {
        "chunk_id": "c0", "thread_id": "t0", "message_doc_id": "m0",
        "source_id": "s0", "text": "x", "embedding_generated": False})
    events = [
        ev.ChunksPrepared(message_doc_id="m0", thread_id="t0",
                          archive_id="a", chunk_ids=["c0"]).to_envelope(),
        ev.ChunksPrepared(message_doc_id="m9", thread_id="t9",
                          archive_id="a",
                          chunk_ids=["ghost1", "ghost2"]).to_envelope(),
    ]
    outcomes = svc.handle_envelopes(events)
    assert outcomes[0] is None
    assert isinstance(outcomes[1], RetryableError)
    assert len(pub.of(ev.EmbeddingsGenerated)) == 1
    # no terminal failure event: the envelope redelivers instead
    assert pub.of(ev.EmbeddingGenerationFailed) == []


def test_wave_finisher_retryable_error_is_transient_not_poison():
    """A RetryableError from a finisher (the orchestrator's
    DocumentNotFoundError on the thread-doc visibility race) must nack
    for redelivery, not quarantine + *Failed."""
    from copilot_for_consensus_tpu.core.retry import (
        DocumentNotFoundError,
        RetryableError,
    )
    from copilot_for_consensus_tpu.services.orchestrator import (
        OrchestrationService,
    )

    store = InMemoryDocumentStore()
    pub = CapturePublisher()
    svc = OrchestrationService(pub, store, retry=fast_retry(),
                               metrics=InMemoryMetrics())

    def raise_nf(tid, corr=""):
        raise DocumentNotFoundError(f"thread {tid} not in store")

    svc.orchestrate_thread = raise_nf
    env = ev.EmbeddingsGenerated(chunk_ids=["c1"],
                                 thread_ids=["t1"]).to_envelope()
    (outcome,) = svc.handle_envelopes([env])
    assert isinstance(outcome, RetryableError)
    assert not isinstance(outcome, PoisonEnvelope)
    assert pub.of(ev.OrchestrationFailed) == []


def test_parsing_wave_redelivery_republish_covers_crash_window():
    """Messages inserted by a crashed previous attempt (stored,
    unchunked, events never published) must republish on redelivery —
    the bulk-insert path widened the old per-message crash window to
    the whole wave."""
    svc, store, archive_store, pub = make_parsing()
    (aid,) = seed_archives(store, archive_store, 1, 3)
    env = ev.ArchiveIngested(archive_id=aid, source_id="s0",
                             archive_uri="u").to_envelope()
    assert svc.handle_envelopes([env]) == [None]
    assert len(pub.of(ev.JSONParsed)) == 3
    # crash-window simulation: chunking never ran (chunked stays
    # False), the event redelivers → the publishes regenerate
    pub.events.clear()
    assert svc.handle_envelopes([env]) == [None]
    assert len(pub.of(ev.JSONParsed)) == 3
    # once chunked, redelivery goes quiet again
    for d in store.query_documents("messages", {}):
        store.update_document("messages", d["message_doc_id"],
                              {"chunked": True})
    pub.events.clear()
    assert svc.handle_envelopes([env]) == [None]
    assert pub.of(ev.JSONParsed) == []
