"""Multi-host runtime (parallel/multihost.py): config validation in-proc
and a REAL two-process CPU cluster exchanging XLA collectives over the
distributed runtime — the DCN tier of SURVEY §5's two-tier comms design,
exercised without TPU pod hardware."""

from __future__ import annotations

import json
import pathlib
import socket
import subprocess
import sys
import textwrap

import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu.parallel.multihost import MultiHostConfig

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_config_parsing_and_validation():
    cfg = MultiHostConfig.from_config({
        "coordinator_address": "10.0.0.1:8476",
        "num_processes": 4, "process_id": 2})
    assert cfg.is_explicit
    cfg.validate()

    with pytest.raises(ValueError, match="needs num_processes"):
        MultiHostConfig(coordinator_address="x:1").validate()
    with pytest.raises(ValueError, match="out of range"):
        MultiHostConfig(coordinator_address="x:1", num_processes=2,
                        process_id=2).validate()
    # implicit (TPU-pod auto) config validates trivially — including the
    # `multihost: true` / empty-section config-file spellings
    MultiHostConfig().validate()
    assert not MultiHostConfig.from_config(True).is_explicit
    assert not MultiHostConfig.from_config({}).is_explicit
    # stray geometry without a coordinator is a config error, not a
    # silent fall-through into auto-discovery
    with pytest.raises(ValueError, match="without"):
        MultiHostConfig(num_processes=4, process_id=2).validate()


def test_single_process_explicit_is_noop():
    from copilot_for_consensus_tpu.parallel.multihost import (
        initialize_multihost,
    )

    assert initialize_multihost({
        "coordinator_address": "127.0.0.1:1", "num_processes": 1,
        "process_id": 0}) is False


_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, "@REPO@")
    from copilot_for_consensus_tpu.parallel.multihost import (
        MultiHostConfig, initialize_multihost, is_multihost,
        process_count)
    initialize_multihost(MultiHostConfig(
        coordinator_address="@COORD@", num_processes=2,
        process_id=int(sys.argv[1])))
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert process_count() == 2 and is_multihost()
    devs = jax.devices()
    assert len(devs) == 4, devs          # 2 procs x 2 local cpu devices
    mesh = Mesh(devs, ("dp",))
    # Each process contributes its local shards; the all-reduce GSPMD
    # inserts for the replicated output crosses the process boundary
    # through the distributed runtime (the DCN tier).
    arr = jax.make_array_from_callback(
        (4,), NamedSharding(mesh, P("dp")),
        lambda idx: jnp.asarray(
            [float(idx[0].start if idx[0].start else 0) + 1.0]))
    total = jax.jit(
        lambda x: jnp.sum(x),
        out_shardings=NamedSharding(mesh, P()),
    )(arr)
    # shards hold [1, 2, 3, 4] -> sum = 10 everywhere
    local = jax.device_get(total.addressable_shards[0].data)
    print(json.dumps({"rank": int(sys.argv[1]),
                      "psum": float(jnp.asarray(local).reshape(-1)[0])}),
          flush=True)
""")


def test_two_process_cpu_cluster_psum(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.replace("@REPO@", str(REPO))
                      .replace("@COORD@", coord))

    procs = [subprocess.Popen(
        [sys.executable, str(script), str(rank)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin"})
        for rank in (0, 1)]
    outs = []
    for p in procs:
        out, errtxt = p.communicate(timeout=150)
        assert p.returncode == 0, errtxt[-2000:]
        outs.append(json.loads(out.strip().splitlines()[-1]))
    assert {o["rank"] for o in outs} == {0, 1}
    assert all(o["psum"] == 10.0 for o in outs)


# -- multi-process SERVING (round-5 verdict item 4) ---------------------
#
# The dryrun phases and the psum test above prove collectives and
# compilation; this proves the serving layer itself: a GenerationEngine
# jitted over a dp=2 x tp=2 mesh spanning TWO processes, requests
# arriving over the durable broker, completions published back, and a
# crash-while-holding-leases recovered by the broker's lease expiry
# (the retry spine's transport tier).

_SERVE_WORKER = textwrap.dedent("""
    import json, os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, "@REPO@")
    rank = int(sys.argv[1]); mode = sys.argv[2]; total = int(sys.argv[3])
    from copilot_for_consensus_tpu.bus.broker import (
        BrokerPublisher, _Client)

    BROKER = "@BROKER@"
    cli = _Client(BROKER)
    cli.request({"op": "bind", "rks": ["serve.request"], "group": "svc"})

    def fetch_requests(max_n=4, wait_s=15.0):
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            r = cli.request({"op": "fetch", "rks": ["serve.request"],
                             "group": "svc", "max": max_n})
            if r.get("msgs"):
                return r["msgs"]
            time.sleep(0.2)
        return []

    if mode == "crash":
        # Lease a batch, then die WITHOUT serving or acking: recovery
        # = the broker re-leases these to the next incarnation.
        held = fetch_requests() if rank == 0 else []
        print(json.dumps({"rank": rank, "crashed_holding": len(held)}),
              flush=True)
        sys.exit(0)

    from copilot_for_consensus_tpu.parallel.multihost import (
        MultiHostConfig, initialize_multihost)
    initialize_multihost(MultiHostConfig(
        coordinator_address="@COORD@", num_processes=2, process_id=rank))
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from copilot_for_consensus_tpu.engine.generation import (
        GenerationEngine)
    from copilot_for_consensus_tpu.models import decoder
    from copilot_for_consensus_tpu.models.configs import decoder_config

    assert len(jax.devices()) == 4      # 2 procs x 2 local cpu devices
    cfg = decoder_config("tiny")
    # identical seed => identical params on both ranks (SPMD lockstep)
    params = decoder.init_params(jax.random.PRNGKey(7), cfg,
                                 dtype=jnp.float32)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2), ("dp", "tp"))
    eng = GenerationEngine(cfg, params, mesh=mesh, num_slots=4,
                           max_len=64, prefill_buckets=(16,),
                           dtype=jnp.float32, attn_impl="xla",
                           decode_window=4)

    # Rank 0 leads: it owns the request leases and publishes each batch
    # to rank 1 (its own queue group) so BOTH ranks drive the identical
    # jit sequence — the broker is the control plane, XLA collectives
    # the data plane (SURVEY two-tier comms).
    # Progress is counted in UNIQUE request ids, not fetch sizes: the
    # broker is at-least-once (a slow first batch — compile time — can
    # outlive its lease, so its ack no-ops and the batch REDELIVERS).
    # Counting fetches would then hit `total` before later requests
    # were ever fetched; unique-id accounting serves every request no
    # matter how deliveries repeat.
    seen = set()
    if rank == 0:
        pub = BrokerPublisher({"address": BROKER})
        while len(seen) < total:
            msgs = fetch_requests()
            if not msgs:
                break
            reqs = [m["envelope"] for m in msgs]
            pub.publish_envelope({"event_type": "serve_batch",
                                  "reqs": reqs}, "serve.batch")
            comps = eng.generate([r["prompt"] for r in reqs],
                                 max_new_tokens=6)
            for r, c in zip(reqs, comps):
                pub.publish_envelope(
                    {"event_type": "serve_done",
                     "request_id": r["request_id"],
                     "tokens": list(c.tokens)}, "serve.done")
            # ack ONLY after completions are durably published: a crash
            # before this line re-leases the whole batch (at-least-once)
            cli.request({"op": "ack", "ids": [m["id"] for m in msgs]})
            seen.update(r["request_id"] for r in reqs)
        pub.publish_envelope({"event_type": "serve_batch", "reqs": []},
                             "serve.batch")
    else:
        bcli = _Client(BROKER)
        bcli.request({"op": "bind", "rks": ["serve.batch"],
                      "group": "rank1"})
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            r = bcli.request({"op": "fetch", "rks": ["serve.batch"],
                              "group": "rank1", "max": 1})
            msgs = r.get("msgs") or []
            if not msgs:
                time.sleep(0.1)
                continue
            env = msgs[0]["envelope"]
            bcli.request({"op": "ack", "ids": [msgs[0]["id"]]})
            if not env["reqs"]:
                break
            eng.generate([q["prompt"] for q in env["reqs"]],
                         max_new_tokens=6)
            seen.update(q["request_id"] for q in env["reqs"])
    print(json.dumps({"rank": rank, "served": len(seen)}), flush=True)
""")


def _spawn_serve_workers(script: pathlib.Path, mode: str, total: int):
    return [subprocess.Popen(
        [sys.executable, str(script), str(rank), mode, str(total)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin"})
        for rank in (0, 1)]


def test_two_process_serving_over_broker_with_crash_recovery(tmp_path):
    import numpy as np

    from copilot_for_consensus_tpu.bus.broker import (
        Broker,
        BrokerPublisher,
        BrokerSubscriber,
    )

    broker = Broker(port=0, db_path=str(tmp_path / "queues.db"),
                    lease_s=3.0).start()
    try:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            coord = f"127.0.0.1:{s.getsockname()[1]}"
        script = tmp_path / "serve_worker.py"
        script.write_text(_SERVE_WORKER.replace("@REPO@", str(REPO))
                          .replace("@COORD@", coord)
                          .replace("@BROKER@", broker.address))

        rng = np.random.default_rng(3)
        pub = BrokerPublisher({"address": broker.address})
        n_requests = 8
        for i in range(n_requests):
            pub.publish_envelope({
                "event_type": "serve_request",
                "request_id": f"req-{i}",
                "prompt": rng.integers(3, 500, size=7).tolist(),
            }, "serve.request")

        # Phase 1: the engine host crashes while HOLDING leased
        # requests, before serving or acking any of them.
        crash = _spawn_serve_workers(script, "crash", n_requests)
        held = 0
        for p in crash:
            out, err = p.communicate(timeout=60)
            assert p.returncode == 0, err[-2000:]
            held += json.loads(out.strip().splitlines()[-1]
                               )["crashed_holding"]
        assert held > 0, "crash phase must die holding leases"

        # Phase 2: fresh incarnation. The broker re-leases the crashed
        # batch after lease_s; ALL requests must complete exactly once
        # (ack-after-publish makes redelivery at-least-once; the
        # request_id set proves full coverage).
        import time as _t
        _t.sleep(3.2)                    # let the crashed leases expire
        procs = _spawn_serve_workers(script, "serve", n_requests)
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=420)
            assert p.returncode == 0, err[-3000:]
            outs.append(json.loads(out.strip().splitlines()[-1]))
        assert {o["rank"] for o in outs} == {0, 1}
        # both ranks drove every request through the SPMD engine
        assert all(o["served"] == n_requests for o in outs), outs

        got: dict[str, list[int]] = {}
        sub = BrokerSubscriber({"address": broker.address}, group="test")
        sub.subscribe(["serve.done"],
                      lambda e: got.setdefault(e["request_id"],
                                               e["tokens"]))
        sub.drain()
        assert set(got) == {f"req-{i}" for i in range(n_requests)}
        assert all(len(toks) > 0 for toks in got.values())
    finally:
        broker.stop()
