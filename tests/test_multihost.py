"""Multi-host runtime (parallel/multihost.py): config validation in-proc
and a REAL two-process CPU cluster exchanging XLA collectives over the
distributed runtime — the DCN tier of SURVEY §5's two-tier comms design,
exercised without TPU pod hardware."""

from __future__ import annotations

import json
import pathlib
import socket
import subprocess
import sys
import textwrap

import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu.parallel.multihost import MultiHostConfig

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_config_parsing_and_validation():
    cfg = MultiHostConfig.from_config({
        "coordinator_address": "10.0.0.1:8476",
        "num_processes": 4, "process_id": 2})
    assert cfg.is_explicit
    cfg.validate()

    with pytest.raises(ValueError, match="needs num_processes"):
        MultiHostConfig(coordinator_address="x:1").validate()
    with pytest.raises(ValueError, match="out of range"):
        MultiHostConfig(coordinator_address="x:1", num_processes=2,
                        process_id=2).validate()
    # implicit (TPU-pod auto) config validates trivially — including the
    # `multihost: true` / empty-section config-file spellings
    MultiHostConfig().validate()
    assert not MultiHostConfig.from_config(True).is_explicit
    assert not MultiHostConfig.from_config({}).is_explicit
    # stray geometry without a coordinator is a config error, not a
    # silent fall-through into auto-discovery
    with pytest.raises(ValueError, match="without"):
        MultiHostConfig(num_processes=4, process_id=2).validate()


def test_single_process_explicit_is_noop():
    from copilot_for_consensus_tpu.parallel.multihost import (
        initialize_multihost,
    )

    assert initialize_multihost({
        "coordinator_address": "127.0.0.1:1", "num_processes": 1,
        "process_id": 0}) is False


_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, "@REPO@")
    from copilot_for_consensus_tpu.parallel.multihost import (
        MultiHostConfig, initialize_multihost, is_multihost,
        process_count)
    initialize_multihost(MultiHostConfig(
        coordinator_address="@COORD@", num_processes=2,
        process_id=int(sys.argv[1])))
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert process_count() == 2 and is_multihost()
    devs = jax.devices()
    assert len(devs) == 4, devs          # 2 procs x 2 local cpu devices
    mesh = Mesh(devs, ("dp",))
    # Each process contributes its local shards; the all-reduce GSPMD
    # inserts for the replicated output crosses the process boundary
    # through the distributed runtime (the DCN tier).
    arr = jax.make_array_from_callback(
        (4,), NamedSharding(mesh, P("dp")),
        lambda idx: jnp.asarray(
            [float(idx[0].start if idx[0].start else 0) + 1.0]))
    total = jax.jit(
        lambda x: jnp.sum(x),
        out_shardings=NamedSharding(mesh, P()),
    )(arr)
    # shards hold [1, 2, 3, 4] -> sum = 10 everywhere
    local = jax.device_get(total.addressable_shards[0].data)
    print(json.dumps({"rank": int(sys.argv[1]),
                      "psum": float(jnp.asarray(local).reshape(-1)[0])}),
          flush=True)
""")


def test_two_process_cpu_cluster_psum(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.replace("@REPO@", str(REPO))
                      .replace("@COORD@", coord))

    procs = [subprocess.Popen(
        [sys.executable, str(script), str(rank)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin"})
        for rank in (0, 1)]
    outs = []
    for p in procs:
        out, errtxt = p.communicate(timeout=150)
        assert p.returncode == 0, errtxt[-2000:]
        outs.append(json.loads(out.strip().splitlines()[-1]))
    assert {o["rank"] for o in outs} == {0, 1}
    assert all(o["psum"] == 10.0 for o in outs)
