# Ulysses all-to-all sequence parallelism vs the XLA oracle on the
# virtual mesh — the alternative SP strategy to ring attention
# (SURVEY.md §2.3 "Ring attention / Ulysses").
import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu.models import decoder
from copilot_for_consensus_tpu.models.configs import decoder_config
from copilot_for_consensus_tpu.ops.attention import attention_xla
from copilot_for_consensus_tpu.parallel import MeshConfig, build_mesh
from copilot_for_consensus_tpu.parallel.ulysses import (
    make_ulysses_attention,
    ulysses_attention,
)


def _qkv(seed, b=2, hq=4, hkv=2, s=64, d=16):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(kq, (b, hq, s, d)),
            jax.random.normal(kk, (b, hkv, s, d)),
            jax.random.normal(kv, (b, hkv, s, d)))


@pytest.mark.parametrize("sp,causal", [(2, True), (4, True), (4, False)])
def test_ulysses_matches_xla(sp, causal):
    mesh = build_mesh(MeshConfig(sp=sp, tp=0))
    q, k, v = _qkv(0)
    ref = attention_xla(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=1e-2)


def test_ulysses_under_jit():
    mesh = build_mesh(MeshConfig(sp=4, tp=0))
    q, k, v = _qkv(1)
    ref = attention_xla(q, k, v, causal=True)
    out = jax.jit(
        lambda q, k, v: ulysses_attention(q, k, v, mesh=mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=1e-2)


def test_ulysses_sliding_window_and_padded_kv():
    mesh = build_mesh(MeshConfig(sp=4, tp=0))
    q, k, v = _qkv(3)
    lengths = jnp.asarray([40, 64], dtype=jnp.int32)
    ref = attention_xla(q, k, v, causal=True, window=16, kv_lengths=lengths)
    out = ulysses_attention(q, k, v, mesh=mesh, causal=True, window=16,
                            kv_lengths=lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=1e-2)


def test_ulysses_head_divisibility_rejected():
    mesh = build_mesh(MeshConfig(sp=8, tp=0))
    q, k, v = _qkv(2)  # 4 heads < 8 shards
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, k, v, mesh=mesh)


def test_decoder_forward_with_ulysses_attention():
    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(0), cfg,
                                 dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                                cfg.vocab_size)
    ref = decoder.forward(params, tokens, cfg, attn_impl="xla")
    mesh = build_mesh(MeshConfig(sp=4, tp=2))
    uly = make_ulysses_attention(mesh)
    out = decoder.forward(params, tokens, cfg, attn_impl=uly)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=2e-2)
