# Long-context serving: sp-sharded prefill + distributed-cache decode
# (VERDICT r1 item 5) vs an unsharded full-forward oracle on the 8-dev mesh.
import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu.engine.longctx import LongContextEngine
from copilot_for_consensus_tpu.engine.sampling import SamplingConfig
from copilot_for_consensus_tpu.models import decoder
from copilot_for_consensus_tpu.models.configs import decoder_config
from copilot_for_consensus_tpu.parallel import MeshConfig, build_mesh


def _greedy_oracle(params, cfg, prompt, n_steps):
    """Grow the sequence one token at a time with the plain unsharded
    forward pass — the slow-but-obviously-right reference."""
    seq = list(prompt)
    out = []
    for _ in range(n_steps):
        toks = jnp.asarray([seq], dtype=jnp.int32)
        logits = decoder.forward(params, toks, cfg)
        nxt = int(jnp.argmax(logits[0, len(seq) - 1]))
        out.append(nxt)
        seq.append(nxt)
    return out


@pytest.mark.parametrize("cfg_name", ["tiny", "tiny-swa"])
def test_longctx_matches_unsharded_greedy(cfg_name):
    """A prompt LONGER than cfg.max_seq_len serves correctly: greedy
    tokens from the sequence-parallel engine equal the unsharded oracle
    (dense + sliding-window configs)."""
    cfg = decoder_config(cfg_name)
    params = decoder.init_params(jax.random.PRNGKey(0), cfg,
                                 dtype=jnp.float32)
    mesh = build_mesh(MeshConfig(sp=8, tp=0))
    eng = LongContextEngine(cfg, params, mesh=mesh, dtype=jnp.float32,
                            sampling=SamplingConfig(temperature=0.0),
                            eos_id=-1, decode_window=4, ctx_block=16,
                            max_new_tokens=64)
    rng = np.random.default_rng(0)
    prompt = rng.integers(3, cfg.vocab_size, size=600).tolist()
    assert len(prompt) > cfg.max_seq_len     # longer than the model window
    comp = eng.generate(prompt, max_new_tokens=10)
    want = _greedy_oracle(params, cfg, prompt, 10)
    assert comp.tokens == want
    assert comp.prompt_len == 600
    assert comp.finish_reason == "length"


def test_longctx_prefill_cache_is_sequence_sharded():
    """The prefix cache must stay sharded over sp — gathering it would
    defeat the whole design."""
    cfg = decoder_config("tiny")
    mesh = build_mesh(MeshConfig(sp=8, tp=0))
    eng = LongContextEngine(cfg, mesh=mesh, dtype=jnp.float32,
                            ctx_block=16)
    s_ctx = eng.ctx_quantum
    fn = eng._build_prefill(s_ctx)
    tokens = jnp.zeros((1, s_ctx), dtype=jnp.int32)
    _, prefix = fn(eng.params, tokens, jnp.asarray([s_ctx - 3]))
    spec = prefix["k"].sharding.spec
    assert spec[3] == "sp", spec
    # Each device holds 1/8 of the sequence axis.
    shard_shape = prefix["k"].addressable_shards[0].data.shape
    assert shard_shape[3] == s_ctx // 8


def test_longctx_eos_stops_decode():
    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(1), cfg,
                                 dtype=jnp.float32)
    mesh = build_mesh(MeshConfig(sp=8, tp=0))
    eng = LongContextEngine(cfg, params, mesh=mesh, dtype=jnp.float32,
                            decode_window=4, ctx_block=16)
    prompt = list(range(3, 40))
    oracle = _greedy_oracle(params, cfg, prompt, 12)
    # Declare the 3rd greedy token as EOS: generation must stop there.
    eng2 = LongContextEngine(cfg, params, mesh=mesh, dtype=jnp.float32,
                             eos_id=oracle[2], decode_window=4,
                             ctx_block=16)
    comp = eng2.generate(prompt, max_new_tokens=12)
    assert comp.finish_reason == "eos"
    assert comp.tokens == oracle[:2]


def test_summarizer_routes_long_threads_to_longctx_engine():
    """Serving-level: a thread whose prompt exceeds the batch engine's
    window is summarized via the sequence-parallel path — not truncated."""
    from copilot_for_consensus_tpu.engine.generation import GenerationEngine
    from copilot_for_consensus_tpu.summarization.base import ThreadContext
    from copilot_for_consensus_tpu.summarization.tpu_summarizer import (
        TPUSummarizer,
    )

    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(2), cfg,
                                 dtype=jnp.float32)
    mesh = build_mesh(MeshConfig(sp=8, tp=0))
    short = GenerationEngine(cfg, params, num_slots=2, max_len=128,
                             dtype=jnp.float32)
    long_eng = LongContextEngine(cfg, params, mesh=mesh,
                                 dtype=jnp.float32, eos_id=-1,
                                 ctx_block=16, decode_window=4)
    summ = TPUSummarizer(engine=short, long_engine=long_eng,
                         max_new_tokens=8)
    # ~8 chunks of dense text → a ByteTokenizer prompt far beyond 128.
    chunks = [{"chunk_id": f"c{i}", "text": "consensus " * 40}
              for i in range(8)]
    thread = ThreadContext(thread_id="t-long", subject="big thread",
                           participants=["a@x", "b@y"], message_count=8,
                           chunks=chunks)
    calls = {}
    orig = long_eng.generate

    def spy(prompt, max_new_tokens=256):
        calls["len"] = len(prompt)
        return orig(prompt, max_new_tokens)

    long_eng.generate = spy
    s = summ.summarize(thread)
    assert calls["len"] > summ._short_limit      # long path actually ran
    assert s.prompt_tokens == calls["len"]       # and was NOT truncated
    assert s.thread_id == "t-long"
    assert len(s.citations) == 8


def test_longctx_ulysses_matches_ring():
    """The engine's two SP strategies agree on the same prompt."""
    import jax

    from copilot_for_consensus_tpu.engine.longctx import LongContextEngine
    from copilot_for_consensus_tpu.models import DecoderConfig
    from copilot_for_consensus_tpu.parallel import MeshConfig, build_mesh

    cfg = DecoderConfig(vocab_size=64, n_layers=2, d_model=32, n_heads=4,
                        n_kv_heads=2, d_ff=64, max_seq_len=2048)
    mesh = build_mesh(MeshConfig(dp=2, sp=4, ep=1, tp=1))
    params = None
    outs = {}
    for impl in ("ring", "ulysses"):
        eng = LongContextEngine(cfg, params, mesh=mesh, sp_impl=impl,
                                max_new_tokens=8, seed=7)
        params = eng.params  # share exact weights across impls
        outs[impl] = eng.generate(list(range(1, 40)), max_new_tokens=6)
    assert outs["ring"].tokens == outs["ulysses"].tokens


def test_longctx_int4_params_on_tp_mesh():
    """ADVICE r2 (medium): an int4 param tree must construct and serve —
    the engine has to detect the quant mode (not assume int8) and the
    int4 scale's group axis must shard on a tp mesh even when one group
    spans the whole contraction axis (G=1 on tiny's d_model=128)."""
    from copilot_for_consensus_tpu.models import quant

    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(3), cfg,
                                 dtype=jnp.float32)
    qparams = quant.quantize_params(params, mode="int4")
    assert qparams["layers"]["wq"]["scale"].shape[-2] == 1  # G == 1
    mesh = build_mesh(MeshConfig(sp=4, tp=2))
    eng = LongContextEngine(cfg, qparams, mesh=mesh, dtype=jnp.float32,
                            sampling=SamplingConfig(temperature=0.0),
                            eos_id=-1, decode_window=4, ctx_block=16)
    comp = eng.generate(list(range(3, 80)), max_new_tokens=6)
    # Oracle: greedy over the dequantized weights, unsharded.
    deq = jax.tree.map(
        lambda a: a,
        {**params, "layers": dict(params["layers"])})
    for path in quant.DECODER_QUANT_LEAVES:
        node = deq
        for p in path[:-1]:
            node = node[p]
        leaf = qparams
        for p in path:
            leaf = leaf[p]
        node[path[-1]] = quant.dequant_int4(leaf, jnp.float32)
    want = _greedy_oracle(deq, cfg, list(range(3, 80)), 6)
    assert comp.tokens == want
