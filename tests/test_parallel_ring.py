# Ring attention (sequence parallelism) vs the XLA oracle on the 8-dev mesh.
import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu.models import decoder
from copilot_for_consensus_tpu.models.configs import decoder_config
from copilot_for_consensus_tpu.ops.attention import attention_xla
from copilot_for_consensus_tpu.parallel import MeshConfig, build_mesh
from copilot_for_consensus_tpu.parallel.ring import (
    make_ring_attention,
    ring_attention,
)


def _qkv(seed, b=2, hq=4, hkv=2, s=64, d=16):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(kq, (b, hq, s, d)),
            jax.random.normal(kk, (b, hkv, s, d)),
            jax.random.normal(kv, (b, hkv, s, d)))


@pytest.mark.parametrize("sp,causal", [(2, True), (4, True), (4, False)])
def test_ring_matches_xla(sp, causal):
    mesh = build_mesh(MeshConfig(sp=sp, tp=0))
    q, k, v = _qkv(0)
    ref = attention_xla(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=1e-2)


def test_ring_under_jit():
    mesh = build_mesh(MeshConfig(sp=4, tp=0))
    q, k, v = _qkv(1)
    ref = attention_xla(q, k, v, causal=True)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh=mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=1e-2)


def test_non_divisible_sequence_rejected():
    mesh = build_mesh(MeshConfig(sp=4, tp=0))
    q, k, v = _qkv(2, s=30)
    with pytest.raises(ValueError):
        ring_attention(q, k, v, mesh=mesh)


def test_decoder_forward_with_ring_attention():
    # Whole-model long-context forward: attention runs on the sp ring,
    # everything else shards the sequence via GSPMD.
    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(0), cfg,
                                 dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                                cfg.vocab_size)
    ref = decoder.forward(params, tokens, cfg, attn_impl="xla")
    mesh = build_mesh(MeshConfig(sp=4, tp=2))
    ring = make_ring_attention(mesh)
    out = decoder.forward(params, tokens, cfg, attn_impl=ring)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=2e-2)


@pytest.mark.parametrize("window", [16, 48])
def test_ring_sliding_window_matches_xla(window):
    """Mistral-style sliding-window masking, in global coordinates across
    rotated blocks (VERDICT r1 weak #5)."""
    mesh = build_mesh(MeshConfig(sp=4, tp=0))
    q, k, v = _qkv(3)
    ref = attention_xla(q, k, v, causal=True, window=window)
    out = ring_attention(q, k, v, mesh=mesh, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=1e-2)


def test_ring_padded_kv_matches_xla():
    """Right-padded batch rows mask their tail, wherever it lands on the
    ring."""
    mesh = build_mesh(MeshConfig(sp=4, tp=0))
    q, k, v = _qkv(4)
    kv_lengths = jnp.array([37, 64])
    ref = attention_xla(q, k, v, causal=True, kv_lengths=kv_lengths)
    out = ring_attention(q, k, v, mesh=mesh, causal=True,
                         kv_lengths=kv_lengths)
    # Padded *query* rows attend to nothing and the two impls may emit
    # garbage vs zeros there; compare valid query positions only.
    for b, ln in enumerate([37, 64]):
        np.testing.assert_allclose(np.asarray(out)[b, :, :ln],
                                   np.asarray(ref)[b, :, :ln],
                                   rtol=2e-2, atol=1e-2)


def test_ring_window_and_padded_kv_combined():
    mesh = build_mesh(MeshConfig(sp=2, tp=0))
    q, k, v = _qkv(5)
    kv_lengths = jnp.array([50, 29])
    ref = attention_xla(q, k, v, causal=True, window=24,
                        kv_lengths=kv_lengths)
    out = ring_attention(q, k, v, mesh=mesh, causal=True, window=24,
                         kv_lengths=kv_lengths)
    for b, ln in enumerate([50, 29]):
        np.testing.assert_allclose(np.asarray(out)[b, :, :ln],
                                   np.asarray(ref)[b, :, :ln],
                                   rtol=2e-2, atol=1e-2)
