# OpenAPI generation: spec ↔ router sync, served endpoint, UI static.
import json
import pathlib
import urllib.request

import pytest

from copilot_for_consensus_tpu.security.jwt import HAS_CRYPTOGRAPHY
from copilot_for_consensus_tpu.services.bootstrap import serve_pipeline

REPO = pathlib.Path(__file__).resolve().parent.parent
SPEC_PATH = (REPO / "copilot_for_consensus_tpu" / "schemas" /
             "openapi.json")

# building the live router instantiates the auth stack's default
# local_rs256 signer, which needs the optional 'cryptography' wheel
requires_crypto = pytest.mark.skipif(
    not HAS_CRYPTOGRAPHY,
    reason="optional 'cryptography' package not installed (the router's "
           "default RS256 auth signer needs RSA primitives)")


@requires_crypto
def test_committed_spec_matches_router():
    """The committed spec must equal what the live router generates —
    same single-source contract as the event-schema sync test."""
    import sys
    sys.path.insert(0, str(REPO / "scripts"))
    try:
        import generate_openapi as gen
    finally:
        sys.path.pop(0)
    assert SPEC_PATH.exists(), "run scripts/generate_openapi.py"
    committed = json.loads(SPEC_PATH.read_text())
    assert gen.build_spec() == committed, \
        "openapi.json is stale — rerun scripts/generate_openapi.py"


def test_spec_covers_core_surface():
    spec = json.loads(SPEC_PATH.read_text())
    paths = spec["paths"]
    for p in ("/api/sources", "/api/sources/{source_id}",
              "/api/reports", "/api/reports/{report_id}",
              "/api/threads/{thread_id}/messages", "/api/upload",
              "/auth/login", "/auth/admin/users/{email}", "/health"):
        assert p in paths, p
    # Auth-guarded ops carry the bearer requirement; public ones don't.
    assert "security" in paths["/api/sources"]["get"]
    assert "security" not in paths["/auth/login"]["get"]
    # Path params are declared.
    params = paths["/api/sources/{source_id}"]["get"]["parameters"]
    assert params[0]["name"] == "source_id"


def _get(url, token=None):
    req = urllib.request.Request(url)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


def test_gateway_serves_spec_and_ui():
    server = serve_pipeline().start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        status, ctype, body = _get(base + "/api/openapi.json")
        assert status == 200
        spec = json.loads(body)
        assert spec["openapi"].startswith("3.1")
        status, ctype, body = _get(base + "/")
        assert status == 200 and "text/html" in ctype
        assert b"CoPilot" in body
        status, ctype, body = _get(base + "/ui/app.js")
        assert status == 200 and "javascript" in ctype
        status, ctype, body = _get(base + "/ui/style.css")
        assert status == 200 and "text/css" in ctype
    finally:
        server.stop()


def test_ui_asset_traversal_rejected():
    server = serve_pipeline().start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        try:
            status, _, _ = _get(base + "/ui/%2e%2e%2fpyproject.toml")
        except urllib.error.HTTPError as exc:
            status = exc.code
        assert status == 404
    finally:
        server.stop()


@requires_crypto
def test_ui_public_but_api_guarded_when_auth_on():
    server = serve_pipeline({
        "auth": {"require_auth": True, "allow_insecure_mock": True},
    }).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        status, _, _ = _get(base + "/")                  # SPA shell: public
        assert status == 200
        try:
            status, _, _ = _get(base + "/api/reports")   # API: guarded
        except urllib.error.HTTPError as exc:
            status = exc.code
        assert status == 401
    finally:
        server.stop()


import urllib.error  # noqa: E402  (used in except clauses above)


@requires_crypto
def test_committed_service_specs_match_router():
    """Per-service OpenAPI slices (scripts/generate_service_openapi.py)
    must tile the unified spec exactly and stay fresh."""
    import importlib.util
    import json
    import pathlib
    import sys

    repo = pathlib.Path(__file__).resolve().parent.parent
    sys.path.insert(0, str(repo / "scripts"))
    spec_mod = importlib.util.spec_from_file_location(
        "gen_svc_openapi", repo / "scripts" / "generate_service_openapi.py")
    gen = importlib.util.module_from_spec(spec_mod)
    spec_mod.loader.exec_module(gen)
    from generate_openapi import build_spec

    slices = gen.slice_spec(build_spec())
    out_dir = repo / "copilot_for_consensus_tpu" / "schemas" / "openapi"
    committed = {p.stem: json.loads(p.read_text())
                 for p in out_dir.glob("*.json")}
    assert set(committed) == set(slices)
    for svc, want in slices.items():
        assert committed[svc] == want, (
            f"{svc} spec stale; rerun scripts/generate_service_openapi.py")
