# The Pallas paged kernel as the production decode route (ISSUE 16):
# interpret-mode parity of the partial kernel + combine_partials fold
# against the XLA reference across GQA ratios, sliding windows, fp8
# pools, mixed fill levels, and parked rows; the kv_kernel constructor
# guards; the no-materialization trace gate (no paged dispatch on the
# kernel route may call paged_gather_kv — the test fails if the
# materializing gather reappears in a traced program); and engine-level
# greedy token equality between the kernel and reference routes across
# the plain, prefix-cache, spec-decode, and chunked-prefill paths.
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from copilot_for_consensus_tpu.engine.kv_pool import BLOCK_TABLE_DTYPE
from copilot_for_consensus_tpu.models.configs import decoder_config

CFG = decoder_config("tiny")


def _params():
    from copilot_for_consensus_tpu.models import decoder

    return decoder.init_params(jax.random.PRNGKey(7), CFG,
                               dtype=jnp.float32)


def _engine(params, route, **kw):
    from copilot_for_consensus_tpu.engine.generation import (
        GenerationEngine,
    )

    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 256)
    kw.setdefault("prefill_buckets", (64, 128, 192))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("kv_dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("decode_window", 4)
    kw.setdefault("prefill_chunk", 64)
    kw.setdefault("kv_pool_blocks", 12)
    return GenerationEngine(CFG, params, kv_kernel=route, **kw)


# ---------------------------------------------------------------------------
# partial kernel: interpret-mode parity against the XLA reference
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("hq,hkv", [(8, 2), (4, 4), (8, 1)])
@pytest.mark.parametrize("fp8", [False, True])
@pytest.mark.parametrize("window", [0, 5])
def test_partial_kernel_decode_parity(hq, hkv, fp8, window):
    """The kernel route's decode shape: the pool partial alone IS the
    whole kv prefix, so combine_partials of one piece must match the
    gathered reference — across GQA ratios, sliding window, fp8
    dequant-on-load, mixed fill levels, and a parked (length-0) row
    that must emit exact zeros."""
    from copilot_for_consensus_tpu.ops.attention import (
        combine_partials,
        decode_attention,
    )
    from copilot_for_consensus_tpu.ops.paged_attention import (
        paged_attention_partial_pallas,
        paged_gather_layer,
    )

    rng = np.random.default_rng(2)
    b, d, blk, nbtot, nb, nl, li = 4, 16, 8, 12, 4, 3, 2
    q = jnp.asarray(rng.standard_normal((b, hq, d)), jnp.float32)
    pk = jnp.asarray(rng.standard_normal((nl, nbtot, hkv, blk, d)),
                     jnp.float32)
    pv = jnp.asarray(rng.standard_normal((nl, nbtot, hkv, blk, d)),
                     jnp.float32)
    if fp8:
        pk = pk.astype(jnp.float8_e4m3fn)
        pv = pv.astype(jnp.float8_e4m3fn)
    tables = jnp.asarray(rng.integers(0, nbtot, (b, nb)),
                         BLOCK_TABLE_DTYPE)
    # parked row, single token, full table, mid-block fill
    lengths = jnp.asarray([0, 1, blk * nb, 17], jnp.int32)

    k, v = paged_gather_layer(pk[li], pv[li], tables)
    ref = decode_attention(q, k, v, lengths, window=window)
    part = paged_attention_partial_pallas(
        q.reshape(b, hkv, hq // hkv, d), pk, pv,
        jnp.asarray([li], jnp.int32), tables, lengths, lengths - 1,
        window=window, interpret=True)
    got = combine_partials([part], jnp.float32).reshape(b, hq, d)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               atol=1e-5)
    assert bool(jnp.all(got[0] == 0.0))        # parked row: exact zeros


def test_partial_kernel_seeded_rows_parity():
    """The seeded shape (R = group * S query rows): pool partial from
    the kernel + the XLA causal-suffix partial folded by
    combine_partials must match a dense joint softmax over
    [pool prefix | causal suffix] — including a zero-prefix row whose
    pool piece is fully masked."""
    from copilot_for_consensus_tpu.ops.attention import (
        causal_suffix_partial,
        combine_partials,
    )
    from copilot_for_consensus_tpu.ops.paged_attention import (
        paged_attention_partial_pallas,
        paged_gather_layer,
    )

    rng = np.random.default_rng(3)
    b, hkv, g, d, blk, nbtot, nb, s = 2, 2, 2, 16, 8, 10, 3, 4
    hq = hkv * g
    q = jnp.asarray(rng.standard_normal((b, hq, s, d)), jnp.float32)
    pk = jnp.asarray(rng.standard_normal((2, nbtot, hkv, blk, d)),
                     jnp.float32)
    pv = jnp.asarray(rng.standard_normal((2, nbtot, hkv, blk, d)),
                     jnp.float32)
    ks = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    vs = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    tables = jnp.asarray(rng.integers(0, nbtot, (b, nb)),
                         BLOCK_TABLE_DTYPE)
    pref = jnp.asarray([13, 0], jnp.int32)     # mid-block + no prefix

    qr = q.reshape(b, hkv, g, s, d).reshape(b, hkv, g * s, d)
    pool_part = paged_attention_partial_pallas(
        qr, pk, pv, jnp.asarray([1], jnp.int32), tables, pref,
        pref - 1, window=0, interpret=True)
    suf_part = causal_suffix_partial(q, ks, vs)
    got = combine_partials([pool_part, suf_part], jnp.float32)

    # dense reference: joint softmax over pool positions < pref[b] and
    # suffix positions t <= s (row-major (g, s) rows, like the kernel)
    kp, vp = paged_gather_layer(pk[1], pv[1], tables)   # [b,hkv,P,d]
    qg = q.reshape(b, hkv, g, s, d)
    lp = jnp.einsum("bhgsd,bhpd->bhgsp", qg, kp) * (d ** -0.5)
    lp = jnp.where(jnp.arange(nb * blk)[None, None, None, None]
                   < pref[:, None, None, None, None], lp, -jnp.inf)
    ls = jnp.einsum("bhgsd,bhtd->bhgst", qg, ks) * (d ** -0.5)
    ls = jnp.where(jnp.arange(s)[None, None, None, None]
                   <= jnp.arange(s)[None, None, None, :, None],
                   ls, -jnp.inf)
    probs = jax.nn.softmax(jnp.concatenate([lp, ls], axis=-1), axis=-1)
    ref = jnp.einsum("bhgsp,bhpd->bhgsd", probs,
                     jnp.concatenate([vp, vs], axis=-2))
    np.testing.assert_allclose(
        np.asarray(ref.reshape(b, hkv, g * s, d)), np.asarray(got),
        atol=1e-5)


# ---------------------------------------------------------------------------
# engine construction: the kv_kernel knob's guards and resolution
# ---------------------------------------------------------------------------


def test_kv_kernel_constructor_guards_and_resolution():
    params = _params()
    with pytest.raises(ValueError, match="kv_kernel"):
        _engine(params, "cuda")
    with pytest.raises(ValueError, match="paged"):
        _engine(params, "pallas", kv_pool_blocks=0)
    # contiguous engine: no paged dispatches, no route
    assert _engine(params, "auto", kv_pool_blocks=0)._kv_route == ""
    # pinned routes resolve as pinned; auto picks the reference route
    # on CPU (this suite's backend — the kernel would only interpret)
    assert _engine(params, "pallas")._kv_route == "kernel"
    assert _engine(params, "reference")._kv_route == "reference"
    assert _engine(params, "auto")._kv_route == "reference"


# ---------------------------------------------------------------------------
# no-materialization gate: the kernel route must never gather the pool
# ---------------------------------------------------------------------------


def test_kernel_route_never_traces_the_materializing_gather(monkeypatch):
    """THE tentpole's accounting: tracing + running every kernel-route
    paged program (seeded admission, windowed decode, chunked prefill)
    must not call paged_gather_kv even once — if the working-set
    materialization reappears in any dispatch body, this fails. The
    reference engine is the positive control proving the spy sees
    traced calls."""
    from copilot_for_consensus_tpu.ops import paged_attention as pa

    calls = {"n": 0}
    real = pa.paged_gather_kv

    def spy(pool_k, pool_v, bids):
        calls["n"] += 1
        return real(pool_k, pool_v, bids)

    monkeypatch.setattr(pa, "paged_gather_kv", spy)
    params = _params()
    rng = np.random.default_rng(4)
    shared = rng.integers(3, CFG.vocab_size, size=70).tolist()
    prompts = [shared + rng.integers(3, CFG.vocab_size,
                                     size=10).tolist()
               for _ in range(3)]
    ker = _engine(params, "pallas", kv_pool_blocks=16,
                  prefix_cache_blocks=8)
    for _round in range(2):          # round 2 traces seeded admission
        ker.generate(prompts, max_new_tokens=6)
    assert ker.kv_pool_stats()["zero_copy_admits"] > 0
    assert calls["n"] == 0, "kernel route materialized the pool"
    ref = _engine(params, "reference", kv_pool_blocks=16,
                  prefix_cache_blocks=8)
    ref.generate(prompts, max_new_tokens=6)
    assert calls["n"] > 0            # the spy does see traced gathers


# ---------------------------------------------------------------------------
# engine e2e: greedy f32 CPU token equality, kernel vs reference route
# ---------------------------------------------------------------------------


def test_kernel_route_plain_decode_tokens_match_reference():
    params = _params()
    ref = _engine(params, "reference")
    ker = _engine(params, "pallas")
    rng = np.random.default_rng(0)
    prompts = [rng.integers(3, CFG.vocab_size, size=70).tolist()
               for _ in range(6)]
    want = ref.generate(prompts, max_new_tokens=10)
    got = ker.generate(prompts, max_new_tokens=10)
    for w, g in zip(want, got):
        assert w.tokens == g.tokens
        assert w.finish_reason == g.finish_reason
    st = ker.kv_pool_stats()
    assert st["free_blocks"] == st["num_blocks"]   # books still balance


def test_kernel_route_prefix_zero_copy_tokens_match_reference():
    """Seeded admission through the kernel's R > 1 rows: zero-copy
    prefix hits produce the same greedy streams as the reference
    route's gather-and-run seeded program."""
    params = _params()
    ref = _engine(params, "reference", kv_pool_blocks=16,
                  prefix_cache_blocks=8)
    ker = _engine(params, "pallas", kv_pool_blocks=16,
                  prefix_cache_blocks=8)
    rng = np.random.default_rng(1)
    shared = rng.integers(3, CFG.vocab_size, size=128).tolist()
    prompts = [shared + rng.integers(3, CFG.vocab_size,
                                     size=30).tolist()
               for _ in range(6)]
    for _round in range(2):
        want = ref.generate(prompts, max_new_tokens=6)
        got = ker.generate(prompts, max_new_tokens=6)
        for w, g in zip(want, got):
            assert w.tokens == g.tokens
    assert ker.kv_pool_stats()["zero_copy_admits"] > 0


@pytest.mark.slow
def test_kernel_route_spec_decode_tokens_match_reference():
    params = _params()
    rng = np.random.default_rng(0)
    half = 60

    def copy_prompt():
        head = rng.integers(3, CFG.vocab_size, size=half).tolist()
        tail = []
        while len(tail) < half:
            s0 = int(rng.integers(0, max(1, half - 16)))
            tail.extend(head[s0:s0 + 16])
        return head + tail[:half]

    prompts = [copy_prompt() for _ in range(4)]
    ref = _engine(params, "reference", kv_pool_blocks=16,
                  spec_decode=True)
    ker = _engine(params, "pallas", kv_pool_blocks=16,
                  spec_decode=True)
    want = ref.generate(prompts, max_new_tokens=16)
    got = ker.generate(prompts, max_new_tokens=16)
    for w, g in zip(want, got):
        assert w.tokens == g.tokens
    assert ker.spec_stats()["verify_dispatches"] > 0


@pytest.mark.slow
def test_kernel_route_chunked_prefill_tokens_match_reference():
    from copilot_for_consensus_tpu.engine.scheduler import (
        Scheduler,
        SchedulerConfig,
    )

    params = _params()
    rng = np.random.default_rng(5)
    prompts = [rng.integers(3, CFG.vocab_size, size=180).tolist()
               for _ in range(3)]
    ref = _engine(params, "reference", kv_pool_blocks=16,
                  scheduler=Scheduler(SchedulerConfig(chunk_tokens=64)))
    ker = _engine(params, "pallas", kv_pool_blocks=16,
                  scheduler=Scheduler(SchedulerConfig(chunk_tokens=64)))
    want = ref.generate(prompts, max_new_tokens=8)
    got = ker.generate(prompts, max_new_tokens=8)
    for w, g in zip(want, got):
        assert w.tokens == g.tokens
    assert ker.chunk_dispatches > 0
