# The Pallas paged kernel as the production decode route (ISSUE 16):
# interpret-mode parity of the partial kernel + combine_partials fold
# against the XLA reference across GQA ratios, sliding windows, fp8
# pools, mixed fill levels, and parked rows; the kv_kernel constructor
# guards; the no-materialization gate (now an hlo-materialize contract
# on the lowered StableHLO of every kernel-route paged dispatch — this
# file keeps the tripwire proving the hlo lane turns red when the
# materializing gather is re-introduced); and engine-level greedy token
# equality between the kernel and reference routes across the plain,
# prefix-cache, spec-decode, and chunked-prefill paths.
import pathlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from copilot_for_consensus_tpu.engine.kv_pool import BLOCK_TABLE_DTYPE
from copilot_for_consensus_tpu.models.configs import decoder_config

CFG = decoder_config("tiny")


def _params():
    from copilot_for_consensus_tpu.models import decoder

    return decoder.init_params(jax.random.PRNGKey(7), CFG,
                               dtype=jnp.float32)


def _engine(params, route, **kw):
    from copilot_for_consensus_tpu.engine.generation import (
        GenerationEngine,
    )

    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 256)
    kw.setdefault("prefill_buckets", (64, 128, 192))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("kv_dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("decode_window", 4)
    kw.setdefault("prefill_chunk", 64)
    kw.setdefault("kv_pool_blocks", 12)
    return GenerationEngine(CFG, params, kv_kernel=route, **kw)


# ---------------------------------------------------------------------------
# partial kernel: interpret-mode parity against the XLA reference
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("hq,hkv", [(8, 2), (4, 4), (8, 1)])
@pytest.mark.parametrize("fp8", [False, True])
@pytest.mark.parametrize("window", [0, 5])
def test_partial_kernel_decode_parity(hq, hkv, fp8, window):
    """The kernel route's decode shape: the pool partial alone IS the
    whole kv prefix, so combine_partials of one piece must match the
    gathered reference — across GQA ratios, sliding window, fp8
    dequant-on-load, mixed fill levels, and a parked (length-0) row
    that must emit exact zeros."""
    from copilot_for_consensus_tpu.ops.attention import (
        combine_partials,
        decode_attention,
    )
    from copilot_for_consensus_tpu.ops.paged_attention import (
        paged_attention_partial_pallas,
        paged_gather_layer,
    )

    rng = np.random.default_rng(2)
    b, d, blk, nbtot, nb, nl, li = 4, 16, 8, 12, 4, 3, 2
    q = jnp.asarray(rng.standard_normal((b, hq, d)), jnp.float32)
    pk = jnp.asarray(rng.standard_normal((nl, nbtot, hkv, blk, d)),
                     jnp.float32)
    pv = jnp.asarray(rng.standard_normal((nl, nbtot, hkv, blk, d)),
                     jnp.float32)
    if fp8:
        pk = pk.astype(jnp.float8_e4m3fn)
        pv = pv.astype(jnp.float8_e4m3fn)
    tables = jnp.asarray(rng.integers(0, nbtot, (b, nb)),
                         BLOCK_TABLE_DTYPE)
    # parked row, single token, full table, mid-block fill
    lengths = jnp.asarray([0, 1, blk * nb, 17], jnp.int32)

    k, v = paged_gather_layer(pk[li], pv[li], tables)
    ref = decode_attention(q, k, v, lengths, window=window)
    part = paged_attention_partial_pallas(
        q.reshape(b, hkv, hq // hkv, d), pk, pv,
        jnp.asarray([li], jnp.int32), tables, lengths, lengths - 1,
        window=window, interpret=True)
    got = combine_partials([part], jnp.float32).reshape(b, hq, d)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               atol=1e-5)
    assert bool(jnp.all(got[0] == 0.0))        # parked row: exact zeros


def test_partial_kernel_seeded_rows_parity():
    """The seeded shape (R = group * S query rows): pool partial from
    the kernel + the XLA causal-suffix partial folded by
    combine_partials must match a dense joint softmax over
    [pool prefix | causal suffix] — including a zero-prefix row whose
    pool piece is fully masked."""
    from copilot_for_consensus_tpu.ops.attention import (
        causal_suffix_partial,
        combine_partials,
    )
    from copilot_for_consensus_tpu.ops.paged_attention import (
        paged_attention_partial_pallas,
        paged_gather_layer,
    )

    rng = np.random.default_rng(3)
    b, hkv, g, d, blk, nbtot, nb, s = 2, 2, 2, 16, 8, 10, 3, 4
    hq = hkv * g
    q = jnp.asarray(rng.standard_normal((b, hq, s, d)), jnp.float32)
    pk = jnp.asarray(rng.standard_normal((2, nbtot, hkv, blk, d)),
                     jnp.float32)
    pv = jnp.asarray(rng.standard_normal((2, nbtot, hkv, blk, d)),
                     jnp.float32)
    ks = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    vs = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    tables = jnp.asarray(rng.integers(0, nbtot, (b, nb)),
                         BLOCK_TABLE_DTYPE)
    pref = jnp.asarray([13, 0], jnp.int32)     # mid-block + no prefix

    qr = q.reshape(b, hkv, g, s, d).reshape(b, hkv, g * s, d)
    pool_part = paged_attention_partial_pallas(
        qr, pk, pv, jnp.asarray([1], jnp.int32), tables, pref,
        pref - 1, window=0, interpret=True)
    suf_part = causal_suffix_partial(q, ks, vs)
    got = combine_partials([pool_part, suf_part], jnp.float32)

    # dense reference: joint softmax over pool positions < pref[b] and
    # suffix positions t <= s (row-major (g, s) rows, like the kernel)
    kp, vp = paged_gather_layer(pk[1], pv[1], tables)   # [b,hkv,P,d]
    qg = q.reshape(b, hkv, g, s, d)
    lp = jnp.einsum("bhgsd,bhpd->bhgsp", qg, kp) * (d ** -0.5)
    lp = jnp.where(jnp.arange(nb * blk)[None, None, None, None]
                   < pref[:, None, None, None, None], lp, -jnp.inf)
    ls = jnp.einsum("bhgsd,bhtd->bhgst", qg, ks) * (d ** -0.5)
    ls = jnp.where(jnp.arange(s)[None, None, None, None]
                   <= jnp.arange(s)[None, None, None, :, None],
                   ls, -jnp.inf)
    probs = jax.nn.softmax(jnp.concatenate([lp, ls], axis=-1), axis=-1)
    ref = jnp.einsum("bhgsp,bhpd->bhgsd", probs,
                     jnp.concatenate([vp, vs], axis=-2))
    np.testing.assert_allclose(
        np.asarray(ref.reshape(b, hkv, g * s, d)), np.asarray(got),
        atol=1e-5)


# ---------------------------------------------------------------------------
# engine construction: the kv_kernel knob's guards and resolution
# ---------------------------------------------------------------------------


def test_kv_kernel_constructor_guards_and_resolution():
    params = _params()
    with pytest.raises(ValueError, match="kv_kernel"):
        _engine(params, "cuda")
    with pytest.raises(ValueError, match="paged"):
        _engine(params, "pallas", kv_pool_blocks=0)
    # contiguous engine: no paged dispatches, no route
    assert _engine(params, "auto", kv_pool_blocks=0)._kv_route == ""
    # pinned routes resolve as pinned; auto picks the reference route
    # on CPU (this suite's backend — the kernel would only interpret)
    assert _engine(params, "pallas")._kv_route == "kernel"
    assert _engine(params, "reference")._kv_route == "reference"
    assert _engine(params, "auto")._kv_route == "reference"


# ---------------------------------------------------------------------------
# no-materialization gate: the kernel route must never gather the pool.
# The PROD gate is the hlo lane now — the kernel-route contract cases in
# generation.py declare ``HloSpec(forbid_ops=...)`` and hlocheck scans
# the real lowered StableHLO of every paged dispatch (strictly stronger
# than the runtime trace spy this file used to carry: a gather inlined
# WITHOUT calling paged_gather_kv is invisible to a spy, but not to the
# lowering). What stays here is the tripwire proving the lane turns red
# when the materializing gather is re-introduced.
# ---------------------------------------------------------------------------


def test_reintroduced_pool_gather_turns_the_hlo_lane_red(tmp_path):
    """Re-introduce a ``paged_gather_kv`` of the whole committed pool
    working set into ``_decode_paged_kernel``'s body (the exact shape
    of the pre-ISSUE-16 reference route) on a COPY of generation.py:
    hlocheck's hlo-materialize rule must flag the lowered gather. The
    unmutated file is the negative control — same case, same rule,
    clean."""
    from copilot_for_consensus_tpu.analysis import hlocheck
    from copilot_for_consensus_tpu.engine import generation

    gen = pathlib.Path(generation.__file__)
    src = gen.read_text()
    # anchor 1: the decode variant's partial_fn (the seeded/verify/
    # chunk variants bind `lns`, so this needle is unique to decode)
    anchor = "                    def partial_fn(li, q_rows, lengths, q_pos):\n"
    assert src.count(anchor) == 1, "decode body moved; update the test"
    gather = ("                    mk_ws, mv_ws = paged_gather_kv("
              "pool_k, pool_v, tables)\n")
    # anchor 2: decode's pool scatter (unique: only decode scatters
    # k_all). The gathered working set must be USED — a dead gather is
    # DCE'd before lowering and would never reach the StableHLO.
    scatter = ("                    pool_k, pool_v = scatter_kfn(\n"
               "                        pool_k, pool_v, k_all, v_all, "
               "sbids, soffs)")
    assert src.count(scatter) == 1, "decode scatter moved; update the test"
    use = ("                    k_all = k_all + 0.0 * mk_ws"
           "[:, :, :, :k_all.shape[3], :].astype(k_all.dtype)\n")
    mutated = tmp_path / "generation_gather_mutated.py"
    mutated.write_text(src.replace(anchor, gather + anchor, 1)
                       .replace(scatter, use + scatter, 1))
    findings, _, skips = hlocheck.check_modules(
        [str(mutated)], labels={"decode-paged-kernel"},
        only_rules={"hlo-materialize"})
    assert skips == [], skips
    assert any(f.rule == "hlo-materialize"
               and "decode-paged-kernel" in f.context
               for f in findings), [f.render() for f in findings]
    clean, _, _ = hlocheck.check_modules(
        [str(gen)], labels={"decode-paged-kernel"},
        only_rules={"hlo-materialize"})
    assert clean == [], [f.render() for f in clean]


# ---------------------------------------------------------------------------
# engine e2e: greedy f32 CPU token equality, kernel vs reference route
# ---------------------------------------------------------------------------


def test_kernel_route_plain_decode_tokens_match_reference():
    params = _params()
    ref = _engine(params, "reference")
    ker = _engine(params, "pallas")
    rng = np.random.default_rng(0)
    prompts = [rng.integers(3, CFG.vocab_size, size=70).tolist()
               for _ in range(6)]
    want = ref.generate(prompts, max_new_tokens=10)
    got = ker.generate(prompts, max_new_tokens=10)
    for w, g in zip(want, got):
        assert w.tokens == g.tokens
        assert w.finish_reason == g.finish_reason
    st = ker.kv_pool_stats()
    assert st["free_blocks"] == st["num_blocks"]   # books still balance


def test_kernel_route_prefix_zero_copy_tokens_match_reference():
    """Seeded admission through the kernel's R > 1 rows: zero-copy
    prefix hits produce the same greedy streams as the reference
    route's gather-and-run seeded program."""
    params = _params()
    ref = _engine(params, "reference", kv_pool_blocks=16,
                  prefix_cache_blocks=8)
    ker = _engine(params, "pallas", kv_pool_blocks=16,
                  prefix_cache_blocks=8)
    rng = np.random.default_rng(1)
    shared = rng.integers(3, CFG.vocab_size, size=128).tolist()
    prompts = [shared + rng.integers(3, CFG.vocab_size,
                                     size=30).tolist()
               for _ in range(6)]
    for _round in range(2):
        want = ref.generate(prompts, max_new_tokens=6)
        got = ker.generate(prompts, max_new_tokens=6)
        for w, g in zip(want, got):
            assert w.tokens == g.tokens
    assert ker.kv_pool_stats()["zero_copy_admits"] > 0


@pytest.mark.slow
def test_kernel_route_spec_decode_tokens_match_reference():
    params = _params()
    rng = np.random.default_rng(0)
    half = 60

    def copy_prompt():
        head = rng.integers(3, CFG.vocab_size, size=half).tolist()
        tail = []
        while len(tail) < half:
            s0 = int(rng.integers(0, max(1, half - 16)))
            tail.extend(head[s0:s0 + 16])
        return head + tail[:half]

    prompts = [copy_prompt() for _ in range(4)]
    ref = _engine(params, "reference", kv_pool_blocks=16,
                  spec_decode=True)
    ker = _engine(params, "pallas", kv_pool_blocks=16,
                  spec_decode=True)
    want = ref.generate(prompts, max_new_tokens=16)
    got = ker.generate(prompts, max_new_tokens=16)
    for w, g in zip(want, got):
        assert w.tokens == g.tokens
    assert ker.spec_stats()["verify_dispatches"] > 0


@pytest.mark.slow
def test_kernel_route_chunked_prefill_tokens_match_reference():
    from copilot_for_consensus_tpu.engine.scheduler import (
        Scheduler,
        SchedulerConfig,
    )

    params = _params()
    rng = np.random.default_rng(5)
    prompts = [rng.integers(3, CFG.vocab_size, size=180).tolist()
               for _ in range(3)]
    ref = _engine(params, "reference", kv_pool_blocks=16,
                  scheduler=Scheduler(SchedulerConfig(chunk_tokens=64)))
    ker = _engine(params, "pallas", kv_pool_blocks=16,
                  scheduler=Scheduler(SchedulerConfig(chunk_tokens=64)))
    want = ref.generate(prompts, max_new_tokens=8)
    got = ker.generate(prompts, max_new_tokens=8)
    for w, g in zip(want, got):
        assert w.tokens == g.tokens
    assert ker.chunk_dispatches > 0
