"""Stats exporters (tools/exporters.py) — roles of the reference's
mongo_collstats / qdrant / document_processing exporter scripts."""

from __future__ import annotations

from copilot_for_consensus_tpu.storage import create_document_store
from copilot_for_consensus_tpu.storage.registry import KNOWN_COLLECTIONS
from copilot_for_consensus_tpu.tools.exporters import StatsExporter
from copilot_for_consensus_tpu.vectorstore import create_vector_store


def _store_with_docs():
    store = create_document_store({"driver": "memory"})
    store.connect()
    store.insert_document("archives", {"archive_id": "a1", "sha256": "0" * 64,
                                       "parsed": True})
    store.insert_document("archives", {"archive_id": "a2", "sha256": "1" * 64,
                                       "parsed": False})
    for i in range(3):
        store.insert_document("chunks", {
            "chunk_id": f"c{i}", "message_doc_id": f"m{i}", "thread_id": "t",
            "text": "body", "embedding_generated": i == 0})
    return store


def test_collection_counts_exported():
    exporter = StatsExporter(store=_store_with_docs())
    metrics = exporter.collect()
    assert metrics.gauge_value("collection_documents",
                               {"collection": "archives"}) == 2
    assert metrics.gauge_value("collection_documents",
                               {"collection": "chunks"}) == 3
    # every known collection is present, even empty ones
    for coll in KNOWN_COLLECTIONS:
        assert metrics.gauge_value("collection_documents",
                                   {"collection": coll}) >= 0


def test_pending_stage_gauges_match_retry_filters():
    exporter = StatsExporter(store=_store_with_docs())
    metrics = exporter.collect()
    assert metrics.gauge_value("documents_pending",
                               {"collection": "archives",
                                "stage": "parsing"}) == 1
    assert metrics.gauge_value("documents_pending",
                               {"collection": "chunks",
                                "stage": "embedding"}) == 2


def test_vectorstore_gauges():
    vs = create_vector_store({"driver": "memory"})
    vs.connect()
    vs.add_embedding("v1", [0.1, 0.2, 0.3], {})
    vs.add_embedding("v2", [0.4, 0.5, 0.6], {})
    exporter = StatsExporter(store=_store_with_docs(), vector_store=vs)
    metrics = exporter.collect()
    assert metrics.gauge_value("vectorstore_vectors") == 2
    assert metrics.gauge_value("vectorstore_dimension") == 3


def test_render_is_prometheus_text():
    exporter = StatsExporter(store=_store_with_docs())
    text = exporter.render()
    assert 'copilot_collection_documents{collection="archives"} 2' in text
    assert "copilot_exporter_scrape_seconds" in text


def test_unreadable_store_surfaces_minus_one():
    class Broken:
        def count_documents(self, *a, **k):
            raise RuntimeError("down")

    exporter = StatsExporter(store=Broken())
    metrics = exporter.collect()
    assert metrics.gauge_value("collection_documents",
                               {"collection": "archives"}) == -1


def test_partial_failure_leaves_no_stale_series():
    """A vector store that dies between scrapes must not leave last
    scrape's dimension gauge standing next to the -1 error sentinel."""
    vs = create_vector_store({"driver": "memory"})
    vs.connect()
    vs.add_embedding("v1", [0.1, 0.2], {})
    exporter = StatsExporter(store=_store_with_docs(), vector_store=vs)
    assert exporter.collect().gauge_value("vectorstore_dimension") == 2

    def _boom():
        raise RuntimeError("down")

    vs.count = _boom
    metrics = exporter.collect()
    assert metrics.gauge_value("vectorstore_vectors") == -1
    assert "vectorstore_dimension" not in metrics.render_prometheus()


def test_scrape_reflects_live_changes():
    store = _store_with_docs()
    exporter = StatsExporter(store=store)
    assert exporter.collect().gauge_value(
        "collection_documents", {"collection": "archives"}) == 2
    store.insert_document("archives", {"archive_id": "a3", "sha256": "2" * 64,
                                       "parsed": True})
    assert exporter.collect().gauge_value(
        "collection_documents", {"collection": "archives"}) == 3
