# End-to-end pipeline on fakes: fixture mbox → reports, with idempotency,
# cascade delete, and failure-event behavior. Mirrors the reference's
# zero-infra full-pipeline strategy (SURVEY.md §4).
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu.core import events as ev
from copilot_for_consensus_tpu.services.runner import build_pipeline


@pytest.fixture
def pipeline(fixtures_dir):
    p = build_pipeline()
    p.ingestion.create_source({
        "source_id": "ietf-test", "name": "ietf-test",
        "fetcher": "local",
        "location": str(fixtures_dir / "ietf-sample.mbox"),
    })
    return p


def test_end_to_end_fixture_mbox(pipeline):
    stats = pipeline.ingest_and_run("ietf-test")
    assert stats["archives"] == 1
    assert stats["messages"] > 0
    assert stats["threads"] > 0
    assert stats["chunks"] >= stats["messages"]
    assert stats["summaries"] == stats["threads"]
    assert stats["reports"] == stats["threads"]

    # every chunk embedded + in the vector store
    chunks = pipeline.store.query_documents("chunks", {})
    assert all(c["embedding_generated"] for c in chunks)
    assert pipeline.vector_store.count() == len(chunks)

    # reports carry citations into real chunks and a consensus signal
    report = pipeline.reporting.get_reports(limit=1)[0]
    assert report["citations"]
    cited = report["citations"][0]["chunk_id"]
    assert pipeline.store.get_document("chunks", cited) is not None
    summaries = pipeline.store.query_documents("summaries", {})
    assert all("consensus" in s for s in summaries)

    # threads link back to their summary
    for th in pipeline.store.query_documents("threads", {}):
        assert th.get("summary_id")


def test_reingest_is_idempotent(pipeline):
    first = pipeline.ingest_and_run("ietf-test")
    second = pipeline.ingest_and_run("ietf-test")
    assert first == second  # sha256 dedupe: no new docs anywhere


def test_replayed_events_do_not_duplicate(pipeline):
    pipeline.ingest_and_run("ietf-test")
    stats = pipeline.reporting.stats()
    # Replay every forward event type through the bus again.
    msg = pipeline.store.query_documents("messages", {}, limit=1)[0]
    archive = pipeline.store.query_documents("archives", {}, limit=1)[0]
    pub = pipeline.ingestion.publisher
    pub.publish(ev.ArchiveIngested(archive_id=archive["archive_id"],
                                   source_id="ietf-test"))
    pub.publish(ev.JSONParsed(message_doc_id=msg["message_doc_id"],
                              archive_id=msg["archive_id"],
                              thread_id=msg["thread_id"]))
    pipeline.drain()
    assert pipeline.reporting.stats() == stats


def test_changed_context_triggers_resummarization(pipeline):
    pipeline.ingest_and_run("ietf-test")
    n_before = pipeline.reporting.stats()["summaries"]
    # New message in an existing thread → new chunks → new summary id.
    th = pipeline.store.query_documents("threads", {}, limit=1)[0]
    old_summary_id = th["summary_id"]
    archive_id = th["archive_ids"][0]
    pipeline.store.insert_or_ignore("messages", {
        "message_doc_id": "m-new", "archive_id": archive_id,
        "source_id": "ietf-test", "message_id": "<new@x>",
        "thread_id": th["thread_id"], "subject": th["subject"],
        "from_addr": "late@example.org", "date": None,
        "body": "I strongly disagree with the proposed change. -1.",
        "chunked": False,
    })
    pipeline.chunking.publisher.publish(ev.JSONParsed(
        message_doc_id="m-new", archive_id=archive_id,
        thread_id=th["thread_id"]))
    pipeline.drain()
    # Supersede contract (docs/RESILIENCE.md): the thread re-summarizes
    # over the larger context under a NEW deterministic id, the pointer
    # moves forward, and the predecessor summary + report are deleted —
    # exactly one live terminal artifact per thread, so the totals stay
    # flat instead of accumulating duplicates.
    new_summary_id = pipeline.store.get_document(
        "threads", th["thread_id"])["summary_id"]
    assert new_summary_id != old_summary_id
    assert pipeline.store.get_document("summaries", old_summary_id) is None
    assert pipeline.store.query_documents(
        "reports", {"summary_id": old_summary_id}) == []
    assert pipeline.store.get_document(
        "summaries", new_summary_id) is not None
    assert pipeline.reporting.stats()["summaries"] == n_before
    reports = pipeline.store.query_documents(
        "reports", {"thread_id": th["thread_id"]})
    assert len(reports) == 1 and reports[0]["summary_id"] == new_summary_id


def test_source_cascade_delete(pipeline):
    pipeline.ingest_and_run("ietf-test")
    pipeline.ingestion.delete_source("ietf-test")
    pipeline.drain()
    stats = pipeline.reporting.stats()
    assert stats["archives"] == 0
    assert stats["messages"] == 0
    assert stats["chunks"] == 0
    assert pipeline.vector_store.count() == 0
    # cleanup-completed event observed end of cascade
    assert pipeline.store.get_document("sources", "ietf-test") is None


def test_failure_event_published_on_bad_archive(pipeline):
    failures = []
    pipeline.broker.bind("parsing.failed",
                         lambda env: failures.append(env))
    # ArchiveIngested for an archive id that never lands in the store:
    # parsing retries DocumentNotFoundError, exhausts, emits ParsingFailed.
    pipeline.parsing.publisher.publish(
        ev.ArchiveIngested(archive_id="missing-archive"))
    pipeline.drain()
    assert failures
    assert failures[0]["data"]["archive_id"] == "missing-archive"


def test_startup_requeue_resumes_stuck_documents(pipeline):
    pipeline.ingestion.trigger_source("ietf-test")
    pipeline.drain()
    # Simulate a crash that lost the ChunksPrepared event: flags reset.
    chunk = pipeline.store.query_documents("chunks", {}, limit=1)[0]
    pipeline.store.update_document("chunks", chunk["chunk_id"],
                                   {"embedding_generated": False})
    pipeline.vector_store.delete([chunk["chunk_id"]])
    n = pipeline.vector_store.count()
    pipeline.startup()
    pipeline.drain()
    assert pipeline.vector_store.count() == n + 1
    assert pipeline.store.get_document(
        "chunks", chunk["chunk_id"])["embedding_generated"]


def test_semantic_search_finds_reports(pipeline):
    pipeline.ingest_and_run("ietf-test")
    msg = pipeline.store.query_documents("messages", {}, limit=1)[0]
    topic_word = next((w for w in msg["body"].split() if len(w) > 5),
                      msg["subject"].split()[0])
    hits = pipeline.reporting.search_reports(topic_word)
    assert isinstance(hits, list)


def test_pipelined_summarization_matches_sync():
    """Pipelined mode (async engine submission + harvester thread) must
    produce the same set of reports as the synchronous path — drain()
    treats in-flight generations as pending work."""
    import pathlib

    from copilot_for_consensus_tpu.services.runner import build_pipeline

    fixture = str(pathlib.Path(__file__).parent / "fixtures"
                  / "ietf-sample.mbox")
    results = {}
    for mode in ("sync", "pipelined"):
        p = build_pipeline({
            "embedding": {"driver": "mock", "dimension": 16},
            "llm": {"driver": "tpu", "model": "tiny", "num_slots": 4,
                    "max_len": 160, "max_new_tokens": 8,
                    "pipelined": mode == "pipelined"},
        })
        p.ingestion.create_source({"source_id": "s", "name": "s",
                                   "fetcher": "local",
                                   "location": fixture})
        stats = p.ingest_and_run("s")
        assert p.summarization.in_flight == 0
        results[mode] = stats
        p.summarization.summarizer.close()
    assert results["pipelined"]["reports"] == results["sync"]["reports"]
    assert results["pipelined"]["reports"] >= 3


def test_pipelined_crash_between_ack_and_store_recovers():
    """The pipelined summarizer ACKS the bus before the summary is
    durable (docs/PERF.md durability note). Kill the worker between
    engine ack and report store and prove the documented recovery
    spine actually materializes the summary — exactly once, no loss,
    no duplicate."""
    import pathlib

    from copilot_for_consensus_tpu.services.runner import build_pipeline

    fixture = str(pathlib.Path(__file__).parent / "fixtures"
                  / "ietf-sample.mbox")
    p = build_pipeline({
        "embedding": {"driver": "mock", "dimension": 16},
        "llm": {"driver": "tpu", "model": "tiny", "num_slots": 4,
                "max_len": 160, "max_new_tokens": 8,
                "pipelined": True},
    })
    p.ingestion.create_source({"source_id": "s", "name": "s",
                               "fetcher": "local", "location": fixture})
    summ = p.summarization
    assert summ.pipelined

    # Crash simulation: the harvester never runs, so generations are
    # submitted into the engine (bus events ACKED on submit — exactly
    # the at-risk window) but their summaries are never stored. Then
    # the process "dies": in-flight state is dropped on the floor.
    summ._ensure_harvester = lambda: None
    p.ingestion.trigger_source("s")
    p.broker.drain(None)              # plain bus drain: no flight wait
    assert summ.in_flight > 0         # acked, submitted, NOT stored
    lost_threads = p.store.count_documents("threads")
    assert lost_threads >= 3
    assert p.store.count_documents("summaries") == 0   # nothing durable
    summ._in_flight.clear()           # the crash drops in-flight state
    del summ._ensure_harvester        # the "restarted" worker is whole

    # recovery: startup requeue (the orchestrator re-requests summaries
    # for every thread that never got one)
    p.startup()
    p.drain()
    threads = p.store.query_documents("threads", {})
    summaries = p.store.query_documents("summaries", {})
    assert len(summaries) == lost_threads   # every thread's summary back
    assert all(t.get("summary_id") for t in threads)
    tids = [s["thread_id"] for s in summaries]
    assert len(tids) == len(set(tids))       # exactly once per thread
    n_before = len(summaries)

    # and once healthy, another startup requeue is a no-op (no dupes)
    p.startup()
    p.drain()
    assert p.store.count_documents("summaries") == n_before
    p.summarization.summarizer.close()


def test_retry_job_recovers_lost_summary_without_restart():
    """Same crash, recovered by the periodic retry JOB alone (the
    deployment mode where nothing restarts — only the cron job runs):
    the new threads-stage rule must fire and the summary must
    materialize exactly once."""
    import pathlib

    from copilot_for_consensus_tpu.services.runner import build_pipeline
    from copilot_for_consensus_tpu.tools.retry_job import (
        RetryStuckDocumentsJob,
        default_rules,
    )

    fixture = str(pathlib.Path(__file__).parent / "fixtures"
                  / "ietf-sample.mbox")
    p = build_pipeline({
        "embedding": {"driver": "mock", "dimension": 16},
        "llm": {"driver": "tpu", "model": "tiny", "num_slots": 4,
                "max_len": 160, "max_new_tokens": 8,
                "pipelined": True},
    })
    p.ingestion.create_source({"source_id": "s", "name": "s",
                               "fetcher": "local", "location": fixture})
    summ = p.summarization
    summ._ensure_harvester = lambda: None
    p.ingestion.trigger_source("s")
    p.broker.drain(None)
    assert summ.in_flight > 0
    lost_threads = p.store.count_documents("threads")
    summ._in_flight.clear()           # crash; the store survives
    del summ._ensure_harvester

    import time as _time

    job = RetryStuckDocumentsJob(
        p.store, p.orchestrator.publisher, default_rules(),
        min_stuck_seconds=0.0)
    # thread docs carry parsed_at, so a young thread correctly waits
    # out the backoff — simulate the cron firing past it
    counts = job.run_once(now=_time.time() + 600)
    assert counts.get("threads", 0) >= 1   # the new stage rule fired
    p.drain()
    summaries = p.store.query_documents("summaries", {})
    assert len(summaries) == lost_threads
    tids = [s["thread_id"] for s in summaries]
    assert len(tids) == len(set(tids))     # exactly once
    # a second sweep over the healthy store requeues nothing
    assert job.run_once(now=_time.time() + 1200)["threads"] == 0
    p.summarization.summarizer.close()
