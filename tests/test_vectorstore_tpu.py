# On-device vector store vs the in-memory oracle.
import numpy as np
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu.vectorstore.factory import create_vector_store


def _fill(store, n=50, dim=16, seed=0):
    rng = np.random.default_rng(seed)
    vecs = rng.normal(size=(n, dim)).astype(np.float32)
    store.add_embeddings(
        (f"v{i}", vecs[i], {"thread_id": f"t{i % 5}", "seq": i})
        for i in range(n))
    return vecs


@pytest.fixture
def tpu_store():
    return create_vector_store({"driver": "tpu"})


@pytest.fixture
def oracle():
    return create_vector_store({"driver": "memory"})


def test_topk_matches_memory_oracle(tpu_store, oracle):
    vecs = _fill(tpu_store)
    _fill(oracle)
    q = np.random.default_rng(1).normal(size=16)
    got = tpu_store.query(q, top_k=7)
    want = oracle.query(q, top_k=7)
    assert [r.id for r in got] == [r.id for r in want]
    np.testing.assert_allclose([r.score for r in got],
                               [r.score for r in want], atol=2e-2)


def test_filtered_query_selective_path(tpu_store, oracle):
    _fill(tpu_store)
    _fill(oracle)
    q = np.random.default_rng(2).normal(size=16)
    got = tpu_store.query(q, top_k=5, flt={"thread_id": "t3"})
    want = oracle.query(q, top_k=5, flt={"thread_id": "t3"})
    assert [r.id for r in got] == [r.id for r in want]
    assert all(r.metadata["thread_id"] == "t3" for r in got)


def test_upsert_and_delete(tpu_store):
    _fill(tpu_store, n=10)
    assert tpu_store.count() == 10
    # upsert changes the vector in place
    newv = np.zeros(16)
    newv[0] = 1.0
    tpu_store.add_embedding("v3", newv, {"thread_id": "tX"})
    assert tpu_store.count() == 10
    hits = tpu_store.query(newv, top_k=1)
    assert hits[0].id == "v3"
    assert tpu_store.delete(["v3", "v4"]) == 2
    assert tpu_store.count() == 8
    assert tpu_store.get("v3") is None
    assert all(r.id not in ("v3", "v4")
               for r in tpu_store.query(newv, top_k=8))


def test_delete_by_filter(tpu_store):
    _fill(tpu_store)
    n = tpu_store.delete_by_filter({"thread_id": "t1"})
    assert n == 10
    assert tpu_store.count() == 40


def test_growth_past_initial_capacity(tpu_store):
    _fill(tpu_store, n=100)       # initial capacity is 16 → multiple grows
    assert tpu_store.count() == 100
    q = np.random.default_rng(3).normal(size=16)
    assert len(tpu_store.query(q, top_k=10)) == 10


def test_persistence_roundtrip(tpu_store, tmp_path):
    _fill(tpu_store, n=20)
    tpu_store.delete(["v0"])
    path = str(tmp_path / "index.npz")
    tpu_store.save(path)
    other = create_vector_store({"driver": "tpu"})
    assert other.load(path) == 19
    q = np.random.default_rng(4).normal(size=16)
    a = [r.id for r in tpu_store.query(q, top_k=5)]
    b = [r.id for r in other.query(q, top_k=5)]
    assert a == b


def test_dimension_mismatch_raises(tpu_store):
    tpu_store.add_embedding("a", np.ones(8))
    import pytest as _p
    from copilot_for_consensus_tpu.vectorstore.base import VectorStoreError
    with _p.raises(VectorStoreError):
        tpu_store.add_embedding("b", np.ones(9))


def test_pipeline_runs_on_tpu_store(fixtures_dir):
    from copilot_for_consensus_tpu.services.runner import build_pipeline
    p = build_pipeline({"vector_store": {"driver": "tpu"}})
    p.ingestion.create_source({
        "source_id": "s", "name": "s", "fetcher": "local",
        "location": str(fixtures_dir / "ietf-sample.mbox")})
    stats = p.ingest_and_run("s")
    assert stats["reports"] == stats["threads"] > 0


def test_query_batch_matches_single_queries():
    """One fused dispatch for B queries returns exactly what B single
    queries return — including deleted-row skipping and metadata
    filters."""
    import numpy as np

    from copilot_for_consensus_tpu.vectorstore import create_vector_store

    rng = np.random.default_rng(3)
    vs = create_vector_store({"driver": "tpu", "dimension": 16})
    vs.connect()
    vs.add_embeddings([
        (f"v{i}", rng.standard_normal(16).astype(np.float32),
         {"group": "a" if i % 2 else "b"})
        for i in range(50)
    ])
    vs.delete(["v7", "v8"])
    queries = [rng.standard_normal(16).astype(np.float32)
               for _ in range(5)]

    batch = vs.query_batch(queries, top_k=4)
    singles = [vs.query(q, top_k=4) for q in queries]
    assert len(batch) == 5
    for b, s in zip(batch, singles):
        assert [r.id for r in b] == [r.id for r in s]
        assert all(abs(x.score - y.score) < 1e-5 for x, y in zip(b, s))

    # filtered batch matches filtered singles
    fb = vs.query_batch(queries, top_k=3, flt={"group": "a"})
    fs = [vs.query(q, top_k=3, flt={"group": "a"}) for q in queries]
    for b, s in zip(fb, fs):
        assert [r.id for r in b] == [r.id for r in s]
        assert all(r.metadata["group"] == "a" for r in b)

    # empty store returns a list per query
    empty = create_vector_store({"driver": "tpu", "dimension": 16})
    empty.connect()
    assert empty.query_batch(queries, top_k=3) == [[]] * 5
