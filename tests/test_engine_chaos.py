# Chaos harness: fault-injection plane (engine/faults.py) + supervisor
# (engine/supervisor.py) — watchdog, crash containment, request replay,
# degraded-mode breakers, per-request deadlines.
#
# Layout (satellite: the chaos suite runs in the tier-1 FAST lane):
# host-level units (fault plan, breakers, watchdog/stub-runner,
# replay stitching, audit, satellites) are unmarked; the real-engine
# e2e gates (bit-identical recovery, spec-breaker flip/restore) build
# ONE shared tiny CPU engine config; the long-storm variant (many
# faults incl. a real-engine hang over a bigger script) is @slow.
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from copilot_for_consensus_tpu.engine.async_runner import (
    AsyncEngineRunner,
    Handle,
)
from copilot_for_consensus_tpu.engine.faults import (
    PERSISTENT,
    FaultInjector,
    FaultPlan,
    FaultSpec,
    InjectedFault,
    resolve_faults,
)
from copilot_for_consensus_tpu.engine.supervisor import (
    CircuitBreaker,
    EngineFailed,
    EngineSupervisor,
    EngineSuspect,
    SupervisorConfig,
    is_resource_exhaustion,
    resolve_supervisor,
)


# ---------------------------------------------------------------------------
# fault plane (host units)
# ---------------------------------------------------------------------------


def test_fault_spec_occurrence_windows():
    s = FaultSpec(kind="decode", at=3, count=2)
    assert [s.fires_at(i) for i in range(1, 7)] == [
        False, False, True, True, False, False]
    p = FaultSpec(kind="decode", at=2, count=PERSISTENT)
    assert not p.fires_at(1) and p.fires_at(2) and p.fires_at(999)


def test_fault_spec_validation():
    with pytest.raises(ValueError, match="mode"):
        FaultSpec(kind="decode", mode="explode")
    with pytest.raises(ValueError, match="at"):
        FaultSpec(kind="decode", at=0)
    with pytest.raises(ValueError, match="count"):
        FaultSpec(kind="decode", count=0)
    with pytest.raises(ValueError, match="hang_s"):
        FaultSpec(kind="decode", mode="hang")
    with pytest.raises(ValueError, match="rate"):
        FaultSpec(kind="decode", rate=1.5)


def test_injector_transient_vs_persistent_and_wildcard():
    inj = FaultInjector(FaultPlan(specs=[
        FaultSpec(kind="decode", at=2, count=1),
        FaultSpec(kind="*", at=5, count=PERSISTENT)]))
    inj.check("decode")                      # occurrence 1: clean
    with pytest.raises(InjectedFault) as ei:
        inj.check("decode")                  # occurrence 2: transient
    assert ei.value.kind == "decode" and ei.value.occurrence == 2
    assert ei.value.device_state_intact
    inj.check("decode")                      # 3: clean again
    inj.check("decode")                      # 4
    for _ in range(3):                       # 5+: wildcard persistent
        with pytest.raises(InjectedFault):
            inj.check("decode")
    # a different kind has its own counter; wildcard applies there too
    for _ in range(4):
        inj.check("prefill")
    with pytest.raises(InjectedFault):
        inj.check("prefill")
    # clear() ends the persistent fault (half-open probes rely on it)
    inj.clear()
    inj.check("decode")
    assert inj.stats()["fired"] == 5


def test_injector_seeded_rate_is_deterministic():
    plan = {"seed": 42, "specs": [
        {"kind": "decode", "rate": 0.5, "mode": "error"}]}

    def firing_pattern():
        inj = FaultInjector(FaultPlan.from_dict(plan))
        out = []
        for _ in range(32):
            try:
                inj.check("decode")
                out.append(False)
            except InjectedFault:
                out.append(True)
        return out

    a, b = firing_pattern(), firing_pattern()
    assert a == b                    # same seed → same fault sequence
    assert any(a) and not all(a)     # actually probabilistic


def test_fault_plan_dict_roundtrip():
    plan = FaultPlan(seed=3, specs=[
        FaultSpec(kind="verify", at=1, count=3),
        FaultSpec(kind="decode", mode="hang", hang_s=0.5)])
    again = FaultPlan.from_dict(plan.to_dict())
    assert again == plan


def test_injected_hang_is_stop_aware():
    inj = FaultInjector(FaultPlan(specs=[
        FaultSpec(kind="decode", mode="hang", hang_s=30.0)]))
    t0 = time.monotonic()
    releaser = threading.Timer(0.1, inj.release_hangs)
    releaser.start()
    try:
        with pytest.raises(InjectedFault) as ei:
            inj.check("decode")
    finally:
        releaser.cancel()
    assert time.monotonic() - t0 < 10.0      # released, not waited out
    assert ei.value.mode == "hang"


def test_resolve_faults_semantics():
    assert resolve_faults(None) is None
    assert resolve_faults(False) is None
    inj = FaultInjector(FaultPlan())
    assert resolve_faults(inj) is inj
    assert isinstance(resolve_faults(FaultPlan()), FaultInjector)
    assert isinstance(
        resolve_faults([FaultSpec(kind="decode")]), FaultInjector)
    with pytest.raises(ValueError):
        resolve_faults("chaos")


# ---------------------------------------------------------------------------
# circuit breaker (host units)
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_breaker_lifecycle_closed_open_halfopen_closed():
    clk = _Clock()
    b = CircuitBreaker("spec_verify", threshold=3, probe_after_s=10.0,
                       clock=clk)
    assert b.allow() and b.gauge == 0.0
    assert not b.record_failure()
    assert not b.record_failure()
    assert b.record_failure()            # 3rd consecutive → trips
    assert b.state == "open" and b.gauge == 1.0 and b.trips == 1
    assert not b.allow()                 # cooldown not elapsed
    clk.t = 10.0
    assert b.allow() and b.state == "half-open" and b.gauge == 0.5
    b.record_success()                   # probe succeeded
    assert b.state == "closed" and b.gauge == 0.0


def test_breaker_probe_failure_reopens():
    clk = _Clock()
    b = CircuitBreaker("spec_verify", threshold=1, probe_after_s=5.0,
                       clock=clk)
    assert b.record_failure()            # threshold 1: first trip
    clk.t = 5.0
    assert b.allow() and b.state == "half-open"
    assert b.record_failure()            # probe failed → re-open
    assert b.state == "open" and b.trips == 2
    assert not b.allow()                 # cooldown restarted at t=5
    clk.t = 9.9
    assert not b.allow()
    clk.t = 10.0
    assert b.allow()


def test_breaker_success_resets_consecutive_count():
    b = CircuitBreaker("x", threshold=2, probe_after_s=1.0)
    b.record_failure()
    b.record_success()
    b.record_failure()                   # not consecutive: no trip
    assert b.state == "closed"


def test_resource_exhaustion_classifier():
    assert is_resource_exhaustion(RuntimeError("RESOURCE_EXHAUSTED: "
                                               "while allocating"))
    assert is_resource_exhaustion(MemoryError())
    assert not is_resource_exhaustion(RuntimeError("shape mismatch"))
    assert not is_resource_exhaustion(InjectedFault("x"))


# ---------------------------------------------------------------------------
# stub engine: the host-level harness for runner/supervisor units
# ---------------------------------------------------------------------------


class StubEngine:
    """Scriptable engine stand-in with the host tables the supervisor
    audits. ``script`` entries per step(): "ok" (complete everything
    queued), "fail" (activate queued with ``fail_gen`` tokens each,
    then raise), "block" (wait on self.release, then return [])."""

    def __init__(self, script=(), fail_gen=2, fail_exc=None):
        self.script = list(script)
        self.fail_gen = fail_gen
        self.fail_exc = fail_exc or RuntimeError("stub dispatch died")
        self.release = threading.Event()
        self.num_slots = 4
        self.max_len = 64
        self.telemetry = None
        self.faults = None
        self.supervisor = None
        self._last_failed_kind = "decode"
        self._queue = []
        self._active = {}
        self._generated = {}
        self._draft_index = {}
        self._t_prefill = {}
        self._prefix = None
        self._prefix_pins = {}
        self._chunking = {}
        self._chunk_pending = []
        self._prefilling = []
        self._sched = None
        self._free = list(range(self.num_slots))
        self._positions = np.full(self.num_slots, self.max_len,
                                  dtype=np.int32)
        self._rid = 0
        self.submits = []

    def submit(self, prompt, max_new_tokens, **kw):
        self._rid += 1
        req = SimpleNamespace(
            request_id=self._rid, prompt=list(prompt),
            max_new_tokens=max_new_tokens,
            cache_eligible_tokens=kw.get("cache_eligible_tokens"),
            correlation_id=kw.get("correlation_id", ""),
            tenant=kw.get("tenant", ""), priority=kw.get("priority", ""),
            deadline_at=float("inf"))
        self._queue.append(req)
        self.submits.append((list(prompt), max_new_tokens, dict(kw)))
        return self._rid

    def _complete(self, req):
        # deterministic: token i is sum(first-3 prompt tokens) + i —
        # enough structure for the stitching assertions
        base = sum(req.prompt[:3])
        toks = [base + i for i in range(req.max_new_tokens)]
        from copilot_for_consensus_tpu.engine.generation import (
            Completion,
        )
        return Completion(request_id=req.request_id,
                          prompt_len=len(req.prompt), tokens=toks,
                          finish_reason="length")

    def step(self):
        action = self.script.pop(0) if self.script else "ok"
        if action == "block":
            self.release.wait(15.0)
            return []
        if action == "fail_queued":
            # admission-wave style failure: the lossless unwind left
            # the requests QUEUED (never activated) — nothing for the
            # supervisor to evacuate, nothing for replay to budget
            raise self.fail_exc
        if action == "fail":
            for req in self._queue:
                slot = self._free.pop(0)
                self._active[slot] = req
                self._generated[slot] = list(
                    range(100, 100 + self.fail_gen))
            self._queue = []
            raise self.fail_exc
        out = [self._complete(r) for r in self._queue]
        self._queue = []
        return out


def _sup_cfg(**kw):
    kw.setdefault("watchdog_poll_s", 0.01)
    kw.setdefault("deadlines_s", {"step": 0.25})
    return SupervisorConfig(**kw)


# ---------------------------------------------------------------------------
# watchdog (acceptance: hung dispatch → contained suspect event,
# dispatcher stays live for new work — within the test timeout)
# ---------------------------------------------------------------------------


def test_watchdog_converts_hung_dispatch_into_suspect_event():
    eng = StubEngine(script=["block"])
    runner = AsyncEngineRunner(eng, supervisor=_sup_cfg()).start()
    try:
        h = runner.submit([1, 2, 3], 4, correlation_id="hang-1")
        t0 = time.monotonic()
        with pytest.raises(EngineSuspect) as ei:
            h.result(timeout=10.0)
        # the watchdog failed the handle LONG before the 15s block
        # ends — the caller is unwedged, not waiting out the hang
        assert time.monotonic() - t0 < 5.0
        assert ei.value.kind == "step"
        assert ei.value.deadline_s == 0.25
        assert "suspect" in str(ei.value)
        assert runner.suspect_failures == 1
        assert runner.supervisor.watchdog_trips >= 1
        # release the hang: the dispatcher returns, evacuates the
        # zombie work, and keeps serving NEW requests
        eng.release.set()
        h2 = runner.submit([5, 6], 3)
        c = h2.result(timeout=10.0)
        assert c.tokens and c.finish_reason == "length"
    finally:
        eng.release.set()
        assert runner.stop()


def test_watchdog_pending_submits_survive_the_hang():
    """Handles already inside the engine fail at trip time; submits
    that arrive DURING the hang never touched the suspect engine and
    must serve after recovery."""
    eng = StubEngine(script=["block"])
    runner = AsyncEngineRunner(eng, supervisor=_sup_cfg()).start()
    try:
        h_stuck = runner.submit([1, 2, 3], 4)
        with pytest.raises(EngineSuspect):
            h_stuck.result(timeout=10.0)
        h_pending = runner.submit([9, 9], 2)   # arrives mid-hang
        eng.release.set()
        assert h_pending.result(timeout=10.0).tokens
    finally:
        eng.release.set()
        runner.stop()


# ---------------------------------------------------------------------------
# request replay (stub-level: stitching, budget, EngineFailed)
# ---------------------------------------------------------------------------


def test_replay_stitches_one_completion_with_original_identity():
    eng = StubEngine(script=["fail"], fail_gen=2)
    runner = AsyncEngineRunner(
        eng, supervisor=_sup_cfg(replay_budget=2)).start()
    try:
        h = runner.submit([1, 2, 3], 6, correlation_id="r-1")
        c = h.result(timeout=10.0)
        # original identity: the caller's prompt length, not the
        # continuation's (prompt+2 salvaged tokens)
        assert c.prompt_len == 3
        # stitched stream: 2 salvaged tokens + 4 continuation tokens
        assert c.tokens[:2] == [100, 101]
        assert len(c.tokens) == 6
        assert c.finish_reason == "length"
        assert runner.replayed == 1 and runner.recovered == 1
        assert runner.replay_failed == 0
        # the continuation resubmitted prompt+generated with the
        # remaining budget and the caller's correlation id
        prompt2, mnt2, kw2 = eng.submits[-1]
        assert prompt2 == [1, 2, 3, 100, 101]
        assert mnt2 == 4
        assert kw2.get("correlation_id") == "r-1"
    finally:
        runner.stop()


def test_replay_budget_spent_raises_structured_engine_failed():
    eng = StubEngine(script=["fail", "fail", "fail", "fail"],
                     fail_gen=1)
    runner = AsyncEngineRunner(
        eng, supervisor=_sup_cfg(replay_budget=2)).start()
    try:
        h = runner.submit([4, 5], 8, correlation_id="doomed")
        with pytest.raises(EngineFailed) as ei:
            h.result(timeout=10.0)
        e = ei.value
        assert e.correlation_id == "doomed"
        assert e.attempts == 2                 # budget, then terminal
        assert e.reason == "replay-budget"
        assert "replay" in str(e)
        fields = e.as_event_fields()
        assert fields["correlation_id"] == "doomed"
        assert runner.replayed == 2 and runner.replay_failed == 1
    finally:
        runner.stop()


def test_replay_without_supervisor_keeps_legacy_fail_all():
    eng = StubEngine(script=["fail"])
    runner = AsyncEngineRunner(eng).start()
    try:
        h = runner.submit([1, 2], 4)
        with pytest.raises(RuntimeError, match="stub dispatch died"):
            h.result(timeout=10.0)
    finally:
        runner.stop()


def test_replay_resolves_request_whose_output_was_already_complete():
    """A failed step that had already harvested a request's FULL
    output (multi-window dispatches) must resolve the handle with its
    finished completion — not burn a replay or fail it."""
    eng = StubEngine(script=["fail"], fail_gen=6)   # == max_new below
    runner = AsyncEngineRunner(
        eng, supervisor=_sup_cfg(replay_budget=2)).start()
    try:
        h = runner.submit([1, 2, 3], 6)
        c = h.result(timeout=10.0)
        assert c.tokens == [100, 101, 102, 103, 104, 105]
        assert c.finish_reason == "length"
        assert c.prompt_len == 3
        assert runner.replayed == 0 and runner.replay_failed == 0
        assert len(eng.submits) == 1          # never resubmitted
    finally:
        runner.stop()


def test_suspect_recovery_purges_waiterless_queued_work():
    """The watchdog failed EVERY in-engine handle — queued requests
    included. After the stuck step returns, their queued work must be
    purged, not computed for nobody."""
    eng = StubEngine(script=["block"])
    runner = AsyncEngineRunner(eng, supervisor=_sup_cfg()).start()
    try:
        handles = [runner.submit([i, i + 1], 4) for i in range(3)]
        for h in handles:
            with pytest.raises(EngineSuspect):
                h.result(timeout=10.0)
        assert eng._queue                     # zombies queued in-engine
        eng.release.set()
        # new work serves; by then the zombie queue must be gone
        h2 = runner.submit([9, 9], 2)
        assert h2.result(timeout=10.0).tokens
        assert eng._queue == []
        # completed counts only real resolutions, not dropped zombies
        assert runner.completed <= 1 + len(handles)
    finally:
        eng.release.set()
        runner.stop()


def test_purge_queued_repays_scheduler_ledgers():
    from copilot_for_consensus_tpu.engine.scheduler import Scheduler

    eng = StubEngine()
    sched = Scheduler()
    eng._sched = sched
    req = SimpleNamespace(request_id=1, prompt=[1] * 12, tenant="a",
                          priority="interactive",
                          deadline_at=float("inf"))
    sched.enqueue(req)
    stale = SimpleNamespace(request_id=2, prompt=[3, 4],
                            deadline_at=float("inf"))
    eng._queue.append(stale)
    sup = EngineSupervisor(eng, _sup_cfg())
    dropped = sup.purge_queued()
    assert {getattr(r, "request_id", None) for r in dropped} == {1, 2}
    assert sched.queued == 0
    assert sched._tenants["a"].queued_tokens == 0
    assert eng._queue == []


def test_persistent_admit_failure_terminates_structured():
    """Review regression: a persistently failing admission wave
    requeues its requests (never active → never replay-budgeted) —
    the consecutive-failure gate must declare the engine unhealthy
    and fail the stuck handles structured instead of raise/requeue
    looping until the caller's own timeout."""
    eng = StubEngine(script=["fail_queued"] * 20)
    runner = AsyncEngineRunner(
        eng, supervisor=_sup_cfg(max_consecutive_failures=3)).start()
    try:
        h = runner.submit([1, 2, 3], 4, correlation_id="stuck")
        with pytest.raises(EngineFailed) as ei:
            h.result(timeout=10.0)
        assert ei.value.reason == "engine-unhealthy"
        assert "consecutive failed steps" in str(ei.value)
        assert eng._queue == []             # purged, not looping
        # a success after the fault clears resets the counter and the
        # dispatcher serves new traffic normally
        eng.script = []
        h2 = runner.submit([5, 6], 3)
        assert h2.result(timeout=10.0).tokens
        assert runner.supervisor.consecutive_failures == 0
    finally:
        runner.stop()


def test_replay_overflowing_prompt_limit_fails_structured():
    """Review regression: a continuation whose prompt+generated no
    longer fits prompt_limit must fail structured — submit would
    silently head-truncate it and the replay would diverge from the
    fault-free stream."""
    eng = StubEngine(script=["fail"], fail_gen=3)
    eng.prompt_limit = 5                    # prompt 3 + gen 3 = 6 > 5
    runner = AsyncEngineRunner(
        eng, supervisor=_sup_cfg(replay_budget=4)).start()
    try:
        h = runner.submit([1, 2, 3], 10, correlation_id="overflow")
        with pytest.raises(EngineFailed) as ei:
            h.result(timeout=10.0)
        assert ei.value.reason == "continuation-too-long"
        assert ei.value.correlation_id == "overflow"
        assert len(eng.submits) == 1        # never resubmitted
    finally:
        runner.stop()


def test_deadline_completion_surfaces_as_structured_failure():
    """Satellite follow-up (review): an empty deadline completion must
    NOT decode into a successful empty Summary — the summarizer raises
    a structured EngineFailed the service maps to its retry path."""
    from copilot_for_consensus_tpu.engine.generation import Completion
    from copilot_for_consensus_tpu.summarization.tpu_summarizer import (
        TPUSummarizer,
    )

    dead = Completion(request_id=5, prompt_len=8, tokens=[],
                      finish_reason="deadline")
    with pytest.raises(EngineFailed) as ei:
        TPUSummarizer._checked(dead)
    assert ei.value.reason == "deadline-expired"
    assert ei.value.request_id == 5
    ok = Completion(request_id=6, prompt_len=8, tokens=[1, 2],
                    finish_reason="length")
    assert TPUSummarizer._checked(ok) is ok


# ---------------------------------------------------------------------------
# satellite: stop() join-timeout must fail outstanding handles
# ---------------------------------------------------------------------------


def test_stop_join_timeout_fails_handles_with_stuck_state():
    eng = StubEngine(script=["block"])
    runner = AsyncEngineRunner(eng).start()
    h = runner.submit([1, 2, 3], 4)
    time.sleep(0.1)                     # let the dispatcher enter step()
    t0 = time.monotonic()
    joined = runner.stop(timeout=0.3)
    assert time.monotonic() - t0 < 5.0
    assert joined is False              # condition returned, not hidden
    with pytest.raises(EngineSuspect) as ei:
        h.result(timeout=1.0)
    msg = str(ei.value)
    assert "failed to join" in msg
    assert "engine.step()" in msg       # names the stuck state
    eng.release.set()                   # let the daemon thread die


def test_stop_clean_join_returns_true():
    eng = StubEngine()
    runner = AsyncEngineRunner(eng).start()
    h = runner.submit([1, 2], 3)
    assert h.result(timeout=10.0).tokens
    assert runner.stop() is True


# ---------------------------------------------------------------------------
# satellite: Handle.result timeout enrichment
# ---------------------------------------------------------------------------


def test_result_timeout_names_request_and_correlation_id():
    h = Handle(request_id=41, correlation_id="corr-41")
    with pytest.raises(TimeoutError) as ei:
        h.result(timeout=0.05)
    msg = str(ei.value)
    assert "request_id=41" in msg
    assert "correlation_id=corr-41" in msg
    assert "not finished after" in msg      # elapsed time present
    h2 = Handle()                            # defaults stay readable
    with pytest.raises(TimeoutError) as ei2:
        h2.result(timeout=0.01)
    assert "correlation_id=<none>" in str(ei2.value)


# ---------------------------------------------------------------------------
# satellite: _report_engine_error best-effort guarantees
# ---------------------------------------------------------------------------


class _BoomTelemetry:
    def record_error(self, exc):
        raise RuntimeError("telemetry imploded")


class _GoodTelemetry:
    def __init__(self):
        self.recorded = []

    def record_error(self, exc):
        self.recorded.append(exc)
        return {"correlation_ids": ["c-1"], "in_flight": [1],
                "dump_path": "/tmp/dump.json"}


class _BoomReporter:
    def __init__(self):
        self.calls = 0

    def report(self, exc, context):
        self.calls += 1
        raise RuntimeError("reporter imploded")


class _GoodReporter:
    def __init__(self):
        self.calls = []

    def report(self, exc, context):
        self.calls.append((exc, context))


def test_report_engine_error_survives_raising_telemetry():
    """A record_error that itself raises must neither mask the engine
    failure (the handle still sees the ORIGINAL exception) nor stop
    the error reporter from being called (without dump context)."""
    eng = StubEngine(script=["fail"],
                     fail_exc=RuntimeError("original engine failure"))
    eng.telemetry = _BoomTelemetry()
    reporter = _GoodReporter()
    runner = AsyncEngineRunner(eng, error_reporter=reporter).start()
    try:
        h = runner.submit([1, 2], 4)
        with pytest.raises(RuntimeError, match="original engine "
                                               "failure"):
            h.result(timeout=10.0)
        assert len(reporter.calls) == 1
        exc, context = reporter.calls[0]
        assert "original engine failure" in str(exc)
        assert context["component"] == "engine-dispatch"
        assert "flight_record" not in context     # dump never happened
        # the dispatcher survived: a new request still serves
        assert runner.submit([3], 2).result(timeout=10.0).tokens
    finally:
        runner.stop()


def test_report_engine_error_survives_raising_reporter():
    """A reporter that raises must not mask or amplify the original
    failure either — and the flight-recorder dump it was handed still
    happened first."""
    eng = StubEngine(script=["fail"],
                     fail_exc=RuntimeError("original engine failure"))
    tele = _GoodTelemetry()
    eng.telemetry = tele
    reporter = _BoomReporter()
    runner = AsyncEngineRunner(eng, error_reporter=reporter).start()
    try:
        h = runner.submit([1, 2], 4)
        with pytest.raises(RuntimeError, match="original engine "
                                               "failure"):
            h.result(timeout=10.0)
        assert reporter.calls == 1
        assert len(tele.recorded) == 1          # dump happened first
        assert runner.submit([3], 2).result(timeout=10.0).tokens
    finally:
        runner.stop()


# ---------------------------------------------------------------------------
# invariant audit (stub-level)
# ---------------------------------------------------------------------------


def test_audit_repairs_slot_table_and_quarantines_lost_slots():
    eng = StubEngine()
    sup = EngineSupervisor(eng, _sup_cfg())
    req = SimpleNamespace(request_id=7, prompt=[1, 2],
                          max_new_tokens=4, cache_eligible_tokens=None,
                          correlation_id="", tenant="", priority="",
                          deadline_at=float("inf"))
    # corrupt the tables: slot 0 both free and active, slot 1 free
    # twice, slot 3 tracked nowhere, an orphan _generated entry
    eng._active[0] = req
    eng._generated[0] = [9]
    eng._free = [0, 1, 1, 2]
    eng._generated[2] = [8, 8]          # orphan (slot 2 not active)
    findings = sup.audit(repair=True)
    assert findings["free_while_active"] == [0]
    assert findings["duplicate_free_slots"] == [1]
    assert findings["quarantined_slots"] == [3]
    assert findings["generated_orphans"] == [2]
    assert eng._free == [1, 2]          # deduped, active slot removed
    assert 2 not in eng._generated
    assert sup.quarantined == [3]
    # a clean engine audits clean (and the repair is idempotent)
    assert sup.audit(repair=True) == {}


def test_audit_releases_leaked_prefix_pins():
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.engine.prefix_cache import PrefixCache
    from copilot_for_consensus_tpu.models.configs import decoder_config

    cfg = decoder_config("tiny")
    pc = PrefixCache(cfg, num_blocks=4, block_size=4,
                     kv_dtype=jnp.float32)
    eng = StubEngine()
    eng._prefix = pc
    sup = EngineSupervisor(eng, _sup_cfg())
    # publish one block's worth, then pin it via lookup under a
    # request id that is NOT active — a leaked pin
    import numpy as _np

    cache = {"k": _np.zeros((cfg.n_layers, 2, cfg.n_kv_heads, 16,
                             cfg.head_dim), dtype=_np.float32),
             "v": _np.zeros((cfg.n_layers, 2, cfg.n_kv_heads, 16,
                             cfg.head_dim), dtype=_np.float32)}
    tokens = list(range(10))
    pc.publish(tokens, cache, 0)
    m = pc.lookup(tokens)
    assert m.tokens > 0 and pc.pinned_refcount > 0
    eng._prefix_pins[99] = m            # request 99 does not exist
    findings = sup.audit(repair=True)
    assert findings["leaked_pins"] == [99]
    assert pc.pinned_refcount == 0
    assert sup.released_pins == 1


def test_prefix_cache_flush_frees_everything():
    import jax.numpy as jnp
    import numpy as _np

    from copilot_for_consensus_tpu.engine.prefix_cache import PrefixCache
    from copilot_for_consensus_tpu.models.configs import decoder_config

    cfg = decoder_config("tiny")
    pc = PrefixCache(cfg, num_blocks=4, block_size=4,
                     kv_dtype=jnp.float32)
    cache = {"k": _np.zeros((cfg.n_layers, 1, cfg.n_kv_heads, 16,
                             cfg.head_dim), dtype=_np.float32),
             "v": _np.zeros((cfg.n_layers, 1, cfg.n_kv_heads, 16,
                             cfg.head_dim), dtype=_np.float32)}
    pc.publish(list(range(13)), cache, 0)
    assert pc.blocks_in_use == 3
    assert pc.flush() == 3
    assert pc.blocks_in_use == 0 and pc.node_count == 0
    assert pc.match_tokens(list(range(13))) == 0


def test_resolve_supervisor_semantics():
    eng = StubEngine()
    assert resolve_supervisor(None, eng) is None
    assert resolve_supervisor(False, eng) is None
    sup = resolve_supervisor(True, eng)
    assert isinstance(sup, EngineSupervisor) and eng.supervisor is sup
    eng2 = StubEngine()
    sup2 = resolve_supervisor(SupervisorConfig(replay_budget=7), eng2)
    assert sup2.cfg.replay_budget == 7
    assert resolve_supervisor(sup2, eng2) is sup2
    with pytest.raises(ValueError, match="different engine"):
        resolve_supervisor(sup2, eng)
    with pytest.raises(ValueError):
        resolve_supervisor("yes", eng)


def test_resource_breaker_lowers_cap_and_informs_scheduler():
    from copilot_for_consensus_tpu.engine.scheduler import Scheduler

    class _CapEngine(StubEngine):
        def __init__(self):
            super().__init__()
            self._slot_cap = self.num_slots

        def set_slot_cap(self, cap):
            self._slot_cap = max(1, min(self.num_slots, int(cap)))

    clk = _Clock()
    eng = _CapEngine()
    eng._sched = Scheduler()
    sup = EngineSupervisor(
        eng, SupervisorConfig(resource_breaker_threshold=2,
                              breaker_probe_after_s=10.0), clock=clk)
    oom = RuntimeError("RESOURCE_EXHAUSTED: out of memory while "
                       "allocating 1.2G")
    sup.on_dispatch_error("decode", oom)
    assert eng._slot_cap == 4           # threshold not reached yet
    sup.on_dispatch_error("decode", oom)
    assert eng._slot_cap == 2           # tripped: halved
    assert eng._sched.pressure == 1     # shed loop informed
    eng._sched.observe(queued=0, active=0, num_slots=4)
    assert eng._sched.overload_level == 1
    # recovery: after the cooldown each clean dispatch doubles back
    sup.on_dispatch_ok("decode")
    assert eng._slot_cap == 2           # cooldown not elapsed
    clk.t = 10.0
    sup.on_dispatch_ok("decode")
    assert eng._slot_cap == 4           # restored
    sup.on_dispatch_ok("decode")        # probe success at full cap
    assert sup.resource_breaker.state == "closed"
    assert eng._sched.pressure == 0
    eng._sched.observe(queued=0, active=0, num_slots=4)
    assert eng._sched.overload_level == 0


def test_scheduler_drop_expired_repays_quota_ledger():
    from copilot_for_consensus_tpu.engine.scheduler import Scheduler

    sched = Scheduler()
    live = SimpleNamespace(request_id=1, prompt=[1] * 10, tenant="a",
                           priority="interactive",
                           deadline_at=float("inf"))
    dead = SimpleNamespace(request_id=2, prompt=[1] * 20, tenant="a",
                           priority="interactive", deadline_at=1.0)
    sched.enqueue(live)
    sched.enqueue(dead)
    assert sched._tenants["a"].queued_tokens == 30
    dropped = sched.drop_expired(now=2.0)
    assert [r.request_id for r in dropped] == [2]
    assert sched.queued == 1
    assert sched._tenants["a"].queued_tokens == 10


# ---------------------------------------------------------------------------
# real-engine e2e (tiny CPU engine — the tier-1-fast chaos gate)
# ---------------------------------------------------------------------------


def _real_engine(**kw):
    import jax
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.engine.generation import (
        GenerationEngine,
    )
    from copilot_for_consensus_tpu.models import decoder
    from copilot_for_consensus_tpu.models.configs import decoder_config

    cfg = decoder_config("tiny")
    params = _real_engine._params
    if params is None:
        params = decoder.init_params(jax.random.PRNGKey(7), cfg,
                                     dtype=jnp.float32)
        _real_engine._params = params
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 96)
    kw.setdefault("prefill_buckets", (48,))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("kv_dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("decode_window", 4)
    return GenerationEngine(cfg, params, **kw)


_real_engine._params = None

# copy-heavy prompts (give the spec-decode n-gram index verbatim spans
# to draft from) — module-level so the fast gate and the slow storm
# compare against the same baseline
_CHAOS_PROMPTS = [
    [5, 9, 13, 5, 9, 13, 5, 9, 13, 5, 9, 13],
    [7, 8, 9, 10, 7, 8, 9, 10, 7, 8, 9, 10],
    [3, 4, 3, 4, 3, 4, 3, 4],
    [40, 41, 42, 40, 41, 42, 40, 41, 42],
    [11, 12, 13, 14, 15, 11, 12, 13, 14, 15],
    [21, 22, 21, 22, 21, 22, 21, 22],
]


def _baseline_outputs(max_new=8):
    eng = _real_engine()
    comps = eng.generate([list(p) for p in _CHAOS_PROMPTS],
                         max_new_tokens=max_new)
    return {i: c.tokens for i, c in enumerate(comps)}


def _copy_cycle_setup(period=7):
    """The spec-decode acceptance fixture (test_engine_spec_decode):
    zeroed attention/FFN outputs + one-hot embeddings/lm_head make
    greedy generation the exact cycle t -> 3 + ((t - 3 + 1) % period),
    so prompt-lookup drafts ALWAYS hit — which guarantees the verify
    dispatch fires, the thing the persistent verify fault targets."""
    import jax
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.models import decoder
    from copilot_for_consensus_tpu.models.configs import decoder_config

    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(7), cfg,
                                 dtype=jnp.float32)
    params["layers"]["wo"] = jnp.zeros_like(params["layers"]["wo"])
    params["layers"]["w_down"] = jnp.zeros_like(
        params["layers"]["w_down"])
    emb = np.zeros((cfg.vocab_size, cfg.d_model), np.float32)
    head = np.zeros((cfg.d_model, cfg.vocab_size), np.float32)
    for i in range(period):
        emb[3 + i, i] = 1.0
        head[i, 3 + (i + 1) % period] = 1.0
    params["tok_emb"] = jnp.asarray(emb)
    params["lm_head"] = jnp.asarray(head)
    return cfg, params


def _cycle_engine(cfg, params, **kw):
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.engine.generation import (
        GenerationEngine,
    )

    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 96)
    kw.setdefault("prefill_buckets", (48,))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("kv_dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("decode_window", 4)
    kw.setdefault("spec_decode", True)
    kw.setdefault("spec_draft_lens", (0, 4, 8))
    return GenerationEngine(cfg, params, **kw)


def _cycle_prompt(offset, length, period=7):
    return [3 + ((offset + j) % period) for j in range(length)]


def test_chaos_gate_transient_faults_bit_identical_recovery():
    """The chaos gate (fast variant): injected dispatch exceptions on
    prefill and decode over mixed traffic — every handle resolves, all
    completions (replayed ones included) are bit-identical to the
    fault-free run, and no replay budget is spent."""
    base = _baseline_outputs()
    plan = FaultPlan(specs=[
        FaultSpec(kind="prefill", at=2, count=1),
        FaultSpec(kind="decode", at=3, count=2),
    ])
    eng = _real_engine(faults=plan)
    runner = AsyncEngineRunner(
        eng, supervisor=SupervisorConfig(replay_budget=4)).start()
    try:
        handles = [runner.submit(list(p), 8)
                   for p in _CHAOS_PROMPTS]
        outputs = {i: h.result(timeout=120.0).tokens
                   for i, h in enumerate(handles)}
        assert outputs == base           # bit-identical, zero lost
        assert eng.faults.stats()["fired"] == 3
        rec = runner.recovery_stats()
        assert rec["replayed"] >= 1
        assert rec["recovered"] >= 1
        assert rec["failed"] == 0
        assert rec["containments"] == 3
        # audits found nothing broken after containment
        assert rec["quarantined_slots"] == []
    finally:
        runner.stop()


def test_chaos_gate_persistent_verify_fault_flips_spec_breaker():
    """Acceptance: persistent verify faults flip the engine to plain
    decode (served traffic keeps completing, bit-identical), the
    breaker opens, and the half-open probe restores speculation once
    the faults clear. Copy-cycle fixture: drafts ALWAYS hit, so the
    verify dispatch — the fault's target — reliably fires."""
    cfg_m, params = _copy_cycle_setup()
    prompts = [_cycle_prompt(i, 14) for i in range(4)]
    base_eng = _cycle_engine(cfg_m, params)
    base = {i: c.tokens for i, c in enumerate(
        base_eng.generate([list(p) for p in prompts],
                          max_new_tokens=12))}
    plan = FaultPlan(specs=[
        FaultSpec(kind="verify", at=1, count=PERSISTENT)])
    eng = _cycle_engine(cfg_m, params, faults=plan)
    cfg = SupervisorConfig(replay_budget=8,
                           verify_breaker_threshold=2,
                           breaker_probe_after_s=0.05)
    runner = AsyncEngineRunner(eng, supervisor=cfg).start()
    sup = runner.supervisor
    try:
        handles = [runner.submit(list(p), 12) for p in prompts]
        outputs = {i: h.result(timeout=120.0).tokens
                   for i, h in enumerate(handles)}
        # traffic completed on plain decode, bit-identical (greedy
        # spec-on == spec-off == plain decode)
        assert outputs == base
        assert sup.verify_breaker.trips >= 1
        verify_faults = [f for f in eng.faults.stats()["log"]
                         if f["kind"] == "verify"]
        assert len(verify_faults) >= cfg.verify_breaker_threshold
        # clear the fault; the half-open probe restores speculation
        eng.faults.clear("verify")
        time.sleep(0.1)                 # past breaker_probe_after_s
        spec0 = eng.spec_dispatches
        handles = [runner.submit(list(p), 12) for p in prompts]
        outputs = {i: h.result(timeout=120.0).tokens
                   for i, h in enumerate(handles)}
        assert outputs == base
        assert sup.verify_breaker.state == "closed"
        assert eng.spec_dispatches > spec0   # speculation is back
    finally:
        runner.stop()


def test_deadline_expired_work_is_dropped_not_computed():
    """Per-request deadlines: queued-expired work resolves with an
    EMPTY deadline completion before any dispatch runs for it."""
    eng = _real_engine()
    rid = eng.submit([1, 2, 3], 8, deadline_s=0.0)
    rid_live = eng.submit([4, 5, 6], 4)
    done = {}
    for _ in range(30):
        for c in eng.step():
            done[c.request_id] = c
        if rid in done and rid_live in done:
            break
    assert done[rid].finish_reason == "deadline"
    assert done[rid].tokens == []
    assert done[rid_live].finish_reason in ("eos", "length")
    assert done[rid_live].tokens
    assert eng.deadline_expired == 1
    # the telemetry counter moved too
    m = eng.telemetry.metrics
    assert m.counters["engine_recovery_deadline_expired_total"]


def test_prefix_publish_failure_is_contained():
    """An injected prefix_publish fault costs only the cache
    contribution — the completion still resolves and the pin is
    released."""
    plan = FaultPlan(specs=[
        FaultSpec(kind="prefix_publish", at=1, count=PERSISTENT)])
    eng = _real_engine(prefix_cache_blocks=8, faults=plan)
    comps = eng.generate([[5, 6, 7, 8, 9, 10, 11, 12]],
                         max_new_tokens=4)
    assert comps[0].tokens
    assert eng.prefix_publish_failures >= 1
    assert eng.prefix_stats()["publish_failures"] >= 1
    assert eng._prefix.pinned_refcount == 0


def test_tokenize_fault_point_fires_in_generate_text():
    from copilot_for_consensus_tpu.engine.tokenizer import ByteTokenizer

    plan = FaultPlan(specs=[FaultSpec(kind="tokenize", at=1, count=1)])
    eng = _real_engine(faults=plan)
    with pytest.raises(InjectedFault):
        eng.generate_text(["hello"], ByteTokenizer(512),
                          max_new_tokens=2)


# ---------------------------------------------------------------------------
# long-storm variant (slow lane): many faults incl. a real-engine hang
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_long_storm_zero_lost_handles():
    """The storm: seeded-random dispatch faults, a real-engine hang
    past the watchdog deadline, and a persistent verify fault, over a
    bigger scripted workload. The gate: EVERY handle resolves — a
    Completion (bit-identical to fault-free) or a structured error
    carrying a correlation id — and the recovery counters are sane."""
    rng = np.random.default_rng(0)
    cfg_m, params = _copy_cycle_setup()
    prompts = [_cycle_prompt(int(rng.integers(0, 7)),
                             int(rng.integers(8, 20)))
               for _ in range(24)]
    eng0 = _cycle_engine(cfg_m, params, num_slots=8)
    base = {i: c.tokens for i, c in enumerate(
        eng0.generate([list(p) for p in prompts], max_new_tokens=10))}

    # The script: seeded-random transient faults on decode, three
    # transient verify faults (occ 2-4: two trip the breaker, one
    # fails the first half-open probe; each also evacuates + replays
    # the active wave), and a HANG on the THIRD admission wave — the
    # replay churn guarantees prefill occurrence 3 arrives while
    # traffic is in flight, so the watchdog must catch it.
    plan = FaultPlan(seed=11, specs=[
        FaultSpec(kind="decode", rate=0.08),
        FaultSpec(kind="verify", at=1, count=2),
        FaultSpec(kind="prefill", at=3, count=1, mode="hang",
                  hang_s=1.0),
    ])
    eng = _cycle_engine(cfg_m, params, num_slots=8, faults=plan)
    # Warm the compile caches with the injector unplugged: the tight
    # prefill deadline below is for STEADY-STATE dispatches — a first-
    # call XLA compile tripping the watchdog would be a false hang
    # (production deadlines are minutes; chaos tightens them to make
    # the test fast). Admission waves pad rows to powers of two, so
    # every batch shape the storm can hit gets one warm pass.
    inj, eng.faults = eng.faults, None
    for nwarm in (1, 2, 4, 8):
        eng.generate([list(prompts[i % len(prompts)])
                      for i in range(nwarm)], max_new_tokens=10)
    eng.faults = inj
    sup_cfg = SupervisorConfig(
        deadlines_s={"prefill": 0.45, "step": 30.0},
        watchdog_poll_s=0.02, replay_budget=25,
        verify_breaker_threshold=2, breaker_probe_after_s=0.1)
    runner = AsyncEngineRunner(eng, supervisor=sup_cfg).start()
    try:
        handles = [runner.submit(list(p), 10,
                                 correlation_id=f"storm-{i}")
                   for i, p in enumerate(prompts)]
        completions, errors = {}, {}
        for i, h in enumerate(handles):
            try:
                completions[i] = h.result(timeout=300.0)
            except TimeoutError:
                pytest.fail(f"handle {i} LOST (timed out)")
            except Exception as exc:   # noqa: BLE001 — classified below
                errors[i] = exc
        assert len(completions) + len(errors) == len(prompts)
        # every error is structured and names its correlation id
        for i, exc in errors.items():
            assert isinstance(exc, (EngineSuspect, EngineFailed)), exc
            assert hasattr(exc, "correlation_id")
        # every completion is bit-identical to the fault-free run
        for i, c in completions.items():
            assert c.tokens == base[i], f"request {i} diverged"
        rec = runner.recovery_stats()
        assert rec["replayed"] >= 1
        assert rec["watchdog_trips"] >= 1     # the hang was caught
        assert rec["breaker_trips"] >= 1      # verify breaker tripped
        # the scripted storm actually fired: both verify faults + hang
        assert eng.faults.stats()["fired"] >= 3
        # the engine is still healthy for new work after the storm
        h = runner.submit(list(prompts[0]), 10)
        assert h.result(timeout=120.0).tokens == base[0]
    finally:
        eng.faults.release_hangs()
        runner.stop()
