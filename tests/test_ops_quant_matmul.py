# Fused int8 matmul kernel vs the XLA dequant expression.
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from copilot_for_consensus_tpu.models.quant import quantize_tensor
from copilot_for_consensus_tpu.ops.quant_matmul import int8_matmul


@pytest.mark.parametrize("m,d,f", [(4, 64, 96), (1, 128, 512), (9, 32, 33)])
def test_matches_xla_dequant(m, d, f):
    w = jax.random.normal(jax.random.PRNGKey(0), (d, f)) * 0.05
    x = jax.random.normal(jax.random.PRNGKey(1), (m, d))
    qw = quantize_tensor(w)
    ref = (x @ qw["q"].astype(x.dtype)) * qw["scale"].astype(x.dtype)
    out = int8_matmul(x, qw["q"], qw["scale"], block_f=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=1e-2)


def test_leading_batch_dims():
    w = jax.random.normal(jax.random.PRNGKey(2), (32, 48)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 3, 32))
    qw = quantize_tensor(w)
    ref = (x @ qw["q"].astype(x.dtype)) * qw["scale"].astype(x.dtype)
    out = int8_matmul(x, qw["q"], qw["scale"], block_f=16, interpret=True)
    assert out.shape == (2, 3, 48)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=1e-2)
