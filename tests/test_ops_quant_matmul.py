# Fused int8 matmul kernel vs the XLA dequant expression.
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from copilot_for_consensus_tpu.models.quant import quantize_tensor
from copilot_for_consensus_tpu.ops.quant_matmul import int8_matmul


@pytest.mark.parametrize("m,d,f", [(4, 64, 96), (1, 128, 512), (9, 32, 33)])
def test_matches_xla_dequant(m, d, f):
    w = jax.random.normal(jax.random.PRNGKey(0), (d, f)) * 0.05
    x = jax.random.normal(jax.random.PRNGKey(1), (m, d))
    qw = quantize_tensor(w)
    ref = (x @ qw["q"].astype(x.dtype)) * qw["scale"].astype(x.dtype)
    out = int8_matmul(x, qw["q"], qw["scale"], block_f=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=1e-2)


def test_leading_batch_dims():
    w = jax.random.normal(jax.random.PRNGKey(2), (32, 48)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 3, 32))
    qw = quantize_tensor(w)
    ref = (x @ qw["q"].astype(x.dtype)) * qw["scale"].astype(x.dtype)
    out = int8_matmul(x, qw["q"], qw["scale"], block_f=16, interpret=True)
    assert out.shape == (2, 3, 48)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2,
                               atol=1e-2)


# ---------------------------------------------------------------------------
# Packed int4 with group-wise scales
# ---------------------------------------------------------------------------

from copilot_for_consensus_tpu.models.quant import (  # noqa: E402
    quantize_tensor_int4,
)
from copilot_for_consensus_tpu.ops.quant_matmul import (  # noqa: E402
    int4_matmul,
    int4_matmul_xla,
    pack_int4,
    unpack_int4,
)


def test_pack_unpack_roundtrip():
    q = jax.random.randint(jax.random.PRNGKey(0), (64, 48), -8, 8,
                           jnp.int8)
    packed = pack_int4(q)
    assert packed.shape == (32, 48) and packed.dtype == jnp.int8
    assert (unpack_int4(packed) == q.astype(jnp.int32)).all()


def test_pack_rejects_odd_rows():
    with pytest.raises(ValueError, match="even"):
        pack_int4(jnp.zeros((7, 8), jnp.int8))


@pytest.mark.parametrize("m,d,f,group", [(4, 512, 96, 256),
                                         (9, 256, 33, 256),
                                         (2, 128, 64, 128)])
def test_int4_kernel_matches_xla_reference(m, d, f, group):
    w = jax.random.normal(jax.random.PRNGKey(0), (d, f)) * 0.05
    x = jax.random.normal(jax.random.PRNGKey(1), (m, d))
    qw = quantize_tensor_int4(w, group=group)
    ref = int4_matmul_xla(x, qw["q4"], qw["scale"])
    out = int4_matmul(x, qw["q4"], qw["scale"], block_f=32,
                      interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=1e-2)


def test_int4_dequant_error_bounded():
    """Grouped int4 round-to-nearest noise on gaussian weights is
    ~(amax/7)/sqrt(12) per weight — about 13% relative. The contract is
    that the implementation adds nothing on top of that floor (bad
    packing or scale indexing would blow far past it), and that it
    clearly beats 3-bit-level error."""
    w = jax.random.normal(jax.random.PRNGKey(5), (512, 64)) * 0.04
    x = jax.random.normal(jax.random.PRNGKey(6), (8, 512))
    qw = quantize_tensor_int4(w, group=256)
    ref = x @ w
    out = int4_matmul_xla(x, qw["q4"], qw["scale"])
    err = np.abs(np.asarray(out - ref)).mean()
    base = np.abs(np.asarray(ref)).mean()
    assert err / base < 0.18, f"int4 rel err {err / base:.3f}"


def test_int4_leading_batch_dims():
    w = jax.random.normal(jax.random.PRNGKey(2), (256, 48)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 3, 256))
    qw = quantize_tensor_int4(w, group=256)
    ref = int4_matmul_xla(x, qw["q4"], qw["scale"])
    out = int4_matmul(x, qw["q4"], qw["scale"], block_f=16,
                      interpret=True)
    assert out.shape == (2, 3, 48)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=1e-2)


def test_int4_rejects_bad_group():
    qw = quantize_tensor_int4(
        jax.random.normal(jax.random.PRNGKey(0), (512, 64)), group=256)
    bad_scale = jnp.ones((3, 64), jnp.float32)   # 512 not divisible by 3
    with pytest.raises(ValueError, match="divide"):
        int4_matmul(jnp.ones((4, 512)), qw["q4"], bad_scale,
                    interpret=True)


def test_int4_dequant_with_stacked_leading_dims():
    """Stacked (layer/expert) int4 leaves dequantize correctly: the
    group axis is -2 of the scale, not axis 0 — the bug here was
    ``int4_matmul_xla`` reading ``scale.shape[0]`` as the group count,
    which broke every stacked leaf."""
    from copilot_for_consensus_tpu.models.quant import dequant_int4

    w = jax.random.normal(jax.random.PRNGKey(7), (3, 256, 16)) * 0.1
    qw = quantize_tensor_int4(w, group=128)
    assert qw["scale"].shape == (3, 2, 16)

    wd = dequant_int4(qw, jnp.float32)
    assert wd.shape == w.shape
    assert float(jnp.abs(wd - w).mean() / jnp.abs(w).mean()) < 0.2
    # stacked dequant matches slicing each layer out first
    for i in range(3):
        per_slice = dequant_int4(
            {"q4": qw["q4"][i], "scale": qw["scale"][i]}, jnp.float32)
        np.testing.assert_array_equal(np.asarray(wd[i]),
                                      np.asarray(per_slice))
    # and the 2D XLA fallback stays consistent with the stacked dequant
    x = jax.random.normal(jax.random.PRNGKey(8), (4, 256))
    np.testing.assert_allclose(
        np.asarray(int4_matmul_xla(x, qw["q4"][0], qw["scale"][0])),
        np.asarray(x @ wd[0]), rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# W8A8 / W4A8: quantized-activation kernels (native int8 MXU path)
# ---------------------------------------------------------------------------

from copilot_for_consensus_tpu.ops.quant_matmul import (  # noqa: E402
    quantize_rows,
    w4a8_matmul,
    w8a8_matmul,
)


def test_quantize_rows_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(9), (6, 128)) * 3.0
    xq, sx = quantize_rows(x)
    assert xq.dtype == jnp.int8 and sx.shape == (6, 1)
    rel = jnp.abs(xq * sx - x) / (jnp.abs(x).max(axis=-1, keepdims=True))
    assert float(rel.max()) < 1 / 127  # half-ULP of the per-row scale
    # zero rows must not divide by zero
    xq0, sx0 = quantize_rows(jnp.zeros((2, 16)))
    assert int(jnp.abs(xq0).sum()) == 0 and bool(jnp.all(sx0 == 1.0))


@pytest.mark.parametrize("m,d,f", [(4, 64, 96), (1, 128, 512), (9, 32, 33)])
def test_w8a8_matches_quantized_oracle(m, d, f):
    """Exactness contract: given the per-row-quantized activations the
    kernel's arithmetic is EXACT (int32 accumulation, scales factored
    out) — only quantize_rows loses information."""
    w = jax.random.normal(jax.random.PRNGKey(0), (d, f)) * 0.05
    x = jax.random.normal(jax.random.PRNGKey(1), (m, d))
    qw = quantize_tensor(w)
    xq, sx = quantize_rows(x)
    ref = (xq.astype(jnp.float32) @ qw["q"].astype(jnp.float32)) \
        * sx * qw["scale"]
    out = w8a8_matmul(x, qw["q"], qw["scale"], block_f=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    # and end-to-end error vs the full-precision product stays at the
    # few-percent W8A8 level
    rel = np.abs(np.asarray(out - x @ w)).mean() / \
        np.abs(np.asarray(x @ w)).mean()
    assert rel < 0.05, rel


@pytest.mark.parametrize("d,f,group", [(512, 64, 256), (256, 48, 256),
                                       (1024, 96, 512)])
def test_w4a8_matches_quantized_oracle(d, f, group):
    w = jax.random.normal(jax.random.PRNGKey(5), (d, f)) * 0.04
    x = jax.random.normal(jax.random.PRNGKey(6), (8, d))
    qw = quantize_tensor_int4(w, group=group)
    xq, sx = quantize_rows(x)
    wd = dequant_int4_f32(qw)
    ref = (xq.astype(jnp.float32) @ wd) * sx
    out = w4a8_matmul(x, qw["q4"], qw["scale"], block_f=16,
                      interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def dequant_int4_f32(qw):
    from copilot_for_consensus_tpu.models.quant import dequant_int4
    return dequant_int4(qw, jnp.float32)


def test_w4a8_leading_batch_dims():
    w = jax.random.normal(jax.random.PRNGKey(2), (256, 48)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 3, 256))
    qw = quantize_tensor_int4(w, group=256)
    out = w4a8_matmul(x, qw["q4"], qw["scale"], block_f=16,
                      interpret=True)
    assert out.shape == (2, 3, 48)
    flat = w4a8_matmul(x.reshape(6, 256), qw["q4"], qw["scale"],
                       block_f=16, interpret=True)
    np.testing.assert_array_equal(np.asarray(out).reshape(6, 48),
                                  np.asarray(flat))
