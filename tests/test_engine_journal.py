# Durable engine request journal (engine/journal.py) + warm restart —
# the process-level "restart costs latency, not work" contract
# (ISSUE 12; docs/RESILIENCE.md#process-lifecycle).
#
# Layout (the chaos-suite convention, test_engine_chaos.py): journal
# units and stub-engine runner-integration tests are unmarked (tier-1
# fast lane); the tiny REAL-engine warm-restart gates are unmarked too
# (they share one tiny f32 CPU engine config); the real-PROCESS variant
# — an actual SIGKILL of a child interpreter mid-storm via
# tools/journal_storm.py — is @slow.
import json
import os
import signal
import subprocess
import sys
import threading
import time
from types import SimpleNamespace

import pytest

from copilot_for_consensus_tpu.engine.journal import (
    EngineJournal,
    resolve_journal,
)


# ---------------------------------------------------------------------------
# journal units (no jax)
# ---------------------------------------------------------------------------


def test_journal_submit_retire_roundtrip(tmp_path):
    j = EngineJournal(str(tmp_path / "j.sqlite3"))
    j.record_submit(1, [5, 6, 7], 16, correlation_id="c-1",
                    tenant="t", priority="batch", deadline_wall=123.0,
                    trace_id="tr", span_id="sp")
    j.record_submit(2, [9], 8)
    assert j.depth() == 2
    rows = j.unfinished()
    assert [r.request_id for r in rows] == [1, 2]
    assert rows[0].prompt == [5, 6, 7]
    assert rows[0].max_new_tokens == 16
    assert rows[0].correlation_id == "c-1"
    assert rows[0].tenant == "t" and rows[0].priority == "batch"
    assert rows[0].deadline_wall == 123.0
    assert rows[0].trace_id == "tr" and rows[0].span_id == "sp"
    assert rows[0].tokens == [] and rows[0].attempt == 0
    j.record_retire(1)
    assert j.depth() == 1
    assert [r.request_id for r in j.unfinished()] == [2]
    j.record_abandon(2)
    assert j.depth() == 0
    s = j.stats()
    assert s["journaled"] == 2 and s["retired"] == 1 \
        and s["abandoned"] == 1
    # deleting a missing row is a no-op, not drift
    j.record_retire(99)
    assert j.depth() == 0 and j.stats()["retired"] == 1


def test_journal_checkpoint_and_supersede_preserve_identity(tmp_path):
    j = EngineJournal(str(tmp_path / "j.sqlite3"))
    j.record_submit(3, [1, 2, 3], 10, correlation_id="c-3")
    j.checkpoint(3, [50, 51])
    assert j.unfinished()[0].tokens == [50, 51]
    # crash #1: the continuation resubmits as rid 7 — the re-key is
    # ONE atomic UPDATE (the continuation's own record_submit is
    # suppressed), so at no instant does the journal hold two live
    # rows for one request (a crash around the resubmission replays
    # exactly one of original/continuation, never both)
    j.supersede(3, 7, [50, 51])
    assert j.depth() == 1
    row = j.unfinished()[0]
    assert row.request_id == 7
    assert row.prompt == [1, 2, 3]          # original, not flattened
    assert row.max_new_tokens == 10          # original budget
    assert row.tokens == [50, 51]
    assert row.attempt == 1
    assert row.correlation_id == "c-3"
    # continuation checkpoints are RELATIVE to the continuation; the
    # durable column stays relative to the original prompt
    j.checkpoint(7, [52])
    assert j.unfinished()[0].tokens == [50, 51, 52]
    # crash #2: the chain holds
    j.supersede(7, 9, [50, 51, 52])
    row = j.unfinished()[0]
    assert row.prompt == [1, 2, 3] and row.attempt == 2
    assert row.tokens == [50, 51, 52]
    # superseding a missing rid is a no-op, not drift
    j.supersede(99, 100, [1])
    assert j.depth() == 1
    j.record_retire(9)
    assert j.depth() == 0


def test_journal_survives_reopen(tmp_path):
    path = str(tmp_path / "durable.sqlite3")
    j = EngineJournal(path)
    j.record_submit(1, [4, 5], 6, correlation_id="x")
    j.checkpoint(1, [9])
    j.close()   # the SIGKILL case never even gets this
    j2 = EngineJournal(path)
    assert j2.depth() == 1
    row = j2.unfinished()[0]
    assert row.prompt == [4, 5] and row.tokens == [9]
    assert row.correlation_id == "x"


def test_journal_checkpoint_missing_row_is_noop(tmp_path):
    j = EngineJournal(str(tmp_path / "j.sqlite3"))
    j.checkpoint(42, [1, 2])
    assert j.depth() == 0 and j.stats()["checkpoints"] == 0


def test_resolve_journal_semantics(tmp_path):
    assert resolve_journal(None) is None
    assert resolve_journal(False) is None
    j = resolve_journal(str(tmp_path / "a.sqlite3"))
    assert isinstance(j, EngineJournal)
    assert resolve_journal(j) is j
    jd = resolve_journal({"path": str(tmp_path / "b.sqlite3"),
                          "checkpoint_every": 3})
    assert jd.checkpoint_every == 3
    with pytest.raises(ValueError, match="journal"):
        resolve_journal(123)


# ---------------------------------------------------------------------------
# runner integration (stub engine, no jax)
# ---------------------------------------------------------------------------


class _StubJournalEngine:
    """Minimal engine surface the runner needs, with a real journal:
    submit journals, step() either parks work forever ('park'),
    completes everything ('complete'), or raises ('fail')."""

    prompt_limit = 4096

    def __init__(self, journal, mode="park"):
        self.journal = journal
        self.mode = mode
        self.telemetry = None
        self._queue = []
        self._active = {}
        self._generated = {}
        self._prefilling = []
        self._done = {}
        self._next = 0

    def submit(self, prompt, max_new_tokens, **kw):
        rid = self._next
        self._next += 1
        self.journal.record_submit(
            rid, prompt, max_new_tokens,
            correlation_id=kw.get("correlation_id", ""))
        self._queue.append(SimpleNamespace(
            request_id=rid, prompt=list(prompt),
            max_new_tokens=max_new_tokens, cache_eligible_tokens=None,
            correlation_id=kw.get("correlation_id", ""), tenant="",
            priority="", deadline_at=float("inf")))
        return rid

    def step(self):
        from copilot_for_consensus_tpu.engine.generation import (
            Completion,
        )

        if self.mode == "fail":
            raise RuntimeError("stub step failure")
        if self.mode == "park":
            return []
        comps = []
        for req in self._queue:
            comps.append(Completion(
                request_id=req.request_id,
                prompt_len=len(req.prompt), tokens=[1, 2],
                finish_reason="length"))
            self.journal.record_retire(req.request_id)
        self._queue = []
        return comps


def _runner(eng, **kw):
    from copilot_for_consensus_tpu.engine.async_runner import (
        AsyncEngineRunner,
    )

    return AsyncEngineRunner(eng, **kw).start()


def test_runner_stop_keeps_journal_rows(tmp_path):
    """A stop (graceful or not) is the crash-only clean case: handles
    fail 'runner stopped', but the rows SURVIVE for the next process's
    warm restart — stop must not turn restart-costs-latency back into
    restart-costs-work."""
    j = EngineJournal(str(tmp_path / "j.sqlite3"))
    eng = _StubJournalEngine(j, mode="park")
    r = _runner(eng)
    h1 = r.submit([1, 2, 3], 8, correlation_id="keep-1")
    h2 = r.submit([4, 5], 8, correlation_id="keep-2")
    deadline = time.monotonic() + 5
    while not eng._queue and time.monotonic() < deadline:
        time.sleep(0.01)
    assert r.stop() is True
    for h in (h1, h2):
        with pytest.raises(RuntimeError, match="runner stopped"):
            h.result(timeout=5)
    assert j.depth() == 2
    assert {e.correlation_id for e in j.unfinished()} == {
        "keep-1", "keep-2"}


def test_runner_legacy_failure_abandons_rows(tmp_path):
    """Without a supervisor, an engine failure fails every handle —
    the callers were TOLD, so the rows must not replay at the next
    restart (that would duplicate work the caller already retried via
    the bus)."""
    j = EngineJournal(str(tmp_path / "j.sqlite3"))
    eng = _StubJournalEngine(j, mode="fail")
    r = _runner(eng)
    h = r.submit([1, 2, 3], 8, correlation_id="gone")
    with pytest.raises(RuntimeError, match="stub step failure"):
        h.result(timeout=5)
    deadline = time.monotonic() + 5
    while j.depth() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert j.depth() == 0
    assert j.stats()["abandoned"] == 1
    r.stop()


def test_runner_drain_completes_then_reports(tmp_path):
    j = EngineJournal(str(tmp_path / "j.sqlite3"))
    eng = _StubJournalEngine(j, mode="complete")
    r = _runner(eng)
    h = r.submit([1, 2], 4)
    assert r.drain(timeout=5) is True
    assert h.result(timeout=1).tokens == [1, 2]
    assert j.depth() == 0
    assert r.stop() is True


def test_runner_drain_times_out_on_parked_work(tmp_path):
    j = EngineJournal(str(tmp_path / "j.sqlite3"))
    eng = _StubJournalEngine(j, mode="park")
    r = _runner(eng)
    r.submit([1, 2], 4)
    t0 = time.monotonic()
    assert r.drain(timeout=0.3) is False
    assert time.monotonic() - t0 < 3.0
    r.stop()
    assert j.depth() == 1    # evacuate-and-journal: the row survives


def test_runner_drain_unblocks_on_stop(tmp_path):
    j = EngineJournal(str(tmp_path / "j.sqlite3"))
    eng = _StubJournalEngine(j, mode="park")
    r = _runner(eng)
    r.submit([1], 4)
    out = {}

    def drainer():
        out["drained"] = r.drain(timeout=30.0)

    t = threading.Thread(target=drainer)
    t.start()
    time.sleep(0.1)
    r.stop()
    t.join(timeout=5)
    assert not t.is_alive()
    assert out["drained"] is False


# ---------------------------------------------------------------------------
# real tiny engine (f32 CPU — the chaos-gate fixture discipline)
# ---------------------------------------------------------------------------


def _tiny_engine(journal=None, **kw):
    import jax
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.engine.generation import (
        GenerationEngine,
    )
    from copilot_for_consensus_tpu.models import decoder
    from copilot_for_consensus_tpu.models.configs import decoder_config

    cfg = decoder_config("tiny")
    params = _tiny_engine._params
    if params is None:
        params = decoder.init_params(jax.random.PRNGKey(7), cfg,
                                     dtype=jnp.float32)
        _tiny_engine._params = params
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 96)
    kw.setdefault("prefill_buckets", (48,))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("kv_dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("decode_window", 4)
    kw.setdefault("telemetry", False)
    return GenerationEngine(cfg, params, journal=journal, **kw)


_tiny_engine._params = None

_PROMPTS = [
    [5, 9, 13, 6, 11, 4, 9, 2],
    [7, 8, 9, 10, 11, 12],
    [3, 4, 5, 6, 7, 8, 9, 10, 11],
    [40, 41, 42, 43, 44],
    [11, 12, 13, 14, 15, 16, 17],
    [21, 22, 23, 24],
]


def test_engine_journals_before_queue_and_retires_at_harvest(tmp_path):
    j = EngineJournal(str(tmp_path / "j.sqlite3"), checkpoint_every=2)
    eng = _tiny_engine(journal=j)
    rid = eng.submit(list(_PROMPTS[0]), 6, correlation_id="e-0")
    assert j.depth() == 1
    row = j.unfinished()[0]
    assert row.request_id == rid and row.correlation_id == "e-0"
    comps = []
    steps = 0
    while not comps and steps < 50:
        steps += 1
        comps = eng.step()
    assert comps and comps[0].request_id == rid
    assert j.depth() == 0 and j.stats()["retired"] == 1


def test_warm_restart_is_bit_identical_and_drains_journal(tmp_path):
    """The fast-lane kill gate: run a storm, 'kill' the process by
    dropping the engine mid-storm (the sqlite file IS the surviving
    state — the @slow variant does it with a real SIGKILL), rebuild on
    the same journal, and require lost 0 / duplicated 0 /
    journal_replayed > 0 / final depth 0 / greedy outputs bit-identical
    (f32) to an uninterrupted run."""
    ref = _tiny_engine()
    ref_out = {c.request_id: c.tokens
               for c in ref.generate([list(p) for p in _PROMPTS], 16)}

    path = str(tmp_path / "j.sqlite3")
    eng = _tiny_engine(journal=EngineJournal(path, checkpoint_every=2))
    rids = [eng.submit(list(p), 16, correlation_id=f"w-{i}")
            for i, p in enumerate(_PROMPTS)]
    got: dict[str, list] = {}
    dup = 0
    for _ in range(4):   # partial progress: checkpoints exist, nothing
        for c in eng.step():   # near the full set has retired
            cid = f"w-{rids.index(c.request_id)}"
            dup += cid in got
            got[cid] = c.tokens
    interrupted_depth = eng.journal.depth()
    assert interrupted_depth > 0, "storm finished before the kill"
    del eng   # process death: no close, no flush

    j2 = EngineJournal(path, checkpoint_every=2)
    eng2 = _tiny_engine(journal=j2)
    assert eng2.journal_replayed == interrupted_depth > 0
    rec = dict(eng2.journal_recovered)
    steps = 0
    while (eng2._active or eng2._queue or eng2._done) and steps < 400:
        steps += 1
        for c in eng2.step():
            cid = rec[c.request_id]
            dup += cid in got
            got[cid] = c.tokens
    assert dup == 0
    assert len(got) == len(_PROMPTS)        # lost 0
    for i, rid in enumerate(rids):
        assert got[f"w-{i}"] == ref_out[i], f"diverged: w-{i}"
    assert j2.depth() == 0                  # final depth 0


def test_warm_restart_expired_deadline_is_honest_drop(tmp_path):
    path = str(tmp_path / "j.sqlite3")
    j = EngineJournal(path)
    # a journaled request whose wall-clock deadline passed during the
    # outage: recovery must DROP it (finish_reason deadline), never
    # compute it
    j.record_submit(0, [5, 6, 7], 8, correlation_id="late",
                    deadline_wall=time.time() - 5.0)
    j.close()
    eng = _tiny_engine(journal=EngineJournal(path))
    assert eng.journal_replayed == 0
    comps = eng.step()
    assert [c.finish_reason for c in comps] == ["deadline"]
    assert eng.journal.depth() == 0


def test_warm_restart_abandons_overlong_continuation(tmp_path):
    path = str(tmp_path / "j.sqlite3")
    j = EngineJournal(path)
    # prompt+checkpointed tokens beyond prompt_limit (48 on the tiny
    # engine): resuming would head-truncate and diverge — abandon,
    # honestly counted
    j.record_submit(0, list(range(3, 43)), 64, correlation_id="big")
    j.checkpoint(0, list(range(3, 23)))
    j.close()
    eng = _tiny_engine(journal=EngineJournal(path))
    assert eng.journal_replayed == 0
    assert eng.journal_abandoned == 1
    assert eng.journal.depth() == 0
    assert eng.journal_stats()["abandoned"] == 1


def test_warm_restart_already_complete_row_emits_without_compute(
        tmp_path):
    path = str(tmp_path / "j.sqlite3")
    j = EngineJournal(path)
    j.record_submit(0, [5, 6, 7], 4, correlation_id="done")
    j.checkpoint(0, [50, 51, 52, 53])     # full budget checkpointed
    j.close()
    eng = _tiny_engine(journal=EngineJournal(path))
    assert eng.journal_replayed == 0
    comps = eng.step()
    assert len(comps) == 1
    assert comps[0].tokens == [50, 51, 52, 53]
    assert comps[0].finish_reason == "length"
    assert eng.journal.depth() == 0


def test_journal_stats_surface(tmp_path):
    eng = _tiny_engine()
    assert eng.journal_stats() == {
        "enabled": False, "replayed": 0, "abandoned": 0}
    j = EngineJournal(str(tmp_path / "j.sqlite3"))
    eng2 = _tiny_engine(journal=j)
    s = eng2.journal_stats()
    assert s["enabled"] is True and s["depth"] == 0


# ---------------------------------------------------------------------------
# real-process SIGKILL (@slow): the bench kill phase as a test
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_real_process_sigkill_and_warm_restart(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    def child(journal, out, result, kill_after=0):
        cmd = [sys.executable, "-m",
               "copilot_for_consensus_tpu.tools.journal_storm",
               "--journal", str(journal), "--out", str(out),
               "--result", str(result), "--requests", "10",
               "--new-tokens", "20", "--seed", "5"]
        if kill_after:
            cmd += ["--kill-after-step", str(kill_after)]
        return subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=300)

    r = child(tmp_path / "ref.sqlite3", tmp_path / "ref.jsonl",
              tmp_path / "ref.json")
    assert r.returncode == 0, r.stderr[-2000:]

    r = child(tmp_path / "kill.sqlite3", tmp_path / "kill.jsonl",
              tmp_path / "kill.json", kill_after=6)
    assert r.returncode in (-signal.SIGKILL, 137), (
        "child was not SIGKILLed", r.returncode, r.stderr[-500:])

    r = child(tmp_path / "kill.sqlite3", tmp_path / "kill.jsonl",
              tmp_path / "resume.json")
    assert r.returncode == 0, r.stderr[-2000:]
    resume = json.loads((tmp_path / "resume.json").read_text())
    assert resume["resume"] is True
    assert resume["journal_replayed"] > 0
    assert resume["journal_depth"] == 0

    def lines(p):
        out, dup = {}, 0
        for line in p.read_text().splitlines():
            d = json.loads(line)
            dup += d["cid"] in out
            out[d["cid"]] = d["tokens"]
        return out, dup

    ref, _ = lines(tmp_path / "ref.jsonl")
    got, dup = lines(tmp_path / "kill.jsonl")
    assert dup == 0
    assert set(got) == set(ref)                       # lost 0
    assert all(got[c] == ref[c] for c in ref)         # bit-identical
