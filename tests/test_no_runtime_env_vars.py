"""Policy check as an executable test: services must not read os.environ at
runtime — all environment access goes through the config layer.

Parity with the reference's ``scripts/check_no_runtime_env_vars.py`` CI gate
(SURVEY.md §5 "Config / flag system").
"""

import pathlib
import re

PKG = pathlib.Path(__file__).resolve().parent.parent / "copilot_for_consensus_tpu"

# Modules allowed to touch the environment: the config layer itself, secret
# providers, and device/mesh bootstrap (XLA flags must be set pre-init).
# analysis/shardcheck.py and analysis/hlocheck.py are bootstrap of the same
# kind: they force the CPU platform + virtual device count for their analysis
# subprocess BEFORE jax's backend initializes — dev/CI tools, not runtime
# services.
ALLOWLIST = {
    "core/config.py",
    "security/secrets.py",
    "parallel/mesh.py",
    "analysis/shardcheck.py",
    "analysis/hlocheck.py",
}

PATTERN = re.compile(r"os\.environ|os\.getenv")


def test_no_runtime_env_reads_outside_config_layer():
    offenders = []
    for path in PKG.rglob("*.py"):
        rel = str(path.relative_to(PKG))
        if rel in ALLOWLIST:
            continue
        if PATTERN.search(path.read_text()):
            offenders.append(rel)
    assert offenders == [], (
        f"runtime os.environ access outside config layer: {offenders}"
    )
