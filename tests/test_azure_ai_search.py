# Azure AI Search vector store against a wire-contract mock: index
# provisioning with the HNSW profile, mergeOrUpload batching, vector
# search with OData filter pushdown, score conversion, lookup/delete/
# count/clear — with the in-memory store as the similarity oracle.
import json
import math
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from copilot_for_consensus_tpu.vectorstore.azure_ai_search import (
    AzureAISearchVectorStore,
)
from copilot_for_consensus_tpu.vectorstore.base import VectorStoreError
from copilot_for_consensus_tpu.vectorstore.memory import InMemoryVectorStore

API_KEY = "search-admin-key"


class _MockSearchService:
    def __init__(self):
        self.indexes = {}          # name -> {"definition", "docs"}
        self.lock = threading.Lock()
        self.stats = {"bad_auth": 0, "searches": 0}

    @staticmethod
    def _cosine(a, b):
        dot = sum(x * y for x, y in zip(a, b))
        na = math.sqrt(sum(x * x for x in a)) or 1e-30
        nb = math.sqrt(sum(x * x for x in b)) or 1e-30
        return dot / (na * nb)

    def _filter_pred(self, expr):
        """Evaluate the OData subset the driver emits; anything else
        fails loudly."""
        if expr is None:
            return lambda doc: True
        def eq_pred(term):
            m = re.fullmatch(r"(\w+) eq '((?:[^']|'')*)'", term.strip())
            if not m:
                return None
            key, val = m.group(1), m.group(2).replace("''", "'")
            return lambda d, k=key, v=val: d.get(k) == v

        terms = expr.split(" and ")
        preds = []
        for term in terms:
            term = term.strip()
            p = eq_pred(term)
            if p:
                preds.append(p)
                continue
            # eq-or membership chains: (k eq 'a' or k eq 'b')
            if term.startswith("(") and term.endswith(")"):
                alts = [eq_pred(t) for t in term[1:-1].split(" or ")]
                assert all(alts), f"mock cannot evaluate: {term!r}"
                preds.append(
                    lambda d, a=alts: any(p(d) for p in a))
                continue
            raise AssertionError(f"mock cannot evaluate OData: {term!r}")
        return lambda doc: all(p(doc) for p in preds)

    def search(self, index, body):
        self.stats["searches"] += 1
        docs = list(self.indexes[index]["docs"].values())
        pred = self._filter_pred(body.get("filter"))
        docs = [d for d in docs if pred(d)]
        vqs = body.get("vectorQueries") or []
        if vqs:
            (vq,) = vqs
            assert vq["kind"] == "vector" and vq["fields"] == "embedding"
            scored = []
            for d in docs:
                cos = self._cosine(vq["vector"], d["embedding"])
                scored.append((1.0 / (1.0 + (1.0 - cos)), d))
            scored.sort(key=lambda t: -t[0])
            scored = scored[:min(int(vq["k"]),
                                 int(body.get("top", vq["k"])))]
        else:
            scored = [(1.0, d) for d in docs][:int(body.get("top",
                                                            50))]
        select = (body.get("select") or "").split(",")
        out = []
        for score, d in scored:
            row = {k: d.get(k) for k in select if k}
            row["@search.score"] = score
            out.append(row)
        resp = {"value": out}
        if body.get("count"):
            resp["@odata.count"] = len(docs)
        return resp


def _make_handler(state):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _reply(self, status, obj=None):
            body = (json.dumps(obj).encode()
                    if obj is not None else b"")
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _handle(self, method):
            if self.headers.get("api-key") != API_KEY:
                state.stats["bad_auth"] += 1
                return self._reply(403, {"error": "forbidden"})
            parsed = urllib.parse.urlparse(self.path)
            assert "api-version=" in (parsed.query or "")
            path = urllib.parse.unquote(parsed.path)
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n)) if n else None
            with state.lock:
                return self._route(method, path, body)

        def _route(self, method, path, body):
            m = re.fullmatch(r"/indexes/([^/]+)", path)
            if m:
                name = m.group(1)
                if method == "PUT":
                    # index update with a changed schema is rejected
                    # like the real service
                    old = state.indexes.get(name)
                    if old and old["definition"]["fields"] != \
                            body["fields"]:
                        return self._reply(400,
                                           {"error": "schema change"})
                    state.indexes.setdefault(
                        name, {"definition": body, "docs": {}})
                    state.indexes[name]["definition"] = body
                    return self._reply(201 if old is None else 200)
                if method == "DELETE":
                    return self._reply(
                        204 if state.indexes.pop(name, None) else 404)
            m = re.fullmatch(r"/indexes/([^/]+)/docs/index", path)
            if m and method == "POST":
                index = state.indexes.get(m.group(1))
                if index is None:
                    return self._reply(404)
                results = []
                dims = next(
                    f["dimensions"] for f in
                    index["definition"]["fields"]
                    if f["name"] == "embedding")
                for action in body["value"]:
                    act = action.pop("@search.action")
                    key = action["id"]
                    if act in ("mergeOrUpload", "upload"):
                        if len(action.get("embedding") or []) != dims:
                            results.append(
                                {"key": key, "status": False,
                                 "errorMessage": "dimension mismatch",
                                 "statusCode": 400})
                            continue
                        index["docs"][key] = action
                        results.append({"key": key, "status": True,
                                        "statusCode": 200})
                    elif act == "delete":
                        index["docs"].pop(key, None)
                        results.append({"key": key, "status": True,
                                        "statusCode": 200})
                return self._reply(200, {"value": results})
            m = re.fullmatch(r"/indexes/([^/]+)/docs/search", path)
            if m and method == "POST":
                index = state.indexes.get(m.group(1))
                if index is None:
                    return self._reply(404)
                return self._reply(200, state.search(m.group(1), body))
            m = re.fullmatch(r"/indexes/([^/]+)/docs/\$count", path)
            if m and method == "GET":
                index = state.indexes.get(m.group(1))
                if index is None:
                    return self._reply(404)
                return self._reply(200, len(index["docs"]))
            m = re.fullmatch(
                r"/indexes/([^/]+)/docs\('((?:[^']|'')*)'\)", path)
            if m and method == "GET":
                index = state.indexes.get(m.group(1))
                # OData key literal: '' unescapes to ' (path itself
                # already percent-decoded above)
                doc = (index or {"docs": {}})["docs"].get(
                    m.group(2).replace("''", "'"))
                if doc is None:
                    return self._reply(404)
                return self._reply(200, doc)
            return self._reply(400, {"error": f"unroutable {path}"})

        def do_GET(self):
            self._handle("GET")

        def do_POST(self):
            self._handle("POST")

        def do_PUT(self):
            self._handle("PUT")

        def do_DELETE(self):
            self._handle("DELETE")

    return Handler


@pytest.fixture()
def mock_search():
    state = _MockSearchService()
    server = ThreadingHTTPServer(("127.0.0.1", 0),
                                 _make_handler(state))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        yield f"http://127.0.0.1:{server.server_address[1]}", state
    finally:
        server.shutdown()
        server.server_close()


def _store(endpoint, **kw):
    cfg = {"endpoint": endpoint, "api_key": API_KEY, "dimension": 8,
           **kw}
    s = AzureAISearchVectorStore(cfg)
    s.connect()
    return s


def _vec(seed, dim=8):
    return [math.sin(seed * (i + 1)) for i in range(dim)]


def test_index_provisioned_with_reference_hnsw_profile(mock_search):
    """The created index carries the reference's HNSW configuration
    (azure_ai_search_store.py:255 — m=4, efConstruction=400,
    efSearch=500, cosine)."""
    endpoint, state = mock_search
    _store(endpoint, index_name="emb")
    definition = state.indexes["emb"]["definition"]
    (algo,) = definition["vectorSearch"]["algorithms"]
    assert algo["kind"] == "hnsw"
    assert algo["hnswParameters"] == {
        "m": 4, "efConstruction": 400, "efSearch": 500,
        "metric": "cosine"}
    fields = {f["name"]: f for f in definition["fields"]}
    assert fields["id"]["key"] and fields["id"]["filterable"]
    assert fields["embedding"]["dimensions"] == 8
    assert fields["thread_id"]["filterable"]


def test_query_matches_memory_store_oracle(mock_search):
    """Same vectors, same queries, same filters: ids, order, and
    (converted) cosine scores match the in-memory reference store."""
    endpoint, _ = mock_search
    azure = _store(endpoint)
    mem = InMemoryVectorStore({})
    for i in range(30):
        md = {"thread_id": f"t{i % 3}", "chunk_id": f"c{i}",
              "note": "unfiltered-extra"}
        azure.add_embedding(f"v{i}", _vec(i + 1), md)
        mem.add_embedding(f"v{i}", _vec(i + 1), md)
    for flt in (None, {"thread_id": "t1"},
                {"thread_id": {"$in": ["t0", "t2"]}},
                {"thread_id": {"$in": []}},
                {"thread_id": "t1", "chunk_id": "c4"},
                {"thread_id": "nope"}):
        got = azure.query(_vec(5), top_k=5, flt=flt)
        want = mem.query(_vec(5), top_k=5, flt=flt)
        assert [r.id for r in got] == [r.id for r in want], flt
        for g, w in zip(got, want):
            assert g.score == pytest.approx(w.score, abs=1e-6)
            assert g.metadata == w.metadata


def test_batched_upsert_and_count_and_get(mock_search):
    endpoint, _ = mock_search
    azure = _store(endpoint)
    n = azure.add_embeddings(
        (f"v{i}", _vec(i + 1), {"chunk_id": f"c{i}"})
        for i in range(7))
    assert n == 7 and azure.count() == 7
    vec, md = azure.get("v3")
    assert vec == pytest.approx(_vec(4))
    assert md == {"chunk_id": "c3"}
    assert azure.get("absent") is None
    # upsert semantics: same id replaces, count stable
    azure.add_embedding("v3", _vec(99), {"chunk_id": "r"})
    assert azure.count() == 7
    assert azure.get("v3")[1] == {"chunk_id": "r"}


def test_delete_reports_honest_counts(mock_search):
    endpoint, _ = mock_search
    azure = _store(endpoint)
    for i in range(5):
        azure.add_embedding(f"v{i}", _vec(i + 1),
                            {"thread_id": f"t{i % 2}"})
    assert azure.delete(["v0", "v1", "ghost"]) == 2
    assert azure.count() == 3
    assert azure.delete_by_filter({"thread_id": "t0"}) == 2
    assert azure.count() == 1


def test_hostile_ids_roundtrip(mock_search):
    """Ids are arbitrary strings per the base contract: commas must not
    split membership filters, quotes must not break OData literals."""
    endpoint, _ = mock_search
    azure = _store(endpoint)
    hostile = ["a,b", "it's", "plain", "a", "b"]
    for i, vid in enumerate(hostile):
        azure.add_embedding(vid, _vec(i + 1), {"chunk_id": vid})
    vec, md = azure.get("it's")
    assert md == {"chunk_id": "it's"}
    # deleting "a,b" must NOT count/touch docs "a" and "b"
    assert azure.delete(["a,b"]) == 1
    assert azure.count() == 4
    assert azure.get("a") is not None and azure.get("b") is not None


def test_dimension_mismatch_and_unsupported_filters(mock_search):
    endpoint, _ = mock_search
    azure = _store(endpoint)
    with pytest.raises(VectorStoreError, match="dimension"):
        azure.add_embedding("bad", [1.0, 2.0], {})
    with pytest.raises(VectorStoreError, match="dimension"):
        azure.query([1.0] * 3)
    azure.add_embedding("ok", _vec(1), {"note": "x"})
    with pytest.raises(VectorStoreError, match="filterable_keys"):
        azure.query(_vec(1), flt={"note": "x"})
    with pytest.raises(VectorStoreError, match="operator"):
        azure.query(_vec(1), flt={"thread_id": {"$gt": "a"}})


def test_clear_drops_and_recreates_index(mock_search):
    endpoint, state = mock_search
    azure = _store(endpoint)
    azure.add_embedding("v1", _vec(1), {})
    azure.clear()
    assert azure.count() == 0
    assert "embeddings" in state.indexes       # recreated
    azure.add_embedding("v2", _vec(2), {})
    assert azure.count() == 1


def test_bad_api_key_and_validation(mock_search):
    endpoint, state = mock_search
    bad = AzureAISearchVectorStore(
        {"endpoint": endpoint, "api_key": "wrong", "dimension": 8})
    with pytest.raises(VectorStoreError, match="403"):
        bad.connect()
    assert state.stats["bad_auth"] >= 1
    with pytest.raises(ValueError, match="endpoint"):
        AzureAISearchVectorStore({"api_key": "k", "dimension": 8})
    with pytest.raises(ValueError, match="dimension"):
        AzureAISearchVectorStore({"endpoint": "http://x",
                                  "api_key": "k"})


def test_factory_registration(mock_search):
    from copilot_for_consensus_tpu.vectorstore.factory import (
        create_vector_store,
    )

    endpoint, _ = mock_search
    store = create_vector_store({
        "driver": "azure_ai_search", "endpoint": endpoint,
        "api_key": API_KEY, "dimension": 8})
    assert isinstance(store, AzureAISearchVectorStore)
    assert store.dimension == 8
