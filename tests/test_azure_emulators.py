"""Azure emulator integration lane (round-5 verdict item 7).

The wire-contract mock suites (`test_azure_servicebus.py`,
`test_azure_ai_search.py`, ...) encode our BELIEF about each Azure
REST protocol; this lane checks that belief against Microsoft's own
emulators, the way the reference's azure-integration CI does
(reference ``docker-compose.azure-emulators.yml``,
``.github/workflows/azure-integration-ci.yml``).

Coverage here is the two drivers whose emulators speak the REST data
plane our drivers implement:

- **Azure Blob archive store** against **Azurite** (full Blob REST).
- **Cosmos document store** against the **Cosmos vNext emulator**
  (SQL-over-REST).

Not emulatable: the Service Bus emulator exposes AMQP 1.0 only (no
REST data plane, which `bus/azure_servicebus.py` implements), and AI
Search / Key Vault have no official emulators — those drivers remain
wire-mock-verified only, matching the reference's own gaps (its SB
emulator block is marked "not yet used in CI").

Run:
    docker compose -f deploy/docker-compose.azure-emulators.yml up -d
    AZURITE_BLOB_ENDPOINT=http://127.0.0.1:10000/devstoreaccount1 \
    COSMOS_EMULATOR_ENDPOINT=http://127.0.0.1:8081 \
        python -m pytest tests/test_azure_emulators.py -m emulator -v

Each driver's tests skip cleanly when its endpoint env var is unset,
so the default lanes never depend on docker.
"""

from __future__ import annotations

import os
import uuid

import pytest

pytestmark = [pytest.mark.emulator, pytest.mark.integration]

AZURITE = os.environ.get("AZURITE_BLOB_ENDPOINT", "")
COSMOS = os.environ.get("COSMOS_EMULATOR_ENDPOINT", "")

# Microsoft's documented well-known Azurite dev credentials — NOT
# secrets (they only ever authenticate against a local emulator).
AZURITE_ACCOUNT = "devstoreaccount1"
AZURITE_KEY = ("Eby8vdM02xNOcqFlqUwJPLlmEtlCDXJ1OUzFT50uSRZ6IFsuFq2UVErC"
               "z4I6tq/K1SZFPTOtr/KBHBeksoGMGw==")
# Cosmos emulator's documented fixed master key — same status.
COSMOS_KEY = ("C2y6yDjf5/R+ob0N8A7Cgv30VRDJIWEHLM+4QDU5DE2nQ9nDuVTqobD4b8"
              "mGGyPMbIZnqyMsEcaGQy67XIw/Jw==")


# -- Azurite: Blob archive store ---------------------------------------

azurite = pytest.mark.skipif(
    not AZURITE, reason="AZURITE_BLOB_ENDPOINT not set (emulator lane)")


def _create_container(container: str) -> None:
    """Provision the test container with a raw SharedKey PUT (the
    driver itself deliberately has no provisioning surface — operators
    own container lifecycle)."""
    import email.utils
    import urllib.request

    from copilot_for_consensus_tpu.archive.azure_blob import (
        _shared_key_signature,
    )

    url = f"{AZURITE.rstrip('/')}/{container}?restype=container"
    headers = {"x-ms-date": email.utils.formatdate(usegmt=True),
               "x-ms-version": "2021-08-06"}
    headers["Authorization"] = _shared_key_signature(
        AZURITE_ACCOUNT, AZURITE_KEY, "PUT", url, headers, 0)
    req = urllib.request.Request(url, method="PUT", headers=headers)
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.status == 201


@pytest.fixture()
def blob_store():
    from copilot_for_consensus_tpu.archive.azure_blob import (
        AzureBlobArchiveStore,
    )

    container = f"emul-{uuid.uuid4().hex[:10]}"
    _create_container(container)
    return AzureBlobArchiveStore(
        AZURITE_ACCOUNT, container,
        account_key=AZURITE_KEY, endpoint=AZURITE)


@azurite
def test_blob_round_trip_against_azurite(blob_store):
    aid = uuid.uuid4().hex[:16]
    uri = blob_store.save(aid, b"From x@y Mon\nSubject: hi\n\nbody\n",
                          {"source_id": "emul"})
    assert aid in uri
    assert blob_store.load(aid).startswith(b"From x@y")
    assert blob_store.exists(aid)
    assert blob_store.delete(aid)
    assert not blob_store.exists(aid)
    assert not blob_store.delete(aid)      # second delete reports absent


@azurite
def test_blob_overwrite_and_missing_against_azurite(blob_store):
    aid = uuid.uuid4().hex[:16]
    blob_store.save(aid, b"v1", {})
    blob_store.save(aid, b"v2 longer content", {})
    assert blob_store.load(aid) == b"v2 longer content"
    with pytest.raises(Exception):
        blob_store.load("0" * 16)          # absent blob must not return junk


# -- Cosmos emulator: document store -----------------------------------

cosmos = pytest.mark.skipif(
    not COSMOS, reason="COSMOS_EMULATOR_ENDPOINT not set (emulator lane)")


@pytest.fixture()
def cosmos_store():
    from copilot_for_consensus_tpu.storage.azure_cosmos import (
        AzureCosmosDocumentStore,
    )

    store = AzureCosmosDocumentStore(
        "emulator", COSMOS_KEY, database=f"emul{uuid.uuid4().hex[:8]}",
        endpoint=COSMOS)
    store.connect()
    return store


@cosmos
def test_cosmos_crud_and_filters_against_emulator(cosmos_store):
    st = cosmos_store
    for i in range(5):
        st.insert_document("threads", {
            "thread_id": f"t{i}", "subject": f"subject {i}",
            "message_count": i})
    assert st.count_documents("threads") == 5
    got = st.get_document("threads", "t3")
    assert got and got["message_count"] == 3
    # the filter->SQL translation must hold against the REAL query
    # engine, not just the oracle mock
    rows = st.query_documents("threads",
                              {"message_count": {"$gte": 3}})
    assert sorted(r["thread_id"] for r in rows) == ["t3", "t4"]
    rows = st.query_documents(
        "threads", {"thread_id": {"$in": ["t0", "t4", "zz"]}},
        sort=[("message_count", -1)])
    assert [r["thread_id"] for r in rows] == ["t4", "t0"]
    st.update_document("threads", "t0", {"message_count": 99})
    assert st.get_document("threads", "t0")["message_count"] == 99
    assert st.delete_document("threads", "t1")
    assert st.count_documents("threads") == 4
