import numpy as np
import pytest

from copilot_for_consensus_tpu.vectorstore import (
    InMemoryVectorStore,
    VectorStoreError,
    create_vector_store,
)


def test_add_query_exact_top_k():
    vs = InMemoryVectorStore()
    vs.add_embedding("a", [1.0, 0.0, 0.0], {"thread_id": "t1"})
    vs.add_embedding("b", [0.0, 1.0, 0.0], {"thread_id": "t1"})
    vs.add_embedding("c", [0.9, 0.1, 0.0], {"thread_id": "t2"})
    res = vs.query([1.0, 0.0, 0.0], top_k=2)
    assert [r.id for r in res] == ["a", "c"]
    assert res[0].score == pytest.approx(1.0)
    assert res[0].score >= res[1].score


def test_metadata_filter():
    vs = InMemoryVectorStore()
    vs.add_embedding("a", [1.0, 0.0], {"thread_id": "t1"})
    vs.add_embedding("b", [0.99, 0.01], {"thread_id": "t2"})
    res = vs.query([1.0, 0.0], top_k=5, flt={"thread_id": "t2"})
    assert [r.id for r in res] == ["b"]


def test_upsert_semantics():
    vs = InMemoryVectorStore()
    vs.add_embedding("a", [1.0, 0.0], {"v": 1})
    vs.add_embedding("a", [0.0, 1.0], {"v": 2})
    assert vs.count() == 1
    vec, meta = vs.get("a")
    assert meta == {"v": 2}
    assert np.argmax(vec) == 1


def test_dimension_enforced():
    vs = InMemoryVectorStore()
    vs.add_embedding("a", [1.0, 0.0, 0.0])
    assert vs.dimension == 3
    with pytest.raises(VectorStoreError):
        vs.add_embedding("b", [1.0, 0.0])


def test_delete_and_clear():
    vs = InMemoryVectorStore()
    for i in range(5):
        vs.add_embedding(f"v{i}", np.eye(5)[i])
    assert vs.delete(["v0", "v3"]) == 2
    assert vs.count() == 3
    assert vs.get("v0") is None
    assert [r.id for r in vs.query(np.eye(5)[1], top_k=1)] == ["v1"]
    vs.clear()
    assert vs.count() == 0
    assert vs.query([1, 0, 0, 0, 0]) == []


def test_persistence_roundtrip(tmp_path):
    path = tmp_path / "vs.npz"
    vs = InMemoryVectorStore()
    vs.add_embedding("a", [0.5, 0.5], {"thread_id": "t1"})
    vs.save(path)
    vs2 = InMemoryVectorStore()
    vs2.load(path)
    assert vs2.count() == 1
    res = vs2.query([0.5, 0.5], top_k=1)
    assert res[0].id == "a"
    assert res[0].metadata == {"thread_id": "t1"}


def test_factory():
    vs = create_vector_store({"driver": "memory", "dimension": 4})
    assert vs.dimension == 4
    with pytest.raises(ValueError):
        create_vector_store({"driver": "qdrant"})


def test_query_filters_beyond_the_inverted_index():
    """Dotted-path keys and non-scalar metadata values can't be answered
    by the inverted index; the store must fall back to the matcher scan
    instead of treating an index miss as 'no results' (regression)."""
    from copilot_for_consensus_tpu.vectorstore.memory import (
        InMemoryVectorStore,
    )

    s = InMemoryVectorStore()
    s.add_embedding("a", [1.0, 0.0], {"meta": {"lang": "en"}, "page": 1.0})
    s.add_embedding("b", [0.0, 1.0], {"meta": {"lang": "de"}, "page": 2.0})
    got = s.query([1.0, 0.0], top_k=2, flt={"meta.lang": "en"})
    assert [g.id for g in got] == ["a"]
    got = s.query([1.0, 0.0], top_k=2, flt={"page": 1})
    assert [g.id for g in got] == ["a"]
    # A key that was scalar everywhere still uses the index path.
    s.add_embedding("c", [1.0, 1.0], {"thread_id": "t1"})
    got = s.query([1.0, 0.0], top_k=3, flt={"thread_id": "t1"})
    assert [g.id for g in got] == ["c"]
