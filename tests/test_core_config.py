import json

import pytest

from copilot_for_consensus_tpu.core.config import (
    ConfigError,
    FrozenConfig,
    get_config,
)


def test_defaults_from_schema():
    cfg = get_config("embedding", env={})
    assert cfg.bus.driver == "inproc"
    assert cfg.document_store.driver == "memory"
    assert cfg.embedding_backend.batch_size == 128
    assert cfg.service_name == "embedding"


def test_env_overrides_nested(tmp_path):
    env = {"COPILOT_EMBEDDING__EMBEDDING_BACKEND__BATCH_SIZE": "64",
           "COPILOT_EMBEDDING__BUS__DRIVER": "zmq"}
    cfg = get_config("embedding", env=env)
    assert cfg.embedding_backend.batch_size == 64
    assert cfg.bus.driver == "zmq"


def test_config_file_and_combined_file(tmp_path):
    single = tmp_path / "emb.json"
    single.write_text(json.dumps({"embedding_backend": {"driver": "tpu"}}))
    cfg = get_config("embedding", env={}, config_path=single)
    assert cfg.embedding_backend.driver == "tpu"

    combined = tmp_path / "all.json"
    combined.write_text(json.dumps(
        {"services": {"embedding": {"embedding_backend": {"dimension": 512}},
                      "parsing": {}}}))
    cfg = get_config("embedding", env={"COPILOT_CONFIG": str(combined)})
    assert cfg.embedding_backend.dimension == 512


def test_per_service_file_with_self_named_section(tmp_path):
    # A service whose schema has a section named after itself (auth.auth)
    # must not be mistaken for a combined file.
    p = tmp_path / "auth.json"
    p.write_text(json.dumps({"auth": {"enabled": True},
                             "jwt_signer": {"issuer": "x"}}))
    cfg = get_config("auth", env={}, config_path=p)
    assert cfg.auth.enabled is True
    assert cfg.jwt_signer.issuer == "x"


def test_secret_values_redacted_in_validation_errors():
    from copilot_for_consensus_tpu.core.validation import SchemaValidationError
    env = {"COPILOT_EMBEDDING__EMBEDDING_BACKEND__BATCH_SIZE": '"secret://bs"',
           "COPILOT_SECRET_BS": "hunter2-super-secret"}
    with pytest.raises(SchemaValidationError) as exc_info:
        get_config("embedding", env=env)
    assert "hunter2-super-secret" not in str(exc_info.value)
    assert "***" in str(exc_info.value)


def test_missing_config_file_fails_fast():
    with pytest.raises(ConfigError):
        get_config("embedding", env={}, config_path="/nonexistent/cfg.json")


def test_secret_resolution():
    env = {"COPILOT_EMBEDDING__VECTOR_STORE__API_KEY": '"secret://vk"',
           "COPILOT_SECRET_VK": "s3cret"}
    cfg = get_config("embedding", env=env)
    assert cfg.vector_store.api_key == "s3cret"


def test_frozen_config_immutable_and_replace():
    cfg = FrozenConfig({"a": {"b": 1}, "c": 2})
    with pytest.raises(AttributeError):
        cfg.c = 3
    stamped = cfg.replace(a={"b": 9}, service_name="x")
    assert stamped.a.b == 9
    assert cfg.a.b == 1
    assert stamped.service_name == "x"


def test_all_service_schemas_load():
    for svc in ("ingestion", "parsing", "chunking", "embedding",
                "orchestrator", "summarization", "reporting", "auth",
                "tpu_engine"):
        cfg = get_config(svc, env={})
        assert cfg.service_name == svc
