# Checkpoint subsystem: HF import golden-logit parity (vs transformers on
# CPU), native round-trip, offline int8 quantization accuracy.
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu import checkpoint
from copilot_for_consensus_tpu.models import decoder

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

TOKENS = np.array([[1, 7, 42, 250, 3, 99, 17, 5]], dtype=np.int32)


def _tiny_hf_dir(tmp_path, moe=False):
    """Build a small *real* HF checkpoint with random weights, fixed seed."""
    torch.manual_seed(0)
    common = dict(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    if moe:
        cfg = transformers.MixtralConfig(
            num_local_experts=4, num_experts_per_tok=2, **common)
        model = transformers.MixtralForCausalLM(cfg)
    else:
        cfg = transformers.MistralConfig(sliding_window=None, **common)
        model = transformers.MistralForCausalLM(cfg)
    model = model.to(torch.float32).eval()
    out = tmp_path / ("hf-mixtral" if moe else "hf-mistral")
    model.save_pretrained(out, safe_serialization=True)
    return out, model


@pytest.fixture(scope="module")
def mistral(tmp_path_factory):
    return _tiny_hf_dir(tmp_path_factory.mktemp("ckpt"))


def _to_jax(params):
    return jax.tree.map(jnp.asarray, params)


def test_config_mapping(mistral):
    path, _ = mistral
    cfg = checkpoint.config_from_hf(checkpoint.read_hf_config(path))
    assert cfg.d_model == 64 and cfg.n_layers == 2
    assert cfg.n_heads == 4 and cfg.n_kv_heads == 2
    assert cfg.vocab_size == 256 and not cfg.is_moe


def test_golden_logits_mistral(mistral):
    path, model = mistral
    cfg, params = checkpoint.load_hf_checkpoint(path, dtype="float32")
    with torch.no_grad():
        ref = model(torch.from_numpy(TOKENS).long()).logits.numpy()
    got = np.asarray(
        decoder.forward(_to_jax(params), jnp.asarray(TOKENS), cfg,
                        attn_impl="xla"))
    np.testing.assert_allclose(got, ref, atol=2e-3, rtol=1e-3)


def test_golden_logits_mixtral(tmp_path):
    path, model = _tiny_hf_dir(tmp_path, moe=True)
    cfg, params = checkpoint.load_hf_checkpoint(path, dtype="float32")
    # HF Mixtral routes without capacity limits; crank capacity so our
    # dispatch drops nothing and parity is exact.
    cfg = dataclasses.replace(cfg, expert_capacity_factor=8.0)
    assert cfg.is_moe and cfg.n_experts == 4
    with torch.no_grad():
        ref = model(torch.from_numpy(TOKENS).long()).logits.numpy()
    got = np.asarray(
        decoder.forward(_to_jax(params), jnp.asarray(TOKENS), cfg,
                        attn_impl="xla"))
    np.testing.assert_allclose(got, ref, atol=5e-3, rtol=1e-3)


def test_native_roundtrip_and_quantized_accuracy(mistral, tmp_path):
    path, _ = mistral
    dst = tmp_path / "native"
    meta = checkpoint.convert(path, dst, quantize=True, dtype="float32")
    assert meta["quantized"] == "int8"   # mode string; truthy for callers

    cfg, qparams, meta2 = checkpoint.load_checkpoint(dst)
    assert meta2["format"] == checkpoint.FORMAT
    assert qparams["layers"]["wq"]["q"].dtype == np.int8

    # int8 weight-only logits stay close to the fp32 reference
    cfg_f, fparams = checkpoint.load_hf_checkpoint(path, dtype="float32")
    full = np.asarray(decoder.forward(_to_jax(fparams), jnp.asarray(TOKENS),
                                      cfg_f, attn_impl="xla"))
    quant = np.asarray(decoder.forward(_to_jax(qparams), jnp.asarray(TOKENS),
                                       cfg, attn_impl="xla"))
    # same top-1 next-token choice at every position
    assert (quant.argmax(-1) == full.argmax(-1)).mean() > 0.95
    assert np.abs(quant - full).max() < 0.15


def test_native_int4_roundtrip(mistral, tmp_path):
    """Offline int4 conversion → native load → forward. 4-bit RTN is
    coarser than int8, so the bar is agreement on most top-1 choices,
    not tight logit closeness."""
    path, _ = mistral
    dst = tmp_path / "native4"
    meta = checkpoint.convert(path, dst, quantize="int4", dtype="float32")
    assert meta["quantized"] == "int4"

    cfg, qparams, _ = checkpoint.load_checkpoint(dst)
    wq = qparams["layers"]["wq"]
    assert wq["q4"].dtype == np.int8
    # packed rows are half the contraction dim
    assert wq["q4"].shape[-2] * 2 == qparams["layers"]["attn_norm"].shape[-1]

    cfg_f, fparams = checkpoint.load_hf_checkpoint(path, dtype="float32")
    full = np.asarray(decoder.forward(_to_jax(fparams), jnp.asarray(TOKENS),
                                      cfg_f, attn_impl="xla"))
    q4 = np.asarray(decoder.forward(_to_jax(qparams), jnp.asarray(TOKENS),
                                    cfg, attn_impl="xla"))
    # Tiny random models have near-uniform logits, so top-1 flips on
    # quantization noise; the stable contract is directional agreement
    # of the logit vectors.
    f = full.reshape(-1, full.shape[-1])
    q = q4.reshape(-1, q4.shape[-1])
    cos = (f * q).sum(-1) / (
        np.linalg.norm(f, axis=-1) * np.linalg.norm(q, axis=-1) + 1e-9)
    assert cos.min() > 0.9, f"min logit cosine {cos.min():.3f}"


def test_hf_dir_autodetect(mistral):
    path, _ = mistral
    cfg, params, meta = checkpoint.load_checkpoint(path, dtype="float32")
    assert meta["format"] == "hf" and not meta["quantized"]
    assert params["layers"]["wq"].shape == (2, 64, 64)


def _write_tiny_tokenizer(path):
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers
    tok = Tokenizer(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    trainer = trainers.BpeTrainer(
        vocab_size=200,
        special_tokens=["<pad>", "<s>", "</s>", "<unk>"])
    tok.train_from_iterator(
        ["hello world consensus draft ietf thread summary agree"] * 4,
        trainer)
    tok.save(str(path / "tokenizer.json"))


def test_engine_from_checkpoint_end_to_end(mistral, tmp_path):
    from copilot_for_consensus_tpu.engine.generation import GenerationEngine

    path, _ = mistral
    _write_tiny_tokenizer(path)
    dst = tmp_path / "native"
    checkpoint.convert(path, dst, quantize=True, dtype="float32")

    eng = GenerationEngine.from_checkpoint(
        str(dst), dtype=jnp.float32, num_slots=2, max_len=64,
        prefill_buckets=(16,), attn_impl="xla")
    tok = checkpoint.load_tokenizer(dst)
    assert tok is not None and tok.bos_id == 1 and tok.eos_id == 2
    texts = eng.generate_text(["hello consensus draft"], tok,
                              max_new_tokens=8)
    assert len(texts) == 1 and isinstance(texts[0], str)


def test_tpu_summarizer_from_checkpoint(mistral, tmp_path):
    from copilot_for_consensus_tpu.summarization.base import ThreadContext
    from copilot_for_consensus_tpu.summarization.factory import (
        create_summarizer,
    )

    path, _ = mistral
    _write_tiny_tokenizer(path)
    dst = tmp_path / "native-s"
    checkpoint.convert(path, dst, quantize=True, dtype="float32")
    s = create_summarizer({
        "driver": "tpu", "checkpoint": str(dst), "num_slots": 2,
        "max_len": 64, "max_new_tokens": 8})
    s.engine.buckets = (64,)
    out = s.summarize(ThreadContext(
        thread_id="t1", subject="hello", participants=["a@x"],
        message_count=1, chunks=[{"chunk_id": "c1", "text": "hello world",
                                  "message_doc_id": "m1"}]))
    assert out.thread_id == "t1" and "checkpoint:" in out.model


def test_multi_eos_and_missing_tokenizer(mistral, tmp_path):
    import json as _json

    from copilot_for_consensus_tpu.engine.generation import GenerationEngine
    from copilot_for_consensus_tpu.engine.tokenizer import HFTokenizer
    from copilot_for_consensus_tpu.summarization.tpu_summarizer import (
        TPUSummarizer,
    )

    path, _ = mistral
    # simulate a Llama-3.1-style list-valued eos_token_id
    cfg_file = path / "config.json"
    hf_cfg = _json.loads(cfg_file.read_text())
    hf_cfg["eos_token_id"] = [2, 5]
    cfg_file.write_text(_json.dumps(hf_cfg))
    dst = tmp_path / "native-eos"
    checkpoint.convert(path, dst, quantize=False, dtype="float32")
    meta = _json.loads((dst / "meta.json").read_text())
    assert meta["eos_id"] == 2 and meta["eos_ids"] == [2, 5]

    eng = GenerationEngine.from_checkpoint(
        str(dst), dtype=jnp.float32, num_slots=2, max_len=32,
        prefill_buckets=(16,), attn_impl="xla")
    assert eng._eos_set == {2, 5}

    tok = checkpoint.load_tokenizer(dst)
    assert tok is not None and tok.eos_ids == (2, 5)

    # a native dir without tokenizer.json must refuse, not fall back
    (dst / "tokenizer.json").unlink()
    with pytest.raises(ValueError, match="tokenizer.json"):
        TPUSummarizer(checkpoint=str(dst), num_slots=2, max_len=32)
    hf_cfg["eos_token_id"] = 2
    cfg_file.write_text(_json.dumps(hf_cfg))


def test_rope_scaling_rejected(mistral):
    import json as _json

    path, _ = mistral
    hf_cfg = _json.loads((path / "config.json").read_text())
    hf_cfg["rope_scaling"] = {"rope_type": "llama3", "factor": 8.0}
    try:
        with pytest.raises(checkpoint.CheckpointError, match="rope_scaling"):
            checkpoint.config_from_hf(hf_cfg)
    finally:
        pass
