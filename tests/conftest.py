# Test harness: force an 8-device virtual CPU platform BEFORE jax initialises.
#
# Mirrors the reference's fake-backend strategy (SURVEY.md §4): the full
# multi-chip sharding path is exercised on a virtual device mesh so the suite
# runs anywhere; bench.py (not pytest) is what touches the real TPU chip.
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override axon/tpu: tests use the fake mesh
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize imports jax at interpreter start (to register
# the axon TPU plugin), so jax snapshotted JAX_PLATFORMS from the original
# env. Backends are still uninitialized here, so a config update wins.
import jax

jax.config.update("jax_platforms", "cpu")

import pathlib
import sys

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

FIXTURES = pathlib.Path(__file__).resolve().parent / "fixtures"


@pytest.fixture
def fixtures_dir() -> pathlib.Path:
    return FIXTURES


# -- telemetry-bundle CI artifact --------------------------------------
#
# When COPILOT_FLIGHT_RECORD_DIR is set (ci.yml exports it for the test
# lanes), engine telemetry auto-dumps land there on engine errors, and
# the hook below additionally dumps every live recorder when a test
# FAILS — flight records, pipeline trace dumps, AND every live
# telemetry shipper's spool (obs/ship.py) land in ONE directory that
# ci.yml uploads as the telemetry-bundle artifact. A red suite ships
# its whole post-mortem (per-dispatch step records, span DAGs readable
# by tools/tracepath, crash-safe spools readable by the aggregator and
# the slo CLI) instead of a bare traceback. The env read happens here
# in the harness, not in the package (test_no_runtime_env_vars policy).
_FLIGHT_DIR = os.environ.get("COPILOT_FLIGHT_RECORD_DIR", "")
if _FLIGHT_DIR:
    from copilot_for_consensus_tpu.engine import telemetry as _telemetry
    from copilot_for_consensus_tpu.obs import ship as _ship
    from copilot_for_consensus_tpu.obs import trace as _trace

    _telemetry.set_default_dump_dir(_FLIGHT_DIR)
    # Pipeline trace dumps (obs/trace.py) land in the same artifact
    # directory, so a red pipeline test ships its span DAG (stage
    # spans + queue waits + correlation ids, readable by
    # tools/tracepath) alongside the engine flight records.
    _trace.set_default_dump_dir(_FLIGHT_DIR)
    # Shippers built without an explicit path spool here too — the
    # failure hook flushes them so committed rows are in the bundle.
    _ship.set_default_spool_dir(_FLIGHT_DIR)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    if not _FLIGHT_DIR:
        return
    rep = outcome.get_result()
    if rep.when == "call" and rep.failed:
        import re

        from copilot_for_consensus_tpu.engine import (
            telemetry as _telemetry,
        )
        from copilot_for_consensus_tpu.obs import ship as _ship
        from copilot_for_consensus_tpu.obs import trace as _trace

        tag = re.sub(r"[^A-Za-z0-9._-]+", "_", item.nodeid)[-80:]
        _telemetry.dump_all(_FLIGHT_DIR, tag=tag)
        _trace.dump_all(_FLIGHT_DIR, tag=f"pipeline-trace-{tag}")
        _ship.dump_all(_FLIGHT_DIR, tag=f"telemetry-{tag}")
