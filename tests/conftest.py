# Test harness: force an 8-device virtual CPU platform BEFORE jax initialises.
#
# Mirrors the reference's fake-backend strategy (SURVEY.md §4): the full
# multi-chip sharding path is exercised on a virtual device mesh so the suite
# runs anywhere; bench.py (not pytest) is what touches the real TPU chip.
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override axon/tpu: tests use the fake mesh
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize imports jax at interpreter start (to register
# the axon TPU plugin), so jax snapshotted JAX_PLATFORMS from the original
# env. Backends are still uninitialized here, so a config update wins.
import jax

jax.config.update("jax_platforms", "cpu")

import pathlib
import sys

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

FIXTURES = pathlib.Path(__file__).resolve().parent / "fixtures"


@pytest.fixture
def fixtures_dir() -> pathlib.Path:
    return FIXTURES
