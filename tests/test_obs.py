import io
import json

from copilot_for_consensus_tpu.obs.errors import CollectingErrorReporter
from copilot_for_consensus_tpu.obs.logging import MemoryLogger, StdoutLogger
from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics


def test_stdout_logger_emits_json_lines():
    buf = io.StringIO()
    log = StdoutLogger(service="embedding", stream=buf)
    log.info("processed", count=3, correlation_id="abc")
    record = json.loads(buf.getvalue())
    assert record["service"] == "embedding"
    assert record["message"] == "processed"
    assert record["count"] == 3
    assert record["correlation_id"] == "abc"


def test_logger_level_filtering_and_bind():
    buf = io.StringIO()
    log = StdoutLogger(level="warning", stream=buf)
    log.info("hidden")
    assert buf.getvalue() == ""
    bound = log.bind(thread_id="t1")
    bound.error("shown")
    assert json.loads(buf.getvalue())["thread_id"] == "t1"


def test_metrics_counters_gauges_histograms():
    m = InMemoryMetrics()
    m.increment("events_processed", labels={"stage": "parsing"})
    m.increment("events_processed", 2, labels={"stage": "parsing"})
    m.gauge("queue_depth", 7)
    m.observe("latency_seconds", 0.3)
    m.observe("latency_seconds", 2.0)
    assert m.counter_value("events_processed", {"stage": "parsing"}) == 3
    assert m.gauge_value("queue_depth") == 7
    assert m.histogram_stats("latency_seconds") == {"sum": 2.3, "count": 2}


def test_prometheus_exposition_format():
    m = InMemoryMetrics(namespace="copilot")
    m.increment("events", labels={"stage": "chunking"})
    m.observe("latency_seconds", 0.05)
    text = m.render_prometheus()
    assert '# TYPE copilot_events counter' in text
    assert 'copilot_events{stage="chunking"} 1.0' in text
    assert 'copilot_latency_seconds_count 1' in text
    assert 'le="+Inf"' in text


def test_prometheus_histogram_exposition_exact():
    """Lock the histogram wire format: cumulative buckets, a +Inf
    bucket equal to _count, then _sum and _count — exactly the series
    histogram_quantile() and the alert pack consume."""
    m = InMemoryMetrics(namespace="copilot")
    m.buckets = (0.1, 1.0)
    m.observe("ttft_seconds", 0.05, labels={"engine": "generation"})
    m.observe("ttft_seconds", 0.5, labels={"engine": "generation"})
    m.observe("ttft_seconds", 99.0, labels={"engine": "generation"})
    text = m.render_prometheus()
    expected = (
        "# TYPE copilot_ttft_seconds histogram\n"
        'copilot_ttft_seconds_bucket{engine="generation",le="0.1"} 1\n'
        'copilot_ttft_seconds_bucket{engine="generation",le="1.0"} 2\n'
        'copilot_ttft_seconds_bucket{engine="generation",le="+Inf"} 3\n'
        'copilot_ttft_seconds_sum{engine="generation"} 99.55\n'
        'copilot_ttft_seconds_count{engine="generation"} 3\n'
    )
    assert expected in text
    assert text.endswith("\n")


def test_prometheus_label_value_escaping():
    """Backslash, quote and newline in label values must escape per the
    text format (backslash first — or its own escapes double up)."""
    m = InMemoryMetrics(namespace="copilot")
    m.increment("events", labels={"path": 'a\\b"c\nd'})
    text = m.render_prometheus()
    assert 'path="a\\\\b\\"c\\nd"' in text


def test_prometheus_nonfinite_values_render_as_prometheus_floats():
    """str(float('inf')) is 'inf', which a Prometheus scraper rejects,
    dropping the WHOLE exposition — non-finite samples must render as
    +Inf/-Inf/NaN."""
    m = InMemoryMetrics(namespace="copilot")
    m.gauge("ratio", float("inf"))
    m.gauge("neg", float("-inf"))
    m.gauge("nan", float("nan"))
    text = m.render_prometheus()
    assert "copilot_ratio +Inf" in text
    assert "copilot_neg -Inf" in text
    assert "copilot_nan NaN" in text
    assert "\ncopilot_ratio inf" not in text


def test_extract_correlation_ids_normalization():
    from copilot_for_consensus_tpu.obs.errors import (
        extract_correlation_ids,
    )

    assert extract_correlation_ids(None) == []
    assert extract_correlation_ids({"correlation_id": "a"}) == ["a"]
    assert extract_correlation_ids(
        {"correlation_ids": ["a", "b", "", "a"]}) == ["a", "b"]
    assert extract_correlation_ids(
        {"correlation_id": "a",
         "correlation_ids": ("b", "a")}) == ["a", "b"]


def test_collecting_error_reporter():
    r = CollectingErrorReporter()
    r.report(ValueError("x"), {"stage": "parse"})
    assert len(r.reports) == 1
    assert r.reports[0][1]["stage"] == "parse"


def test_memory_logger_captures():
    log = MemoryLogger()
    log.warning("hmm", a=1)
    assert log.records == [{"level": "warning", "message": "hmm", "a": 1}]


def test_http_error_reporter_sentry_role():
    """Sentry-role driver: events POST as JSON with fingerprint +
    tags; repeats of the same error site rate-limit; a dead endpoint
    degrades to the fallback without raising."""
    import json as _json
    import time

    from copilot_for_consensus_tpu.obs.errors import (
        CollectingErrorReporter,
        HTTPErrorReporter,
        create_error_reporter,
    )
    from copilot_for_consensus_tpu.services.http import HTTPServer, Router

    received = []
    router = Router()

    @router.post("/events")
    def events(req):
        received.append(_json.loads(req.body))
        return {"ok": True}

    srv = HTTPServer(router)
    srv.start()
    try:
        rep = HTTPErrorReporter(
            f"http://127.0.0.1:{srv.port}/events",
            release="r3", environment="test", min_interval_s=60.0)

        def boom():
            raise RuntimeError("kaboom")

        for _ in range(3):       # same site: only the first ships
            try:
                boom()
            except RuntimeError as exc:
                rep.report(exc, {"service": "parsing", "doc": "d1",
                                 "correlation_ids": ["c-1", "c-2"]})
        deadline = time.monotonic() + 10
        while not received and time.monotonic() < deadline:
            time.sleep(0.05)
        assert len(received) == 1
        ev = received[0]
        assert ev["error_type"] == "RuntimeError"
        assert ev["release"] == "r3" and ev["environment"] == "test"
        assert ev["tags"]["service"] == "parsing"
        # correlation ids ride FIRST-CLASS on the event, not only as a
        # stringified tag — an engine failure names its in-flight
        # requests in a joinable field
        assert ev["correlation_ids"] == ["c-1", "c-2"]
        assert "boom" in ev["stacktrace"]
        assert rep.suppressed == 2
    finally:
        srv.stop()

    # endpoint down: report() must not raise; fallback collects
    fb = CollectingErrorReporter()
    dead = HTTPErrorReporter("http://127.0.0.1:1/events", fallback=fb,
                             min_interval_s=0.0)
    try:
        raise ValueError("lost")
    except ValueError as exc:
        dead.report(exc)
    deadline = time.monotonic() + 10
    while not fb.reports and time.monotonic() < deadline:
        time.sleep(0.05)
    assert fb.reports and "lost" in str(fb.reports[0][0])

    # factory dispatch + config validation
    assert isinstance(create_error_reporter(
        {"driver": "http", "endpoint": "http://x/e"}), HTTPErrorReporter)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="endpoint"):
        create_error_reporter({"driver": "http"})
