import io
import json

from copilot_for_consensus_tpu.obs.errors import CollectingErrorReporter
from copilot_for_consensus_tpu.obs.logging import MemoryLogger, StdoutLogger
from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics


def test_stdout_logger_emits_json_lines():
    buf = io.StringIO()
    log = StdoutLogger(service="embedding", stream=buf)
    log.info("processed", count=3, correlation_id="abc")
    record = json.loads(buf.getvalue())
    assert record["service"] == "embedding"
    assert record["message"] == "processed"
    assert record["count"] == 3
    assert record["correlation_id"] == "abc"


def test_logger_level_filtering_and_bind():
    buf = io.StringIO()
    log = StdoutLogger(level="warning", stream=buf)
    log.info("hidden")
    assert buf.getvalue() == ""
    bound = log.bind(thread_id="t1")
    bound.error("shown")
    assert json.loads(buf.getvalue())["thread_id"] == "t1"


def test_metrics_counters_gauges_histograms():
    m = InMemoryMetrics()
    m.increment("events_processed", labels={"stage": "parsing"})
    m.increment("events_processed", 2, labels={"stage": "parsing"})
    m.gauge("queue_depth", 7)
    m.observe("latency_seconds", 0.3)
    m.observe("latency_seconds", 2.0)
    assert m.counter_value("events_processed", {"stage": "parsing"}) == 3
    assert m.gauge_value("queue_depth") == 7
    assert m.histogram_stats("latency_seconds") == {"sum": 2.3, "count": 2}


def test_prometheus_exposition_format():
    m = InMemoryMetrics(namespace="copilot")
    m.increment("events", labels={"stage": "chunking"})
    m.observe("latency_seconds", 0.05)
    text = m.render_prometheus()
    assert '# TYPE copilot_events counter' in text
    assert 'copilot_events{stage="chunking"} 1.0' in text
    assert 'copilot_latency_seconds_count 1' in text
    assert 'le="+Inf"' in text


def test_collecting_error_reporter():
    r = CollectingErrorReporter()
    r.report(ValueError("x"), {"stage": "parse"})
    assert len(r.reports) == 1
    assert r.reports[0][1]["stage"] == "parse"


def test_memory_logger_captures():
    log = MemoryLogger()
    log.warning("hmm", a=1)
    assert log.records == [{"level": "warning", "message": "hmm", "a": 1}]
