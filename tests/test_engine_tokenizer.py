import pytest

from copilot_for_consensus_tpu.engine.tokenizer import (
    ByteTokenizer,
    HashWordTokenizer,
    create_tokenizer,
)


def test_byte_roundtrip():
    tok = ByteTokenizer(512)
    text = "Hello, IETF wörking group! \n-- sig"
    ids = tok.encode(text, add_bos=True, add_eos=True)
    assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
    assert tok.decode(ids) == text
    assert max(ids) < 512


def test_byte_vocab_guard():
    with pytest.raises(ValueError):
        ByteTokenizer(100)


def test_hash_word_stable_and_bounded():
    tok = HashWordTokenizer(1000)
    a = tok.encode("Consensus on the draft")
    b = tok.encode("consensus ON the DRAFT")
    assert a == b                      # case-normalized
    assert all(3 <= i < 1000 for i in a)


def test_factory_dispatch():
    assert isinstance(create_tokenizer("byte", vocab_size=300),
                      ByteTokenizer)
    assert isinstance(create_tokenizer("hash_word", vocab_size=300),
                      HashWordTokenizer)
    with pytest.raises(ValueError):
        create_tokenizer("nope")
    with pytest.raises(ValueError):
        create_tokenizer("hf")
