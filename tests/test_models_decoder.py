# Decoder correctness: prefill+decode must reproduce the full forward pass.
import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu.models import decoder
from copilot_for_consensus_tpu.models.configs import decoder_config


def _setup(name, dtype=jnp.float32):
    cfg = decoder_config(name)
    params = decoder.init_params(jax.random.PRNGKey(0), cfg, dtype=dtype)
    return cfg, params


@pytest.mark.parametrize("name", ["tiny", "tiny-swa", "tiny-moe"])
def test_forward_shape_and_dtype(name):
    cfg, params = _setup(name)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 24), 0,
                                cfg.vocab_size)
    logits = decoder.forward(params, tokens, cfg, attn_impl="xla")
    assert logits.shape == (2, 24, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


@pytest.mark.parametrize("name", ["tiny", "tiny-swa"])
def test_prefill_decode_matches_forward(name):
    # Teacher-forced decode over the cache must reproduce forward() logits.
    cfg, params = _setup(name)
    b, s_prompt, s_total, s_max = 2, 7, 12, 32
    tokens = jax.random.randint(jax.random.PRNGKey(2), (b, s_total), 0,
                                cfg.vocab_size)
    ref = decoder.forward(params, tokens, cfg, attn_impl="xla")

    cache = decoder.init_cache(cfg, b, s_max, dtype=jnp.float32)
    lengths = jnp.array([s_prompt] * b)
    last, cache = decoder.prefill(params, tokens[:, :s_prompt], lengths,
                                  cfg, cache, attn_impl="xla")
    np.testing.assert_allclose(np.asarray(last),
                               np.asarray(ref[:, s_prompt - 1]),
                               rtol=2e-4, atol=2e-4)
    for i in range(s_prompt, s_total):
        logits, cache = decoder.decode_step(
            params, tokens[:, i], jnp.array([i] * b), cfg, cache)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(ref[:, i]),
                                   rtol=2e-4, atol=2e-4)


def test_prefill_respects_padding():
    # Padded prompt positions must not influence the last-valid logits.
    cfg, params = _setup("tiny")
    tok = jax.random.randint(jax.random.PRNGKey(3), (1, 6), 0,
                             cfg.vocab_size)
    cache = decoder.init_cache(cfg, 1, 16, dtype=jnp.float32)
    last_a, _ = decoder.prefill(params, tok, jnp.array([6]), cfg, cache,
                                attn_impl="xla")
    padded = jnp.pad(tok, ((0, 0), (0, 4)), constant_values=1)
    last_b, _ = decoder.prefill(params, padded, jnp.array([6]), cfg, cache,
                                attn_impl="xla")
    np.testing.assert_allclose(np.asarray(last_a), np.asarray(last_b),
                               rtol=1e-5, atol=1e-5)


def test_moe_gradients_flow_to_all_expert_weights():
    cfg, params = _setup("tiny-moe")
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0,
                                cfg.vocab_size)

    def loss(p):
        logits = decoder.forward(p, tokens, cfg, attn_impl="xla")
        return jnp.mean(jax.nn.logsumexp(logits, axis=-1))

    grads = jax.grad(loss)(params)
    g = grads["layers"]["w_gate"]
    assert g.shape == params["layers"]["w_gate"].shape
    # Router spreads top-2 of 4 experts over 32 tokens: every expert used.
    per_expert = jnp.sum(jnp.abs(g), axis=(0, 2, 3))
    assert bool(jnp.all(per_expert > 0))


def test_param_count_tracks_config():
    cfg, params = _setup("tiny")
    n = decoder.param_count(params)
    assert n > cfg.vocab_size * cfg.d_model  # at least embeddings
    axes = decoder.logical_axes(cfg)
    assert jax.tree.structure(axes, is_leaf=lambda x: isinstance(x, tuple)) \
        == jax.tree.structure(params)
