# HTTP layer: router, health quartet, REST APIs, auth flow — driven over
# real sockets against the single-process pipeline server.
import base64
import json
import pathlib
import urllib.error
import urllib.request

import pytest

from copilot_for_consensus_tpu.services.bootstrap import serve_pipeline

FIXTURE = pathlib.Path(__file__).parent / "fixtures" / "ietf-sample.mbox"


def _call(port, path, method="GET", body=None, token=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json",
                 **({"Authorization": f"Bearer {token}"} if token else {})})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None
    except urllib.error.HTTPError as exc:
        raw = exc.read()
        return exc.code, json.loads(raw) if raw else None


@pytest.fixture(scope="module")
def server():
    srv = serve_pipeline({
        "auth": {
            "signer": {"driver": "hs256", "secret": "test-secret"},
            "bootstrap_admins": {"admin@example.org": ["admin"]},
            "providers": {"mock": {}},
            "allow_insecure_mock": True,
        },
    }).start()
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def tokens(server):
    out = {}
    for email in ("admin@example.org", "reader@example.org"):
        _, login = _call(server.port, "/auth/login?provider=mock")
        status, resp = _call(
            server.port,
            f"/auth/callback?state={login['state']}&code=mock:{email}")
        assert status == 200
        out[email] = resp["access_token"]
    return out


def test_health_quartet_public(server):
    for path in ("/health", "/readyz", "/metrics"):
        status, _ = _call(server.port, path) if path != "/metrics" else (
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics").status, None)
        assert status == 200


def test_head_routes_to_get_handler(server):
    """HEAD on a GET route must return the GET status + headers and
    NO body bytes on the wire (RFC 9110 §9.3.2) — stray body bytes
    corrupt keep-alive streams for strict probes. Raw socket because
    urllib's HTTPResponse never reads a HEAD body, which would make a
    read()==b'' assertion vacuous."""
    import socket

    with socket.create_connection(("127.0.0.1", server.port),
                                  timeout=10) as s:
        s.sendall(b"HEAD /health HTTP/1.1\r\n"
                  b"Host: x\r\nConnection: close\r\n\r\n")
        raw = b""
        while chunk := s.recv(4096):
            raw += chunk
    head, _, after_headers = raw.partition(b"\r\n\r\n")
    assert head.split(b"\r\n")[0].split(b" ")[1] == b"200"
    m = [ln for ln in head.split(b"\r\n")
         if ln.lower().startswith(b"content-length:")]
    assert m and int(m[0].split(b":")[1]) > 0            # honest length
    assert after_headers == b""                          # no body bytes


def test_api_requires_token(server):
    status, body = _call(server.port, "/api/reports")
    assert status == 401


def test_jwks_published(server):
    status, jwks = _call(server.port, "/.well-known/jwks.json")
    assert status == 200
    assert isinstance(jwks["keys"], list)   # empty for HS256, present RS256


def test_role_enforcement(server, tokens):
    reader = tokens["reader@example.org"]
    admin = tokens["admin@example.org"]
    # reader can read reports but not create sources
    assert _call(server.port, "/api/reports", token=reader)[0] == 200
    status, _ = _call(server.port, "/api/sources", method="POST",
                      body={"name": "x"}, token=reader)
    assert status == 403
    status, _ = _call(server.port, "/api/sources", method="POST",
                      body={"name": "gated", "fetcher": "local",
                            "location": str(FIXTURE)}, token=admin)
    assert status == 201


def test_end_to_end_over_http(server, tokens):
    admin = tokens["admin@example.org"]
    status, body = _call(server.port, "/api/sources", method="POST",
                         body={"name": "ietf-http", "fetcher": "local",
                               "location": str(FIXTURE)}, token=admin)
    assert status == 201
    status, body = _call(server.port, "/api/sources/ietf-http/trigger",
                         method="POST", body={}, token=admin)
    assert status == 202 and body["ingested_archives"]
    # in-proc broker pump drains asynchronously; wait for reports
    import time
    deadline = time.time() + 30
    while time.time() < deadline:
        status, body = _call(server.port, "/api/reports",
                             token=tokens["reader@example.org"])
        if status == 200 and body["reports"]:
            break
        time.sleep(0.2)
    assert body["reports"], "pipeline produced no reports over http"
    report = body["reports"][0]
    # drill into a thread and its messages
    status, thread = _call(server.port,
                           f"/api/threads/{report['thread_id']}",
                           token=admin)
    assert status == 200 and thread["message_count"] > 0
    status, msgs = _call(
        server.port, f"/api/threads/{report['thread_id']}/messages",
        token=admin)
    assert status == 200 and msgs["messages"]
    # search
    status, hits = _call(server.port, "/api/reports/search?topic=draft",
                         token=admin)
    assert status == 200


def test_upload_endpoint(server, tokens):
    admin = tokens["admin@example.org"]
    content = base64.b64encode(FIXTURE.read_bytes()).decode()
    status, body = _call(server.port, "/api/upload", method="POST",
                         body={"filename": "up.mbox",
                               "content_b64": content,
                               "source_id": "uploads"}, token=admin)
    # the fixture may already be ingested by another test → duplicate ok
    assert status in (200, 201)


def test_admin_user_management(server, tokens):
    admin = tokens["admin@example.org"]
    reader = tokens["reader@example.org"]
    status, _ = _call(server.port, "/auth/admin/users", token=reader)
    assert status == 403
    status, body = _call(server.port,
                         "/auth/admin/users/new@example.org",
                         method="PUT", body={"roles": ["processor"]},
                         token=admin)
    assert status == 200 and body["roles"] == ["processor"]
    status, body = _call(server.port, "/auth/admin/users", token=admin)
    assert any(u["email"] == "new@example.org" for u in body["users"])


def test_invalid_token_rejected(server):
    status, _ = _call(server.port, "/api/reports", token="garbage.token.x")
    assert status == 401


def test_unknown_route_404(server, tokens):
    status, _ = _call(server.port, "/api/nothing",
                      token=tokens["admin@example.org"])
    assert status == 404


def test_mock_provider_refused_without_optin():
    # require_auth defaults on; a silent mock default (or an un-gated mock
    # driver) would let anyone mint admin tokens via the public callback.
    with pytest.raises(ValueError, match="providers is empty"):
        serve_pipeline({"auth": {
            "signer": {"driver": "hs256", "secret": "s"}}})
    with pytest.raises(ValueError, match="insecure mock"):
        serve_pipeline({"auth": {
            "signer": {"driver": "hs256", "secret": "s"},
            "providers": {"mock": {}}}})


def test_public_path_prefix_does_not_leak(server):
    # /metrics is public; /metricsX must still require a token.
    status, _ = _call(server.port, "/metricsX")
    assert status in (401, 404)
    assert urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/metrics").status == 200


def test_handler_exception_returns_500(server, tokens):
    # trigger an unknown source -> handler raises HTTPError(404) normally;
    # instead force a genuine bug path via a malformed body to /api/sources
    status, body = _call(server.port, "/api/sources", method="POST",
                         body={"name": {"bad": "type"}},
                         token=tokens["admin@example.org"])
    assert status in (400, 500)
    assert body and "error" in body


def test_pending_login_states_pruned(server):
    from copilot_for_consensus_tpu.security.auth import AuthService
    svc = server.auth_service
    before = len(svc._pending)
    svc._pending["expired-state"] = {
        "provider": "mock", "verifier": "v", "nonce": "n", "expires": 0.0}
    _call(server.port, "/auth/login?provider=mock")
    assert "expired-state" not in svc._pending
    assert len(svc._pending) <= before + 1


def test_pending_login_cap():
    from copilot_for_consensus_tpu.security.auth import (
        AuthService, MockProvider, RoleStore)
    from copilot_for_consensus_tpu.security.jwt import (
        JWTManager, create_jwt_signer)
    from copilot_for_consensus_tpu.storage.memory import (
        InMemoryDocumentStore)
    jwt = JWTManager(create_jwt_signer({"driver": "hs256", "secret": "s"}))
    svc = AuthService(jwt, RoleStore(InMemoryDocumentStore()),
                      {"mock": MockProvider()})
    svc.MAX_PENDING = 16
    for _ in range(64):
        svc.initiate_login("mock")
    assert len(svc._pending) <= 16


def test_ops_snapshot(server, tokens):
    """/api/ops: operator snapshot behind auth — collections, queue
    depths, dead letters, per-stage pending (the UI Ops page's data)."""
    status, _ = _call(server.port, "/api/ops")
    assert status == 401                       # guarded
    status, ops = _call(server.port, "/api/ops",
                        token=tokens["reader@example.org"])
    assert status == 200
    assert set(ops) == {"collections", "queues", "dead_letters", "pending"}
    assert "reports" in ops["collections"]
    assert set(ops["pending"]) == {"archives", "messages", "chunks",
                                   "threads"}


def test_discovery_doc_prefers_configured_base_url():
    """ADVICE r2: with auth.external_base_url set, the discovery document
    must advertise it — not client-controlled Host/X-Forwarded-Proto
    headers (discovery-document poisoning via cache/proxy)."""
    srv = serve_pipeline({
        "auth": {
            "signer": {"driver": "hs256", "secret": "s"},
            "providers": {"mock": {}},
            "allow_insecure_mock": True,
            "external_base_url": "https://copilot.example.org/",
        },
    }).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/.well-known/openid-configuration",
            headers={"Host": "evil.example.net",
                     "X-Forwarded-Proto": "gopher"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            doc = json.loads(resp.read())
        base = "https://copilot.example.org"
        assert doc["jwks_uri"] == f"{base}/.well-known/jwks.json"
        assert doc["authorization_endpoint"].startswith(base)
        assert "evil.example.net" not in json.dumps(doc)
    finally:
        srv.stop()


def test_token_refresh_and_logout(server):
    """VERDICT r2 item 4: silent refresh mints a successor; logout
    revokes the token so the API rejects it afterwards. Mints its own
    session: the shared ``tokens`` fixture is module-scoped and a
    logout here would poison later tests."""
    _, login = _call(server.port, "/auth/login?provider=mock")
    _, resp = _call(server.port,
                    f"/auth/callback?state={login['state']}"
                    f"&code=mock:logout-case@example.org")
    old = resp["access_token"]
    status, fresh = _call(server.port, "/auth/refresh", method="POST",
                          token=old)
    assert status == 200 and fresh["access_token"] != old
    # both tokens work until logout
    assert _call(server.port, "/api/reports", token=old)[0] == 200
    new = fresh["access_token"]
    assert _call(server.port, "/api/reports", token=new)[0] == 200
    # logout the OLD token: it dies, the refreshed one survives
    status, body = _call(server.port, "/auth/logout", method="POST",
                         token=old)
    assert status == 200 and body["status"] == "logged_out"
    assert _call(server.port, "/api/reports", token=old)[0] == 401
    assert _call(server.port, "/api/reports", token=new)[0] == 200
    # a revoked token cannot refresh either
    assert _call(server.port, "/auth/refresh", method="POST",
                 token=old)[0] == 401


def test_service_token_mint():
    """Machine clients mint scoped tokens with client credentials
    (reference auth/main.py:494)."""
    srv = serve_pipeline({
        "auth": {
            "signer": {"driver": "hs256", "secret": "s"},
            "providers": {"mock": {}}, "allow_insecure_mock": True,
            "service_accounts": {
                "retry-job": {"secret": "s3cr3t",
                              "roles": ["processor"]},
            },
        },
    }).start()
    try:
        status, tok = _call(srv.port, "/auth/token", method="POST",
                            body={"client_id": "retry-job",
                                  "client_secret": "s3cr3t"})
        assert status == 200 and tok["roles"] == ["processor"]
        # the minted token passes middleware + role checks
        status, _ = _call(srv.port, "/api/sources", token=tok["access_token"])
        assert status == 200
        # wrong secret is rejected
        status, _ = _call(srv.port, "/auth/token", method="POST",
                          body={"client_id": "retry-job",
                                "client_secret": "nope"})
        assert status == 401
    finally:
        srv.stop()


def test_pending_assignment_workflow(server, tokens):
    """Request → admin list → approve: the requester gains the role
    (reference auth/main.py:787,1074); deny leaves roles unchanged."""
    reader = tokens["reader@example.org"]
    admin = tokens["admin@example.org"]
    status, req1 = _call(server.port, "/auth/roles/request",
                         method="POST", token=reader,
                         body={"roles": ["processor"], "note": "bulk"})
    assert status == 200 and req1["status"] == "pending"
    # non-admin cannot see or resolve pending assignments
    assert _call(server.port, "/auth/admin/pending",
                 token=reader)[0] == 403
    status, pend = _call(server.port, "/auth/admin/pending", token=admin)
    assert status == 200
    assert any(p["_id"] == req1["_id"] for p in pend["pending"])
    status, resolved = _call(
        server.port, f"/auth/admin/pending/{req1['_id']}",
        method="POST", token=admin, body={"action": "approve"})
    assert status == 200 and resolved["status"] == "approved"
    # the approved role is live on the next refresh
    status, fresh = _call(server.port, "/auth/refresh", method="POST",
                          token=reader)
    assert "processor" in fresh["roles"]
    # an approved assignment cannot be resolved twice
    status, _ = _call(server.port, f"/auth/admin/pending/{req1['_id']}",
                      method="POST", token=admin,
                      body={"action": "deny"})
    assert status == 404
    # deny path: unknown role request is rejected outright
    status, _ = _call(server.port, "/auth/roles/request", method="POST",
                      token=reader, body={"roles": ["superuser"]})
    assert status == 400


def test_percent_encoded_path_params_decode(server, tokens):
    """UI clients encodeURIComponent path ids ('@', ':'); the router
    must decode them before handlers use them as store keys — found by
    review: admin approve/deny always 404'd on encoded assignment ids."""
    admin = tokens["admin@example.org"]
    status, _ = _call(server.port,
                      "/auth/admin/users/enc%40example.org",
                      method="PUT", token=admin,
                      body={"roles": ["reader"]})
    assert status == 200
    status, users = _call(server.port, "/auth/admin/users", token=admin)
    assert any(u["email"] == "enc@example.org" for u in users["users"])
    # pending-assignment ids contain '@' and ':' — resolve via the
    # encoded form exactly as ui/app.js sends it
    _, login = _call(server.port, "/auth/login?provider=mock")
    _, who = _call(server.port,
                   f"/auth/callback?state={login['state']}"
                   f"&code=mock:enc2@example.org")
    status, reqd = _call(server.port, "/auth/roles/request",
                         method="POST", token=who["access_token"],
                         body={"roles": ["processor"]})
    assert status == 200
    import urllib.parse as up
    status, out = _call(
        server.port,
        "/auth/admin/pending/" + up.quote(reqd["_id"], safe=""),
        method="POST", token=admin, body={"action": "approve"})
    assert status == 200 and out["status"] == "approved"


def test_hostile_asset_names_404_not_500(server):
    """Regression for a REAL api-fuzzer finding: once the router began
    percent-decoding path params, /ui/%00 put a NUL byte into a pathlib
    path and 500'd. Hostile asset names must 404."""
    for bad in ("%00", "..%2f..%2fetc%2fpasswd", "%0a", "a%00b.js"):
        status, _ = _call(server.port, f"/ui/{bad}")
        assert status == 404, bad


def test_threads_filtering_and_sorting(server, tokens):
    """DiscussionsList-parity query surface (r5): source/message/
    participant filters + sort compose server-side so pagination stays
    correct under filtering."""
    import time

    tok = tokens["admin@example.org"]
    # make sure the fixture corpus is ingested (duplicate is fine) and
    # the async pump has parsed it into thread docs
    raw = FIXTURE.read_bytes()
    _call(server.port, "/api/upload", method="POST", token=tok,
          body={"filename": "threads-filter.mbox",
                "content_b64": base64.b64encode(raw).decode(),
                "source_id": "threads-filter"})
    deadline = time.time() + 30
    all_t = {"threads": []}
    while time.time() < deadline and not all_t["threads"]:
        status, all_t = _call(server.port, "/api/threads?limit=50",
                              token=tok)
        assert status == 200
        time.sleep(0.2)
    assert all_t["threads"], "pipeline produced no threads"

    status, out = _call(server.port,
                        "/api/threads?min_messages=2", token=tok)
    assert status == 200
    assert all(t["message_count"] >= 2 for t in out["threads"])
    n_ge2 = sum(1 for t in all_t["threads"] if t["message_count"] >= 2)
    assert len(out["threads"]) == min(50, n_ge2)

    status, out = _call(server.port,
                        "/api/threads?max_messages=1", token=tok)
    assert status == 200
    assert all(t["message_count"] <= 1 for t in out["threads"])

    status, out = _call(
        server.port,
        "/api/threads?sort_by=subject&sort_order=asc", token=tok)
    subjects = [t.get("subject") or "" for t in out["threads"]]
    assert subjects == sorted(subjects)

    status, out = _call(server.port,
                        "/api/threads?max_participants=2", token=tok)
    assert all(len(t.get("participants") or []) <= 2
               for t in out["threads"])

    # filters compose with pagination: page size honored after filter
    status, out = _call(server.port,
                        "/api/threads?min_messages=1&limit=1", token=tok)
    assert len(out["threads"]) <= 1

    # a non-integer range value is a 400, not a 500
    status, _ = _call(server.port,
                      "/api/threads?min_messages=bogus", token=tok)
    assert status == 400


def test_pending_resolution_rejects_non_object_body(server, tokens):
    """Valid JSON that is not an object (a bare string) must 400, not
    500 via AttributeError — r5 deep-fuzz find on
    /auth/admin/pending/{id}."""
    admin = tokens["admin@example.org"]
    status, body = _call(server.port, "/auth/admin/pending/x",
                         method="POST", body="approve", token=admin)
    assert status == 400 and "object" in body["error"]
