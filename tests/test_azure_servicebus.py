# Azure Service Bus driver against an in-process wire-contract mock:
# SAS auth, topic/subscription/rule provisioning (ATOM), SQL-filter
# fanout, peek-lock settle (complete/abandon/renew), DeliveryCount
# accounting, MaxDeliveryCount dead-lettering, and lock expiry — the
# same protocol surface the real broker (or its emulator) exposes, so
# the driver is exercised over genuine HTTP without egress.
import base64
import hashlib
import hmac
import json
import re
import threading
import time
import urllib.parse
import uuid
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from copilot_for_consensus_tpu.bus.azure_servicebus import (
    AzureServiceBusPublisher,
    AzureServiceBusSubscriber,
    entity_name,
    sas_token,
)
from copilot_for_consensus_tpu.bus.base import PublishError

KEY_NAME = "RootManageSharedAccessKey"
KEY = "mock-sb-key-secret"


class _Sub:
    def __init__(self, lock_duration_s, max_delivery):
        self.rules = {"$Default": "1=1"}
        self.queue = deque()                  # ready messages
        self.locked = {}                      # token -> (msg, until)
        self.dlq = deque()
        self.lock_duration_s = lock_duration_s
        self.max_delivery = max_delivery


class _MockServiceBus:
    """State + wire behavior of one namespace."""

    def __init__(self):
        self.topics = {}                      # topic -> {sub: _Sub}
        self.lock = threading.Lock()
        self.stats = {"bad_auth": 0, "sent": 0, "delivered": 0}

    # -- auth ----------------------------------------------------------

    def check_auth(self, header, endpoint):
        m = re.match(
            r"SharedAccessSignature sr=(?P<sr>[^&]+)&sig=(?P<sig>[^&]+)"
            r"&se=(?P<se>\d+)&skn=(?P<skn>.+)", header or "")
        if not m:
            return False
        se = int(m.group("se"))
        if se < time.time():
            return False
        to_sign = f"{m.group('sr')}\n{se}".encode()
        want = base64.b64encode(
            hmac.new(KEY.encode(), to_sign, hashlib.sha256).digest())
        got = urllib.parse.unquote_plus(m.group("sig")).encode()
        return hmac.compare_digest(want, got) and \
            urllib.parse.unquote_plus(m.group("sr")) == endpoint.lower()

    # -- broker mechanics ----------------------------------------------

    def _expire_locks(self, sub):
        now = time.monotonic()
        for token in [t for t, (_, until) in sub.locked.items()
                      if until < now]:
            msg, _ = sub.locked.pop(token)
            sub.queue.appendleft(msg)         # redeliver-first

    def fanout(self, topic, body, props):
        with self.lock:
            self.stats["sent"] += 1
            for sub in self.topics[topic].values():
                for expr in sub.rules.values():
                    if self._rule_matches(expr, props):
                        sub.queue.append({"body": body,
                                          "props": dict(props)})
                        break

    @staticmethod
    def _rule_matches(expr, props):
        if expr.strip() == "1=1":
            return True
        m = re.match(r"(\w+) = '([^']*)'$", expr.strip())
        assert m, f"mock cannot evaluate rule {expr!r}"
        return str(props.get(m.group(1), "")) == m.group(2)

    def receive(self, topic, subname, dlq):
        """Peek-lock pop honoring DeliveryCount/MaxDeliveryCount."""
        with self.lock:
            sub = self.topics[topic][subname]
            self._expire_locks(sub)
            queue = sub.dlq if dlq else sub.queue
            while queue:
                msg = queue.popleft()
                msg["props"]["DeliveryCount"] = \
                    msg["props"].get("DeliveryCount", 0) + 1
                if not dlq and \
                        msg["props"]["DeliveryCount"] > sub.max_delivery:
                    msg["props"]["DeadLetterReason"] = \
                        "MaxDeliveryCountExceeded"
                    sub.dlq.append(msg)
                    continue
                token = str(uuid.uuid4())
                until = time.monotonic() + sub.lock_duration_s
                sub.locked[token] = (msg, until)
                self.stats["delivered"] += 1
                return msg, token
            return None, None

    def settle(self, topic, subname, token, action):
        """complete/abandon/renew; returns HTTP status."""
        with self.lock:
            sub = self.topics[topic][subname]
            self._expire_locks(sub)
            if token not in sub.locked:
                return 404
            msg, _ = sub.locked.pop(token)
            if action == "complete":
                pass
            elif action == "abandon":
                sub.queue.appendleft(msg)
            elif action == "renew":
                sub.locked[token] = (
                    msg, time.monotonic() + sub.lock_duration_s)
            return 200


def _make_handler(state, endpoint_holder):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _reply(self, status, body=b"", headers=None):
            self.send_response(status)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _authorized(self):
            ok = state.check_auth(self.headers.get("Authorization"),
                                  endpoint_holder[0])
            if not ok:
                state.stats["bad_auth"] += 1
                self._reply(401)
            return ok

        def _body(self):
            n = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(n) if n else b""

        # entity management + message ops share the URL space; route by
        # decoded path segments
        def _route(self, method):
            if not self._authorized():
                return
            parsed = urllib.parse.urlparse(self.path)
            parts = [urllib.parse.unquote(p)
                     for p in parsed.path.strip("/").split("/")]
            body = self._body()
            # POST/DELETE {topic}/[subscriptions/{sub}/[$DeadLetterQueue/]]messages/...
            if "messages" in parts:
                return self._message_op(method, parts, parsed, body)
            return self._entity_op(method, parts, body)

        def _entity_op(self, method, parts, body):
            with state.lock:
                if method == "PUT" and len(parts) == 1:
                    status = 409 if parts[0] in state.topics else 201
                    state.topics.setdefault(parts[0], {})
                    return self._reply(status)
                if len(parts) >= 3 and parts[1] == "subscriptions":
                    topic, sub = parts[0], parts[2]
                    if topic not in state.topics:
                        return self._reply(404)
                    subs = state.topics[topic]
                    if method == "PUT" and len(parts) == 3:
                        if sub in subs:
                            return self._reply(409)
                        lock_s = int(re.search(
                            rb"<LockDuration>PT(\d+)S</LockDuration>",
                            body).group(1))
                        max_d = int(re.search(
                            rb"<MaxDeliveryCount>(\d+)</MaxDeliveryCount>",
                            body).group(1))
                        subs[sub] = _Sub(lock_s, max_d)
                        return self._reply(201)
                    if len(parts) == 5 and parts[3] == "rules":
                        if sub not in subs:
                            return self._reply(404)
                        rules = subs[sub].rules
                        if method == "PUT":
                            if parts[4] in rules:
                                return self._reply(409)
                            expr = re.search(
                                rb"<SqlExpression>(.*?)</SqlExpression>",
                                body, re.S).group(1).decode()
                            rules[parts[4]] = expr
                            return self._reply(201)
                        if method == "DELETE":
                            return self._reply(
                                200 if rules.pop(parts[4], None)
                                else 404)
            return self._reply(400)

        def _message_op(self, method, parts, parsed, body):
            topic = parts[0]
            if topic not in state.topics:
                return self._reply(404)
            # send: POST {topic}/messages
            if parts[1:] == ["messages"]:
                if method != "POST":
                    return self._reply(405)
                props = json.loads(
                    self.headers.get("BrokerProperties", "{}"))
                # custom properties arrive as JSON-quoted headers
                for name in ("routing_key", "event_type"):
                    if self.headers.get(name):
                        props[name] = json.loads(self.headers[name])
                props.setdefault("MessageId", str(uuid.uuid4()))
                state.fanout(topic, body, props)
                return self._reply(201)
            assert parts[1] == "subscriptions"
            sub = parts[2]
            rest = parts[3:]
            dlq = rest and rest[0] == "$DeadLetterQueue"
            if dlq:
                rest = rest[1:]
            if sub not in state.topics[topic]:
                return self._reply(404)
            sub_path = (f"/{topic}/subscriptions/"
                        f"{urllib.parse.quote(sub)}"
                        + ("/%24DeadLetterQueue" if dlq else ""))
            # receive: POST .../messages/head?timeout=N — a nonzero
            # timeout long-polls server-side like real Service Bus
            if rest == ["messages", "head"]:
                if method != "POST":
                    return self._reply(405)
                q = urllib.parse.parse_qs(parsed.query)
                deadline = time.monotonic() + min(
                    int((q.get("timeout") or ["0"])[0]), 5)
                msg, token = state.receive(topic, sub, dlq)
                while msg is None and time.monotonic() < deadline:
                    time.sleep(0.02)
                    msg, token = state.receive(topic, sub, dlq)
                if msg is None:
                    return self._reply(204)
                bp = dict(msg["props"])
                bp["LockToken"] = token
                loc = (f"http://{endpoint_holder[1]}{sub_path}/messages/"
                       f"{urllib.parse.quote(str(bp['MessageId']))}/"
                       f"{token}")
                reply_headers = {"Location": loc}
                # real Service Bus returns custom properties as their
                # own JSON-quoted headers, NOT inside BrokerProperties
                for name in ("routing_key", "event_type"):
                    if name in bp:
                        reply_headers[name] = json.dumps(bp.pop(name))
                reply_headers["BrokerProperties"] = json.dumps(bp)
                return self._reply(201, msg["body"], reply_headers)
            # settle: DELETE/PUT/POST .../messages/{mid}/{token}
            if len(rest) == 3 and rest[0] == "messages":
                token = rest[2]
                action = {"DELETE": "complete", "PUT": "abandon",
                          "POST": "renew"}.get(method)
                if action is None:
                    return self._reply(405)
                return self._reply(state.settle(topic, sub, token,
                                                action))
            return self._reply(400)

        def do_GET(self):
            self._route("GET")

        def do_POST(self):
            self._route("POST")

        def do_PUT(self):
            self._route("PUT")

        def do_DELETE(self):
            self._route("DELETE")

    return Handler


@pytest.fixture()
def mock_sb():
    state = _MockServiceBus()
    holder = ["", ""]
    server = ThreadingHTTPServer(
        ("127.0.0.1", 0), _make_handler(state, holder))
    host = f"127.0.0.1:{server.server_address[1]}"
    holder[0] = f"http://{host}"
    holder[1] = host
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        yield holder[0], state
    finally:
        server.shutdown()
        server.server_close()


def _cfg(endpoint, **kw):
    return {"endpoint": endpoint, "key_name": KEY_NAME, "key": KEY,
            "retry_attempts": 0, **kw}


def _envelope(n=0, rk="chunk.created"):
    return {"event_type": rk.replace(".", "_"), "event_id": f"e{n}",
            "payload": {"n": n}}


def test_publish_subscribe_sql_filter_fanout(mock_sb):
    """Two routing keys, one topic: each subscription's SQL rule admits
    only its own key (the server-side filtering the reference
    provisions as the EventTypeFilter rule)."""
    endpoint, state = mock_sb
    pub = AzureServiceBusPublisher(_cfg(endpoint))
    got_a, got_b = [], []
    sub_a = AzureServiceBusSubscriber(_cfg(endpoint, group="svc-a"))
    sub_a.subscribe(["chunk.created"], got_a.append)
    sub_b = AzureServiceBusSubscriber(_cfg(endpoint, group="svc-b"))
    sub_b.subscribe(["thread.parsed"], got_b.append)
    for i in range(3):
        pub.publish_envelope(_envelope(i, "chunk.created"),
                             "chunk.created")
    pub.publish_envelope(_envelope(9, "thread.parsed"), "thread.parsed")
    assert sub_a.drain() == 3 and sub_b.drain() == 1
    assert [e["event_id"] for e in got_a] == ["e0", "e1", "e2"]
    assert [e["event_id"] for e in got_b] == ["e9"]
    assert state.stats["bad_auth"] == 0


def test_groups_fan_out_and_competing_consumers_share(mock_sb):
    """Distinct groups each see every message (separate subscriptions);
    same group shares one subscription and splits the work."""
    endpoint, _ = mock_sb
    pub = AzureServiceBusPublisher(_cfg(endpoint))
    seen = {"g1": [], "g2": [], "g2b": []}
    s1 = AzureServiceBusSubscriber(_cfg(endpoint, group="g1"))
    s1.subscribe(["x.y"], seen["g1"].append)
    s2 = AzureServiceBusSubscriber(_cfg(endpoint, group="g2"))
    s2.subscribe(["x.y"], seen["g2"].append)
    s2b = AzureServiceBusSubscriber(_cfg(endpoint, group="g2"))
    s2b.subscribe(["x.y"], seen["g2b"].append)
    for i in range(4):
        pub.publish_envelope(_envelope(i, "x.y"), "x.y")
    assert s1.drain() == 4
    # competing: alternate drains one message at a time
    while s2.drain(1) + s2b.drain(1):
        pass
    assert len(seen["g1"]) == 4
    assert len(seen["g2"]) + len(seen["g2b"]) == 4


def test_redelivery_then_success_and_delivery_count(mock_sb):
    endpoint, _ = mock_sb
    pub = AzureServiceBusPublisher(_cfg(endpoint))
    attempts = []

    def flaky(env):
        attempts.append(env["event_id"])
        if len(attempts) < 3:
            raise RuntimeError("transient handler failure")

    sub = AzureServiceBusSubscriber(
        _cfg(endpoint, group="g", max_redeliveries=5))
    sub.subscribe(["a.b"], flaky)
    pub.publish_envelope(_envelope(1, "a.b"), "a.b")
    assert sub.drain() == 3          # two failures + final success
    assert attempts == ["e1", "e1", "e1"]
    assert sub.dead_letters("a.b") == []


def test_dead_letter_after_max_redeliveries(mock_sb):
    endpoint, _ = mock_sb
    pub = AzureServiceBusPublisher(_cfg(endpoint))
    attempts = []

    def poison(env):
        attempts.append(1)
        raise RuntimeError("always fails")

    sub = AzureServiceBusSubscriber(
        _cfg(endpoint, group="g", max_redeliveries=2))
    sub.subscribe(["a.b"], poison)
    pub.publish_envelope(_envelope(7, "a.b"), "a.b")
    sub.drain()
    assert len(attempts) == 3        # 1 first + 2 redeliveries
    dead = sub.dead_letters("a.b")
    assert [e["event_id"] for e in dead] == ["e7"]
    assert sub.dead_letters("a.b") == []   # drained
    assert sub.drain() == 0


def test_lock_expiry_redelivers_without_renewal(mock_sb):
    """A handler slower than the lock with auto_renew off loses the
    message to redelivery; the late complete must not crash the loop."""
    endpoint, _ = mock_sb
    pub = AzureServiceBusPublisher(_cfg(endpoint))
    calls = []

    def slow(env):
        calls.append(env["event_id"])
        if len(calls) == 1:
            time.sleep(1.4)          # past the 1s lock

    sub = AzureServiceBusSubscriber(
        _cfg(endpoint, group="g", lock_duration_s=1, auto_renew=False,
             max_redeliveries=3))
    sub.subscribe(["a.b"], slow)
    pub.publish_envelope(_envelope(1, "a.b"), "a.b")
    assert sub.drain() == 2          # expired attempt + redelivery
    assert calls == ["e1", "e1"]
    assert sub.dead_letters("a.b") == []


def test_lock_renewal_keeps_slow_handler_alive(mock_sb):
    endpoint, _ = mock_sb
    pub = AzureServiceBusPublisher(_cfg(endpoint))
    calls = []

    def slow(env):
        calls.append(env["event_id"])
        time.sleep(1.4)              # renewer must fire at ~0.5s

    sub = AzureServiceBusSubscriber(
        _cfg(endpoint, group="g", lock_duration_s=1, auto_renew=True))
    sub.subscribe(["a.b"], slow)
    pub.publish_envelope(_envelope(3, "a.b"), "a.b")
    assert sub.drain() == 1          # exactly one delivery
    assert calls == ["e3"]
    assert sub.drain() == 0


def test_subscribe_repairs_half_provisioned_subscription(mock_sb):
    """A crash between subscription-create and rule-create leaves a
    match-all $Default rule; the next subscribe() must repair it (or
    this group would receive EVERY routing key forever)."""
    endpoint, state = mock_sb
    sub = AzureServiceBusSubscriber(_cfg(endpoint, group="g"))
    name = entity_name("a.b", "g")
    # simulate the half-provisioned state: entity exists, rules don't
    sub._t.ensure_topic(sub.topic)
    sub._t.request(
        "PUT", f"/{sub.topic}/subscriptions/{name}",
        body=(b'<entry><content><SubscriptionDescription>'
              b"<LockDuration>PT60S</LockDuration>"
              b"<MaxDeliveryCount>4</MaxDeliveryCount>"
              b"</SubscriptionDescription></content></entry>"),
        content_type="application/atom+xml", ok=(201,))
    got = []
    sub.subscribe(["a.b"], got.append)
    rules = state.topics[sub.topic][name].rules
    assert "$Default" not in rules
    assert rules.get("RoutingKeyFilter") == "routing_key = 'a.b'"
    pub = AzureServiceBusPublisher(_cfg(endpoint))
    pub.publish_envelope(_envelope(1, "other.key"), "other.key")
    pub.publish_envelope(_envelope(2, "a.b"), "a.b")
    assert sub.drain() == 1
    assert [e["event_id"] for e in got] == ["e2"]


def test_bad_key_rejected(mock_sb):
    endpoint, state = mock_sb
    pub = AzureServiceBusPublisher(
        {"endpoint": endpoint, "key_name": KEY_NAME,
         "key": "wrong-key", "retry_attempts": 0})
    with pytest.raises(PublishError, match="401"):
        pub.publish_envelope(_envelope(), "a.b")
    assert state.stats["bad_auth"] >= 1


def test_expired_sas_rejected(mock_sb):
    endpoint, state = mock_sb
    tok = sas_token(endpoint, KEY_NAME, KEY, ttl_s=10,
                    now=time.time() - 100)
    assert not state.check_auth(tok, endpoint)
    assert state.check_auth(sas_token(endpoint, KEY_NAME, KEY),
                            endpoint)


def test_malformed_body_is_completed_not_looped(mock_sb):
    """A non-JSON message can never be handled: the subscriber must
    complete (discard) it so it doesn't wedge the subscription."""
    endpoint, _ = mock_sb
    calls = []
    sub = AzureServiceBusSubscriber(_cfg(endpoint, group="g"))
    sub.subscribe(["a.b"], calls.append)
    # raw send bypassing the publisher's JSON serialization
    sub._t.request("POST", f"/{sub.topic}/messages",
                   body=b"\xff\xfenot json",
                   headers={"routing_key": json.dumps("a.b"),
                            "BrokerProperties": "{}"}, ok=(201,))
    assert sub.drain() == 1
    assert calls == []
    assert sub.drain() == 0


def test_start_consuming_blocks_until_stop(mock_sb):
    endpoint, _ = mock_sb
    pub = AzureServiceBusPublisher(_cfg(endpoint))
    got = []
    sub = AzureServiceBusSubscriber(_cfg(endpoint, group="g"))
    sub.subscribe(["a.b"], got.append)
    t = threading.Thread(target=sub.start_consuming, daemon=True)
    t.start()
    pub.publish_envelope(_envelope(5, "a.b"), "a.b")
    deadline = time.time() + 5
    while not got and time.time() < deadline:
        time.sleep(0.02)
    assert [e["event_id"] for e in got] == ["e5"]
    sub.stop()
    t.join(timeout=5)
    assert not t.is_alive()


def test_unreachable_namespace_surfaces_publish_error():
    pub = AzureServiceBusPublisher(
        {"endpoint": "http://127.0.0.1:1", "key": KEY,
         "retry_attempts": 0, "timeout_s": 0.5})
    with pytest.raises(PublishError, match="unreachable"):
        pub.publish_envelope(_envelope(), "a.b")


def test_config_validation_and_factory():
    from copilot_for_consensus_tpu.bus.factory import (
        create_publisher,
        create_subscriber,
    )

    with pytest.raises(ValueError, match="namespace or endpoint"):
        AzureServiceBusPublisher({"key": "k"})
    with pytest.raises(ValueError, match="needs key"):
        AzureServiceBusSubscriber({"namespace": "ns"})
    pub = create_publisher({"driver": "azure_servicebus",
                            "namespace": "ns", "key": "k"})
    sub = create_subscriber({"driver": "azure_servicebus",
                             "namespace": "ns", "key": "k"})
    assert pub.inner._t.endpoint == "https://ns.servicebus.windows.net"
    assert sub.inner._t.endpoint == "https://ns.servicebus.windows.net"


def test_entity_name_injective_sanitized_and_clamped():
    n = entity_name("chunk.created", "svc")
    assert n.startswith("svc-chunk.created-") and len(n) <= 50
    assert re.fullmatch(r"[A-Za-z0-9._-]+", entity_name("weird/key*",
                                                        "g"))
    long = entity_name("a" * 80, "group")
    assert len(long) <= 50
    assert long == entity_name("a" * 80, "group")       # stable
    assert long != entity_name("a" * 81, "group")       # distinct
    # sanitization/joining must not collide distinct (group, rk) pairs
    assert entity_name("a-b.c", "svc") != entity_name("b.c", "svc-a")
    assert entity_name("weird/key", "g") != entity_name("weird*key",
                                                        "g")


def test_unsafe_routing_key_rejected_at_subscribe(mock_sb):
    """A routing key outside [A-Za-z0-9._-] would be interpolated into
    the SqlFilter expression and the ATOM XML rule body; subscribe must
    refuse it loudly instead of building a broken/altered rule."""
    endpoint, _ = mock_sb
    sub = AzureServiceBusSubscriber(_cfg(endpoint, group="g"))
    for bad in ("a'b", "a<b>", "k&amp", "x y", "q\"r"):
        with pytest.raises(ValueError, match="routing key"):
            sub.subscribe([bad], lambda e: None)
    # a bad key mid-batch must not leave earlier keys half-registered
    with pytest.raises(ValueError, match="routing key"):
        sub.subscribe(["good.key", "bad'key"], lambda e: None)
    assert not sub._routes and not sub._subs


def test_default_rule_window_message_not_misrouted(mock_sb):
    """A message that slipped in through the match-all $Default rule
    (create-subscription -> delete-$Default window) carries a STAMPED
    routing key that does not match the subscription's; _dispatch must
    drop it (complete) rather than hand it to the wrong callback."""
    endpoint, _ = mock_sb
    pub = AzureServiceBusPublisher(_cfg(endpoint))
    got = []
    sub = AzureServiceBusSubscriber(_cfg(endpoint, group="g"))
    # simulate the half-provisioned window: subscription exists with
    # ONLY the match-all $Default rule (crash before rule replacement)
    name = entity_name("summary.complete", "g")
    sub._t.ensure_topic(sub.topic)
    sub._t.request(
        "PUT", f"/{sub.topic}/subscriptions/{name}",
        body=(b'<entry xmlns="http://www.w3.org/2005/Atom">'
              b'<content type="application/xml"><SubscriptionDescription'
              b' xmlns="http://schemas.microsoft.com/netservices/2010/10/'
              b'servicebus/connect"><LockDuration>PT60S</LockDuration>'
              b"<MaxDeliveryCount>4</MaxDeliveryCount>"
              b"</SubscriptionDescription></content></entry>"),
        content_type="application/atom+xml", ok=(201, 409))
    sub._routes["summary.complete"] = got.append
    sub._subs["summary.complete"] = name
    # a foreign-key message admitted by $Default during the window...
    pub.publish_envelope({"event_type": "ArchiveIngested",
                          "event_id": "stray", "payload": {}},
                         "archive.ingested")
    # ...and a legitimate one
    pub.publish_envelope({"event_type": "SummaryComplete",
                          "event_id": "ok1", "payload": {}},
                         "summary.complete")
    assert sub.drain() == 2          # both settled (one dropped)
    assert [e["event_id"] for e in got] == ["ok1"]


def test_default_rule_window_message_dispatches_locally_when_routed(
        mock_sb):
    """When the stamped key has a LOCAL route, the $Default-window guard
    reroutes the message to that callback instead of dropping it — and
    a drop (no local route) is observable: log line, instance counter,
    bus_misroute_dropped metric."""
    from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics

    endpoint, _ = mock_sb
    pub = AzureServiceBusPublisher(_cfg(endpoint))
    got_summary, got_archive = [], []
    sub = AzureServiceBusSubscriber(_cfg(endpoint, group="g2"))
    sub.metrics = InMemoryMetrics()
    # half-provisioned window again: only the match-all $Default rule
    name = entity_name("summary.complete", "g2")
    sub._t.ensure_topic(sub.topic)
    sub._t.request(
        "PUT", f"/{sub.topic}/subscriptions/{name}",
        body=(b'<entry xmlns="http://www.w3.org/2005/Atom">'
              b'<content type="application/xml"><SubscriptionDescription'
              b' xmlns="http://schemas.microsoft.com/netservices/2010/10/'
              b'servicebus/connect"><LockDuration>PT60S</LockDuration>'
              b"<MaxDeliveryCount>4</MaxDeliveryCount>"
              b"</SubscriptionDescription></content></entry>"),
        content_type="application/atom+xml", ok=(201, 409))
    sub._routes["summary.complete"] = got_summary.append
    sub._subs["summary.complete"] = name
    # this consumer ALSO consumes archive.ingested → reroute, not drop
    sub._routes["archive.ingested"] = got_archive.append
    pub.publish_envelope({"event_type": "ArchiveIngested",
                          "event_id": "rerouted", "payload": {}},
                         "archive.ingested")
    # unroutable stamped key → dropped + counted
    pub.publish_envelope({"event_type": "SummaryComplete",
                          "event_id": "stray", "payload": {}},
                         "chunking.complete")
    assert sub.drain() == 2
    assert [e["event_id"] for e in got_archive] == ["rerouted"]
    assert not got_summary
    assert sub.misroute_dropped == 1
    assert sub.metrics.counter_value(
        "bus_misroute_dropped",
        {"stamped": "chunking.complete",
         "subscription": "summary.complete"}) == 1


def test_override_routing_key_publish_still_delivered(mock_sb):
    """publish_envelope(env, routing_key=override) is a supported bus
    shape: the misroute guard compares the STAMPED key (which equals
    the override), so override publishes must reach their subscription
    even though the event type's canonical key differs."""
    endpoint, _ = mock_sb
    pub = AzureServiceBusPublisher(_cfg(endpoint))
    got = []
    sub = AzureServiceBusSubscriber(_cfg(endpoint, group="audit"))
    sub.subscribe(["audit.summaries"], got.append)
    pub.publish_envelope({"event_type": "SummaryComplete",
                          "event_id": "ov1", "payload": {}},
                         "audit.summaries")
    assert sub.drain() == 1
    assert [e["event_id"] for e in got] == ["ov1"]
