# OpenAI-compatible drivers against an in-process mock server: the
# interoperability path the reference serves via llm_openai /
# llm_azure_openai_gpt / OpenAIEmbeddingProvider.
import json

import pytest

from copilot_for_consensus_tpu.embedding.base import EmbeddingError
from copilot_for_consensus_tpu.embedding.factory import (
    create_embedding_provider,
)
from copilot_for_consensus_tpu.services.http import HTTPServer, Router
from copilot_for_consensus_tpu.summarization.base import (
    RateLimitError,
    SummarizationError,
    ThreadContext,
)
from copilot_for_consensus_tpu.summarization.factory import create_summarizer


@pytest.fixture()
def mock_openai():
    """Minimal OpenAI-compatible endpoint: records requests, scriptable
    failures via state dict."""
    router = Router()
    state = {"requests": [], "fail_next": None}

    @router.post("/v1/chat/completions")
    def chat(req):
        body = req.json()
        state["requests"].append(("chat", dict(req.headers), body))
        if state["fail_next"] == 429:
            state["fail_next"] = None
            from copilot_for_consensus_tpu.services.http import (
                HTTPError,
                Response,
            )
            return Response({"error": "slow down"}, status=429,
                            headers={"Retry-After": "7"})
        user = body["messages"][-1]["content"]
        return {
            "model": body["model"],
            "choices": [{"message": {
                "role": "assistant",
                "content": f"SUMMARY[{body['model']}] of: {user[:40]}"}}],
            "usage": {"prompt_tokens": 10, "completion_tokens": 5},
        }

    @router.post("/v1/embeddings")
    def embeddings(req):
        body = req.json()
        state["requests"].append(("emb", dict(req.headers), body))
        texts = body["input"]
        return {"data": [
            {"index": i, "embedding": [float(len(t)), float(i), 1.0]}
            for i, t in enumerate(texts)
        ][::-1]}     # reversed: clients must re-sort by index

    srv = HTTPServer(router)
    srv.start()
    yield srv, state
    srv.stop()


def _thread():
    return ThreadContext(
        thread_id="t1", subject="QUIC drafts", participants=["a@x"],
        message_count=3,
        chunks=[{"chunk_id": "c1", "message_doc_id": "m1",
                 "text": "we should adopt the draft", "score": 0.9}])


def test_openai_summarizer_end_to_end(mock_openai):
    srv, state = mock_openai
    summ = create_summarizer({
        "driver": "openai",
        "base_url": f"http://127.0.0.1:{srv.port}/v1",
        "api_key": "sk-test", "model": "gpt-4o-mini"})
    s = summ.summarize(_thread())
    assert s.summary_text.startswith("SUMMARY[gpt-4o-mini]")
    assert s.prompt_tokens == 10 and s.completion_tokens == 5
    # citations come from chunks, never the model output
    assert s.citations[0].chunk_id == "c1"
    kind, headers, body = state["requests"][0]
    assert headers.get("Authorization") == "Bearer sk-test"
    assert body["messages"][0]["role"] == "system"
    assert "QUIC drafts" in body["messages"][1]["content"]


def test_openai_summarizer_rate_limit_surfaces_retry_after(mock_openai):
    srv, state = mock_openai
    state["fail_next"] = 429
    summ = create_summarizer({
        "driver": "openai",
        "base_url": f"http://127.0.0.1:{srv.port}/v1"})
    with pytest.raises(RateLimitError) as ei:
        summ.summarize(_thread())
    assert ei.value.retry_after_s == 7.0
    # next call succeeds — the service retry loop handles the wait
    assert summ.summarize(_thread()).summary_text


def test_azure_conventions(mock_openai):
    srv, state = mock_openai
    summ = create_summarizer({
        "driver": "azure_openai",
        "base_url": f"http://127.0.0.1:{srv.port}/v1",
        "api_key": "azkey"})
    summ.summarize(_thread())
    _, headers, _ = state["requests"][0]
    assert headers.get("Api-Key") == "azkey" or \
        headers.get("api-key") == "azkey"


def test_openai_embeddings_batch_and_ordering(mock_openai):
    srv, state = mock_openai
    prov = create_embedding_provider({
        "driver": "openai",
        "base_url": f"http://127.0.0.1:{srv.port}/v1",
        "dimension": 3, "batch_size": 2})
    vecs = prov.embed_batch(["aa", "bbbb", "cc"])
    # one request per batch_size=2 window
    assert len([r for r in state["requests"] if r[0] == "emb"]) == 2
    # index re-sort: vector i belongs to text i despite reversed reply
    assert vecs[0][0] == 2.0 and vecs[1][0] == 4.0 and vecs[2][0] == 2.0
    assert prov.embed("xyz")[0] == 3.0


def test_unreachable_backend_raises_cleanly():
    summ = create_summarizer({"driver": "openai",
                              "base_url": "http://127.0.0.1:1/v1"})
    with pytest.raises(SummarizationError, match="unreachable"):
        summ.summarize(_thread())
    prov = create_embedding_provider({"driver": "openai",
                                      "base_url": "http://127.0.0.1:1/v1"})
    with pytest.raises(EmbeddingError):
        prov.embed("x")


def test_base_url_required():
    with pytest.raises(ValueError, match="base_url"):
        create_summarizer({"driver": "openai"})
    with pytest.raises(ValueError, match="base_url"):
        create_embedding_provider({"driver": "azure_openai"})


def test_retry_after_parses_http_date_and_garbage():
    """RFC 7231 allows an HTTP-date Retry-After (some gateways send it);
    it must map to seconds, and garbage must fall back — never raise
    (review finding: a date crashed the 429 path entirely)."""
    import email.utils
    import time as _time

    from copilot_for_consensus_tpu.core.openai_compat import (
        parse_retry_after,
    )

    assert parse_retry_after("7") == 7.0
    assert parse_retry_after(None, default=2.0) == 2.0
    assert parse_retry_after("soon™", default=3.0) == 3.0
    future = email.utils.formatdate(_time.time() + 30, usegmt=True)
    assert 20.0 < parse_retry_after(future) <= 31.0
    past = email.utils.formatdate(_time.time() - 300, usegmt=True)
    assert parse_retry_after(past) == 0.0
