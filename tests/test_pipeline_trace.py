"""Pipeline-wide distributed tracing (obs/trace.py), the critical-path
analyzer (tools/tracepath.py), and trace-context propagation across the
bus drivers: redelivery, outbox replay, engine request replay — the
acceptance surface of the tracing tentpole.

Fast lane. The chaos-integration orphan gate at storm scale lives in
tests/test_bus_resilience.py::test_pipeline_chaos_storm_gate (slow)."""

import json
import pathlib
import sys
import tempfile
import threading
import time

import pytest

from copilot_for_consensus_tpu.bus import broker as broker_mod
from copilot_for_consensus_tpu.bus.inproc import (
    InProcBroker,
    InProcPublisher,
    InProcSubscriber,
)
from copilot_for_consensus_tpu.core.events import JSONParsed
from copilot_for_consensus_tpu.obs import trace
from copilot_for_consensus_tpu.tools import tracepath

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _fresh_collector():
    """Every test gets an empty global ring (and leaves one behind)."""
    trace.configure(capacity=50_000)
    yield
    trace.configure(capacity=8192)


# ---------------------------------------------------------------------------
# context propagation primitives
# ---------------------------------------------------------------------------


def test_inject_stamps_context_and_records_publish_span():
    env = JSONParsed(message_doc_id="m1",
                     correlation_id="c-1").to_envelope()
    out = trace.inject(env, "json.parsed", service="parsing")
    ctx = trace.extract(out)
    assert ctx is not None
    assert ctx["trace_id"] and ctx["span_id"]
    assert ctx["parent_span_id"] == ""          # no ambient span: root
    assert ctx["published_at"] > 0
    spans = trace.get_collector().spans()
    assert len(spans) == 1
    pub = spans[0]
    assert pub.kind == "publish"
    assert pub.span_id == ctx["span_id"]
    assert pub.routing_key == "json.parsed"
    assert pub.correlation_id == "c-1"
    # the input envelope was not mutated
    assert trace.extract(env) is None


def test_inject_preserves_existing_context():
    env = trace.inject(JSONParsed().to_envelope(), "json.parsed")
    before = trace.extract(env)
    n = len(trace.get_collector().spans())
    again = trace.inject(env, "json.parsed")
    assert trace.extract(again) == before
    # re-publish records no second publish span (outbox replay /
    # requeue must not fork the DAG)
    assert len(trace.get_collector().spans()) == n


def test_publish_inside_span_parents_under_it():
    with trace.span("parsing", kind="stage", service="parsing") as sp:
        env = trace.inject(JSONParsed().to_envelope(), "json.parsed")
        ctx = trace.extract(env)
        assert ctx["trace_id"] == sp.trace_id
        assert ctx["parent_span_id"] == sp.span_id
    assert trace.orphan_spans(trace.get_collector().spans()) == []


def test_stage_span_queue_wait_and_attempt():
    env = trace.inject(JSONParsed().to_envelope(), "json.parsed")
    env["trace"]["published_at"] = time.time() - 2.0
    trace.annotate_delivery(env, 3)
    with trace.stage_span("chunking", env) as sp:
        pass
    assert 1.5 < sp.queue_wait_s < 10.0
    assert sp.attempt == 3
    ctx = trace.extract(env)
    assert sp.trace_id == ctx["trace_id"]
    assert sp.parent_span_id == ctx["span_id"]


def test_stage_span_marks_error_and_propagates():
    env = trace.inject(JSONParsed().to_envelope(), "json.parsed")
    with pytest.raises(RuntimeError):
        with trace.stage_span("chunking", env):
            raise RuntimeError("boom")
    s = trace.get_collector().spans()[-1]
    assert s.status == "error" and "boom" in s.error


def test_use_context_resumes_trace_on_another_thread():
    got = {}

    with trace.span("summarization", kind="stage") as sp:
        ctx = trace.current_ids()

    def worker():
        with trace.use_context(*ctx):
            env = trace.inject(JSONParsed().to_envelope(),
                               "summary.complete")
            got["ctx"] = trace.extract(env)

    t = threading.Thread(target=worker)
    t.start()
    t.join(5)
    assert got["ctx"]["trace_id"] == sp.trace_id
    assert got["ctx"]["parent_span_id"] == sp.span_id


# ---------------------------------------------------------------------------
# collector: ring accounting, exports, orphan audit
# ---------------------------------------------------------------------------


def test_collector_ring_counts_drops_exactly():
    c = trace.TraceCollector(capacity=4)
    for i in range(10):
        c.record(trace.Span(trace_id="t", span_id=f"s{i}",
                            parent_span_id="", name="x", kind="stage"))
    st = c.stats()
    assert st == {"opened": 10, "retained": 4, "dropped": 6,
                  "capacity": 4}


def test_exports_are_well_formed(tmp_path):
    with trace.span("parsing", kind="stage", service="parsing",
                    correlation_id="c-9"):
        with trace.child_span("store_write", "upsert_document",
                              collection="messages"):
            pass
    col = trace.get_collector()
    perfetto = col.export_perfetto()
    assert perfetto["traceEvents"]
    ev = perfetto["traceEvents"][0]
    assert ev["ph"] == "X" and ev["ts"] > 0 and ev["dur"] > 0
    otlp = col.export_otlp()
    spans = [s for rs in otlp["resourceSpans"]
             for ss in rs["scopeSpans"] for s in ss["spans"]]
    assert spans
    assert all(s["traceId"] and s["spanId"] for s in spans)
    # round-trip through files in every format
    for fmt in ("raw", "perfetto", "otlp"):
        p = col.dump_to_file(directory=str(tmp_path), tag=fmt, fmt=fmt)
        assert json.loads(pathlib.Path(p).read_text())
    # the raw dump is what tracepath loads
    raw = col.dump_to_file(directory=str(tmp_path))
    assert tracepath.load_spans(raw)


def test_orphan_audit_flags_missing_parents():
    spans = [
        {"trace_id": "t", "span_id": "a", "parent_span_id": ""},
        {"trace_id": "t", "span_id": "b", "parent_span_id": "a"},
        {"trace_id": "t", "span_id": "c", "parent_span_id": "ZZZ"},
    ]
    orphans = trace.orphan_spans(spans)
    assert [o["span_id"] for o in orphans] == ["c"]


def test_dispatch_failure_dump_contains_the_error_span(tmp_path):
    """The auto-dump for a failing dispatch must be written AFTER the
    stage span closes: it must contain the error span itself, and its
    already-recorded failure-event publish span must not read as an
    orphan (the triage artifact must not misrepresent the failure it
    exists to diagnose)."""
    from copilot_for_consensus_tpu.bus.base import (
        NoopPublisher,
        PoisonEnvelope,
    )
    from copilot_for_consensus_tpu.core import events as ev
    from copilot_for_consensus_tpu.services.base import BaseService
    from copilot_for_consensus_tpu.storage.memory import (
        InMemoryDocumentStore,
    )

    class Pub(NoopPublisher):
        def publish_envelope(self, envelope, routing_key=None):
            trace.inject(envelope, routing_key or "chunking.failed")

    class Svc(BaseService):
        name = "chunking"
        consumes = ("JSONParsed",)

        def on_JSONParsed(self, event):
            raise ValueError("deterministic")

        def failure_event(self, envelope, error, attempts):
            return ev.ChunkingFailed(error=str(error))

    prev = trace.get_default_dump_dir()
    trace.set_default_dump_dir(str(tmp_path))
    try:
        svc = Svc(Pub(), InMemoryDocumentStore())
        env = trace.inject(ev.JSONParsed(
            message_doc_id="m1").to_envelope(), "json.parsed")
        with pytest.raises(PoisonEnvelope):
            svc.handle_envelope(env)
    finally:
        trace.set_default_dump_dir(prev)
    dumps = sorted(tmp_path.glob("dispatch-failure-*.json"))
    assert dumps
    data = json.loads(dumps[-1].read_text())
    spans = data["spans"]
    errs = [s for s in spans
            if s["kind"] == "stage" and s["status"] == "error"]
    assert errs, "dump written before the failing stage span recorded"
    assert "deterministic" in errs[0]["error"]
    assert trace.orphan_spans(spans) == []


def test_dump_on_failure_writes_to_configured_dir(tmp_path):
    prev = trace.get_default_dump_dir()
    trace.set_default_dump_dir(str(tmp_path))
    try:
        with trace.span("parsing", kind="stage"):
            pass
        path = trace.dump_on_failure(RuntimeError("x"))
        assert path and pathlib.Path(path).exists()
        data = json.loads(pathlib.Path(path).read_text())
        assert data["error"]["type"] == "RuntimeError"
        assert data["spans"]
    finally:
        trace.set_default_dump_dir(prev)


# ---------------------------------------------------------------------------
# end-to-end: one message through the real topology → one connected
# trace spanning the forward path, joinable with engine telemetry
# ---------------------------------------------------------------------------


def _run_small_pipeline():
    sys.path.insert(0, str(REPO / "scripts"))
    from scale_bench import synthetic_mbox

    from copilot_for_consensus_tpu.services.runner import build_pipeline

    tmp = pathlib.Path(tempfile.mkdtemp(prefix="trace-e2e-"))
    synthetic_mbox(tmp / "a.mbox", 8, thread_size=4)
    p = build_pipeline({})
    p.ingestion.create_source({
        "source_id": "s1", "name": "s1", "fetcher": "local",
        "location": str(tmp / "a.mbox")})
    stats = p.ingest_and_run("s1")
    return p, stats


def test_single_ingest_yields_one_connected_trace_over_5_stages():
    p, stats = _run_small_pipeline()
    assert stats["reports"] >= 1
    spans = trace.get_collector().spans()
    # zero orphans: every span's parent is recorded
    assert trace.orphan_spans(spans) == []
    roots = [s for s in spans if s.kind == "publish"
             and s.routing_key == "archive.ingested"]
    assert len(roots) == 1
    tp = tracepath.trace_path(spans, roots[0].trace_id)
    stages = {h["stage"] for h in tp["path"]}
    assert {"ingestion", "parsing", "chunking", "embedding",
            "orchestrator", "summarization",
            "reporting"} <= stages                      # ≥ 5 stages
    assert tp["orphan_spans"] == 0
    assert tp["e2e_s"] > 0
    # queue-wait vs service-time breakdown is populated
    assert tp["service_total_s"] > 0
    assert all(h["queue_wait_s"] >= 0 for h in tp["path"])
    # child spans: store writes, vector upserts and the engine submit
    # all recorded under the stage spans
    kinds = {s.kind for s in spans
             if s.trace_id == roots[0].trace_id}
    assert {"publish", "stage", "store_write", "vector_upsert",
            "engine_submit"} <= kinds


def test_trace_joins_engine_request_trace_by_correlation_id():
    """The pipeline stage spans and the engine's RequestTrace share the
    event correlation_id — the join key that stitches host-side stage
    attribution to the PR-5 flight recorder."""
    from copilot_for_consensus_tpu.engine.telemetry import EngineTelemetry

    p, _stats = _run_small_pipeline()
    spans = trace.get_collector().spans()
    sub = [s for s in spans if s.kind == "engine_submit"]
    assert sub, "no engine_submit spans recorded"
    corr = sub[0].correlation_id
    assert corr
    # the summarization stage span carries the same correlation id
    stage_corrs = {s.correlation_id for s in spans
                   if s.kind == "stage" and s.name == "summarization"}
    assert corr in stage_corrs
    # an engine fed that correlation id produces a joinable span
    tele = EngineTelemetry(engine="generation")
    tele.on_submit(1, prompt_len=8, correlation_id=corr)
    assert corr in tele.correlation_ids()


def test_stage_metrics_emitted_per_dispatch():
    from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics

    p, _stats = _run_small_pipeline()
    m = p.metrics
    assert isinstance(m, InMemoryMetrics)
    stats = m.histogram_stats("pipeline_stage_duration_seconds",
                              {"stage": "chunking"})
    assert stats and stats["count"] >= 1
    waits = m.histogram_stats("pipeline_stage_queue_wait_seconds",
                              {"stage": "chunking"})
    assert waits and waits["count"] >= 1


# ---------------------------------------------------------------------------
# propagation under redelivery (inproc + durable broker)
# ---------------------------------------------------------------------------


def test_inproc_redelivery_annotates_attempts_without_orphans():
    broker = InProcBroker(max_redeliveries=3)
    pub = InProcPublisher(broker=broker)
    sub = InProcSubscriber(broker=broker, group="g")
    seen = []

    def cb(env):
        with trace.stage_span("chunking", env) as sp:
            seen.append(sp.attempt)
            if len(seen) < 3:
                raise RuntimeError("transient")

    sub.subscribe(["json.parsed"], cb)
    pub.publish(JSONParsed(message_doc_id="m1"))
    broker.drain()
    assert seen == [0, 1, 2]
    spans = trace.get_collector().spans()
    stages = [s for s in spans if s.kind == "stage"]
    assert len(stages) == 3
    # every retry is a NEW span with the SAME recorded parent
    assert len({s.span_id for s in stages}) == 3
    assert len({s.parent_span_id for s in stages}) == 1
    assert [s.attempt for s in stages] == [0, 1, 2]
    assert [s.status for s in stages] == ["error", "error", "ok"]
    assert trace.orphan_spans(spans) == []


def test_fanout_groups_do_not_share_attempt_annotations():
    """The in-proc broker fan-out shallow-copies envelopes per consumer
    group; a retry in one group must not stamp its attempt count onto
    another group's pristine first delivery (annotate_delivery replaces
    the trace dict, never mutates the shared one)."""
    broker = InProcBroker(max_redeliveries=3)
    pub = InProcPublisher(broker=broker)
    sub_a = InProcSubscriber(broker=broker, group="a")
    sub_b = InProcSubscriber(broker=broker, group="b")
    a_attempts, b_attempts = [], []

    def cb_a(env):
        with trace.stage_span("chunking", env) as sp:
            a_attempts.append(sp.attempt)
            if len(a_attempts) < 2:
                raise RuntimeError("transient")

    def cb_b(env):
        # group B consumes AFTER group A's retry cycled, so a shared
        # trace dict would leak A's attempt stamp into B's delivery
        with trace.stage_span("embedding", env) as sp:
            b_attempts.append(sp.attempt)

    # BOTH groups bound before the publish: each queue gets a shallow
    # dict(envelope) copy sharing the nested trace dict. A's retry is
    # dispatched (and annotated) before B's first delivery, so an
    # in-place attempt write would bleed into B's copy.
    sub_a.subscribe(["json.parsed"], cb_a)
    sub_b.subscribe(["json.parsed"], cb_b)
    pub.publish(JSONParsed(message_doc_id="m1"))
    broker.drain()
    assert a_attempts == [0, 1]
    # B's only delivery is a FIRST delivery: attempt 0 — before the
    # fix, the shared trace dict reported A's retry stamp here
    assert b_attempts == [0]


def test_child_and_publish_spans_inherit_owning_service():
    """A store write under the parsing stage belongs to service
    "parsing" — not to a fake service named after the store method —
    and a publish made inside a handler is attributed to the handler's
    service (the Perfetto pid grouping contract)."""
    env = trace.inject(JSONParsed().to_envelope(), "json.parsed")
    with trace.stage_span("parsing", env):
        with trace.child_span("store_write", "upsert_document") as c:
            pass
        out = trace.inject(JSONParsed().to_envelope(), "chunks.prepared")
        assert trace.extract(out)
    assert c.service == "parsing"
    pub = [s for s in trace.get_collector().spans()
           if s.kind == "publish" and s.routing_key == "chunks.prepared"]
    assert pub[0].service == "parsing"


@pytest.mark.skipif(not broker_mod.HAS_ZMQ, reason="pyzmq missing")
def test_broker_redelivery_annotates_attempts_without_orphans():
    from copilot_for_consensus_tpu.core.retry import RetryableError

    broker = broker_mod.Broker(port=0, db_path=":memory:").start()
    try:
        pub = broker_mod.BrokerPublisher({"address": broker.address})
        sub = broker_mod.BrokerSubscriber({"address": broker.address},
                                          group="g")
        attempts = []

        def cb(env):
            with trace.stage_span("chunking", env) as sp:
                attempts.append(sp.attempt)
                if len(attempts) < 2:
                    raise RetryableError("transient")   # nack → requeue

        sub.subscribe(["json.parsed"], cb)
        pub.publish(JSONParsed(message_doc_id="m1"))
        deadline = time.monotonic() + 10
        while len(attempts) < 2 and time.monotonic() < deadline:
            sub.drain()
            time.sleep(0.02)
        assert attempts == [0, 1]
        spans = trace.get_collector().spans()
        stages = [s for s in spans if s.kind == "stage"]
        assert len(stages) == 2
        assert len({s.parent_span_id for s in stages}) == 1
        assert trace.orphan_spans(spans) == []
        pub.close()
        sub.close()
    finally:
        broker.stop()


# ---------------------------------------------------------------------------
# propagation across outbox replay (broker outage ride-through)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not broker_mod.HAS_ZMQ, reason="pyzmq missing")
def test_outbox_replay_preserves_trace_context():
    probe = broker_mod.Broker(port=0, db_path=":memory:").start()
    port = probe.port
    probe.stop()        # a port that WAS free; broker now down
    pub = broker_mod.BrokerPublisher({"port": port, "timeout_ms": 150,
                                      "retries": 1})
    pub.publish(JSONParsed(message_doc_id="m1", correlation_id="c-7"))
    assert pub.outbox.depth() == 1
    # the parked row already carries the injected context
    (_oid, _rk, env_json), = pub.outbox.oldest(1)
    parked_ctx = json.loads(env_json)["trace"]
    assert parked_ctx["trace_id"]
    n_pub_spans = len([s for s in trace.get_collector().spans()
                       if s.kind == "publish"])
    assert n_pub_spans == 1
    # broker comes back on the same port: the replayer drains in order
    broker = broker_mod.Broker(port=port, db_path=":memory:").start()
    try:
        got = []
        sub = broker_mod.BrokerSubscriber({"port": port}, group="g")
        sub.subscribe(["json.parsed"], lambda env: got.append(env))
        deadline = time.monotonic() + 10
        while not got and time.monotonic() < deadline:
            sub.drain()
            time.sleep(0.05)
        assert got, "parked publish never replayed"
        # identical context after the replay — and no second publish
        # span was recorded for the re-publish
        assert got[0]["trace"] == parked_ctx
        assert pub.outbox_stats()["replayed"] == 1
        assert len([s for s in trace.get_collector().spans()
                    if s.kind == "publish"]) == n_pub_spans
        sub.close()
    finally:
        pub.close()
        broker.stop()


# ---------------------------------------------------------------------------
# propagation across engine request replay
# ---------------------------------------------------------------------------


def test_engine_replay_records_annotated_child_span():
    from test_engine_chaos import StubEngine, _sup_cfg

    from copilot_for_consensus_tpu.engine.async_runner import (
        AsyncEngineRunner,
    )

    eng = StubEngine(script=["fail"], fail_gen=2)
    runner = AsyncEngineRunner(
        eng, supervisor=_sup_cfg(replay_budget=2)).start()
    try:
        with trace.span("summarization", kind="stage",
                        service="summarization") as sp:
            h = runner.submit([1, 2, 3], 6, correlation_id="r-1")
        c = h.result(timeout=10.0)
        assert len(c.tokens) == 6
        assert runner.replayed == 1
        spans = trace.get_collector().spans()
        replays = [s for s in spans if s.kind == "engine_replay"]
        assert len(replays) == 1
        r = replays[0]
        # annotated retry: attempt number, correlation id, and the
        # submitting stage span as parent — joined, not orphaned
        assert r.attempt == 1
        assert r.correlation_id == "r-1"
        assert r.trace_id == sp.trace_id
        assert r.parent_span_id == sp.span_id
        assert trace.orphan_spans(spans) == []
    finally:
        runner.stop()


def test_pipelined_summarization_tail_stays_in_trace():
    """The harvester thread's store/publish tail re-enters the
    originating trace (summarization stows trace_ctx with each
    in-flight generation), so SummaryComplete never roots a new
    trace."""
    from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics
    from copilot_for_consensus_tpu.services.summarization import (
        SummarizationService,
    )
    from copilot_for_consensus_tpu.storage.memory import (
        InMemoryDocumentStore,
    )
    from copilot_for_consensus_tpu.summarization.base import Summary

    class AsyncSummarizer:
        model_name = "fake"

        def summarize(self, context):
            raise AssertionError("pipelined path only")

        def summarize_async(self, context, correlation_id=""):
            def wait():
                return Summary(thread_id=context.thread_id,
                               summary_text="s", model=self.model_name)
            return wait

    broker = InProcBroker()
    pub = InProcPublisher(broker=broker)
    store = trace.TracingDocumentStore(InMemoryDocumentStore())
    store.upsert_document("threads", {
        "thread_id": "t1", "subject": "x", "participants": [],
        "message_count": 1})
    store.upsert_document("chunks", {
        "chunk_id": "ck1", "thread_id": "t1", "text": "hello"})
    svc = SummarizationService(pub, store, AsyncSummarizer(),
                               pipelined=True,
                               metrics=InMemoryMetrics())
    from copilot_for_consensus_tpu.core.events import (
        SummarizationRequested,
    )

    env = trace.inject(SummarizationRequested(
        thread_id="t1", summary_id="sum1", selected_chunks=["ck1"],
        correlation_id="c-5").to_envelope(), "summarization.requested")
    root_trace = trace.extract(env)["trace_id"]
    svc.handle_envelope(env)
    svc.flush(timeout=10)
    spans = trace.get_collector().spans()
    done = [s for s in spans if s.kind == "publish"
            and s.routing_key == "summary.complete"]
    assert len(done) == 1
    assert done[0].trace_id == root_trace
    assert done[0].parent_span_id        # parented, not a new root
    # the resumed-thread tail attributes to the ORIGINATING service,
    # not the "publisher"/store-method fallbacks (use_context carries
    # the service for Perfetto/OTLP grouping)
    assert done[0].service == "summarization"
    tail_writes = [s for s in spans if s.kind == "store_write"
                   and s.trace_id == root_trace]
    assert tail_writes
    assert all(s.service == "summarization" for s in tail_writes)
    assert trace.orphan_spans(spans) == []


# ---------------------------------------------------------------------------
# tracepath: aggregate analysis + bottleneck naming + CLI
# ---------------------------------------------------------------------------


def _stage_dict(stage, dur, wait, trace_id="t1", status="ok"):
    sid = trace._new_span_id()
    return {"trace_id": trace_id, "span_id": sid, "parent_span_id": "",
            "name": stage, "kind": "stage", "service": stage,
            "start_wall": time.time(), "duration_s": dur,
            "queue_wait_s": wait, "status": status, "attempt": 0,
            "correlation_id": "c", "event_type": "", "routing_key": "",
            "error": "", "attrs": {}}


def test_analyze_names_the_dragged_stage_as_bottleneck():
    spans = []
    for _ in range(50):
        spans.append(_stage_dict("parsing", 0.002, 0.001))
        spans.append(_stage_dict("chunking", 0.02, 0.15))   # dragged
        spans.append(_stage_dict("embedding", 0.004, 0.002))
    # one rare slow parse must not outweigh the per-message pileup
    spans.append(_stage_dict("parsing", 1.0, 0.0))
    a = tracepath.analyze(spans)
    assert a["bottleneck_stage"] == "chunking"
    assert a["stage_p95_s"]["chunking"] >= 0.02
    assert a["queue_wait_p95_s"]["chunking"] >= 0.15
    assert set(a["stages"]) == {"parsing", "chunking", "embedding"}
    st = a["stages"]["chunking"]
    assert st["count"] == 50
    assert st["queue_wait_total_s"] > st["total_s"]   # wait-dominated
    assert a["orphan_spans"] == 0


def test_analyze_counts_errors_and_orphans():
    spans = [_stage_dict("parsing", 0.01, 0.0, status="error"),
             {**_stage_dict("chunking", 0.01, 0.0),
              "parent_span_id": "missing-parent"}]
    a = tracepath.analyze(spans)
    assert a["stages"]["parsing"]["errors"] == 1
    assert a["orphan_spans"] == 1


def test_tracepath_cli_reports_and_reconstructs(tmp_path, capsys):
    with trace.span("parsing", kind="stage", service="parsing"):
        with trace.child_span("store_write", "upsert_document"):
            pass
    dump = trace.get_collector().dump_to_file(directory=str(tmp_path))
    assert tracepath.main([dump]) == 0
    out = capsys.readouterr().out
    assert "bottleneck:" in out and "parsing" in out
    assert tracepath.main([dump, "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["bottleneck_stage"] == "parsing"
    tid = trace.get_collector().spans()[0].trace_id
    assert tracepath.main([dump, "--trace", tid]) == 0
    tp = json.loads(capsys.readouterr().out)
    assert tp["trace_id"] == tid and tp["spans"] == 2


def test_tracepath_module_entrypoint(tmp_path):
    import subprocess

    with trace.span("parsing", kind="stage"):
        pass
    dump = trace.get_collector().dump_to_file(directory=str(tmp_path))
    res = subprocess.run(
        [sys.executable, "-m",
         "copilot_for_consensus_tpu.tools.tracepath", dump],
        capture_output=True, text=True, cwd=str(REPO), timeout=120)
    assert res.returncode == 0, res.stderr
    assert "bottleneck:" in res.stdout


# ---------------------------------------------------------------------------
# DLQ triage carries the trace join keys
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not broker_mod.HAS_ZMQ, reason="pyzmq missing")
def test_dead_letter_listing_surfaces_correlation_and_trace_ids():
    from copilot_for_consensus_tpu.bus.base import PoisonEnvelope
    from copilot_for_consensus_tpu.tools.failed_queues import (
        DeadLetterManager,
    )

    broker = broker_mod.Broker(port=0, db_path=":memory:").start()
    try:
        pub = broker_mod.BrokerPublisher({"address": broker.address})
        sub = broker_mod.BrokerSubscriber({"address": broker.address},
                                          group="g")

        def cb(env):
            raise PoisonEnvelope("deterministic failure")

        sub.subscribe(["json.parsed"], cb)
        pub.publish(JSONParsed(message_doc_id="m1",
                               correlation_id="c-13"))
        deadline = time.monotonic() + 10
        while not broker.store.dead_letters() \
                and time.monotonic() < deadline:
            sub.drain()
            time.sleep(0.02)
        dlq = DeadLetterManager(broker.address)
        msgs = dlq.list_dead()
        assert len(msgs) == 1
        assert msgs[0]["correlation_id"] == "c-13"
        assert msgs[0]["trace_id"]
        assert msgs[0]["trace_id"] == \
            msgs[0]["envelope"]["trace"]["trace_id"]
        dlq.close()
        pub.close()
        sub.close()
    finally:
        broker.stop()


def test_orchestrator_retrieval_span_carries_index_stats():
    """Top-k context selection is a first-class traced stage (ISSUE
    19): the orchestrator's retrieval span carries the vector store's
    last_query_stats (route / nprobe / lists_scanned_frac) so
    tracepath can attribute retrieval latency to the index
    configuration, not just "orchestrator time"."""
    from copilot_for_consensus_tpu.services.orchestrator import (
        OrchestrationService,
    )

    class Hit:
        def __init__(self, i):
            self.id = f"c{i}"
            self.score = 0.9 - 0.1 * i

    class StubVS:
        last_query_stats = None

        def query(self, vec, top_k=10, flt=None):
            self.last_query_stats = {
                "route": "ivf", "queries": 1, "nprobe": 8,
                "lists_scanned_frac": 0.0625}
            return [Hit(i) for i in range(3)]

    class StubEmb:
        def embed(self, text):
            return [0.1] * 8

    class StubStore:
        def query_documents(self, coll, q, sort=None, limit=None):
            if "chunk_id" in q:
                return [{"chunk_id": f"c{i}", "thread_id": "t1",
                         "text": f"chunk {i}", "message_doc_id": "m",
                         "token_count": 3} for i in range(3)]
            return [{"chunk_id": "c0", "thread_id": "t1",
                     "text": "body", "seq": 0}]

    svc = OrchestrationService(object(), StubStore(),
                               vector_store=StubVS(),
                               embedding_provider=StubEmb())
    cands = svc._retrieve_context({"thread_id": "t1",
                                   "subject": "consensus"})
    assert [c.chunk_id for c in cands] == ["c0", "c1", "c2"]
    spans = [s for s in trace.get_collector().spans()
             if s.kind == "retrieval"]
    assert len(spans) == 1
    sp = spans[0]
    assert sp.name == "vector_topk"
    assert sp.attrs["route"] == "ivf"
    assert sp.attrs["nprobe"] == 8
    assert sp.attrs["lists_scanned_frac"] == 0.0625
    assert sp.attrs["hits"] == 3
