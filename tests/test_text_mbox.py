import pathlib

import pytest

from copilot_for_consensus_tpu.text.mbox import (
    decode_header_value,
    parse_date,
    parse_mbox_bytes,
    parse_mbox_file,
)

FIXTURE = pathlib.Path(__file__).parent / "fixtures" / "ietf-sample.mbox"


@pytest.fixture(scope="module")
def parsed():
    return list(parse_mbox_file(FIXTURE))


def test_parses_all_messages(parsed):
    assert len(parsed) == 7


def test_headers_decoded(parsed):
    msgs = [m for m, _ in parsed]
    assert msgs[0].message_id == "qr-root-1@example.org"
    assert msgs[0].from_addr == "alice@example.org"
    assert msgs[0].from_name == "Alice Example"
    # RFC-2047 encoded name
    assert msgs[2].from_name == "Carol Müller"
    # Cc merged into to_addrs
    assert "bob@example.net" in msgs[2].to_addrs


def test_reply_chain_headers(parsed):
    msgs = [m for m, _ in parsed]
    assert msgs[1].in_reply_to == "qr-root-1@example.org"
    assert msgs[2].references == ["qr-root-1@example.org",
                                  "qr-reply-1@example.net"]
    assert msgs[6].message_id == ""  # missing Message-ID tolerated


def test_dates_utc_iso(parsed):
    msgs = [m for m, _ in parsed]
    assert msgs[0].date == "2026-01-05T10:00:00+00:00"
    assert parse_date("garbage") is None
    assert parse_date(None) is None


def test_multipart_prefers_plain_text(parsed):
    msg, is_html = parsed[4]
    assert not is_html
    assert "consensus call" in msg.body_raw
    assert "<p>" not in msg.body_raw


def test_bytes_roundtrip(parsed):
    raw = FIXTURE.read_bytes()
    from_bytes = list(parse_mbox_bytes(raw))
    assert len(from_bytes) == len(parsed)
    assert from_bytes[0][0].message_id == parsed[0][0].message_id


def test_malformed_archive_yields_nothing():
    assert list(parse_mbox_bytes(b"this is not an mbox at all")) == []


def test_decode_header_edge_cases():
    assert decode_header_value(None) == ""
    assert decode_header_value("plain subject") == "plain subject"
    assert decode_header_value("=?utf-8?q?caf=C3=A9?=") == "café"
