"""Training checkpoint/resume (checkpoint/train_state.py): Orbax-backed
preemption recovery for the fine-tuning loop (SURVEY §5 checkpoint/
resume item for the TPU build)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu import train
from copilot_for_consensus_tpu.checkpoint import TrainCheckpointer
from copilot_for_consensus_tpu.models import decoder
from copilot_for_consensus_tpu.models.configs import decoder_config


@pytest.fixture(scope="module")
def setup():
    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(0), cfg,
                                 dtype=jnp.float32)
    opt = optax.adam(1e-3)
    step_fn = train.make_train_step(cfg, opt)
    rng = np.random.default_rng(0)
    batches = [
        (jnp.asarray(rng.integers(3, cfg.vocab_size, (4, 16)),
                     jnp.int32),
         jnp.asarray(rng.integers(8, 17, (4,)), jnp.int32))
        for _ in range(6)
    ]
    return cfg, params, opt, step_fn, batches


def _run(step_fn, params, opt_state, batches):
    loss = None
    for tokens, lengths in batches:
        params, opt_state, loss = step_fn(params, opt_state, tokens,
                                          lengths)
    return params, opt_state, loss


def test_save_restore_roundtrip(setup, tmp_path):
    cfg, params, opt, step_fn, batches = setup
    opt_state = opt.init(params)
    params2, opt_state2, _ = _run(step_fn, params, opt_state, batches[:2])

    with TrainCheckpointer(tmp_path / "ckpt") as ckpt:
        ckpt.save(2, params2, opt_state2)
        assert ckpt.latest_step() == 2
        step, p, o = ckpt.restore(like=(params2, opt_state2))
    assert step == 2
    for a, b in zip(jax.tree.leaves(params2), jax.tree.leaves(p)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(opt_state2), jax.tree.leaves(o)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resume_equals_uninterrupted(setup, tmp_path):
    """Preemption at step 3 then resume must reproduce the exact state
    an uninterrupted 6-step run reaches — optimizer moments included."""
    cfg, params, opt, step_fn, batches = setup
    straight_p, straight_o, straight_loss = _run(
        step_fn, params, opt.init(params), batches)

    p, o = params, opt.init(params)
    p, o, _ = _run(step_fn, p, o, batches[:3])
    with TrainCheckpointer(tmp_path / "ckpt2") as ckpt:
        ckpt.save(3, p, o)
    del p, o                                    # the "preemption"
    with TrainCheckpointer(tmp_path / "ckpt2") as ckpt:
        step, p, o = ckpt.restore(like=(params, opt.init(params)))
    assert step == 3
    p, o, resumed_loss = _run(step_fn, p, o, batches[3:])

    np.testing.assert_allclose(float(resumed_loss), float(straight_loss),
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(straight_p), jax.tree.leaves(p)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_retention_keeps_last_n(setup, tmp_path):
    cfg, params, opt, step_fn, batches = setup
    opt_state = opt.init(params)
    with TrainCheckpointer(tmp_path / "ckpt3", max_to_keep=2) as ckpt:
        for s in (1, 2, 3, 4):
            ckpt.save(s, params, opt_state)
        assert ckpt.all_steps() == [3, 4]
        assert ckpt.latest_step() == 4


def test_restore_empty_dir_raises(tmp_path):
    with TrainCheckpointer(tmp_path / "none") as ckpt:
        with pytest.raises(FileNotFoundError):
            ckpt.restore()


def test_sharded_state_roundtrip(tmp_path):
    """A pjit-style sharded pytree restores with its sharding intact on
    the 8-device virtual mesh (slice-preemption recovery without
    gathering to one host)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from copilot_for_consensus_tpu.parallel.mesh import (
        MeshConfig,
        build_mesh,
    )

    mesh = build_mesh(MeshConfig(dp=2, tp=4))
    sh = NamedSharding(mesh, P("tp", None))
    w = jax.device_put(jnp.arange(64.0).reshape(8, 8), sh)
    state = {"w": w, "step_scale": jnp.float32(0.5)}

    with TrainCheckpointer(tmp_path / "sharded") as ckpt:
        ckpt.save(1, state, {"m": w * 2})
        _, p, o = ckpt.restore(like=(state, {"m": w}))
    assert p["w"].sharding.is_equivalent_to(sh, ndim=2)
    np.testing.assert_array_equal(np.asarray(p["w"]), np.asarray(w))
    np.testing.assert_array_equal(np.asarray(o["m"]), np.asarray(w) * 2)
