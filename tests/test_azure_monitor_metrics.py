# Azure Monitor (Application Insights) metrics driver against a
# wire-contract mock of the /v2.1/track ingestion endpoint: envelope
# shape, counter delta temporality, gauge/histogram aggregation, label
# propagation, failure rollback (no double counting), shutdown flush.
import json
import threading

import pytest

from copilot_for_consensus_tpu.obs.azure_monitor import (
    AzureMonitorMetrics,
    parse_connection_string,
)
from copilot_for_consensus_tpu.obs.metrics import create_metrics_collector
from copilot_for_consensus_tpu.services.http import (
    HTTPServer,
    Response,
    Router,
)

IKEY = "12345678-abcd-ef00-1111-222233334444"


@pytest.fixture()
def mock_ingest():
    router = Router()
    state = {"envelopes": [], "fail_next": 0, "lock": threading.Lock()}

    @router.post("/v2.1/track")
    def track(req):
        with state["lock"]:
            if state["fail_next"] > 0:
                state["fail_next"] -= 1
                return Response({"error": "throttled"}, status=500)
            lines = [json.loads(ln) for ln in
                     req.body.decode().splitlines() if ln.strip()]
            for env in lines:
                assert env["iKey"] == IKEY
                assert env["data"]["baseType"] == "MetricData"
            state["envelopes"].extend(lines)
            return {"itemsReceived": len(lines),
                    "itemsAccepted": len(lines), "errors": []}

    srv = HTTPServer(router)
    srv.start()
    yield srv, state
    srv.stop()


def _collector(srv, **kw):
    conn = (f"InstrumentationKey={IKEY};"
            f"IngestionEndpoint=http://127.0.0.1:{srv.port}")
    kw.setdefault("export_interval_s", 0)     # flush manually in tests
    return AzureMonitorMetrics(conn, **kw)


def _metric_points(state, name):
    out = []
    for env in state["envelopes"]:
        for point in env["data"]["baseData"]["metrics"]:
            if point["name"] == name:
                out.append((point,
                            env["data"]["baseData"]["properties"]))
    return out


def test_counter_delta_temporality(mock_ingest):
    """Counters export the delta since the previous flush, so restarts/
    repeated flushes never double count (the OTel exporter contract
    the reference relies on)."""
    srv, state = mock_ingest
    m = _collector(srv)
    m.increment("events_processed", 3)
    m.safe_push()
    m.increment("events_processed", 2)
    m.safe_push()
    m.safe_push()                              # nothing new: no envelope
    points = _metric_points(state, "copilot.events_processed")
    assert [p["value"] for p, _ in points] == [3, 2]


def test_gauge_and_histogram_aggregates(mock_ingest):
    srv, state = mock_ingest
    m = _collector(srv, namespace="svc")
    m.gauge("queue_depth", 17, labels={"queue": "chunks"})
    for v in (0.1, 0.2, 0.3):
        m.observe("latency_seconds", v)
    m.safe_push()
    (gpoint, gprops), = _metric_points(state, "svc.queue_depth")
    assert gpoint["value"] == 17 and gprops == {"queue": "chunks"}
    (hpoint, _), = _metric_points(state, "svc.latency_seconds")
    assert hpoint["count"] == 3
    assert hpoint["value"] == pytest.approx(0.6)
    # histogram also exports deltas only
    m.observe("latency_seconds", 0.4)
    m.safe_push()
    points = _metric_points(state, "svc.latency_seconds")
    assert points[-1][0]["count"] == 1
    assert points[-1][0]["value"] == pytest.approx(0.4)


def test_failed_export_rolls_back_without_double_count(mock_ingest):
    srv, state = mock_ingest
    m = _collector(srv)
    m.increment("jobs", 5)
    m.safe_push()                               # shipped: 5
    m.increment("jobs", 4)
    state["fail_next"] = 1
    m.safe_push()                               # fails; delta 4 unshipped
    assert m.get_errors_count() == 1
    m.safe_push()                               # retries the SAME delta
    points = _metric_points(state, "copilot.jobs")
    assert [p["value"] for p, _ in points] == [5, 4]   # total 9, not 14


def test_raise_on_error_mode(mock_ingest):
    srv, state = mock_ingest
    m = _collector(srv, raise_on_error=True)
    m.increment("x")
    state["fail_next"] = 1
    with pytest.raises(RuntimeError, match="export failed"):
        m.safe_push()


def test_background_export_and_shutdown_flush(mock_ingest):
    srv, state = mock_ingest
    m = _collector(srv, export_interval_s=3600)   # won't fire in test
    m.increment("final_counter", 7)
    m.shutdown()                                  # must flush pending
    (point, _), = _metric_points(state, "copilot.final_counter")
    assert point["value"] == 7
    assert m._thread is None


def test_parse_connection_string():
    ikey, ep = parse_connection_string(
        f"InstrumentationKey={IKEY};"
        "IngestionEndpoint=https://westus-0.in.applicationinsights.azure.com/")
    assert ikey == IKEY
    assert ep == "https://westus-0.in.applicationinsights.azure.com"
    ikey2, ep2 = parse_connection_string(IKEY)     # bare key form
    assert ikey2 == IKEY and ep2.startswith("https://dc.services")
    with pytest.raises(ValueError, match="InstrumentationKey"):
        parse_connection_string("garbage")


def test_factory_registration(mock_ingest):
    srv, _ = mock_ingest
    m = create_metrics_collector({
        "driver": "azure_monitor",
        "connection_string":
            f"InstrumentationKey={IKEY};"
            f"IngestionEndpoint=http://127.0.0.1:{srv.port}",
        "export_interval_s": 0})
    assert isinstance(m, AzureMonitorMetrics)
    # it is still a full local metrics surface (Prometheus renderable)
    m.increment("n")
    assert "copilot_n" in m.render_prometheus()
