from copilot_for_consensus_tpu.core import ids


def test_archive_id_deterministic_and_16_hex():
    a = ids.generate_archive_id_from_bytes(b"hello world")
    b = ids.generate_archive_id_from_bytes(b"hello world")
    assert a == b
    assert len(a) == 16
    assert int(a, 16) >= 0


def test_archive_id_distinguishes_content():
    assert (ids.generate_archive_id_from_bytes(b"a")
            != ids.generate_archive_id_from_bytes(b"b"))


def test_message_doc_id_uses_index_for_missing_message_id():
    a = ids.generate_message_doc_id("arch", "", 0)
    b = ids.generate_message_doc_id("arch", "", 1)
    assert a != b


def test_summary_id_order_invariant_over_chunks():
    a = ids.generate_summary_id("t1", ["c1", "c2", "c3"])
    b = ids.generate_summary_id("t1", ["c3", "c1", "c2"])
    assert a == b
    assert a != ids.generate_summary_id("t1", ["c1", "c2"])
    assert a != ids.generate_summary_id("t2", ["c1", "c2", "c3"])


def test_namespaces_do_not_collide():
    assert (ids.generate_chunk_id("x", 0)
            != ids.generate_message_doc_id("x", "0", 0))
