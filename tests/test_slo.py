# Declarative SLO scoreboard (obs/slo.py, ISSUE 20): PromQL-parity
# percentile/CDF math over in-memory histograms, objective evaluation
# with error-budget burn, registry uniqueness, the CLI, and the
# contract tying default_registry() to the Grafana dashboard
# (infra/grafana/dashboards/slo.json) so the scoreboard and the panels
# can never judge different series or thresholds.
import json
import pathlib

import pytest

from copilot_for_consensus_tpu.obs import slo
from copilot_for_consensus_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    InMemoryMetrics,
)
from copilot_for_consensus_tpu.obs.slo import (
    SLObjective,
    SLORegistry,
    default_registry,
    histogram_cdf,
    histogram_percentile,
    render_scoreboard,
)

ROOT = pathlib.Path(__file__).resolve().parent.parent
SLO_DASHBOARD = ROOT / "infra" / "grafana" / "dashboards" / "slo.json"


def _metrics(observations):
    m = InMemoryMetrics(namespace="copilot")
    for value in observations:
        m.observe("lat_seconds", value)
    return m


# -- percentile / CDF math (PromQL histogram_quantile parity) ------------


def test_percentile_interpolates_inside_the_bucket():
    # 50 obs land in the first bucket (<=0.005), 50 in the third
    # (<=0.025); cumulative counts: [50, 50, 100, ...]
    m = _metrics([0.004] * 50 + [0.02] * 50)
    # rank 50 resolves in the first bucket, fully interpolated
    assert histogram_percentile(m, "lat_seconds", 0.50) == \
        pytest.approx(0.005)
    # rank 75: halfway through the (0.01, 0.025] bucket
    assert histogram_percentile(m, "lat_seconds", 0.75) == \
        pytest.approx(0.01 + (0.025 - 0.01) * 0.5)
    assert histogram_percentile(m, "lat_seconds", 1.0) == \
        pytest.approx(0.025)


def test_percentile_caps_at_largest_finite_bound():
    # beyond every finite bucket: PromQL caps at the top bound rather
    # than extrapolating
    m = _metrics([1000.0] * 10)
    assert histogram_percentile(m, "lat_seconds", 0.99) == \
        DEFAULT_BUCKETS[-1]


def test_percentile_none_without_observations():
    assert histogram_percentile(
        InMemoryMetrics(namespace="copilot"), "lat_seconds", 0.99) is None


def test_percentile_respects_label_filter():
    m = InMemoryMetrics(namespace="copilot")
    m.observe("lat_seconds", 0.004, {"proc": "fast"})
    m.observe("lat_seconds", 40.0, {"proc": "slow"})
    fast = histogram_percentile(m, "lat_seconds", 0.5, {"proc": "fast"})
    slow = histogram_percentile(m, "lat_seconds", 0.5, {"proc": "slow"})
    both = histogram_percentile(m, "lat_seconds", 0.99)
    assert fast <= 0.005 < slow
    assert both > 1.0                           # fleet view merges procs


def test_cdf_fraction_under_threshold():
    m = _metrics([0.004] * 50 + [0.02] * 50)
    assert histogram_cdf(m, "lat_seconds", 0.01) == pytest.approx(0.5)
    assert histogram_cdf(m, "lat_seconds", 0.025) == pytest.approx(1.0)
    assert histogram_cdf(m, "lat_seconds", 700.0) == 1.0
    assert histogram_cdf(m, "lat_seconds", 0.0) == pytest.approx(0.0)


# -- objectives ----------------------------------------------------------


def _objective(threshold=2.0, budget=0.01):
    return SLObjective(name="lat-p99", series="copilot_lat_seconds",
                       percentile=0.99, threshold_s=threshold,
                       window="unit", workload="interactive",
                       budget=budget)


def test_objective_holds_under_threshold():
    row = _objective().evaluate(_metrics([0.004] * 100))
    assert row["ok"] is True
    assert row["observations"] == 100
    assert row["value_s"] <= 0.005
    assert row["violation_fraction"] == 0.0
    assert row["burn"] == 0.0


def test_objective_breach_and_budget_burn():
    # 10% of requests at 3s against a 2s threshold and a 1% budget:
    # the p99 breaches AND the error budget burns >1
    row = _objective().evaluate(_metrics([0.004] * 90 + [3.0] * 10))
    assert row["ok"] is False
    assert row["value_s"] > 2.0
    # the slow 10% land past the 2.5 bound; the 2.0 threshold sits in
    # the flat (1.0, 2.5] bucket, so the CDF there is exactly 0.9
    assert row["violation_fraction"] == pytest.approx(0.1)
    assert row["burn"] == pytest.approx(10.0)


def test_burn_can_exhaust_while_percentile_holds():
    # 3% slow against a 1% budget: p99... breaches here, so pick p50 —
    # the point estimate holds while the budget is triple-spent
    obj = SLObjective(name="lat-p50", series="copilot_lat_seconds",
                      percentile=0.50, threshold_s=2.0, budget=0.01)
    row = obj.evaluate(_metrics([0.004] * 97 + [4.0] * 3))
    assert row["ok"] is True
    assert row["burn"] > 1.0


def test_objective_no_data_is_none_not_false():
    row = _objective().evaluate(InMemoryMetrics(namespace="copilot"))
    assert row["ok"] is None
    assert row["observations"] == 0
    assert row["value_s"] is None


def test_check_judges_external_value():
    good = _objective().check(0.5)
    bad = _objective().check(2.5)
    assert good["ok"] is True and bad["ok"] is False
    assert good["value_s"] == 0.5
    assert good["name"] == "lat-p99" and good["observations"] is None


# -- registry ------------------------------------------------------------


def test_registry_rejects_duplicate_names():
    reg = SLORegistry([_objective()])
    with pytest.raises(ValueError, match="already registered"):
        reg.register(_objective())


def test_registry_evaluate_and_require_data():
    reg = SLORegistry([
        _objective(),
        SLObjective(name="other-p99", series="copilot_other_seconds",
                    percentile=0.99, threshold_s=1.0),
    ])
    board = reg.evaluate(_metrics([0.004] * 10))
    assert board["ok"] is True                  # no-data rows don't fail
    assert board["evaluated"] == 1 and board["total"] == 2
    strict = reg.evaluate(_metrics([0.004] * 10), require_data=True)
    assert strict["ok"] is False                # ...unless the gate asks


def test_default_registry_names_and_series():
    reg = default_registry()
    by_name = {o.name: o for o in reg.objectives()}
    assert set(by_name) == {
        "interactive-ttft-p99", "interactive-itl-p95", "queue-wait-p99",
        "stage-latency-p95", "kv-handoff-wait-p99"}
    # thresholds must match the bench knobs (BENCH_TTFT_SLO/
    # BENCH_ITL_SLO defaults) and the alert pack
    assert by_name["interactive-ttft-p99"].threshold_s == 2.0
    assert by_name["interactive-itl-p95"].threshold_s == 0.25
    assert by_name["kv-handoff-wait-p99"].workload == "disaggregated"
    for obj in reg.objectives():
        assert obj.series.startswith("copilot_")


def test_render_scoreboard_verdicts():
    reg = default_registry()
    m = InMemoryMetrics(namespace="copilot")
    for _ in range(100):
        m.observe("engine_ttft_seconds", 0.02)
    text = render_scoreboard(reg.evaluate(m))
    assert "interactive-ttft-p99" in text
    assert "[     ok]" in text and "no-data" in text
    for _ in range(100):
        m.observe("engine_ttft_seconds", 4.0)
    text = render_scoreboard(reg.evaluate(m))
    assert "BREACH" in text


# -- CLI over spools -----------------------------------------------------


def _spool_with(tmp_path, name, values):
    from copilot_for_consensus_tpu.obs.ship import (
        TelemetryShipper,
        spool_path,
    )

    m = InMemoryMetrics(namespace="copilot")
    for v in values:
        m.observe(name, v)
    ship = TelemetryShipper(spool_path(tmp_path, "cli"), proc="cli",
                            role="serve", metrics=m)
    ship.close()
    return ship.path


def test_cli_scoreboard_over_spool(tmp_path, capsys):
    path = _spool_with(tmp_path, "engine_ttft_seconds", [0.02] * 100)
    assert slo.main([path]) == 0
    assert "SLO scoreboard" in capsys.readouterr().out
    assert slo.main([path, "--require-data"]) == 1   # 4 objectives idle
    capsys.readouterr()
    assert slo.main([str(tmp_path), "--json"]) == 0  # dir ingestion
    board = json.loads(capsys.readouterr().out)
    rows = {r["name"]: r for r in board["objectives"]}
    assert rows["interactive-ttft-p99"]["ok"] is True
    assert rows["interactive-ttft-p99"]["observations"] == 100


def test_cli_exits_one_on_breach(tmp_path, capsys):
    path = _spool_with(tmp_path, "engine_ttft_seconds", [4.0] * 100)
    assert slo.main([path]) == 1
    assert "BREACH" in capsys.readouterr().out


# -- dashboard contract --------------------------------------------------


def test_default_registry_matches_slo_dashboard():
    """Every default objective must be rendered by slo.json with the
    SAME series and threshold — a drifted dashboard would show green
    while the scoreboard (and bench gates) judge red."""
    dash = json.loads(SLO_DASHBOARD.read_text())
    exprs = " ".join(t["expr"]
                     for p in dash["panels"]
                     for t in p.get("targets", ()))
    for obj in default_registry().objectives():
        assert f"{obj.series}_bucket" in exprs, obj.name
        assert f"histogram_quantile({obj.percentile}" in exprs, obj.name
        assert f"/ {obj.threshold_s}" in exprs, obj.name


def test_slo_dashboard_burn_panel_uses_real_bucket_bounds():
    """The burn panels select a single ``le`` bucket as the threshold
    proxy; it must be a real DEFAULT_BUCKETS bound or the series
    silently never matches."""
    dash = json.loads(SLO_DASHBOARD.read_text())
    bounds = {str(b) for b in DEFAULT_BUCKETS}
    for panel in dash["panels"]:
        for target in panel.get("targets", ()):
            expr = target["expr"]
            start = 0
            while True:
                i = expr.find('le="', start)
                if i < 0:
                    break
                j = expr.index('"', i + 4)
                assert expr[i + 4:j] in bounds, expr
                start = j
