# Graceful process lifecycle (services/lifecycle.py; ISSUE 12):
# STARTING→READY→DRAINING→STOPPED state machine, the ordered drain
# sequence (readiness 503 FIRST, pools stop without nacking, engines
# drain, outbox flushes), the degraded /health surface, and the
# stuck-thread accounting satellites (StageWorkerPool.stop /
# HTTPServer.stop returning False instead of silently leaking).
import json
import pathlib
import sys
import tempfile
import threading
import time
import urllib.request

import pytest

from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics
from copilot_for_consensus_tpu.services.lifecycle import (
    DRAINING,
    READY,
    STARTING,
    STATE_GAUGE,
    STOPPED,
    ServiceLifecycle,
    drain_pipeline,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# state machine
# ---------------------------------------------------------------------------


def test_lifecycle_transitions_and_readiness():
    lc = ServiceLifecycle("pipeline")
    assert lc.state == STARTING and not lc.is_ready()
    assert lc.mark_ready() is True
    assert lc.state == READY and lc.is_ready()
    assert lc.begin_drain() is True
    assert lc.state == DRAINING and not lc.is_ready()
    # drain aborted → back in service (the bench warm-resume arm)
    assert lc.mark_ready() is True and lc.is_ready()
    lc.begin_drain()
    assert lc.mark_stopped() is True
    assert lc.state == STOPPED and not lc.is_ready()
    # same-state transition is a no-op, not an error
    assert lc.mark_stopped() is False


def test_lifecycle_illegal_transition_raises():
    lc = ServiceLifecycle("x")
    lc.mark_ready()
    lc.mark_stopped()
    with pytest.raises(ValueError, match="illegal lifecycle"):
        lc.mark_ready()
    with pytest.raises(ValueError, match="unknown lifecycle"):
        lc.transition("zombie")


def test_lifecycle_history_and_gauge_export():
    m = InMemoryMetrics(namespace="copilot")
    lc = ServiceLifecycle("pipeline", metrics=m)
    lc.mark_ready()
    lc.begin_drain()
    states = [s for s, _t in lc.history]
    assert states == [STARTING, READY, DRAINING]
    # timestamps are monotone non-decreasing wall clock
    times = [t for _s, t in lc.history]
    assert times == sorted(times)
    assert m.gauge_value("lifecycle_state",
                         {"service": "pipeline"}) \
        == STATE_GAUGE[DRAINING]
    lc.mark_stopped()
    assert m.gauge_value("lifecycle_state",
                         {"service": "pipeline"}) \
        == STATE_GAUGE[STOPPED]


def test_lifecycle_listeners_fire_outside_lock():
    lc = ServiceLifecycle("x")
    seen = []

    def cb(old, new):
        # would deadlock if fired under the (non-reentrant) lock
        seen.append((old, new, lc.state))

    lc.on_transition(cb)
    lc.mark_ready()
    assert seen == [(STARTING, READY, READY)]
    # a broken listener must not block the transition
    lc.on_transition(lambda old, new: 1 / 0)
    lc.begin_drain()
    assert lc.state == DRAINING


# ---------------------------------------------------------------------------
# /health degraded + /readyz 503 (services/http.py satellites)
# ---------------------------------------------------------------------------


def _dispatch(router, method, path):
    resp = router.dispatch(method, path, {}, b"")
    return resp.status, json.loads(resp.raw) if resp.raw else None


def test_health_reports_degraded_but_stays_200():
    from copilot_for_consensus_tpu.services.http import health_router

    problems = []
    router = health_router("pipeline", degraded=lambda: problems)
    status, body = _dispatch(router, "GET", "/health")
    assert status == 200 and body == {"status": "ok",
                                      "service": "pipeline"}
    problems[:] = ["engine-breaker:spec_verify:open"]
    status, body = _dispatch(router, "GET", "/health")
    assert status == 200
    assert body["status"] == "degraded"
    assert body["degraded"] == ["engine-breaker:spec_verify:open"]


def test_health_degraded_check_failure_is_reported_not_raised():
    from copilot_for_consensus_tpu.services.http import health_router

    router = health_router("pipeline",
                           degraded=lambda: 1 / 0)
    status, body = _dispatch(router, "GET", "/health")
    assert status == 200
    assert body["degraded"] == ["degraded-check-failed"]


def test_readyz_503_while_not_ready():
    from copilot_for_consensus_tpu.services.http import health_router

    lc = ServiceLifecycle("pipeline")
    router = health_router("pipeline", ready_check=lc.is_ready)
    assert _dispatch(router, "GET", "/readyz")[0] == 503
    lc.mark_ready()
    assert _dispatch(router, "GET", "/readyz")[0] == 200
    lc.begin_drain()
    assert _dispatch(router, "GET", "/readyz")[0] == 503


def test_pipeline_degraded_reads_supervisor_breakers():
    from copilot_for_consensus_tpu.services.runner import build_pipeline

    p = build_pipeline({})
    assert p.degraded() == []       # mock summarizer: nothing to say

    class _Breaker:
        def __init__(self, name, state):
            self.name, self.state = name, state

    class _Sup:
        verify_breaker = _Breaker("spec_verify", "open")
        resource_breaker = _Breaker("resource", "closed")
        suspect = False
        unhealthy = False

    class _Runner:
        supervisor = _Sup()

    p.summarization.summarizer._runner = _Runner()
    assert p.degraded() == ["engine-breaker:spec_verify:open"]
    _Sup.unhealthy = True
    assert "engine-unhealthy" in p.degraded()


# ---------------------------------------------------------------------------
# stuck-thread accounting satellites
# ---------------------------------------------------------------------------


def test_http_server_stop_returns_bool():
    from copilot_for_consensus_tpu.services.http import (
        HTTPServer,
        Router,
    )

    srv = HTTPServer(Router(), "127.0.0.1", 0)
    srv.start()
    assert srv.stop() is True

    # a wedged serve thread: stop() must return False (and log), not
    # silently leak the thread. Start the server for real, then swap
    # in a thread that ignores the shutdown (the real serve loop exits
    # on shutdown(); it is daemonized and simply unjoined here).
    srv2 = HTTPServer(Router(), "127.0.0.1", 0)
    srv2.start()
    real = srv2._thread
    release = threading.Event()
    stuck = threading.Thread(target=release.wait, args=(30,),
                             daemon=True)
    stuck.start()
    srv2._thread = stuck
    try:
        assert srv2.stop() is False
    finally:
        release.set()
        stuck.join(timeout=5)
        real.join(timeout=5)


class _StuckSubscriber:
    """start_consuming ignores stop() until released — the hung-
    dispatch shape StageWorkerPool.stop() must surface."""

    def __init__(self):
        self.release = threading.Event()
        self.stopped = threading.Event()
        self.closed = False

    def start_consuming(self):
        self.release.wait(10)

    def stop(self):
        self.stopped.set()

    def close(self):
        self.closed = True

    def current_dispatch(self):
        return "json.parsed wave x4 (9.9s)"


class _Log:
    def __init__(self):
        self.errors = []

    def error(self, msg, **kw):
        self.errors.append((msg, kw))

    def info(self, msg, **kw):
        pass


def test_pool_stop_returns_false_and_logs_stuck_worker():
    from copilot_for_consensus_tpu.services.pool import StageWorkerPool

    subs = [_StuckSubscriber(), _StuckSubscriber()]
    log = _Log()
    pool = StageWorkerPool("chunking", subs, logger=log)
    pool.start()
    try:
        assert pool.stop(timeout=0.2) is False
        assert all(s.stopped.is_set() for s in subs)
        assert log.errors, "stuck worker was not logged"
        msg, kw = log.errors[0]
        assert "failed to join" in msg
        assert kw["pool"] == "chunking"
        assert kw["worker"].startswith("chunking-w")
        assert "json.parsed wave" in kw["dispatch"]
    finally:
        for s in subs:
            s.release.set()
        assert pool.join(timeout=5)
    # released workers: a later stop() is clean and True
    assert pool.stop(timeout=1) is True


def test_pool_stop_clean_returns_true():
    from copilot_for_consensus_tpu.services.pool import StageWorkerPool

    class _Clean:
        def __init__(self):
            self._stop = threading.Event()
            self.closed = False

        def start_consuming(self):
            self._stop.wait(10)

        def stop(self):
            self._stop.set()

        def close(self):
            self.closed = True

    subs = [_Clean(), _Clean()]
    pool = StageWorkerPool("parsing", subs)
    pool.start()
    assert pool.stop() is True
    pool.close()
    assert all(s.closed for s in subs)


def test_broker_subscriber_tracks_current_dispatch():
    from copilot_for_consensus_tpu.bus.broker import BrokerSubscriber

    sub = BrokerSubscriber({"address": "tcp://127.0.0.1:1"},
                           client=object())
    assert sub.current_dispatch() is None
    sub._current = ("json.parsed", "id=7", time.monotonic() - 2.0)
    state = sub.current_dispatch()
    assert state.startswith("json.parsed id=7 (")


# ---------------------------------------------------------------------------
# PipelineServer lifecycle (in-proc pipeline)
# ---------------------------------------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}") as resp:
        return resp.status, json.loads(resp.read())


def test_pipeline_server_readyz_flips_with_lifecycle():
    from copilot_for_consensus_tpu.services.bootstrap import (
        serve_pipeline,
    )

    server = serve_pipeline({})
    try:
        # before start(): lifecycle STARTING → /readyz already answers
        # 503 at the router level (nothing routable yet)
        resp = server.http.router.dispatch("GET", "/readyz", {}, b"")
        assert resp.status == 503
        server.start()
        assert server.lifecycle.state == READY
        status, body = _get(server.port, "/readyz")
        assert status == 200 and body["status"] == "ready"
        status, body = _get(server.port, "/health")
        assert status == 200 and body["status"] == "ok"
        report = server.drain(deadline_s=5)
        assert report["readiness_flipped"] is True
        assert report["consumers_stopped"] is True
        assert report["outbox_flushed"] is True
        assert server.lifecycle.state == STOPPED
        states = [s for s, _t in server.lifecycle.history]
        assert states == [STARTING, READY, DRAINING, STOPPED]
    finally:
        if server.lifecycle.state != STOPPED:
            server.stop()


def test_drain_after_stop_reports_instead_of_raising():
    """drain() on an already-stopped server must return an honest
    report (readiness_flipped False), never crash the shutdown path
    with an illegal-transition error."""
    from copilot_for_consensus_tpu.services.bootstrap import (
        serve_pipeline,
    )

    server = serve_pipeline({})
    server.start()
    server.stop()
    assert server.lifecycle.state == STOPPED
    report = server.drain(deadline_s=1)
    assert report["readiness_flipped"] is False
    assert server.lifecycle.state == STOPPED


def test_flush_outboxes_unreadable_is_not_flushed():
    """An unreadable outbox ledger must poll to the deadline and
    report False — never claim a clean flush it cannot see."""
    from copilot_for_consensus_tpu.services.runner import build_pipeline

    p = build_pipeline({})
    stats = {"n": 0}

    def boom():
        stats["n"] += 1
        raise RuntimeError("publisher torn down")

    p.publisher_stats = boom
    t0 = time.monotonic()
    assert p.flush_outboxes(timeout_s=0.2) is False
    assert stats["n"] > 1          # kept polling, not first-hit True
    assert time.monotonic() - t0 < 2.0


# ---------------------------------------------------------------------------
# drain ordering under a REAL broker with pools >= 2 (satellite 4):
# SIGTERM during an in-flight wave → readiness flips BEFORE consume
# stops, shutdown nacks nothing, the outbox drains, and the broker
# redelivers nothing after a clean drain.
# ---------------------------------------------------------------------------


def test_drain_ordering_under_broker_with_pools():
    from copilot_for_consensus_tpu.bus import broker as broker_mod

    if not broker_mod.HAS_ZMQ:
        pytest.skip("pyzmq not available")
    sys.path.insert(0, str(REPO / "scripts"))
    from scale_bench import synthetic_mbox

    from copilot_for_consensus_tpu.obs import trace as trace_mod
    from copilot_for_consensus_tpu.services.runner import build_pipeline

    tmp = pathlib.Path(tempfile.mkdtemp(prefix="drain-test-"))
    synthetic_mbox(tmp / "a.mbox", 24, thread_size=4)
    b = broker_mod.Broker(port=0, db_path=str(tmp / "q.sqlite3"),
                          lease_s=30.0).start()
    collector = trace_mod.configure(capacity=50_000)
    p = build_pipeline({
        "bus": {"driver": "broker", "port": b.port,
                "timeout_ms": 1000, "retries": 2},
        "document_store": {"driver": "sqlite",
                           "path": str(tmp / "docs.sqlite3")},
        "archive_store": {"driver": "document"},
        "services": {"parsing": {"workers": 2},
                     "chunking": {"workers": 2}},
    })
    try:
        for pool in p.worker_pools:
            pool.start()
        p.ingestion.create_source({
            "source_id": "s1", "name": "s1", "fetcher": "local",
            "location": str(tmp / "a.mbox")})
        p.ingestion.trigger_source("s1")   # waves now in flight

        lc = ServiceLifecycle("pipeline")
        lc.mark_ready()
        order = []
        orig_stop = p.stop_consuming

        def spying_stop(*a, **kw):
            order.append(("stop_consuming", time.time()))
            return orig_stop(*a, **kw)

        p.stop_consuming = spying_stop
        report = drain_pipeline(p, lc, deadline_s=20)
        # ORDERING: the DRAINING transition (readyz 503) happened
        # strictly before consumers stopped
        drain_at = [t for s, t in lc.history if s == DRAINING][0]
        assert order and drain_at <= order[0][1]
        assert report["consumers_stopped"] is True
        assert report["outbox_flushed"] is True
        # clean drain: zero leases left → the broker has nothing to
        # redeliver because of the shutdown
        counts = b.store.counts()
        assert sum(st.get("inflight", 0)
                   for st in counts.values()) == 0
        # nothing was nacked by shutdown: no dead letters at all
        assert not b.store.dead_letters()

        # warm resume: drain aborted, pools restart, work completes
        lc.mark_ready()
        for pool in p.worker_pools:
            pool.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            stored = p.store.count_documents("messages", {})
            missing = p.store.count_documents(
                "threads", {"summary_id": {"$exists": False}})
            if stored >= 24 and missing == 0:
                break
            p.drain(max_messages=50)
            time.sleep(0.02)
        assert p.store.count_documents("messages", {}) >= 24
        # zero redeliveries in the whole fault-free run: shutdown
        # itself caused none (every stage span has attempt == 0)
        assert sum(1 for s in collector.spans()
                   if getattr(s, "attempt", 0) > 0) == 0
    finally:
        p.stop_consuming()
        for sub in p.ext_subscribers:
            sub.close()
        for svc in p.services:
            try:
                svc.publisher.close()
            except Exception:
                pass
        p.store.close()
        b.stop()
