# Mesh + sharding: 8 virtual CPU devices (conftest forces the platform).
import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")

from jax.sharding import NamedSharding, PartitionSpec

from copilot_for_consensus_tpu.models import decoder
from copilot_for_consensus_tpu.models.configs import decoder_config
from copilot_for_consensus_tpu.parallel import (
    MeshConfig,
    build_mesh,
    logical_to_spec,
    shard_pytree,
)
from copilot_for_consensus_tpu.parallel.mesh import auto_mesh_for_serving


def test_virtual_device_count():
    assert len(jax.devices()) == 8


def test_mesh_axes_and_resolution():
    mesh = build_mesh(MeshConfig(dp=2, tp=0))  # tp auto-fills to 4
    assert mesh.axis_names == ("dp", "pp", "sp", "ep", "tp")
    assert mesh.shape == {"dp": 2, "pp": 1, "sp": 1, "ep": 1, "tp": 4}
    assert auto_mesh_for_serving().shape["tp"] == 8


def test_mesh_rejects_non_divisible():
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(dp=3, tp=0))


def test_logical_to_spec():
    assert logical_to_spec(("vocab", "embed")) == PartitionSpec("tp", None)
    assert logical_to_spec((None, "embed", "heads")) == \
        PartitionSpec(None, None, "tp")


def test_sharded_forward_matches_unsharded():
    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(0), cfg,
                                 dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size)
    ref = decoder.forward(params, tokens, cfg, attn_impl="xla")

    mesh = build_mesh(MeshConfig(dp=2, tp=4))
    sharded = shard_pytree(params, decoder.logical_axes(cfg), mesh)
    tok_sharded = jax.device_put(
        tokens, NamedSharding(mesh, PartitionSpec("dp", None)))
    fwd = jax.jit(lambda p, t: decoder.forward(p, t, cfg, attn_impl="xla"))
    out = fwd(sharded, tok_sharded)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)


def test_moe_ep_sharded_forward_matches():
    cfg = decoder_config("tiny-moe")
    params = decoder.init_params(jax.random.PRNGKey(2), cfg,
                                 dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0,
                                cfg.vocab_size)
    ref = decoder.forward(params, tokens, cfg, attn_impl="xla")
    mesh = build_mesh(MeshConfig(dp=1, ep=4, tp=2))
    sharded = shard_pytree(params, decoder.logical_axes(cfg), mesh)
    out = jax.jit(
        lambda p, t: decoder.forward(p, t, cfg, attn_impl="xla")
    )(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)
