# Observability pack: alert rules + dashboards as code, bus gauges on the
# gateway /metrics, jax.profiler capture (VERDICT r1 item 9).
import json
import pathlib
import re
import urllib.request

import pytest

yaml = pytest.importorskip(
    "yaml", reason="pyyaml (dev extra) needed for alert-rule linting")

REPO = pathlib.Path(__file__).resolve().parent.parent
ALERTS = REPO / "infra" / "prometheus" / "alerts"
DASHBOARDS = REPO / "infra" / "grafana" / "dashboards"

# Metric families the code actually emits (services/base.py central
# counters + per-service counters + bus gauges + pushgateway self-metric
# + prometheus built-ins). The lint below keeps alert exprs honest.
KNOWN_SERIES = {
    "copilot_ingestion_events_total", "copilot_parsing_events_total",
    "copilot_chunking_events_total", "copilot_embedding_events_total",
    "copilot_orchestrator_events_total",
    "copilot_summarization_events_total",
    "copilot_reporting_events_total",
    # per-stage handle histograms (services/base.py:90)
    "copilot_ingestion_handle_seconds", "copilot_parsing_handle_seconds",
    "copilot_chunking_handle_seconds", "copilot_embedding_handle_seconds",
    "copilot_orchestrator_handle_seconds",
    "copilot_summarization_handle_seconds",
    "copilot_reporting_handle_seconds",
    "copilot_ingestion_archives_total", "copilot_ingestion_dedup_total",
    "copilot_parsing_messages_total", "copilot_chunking_chunks_total",
    "copilot_embedding_chunks_total", "copilot_embedding_batch_seconds",
    "copilot_orchestrator_requests_total",
    "copilot_orchestrator_dedup_total",
    "copilot_summarization_summaries_total",
    "copilot_summarization_latency_seconds",
    "copilot_reporting_reports_total",
    # stats exporter gauges (tools/exporters.py)
    "copilot_collection_documents", "copilot_documents_pending",
    "copilot_vectorstore_vectors", "copilot_vectorstore_dimension",
    "copilot_exporter_scrape_seconds",
    # retry-job pushed metrics (tools/retry_job.py)
    "copilot_retry_requeued_total", "copilot_retry_exhausted_documents",
    "copilot_retry_last_sweep_timestamp", "copilot_retry_sweep_seconds",
    # process/host resource gauges (obs/resources.py)
    "copilot_process_resident_bytes", "copilot_process_memory_limit_bytes",
    "copilot_process_cpu_seconds_total", "copilot_process_open_fds",
    "copilot_process_start_time_seconds",
    "copilot_disk_free_bytes", "copilot_disk_total_bytes",
    "up", "push_time_seconds", "time", "vector", "absent",
}

# Engine flight-recorder series come from the telemetry REGISTRY
# (engine/telemetry.py:METRICS), not a hand-copied list — the registry
# is what the telemetry layer actually emits, so dashboard/alert
# references can only reference what exists.
from copilot_for_consensus_tpu.engine.telemetry import (  # noqa: E402
    METRICS as ENGINE_METRICS,
    prometheus_series as _engine_series,
)

KNOWN_SERIES |= set(_engine_series())

# Bus series likewise come from the BUS_METRICS registry next to the
# emitter (services/bootstrap.py:_BusGaugeMetrics) — the PR-5 pattern
# extended to the pipeline fault plane (PR 8): alerts/dashboards can
# only reference bus series the gateway exposition actually carries.
from copilot_for_consensus_tpu.services.bootstrap import (  # noqa: E402
    BUS_METRICS,
)

KNOWN_SERIES |= set(BUS_METRICS)

# Pipeline-trace series come from the tracing registry
# (obs/trace.py:PIPELINE_METRICS) — stage span histograms emitted by
# services/base.py per dispatch, span-ledger counters refreshed on the
# gateway scrape — same contract discipline as the engine registry.
from copilot_for_consensus_tpu.obs.trace import (  # noqa: E402
    PIPELINE_METRICS,
    prometheus_series as _pipeline_series,
)

KNOWN_SERIES |= set(_pipeline_series())

# Process-lifecycle series (services/lifecycle.py) — the drain state
# machine's gauge, same registry-next-to-emitter discipline.
from copilot_for_consensus_tpu.services.lifecycle import (  # noqa: E402
    LIFECYCLE_METRICS,
)

KNOWN_SERIES |= set(LIFECYCLE_METRICS)

# Retrieval series (vectorstore/tpu.py) — query latency/route counters,
# ivf probe/spill gauges — same registry-next-to-emitter discipline.
from copilot_for_consensus_tpu.vectorstore.tpu import (  # noqa: E402
    VECTORSTORE_METRICS,
)

KNOWN_SERIES |= set(VECTORSTORE_METRICS)

# Telemetry-shipping self-metrics (obs/ship.py) — spool row counters,
# flush latency, spool depth — same registry-next-to-emitter
# discipline (ISSUE 20).
from copilot_for_consensus_tpu.obs.ship import (  # noqa: E402
    SHIP_METRICS,
)

KNOWN_SERIES |= set(SHIP_METRICS)
# [a-z0-9_]: engine series contain digits (engine_e2e_seconds)
_SERIES_RE = re.compile(r"\b(copilot_[a-z0-9_]+|up|push_time_seconds)\b")


def _alert_files():
    files = sorted(ALERTS.glob("*.yml"))
    assert len(files) >= 5, "alert pack incomplete"
    return files


def test_alert_rules_parse_and_have_required_fields():
    total = 0
    for f in _alert_files():
        doc = yaml.safe_load(f.read_text())
        for group in doc["groups"]:
            assert group["name"]
            for rule in group["rules"]:
                assert rule["alert"] and rule["expr"], (f.name, rule)
                assert "summary" in rule.get("annotations", {}), rule
                assert "severity" in rule.get("labels", {}), rule
                total += 1
    assert total >= 60, f"only {total} rules"


def test_alert_exprs_reference_real_series():
    """Every metric family an alert references must be one the code
    emits — an alert on a typo'd series never fires and rots silently."""
    for f in _alert_files():
        doc = yaml.safe_load(f.read_text())
        for group in doc["groups"]:
            for rule in group["rules"]:
                for name in _SERIES_RE.findall(rule["expr"]):
                    base = re.sub(r"_(bucket|sum|count)$", "", name)
                    assert base in KNOWN_SERIES, (f.name, rule["alert"],
                                                  name)


def test_dashboards_parse_and_reference_real_series():
    files = sorted(DASHBOARDS.glob("*.json"))
    assert len(files) >= 11, "dashboard pack incomplete"
    uids = set()
    for f in files:
        doc = json.loads(f.read_text())
        assert doc["title"] and doc["panels"], f.name
        assert doc["uid"] not in uids, f"duplicate uid {doc['uid']}"
        uids.add(doc["uid"])
        for panel in doc["panels"]:
            for target in panel.get("targets", []):
                for name in _SERIES_RE.findall(target["expr"]):
                    base = re.sub(r"_(bucket|sum|count)$", "", name)
                    assert base in KNOWN_SERIES, (f.name, panel["title"],
                                                  name)


# -- engine flight-recorder metric-name contract -------------------------
#
# The PR-1 bug class: an alert wrote deriv() where the series needed
# rate() (or referenced a series nobody emits) and rotted silently —
# the expression evaluates to empty/garbage and the alert can never
# fire. These tests catch both statically: every copilot_engine_*
# reference must exist in the telemetry registry, carry the right
# suffix for its type, and sit under a PromQL function legal for that
# type. A separate test drives a full EngineTelemetry lifecycle and
# asserts the registry matches what is ACTUALLY emitted, both ways.


def _serving_pack_exprs():
    exprs = []
    doc = json.loads((DASHBOARDS / "serving-engines.json").read_text())
    for panel in doc["panels"]:
        for target in panel.get("targets", []):
            exprs.append((f"dashboard:{panel['title']}", target["expr"]))
    doc = yaml.safe_load((ALERTS / "serving.yml").read_text())
    for group in doc["groups"]:
        for rule in group["rules"]:
            exprs.append((f"alert:{rule['alert']}", rule["expr"]))
    return exprs


_ENGINE_REF_RE = re.compile(r"\bcopilot_engine_[a-z0-9_]+\b")


def test_engine_series_references_are_emitted_by_registry():
    emitted = _engine_series()            # full name -> type
    refs = {}
    for where, expr in _serving_pack_exprs():
        for name in _ENGINE_REF_RE.findall(expr):
            refs.setdefault(name, where)
    assert refs, "serving pack references no engine telemetry series"
    for name, where in refs.items():
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert base in emitted, (
            f"{where} references {name}, which the telemetry registry "
            f"(engine/telemetry.py:METRICS) does not emit")
        if name != base:
            assert emitted[base] == "histogram", (
                f"{where}: {name} uses a histogram suffix but "
                f"{base} is a {emitted[base]}")


def test_engine_promql_functions_match_series_types():
    """rate()/increase() need counters (or histogram components);
    deriv()/ *_over_time need gauges — applied to the wrong type the
    expression silently evaluates to nonsense."""
    emitted = _engine_series()
    rate_re = re.compile(r"\b(?:rate|irate|increase)\(\s*"
                         r"(copilot_engine_[a-z0-9_]+)")
    gauge_fn_re = re.compile(
        r"\b(?:deriv|avg_over_time|min_over_time|max_over_time|"
        r"quantile_over_time|delta)\(\s*(copilot_engine_[a-z0-9_]+)")
    for where, expr in _serving_pack_exprs():
        for name in rate_re.findall(expr):
            base = re.sub(r"_(bucket|sum|count)$", "", name)
            typ = emitted.get(base)
            assert typ in ("counter", "histogram"), (
                f"{where}: rate() over {name} ({typ}) — gauges need "
                f"deriv()/…_over_time")
            if typ == "histogram":
                assert name != base, (
                    f"{where}: rate() over bare histogram {name}; use "
                    f"_bucket/_sum/_count")
        for name in gauge_fn_re.findall(expr):
            base = re.sub(r"_(bucket|sum|count)$", "", name)
            assert emitted.get(base) == "gauge", (
                f"{where}: gauge function over {name} "
                f"({emitted.get(base)}) — counters/histograms need "
                f"rate()")


def test_telemetry_registry_matches_actual_emission():
    """Drive one full lifecycle through EngineTelemetry and assert the
    set of series it lands in its collector EQUALS the registry — a
    metric added to the code but not the registry (or vice versa) fails
    here, keeping the contract tests above honest."""
    from copilot_for_consensus_tpu.engine.telemetry import (
        EngineTelemetry,
    )
    from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics

    m = InMemoryMetrics(namespace="copilot")
    tele = EngineTelemetry(engine="generation", num_slots=4, metrics=m)
    tele.on_submit(1, prompt_len=16, correlation_id="c-1")
    tele.on_admit(1, wave_start=0.0, admit_kind="seeded",
                  prefix_hit_tokens=8)
    tele.record_step("prefill_seeded", 0.01, rows=1, batch=2,
                     tokens=8, padded_tokens=32)
    tele.record_step("decode", 0.002, rows=1, batch=4, tokens=4,
                     padded_tokens=32)
    tele.record_step("verify", 0.002, rows=1, batch=4, tokens=3,
                     padded_tokens=16, draft_tokens=4,
                     accepted_tokens=2)
    tele.gauge_queue(3, active=1)
    # scheduler series (engine/scheduler.py): per-tenant gauges, the
    # shed counter, and the chunked-prefill counter
    tele.sched_gauges({"tenant-a": 2, "": 1},
                      {"tenant-a": 128.0, "": 0.0})
    tele.on_shed("tenant-a", "batch")
    tele.on_prefill_chunks(3)
    tele.record_step("prefill_chunk", 0.004, rows=2, batch=4,
                     tokens=48, padded_tokens=256)
    # resilience series (engine/faults.py + engine/supervisor.py):
    # fault plane, watchdog, breakers, replay, audit, deadlines
    tele.on_fault_injected("decode", "error")
    tele.on_watchdog_trip("decode")
    tele.breaker_gauge("spec_verify", 1.0)
    tele.breaker_gauge("resource", 0.5)
    tele.on_replay()
    tele.on_replay_failed()
    tele.gauge_quarantined(1)
    tele.on_released_pins(2)
    tele.on_deadline_expired()
    # paged KV block pool (engine/kv_pool.py)
    tele.gauge_kv_pool(12, pinned_blocks=3, fragmentation_ratio=0.25)
    tele.on_zero_copy_admits(2)
    tele.gauge_kv_route("kernel")
    # disaggregated prefill/decode roles (engine/roles.py)
    tele.gauge_role_occupancy("prefill", 0.75)
    tele.on_handoff(blocks=6, wait_s=0.01)
    # durable request journal (engine/journal.py)
    tele.gauge_journal(2, checkpoint_lag=5)
    tele.on_journal_replayed()
    tele.on_retire(1, new_tokens=8, finish_reason="eos")
    tele.update_ledgers(
        prefix_stats={"enabled": True, "hit_rate": 0.5},
        spec_stats={"enabled": True, "acceptance_rate": 0.5,
                    "draft_hit_rate": 0.25,
                    "tokens_per_weight_pass": 2.0})
    tele.record_error(RuntimeError("boom"))
    emitted = (set(m.counters) | set(m.gauges) | set(m.histograms))
    assert emitted == set(ENGINE_METRICS), (
        f"registry drift: only-in-code {emitted - set(ENGINE_METRICS)}, "
        f"only-in-registry {set(ENGINE_METRICS) - emitted}")
    # and the TYPE of each emitted series matches its declaration
    for name, (typ, _labels, _help) in ENGINE_METRICS.items():
        store = {"counter": m.counters, "gauge": m.gauges,
                 "histogram": m.histograms}[typ]
        assert name in store, (name, typ)


def test_pipeline_trace_registry_matches_actual_emission():
    """Drive one traced dispatch through a BaseService and assert the
    set of pipeline_* series it lands EQUALS the registry's histogram
    families (the span-ledger counters are scrape-time, asserted in
    test_gateway_metrics_exposes_pipeline_span_counters) — with the
    declared types."""
    from copilot_for_consensus_tpu.bus.base import NoopPublisher
    from copilot_for_consensus_tpu.core.events import JSONParsed
    from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics
    from copilot_for_consensus_tpu.services.base import BaseService
    from copilot_for_consensus_tpu.storage.memory import (
        InMemoryDocumentStore,
    )

    class Svc(BaseService):
        name = "chunking"
        consumes = ("JSONParsed",)

        def on_JSONParsed(self, event):
            pass

    m = InMemoryMetrics(namespace="copilot")
    svc = Svc(NoopPublisher(), InMemoryDocumentStore(), metrics=m)
    svc.handle_envelope(JSONParsed(message_doc_id="m1").to_envelope())
    emitted = {n for n in (set(m.counters) | set(m.gauges)
                           | set(m.histograms))
               if n.startswith("pipeline_")}
    declared_hists = {n for n, (typ, _l, _h) in PIPELINE_METRICS.items()
                      if typ == "histogram"}
    assert emitted == declared_hists, (
        f"registry drift: only-in-code {emitted - declared_hists}, "
        f"only-in-registry {declared_hists - emitted}")
    for name in declared_hists:
        assert name in m.histograms, name
        assert m.histograms[name], name


def test_pipeline_alert_functions_match_series_types():
    """rate()/increase() need counters or histogram components;
    deriv()/delta() need gauges — the dead-alert bug class, applied to
    the copilot_pipeline_* pack."""
    emitted = _pipeline_series()
    fn_re = re.compile(r"\b(rate|irate|increase|deriv|delta|idelta)\s*"
                       r"\(\s*(copilot_pipeline_[a-z0-9_]+)")
    seen = 0
    for f in _alert_files():
        doc = yaml.safe_load(f.read_text())
        for group in doc["groups"]:
            for rule in group["rules"]:
                for fn, name in fn_re.findall(rule["expr"]):
                    seen += 1
                    base = re.sub(r"_(bucket|sum|count)$", "", name)
                    typ = emitted.get(base)
                    if fn in ("rate", "irate", "increase"):
                        assert typ in ("counter", "histogram"), (
                            f.name, rule["alert"], fn, name, typ)
                        if typ == "histogram":
                            assert name != base, (
                                f.name, rule["alert"], name)
                    else:
                        assert typ == "gauge", (f.name, rule["alert"],
                                                fn, name, typ)
    assert seen, "no alert references the pipeline-trace series"


def test_gateway_metrics_exposes_pipeline_span_counters():
    """The span-ledger counters are refreshed from the global collector
    on every scrape (services/bootstrap.py), so the
    PipelineTraceSpansDropped alert never watches an absent series."""
    from copilot_for_consensus_tpu.services.bootstrap import serve_pipeline

    server = serve_pipeline().start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics").read().decode()
        assert "copilot_pipeline_spans_open_total" in body
        assert "copilot_pipeline_spans_dropped_total" in body
        assert ("# TYPE copilot_pipeline_spans_open_total counter"
                in body)
    finally:
        server.stop()


def test_gateway_metrics_exposes_bus_gauges():
    from copilot_for_consensus_tpu.services.bootstrap import serve_pipeline

    server = serve_pipeline().start()
    try:
        # Park a message on a routing key nobody consumes → depth shows.
        server.pipeline.broker.publish(
            {"event_type": "report.delivery.failed"},
            "report.delivery.failed")
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics").read().decode()
        assert "copilot_bus_queue_depth" in body
        assert 'queue="report.delivery.failed"' in body
        # Registry ⇄ exposition honesty (the PR-5 equality pattern):
        # every BUS_METRICS family must be present on a live scrape —
        # gauges refreshed per scrape, counters declared at zero — so
        # the alert pack's rate()/deriv() expressions never evaluate
        # over an absent series. copilot_bus_dead_letters is the one
        # exception: its <rk>.dlq gauge only exists once something
        # dead-letters (covered by test_gauge_depths semantics).
        emitted = set(re.findall(r"^(copilot_bus_[a-z_]+)\{?",
                                 body, flags=re.M))
        expected = set(BUS_METRICS) - {"copilot_bus_dead_letters"}
        assert expected <= emitted, sorted(expected - emitted)
        assert emitted <= set(BUS_METRICS), sorted(
            emitted - set(BUS_METRICS))
    finally:
        server.stop()


def test_bus_alert_functions_match_series_types():
    """rate()/increase() need counters; deriv()/delta() need gauges —
    the PR-1 dead-alert bug class, applied to the copilot_bus_* pack."""
    counter_fns = {"rate", "irate", "increase"}
    gauge_fns = {"deriv", "delta", "idelta"}
    fn_re = re.compile(r"\b(rate|irate|increase|deriv|delta|idelta)\s*"
                       r"\(\s*(copilot_bus_[a-z_]+)")
    for f in _alert_files():
        doc = yaml.safe_load(f.read_text())
        for group in doc["groups"]:
            for rule in group["rules"]:
                for fn, series in fn_re.findall(rule["expr"]):
                    typ = BUS_METRICS[series][0]
                    if fn in counter_fns:
                        assert typ == "counter", (f.name, rule["alert"],
                                                  fn, series, typ)
                    if fn in gauge_fns:
                        assert typ == "gauge", (f.name, rule["alert"],
                                                fn, series, typ)


def test_profiler_flag_captures_trace(tmp_path):
    """maybe_profile writes an XLA trace; None is a strict no-op."""
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.obs.profile import maybe_profile

    with maybe_profile(None) as p:
        assert p is None
    trace_dir = tmp_path / "traces"
    with maybe_profile(str(trace_dir)) as p:
        assert p is not None
        (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
    produced = list(trace_dir.rglob("*"))
    assert any(f.is_file() for f in produced), "no trace files written"


def test_engine_profile_dir_plumbing(tmp_path):
    import jax

    from copilot_for_consensus_tpu.engine.generation import GenerationEngine
    from copilot_for_consensus_tpu.models import decoder
    from copilot_for_consensus_tpu.models.configs import decoder_config

    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(0), cfg)
    eng = GenerationEngine(cfg, params, num_slots=2, max_len=64,
                           profile_dir=str(tmp_path / "tr"))
    comps = eng.generate([[5, 6, 7]], max_new_tokens=4)
    assert comps[0].tokens
    assert any(f.is_file() for f in (tmp_path / "tr").rglob("*"))


def test_resource_gauges_on_metrics_exposition():
    """The resource_limits alert group fires on series every service's
    /metrics must actually expose (obs/resources.py gauges)."""
    from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics
    from copilot_for_consensus_tpu.obs.resources import resource_gauges

    m = InMemoryMetrics(namespace="copilot")
    resource_gauges(m)
    body = m.render_prometheus()
    for series in ("copilot_process_resident_bytes",
                   "copilot_process_memory_limit_bytes",
                   "copilot_process_cpu_seconds_total",
                   "copilot_process_open_fds",
                   "copilot_process_start_time_seconds",
                   "copilot_disk_free_bytes", "copilot_disk_total_bytes"):
        assert series in body, series
    # live values, not placeholders: this process HAS memory and fds
    import re as _re

    rss = float(_re.search(
        r"^copilot_process_resident_bytes (\S+)", body, _re.M).group(1))
    fds = float(_re.search(
        r"^copilot_process_open_fds (\S+)", body, _re.M).group(1))
    assert rss > 1e6 and fds >= 3
    # the ratio the memory alerts divide must be computable and sane
    limit = float(_re.search(
        r"^copilot_process_memory_limit_bytes (\S+)", body,
        _re.M).group(1))
    assert limit > rss


# -- cross-process telemetry plane (obs/ship.py, ISSUE 20) ---------------


def test_reserved_labels_collide_loudly_at_registration():
    """proc/role are stamped by the TelemetryAggregator on every merged
    series — a registry that declares them itself would silently alias
    across processes, so check_registry_labels refuses it."""
    from copilot_for_consensus_tpu.obs.metrics import (
        RESERVED_LABELS,
        check_registry_labels,
    )

    for reserved in RESERVED_LABELS:
        bad = {"copilot_x_total": ("counter", (reserved,), "h")}
        with pytest.raises(ValueError, match=reserved):
            check_registry_labels(bad, owner="test")
    # every shipped registry in the repo passes (the import-time call
    # in each module already enforces this; assert it stays true)
    for owner, registry in (
            ("ENGINE_METRICS", ENGINE_METRICS),
            ("BUS_METRICS", BUS_METRICS),
            ("PIPELINE_METRICS", PIPELINE_METRICS),
            ("LIFECYCLE_METRICS", LIFECYCLE_METRICS),
            ("VECTORSTORE_METRICS", VECTORSTORE_METRICS),
            ("SHIP_METRICS", SHIP_METRICS)):
        check_registry_labels(registry, owner=owner)


def test_merged_exposition_has_no_cross_process_type_conflicts():
    """Two processes shipping the SAME series as DIFFERENT types would
    render two contradictory # TYPE lines in the merged scrape — the
    aggregator must refuse; same-typed series from N procs merge into
    one family with proc/role labels."""
    from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics
    from copilot_for_consensus_tpu.obs.ship import TelemetryAggregator

    agg = TelemetryAggregator()
    m1 = InMemoryMetrics(namespace="copilot")
    m1.increment("jobs_total", 3.0, {"q": "a"})
    m2 = InMemoryMetrics(namespace="copilot")
    m2.increment("jobs_total", 2.0, {"q": "a"})
    agg.merge_registry(m1, proc="p1", role="engine")
    agg.merge_registry(m2, proc="p2", role="engine")
    body = agg.render_prometheus()
    assert body.count("# TYPE copilot_jobs_total counter") == 1
    assert 'copilot_jobs_total{proc="p1",q="a",role="engine"} 3' in body
    assert 'copilot_jobs_total{proc="p2",q="a",role="engine"} 2' in body
    # same series shipped as a gauge by a third process: refused loudly
    m3 = InMemoryMetrics(namespace="copilot")
    m3.gauge("jobs_total", 1.0, {"q": "a"})
    with pytest.raises(ValueError, match="type conflict"):
        agg.merge_registry(m3, proc="p3", role="engine")


def test_gateway_metrics_exposes_resource_gauges():
    from copilot_for_consensus_tpu.services.bootstrap import serve_pipeline

    server = serve_pipeline().start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics").read().decode()
        assert "copilot_process_resident_bytes" in body
        assert "copilot_disk_free_bytes" in body
    finally:
        server.stop()
