# Observability pack: alert rules + dashboards as code, bus gauges on the
# gateway /metrics, jax.profiler capture (VERDICT r1 item 9).
import json
import pathlib
import re
import urllib.request

import pytest

yaml = pytest.importorskip(
    "yaml", reason="pyyaml (dev extra) needed for alert-rule linting")

REPO = pathlib.Path(__file__).resolve().parent.parent
ALERTS = REPO / "infra" / "prometheus" / "alerts"
DASHBOARDS = REPO / "infra" / "grafana" / "dashboards"

# Metric families the code actually emits (services/base.py central
# counters + per-service counters + bus gauges + pushgateway self-metric
# + prometheus built-ins). The lint below keeps alert exprs honest.
KNOWN_SERIES = {
    "copilot_ingestion_events_total", "copilot_parsing_events_total",
    "copilot_chunking_events_total", "copilot_embedding_events_total",
    "copilot_orchestrator_events_total",
    "copilot_summarization_events_total",
    "copilot_reporting_events_total",
    # per-stage handle histograms (services/base.py:90)
    "copilot_ingestion_handle_seconds", "copilot_parsing_handle_seconds",
    "copilot_chunking_handle_seconds", "copilot_embedding_handle_seconds",
    "copilot_orchestrator_handle_seconds",
    "copilot_summarization_handle_seconds",
    "copilot_reporting_handle_seconds",
    "copilot_ingestion_archives_total", "copilot_ingestion_dedup_total",
    "copilot_parsing_messages_total", "copilot_chunking_chunks_total",
    "copilot_embedding_chunks_total", "copilot_embedding_batch_seconds",
    "copilot_orchestrator_requests_total",
    "copilot_orchestrator_dedup_total",
    "copilot_summarization_summaries_total",
    "copilot_summarization_latency_seconds",
    "copilot_reporting_reports_total",
    "copilot_bus_queue_depth", "copilot_bus_dead_letters",
    # stats exporter gauges (tools/exporters.py)
    "copilot_collection_documents", "copilot_documents_pending",
    "copilot_vectorstore_vectors", "copilot_vectorstore_dimension",
    "copilot_exporter_scrape_seconds",
    # retry-job pushed metrics (tools/retry_job.py)
    "copilot_retry_requeued_total", "copilot_retry_exhausted_documents",
    "copilot_retry_last_sweep_timestamp", "copilot_retry_sweep_seconds",
    # process/host resource gauges (obs/resources.py)
    "copilot_process_resident_bytes", "copilot_process_memory_limit_bytes",
    "copilot_process_cpu_seconds_total", "copilot_process_open_fds",
    "copilot_process_start_time_seconds",
    "copilot_disk_free_bytes", "copilot_disk_total_bytes",
    "up", "push_time_seconds", "time", "vector", "absent",
}
_SERIES_RE = re.compile(r"\b(copilot_[a-z_]+|up|push_time_seconds)\b")


def _alert_files():
    files = sorted(ALERTS.glob("*.yml"))
    assert len(files) >= 5, "alert pack incomplete"
    return files


def test_alert_rules_parse_and_have_required_fields():
    total = 0
    for f in _alert_files():
        doc = yaml.safe_load(f.read_text())
        for group in doc["groups"]:
            assert group["name"]
            for rule in group["rules"]:
                assert rule["alert"] and rule["expr"], (f.name, rule)
                assert "summary" in rule.get("annotations", {}), rule
                assert "severity" in rule.get("labels", {}), rule
                total += 1
    assert total >= 60, f"only {total} rules"


def test_alert_exprs_reference_real_series():
    """Every metric family an alert references must be one the code
    emits — an alert on a typo'd series never fires and rots silently."""
    for f in _alert_files():
        doc = yaml.safe_load(f.read_text())
        for group in doc["groups"]:
            for rule in group["rules"]:
                for name in _SERIES_RE.findall(rule["expr"]):
                    base = re.sub(r"_(bucket|sum|count)$", "", name)
                    assert base in KNOWN_SERIES, (f.name, rule["alert"],
                                                  name)


def test_dashboards_parse_and_reference_real_series():
    files = sorted(DASHBOARDS.glob("*.json"))
    assert len(files) >= 11, "dashboard pack incomplete"
    uids = set()
    for f in files:
        doc = json.loads(f.read_text())
        assert doc["title"] and doc["panels"], f.name
        assert doc["uid"] not in uids, f"duplicate uid {doc['uid']}"
        uids.add(doc["uid"])
        for panel in doc["panels"]:
            for target in panel.get("targets", []):
                for name in _SERIES_RE.findall(target["expr"]):
                    base = re.sub(r"_(bucket|sum|count)$", "", name)
                    assert base in KNOWN_SERIES, (f.name, panel["title"],
                                                  name)


def test_gateway_metrics_exposes_bus_gauges():
    from copilot_for_consensus_tpu.services.bootstrap import serve_pipeline

    server = serve_pipeline().start()
    try:
        # Park a message on a routing key nobody consumes → depth shows.
        server.pipeline.broker.publish(
            {"event_type": "report.delivery.failed"},
            "report.delivery.failed")
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics").read().decode()
        assert "copilot_bus_queue_depth" in body
        assert 'queue="report.delivery.failed"' in body
    finally:
        server.stop()


def test_profiler_flag_captures_trace(tmp_path):
    """maybe_profile writes an XLA trace; None is a strict no-op."""
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.obs.profile import maybe_profile

    with maybe_profile(None) as p:
        assert p is None
    trace_dir = tmp_path / "traces"
    with maybe_profile(str(trace_dir)) as p:
        assert p is not None
        (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
    produced = list(trace_dir.rglob("*"))
    assert any(f.is_file() for f in produced), "no trace files written"


def test_engine_profile_dir_plumbing(tmp_path):
    import jax

    from copilot_for_consensus_tpu.engine.generation import GenerationEngine
    from copilot_for_consensus_tpu.models import decoder
    from copilot_for_consensus_tpu.models.configs import decoder_config

    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(0), cfg)
    eng = GenerationEngine(cfg, params, num_slots=2, max_len=64,
                           profile_dir=str(tmp_path / "tr"))
    comps = eng.generate([[5, 6, 7]], max_new_tokens=4)
    assert comps[0].tokens
    assert any(f.is_file() for f in (tmp_path / "tr").rglob("*"))


def test_resource_gauges_on_metrics_exposition():
    """The resource_limits alert group fires on series every service's
    /metrics must actually expose (obs/resources.py gauges)."""
    from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics
    from copilot_for_consensus_tpu.obs.resources import resource_gauges

    m = InMemoryMetrics(namespace="copilot")
    resource_gauges(m)
    body = m.render_prometheus()
    for series in ("copilot_process_resident_bytes",
                   "copilot_process_memory_limit_bytes",
                   "copilot_process_cpu_seconds_total",
                   "copilot_process_open_fds",
                   "copilot_process_start_time_seconds",
                   "copilot_disk_free_bytes", "copilot_disk_total_bytes"):
        assert series in body, series
    # live values, not placeholders: this process HAS memory and fds
    import re as _re

    rss = float(_re.search(
        r"^copilot_process_resident_bytes (\S+)", body, _re.M).group(1))
    fds = float(_re.search(
        r"^copilot_process_open_fds (\S+)", body, _re.M).group(1))
    assert rss > 1e6 and fds >= 3
    # the ratio the memory alerts divide must be computable and sane
    limit = float(_re.search(
        r"^copilot_process_memory_limit_bytes (\S+)", body,
        _re.M).group(1))
    assert limit > rss


def test_gateway_metrics_exposes_resource_gauges():
    from copilot_for_consensus_tpu.services.bootstrap import serve_pipeline

    server = serve_pipeline().start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics").read().decode()
        assert "copilot_process_resident_bytes" in body
        assert "copilot_disk_free_bytes" in body
    finally:
        server.stop()
