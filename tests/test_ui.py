# UI lane: the vanilla-JS SPA has no JS runtime in this image, so
# behavior is pinned from three directions — the XSS-escape policy
# scanner over app.js (with seeded-bug effectiveness proofs: dropping
# esc() anywhere fails), a UI↔API contract-sync test (every endpoint
# the SPA calls must exist on the live router), and server-side asset
# integration tests over real sockets. The reference pins the same
# surface with per-route *.test.tsx under a node runtime
# (ui/src/routes/AdminDashboard.test.tsx etc.).
import json
import pathlib
import re
import urllib.request

import pytest

from copilot_for_consensus_tpu.ui import lint

APP_JS = (pathlib.Path(lint.UI_DIR) / "app.js").read_text()


# ---------------------------------------------------------------------------
# XSS-escape policy
# ---------------------------------------------------------------------------


def test_app_js_escape_policy_clean():
    assert lint.unescaped_interpolations(APP_JS) == []


def test_scanner_sees_every_interpolation():
    """The policy is only as good as the scanner's reach: it must find
    every ${...} the source contains (counted lexically)."""
    found = len(lint.template_interpolations(APP_JS))
    # raw count of '${' inside the file minus ones inside ordinary
    # strings/comments is hard to get with grep alone; assert a floor
    # that catches the scanner silently going blind (it found 0 before
    # it learned JS regex literals — this pins that bug class)
    assert found >= 80, found


@pytest.mark.parametrize("snippet", [
    # esc() dropped from an innerHTML interpolation
    "render(`<h2>${r.subject}</h2>`);",
    # element-wise escape dropped from a joined list
    "render(`<p>${(x.participants || []).map(String).join(', ')}</p>`);",
    # new unescaped data in an attribute
    'list.innerHTML = `<a href="#/x/${item.id}">go</a>`;',
    # nested template whose INNER interpolation is unescaped
    "render(`<ul>${xs.map((x) => `<li>${x.name}</li>`).join('')}</ul>`);",
    # COMPOUND bypass attempts (r4 review): a safe fragment must not
    # bless the unsafe terminal riding alongside it
    "render(`<h2>${esc(r.subject) + r.bio}</h2>`);",
    "render(`<p>${r.bio + xs.map(esc).join(', ')}</p>`);",
    "render(`<p>${ok ? `<b>${esc(a)}</b>` : r.subject}</p>`);",
])
def test_scanner_catches_seeded_xss(snippet):
    assert lint.unescaped_interpolations(snippet), snippet


@pytest.mark.parametrize("snippet", [
    "render(`<h2>${esc(r.subject)}</h2>`);",
    "render(`<p>${(x.participants || []).map(esc).join(', ')}</p>`);",
    "api(`/api/reports/${encodeURIComponent(id)}`);",
    "render(`<ul>${xs.map((x) => `<li>${esc(x.name)}</li>`).join('')}</ul>`);",
])
def test_scanner_allows_escaped_forms(snippet):
    assert lint.unescaped_interpolations(snippet) == []


def test_tokenizer_survives_regex_comments_and_nesting():
    """The walker must stay in sync across the constructs that made a
    naive scanner go blind (JS regex literals, comments, nesting)."""
    src = (
        "const re = /[&<>\"'`]/g; // trailing ` in regex and comment `\n"
        "/* block with ` backtick */\n"
        "const a = `outer ${inner ? `mid ${esc(deep)}` : ''} tail`;\n"
    )
    exprs = [e for _, e in lint.template_interpolations(src)]
    assert any("esc(deep)" in e for e in exprs)
    assert any(e.startswith("inner ?") for e in exprs)


def test_esc_function_covers_html_metacharacters():
    """esc() itself must keep escaping all five metacharacters — the
    scanner trusts it."""
    m = re.search(r"function esc\(s\) \{\n(.+?)\n\}", APP_JS, re.S)
    assert m, "esc() definition moved"
    body = m.group(1)
    for ch in ["&amp;", "&lt;", "&gt;", "&quot;", "&#39;"]:
        assert ch in body, f"esc() no longer emits {ch}"


# ---------------------------------------------------------------------------
# UI ↔ API contract sync
# ---------------------------------------------------------------------------


def _ui_api_calls() -> set[tuple[str, str]]:
    """(method, path-pattern) for every api(...) call in app.js, with
    interpolations normalized to {param} and query strings dropped."""
    calls = set()
    for m in re.finditer(
            r'api\(\s*(`([^`]*)`|"([^"]*)")'
            r'(?:\s*\+[^,)]*)?'            # string concatenation tails
            r'(?:,\s*\{\s*method:\s*"(\w+)")?', APP_JS):
        path = m.group(2) or m.group(3) or ""
        method = m.group(4) or "GET"
        path = re.sub(r"\$\{[^}]*\}", "{p}", path)
        path = path.split("?")[0]
        if not path.startswith("/"):
            continue
        calls.add((method, path))
    return calls


def test_every_ui_call_exists_on_the_router():
    """Route drift protection: each endpoint the SPA references must be
    servable by the live router (the reference gets this from typed API
    clients; here the contract is tested)."""
    from copilot_for_consensus_tpu.services.bootstrap import serve_pipeline

    srv = serve_pipeline({"auth": {
        "signer": {"driver": "hs256", "secret": "ui-test"},
        "providers": {"mock": {}}, "allow_insecure_mock": True,
    }})
    table = [(m, re.sub(r"\{\w+\}", "{p}", pattern))
             for m, pattern, _ in srv.http.router.route_table]
    missing = [(m, p) for m, p in _ui_api_calls()
               if not any(m == tm and p == tp for tm, tp in table)]
    assert not missing, f"SPA calls endpoints the router lacks: {missing}"
    assert len(_ui_api_calls()) >= 20   # reach: the SPA's full surface


# ---------------------------------------------------------------------------
# Server-side integration (real sockets)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    from copilot_for_consensus_tpu.services.bootstrap import serve_pipeline

    srv = serve_pipeline({"auth": {
        "signer": {"driver": "hs256", "secret": "ui-test"},
        "providers": {"mock": {}}, "allow_insecure_mock": True,
    }}).start()
    yield srv
    srv.stop()


def _get(port, path):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type",
                                             ""), resp.read()


def test_spa_shell_and_assets_served(server):
    status, ctype, body = _get(server.port, "/")
    assert status == 200 and ctype.startswith("text/html")
    assert b'src="/ui/app.js"' in body or b"src=/ui/app.js" in body
    status, ctype, body = _get(server.port, "/ui/app.js")
    assert status == 200 and "javascript" in ctype
    assert b"function esc(" in body
    status, ctype, _ = _get(server.port, "/ui/style.css")
    assert status == 200 and ctype.startswith("text/css")


def test_hostile_asset_names_404_not_500(server):
    import urllib.error

    for name in ("%2e%2e%2fsecrets", "..%2f..%2fetc%2fpasswd", "%00",
                 "app.js%00.html",
                 # >NAME_MAX component: stat() raises ENAMETOOLONG,
                 # which must read as absent (r5 deep-fuzz find)
                 "A" * 300):
        try:
            status, _, _ = _get(server.port, f"/ui/{name}")
        except urllib.error.HTTPError as exc:
            status = exc.code
        assert status == 404, (name, status)


def test_hostile_report_content_survives_api_roundtrip(server):
    """The API must deliver hostile content VERBATIM as JSON (escaping
    is the SPA's job at render time, enforced by the policy scanner) —
    double-escaping server-side would corrupt legitimate content."""
    payload = "<script>alert(1)</script> & 'quotes' \"too\""
    server.pipeline.store.insert_document("reports", {
        "report_id": "r-xss", "summary_id": "s-xss",
        "thread_id": "t-xss",
        "subject": payload, "summary_text": payload,
        "status": "published", "published_at": "2026-07-31T00:00:00Z",
    })
    # login for the API call
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/auth/login?provider=mock",
            timeout=10) as r:
        state = json.loads(r.read())["state"]
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/auth/callback?state={state}"
            "&code=mock:reader@example.org", timeout=10) as r:
        tok = json.loads(r.read())["access_token"]
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/api/reports/r-xss",
        headers={"Authorization": f"Bearer {tok}"})
    with urllib.request.urlopen(req, timeout=10) as r:
        body = json.loads(r.read())
    assert body["subject"] == payload
    assert body["summary_text"] == payload


def test_safe_expr_rot_guard():
    """Every hand-audited SAFE_EXPR allowlist entry must still match
    something in app.js — a stale entry silently widens the unscanned
    surface as the app grows (r4 verdict, Weak 6). And the guard must
    actually detect rot: scanning a source that uses none of the
    allowlist leaves every entry stale."""
    assert lint.unescaped_interpolations(APP_JS) == []
    assert lint.unused_safe_entries() == []
    # seeded rot: a scan over allowlist-free source flags every entry
    # (each scan resets the hit set — the guard reports the LAST scan)
    lint.unescaped_interpolations("const x = `a ${esc(v)} b`;")
    assert len(lint.unused_safe_entries()) == len(lint.SAFE_EXPR)


def test_node_lane_files_consistent():
    """The ui-ci node lane can't run in this image (no node) — pin its
    wiring statically so a rename/typo can't silently empty the lane:
    package.json is valid JSON with a test script, the vitest config
    include-glob matches the committed test files, and the workflow
    drives the right directory."""
    ui_dir = pathlib.Path(lint.UI_DIR)
    pkg = json.loads((ui_dir / "package.json").read_text())
    assert pkg["scripts"]["test"].startswith("vitest")
    assert "vitest" in pkg["devDependencies"]
    assert "jsdom" in pkg["devDependencies"]
    tests = sorted((ui_dir / "tests").glob("*.test.js"))
    assert len(tests) >= 3, "behavioral suites missing"
    helpers = (ui_dir / "tests" / "helpers.js").read_text()
    assert "../app.js" in helpers          # harness boots the real SPA
    cfg = (ui_dir / "vitest.config.js").read_text()
    assert "tests/**/*.test.js" in cfg and "jsdom" in cfg
    wf = (ui_dir.parents[1] / ".github" / "workflows"
          / "ui-ci.yml").read_text()
    assert "copilot_for_consensus_tpu/ui" in wf and "npm test" in wf
