# The semantic static-analysis lane (shardcheck) must stay green AND
# keep catching what it claims to catch: every rule is proven against a
# fixture corpus (one true positive + one clean negative), and the
# tripwire tests prove the canonical engine mutations — a mesh-axis
# typo, a KV-cache dtype mismatch, a shape-mismatched donated arg —
# turn the lane red. Same spirit as test_static_analysis.py for the
# syntactic groups.
import json
import pathlib
import subprocess
import sys
import textwrap

import pytest

from copilot_for_consensus_tpu.analysis import (
    RULES as CLI_RULES,
    main as jaxlint_main,
)
from copilot_for_consensus_tpu.analysis import shardcheck
from copilot_for_consensus_tpu.analysis.base import rel

ROOT = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = ROOT / "tests" / "fixtures" / "shardcheck"


def _findings(fixture: str, rule: str):
    findings, _, skips = shardcheck.check_modules([str(FIXTURES / fixture)])
    assert skips == [], skips       # conftest provides 8 virtual devices
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------------------
# fixture corpus: one true positive + one clean negative per rule
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fixture,rule,bad_marker,good_marker", [
    ("rule_axis.py", "shard-rule-axis", "bad_rule_axis",
     "good_rule_axis"),
    ("divisibility.py", "shard-divisibility", "bad_divisibility",
     "good_divisibility"),
    ("collective.py", "shard-collective", "bad_collective",
     "good_collective"),
    ("donation.py", "shard-donation", "bad_donation", "good_donation"),
    ("kv_layout.py", "shard-kv-layout", "bad_kv_layout",
     "good_kv_layout"),
    ("bucket.py", "shard-bucket", "bad_bucket", "good_bucket"),
])
def test_rule_true_positive_and_clean_negative(fixture, rule,
                                               bad_marker, good_marker):
    found = _findings(fixture, rule)
    assert any(bad_marker in f.context for f in found), (rule, found)
    assert not any(good_marker in f.context for f in found), (rule, found)


def test_collective_finding_names_the_bad_axis():
    found = _findings("collective.py", "shard-collective")
    assert any("model" in f.message for f in found), found


def test_divisibility_finding_names_dim_and_mesh_size():
    (f,) = _findings("divisibility.py", "shard-divisibility")
    assert "dim 1 (6)" in f.message and "size 4" in f.message


def test_inline_suppression_honored(tmp_path):
    """A `# jaxlint: disable=<rule>` comment above the factory def
    covers every finding the contract emits."""
    mod = tmp_path / "suppressed.py"
    mod.write_text(textwrap.dedent("""\
        from copilot_for_consensus_tpu.analysis.contracts import (
            ContractCase, contract,
        )


        # deliberate: fixture proving inline suppression
        # jaxlint: disable=shard-bucket
        def bad_bucket():
            return ContractCase(buckets=(64,), bucket_covers=(256,))


        SHARDCHECK_CONTRACTS = [contract("bad_bucket", bad_bucket)]
        """))
    findings, _, _ = shardcheck.check_modules([str(mod)])
    assert findings == [], findings


def test_broken_factory_is_a_contract_finding(tmp_path):
    """The registry must not rot silently: a factory that raises (or a
    module with no table) is itself a finding."""
    mod = tmp_path / "broken.py"
    mod.write_text(textwrap.dedent("""\
        from copilot_for_consensus_tpu.analysis.contracts import contract


        def boom():
            raise RuntimeError("factory exploded")


        SHARDCHECK_CONTRACTS = [contract("boom", boom)]
        """))
    findings, _, _ = shardcheck.check_modules([str(mod)])
    assert any(f.rule == "shard-contract" and "factory exploded"
               in f.message for f in findings), findings
    empty = tmp_path / "empty.py"
    empty.write_text("X = 1\n")
    findings, _, _ = shardcheck.check_modules([str(empty)])
    assert any(f.rule == "shard-contract"
               and "no SHARDCHECK_CONTRACTS" in f.message
               for f in findings), findings


# ---------------------------------------------------------------------------
# regression tripwires on the REAL modules: the three mutations the
# acceptance criteria name must turn the lane red.
# ---------------------------------------------------------------------------

_GEN = ROOT / "copilot_for_consensus_tpu" / "engine" / "generation.py"
_ULY = ROOT / "copilot_for_consensus_tpu" / "parallel" / "ulysses.py"


def _mutated_findings(tmp_path, src_path, needle, replacement, stem):
    src = src_path.read_text()
    assert needle in src, f"{src_path.name} moved; update the test"
    mutated = tmp_path / f"{stem}.py"
    mutated.write_text(src.replace(needle, replacement, 1))
    findings, _, skips = shardcheck.check_modules([str(mutated)])
    assert skips == [], skips
    return findings


def test_mesh_axis_typo_in_ulysses_fails_the_lane(tmp_path):
    """Typo the module's default sequence axis: the shard_map specs and
    all_to_all collectives bind an axis no mesh has."""
    findings = _mutated_findings(
        tmp_path, _ULY, 'axis: str = "sp",', 'axis: str = "sq",',
        "ulysses_mutated")
    assert any(f.rule == "shard-collective" and "sq" in f.message
               for f in findings), findings


def test_kv_dtype_mismatch_in_generation_fails_the_lane(tmp_path):
    """Build the slot cache in a different dtype than the prefix pool:
    the five engine programs no longer share one KV-cache layout."""
    needle = ("            cache = decoder.init_cache(cfg, num_slots, "
              "self.max_len,\n"
              "                                       "
              "dtype=self.kv_dtype)")
    findings = _mutated_findings(
        tmp_path, _GEN, needle,
        needle.replace("dtype=self.kv_dtype", "dtype=jnp.float32"),
        "generation_kvdtype_mutated")
    assert any(f.rule == "shard-kv-layout" for f in findings), findings


def test_block_table_dtype_flip_fails_the_lane(tmp_path):
    """Flip the paged dispatches' declared block-table dtype: the
    ``engine.generation-kv-table`` layout group no longer agrees with
    the canonical ``kv_pool.BLOCK_TABLE_DTYPE`` anchor — the drift
    class where host-built tables and the kernel's scalar-prefetch
    spec stop describing the same indirection."""
    needle = ("    table_dtype = jnp.int32       "
              "# dispatch-side block-table dtype")
    findings = _mutated_findings(
        tmp_path, _GEN, needle,
        needle.replace("jnp.int32", "jnp.int16"),
        "generation_tabledtype_mutated")
    assert any(f.rule == "shard-kv-layout"
               and "engine.generation-kv-table" in f.message
               for f in findings), findings


def test_kernel_block_pack_flip_fails_the_lane(tmp_path):
    """Flip the dispatch side's declared lane packing: the
    ``engine.generation-kv-pack`` layout group no longer agrees with
    the kernel's ``KERNEL_BLOCK_PACK`` anchor (and the pool's
    ``POOL_BLOCK_PACK``) — the drift class where the engine's
    128-aligned kv buckets and the kernel's BlockSpec packing stop
    describing the same block layout."""
    needle = ("    block_pack = 128              "
              "# dispatch-side kernel lane packing")
    findings = _mutated_findings(
        tmp_path, _GEN, needle,
        needle.replace("= 128", "= 64"),
        "generation_blockpack_mutated")
    assert any(f.rule == "shard-kv-layout"
               and "engine.generation-kv-pack" in f.message
               for f in findings), findings


def test_shape_mismatched_donated_arg_fails_the_lane(tmp_path):
    """Cast the admit program's cache output: the donated cache buffer
    no longer has a matching output, so XLA would drop the alias."""
    findings = _mutated_findings(
        tmp_path, _GEN, '            return {"k": k, "v": v}',
        '            return {"k": k.astype(jnp.float32), '
        '"v": v.astype(jnp.float32)}',
        "generation_donation_mutated")
    assert any(f.rule == "shard-donation" for f in findings), findings


def test_cast_verify_cache_output_fails_the_lane(tmp_path):
    """Cast the speculative verify dispatch's cache output: its donated
    slot cache loses the aliasable output and the verify program would
    double-allocate the cache every dispatch."""
    needle = "            return out, n_accept, cache"
    findings = _mutated_findings(
        tmp_path, _GEN, needle,
        "            return out, n_accept, jax.tree.map("
        "lambda x: x.astype(jnp.float32), cache)",
        "generation_verify_mutated")
    assert any(f.rule == "shard-donation"
               and "generation-engine:verify" in f.context
               for f in findings), findings


def test_generation_contract_declares_verify_entrypoint():
    """The acceptance contract for speculative decoding: the _verify
    program is registered with the cache donation, rides the one
    engine KV-layout group, and its token-width bucket table covers
    every declared draft length (so the shardcheck preflight guards
    the spec_decode bench preset)."""
    from copilot_for_consensus_tpu.engine import generation

    con = next(c for c in generation.SHARDCHECK_CONTRACTS
               if c.name == "generation-engine")
    cases = {c.label: c for c in con.factory()}
    assert "verify" in cases, sorted(cases)
    vc = cases["verify"]
    assert tuple(vc.donate_argnums) == (4,)
    assert vc.kv_group == "engine.generation-kv"
    assert vc.buckets and max(vc.bucket_covers) <= max(vc.buckets)


# ---------------------------------------------------------------------------
# the real registry is clean, and the CLI glue holds
# ---------------------------------------------------------------------------


def test_registry_contracts_clean():
    """Every registered contract module traces clean — the in-process
    equivalent of `python -m copilot_for_consensus_tpu.analysis` running
    the shard group green under JAX_PLATFORMS=cpu."""
    findings, checked, skips = shardcheck.check_modules()
    assert findings == [], [f.render() for f in findings]
    assert len(checked) == len(
        __import__("copilot_for_consensus_tpu.analysis.contracts",
                   fromlist=["CONTRACT_MODULES"]).CONTRACT_MODULES)
    assert skips == [], skips


def test_cli_shard_group_subprocess_clean():
    """The worker subprocess route (what CI and bench preflight use)
    comes up with the virtual device platform and reports clean."""
    proc = subprocess.run(
        [sys.executable, "-m",
         "copilot_for_consensus_tpu.analysis.shardcheck", "--json"],
        cwd=ROOT, capture_output=True, text=True, timeout=600)
    data = json.loads(proc.stdout.strip().splitlines()[-1])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert data["findings"] == [] and data["skips"] == []
    assert len(data["checked"]) >= 9


def test_cli_rules_table_in_sync():
    shard_rules = {r for r, g in CLI_RULES.items() if g == "shard"}
    assert shard_rules == set(shardcheck.RULES)


def test_worker_baseline_silences_finding(tmp_path, capsys):
    """A justified baseline entry matching a shard finding silences it
    through the worker's --baseline route (what bench preflight
    passes)."""
    findings, _, _ = shardcheck.check_modules(
        [str(FIXTURES / "bucket.py")])
    bad = [f for f in findings if f.rule == "shard-bucket"]
    assert bad
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([
        {"rule": f.rule, "path": f.path, "context": f.context,
         "message": f.message,
         "justification": "fixture: deliberately uncovered bucket"}
        for f in bad]))
    rc = shardcheck.main(["--modules", str(FIXTURES / "bucket.py"),
                          "--baseline", str(bl), "--json"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["findings"] == []


# ---------------------------------------------------------------------------
# CLI satellites: --format=github, --strict
# ---------------------------------------------------------------------------


def test_format_github_annotations(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import json\nimport os\nprint(os.name)\n")
    rc = jaxlint_main(["--rules", "policy", "--no-baseline",
                       "--format=github", str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "::error file=" in out and "policy-unused-import" in out


def test_output_json_artifact(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import json\nimport os\nprint(os.name)\n")
    artifact = tmp_path / "findings.json"
    rc = jaxlint_main(["--rules", "policy", "--no-baseline",
                       "--output-json", str(artifact), str(bad)])
    capsys.readouterr()
    assert rc == 1
    data = json.loads(artifact.read_text())
    assert any(f["rule"] == "policy-unused-import"
               for f in data["findings"])


def test_skipped_shard_group_does_not_judge_shard_baseline(tmp_path,
                                                           capsys):
    """A run that SKIPS the semantic pass (--fast / explicit paths)
    produces no shard findings, so it must not judge shard baseline
    entries — a still-valid entry would otherwise be reported stale
    (and fail under --strict)."""
    ok = tmp_path / "ok.py"
    ok.write_text("import os\nprint(os.name)\n")
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([
        {"rule": "shard-kv-layout", "path": rel(ok),
         "context": "some-contract", "message": "m",
         "justification": "entry only the full semantic run can judge"}]))
    rc = jaxlint_main(["--fast", "--strict", "--baseline", str(bl),
                       str(ok)])
    out = capsys.readouterr().out
    assert rc == 0 and "stale" not in out, out


def test_strict_turns_stale_baseline_into_failure(tmp_path, capsys):
    ok = tmp_path / "ok.py"
    ok.write_text("import os\nprint(os.name)\n")
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([
        {"rule": "policy-unused-import", "path": rel(ok), "context": "",
         "message": "unused import 'gone'",
         "justification": "entry that matches nothing any more"}]))
    rc = jaxlint_main(["--rules", "policy", "--baseline", str(bl),
                       str(ok)])
    capsys.readouterr()
    assert rc == 0                      # stale only warns by default
    rc = jaxlint_main(["--rules", "policy", "--baseline", str(bl),
                       "--strict", str(ok)])
    out = capsys.readouterr().out
    assert rc == 1 and "stale baseline entry" in out


# ---------------------------------------------------------------------------
# bench preflight: contract violations fail fast with the rc-2/ok:false
# artifact (matching the unknown-BENCH_PRESET behavior)
# ---------------------------------------------------------------------------


def test_bench_preset_contract_modules_cover_every_preset():
    """Every bench preset must have an explicit contract-module list —
    a new preset silently falling back to the generation-only default
    would lose e.g. prefix-cache preflight coverage."""
    sys.path.insert(0, str(ROOT))
    try:
        import bench
    finally:
        sys.path.remove(str(ROOT))
    assert set(bench.PRESET_CONTRACT_MODULES) == \
        set(bench.PRESETS) | {""}


def test_bench_preflight_blocks_on_contract_violation():
    proc = subprocess.run(
        [sys.executable, str(ROOT / "bench.py")],
        cwd=ROOT, capture_output=True, text=True, timeout=600,
        env={**__import__("os").environ,
             "BENCH_PREFLIGHT": "1",
             "BENCH_NO_PROBE": "1",
             "BENCH_EXTRA": "0",
             "BENCH_PRESET": "",
             "BENCH_SHARDCHECK_MODULES":
                 str(FIXTURES / "donation.py")})
    assert proc.returncode == 2, proc.stdout + proc.stderr
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["ok"] is False
    assert "shardcheck preflight failed" in line["reason"]
    assert any("shard-donation" in f for f in line["findings"])


DURA_FIXTURES = ROOT / "tests" / "fixtures" / "duracheck"


def test_bench_dura_preflight_blocks_on_violation():
    """pipeline_chaos maps to no jitted entrypoints (shardcheck
    skips), so the dura family is its gate: pointed at the violating
    fixture corpus, the bench must exit 2 with the same rc-2/ok:false
    artifact contract before the storm starts."""
    proc = subprocess.run(
        [sys.executable, str(ROOT / "bench.py")],
        cwd=ROOT, capture_output=True, text=True, timeout=600,
        env={**__import__("os").environ,
             "BENCH_PREFLIGHT": "1",
             "BENCH_NO_PROBE": "1",
             "BENCH_EXTRA": "0",
             "BENCH_PRESET": "pipeline_chaos",
             "BENCH_DURACHECK_PATHS": "tests/fixtures/duracheck"})
    assert proc.returncode == 2, proc.stdout + proc.stderr
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["ok"] is False
    assert "duracheck preflight failed" in line["reason"]
    assert any("dura-" in f for f in line["findings"])


def test_scale_bench_gates_on_dura_preflight():
    """The host-pipeline driver (scripts/scale_bench.py) runs the same
    gate over bus/ + services/ before building the pipeline."""
    proc = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "scale_bench.py")],
        cwd=ROOT, capture_output=True, text=True, timeout=600,
        env={**__import__("os").environ,
             "BENCH_PREFLIGHT": "1",
             "BENCH_DURACHECK_PATHS": "tests/fixtures/duracheck"})
    assert proc.returncode == 2, proc.stdout + proc.stderr
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["ok"] is False
    assert "duracheck preflight failed" in line["reason"]


def test_dura_preflight_opt_out_and_preset_map(monkeypatch):
    """BENCH_PREFLIGHT=0 skips even with violating paths pinned; the
    pipeline_chaos preset map resolves to the live bus/services planes
    (which must pass their own gate); engine presets map to no dura
    paths and skip."""
    sys.path.insert(0, str(ROOT))
    try:
        import bench
    finally:
        sys.path.remove(str(ROOT))
    monkeypatch.setenv("BENCH_PREFLIGHT", "0")
    monkeypatch.setenv("BENCH_DURACHECK_PATHS",
                       "tests/fixtures/duracheck")
    assert bench.duracheck_preflight() is None
    monkeypatch.setenv("BENCH_PREFLIGHT", "1")
    monkeypatch.delenv("BENCH_DURACHECK_PATHS")
    monkeypatch.setenv("BENCH_PRESET", "rag2k")
    assert bench.duracheck_preflight() is None
    monkeypatch.setenv("BENCH_PRESET", "pipeline_chaos")
    assert bench.duracheck_preflight() is None   # live planes CLEAN


def test_mesh_scatter_out_spec_flip_fails_the_lane(tmp_path):
    """Flip the mesh scatter's pool out_specs to replicated: the
    shard_map returns a shard-local-shaped pool as the global result,
    so the donated pool halves lose their shape-matching outputs —
    exactly the pool-PartitionSpec drift the sharded dispatches must
    never ship (ISSUE 15 tripwire)."""
    needle = ("                    in_specs=(POOL, POOL, VIEW, VIEW, "
              "ROW2, ROW2),\n"
              "                    out_specs=(POOL, POOL),")
    findings = _mutated_findings(
        tmp_path, _GEN, needle,
        needle.replace(
            "out_specs=(POOL, POOL),",
            "out_specs=(P(None, None, None, None, None),\n"
            "                               "
            "P(None, None, None, None, None)),"),
        "generation_mesh_poolspec_mutated")
    assert any(f.rule == "shard-donation"
               and "paged-mesh" in f.context
               for f in findings), findings


def test_mesh_handoff_import_dropped_donation_fails_the_lane(tmp_path):
    """Cast the KV-handoff import's pool outputs: the donated pool
    halves no longer alias and every handoff would double-buffer the
    whole decode pool."""
    needle = "                return pk, pv\n\n            " \
             "self._import_fn = jax.jit(_import_kv,"
    findings = _mutated_findings(
        tmp_path, _GEN, needle,
        needle.replace(
            "                return pk, pv",
            "                return pk.astype(jnp.float16), "
            "pv.astype(jnp.float16)"),
        "generation_handoff_donation_mutated")
    assert any(f.rule == "shard-donation"
               and "kv-handoff-import" in f.context
               for f in findings), findings
