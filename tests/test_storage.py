import pytest

from copilot_for_consensus_tpu.core.validation import SchemaValidationError
from copilot_for_consensus_tpu.storage import (
    DuplicateKeyError,
    InMemoryDocumentStore,
    SQLiteDocumentStore,
    ValidatingDocumentStore,
    create_document_store,
    matches_filter,
)


@pytest.fixture(params=["memory", "sqlite"])
def store(request, tmp_path):
    if request.param == "memory":
        yield InMemoryDocumentStore()
    else:
        s = SQLiteDocumentStore({"path": str(tmp_path / "docs.sqlite3")})
        yield s
        s.close()


def _chunk(cid, thread="t1", embedded=False, tokens=100):
    return {"chunk_id": cid, "message_doc_id": "m1", "thread_id": thread,
            "text": "hello", "token_count": tokens,
            "embedding_generated": embedded}


def test_insert_get_roundtrip(store):
    store.insert_document("chunks", _chunk("c1"))
    doc = store.get_document("chunks", "c1")
    assert doc["thread_id"] == "t1"


def test_duplicate_key_raises_and_insert_or_ignore(store):
    store.insert_document("chunks", _chunk("c1"))
    with pytest.raises(DuplicateKeyError):
        store.insert_document("chunks", _chunk("c1"))
    assert store.insert_or_ignore("chunks", _chunk("c1")) is False
    assert store.insert_or_ignore("chunks", _chunk("c2")) is True


def test_query_filters(store):
    store.insert_document("chunks", _chunk("c1", embedded=True, tokens=50))
    store.insert_document("chunks", _chunk("c2", embedded=False, tokens=200))
    store.insert_document("chunks", _chunk("c3", thread="t2", tokens=300))
    assert {d["chunk_id"] for d in store.query_documents(
        "chunks", {"embedding_generated": False})} == {"c2", "c3"}
    assert [d["chunk_id"] for d in store.query_documents(
        "chunks", {"token_count": {"$gte": 200}},
        sort=[("token_count", -1)])] == ["c3", "c2"]
    assert store.count_documents(
        "chunks", {"thread_id": {"$in": ["t2"]}}) == 1
    assert store.count_documents(
        "chunks", {"$or": [{"chunk_id": "c1"}, {"chunk_id": "c3"}]}) == 2


def test_update_and_delete(store):
    store.insert_document("chunks", _chunk("c1"))
    assert store.update_document("chunks", "c1",
                                 {"embedding_generated": True}) is True
    assert store.get_document("chunks", "c1")["embedding_generated"] is True
    assert store.update_document("chunks", "missing", {"x": 1}) is False
    assert store.delete_document("chunks", "c1") is True
    assert store.get_document("chunks", "c1") is None


def test_delete_many_and_pagination(store):
    for i in range(10):
        store.insert_document("chunks", _chunk(f"c{i}", tokens=i))
    page = store.query_documents("chunks", sort=[("token_count", 1)],
                                 limit=3, skip=3)
    assert [d["chunk_id"] for d in page] == ["c3", "c4", "c5"]
    assert store.delete_documents("chunks", {"token_count": {"$lt": 5}}) == 5
    assert store.count_documents("chunks") == 5


def test_sqlite_persistence(tmp_path):
    path = str(tmp_path / "persist.sqlite3")
    s1 = SQLiteDocumentStore({"path": path})
    s1.insert_document("threads", {"thread_id": "t1", "subject": "QUIC"})
    s1.close()
    s2 = SQLiteDocumentStore({"path": path})
    assert s2.get_document("threads", "t1")["subject"] == "QUIC"
    s2.close()


def test_validating_store_rejects_bad_docs():
    store = ValidatingDocumentStore(InMemoryDocumentStore())
    with pytest.raises(SchemaValidationError):
        store.insert_document("chunks", {"chunk_id": "c1"})  # missing required
    store.insert_document("chunks", _chunk("c1"))
    with pytest.raises(SchemaValidationError):
        store.update_document("chunks", "c1", {"token_count": "NaN"})
    # unknown collections pass through
    store.insert_document("scratch", {"_id": "x", "anything": True})


def test_factory_dispatch(tmp_path):
    s = create_document_store({"driver": "sqlite",
                               "path": str(tmp_path / "f.sqlite3")})
    assert isinstance(s, ValidatingDocumentStore)
    with pytest.raises(ValueError):
        create_document_store({"driver": "mongodb"})


def test_matches_filter_edge_cases():
    doc = {"a": {"b": 3}, "s": "draft-ietf-quic-http-34"}
    assert matches_filter(doc, {"a.b": 3})
    assert matches_filter(doc, {"a.b": {"$lt": 4}})
    assert matches_filter(doc, {"s": {"$regex": r"draft-[a-z]+-quic"}})
    assert not matches_filter(doc, {"missing": {"$exists": True}})
    assert matches_filter(doc, {"missing": {"$exists": False}})
    assert matches_filter(doc, {"missing": {"$ne": 5}})
