import pytest

from copilot_for_consensus_tpu.core.validation import SchemaValidationError
from copilot_for_consensus_tpu.storage import (
    DuplicateKeyError,
    InMemoryDocumentStore,
    SQLiteDocumentStore,
    ValidatingDocumentStore,
    create_document_store,
    matches_filter,
)


@pytest.fixture(params=["memory", "sqlite"])
def store(request, tmp_path):
    if request.param == "memory":
        yield InMemoryDocumentStore()
    else:
        s = SQLiteDocumentStore({"path": str(tmp_path / "docs.sqlite3")})
        yield s
        s.close()


def _chunk(cid, thread="t1", embedded=False, tokens=100):
    return {"chunk_id": cid, "message_doc_id": "m1", "thread_id": thread,
            "text": "hello", "token_count": tokens,
            "embedding_generated": embedded}


def test_insert_get_roundtrip(store):
    store.insert_document("chunks", _chunk("c1"))
    doc = store.get_document("chunks", "c1")
    assert doc["thread_id"] == "t1"


def test_duplicate_key_raises_and_insert_or_ignore(store):
    store.insert_document("chunks", _chunk("c1"))
    with pytest.raises(DuplicateKeyError):
        store.insert_document("chunks", _chunk("c1"))
    assert store.insert_or_ignore("chunks", _chunk("c1")) is False
    assert store.insert_or_ignore("chunks", _chunk("c2")) is True


def test_query_filters(store):
    store.insert_document("chunks", _chunk("c1", embedded=True, tokens=50))
    store.insert_document("chunks", _chunk("c2", embedded=False, tokens=200))
    store.insert_document("chunks", _chunk("c3", thread="t2", tokens=300))
    assert {d["chunk_id"] for d in store.query_documents(
        "chunks", {"embedding_generated": False})} == {"c2", "c3"}
    assert [d["chunk_id"] for d in store.query_documents(
        "chunks", {"token_count": {"$gte": 200}},
        sort=[("token_count", -1)])] == ["c3", "c2"]
    assert store.count_documents(
        "chunks", {"thread_id": {"$in": ["t2"]}}) == 1
    assert store.count_documents(
        "chunks", {"$or": [{"chunk_id": "c1"}, {"chunk_id": "c3"}]}) == 2


def test_update_and_delete(store):
    store.insert_document("chunks", _chunk("c1"))
    assert store.update_document("chunks", "c1",
                                 {"embedding_generated": True}) is True
    assert store.get_document("chunks", "c1")["embedding_generated"] is True
    assert store.update_document("chunks", "missing", {"x": 1}) is False
    assert store.delete_document("chunks", "c1") is True
    assert store.get_document("chunks", "c1") is None


def test_delete_many_and_pagination(store):
    for i in range(10):
        store.insert_document("chunks", _chunk(f"c{i}", tokens=i))
    page = store.query_documents("chunks", sort=[("token_count", 1)],
                                 limit=3, skip=3)
    assert [d["chunk_id"] for d in page] == ["c3", "c4", "c5"]
    assert store.delete_documents("chunks", {"token_count": {"$lt": 5}}) == 5
    assert store.count_documents("chunks") == 5


def test_sqlite_persistence(tmp_path):
    path = str(tmp_path / "persist.sqlite3")
    s1 = SQLiteDocumentStore({"path": path})
    s1.insert_document("threads", {"thread_id": "t1", "subject": "QUIC"})
    s1.close()
    s2 = SQLiteDocumentStore({"path": path})
    assert s2.get_document("threads", "t1")["subject"] == "QUIC"
    s2.close()


def test_validating_store_rejects_bad_docs():
    store = ValidatingDocumentStore(InMemoryDocumentStore())
    with pytest.raises(SchemaValidationError):
        store.insert_document("chunks", {"chunk_id": "c1"})  # missing required
    store.insert_document("chunks", _chunk("c1"))
    with pytest.raises(SchemaValidationError):
        store.update_document("chunks", "c1", {"token_count": "NaN"})
    # unknown collections pass through
    store.insert_document("scratch", {"_id": "x", "anything": True})


def test_factory_dispatch(tmp_path):
    s = create_document_store({"driver": "sqlite",
                               "path": str(tmp_path / "f.sqlite3")})
    assert isinstance(s, ValidatingDocumentStore)
    with pytest.raises(ValueError):
        create_document_store({"driver": "mongodb"})


def test_matches_filter_edge_cases():
    doc = {"a": {"b": 3}, "s": "draft-ietf-quic-http-34"}
    assert matches_filter(doc, {"a.b": 3})
    assert matches_filter(doc, {"a.b": {"$lt": 4}})
    assert matches_filter(doc, {"s": {"$regex": r"draft-[a-z]+-quic"}})
    assert not matches_filter(doc, {"missing": {"$exists": True}})
    assert matches_filter(doc, {"missing": {"$exists": False}})
    assert matches_filter(doc, {"missing": {"$ne": 5}})


# ---- SQL pushdown parity + indexing (VERDICT r1 weak #6) -----------------

FIXTURE_DOCS = [
    {"chunk_id": "p1", "thread_id": "ta", "seq": 2,
     "embedding_generated": False, "token_count": 10},
    {"chunk_id": "p2", "thread_id": "ta", "seq": 1,
     "embedding_generated": True, "token_count": 250},
    {"chunk_id": "p3", "thread_id": "tb", "seq": 1,
     "embedding_generated": False, "token_count": 120, "status": None},
    {"chunk_id": "p4", "thread_id": "tb", "seq": 3,
     "token_count": 90, "status": "failed"},
    {"chunk_id": "p5", "thread_id": "tc", "seq": 2,
     "embedding_generated": False, "status": "ok",
     "meta": {"lang": "en"}},
]

PARITY_FILTERS = [
    None,
    {},
    {"thread_id": "ta"},
    {"embedding_generated": False},
    {"thread_id": {"$in": ["ta", "tc"]}},
    {"thread_id": {"$nin": ["ta", "tc"]}},
    {"chunk_id": {"$in": []}},
    {"status": {"$nin": []}},
    {"token_count": {"$gte": 100}},
    {"token_count": {"$lt": 100}},
    {"token_count": {"$gt": 10, "$lte": 250}},
    {"status": {"$exists": True}},
    {"status": {"$exists": False}},
    {"status": None},
    {"status": {"$ne": None}},
    {"status": {"$ne": "failed"}},
    {"meta.lang": "en"},
    {"$or": [{"thread_id": "ta"}, {"status": "ok"}]},
    {"$and": [{"thread_id": "tb"}, {"seq": {"$gte": 2}}]},
    {"thread_id": "ta", "embedding_generated": True},
    {"chunk_id": {"$regex": "p[12]"}},  # exercises the Python fallback
    {"thread_id": {"$ne": []}},         # non-scalar arg → fallback too
]

PARITY_SORTS = [None, [("seq", 1)], [("seq", -1)],
                [("thread_id", 1), ("seq", -1)], [("status", 1)]]


def _loaded_stores(tmp_path):
    mem = InMemoryDocumentStore()
    sql = SQLiteDocumentStore({"path": str(tmp_path / "parity.sqlite3")})
    for s in (mem, sql):
        for d in FIXTURE_DOCS:
            s.insert_document("chunks", d)
    return mem, sql


def test_sql_pushdown_parity_with_matcher(tmp_path):
    """The compiled WHERE/ORDER BY path must agree with the shared Python
    matcher on every operator the filter language documents."""
    mem, sql = _loaded_stores(tmp_path)
    for flt in PARITY_FILTERS:
        for sort in PARITY_SORTS:
            want = [d["chunk_id"] for d in mem.query_documents(
                "chunks", flt, sort=sort)]
            got = [d["chunk_id"] for d in sql.query_documents(
                "chunks", flt, sort=sort)]
            assert got == want, (flt, sort)
        assert sql.count_documents("chunks", flt) == \
            mem.count_documents("chunks", flt), flt
    sql.close()


def test_sql_pushdown_limit_skip_parity(tmp_path):
    mem, sql = _loaded_stores(tmp_path)
    for kwargs in ({"limit": 2}, {"skip": 2}, {"limit": 2, "skip": 1}):
        want = [d["chunk_id"] for d in mem.query_documents(
            "chunks", {"embedding_generated": False},
            sort=[("seq", 1)], **kwargs)]
        got = [d["chunk_id"] for d in sql.query_documents(
            "chunks", {"embedding_generated": False},
            sort=[("seq", 1)], **kwargs)]
        assert got == want, kwargs
    sql.close()


def test_sql_pushdown_delete_parity(tmp_path):
    mem, sql = _loaded_stores(tmp_path)
    for s in (mem, sql):
        assert s.delete_documents("chunks", {"thread_id": "tb"}) == 2
        assert s.count_documents("chunks") == 3
    sql.close()


def test_sqlite_uses_expression_index(tmp_path):
    """Hot-field queries must hit the expression index, not scan."""
    s = SQLiteDocumentStore({"path": str(tmp_path / "idx.sqlite3")})
    s.insert_document("chunks", FIXTURE_DOCS[0])
    plan = " ".join(r[-1] for r in s._conn().execute(
        "EXPLAIN QUERY PLAN SELECT doc FROM docs_chunks "
        "WHERE json_extract(doc, '$.thread_id') = ?", ("ta",)))
    assert "idx_chunks_thread_id" in plan, plan
    s.close()


def test_sqlite_indexed_query_scales(tmp_path):
    """O(result) not O(corpus): a needle query over a 20k-row collection
    must run orders of magnitude faster than the full-scan fallback."""
    import time as _t
    s = SQLiteDocumentStore({"path": str(tmp_path / "scale.sqlite3")})
    rows = [{"chunk_id": f"c{i}", "thread_id": f"t{i % 2000}",
             "embedding_generated": i % 7 == 0,
             "text": "x" * 200, "seq": i % 5} for i in range(20_000)]
    s.insert_many("chunks", rows)
    t0 = _t.perf_counter()
    hits = s.query_documents("chunks", {"thread_id": "t123"},
                             sort=[("seq", 1)])
    dt_indexed = _t.perf_counter() - t0
    assert len(hits) == 10
    t0 = _t.perf_counter()
    hits2 = s.query_documents(
        "chunks", {"thread_id": {"$regex": "^t123$"}})  # fallback path
    dt_scan = _t.perf_counter() - t0
    assert {d["chunk_id"] for d in hits2} == {d["chunk_id"] for d in hits}
    assert dt_indexed < dt_scan / 5, (dt_indexed, dt_scan)
    s.close()


def test_sqlite_lock_contention_is_retryable(tmp_path, monkeypatch):
    """``OperationalError: database is locked`` (writer contention past
    the busy timeout) must surface as the retryable
    ``StorageContentionError`` — infrastructure contention rides the
    retry/redelivery spine, it must never classify as poison."""
    import sqlite3

    from copilot_for_consensus_tpu.core.retry import RetryableError
    from copilot_for_consensus_tpu.storage.base import (
        StorageContentionError,
    )

    s = SQLiteDocumentStore({"path": str(tmp_path / "lock.sqlite3")})
    s.insert_document("sources", {"source_id": "s1", "name": "s1"})

    class _LockedConn:
        def execute(self, *a, **kw):
            raise sqlite3.OperationalError("database is locked")

        def commit(self):
            raise sqlite3.OperationalError("database is locked")

    monkeypatch.setattr(s, "_conn", lambda: _LockedConn())
    with pytest.raises(StorageContentionError) as ei:
        s.upsert_document("sources", {"source_id": "s1", "name": "s2"})
    assert isinstance(ei.value, RetryableError)
    with pytest.raises(StorageContentionError):
        s.get_document("sources", "s1")
    # non-lock OperationalErrors keep their class (genuinely broken SQL
    # or schema must not masquerade as transient)
    class _BrokenConn:
        def execute(self, *a, **kw):
            raise sqlite3.OperationalError("no such table: docs_nope")

    monkeypatch.setattr(s, "_conn", lambda: _BrokenConn())
    with pytest.raises(sqlite3.OperationalError):
        s.get_document("sources", "s1")
    monkeypatch.undo()
    s.close()
