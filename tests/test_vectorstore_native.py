# Native (C++ via ctypes) flat vector store: parity with the NumPy
# driver, filters, and the compiled-core availability contract.
import numpy as np
import pytest

from copilot_for_consensus_tpu.vectorstore.factory import create_vector_store
from copilot_for_consensus_tpu.vectorstore.memory import InMemoryVectorStore
from copilot_for_consensus_tpu.vectorstore.native import (
    NativeFlatVectorStore,
    load_native_lib,
)


def _fill(store, n=200, dim=16, seed=0):
    rng = np.random.default_rng(seed)
    vecs = rng.normal(size=(n, dim)).astype(np.float32)
    store.add_embeddings([
        (f"v{i}", vecs[i].tolist(), {"thread_id": f"t{i % 7}"})
        for i in range(n)])
    return vecs


def test_native_core_compiles():
    """g++ is baked into the image; the core must actually build here
    (the NumPy fallback is for toolchain-free installs, not this repo)."""
    assert load_native_lib() is not None


def test_native_matches_numpy_driver():
    nat, mem = NativeFlatVectorStore(), InMemoryVectorStore()
    _fill(nat)
    _fill(mem)
    rng = np.random.default_rng(1)
    for _ in range(10):
        q = rng.normal(size=16).tolist()
        got = nat.query(q, top_k=9)
        want = mem.query(q, top_k=9)
        assert [g.id for g in got] == [w.id for w in want]
        np.testing.assert_allclose([g.score for g in got],
                                   [w.score for w in want], rtol=1e-5,
                                   atol=1e-6)


def test_native_filtered_query_matches():
    nat, mem = NativeFlatVectorStore(), InMemoryVectorStore()
    _fill(nat)
    _fill(mem)
    q = np.random.default_rng(2).normal(size=16).tolist()
    got = nat.query(q, top_k=5, flt={"thread_id": "t3"})
    want = mem.query(q, top_k=5, flt={"thread_id": "t3"})
    assert [g.id for g in got] == [w.id for w in want]
    assert all(g.metadata["thread_id"] == "t3" for g in got)


def test_native_upsert_delete_and_factory():
    store = create_vector_store({"driver": "native"})
    _fill(store, n=20)
    store.add_embedding("v0", [9.0] + [0.0] * 15, {"thread_id": "tX"})
    hit = store.query([1.0] + [0.0] * 15, top_k=1)[0]
    assert hit.id == "v0" and hit.metadata["thread_id"] == "tX"
    assert store.delete(["v0"]) == 1
    assert store.count() == 19
    assert all(r.id != "v0" for r in store.query([1.0] + [0.0] * 15,
                                                 top_k=19))


def test_native_lib_does_not_break_subnormals():
    """Loading the compiled core must not flip FTZ/DAZ process-wide:
    gcc links crtfastmath.o into -ffast-math shared objects and dlopen
    then silently breaks IEEE subnormals for the whole host process
    (JAX CPU numerics included). Regression for exactly that."""
    assert load_native_lib() is not None
    tiny = np.float32(1e-40) * np.float32(0.01)
    assert tiny != 0.0
