# The post-lowering static-analysis lane (hlocheck) must stay green
# AND keep catching what it claims to catch: every rule is proven
# against a fixture corpus (one true positive + one clean negative),
# the worker/CLI/baseline routes are exercised, the bench preflight
# gates on it with the same rc-2/ok:false artifact contract as the
# shard and dura gates, and the committed HLO_BUDGETS.json snapshot
# stays internally consistent. Same spirit as test_shardcheck.py for
# the trace-level semantic group. The engine-mutation tripwires live
# in test_static_analysis.py (donation drop, bucket-table widening)
# and test_engine_kernel_route.py (re-introduced pool gather).
import json
import pathlib
import subprocess
import sys

import pytest

from copilot_for_consensus_tpu.analysis import (
    RULES as CLI_RULES,
    SEMANTIC_GROUPS,
    main as jaxlint_main,
)
from copilot_for_consensus_tpu.analysis import hlocheck
from copilot_for_consensus_tpu.analysis.contracts import (
    HLO_CONTRACT_MODULES,
)

ROOT = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = ROOT / "tests" / "fixtures" / "hlocheck"


def _findings(fixture: str, rule: str):
    findings, _, skips = hlocheck.check_modules([str(FIXTURES / fixture)])
    assert skips == [], skips       # conftest provides 8 virtual devices
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------------------
# fixture corpus: one true positive + one clean negative per rule
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fixture,rule,bad_marker,good_marker", [
    ("donation_alias.py", "hlo-donation-alias", "bad_alias",
     "good_alias"),
    ("materialize.py", "hlo-materialize", "bad_materialize",
     "good_materialize"),
    ("collective_budget.py", "hlo-collective-budget", "bad_budget",
     "good_budget"),
    ("peak_memory.py", "hlo-peak-memory", "bad_peak", "good_peak"),
    ("program_cache.py", "hlo-program-cache", "bad_cache",
     "good_cache"),
])
def test_rule_true_positive_and_clean_negative(fixture, rule,
                                               bad_marker, good_marker):
    found = _findings(fixture, rule)
    assert any(bad_marker in f.context for f in found), (rule, found)
    assert not any(good_marker in f.context for f in found), (rule, found)


def test_materialize_finding_names_the_tensor():
    found = _findings("materialize.py", "hlo-materialize")
    assert any("2048" in f.message for f in found), found


def test_collective_finding_names_op_and_counts():
    found = _findings("collective_budget.py", "hlo-collective-budget")
    assert any("'all-reduce'" in f.message and "declares 0" in f.message
               for f in found), found


def test_peak_finding_carries_the_byte_breakdown():
    found = _findings("peak_memory.py", "hlo-peak-memory")
    assert any("argument" in f.message and "temp" in f.message
               for f in found), found


def test_program_cache_duplicate_variants_share_a_digest():
    """good_cache declares 4 variants / 3 programs (width 8 twice):
    passing proves the digest identifies programs, not labels."""
    found = _findings("program_cache.py", "hlo-program-cache")
    assert all("good_cache" not in f.context for f in found), found


def test_broken_module_is_a_contract_finding(tmp_path):
    boom = tmp_path / "boom.py"
    boom.write_text("raise RuntimeError('import bomb')\n")
    findings, _, _ = hlocheck.check_modules([str(boom)])
    assert any(f.rule == "hlo-contract" and "failed to import"
               in f.message for f in findings), findings
    empty = tmp_path / "empty.py"
    empty.write_text("X = 1\n")
    findings, _, _ = hlocheck.check_modules([str(empty)])
    assert any(f.rule == "hlo-contract"
               and "no SHARDCHECK_CONTRACTS" in f.message
               for f in findings), findings


def test_module_without_hlo_specs_is_registry_rot(tmp_path):
    """A contract module whose cases all lost their HloSpec has rotted
    out of the post-lowering pass — full (unfiltered) runs must say so
    instead of silently passing."""
    mod = tmp_path / "nospec.py"
    mod.write_text(
        "from copilot_for_consensus_tpu.analysis.contracts import (\n"
        "    ContractCase, contract)\n\n\n"
        "def no_spec():\n"
        "    return ContractCase(label='x')\n\n\n"
        "SHARDCHECK_CONTRACTS = [contract('no_spec', no_spec)]\n")
    findings, _, _ = hlocheck.check_modules([str(mod)])
    assert any(f.rule == "hlo-contract" and "no HloSpec" in f.message
               for f in findings), findings
    # ...but a labels-narrowed tripwire run must not trip it
    findings, _, _ = hlocheck.check_modules(
        [str(mod)], labels={"absent"})
    assert findings == [], findings


# ---------------------------------------------------------------------------
# registry + CLI integration
# ---------------------------------------------------------------------------


def test_hlo_is_a_semantic_group_and_rules_in_sync():
    assert "hlo" in SEMANTIC_GROUPS
    hlo_rules = {r for r, g in CLI_RULES.items() if g == "hlo"}
    assert hlo_rules == set(hlocheck.RULES)


@pytest.mark.slow
def test_cli_hlo_group_subprocess_clean():
    """The worker subprocess route (what CI's hlo matrix arm and bench
    preflight use) comes up with the virtual device platform, lowers +
    compiles the whole registry, and reports clean."""
    proc = subprocess.run(
        [sys.executable, "-m",
         "copilot_for_consensus_tpu.analysis.hlocheck", "--json"],
        cwd=ROOT, capture_output=True, text=True, timeout=600)
    data = json.loads(proc.stdout.strip().splitlines()[-1])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert data["findings"] == [] and data["skips"] == []
    assert len(data["checked"]) == len(HLO_CONTRACT_MODULES)
    # the --budgets report rides the same run: every compiled case
    # with a declared budget must sit under it
    assert data["report"]
    for ctx, stats in data["report"].items():
        if stats.get("budget_bytes") is not None:
            assert stats["peak_bytes"] <= stats["budget_bytes"], ctx


def test_worker_baseline_silences_finding(tmp_path, capsys):
    """A justified baseline entry matching an hlo finding silences it
    through the worker's --baseline route (what bench preflight
    passes)."""
    findings, _, _ = hlocheck.check_modules(
        [str(FIXTURES / "peak_memory.py")])
    bad = [f for f in findings if f.rule == "hlo-peak-memory"]
    assert bad
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([
        {"rule": f.rule, "path": f.path, "context": f.context,
         "message": f.message,
         "justification": "fixture: deliberately starved budget"}
        for f in bad]))
    rc = hlocheck.main(["--modules", str(FIXTURES / "peak_memory.py"),
                        "--baseline", str(bl), "--json"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["findings"] == []


def test_fast_run_skips_hlo_without_judging_its_baseline(tmp_path,
                                                         capsys):
    """--fast skips the hlo group the way it skips shard — and a
    skipped group must not judge hlo baseline entries stale."""
    ok = tmp_path / "ok.py"
    ok.write_text("import os\nprint(os.name)\n")
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([
        {"rule": "hlo-peak-memory", "path": "tests/x.py",
         "context": "some-contract", "message": "m",
         "justification": "entry only the full lowering run can judge"}]))
    rc = jaxlint_main(["--fast", "--strict", "--baseline", str(bl),
                       str(ok)])
    out = capsys.readouterr().out
    assert rc == 0 and "stale" not in out, out


# ---------------------------------------------------------------------------
# bench preflight: the rc-2/ok:false artifact contract
# ---------------------------------------------------------------------------


def test_bench_hlo_preflight_blocks_on_violation():
    """pipeline_chaos maps to no jitted entrypoints (shardcheck skips)
    so the pinned fixture reaches the hlo gate directly: the bench
    must exit 2 with the same rc-2/ok:false artifact contract before
    any timed run starts."""
    import os

    proc = subprocess.run(
        [sys.executable, str(ROOT / "bench.py")],
        cwd=ROOT, capture_output=True, text=True, timeout=600,
        env={**os.environ,
             "BENCH_PREFLIGHT": "1",
             "BENCH_NO_PROBE": "1",
             "BENCH_EXTRA": "0",
             "BENCH_PRESET": "pipeline_chaos",
             "BENCH_HLOCHECK_MODULES":
                 str(FIXTURES / "donation_alias.py")})
    assert proc.returncode == 2, proc.stdout + proc.stderr
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["ok"] is False
    assert "hlocheck preflight failed" in line["reason"]
    assert any("hlo-donation-alias" in f for f in line["findings"])


def test_hlo_preflight_opt_out_and_preset_map(monkeypatch):
    """BENCH_HLOCHECK=0 (and BENCH_PREFLIGHT=0) skip even with
    violating modules pinned; ungated presets resolve to no modules;
    every gated preset intersects the hlo registry non-trivially."""
    sys.path.insert(0, str(ROOT))
    try:
        import bench
    finally:
        sys.path.remove(str(ROOT))
    monkeypatch.setenv("BENCH_HLOCHECK_MODULES",
                       str(FIXTURES / "donation_alias.py"))
    monkeypatch.setenv("BENCH_PREFLIGHT", "0")
    assert bench.hlocheck_preflight() is None
    monkeypatch.setenv("BENCH_PREFLIGHT", "1")
    monkeypatch.setenv("BENCH_HLOCHECK", "0")
    assert bench.hlocheck_preflight() is None
    monkeypatch.delenv("BENCH_HLOCHECK")
    monkeypatch.delenv("BENCH_HLOCHECK_MODULES")
    monkeypatch.setenv("BENCH_PRESET", "rag2k")     # ungated preset
    assert bench.hlocheck_preflight() is None
    assert bench.HLO_PREFLIGHT_PRESETS <= set(bench.PRESETS)
    for preset in bench.HLO_PREFLIGHT_PRESETS:
        mods = [m for m in bench.PRESET_CONTRACT_MODULES[preset]
                if m in HLO_CONTRACT_MODULES]
        assert mods, f"{preset} gates on hlo but maps to no modules"


# ---------------------------------------------------------------------------
# the committed budget snapshot stays honest
# ---------------------------------------------------------------------------


def test_hlo_budgets_snapshot_consistent():
    """docs/artifacts/HLO_BUDGETS.json (regenerated with --budgets)
    must carry every declared budget at/above its recorded peak and
    cover the kernel-route dispatch family the lane exists to pin."""
    data = json.loads(
        (ROOT / "docs" / "artifacts" / "HLO_BUDGETS.json").read_text())
    assert data["device_count"] == 8
    cases = data["cases"]
    assert "generation-engine:decode-paged-kernel" in cases
    assert "generation-engine:decode-paged-mesh-kernel" in cases
    for ctx, stats in cases.items():
        assert stats["peak_bytes"] == (
            stats["argument_bytes"] + stats["output_bytes"]
            + stats["temp_bytes"] - stats["alias_bytes"]), ctx
        assert stats["budget_bytes"] is not None, ctx
        assert stats["peak_bytes"] <= stats["budget_bytes"], ctx
    # the kernel route's whole point: its decode peak stays well under
    # the reference route's materializing decode
    ref = cases["generation-engine:decode-paged"]["peak_bytes"]
    ker = cases["generation-engine:decode-paged-kernel"]["peak_bytes"]
    assert ker < ref
