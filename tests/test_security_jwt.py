# JWT mint/verify: RS256 + JWKS, HS256, expiry/claim checks.
import time

import pytest

from copilot_for_consensus_tpu.security.jwt import (
    HAS_CRYPTOGRAPHY,
    HS256Signer,
    JWTError,
    JWTManager,
    LocalRS256Signer,
    create_jwt_signer,
)

# RS256 needs the optional 'cryptography' wheel; HS256 and the claim /
# middleware plumbing are stdlib and still run without it
requires_crypto = pytest.mark.skipif(
    not HAS_CRYPTOGRAPHY,
    reason="optional 'cryptography' package not installed "
           "(RSA primitives)")


@pytest.fixture(scope="module")
def rs_manager():
    if not HAS_CRYPTOGRAPHY:
        pytest.skip("optional 'cryptography' package not installed "
                    "(RSA primitives)")
    return JWTManager(LocalRS256Signer(), issuer="iss", audience="aud")


def test_rs256_roundtrip(rs_manager):
    token = rs_manager.mint("user@x", roles=["reader"])
    claims = rs_manager.verify(token)
    assert claims["sub"] == "user@x"
    assert claims["roles"] == ["reader"]
    assert claims["iss"] == "iss"


def test_rs256_jwks_has_key(rs_manager):
    jwks = rs_manager.jwks()
    key = jwks["keys"][0]
    assert key["kty"] == "RSA" and key["alg"] == "RS256"
    assert key["kid"] == rs_manager.signer.kid


def test_tampered_token_rejected(rs_manager):
    token = rs_manager.mint("user@x")
    head, payload, sig = token.split(".")
    # flip a character in the payload
    bad = payload[:-2] + ("A" if payload[-2] != "A" else "B") + payload[-1]
    with pytest.raises(JWTError):
        rs_manager.verify(f"{head}.{bad}.{sig}")


def test_expired_token_rejected(rs_manager):
    token = rs_manager.mint("user@x", ttl_seconds=-10)
    with pytest.raises(JWTError, match="expired"):
        rs_manager.verify(token)


def test_wrong_audience_rejected(rs_manager):
    other = JWTManager(rs_manager.signer, issuer="iss", audience="other")
    token = other.mint("user@x")
    with pytest.raises(JWTError, match="audience"):
        rs_manager.verify(token)


def test_hs256_roundtrip_and_cross_secret():
    a = JWTManager(HS256Signer("secret-a"))
    b = JWTManager(HS256Signer("secret-b"))
    token = a.mint("u")
    assert a.verify(token)["sub"] == "u"
    with pytest.raises(JWTError):
        b.verify(token)


@requires_crypto
def test_alg_confusion_rejected():
    # HS256 token must not verify against an RS256 manager (alg pinning).
    hs = JWTManager(HS256Signer("s"), issuer="copilot")
    rs = JWTManager(LocalRS256Signer(), issuer="copilot")
    with pytest.raises(JWTError, match="algorithm"):
        rs.verify(hs.mint("u"))


@requires_crypto
def test_pem_persistence_roundtrip():
    signer = LocalRS256Signer()
    restored = LocalRS256Signer(private_pem=signer.private_pem())
    m1 = JWTManager(signer)
    m2 = JWTManager(restored)
    assert m2.verify(m1.mint("u"))["sub"] == "u"
    assert signer.kid == restored.kid


def test_factory():
    assert create_jwt_signer({"driver": "hs256", "secret": "x"}).alg == "HS256"
    if HAS_CRYPTOGRAPHY:
        assert create_jwt_signer().alg == "RS256"
    with pytest.raises(ValueError):
        create_jwt_signer({"driver": "nope"})


def test_missing_cryptography_is_actionable():
    """Without the optional wheel, RSA signers must raise a JWTError
    that names the dependency — not a ModuleNotFoundError from a lazy
    import deep inside a request."""
    if HAS_CRYPTOGRAPHY:
        pytest.skip("cryptography installed: the guard never fires")
    with pytest.raises(JWTError, match="cryptography"):
        LocalRS256Signer()


def test_jwt_middleware_revocation_cache():
    """The middleware must NOT hit the revocation store on every request
    (with a remote document store that is an HTTP round-trip per call):
    clean verdicts are cached for the TTL, local invalidation is
    immediate, revoked verdicts stick."""
    from copilot_for_consensus_tpu.security.auth import (
        create_jwt_middleware,
    )
    from copilot_for_consensus_tpu.services.http import (
        HTTPError,
        Request,
    )

    manager = JWTManager(HS256Signer("s"), issuer="i", audience="a")
    token = manager.mint("u@example.org", roles=["reader"])
    calls = []
    revoked: set[str] = set()

    def is_revoked(jti):
        calls.append(jti)
        return jti in revoked

    mw = create_jwt_middleware(manager, is_revoked=is_revoked,
                               revocation_cache_ttl=60.0)

    def req():
        return Request("GET", "/api/reports", {}, {
            "Authorization": f"Bearer {token}"}, b"", {})

    for _ in range(5):
        mw(req())
    assert len(calls) == 1            # 4 of 5 served from cache
    jti = calls[0]

    # local logout: invalidate → next request re-checks and rejects
    revoked.add(jti)
    mw.invalidate(jti)
    with pytest.raises(HTTPError) as exc:
        mw(req())
    assert exc.value.status == 401
    assert len(calls) == 2
    # revoked verdict is cached too — no further store traffic
    with pytest.raises(HTTPError):
        mw(req())
    assert len(calls) == 2
