# The first-party static-analysis lane must stay green AND keep
# catching what it claims to catch (a policy that can't fail is not a
# policy — same spirit as the fuzzer's seeded-bug effectiveness proof).
import json
import pathlib
import subprocess
import sys
import textwrap

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
SCRIPT = ROOT / "scripts" / "validate_python.py"
FIXTURES = ROOT / "tests" / "fixtures" / "jaxlint"

sys.path.insert(0, str(ROOT / "scripts"))
import validate_python as vp  # noqa: E402

from copilot_for_consensus_tpu.analysis import (  # noqa: E402
    analyze_files,
    main as jaxlint_main,
)


def test_repo_is_clean_fast():
    """Syntax + AST policies hold over the whole source tree (the
    import-smoke stage runs in CI's dedicated lint job; the suite
    itself already imports everything)."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPT), "--fast"], cwd=ROOT,
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.parametrize("snippet,expect", [
    ("def f(x=[]):\n    return x\n", "mutable default"),
    ("def f(x={'a': 1}):\n    return x\n", "mutable default"),
    ("try:\n    pass\nexcept:\n    pass\n", "bare 'except:'"),
    ("import json\nimport os\nprint(os.name)\n", "unused import 'json'"),
    ("def f(:\n    pass\n", "syntax"),
])
def test_lane_catches_seeded_bugs(tmp_path, snippet, expect):
    bad = tmp_path / "seeded.py"
    bad.write_text(textwrap.dedent(snippet))
    errs = (vp.check_syntax([bad]) if expect == "syntax" else
            vp.check_syntax([bad])
            + vp.check_mutable_defaults([bad])
            + vp.check_bare_except([bad])
            + vp.check_unused_imports([bad]))
    assert any(expect in e for e in errs), errs


def test_lane_exemptions_hold(tmp_path):
    """noqa lines, __all__ strings, and used imports must NOT flag."""
    ok = tmp_path / "ok.py"
    ok.write_text(
        "import json  # noqa: used by doctest\n"
        "import os\n"
        "__all__ = ['os']\n"
        "print(os.name)\n")
    assert vp.check_unused_imports([ok]) == []


def test_syntax_error_reported_not_crashing(tmp_path):
    """A file with a syntax error must yield ONE syntax finding from
    the whole lane, never an unhandled SyntaxError out of main()."""
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n    pass\n")
    errs = (vp.check_syntax([bad]) + vp.check_mutable_defaults([bad])
            + vp.check_bare_except([bad])
            + vp.check_unused_imports([bad]))
    assert len(errs) == 1 and "syntax" in errs[0]


def test_constructor_call_defaults_flagged(tmp_path):
    bad = tmp_path / "ctor.py"
    bad.write_text("def f(x=list(), y=dict()):\n    return x, y\n")
    errs = vp.check_mutable_defaults([bad])
    assert len(errs) == 2
    # frozen-config style defaults (arbitrary constructor calls) pass:
    # only the builtin mutable containers are the documented class
    ok = tmp_path / "cfg.py"
    ok.write_text("def f(x=Config()):\n    return x\n")
    assert vp.check_mutable_defaults([ok]) == []


# ---------------------------------------------------------------------------
# jaxlint rule groups (copilot_for_consensus_tpu/analysis): each rule is
# proven against the fixture corpus — one true positive AND one clean
# negative per rule — so a checker that silently stops firing (or starts
# flagging the blessed idiom) fails here, not in review.
# ---------------------------------------------------------------------------


def _findings(fixture: str, rule: str):
    out = analyze_files([FIXTURES / fixture])
    return [f for f in out if f.rule == rule]


@pytest.mark.parametrize("fixture,rule,bad_marker,good_marker", [
    ("host_sync.py", "host-sync-in-jit", "bad_sync", "good_sync"),
    ("retrace.py", "retrace-hazard", "bad_branch", "good_branch"),
    ("donation.py", "donation", "_step_bad", "_step_good"),
    ("prng.py", "prng-reuse", "bad_double_use", "good_split"),
    ("blocking.py", "blocking-call", "BadConsumer", "GoodConsumer"),
    ("collective.py", "collective-axis", "bad_body", "good_body"),
])
def test_rule_true_positive_and_clean_negative(fixture, rule,
                                               bad_marker, good_marker):
    found = _findings(fixture, rule)
    assert any(bad_marker in f.context or bad_marker in f.message
               for f in found), (rule, found)
    assert not any(good_marker in f.context for f in found), (rule, found)


def test_host_sync_catches_every_surface():
    msgs = "\n".join(f.message for f in
                     _findings("host_sync.py", "host-sync-in-jit"))
    for surface in (".item()", "np.asarray", "jax.device_get",
                    ".block_until_ready()", "`float()`"):
        assert surface in msgs, (surface, msgs)


def test_retrace_unhashable_static_default_flagged():
    found = _findings("retrace.py", "retrace-hazard")
    assert any("unhashable" in f.message for f in found)


def test_prng_all_three_reuse_shapes_flagged():
    ctxs = {f.context for f in _findings("prng.py", "prng-reuse")}
    assert {"bad_double_use", "bad_use_after_split",
            "bad_loop_reuse"} <= ctxs
    assert "good_exclusive_branches" not in ctxs


def test_blocking_flags_publish_under_lock():
    found = _findings("blocking.py", "blocking-call")
    assert any("lock" in f.message for f in found)


def test_inline_suppression_honored():
    """`# jaxlint: disable=<rule>` on (or right above) the line wins."""
    found = _findings("blocking.py", "blocking-call")
    assert not any(f.context.endswith("run_suppressed") for f in found)


def test_collective_axis_literal_vs_mesh():
    found = _findings("collective.py", "collective-axis")
    assert any("'tp'" in f.message for f in found)
    assert any("'model'" in f.message for f in found)


# ---------------------------------------------------------------------------
# regression tripwires on the REAL engine: the two mutations the
# acceptance criteria name must turn the lane red.
# ---------------------------------------------------------------------------

_GEN = ROOT / "copilot_for_consensus_tpu" / "engine" / "generation.py"


def test_deleting_decode_donation_fails_the_lane(tmp_path):
    src = _GEN.read_text()
    needle = "jax.jit(_decode, donate_argnums=(3,),"
    assert needle in src, "decode jit signature moved; update the test"
    mutated = tmp_path / "generation_mutated.py"
    mutated.write_text(src.replace(needle, "jax.jit(_decode,"))
    found = [f for f in analyze_files([mutated]) if f.rule == "donation"]
    assert any("_decode" in f.context and "'cache'" in f.message
               for f in found), found


def test_item_inside_decode_jit_fails_the_lane(tmp_path):
    src = _GEN.read_text()
    needle = "            w_sz = self.decode_window\n"
    assert needle in src, "decode body moved; update the test"
    mutated = tmp_path / "generation_mutated.py"
    mutated.write_text(src.replace(
        needle, needle + "            _dbg = tokens.sum().item()\n", 1))
    found = [f for f in analyze_files([mutated])
             if f.rule == "host-sync-in-jit"]
    assert any("_decode" in f.context for f in found), found


# ---------------------------------------------------------------------------
# baseline workflow: grandfathered findings must carry a justification;
# matching entries silence findings; the e2e repo run is clean.
# ---------------------------------------------------------------------------


def test_baseline_requires_justification(tmp_path):
    entry = {"rule": "donation", "path": "x.py", "context": "f",
             "message": "m"}                    # no justification
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([entry]))
    rc = jaxlint_main(["--rules", "donation", "--baseline", str(bl),
                       str(FIXTURES / "donation.py")])
    assert rc == 1


def test_baseline_silences_matching_finding(tmp_path, capsys):
    found = [f for f in analyze_files([FIXTURES / "donation.py"])
             if f.rule == "donation"]
    assert found
    entries = [{"rule": f.rule, "path": f.path, "context": f.context,
                "message": f.message,
                "justification": "fixture: deliberately undonated"}
               for f in found]
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(entries))
    rc = jaxlint_main(["--rules", "donation", "--baseline", str(bl),
                       str(FIXTURES / "donation.py")])
    assert rc == 0, capsys.readouterr().out


def test_strict_rejects_todo_justification(tmp_path, capsys):
    """A justification still starting with the --write-baseline TODO
    placeholder warns on a normal run but fails under --strict
    (finding id baseline-unjustified) — the placeholder must not
    calcify into the record."""
    found = [f for f in analyze_files([FIXTURES / "donation.py"])
             if f.rule == "donation"]
    assert found
    entries = [{"rule": f.rule, "path": f.path, "context": f.context,
                "message": f.message,
                "justification": "TODO: explain why this is deliberate"}
               for f in found]
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(entries))
    args = ["--rules", "donation", "--baseline", str(bl),
            str(FIXTURES / "donation.py")]
    assert jaxlint_main(args) == 0          # non-strict: warn only
    assert "baseline-unjustified" in capsys.readouterr().err
    rc = jaxlint_main(["--strict"] + args)
    out = capsys.readouterr()
    assert rc == 1
    assert "baseline-unjustified" in out.out


def test_repo_baseline_entries_all_justified():
    from copilot_for_consensus_tpu.analysis.base import (
        DEFAULT_BASELINE,
        load_baseline,
    )

    from copilot_for_consensus_tpu.analysis.base import unjustified_entries

    entries, errors = load_baseline(DEFAULT_BASELINE)
    assert errors == []
    assert all(len(e["justification"]) > 40 for e in entries), (
        "baseline justifications must actually explain the decision")
    assert unjustified_entries(entries) == [], (
        "committed baseline entries must not carry the TODO placeholder")


def test_repo_is_clean_end_to_end():
    """The whole tree passes every jaxlint group (modulo the committed,
    justified baseline). --fast skips import smoke, which the suite
    itself already proves by importing everything."""
    proc = subprocess.run(
        [sys.executable, "-m", "copilot_for_consensus_tpu.analysis",
         "--fast"], cwd=ROOT, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
