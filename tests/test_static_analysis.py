# The first-party static-analysis lane must stay green AND keep
# catching what it claims to catch (a policy that can't fail is not a
# policy — same spirit as the fuzzer's seeded-bug effectiveness proof).
import json
import pathlib
import subprocess
import sys
import textwrap

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
SCRIPT = ROOT / "scripts" / "validate_python.py"
FIXTURES = ROOT / "tests" / "fixtures" / "jaxlint"

sys.path.insert(0, str(ROOT / "scripts"))
import validate_python as vp  # noqa: E402

from copilot_for_consensus_tpu.analysis import (  # noqa: E402
    analyze_files,
    main as jaxlint_main,
)


def test_repo_is_clean_fast():
    """Syntax + AST policies hold over the whole source tree (the
    import-smoke stage runs in CI's dedicated lint job; the suite
    itself already imports everything)."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPT), "--fast"], cwd=ROOT,
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.parametrize("snippet,expect", [
    ("def f(x=[]):\n    return x\n", "mutable default"),
    ("def f(x={'a': 1}):\n    return x\n", "mutable default"),
    ("try:\n    pass\nexcept:\n    pass\n", "bare 'except:'"),
    ("import json\nimport os\nprint(os.name)\n", "unused import 'json'"),
    ("def f(:\n    pass\n", "syntax"),
])
def test_lane_catches_seeded_bugs(tmp_path, snippet, expect):
    bad = tmp_path / "seeded.py"
    bad.write_text(textwrap.dedent(snippet))
    errs = (vp.check_syntax([bad]) if expect == "syntax" else
            vp.check_syntax([bad])
            + vp.check_mutable_defaults([bad])
            + vp.check_bare_except([bad])
            + vp.check_unused_imports([bad]))
    assert any(expect in e for e in errs), errs


def test_lane_exemptions_hold(tmp_path):
    """noqa lines, __all__ strings, and used imports must NOT flag."""
    ok = tmp_path / "ok.py"
    ok.write_text(
        "import json  # noqa: used by doctest\n"
        "import os\n"
        "__all__ = ['os']\n"
        "print(os.name)\n")
    assert vp.check_unused_imports([ok]) == []


def test_syntax_error_reported_not_crashing(tmp_path):
    """A file with a syntax error must yield ONE syntax finding from
    the whole lane, never an unhandled SyntaxError out of main()."""
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n    pass\n")
    errs = (vp.check_syntax([bad]) + vp.check_mutable_defaults([bad])
            + vp.check_bare_except([bad])
            + vp.check_unused_imports([bad]))
    assert len(errs) == 1 and "syntax" in errs[0]


def test_constructor_call_defaults_flagged(tmp_path):
    bad = tmp_path / "ctor.py"
    bad.write_text("def f(x=list(), y=dict()):\n    return x, y\n")
    errs = vp.check_mutable_defaults([bad])
    assert len(errs) == 2
    # frozen-config style defaults (arbitrary constructor calls) pass:
    # only the builtin mutable containers are the documented class
    ok = tmp_path / "cfg.py"
    ok.write_text("def f(x=Config()):\n    return x\n")
    assert vp.check_mutable_defaults([ok]) == []


# ---------------------------------------------------------------------------
# jaxlint rule groups (copilot_for_consensus_tpu/analysis): each rule is
# proven against the fixture corpus — one true positive AND one clean
# negative per rule — so a checker that silently stops firing (or starts
# flagging the blessed idiom) fails here, not in review.
# ---------------------------------------------------------------------------


def _findings(fixture: str, rule: str):
    out = analyze_files([FIXTURES / fixture])
    return [f for f in out if f.rule == rule]


@pytest.mark.parametrize("fixture,rule,bad_marker,good_marker", [
    ("host_sync.py", "host-sync-in-jit", "bad_sync", "good_sync"),
    ("retrace.py", "retrace-hazard", "bad_branch", "good_branch"),
    ("donation.py", "donation", "_step_bad", "_step_good"),
    ("prng.py", "prng-reuse", "bad_double_use", "good_split"),
    ("blocking.py", "blocking-call", "BadConsumer", "GoodConsumer"),
    ("collective.py", "collective-axis", "bad_body", "good_body"),
])
def test_rule_true_positive_and_clean_negative(fixture, rule,
                                               bad_marker, good_marker):
    found = _findings(fixture, rule)
    assert any(bad_marker in f.context or bad_marker in f.message
               for f in found), (rule, found)
    assert not any(good_marker in f.context for f in found), (rule, found)


def test_host_sync_catches_every_surface():
    msgs = "\n".join(f.message for f in
                     _findings("host_sync.py", "host-sync-in-jit"))
    for surface in (".item()", "np.asarray", "jax.device_get",
                    ".block_until_ready()", "`float()`"):
        assert surface in msgs, (surface, msgs)


def test_retrace_unhashable_static_default_flagged():
    found = _findings("retrace.py", "retrace-hazard")
    assert any("unhashable" in f.message for f in found)


def test_prng_all_three_reuse_shapes_flagged():
    ctxs = {f.context for f in _findings("prng.py", "prng-reuse")}
    assert {"bad_double_use", "bad_use_after_split",
            "bad_loop_reuse"} <= ctxs
    assert "good_exclusive_branches" not in ctxs


def test_blocking_flags_publish_under_lock():
    found = _findings("blocking.py", "blocking-call")
    assert any("lock" in f.message for f in found)


def test_inline_suppression_honored():
    """`# jaxlint: disable=<rule>` on (or right above) the line wins."""
    found = _findings("blocking.py", "blocking-call")
    assert not any(f.context.endswith("run_suppressed") for f in found)


def test_collective_axis_literal_vs_mesh():
    found = _findings("collective.py", "collective-axis")
    assert any("'tp'" in f.message for f in found)
    assert any("'model'" in f.message for f in found)


# ---------------------------------------------------------------------------
# regression tripwires on the REAL engine: the two mutations the
# acceptance criteria name must turn the lane red.
# ---------------------------------------------------------------------------

_GEN = ROOT / "copilot_for_consensus_tpu" / "engine" / "generation.py"


def test_deleting_decode_donation_fails_the_lane(tmp_path):
    src = _GEN.read_text()
    needle = "jax.jit(_decode, donate_argnums=(3,),"
    assert needle in src, "decode jit signature moved; update the test"
    mutated = tmp_path / "generation_mutated.py"
    mutated.write_text(src.replace(needle, "jax.jit(_decode,"))
    found = [f for f in analyze_files([mutated]) if f.rule == "donation"]
    assert any("_decode" in f.context and "'cache'" in f.message
               for f in found), found


def test_item_inside_decode_jit_fails_the_lane(tmp_path):
    src = _GEN.read_text()
    needle = "            w_sz = self.decode_window\n"
    assert needle in src, "decode body moved; update the test"
    mutated = tmp_path / "generation_mutated.py"
    mutated.write_text(src.replace(
        needle, needle + "            _dbg = tokens.sum().item()\n", 1))
    found = [f for f in analyze_files([mutated])
             if f.rule == "host-sync-in-jit"]
    assert any("_decode" in f.context for f in found), found


def test_deleting_decode_donation_fails_the_hlo_lane(tmp_path):
    """The post-lowering view of the same mutation: with
    donate_argnums gone from the decode jit, the COMPILED artifact
    carries no input_output_alias for the cache the contract still
    declares donated — hlo-donation-alias must flag (the ast donation
    rule sees the jit call; this sees what XLA actually kept)."""
    from copilot_for_consensus_tpu.analysis import hlocheck

    src = _GEN.read_text()
    needle = "jax.jit(_decode, donate_argnums=(3,),"
    assert needle in src, "decode jit signature moved; update the test"
    mutated = tmp_path / "generation_hlo_donation_mutated.py"
    mutated.write_text(src.replace(needle, "jax.jit(_decode,", 1))
    findings, _, skips = hlocheck.check_modules(
        [str(mutated)], labels={"decode"},
        only_rules={"hlo-donation-alias"})
    assert skips == [], skips
    assert any(f.rule == "hlo-donation-alias" and ":decode" in f.context
               for f in findings), [f.render() for f in findings]


def test_widening_draft_buckets_fails_the_hlo_lane(tmp_path):
    """Widen spec_draft_lens without touching the program-cache
    contract's declared cardinality: the bucket cross-product lowers
    to one more distinct program than declared — hlo-program-cache
    must flag the drift before it ships as a retrace/program-cache
    explosion."""
    from copilot_for_consensus_tpu.analysis import hlocheck

    src = _GEN.read_text()
    needle = "spec_draft_lens=(0, 2, 4)"
    assert src.count(needle) >= 1, "draft buckets moved; update the test"
    mutated = tmp_path / "generation_hlo_buckets_mutated.py"
    mutated.write_text(src.replace(needle, "spec_draft_lens=(0, 2, 4, 6)"))
    findings, _, skips = hlocheck.check_modules(
        [str(mutated)], labels={"program-cache"},
        only_rules={"hlo-program-cache"})
    assert skips == [], skips
    assert any(f.rule == "hlo-program-cache"
               and "7 declared" in f.message
               for f in findings), [f.render() for f in findings]


# ---------------------------------------------------------------------------
# baseline workflow: grandfathered findings must carry a justification;
# matching entries silence findings; the e2e repo run is clean.
# ---------------------------------------------------------------------------


def test_baseline_requires_justification(tmp_path):
    entry = {"rule": "donation", "path": "x.py", "context": "f",
             "message": "m"}                    # no justification
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([entry]))
    rc = jaxlint_main(["--rules", "donation", "--baseline", str(bl),
                       str(FIXTURES / "donation.py")])
    assert rc == 1


def test_baseline_silences_matching_finding(tmp_path, capsys):
    found = [f for f in analyze_files([FIXTURES / "donation.py"])
             if f.rule == "donation"]
    assert found
    entries = [{"rule": f.rule, "path": f.path, "context": f.context,
                "message": f.message,
                "justification": "fixture: deliberately undonated"}
               for f in found]
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(entries))
    rc = jaxlint_main(["--rules", "donation", "--baseline", str(bl),
                       str(FIXTURES / "donation.py")])
    assert rc == 0, capsys.readouterr().out


def test_strict_rejects_todo_justification(tmp_path, capsys):
    """A justification still starting with the --write-baseline TODO
    placeholder warns on a normal run but fails under --strict
    (finding id baseline-unjustified) — the placeholder must not
    calcify into the record."""
    found = [f for f in analyze_files([FIXTURES / "donation.py"])
             if f.rule == "donation"]
    assert found
    entries = [{"rule": f.rule, "path": f.path, "context": f.context,
                "message": f.message,
                "justification": "TODO: explain why this is deliberate"}
               for f in found]
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(entries))
    args = ["--rules", "donation", "--baseline", str(bl),
            str(FIXTURES / "donation.py")]
    assert jaxlint_main(args) == 0          # non-strict: warn only
    assert "baseline-unjustified" in capsys.readouterr().err
    rc = jaxlint_main(["--strict"] + args)
    out = capsys.readouterr()
    assert rc == 1
    assert "baseline-unjustified" in out.out


def test_repo_baseline_entries_all_justified():
    from copilot_for_consensus_tpu.analysis.base import (
        DEFAULT_BASELINE,
        load_baseline,
    )

    from copilot_for_consensus_tpu.analysis.base import unjustified_entries

    entries, errors = load_baseline(DEFAULT_BASELINE)
    assert errors == []
    assert all(len(e["justification"]) > 40 for e in entries), (
        "baseline justifications must actually explain the decision")
    assert unjustified_entries(entries) == [], (
        "committed baseline entries must not carry the TODO placeholder")


# ---------------------------------------------------------------------------
# racecheck (the `race` group): each rule proven against its fixture —
# one true positive AND one clean negative — plus tripwires that
# re-introduce the REAL shipped bugs (PR-7 callback-under-lock, PR-8
# wrapper-shadow, broker stats lock-consistency) and assert the lane
# turns red.
# ---------------------------------------------------------------------------

RACE_FIXTURES = ROOT / "tests" / "fixtures" / "racecheck"

from copilot_for_consensus_tpu.analysis import racecheck  # noqa: E402


def _race_findings(fixture: str, rule: str):
    out = analyze_files([RACE_FIXTURES / fixture])
    return [f for f in out if f.rule == rule]


@pytest.mark.parametrize("fixture,rule,bad_marker,good_marker", [
    ("lock_order.py", "race-lock-order", "BadOrder", "GoodOrder"),
    ("callback_under_lock.py", "race-callback-under-lock",
     "BadNotifier", "GoodNotifier"),
    ("unlocked_field.py", "race-unlocked-field", "BadLedger",
     "GoodLedger"),
    ("thread_lifecycle.py", "race-thread-lifecycle", "BadPump",
     "GoodPump"),
    # pool-shutdown tripwire (ISSUE 11): a worker pool whose consume
    # threads have no stop path must flag; the StageWorkerPool shape
    # (stop-aware loops + owner join over the list) must stay clean
    ("pool_shutdown.py", "race-thread-lifecycle", "BadPool",
     "GoodPool"),
    ("wrapper_shadow.py", "race-wrapper-shadow", "BadWrapper",
     "GoodWrapper"),
    # telemetry-shipper pump (ISSUE 20): a fire-and-forget flush
    # thread must flag; the TelemetryShipper shape (stop-aware wait
    # loop + owner-joined stop before the spool closes) stays clean
    ("ship_pump.py", "race-thread-lifecycle", "BadShipPump",
     "GoodShipPump"),
])
def test_race_rule_true_positive_and_clean_negative(fixture, rule,
                                                    bad_marker,
                                                    good_marker):
    found = _race_findings(fixture, rule)
    assert any(bad_marker in f.context or bad_marker in f.message
               for f in found), (rule, found)
    assert not any(good_marker in f.context or good_marker in f.message
                   for f in found), (rule, found)


def test_lock_order_cycle_names_both_locks():
    """The ABBA report must name both locks so the reader can pick an
    order; the single-lock self-deadlock is reported as guaranteed."""
    found = _race_findings("lock_order.py", "race-lock-order")
    cycle = [f for f in found if "cycle" in f.message]
    assert cycle and "_alpha" in cycle[0].message \
        and "_beta" in cycle[0].message, found
    assert any("self-deadlock" in f.message
               and "BadSelfDeadlock" in f.context for f in found), found
    assert not any("GoodReentrant" in f.context for f in found), found


def test_callback_under_lock_propagates_through_call_graph():
    """``complete()`` never touches a callback directly — it calls
    ``_finish()``, which does. The call site must still flag."""
    found = _race_findings("callback_under_lock.py",
                           "race-callback-under-lock")
    assert any(f.context == "BadIndirect.complete"
               and "_finish" in f.message for f in found), found


def test_unlocked_field_requires_a_common_lock():
    """Accesses under two DIFFERENT locks race just like a bare one:
    the lockset intersection must be non-empty (RacerD's invariant)."""
    found = _race_findings("unlocked_field.py", "race-unlocked-field")
    assert any("BadTwoGuards" in f.context
               and "NO common lock" in f.message for f in found), found


def test_callback_under_lock_catches_subscript_invocation():
    """``self._handlers[key](env)`` under the lock — the element call
    form must flag just like the bound-local form."""
    found = _race_findings("callback_under_lock.py",
                           "race-callback-under-lock")
    assert any(f.context == "BadSubscriptDispatch.dispatch"
               for f in found), found


def test_wrapper_shadow_cross_pass_resolves_relative_imports(tmp_path):
    """``from .base import Base`` must resolve against the importing
    module's own directory — never some other base.py in the tree."""
    pkg = tmp_path / "pkg"
    decoy = tmp_path / "other"
    pkg.mkdir()
    decoy.mkdir()
    # decoy base.py with NO trivial defaults: wrong resolution = miss
    (decoy / "base.py").write_text(
        "class Base:\n    def saturation(self):\n"
        "        raise NotImplementedError\n")
    (pkg / "base.py").write_text(
        "class Base:\n    def saturation(self):\n        return {}\n")
    (pkg / "wrap.py").write_text(
        "from .base import Base\n\n\n"
        "class Wrapper(Base):\n"
        "    def __init__(self, inner):\n"
        "        self.inner = inner\n\n"
        "    def __getattr__(self, name):\n"
        "        return getattr(self.inner, name)\n")
    # and an `as`-aliased import: lookup in the defining module must
    # use the ORIGINAL name, not the local alias
    (pkg / "wrap2.py").write_text(
        "from .base import Base as RenamedBase\n\n\n"
        "class AliasWrapper(RenamedBase):\n"
        "    def __init__(self, inner):\n"
        "        self.inner = inner\n\n"
        "    def __getattr__(self, name):\n"
        "        return getattr(self.inner, name)\n")
    found = [f for f in racecheck.check_cross(
                 [decoy / "base.py", pkg / "base.py", pkg / "wrap.py",
                  pkg / "wrap2.py"])
             if f.rule == "race-wrapper-shadow"]
    assert any("'saturation'" in f.message and f.context == "Wrapper"
               for f in found), found
    assert any("'saturation'" in f.message
               and f.context == "AliasWrapper" for f in found), found


def test_cli_contradictory_rules_group_fails_loudly():
    """--rules blocking-call --group race selects nothing: that must
    be a usage error (rc 2), not a 0-file CLEAN run."""
    with pytest.raises(SystemExit) as exc:
        jaxlint_main(["--rules", "blocking-call", "--group", "race"])
    assert exc.value.code == 2


def test_unlocked_field_counts_container_element_writes():
    """``self._stats[key] += 1`` is a write OF ``_stats`` (the broker
    ledger shape) — bare element mutation must flag."""
    found = _race_findings("unlocked_field.py", "race-unlocked-field")
    assert any(f.context == "BadContainer.bump"
               and "'_stats'" in f.message for f in found), found
    # the verified "# caller holds the lock" idiom must NOT flag
    assert not any("GoodPrivateHelper" in f.context for f in found), found


def test_inferred_held_defeated_by_cross_class_call_site():
    """'caller holds the lock' inference must count EVERY resolvable
    call site: a lock-free cross-class call into ``_mark_done`` makes
    its bare write a real race, not an inherited-lock access."""
    found = _race_findings("unlocked_field.py", "race-unlocked-field")
    assert any(f.context == "_CrossHandle._mark_done"
               and "'_state'" in f.message for f in found), found


def test_module_level_thread_joined_in_sibling_function_is_clean():
    found = _race_findings("thread_lifecycle.py",
                           "race-thread-lifecycle")
    assert not any("_module_loop" in f.message
                   or "good_module" in f.context for f in found), found


def test_thread_lifecycle_join_only_owner_is_clean():
    found = _race_findings("thread_lifecycle.py",
                           "race-thread-lifecycle")
    assert not any("GoodJoinOnly" in f.context for f in found), found


def test_thread_lifecycle_tracked_join_excuses_nothing_else():
    """Joining thread _a must not excuse the forgotten _b; only a
    provenance-free join (the list-loop idiom) excuses untracked
    threads."""
    found = _race_findings("thread_lifecycle.py",
                           "race-thread-lifecycle")
    assert any(f.context == "BadSecondThread.__init__"
               and "_pump" in f.message for f in found), found


def test_lock_model_alias_declared_before_source():
    """``Condition(self._lock)`` textually before ``self._lock =
    threading.Lock()`` still aliases to ONE identity — holding the
    condition while taking the lock is a guaranteed self-deadlock."""
    found = _race_findings("lock_order.py", "race-lock-order")
    assert any("BadAliasBeforeSource" in f.context
               and "self-deadlock" in f.message for f in found), found


def test_lock_field_reassignable_from_parameter_stays_a_lock():
    """A lock field also assignable from a parameter (test injection)
    must neither crash the scan nor be misread as a callback field."""
    found = analyze_files([RACE_FIXTURES / "unlocked_field.py"])
    assert not any("GoodInjectedLock" in f.context
                   for f in found if f.rule.startswith("race-")), found


def test_blocking_call_sees_condition_members():
    """Satellite: the shared assignment-provenance lock model makes
    blocking-call recognize Condition-typed members whose names never
    say 'lock' (``self._work``, the async_runner dispatcher shape)."""
    found = _findings("blocking.py", "blocking-call")
    assert any(f.context == "BadConditionConsumer.run"
               for f in found), found
    assert not any("GoodConditionConsumer" in f.context
                   for f in found), found


# -- tripwires on the REAL runtime files: re-introduce each shipped bug

_RUNNER = ROOT / "copilot_for_consensus_tpu" / "engine" / "async_runner.py"
_VALIDATING = ROOT / "copilot_for_consensus_tpu" / "bus" / "validating.py"
_BUS_BASE = ROOT / "copilot_for_consensus_tpu" / "bus" / "base.py"
_BROKER = ROOT / "copilot_for_consensus_tpu" / "bus" / "broker.py"


def test_done_callback_under_runner_lock_fails_the_lane(tmp_path):
    """PR-7 regression: resolving a Handle inside the dispatcher's
    ``_work`` lock (shared with the watchdog; done-callbacks may
    re-enter submit()) must flag race-callback-under-lock."""
    src = _RUNNER.read_text()
    needle = ("                with self._work:\n"
              "                    h = self._handles.pop(c.request_id, None)\n"
              "                    meta = self._replays.pop(c.request_id, None)\n")
    assert needle in src, "dispatcher harvest block moved; update the test"
    mutated = tmp_path / "async_runner_mutated.py"
    mutated.write_text(src.replace(
        needle,
        needle + "                    if h is not None:\n"
                 "                        h._resolve(c)\n", 1))
    found = [f for f in analyze_files([mutated])
             if f.rule == "race-callback-under-lock"]
    assert any("_resolve" in f.message for f in found), found
    # the unmutated file is part of the clean e2e run (no findings)


def test_wrapper_shadow_catches_inert_saturation(tmp_path):
    """PR-8 regression: drop ValidatingPublisher's explicit
    ``saturation()`` delegation and the cross-module pass must flag the
    base class's concrete ``{}`` default shadowing ``__getattr__`` —
    the bug that silently disabled the throttle/pacer in the assembled
    pipeline."""
    src = _VALIDATING.read_text()
    start = src.index("    def saturation(self)")
    end = src.index("    def pending_depths(self)")
    assert 0 < start < end, "ValidatingPublisher moved; update the test"
    pkg = tmp_path / "copilot_for_consensus_tpu" / "bus"
    pkg.mkdir(parents=True)
    (pkg / "base.py").write_text(_BUS_BASE.read_text())
    (pkg / "validating.py").write_text(src[:start] + src[end:])
    found = [f for f in racecheck.check_cross(
                 [pkg / "base.py", pkg / "validating.py"])
             if f.rule == "race-wrapper-shadow"]
    assert any("'saturation'" in f.message
               and f.context == "ValidatingPublisher"
               for f in found), found
    # the unmutated pair is clean (the explicit delegation overrides)
    clean = [f for f in racecheck.check_cross([_BUS_BASE, _VALIDATING])
             if f.rule == "race-wrapper-shadow"]
    assert clean == [], clean


def test_unlocked_broker_stats_fails_the_lane(tmp_path):
    """Dropping ``_stats_lock`` from the publisher's stats mutation
    must flag race-unlocked-field (the ledger is read under the lock
    elsewhere)."""
    src = _BROKER.read_text()
    needle = ("        with self._stats_lock:\n"
              "            self._stats[key] += n\n")
    assert needle in src, "_bump moved; update the test"
    mutated = tmp_path / "broker_mutated.py"
    mutated.write_text(src.replace(
        needle, "        self._stats[key] += n\n", 1))
    found = [f for f in analyze_files([mutated])
             if f.rule == "race-unlocked-field"]
    assert any("'_stats'" in f.message and "_bump" in f.context
               for f in found), found


_SHIP = ROOT / "copilot_for_consensus_tpu" / "obs" / "ship.py"


def test_fire_and_forget_ship_pump_fails_the_lane(tmp_path):
    """ISSUE-20 tripwire on the REAL shipper: replace the pump's
    stop-aware wait loop with a bare sleep loop AND drop the owner
    join — race-thread-lifecycle must flag the now-unstoppable pump
    thread."""
    src = _SHIP.read_text()
    loop_needle = ("        while not self._stop.is_set():\n"
                   "            self._stop.wait(self.interval_s)\n")
    join_needle = ("        if thread is not None:\n"
                   "            thread.join(timeout=5.0)\n")
    assert loop_needle in src and join_needle in src, \
        "TelemetryShipper pump/stop moved; update the test"
    mutated = tmp_path / "ship_mutated.py"
    mutated.write_text(
        src.replace(loop_needle,
                    "        while True:\n"
                    "            time.sleep(self.interval_s)\n", 1)
        .replace(join_needle, "", 1))
    found = [f for f in analyze_files([mutated])
             if f.rule == "race-thread-lifecycle"]
    assert any("TelemetryShipper" in f.context or "_pump" in f.message
               for f in found), found
    # the unmutated file is part of the clean e2e run (no findings)


def test_torn_spool_flush_fails_the_lane(tmp_path):
    """ISSUE-20 tripwire on the REAL spool: drop the one-transaction
    wrapper around the append loop (per-row autocommit — a SIGKILL
    mid-flush would commit a torn batch) — dura-sqlite-ledger must
    flag the unscoped mutating loop."""
    src = _SHIP.read_text()
    needle = ("                with self._db:\n"
              "                    for kind, payload in batch:\n"
              "                        self._db.execute(\n")
    assert needle in src, "TelemetrySpool.append moved; update the test"
    mutated = tmp_path / "spool_mutated.py"
    mutated.write_text(src.replace(
        needle,
        "                for kind, payload in batch:\n"
        "                    self._db.execute(\n", 1).replace(
        "                            \"INSERT INTO rows (kind, payload) \"\n"
        "                            \"VALUES (?, ?)\", (kind, payload))\n",
        "                        \"INSERT INTO rows (kind, payload) \"\n"
        "                        \"VALUES (?, ?)\", (kind, payload))\n", 1))
    found = [f for f in analyze_files([mutated], {"dura"})
             if f.rule == "dura-sqlite-ledger"]
    assert any("transaction" in f.message for f in found), found


# -- baseline round trip + CLI group filter for the race family


def test_race_baseline_round_trip(tmp_path, capsys):
    """racecheck findings ride the existing baseline machinery: a
    justified entry silences the finding; a TODO placeholder warns on a
    normal run and fails under --strict (the PR-4 rejection rule)."""
    fixture = RACE_FIXTURES / "unlocked_field.py"
    found = [f for f in analyze_files([fixture])
             if f.rule == "race-unlocked-field"]
    assert found
    entries = [{"rule": f.rule, "path": f.path, "context": f.context,
                "message": f.message,
                "justification": "fixture: deliberate bare access kept "
                                 "to prove the baseline round trip"}
               for f in found]
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(entries))
    args = ["--group", "race", "--baseline", str(bl), str(fixture)]
    assert jaxlint_main(args) == 0, capsys.readouterr().out
    for e in entries:
        e["justification"] = "TODO: explain why this is deliberate"
    bl.write_text(json.dumps(entries))
    assert jaxlint_main(args) == 0          # non-strict: warn only
    assert "baseline-unjustified" in capsys.readouterr().err
    rc = jaxlint_main(["--strict"] + args)
    out = capsys.readouterr()
    assert rc == 1
    assert "baseline-unjustified" in out.out


def test_cli_group_filter(capsys):
    """--group runs one rule family: the race fixture fails under
    --group race and passes under --group jax (whose rules don't fire
    on it) — the dev-loop filter the CI matrix uses."""
    fixture = str(RACE_FIXTURES / "callback_under_lock.py")
    rc = jaxlint_main(["--group", "race", "--no-baseline", fixture])
    out = capsys.readouterr()
    assert rc == 1
    assert "race-callback-under-lock" in out.out
    rc = jaxlint_main(["--group", "jax", "--no-baseline", fixture])
    capsys.readouterr()
    assert rc == 0


def test_repo_race_group_clean_with_cross_pass():
    """The full-repo race run (including the cross-module
    wrapper-shadow pass that --fast skips) is clean — the acceptance
    bar for dogfooding the analyzer over its own thread plane."""
    proc = subprocess.run(
        [sys.executable, "-m", "copilot_for_consensus_tpu.analysis",
         "--group", "race", "--strict"], cwd=ROOT,
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "[race]" in proc.stderr, proc.stderr


# ---------------------------------------------------------------------------
# duracheck (the `dura` group): the crash-safety / exactly-once
# contracts from docs/RESILIENCE.md. Each rule proven against its
# fixture — one true positive AND one clean negative — plus tripwires
# that re-introduce the REAL shipped bug classes (PR-11 commit/publish
# window, PR-12 journal ordering, the finisher's transient re-raise)
# and assert the lane turns red.
# ---------------------------------------------------------------------------

DURA_FIXTURES = ROOT / "tests" / "fixtures" / "duracheck"

from copilot_for_consensus_tpu.analysis import duracheck  # noqa: E402


def _dura_findings(fixture: str, rule: str):
    out = analyze_files([DURA_FIXTURES / fixture], {"dura"})
    return [f for f in out if f.rule == rule]


@pytest.mark.parametrize("fixture,rule,bad_marker,good_marker", [
    ("commit_publish_window.py", "dura-commit-publish-window",
     "BadFreshOnlyPublisher", "GoodRepublishStored"),
    ("raw_publish.py", "dura-raw-publish", "BadRawEnvelopePublisher",
     "GoodTypedPublisher"),
    ("ack_swallow.py", "dura-ack-swallow", "BadSwallowingHandler",
     "GoodClassifyingHandler"),
    ("journal_order.py", "dura-journal-order", "BadSubmitAfterEnqueue",
     "GoodJournalOrder"),
    ("idempotent_write.py", "dura-idempotent-write", "BadBlindInsert",
     "GoodDupTolerantInsert"),
    ("sqlite_ledger.py", "dura-sqlite-ledger", "BadLedger",
     "GoodLedger"),
    # telemetry spool (ISSUE 20): a spool without WAL + one-transaction
    # flushes must flag; the TelemetrySpool shape stays clean
    ("ship_spool.py", "dura-sqlite-ledger", "BadSpool", "GoodSpool"),
])
def test_dura_rule_true_positive_and_clean_negative(fixture, rule,
                                                    bad_marker,
                                                    good_marker):
    found = _dura_findings(fixture, rule)
    assert any(bad_marker in f.context or bad_marker in f.message
               for f in found), (rule, found)
    assert not any(good_marker in f.context or good_marker in f.message
                   for f in found), (rule, found)


def test_dura_rules_registered_under_dura_group():
    """duracheck.RULES and the CLI's RULES map must stay in sync (the
    group-scoped baseline judgment keys off this mapping)."""
    from copilot_for_consensus_tpu.analysis import RULES
    for rule in duracheck.RULES:
        assert RULES.get(rule) == "dura", rule


def test_journal_order_flags_both_halves():
    """Submit-before-enqueue AND retire-after-harvest are one
    contract; each half must flag independently."""
    ctxs = {f.context for f in
            _dura_findings("journal_order.py", "dura-journal-order")}
    assert "BadSubmitAfterEnqueue.submit" in ctxs, ctxs
    assert "BadRetireBeforeHarvest.harvest" in ctxs, ctxs
    assert not any("GoodJournalOrder" in c for c in ctxs), ctxs


def test_sqlite_ledger_flags_all_three_disciplines():
    msgs = "\n".join(f.message for f in
                     _dura_findings("sqlite_ledger.py",
                                    "dura-sqlite-ledger"))
    assert "journal_mode=WAL" in msgs, msgs
    assert "transaction" in msgs, msgs
    assert "owner-joined close" in msgs, msgs


def test_ack_swallow_accepts_all_three_classifying_exits():
    """re-raise, `return exc`, and a *Failed-event publish are the
    legitimate exits — none of GoodClassifyingHandler's three handlers
    may flag, and the swallowing handler is the only finding."""
    found = _dura_findings("ack_swallow.py", "dura-ack-swallow")
    assert {f.context for f in found} == \
        {"BadSwallowingHandler.on_JobReady"}, found


def test_raw_publish_flags_wire_protocol_op():
    """A raw broker `pub` op is the sneakier outbox bypass — it must
    flag alongside the publish_envelope form."""
    found = _dura_findings("raw_publish.py", "dura-raw-publish")
    assert any(f.context == "BadRawBrokerOp.on_FlushRequested"
               for f in found), found


def test_effect_provenance_not_name_tokens(tmp_path):
    """Receivers resolve by PROVENANCE: a renamed field bound from an
    `EventPublisher`-annotated param is a publisher; an unrelated
    object whose method merely shares a name is not."""
    mod = tmp_path / "renamed.py"
    mod.write_text(
        "class RenamedFieldHandler:\n"
        "    def __init__(self, bus: EventPublisher):\n"
        "        self.bus = bus\n\n"
        "    def on_ThingHappened(self, event):\n"
        "        self.bus.publish_envelope(event.to_envelope(), 'x')\n\n\n"
        "class NotAPublisher:\n"
        "    def __init__(self, codec):\n"
        "        self.codec = codec\n\n"
        "    def on_ThingHappened(self, event):\n"
        "        self.codec.publish_envelope(event)\n")
    found = [f for f in analyze_files([mod], {"dura"})
             if f.rule == "dura-raw-publish"]
    assert any("RenamedFieldHandler" in f.context for f in found), found
    assert not any("NotAPublisher" in f.context for f in found), found


# -- tripwires on the REAL runtime files: re-introduce each shipped
#    durability bug class

_PARSING = ROOT / "copilot_for_consensus_tpu" / "services" / "parsing.py"
_SERVICES_BASE = ROOT / "copilot_for_consensus_tpu" / "services" / "base.py"


def test_dropping_redelivery_republish_fails_the_lane(tmp_path):
    """PR-11 regression: publish only the fresh rows (drop
    `stored_unchunked` from the republish) and the commit/publish
    crash window is back — dura-commit-publish-window must flag."""
    src = _PARSING.read_text()
    needle = 'to_publish[b["archive_id"]] = fresh + stored_unchunked'
    assert needle in src, "_store_parsed moved; update the test"
    mutated = tmp_path / "parsing_mutated.py"
    mutated.write_text(src.replace(
        needle, 'to_publish[b["archive_id"]] = fresh', 1))
    found = [f for f in analyze_files([mutated], {"dura"})
             if f.rule == "dura-commit-publish-window"]
    assert any("_store_parsed" in f.context for f in found), found
    # the unmutated file is clean under the dura group
    assert analyze_files([_PARSING], {"dura"}) == []


def test_submit_after_scheduler_insert_fails_the_lane(tmp_path):
    """PR-12 regression: a scheduler insertion before `record_submit`
    re-opens the crash window where admitted work is invisible to
    restart replay — dura-journal-order must flag."""
    src = _GEN.read_text()
    needle = "                ids = _trace.current_ids()\n"
    assert src.count(needle) == 1, "submit block moved; update the test"
    mutated = tmp_path / "generation_mutated.py"
    mutated.write_text(src.replace(
        needle, needle + "                self._sched.enqueue(prompt)\n",
        1))
    found = [f for f in analyze_files([mutated], {"dura"})
             if f.rule == "dura-journal-order"]
    assert any(f.context == "GenerationEngine.submit"
               for f in found), found
    assert analyze_files([_GEN], {"dura"}) == []


def test_swallowed_retryable_in_wave_finisher_fails_the_lane(tmp_path):
    """Contract regression: remove the finisher's re-raise after the
    transient (PublishError/RetryableError) metrics bump and the nack/
    redeliver path is silently gone — dura-ack-swallow must flag."""
    src = _SERVICES_BASE.read_text()
    needle = ('                        labels={"event": etype, '
              '"ok": "false"})\n'
              '                    raise\n')
    assert src.count(needle) == 1, "finisher catch moved; update the test"
    mutated = tmp_path / "base_mutated.py"
    mutated.write_text(src.replace(
        needle,
        '                        labels={"event": etype, '
        '"ok": "false"})\n', 1))
    found = [f for f in analyze_files([mutated], {"dura"})
             if f.rule == "dura-ack-swallow"]
    assert any("_finish_wave_envelope" in f.context for f in found), found


# -- baseline round trip + full-repo cleanliness for the dura family


def test_dura_baseline_round_trip(tmp_path, capsys):
    """dura findings ride the existing baseline machinery: a justified
    entry silences the finding; a TODO placeholder warns on a normal
    run and fails under --strict."""
    fixture = DURA_FIXTURES / "ack_swallow.py"
    found = [f for f in analyze_files([fixture], {"dura"})
             if f.rule == "dura-ack-swallow"]
    assert found
    entries = [{"rule": f.rule, "path": f.path, "context": f.context,
                "message": f.message,
                "justification": "fixture: deliberate swallow kept to "
                                 "prove the baseline round trip"}
               for f in found]
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(entries))
    args = ["--group", "dura", "--baseline", str(bl), str(fixture)]
    assert jaxlint_main(args) == 0, capsys.readouterr().out
    for e in entries:
        e["justification"] = "TODO: explain why this is deliberate"
    bl.write_text(json.dumps(entries))
    assert jaxlint_main(args) == 0          # non-strict: warn only
    assert "baseline-unjustified" in capsys.readouterr().err
    rc = jaxlint_main(["--strict"] + args)
    out = capsys.readouterr()
    assert rc == 1
    assert "baseline-unjustified" in out.out


def test_repo_dura_group_clean():
    """The full-repo dura run is clean under --strict — the acceptance
    bar for dogfooding the durability contracts over the live
    pipeline and serving planes."""
    proc = subprocess.run(
        [sys.executable, "-m", "copilot_for_consensus_tpu.analysis",
         "--group", "dura", "--strict"], cwd=ROOT,
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "[dura]" in proc.stderr, proc.stderr


def test_repo_is_clean_end_to_end():
    """The whole tree passes every jaxlint group (modulo the committed,
    justified baseline). --fast skips import smoke, which the suite
    itself already proves by importing everything."""
    proc = subprocess.run(
        [sys.executable, "-m", "copilot_for_consensus_tpu.analysis",
         "--fast"], cwd=ROOT, capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
