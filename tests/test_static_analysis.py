# The first-party static-analysis lane must stay green AND keep
# catching what it claims to catch (a policy that can't fail is not a
# policy — same spirit as the fuzzer's seeded-bug effectiveness proof).
import pathlib
import subprocess
import sys
import textwrap

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
SCRIPT = ROOT / "scripts" / "validate_python.py"

sys.path.insert(0, str(ROOT / "scripts"))
import validate_python as vp  # noqa: E402


def test_repo_is_clean_fast():
    """Syntax + AST policies hold over the whole source tree (the
    import-smoke stage runs in CI's dedicated lint job; the suite
    itself already imports everything)."""
    proc = subprocess.run(
        [sys.executable, str(SCRIPT), "--fast"], cwd=ROOT,
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.parametrize("snippet,expect", [
    ("def f(x=[]):\n    return x\n", "mutable default"),
    ("def f(x={'a': 1}):\n    return x\n", "mutable default"),
    ("try:\n    pass\nexcept:\n    pass\n", "bare 'except:'"),
    ("import json\nimport os\nprint(os.name)\n", "unused import 'json'"),
    ("def f(:\n    pass\n", "syntax"),
])
def test_lane_catches_seeded_bugs(tmp_path, snippet, expect):
    bad = tmp_path / "seeded.py"
    bad.write_text(textwrap.dedent(snippet))
    errs = (vp.check_syntax([bad]) if expect == "syntax" else
            vp.check_syntax([bad])
            + vp.check_mutable_defaults([bad])
            + vp.check_bare_except([bad])
            + vp.check_unused_imports([bad]))
    assert any(expect in e for e in errs), errs


def test_lane_exemptions_hold(tmp_path):
    """noqa lines, __all__ strings, and used imports must NOT flag."""
    ok = tmp_path / "ok.py"
    ok.write_text(
        "import json  # noqa: used by doctest\n"
        "import os\n"
        "__all__ = ['os']\n"
        "print(os.name)\n")
    assert vp.check_unused_imports([ok]) == []


def test_syntax_error_reported_not_crashing(tmp_path):
    """A file with a syntax error must yield ONE syntax finding from
    the whole lane, never an unhandled SyntaxError out of main()."""
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n    pass\n")
    errs = (vp.check_syntax([bad]) + vp.check_mutable_defaults([bad])
            + vp.check_bare_except([bad])
            + vp.check_unused_imports([bad]))
    assert len(errs) == 1 and "syntax" in errs[0]


def test_constructor_call_defaults_flagged(tmp_path):
    bad = tmp_path / "ctor.py"
    bad.write_text("def f(x=list(), y=dict()):\n    return x, y\n")
    errs = vp.check_mutable_defaults([bad])
    assert len(errs) == 2
    # frozen-config style defaults (arbitrary constructor calls) pass:
    # only the builtin mutable containers are the documented class
    ok = tmp_path / "cfg.py"
    ok.write_text("def f(x=Config()):\n    return x\n")
    assert vp.check_mutable_defaults([ok]) == []
