# Async dispatcher front-end: thread-safe submits, correctness vs the
# synchronous engine, and liveness under staggered arrivals.
import threading

import jax
import jax.numpy as jnp
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu.engine.async_runner import AsyncEngineRunner
from copilot_for_consensus_tpu.engine.generation import GenerationEngine
from copilot_for_consensus_tpu.models import decoder
from copilot_for_consensus_tpu.models.configs import decoder_config

CFG = decoder_config("tiny")
PARAMS = decoder.init_params(jax.random.PRNGKey(7), CFG, dtype=jnp.float32)


def _engine(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("decode_window", 4)
    return GenerationEngine(CFG, PARAMS, **kw)


def test_async_matches_sync_results():
    prompts = [[5, 9, 13], [7, 8, 9, 10], [3, 4], [40, 41, 42]]
    sync = {tuple(p): c.tokens
            for p, c in zip(prompts,
                            _engine().generate(prompts, max_new_tokens=6))}
    runner = AsyncEngineRunner(_engine()).start()
    try:
        handles = [(p, runner.submit(list(p), 6)) for p in prompts]
        for p, h in handles:
            assert h.result(timeout=120).tokens == sync[tuple(p)]
    finally:
        runner.stop()


def test_async_concurrent_submitters_and_stragglers():
    """Submits from many threads, arriving while earlier requests are
    mid-decode, all complete; more requests than slots queue cleanly."""
    runner = AsyncEngineRunner(_engine(num_slots=2)).start()
    results = {}
    lock = threading.Lock()

    def client(i):
        h = runner.submit([3 + i, 4 + i, 5 + i], 5)
        c = h.result(timeout=120)
        with lock:
            results[i] = c

    try:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(7)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(results) == 7
        assert runner.completed == 7
        for i, c in results.items():
            assert c.prompt_len == 3 and 1 <= len(c.tokens) <= 5
    finally:
        runner.stop()


def test_async_submit_before_start_raises():
    runner = AsyncEngineRunner(_engine())
    with pytest.raises(RuntimeError):
        runner.submit([1, 2, 3], 4)


def test_async_stop_fails_outstanding_handles_promptly():
    """stop() must resolve blocked callers with RuntimeError instead of
    leaving them to sit out their full result() timeout."""
    import time

    class _StubEngine:
        """Never completes anything; step() blocks until released."""

        def __init__(self):
            self._active = {}
            self._queue = []
            self.release = threading.Event()
            self._rid = 0

        def submit(self, prompt, max_new_tokens):
            self._rid += 1
            self._queue.append(self._rid)
            return self._rid

        def step(self):
            self.release.wait(10)
            return []

    eng = _StubEngine()
    runner = AsyncEngineRunner(eng).start()
    h = runner.submit([1, 2, 3], 4)
    errs = []

    def waiter():
        try:
            h.result(timeout=60)
        except BaseException as exc:   # noqa: BLE001 — record whatever
            errs.append(exc)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)                    # let the dispatcher enter step()
    t0 = time.monotonic()
    eng.release.set()
    runner.stop()
    t.join(timeout=10)
    assert not t.is_alive()
    assert time.monotonic() - t0 < 10        # promptly, not the full 60s
    assert len(errs) == 1 and isinstance(errs[0], RuntimeError)
    assert "runner stopped" in str(errs[0])


def test_async_bad_request_fails_its_handle_not_the_loop():
    """An invalid submit (empty prompt) must error THAT handle while the
    dispatcher keeps serving everyone else."""
    runner = AsyncEngineRunner(_engine()).start()
    try:
        bad = runner.submit([], 4)
        good = runner.submit([5, 6, 7], 4)
        with pytest.raises(ValueError, match="empty prompt"):
            bad.result(timeout=60)
        assert len(good.result(timeout=120).tokens) >= 1
    finally:
        runner.stop()


def test_done_callbacks_fire_without_polling():
    """The r5 harvest path: callbacks fire on resolution (dispatcher
    thread), fire immediately when registered after resolution, and
    fire on failure too — no caller ever needs to poll done()."""
    runner = AsyncEngineRunner(_engine()).start()
    try:
        fired = []
        ev = threading.Event()
        h = runner.submit([5, 6, 7], 4)
        h.add_done_callback(lambda hh: (fired.append(hh.request_id),
                                        ev.set()))
        assert ev.wait(120)
        assert fired == [h.request_id]
        assert h.done() and h.result(0).tokens
        # late registration: fires immediately on the calling thread
        late = []
        h.add_done_callback(lambda hh: late.append("now"))
        assert late == ["now"]
        # failure path: bad request resolves its handle via callback
        fail_ev = threading.Event()
        bad = runner.submit([], 4)
        bad.add_done_callback(lambda hh: fail_ev.set())
        assert fail_ev.wait(30)
        with pytest.raises(Exception):
            bad.result(0)
        # a raising callback must not kill the dispatcher
        h2 = runner.submit([9, 9], 4)
        h2.add_done_callback(lambda hh: 1 / 0)
        assert h2.result(120).tokens
        h3 = runner.submit([4, 5], 4)
        assert h3.result(120).tokens       # dispatcher still alive
    finally:
        runner.stop()


def test_immediate_fire_callback_errors_are_contained():
    """Regression (ADVICE r5): a raising observer registered AFTER
    resolution fired on the caller's stack UNwrapped, while the same
    observer registered before resolution was contained by _finish —
    whether the error escaped depended on the registration/resolution
    race. Both paths must swallow observer errors identically."""
    from copilot_for_consensus_tpu.engine.async_runner import Handle
    from copilot_for_consensus_tpu.engine.generation import Completion

    h = Handle()
    h.request_id = 1
    h._resolve(Completion(request_id=1, prompt_len=3, tokens=[4],
                          finish_reason="length"))
    fired = []
    # already resolved → fires immediately — and must NOT raise
    h.add_done_callback(lambda hh: (fired.append(hh.request_id),
                                    1 / 0))
    assert fired == [1]
    # same containment on the failure-resolved path
    h2 = Handle()
    h2._fail(RuntimeError("boom"))
    h2.add_done_callback(lambda hh: 1 / 0)   # must not raise either
