import pytest

from copilot_for_consensus_tpu.core.retry import (
    DocumentNotFoundError,
    RetryConfig,
    RetryExhaustedError,
    RetryPolicy,
    handle_event_with_retry,
)


def _policy(max_attempts=4):
    return RetryPolicy(RetryConfig(max_attempts=max_attempts, base_delay=0.001),
                       sleep=lambda _: None)


def test_succeeds_after_transient_failures():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise DocumentNotFoundError("not yet")
        return "ok"

    assert _policy().run(flaky) == "ok"
    assert calls["n"] == 3


def test_exhaustion_carries_dlq_info():
    def always_fail():
        raise DocumentNotFoundError("never")

    with pytest.raises(RetryExhaustedError) as exc_info:
        _policy(max_attempts=3).run(always_fail, event_type="JSONParsed")
    err = exc_info.value
    assert err.attempts == 3
    assert err.event_type == "JSONParsed"
    assert err.dlq_info["error_type"] == "DocumentNotFoundError"


def test_non_retryable_errors_propagate_immediately():
    calls = {"n": 0}

    def boom():
        calls["n"] += 1
        raise ValueError("fatal")

    with pytest.raises(ValueError):
        _policy().run(boom)
    assert calls["n"] == 1


def test_backoff_is_exponential_and_capped():
    p = RetryPolicy(RetryConfig(base_delay=0.1, max_delay=0.5, jitter="none"))
    assert p.delay_for(1) == pytest.approx(0.1)
    assert p.delay_for(2) == pytest.approx(0.2)
    assert p.delay_for(3) == pytest.approx(0.4)
    assert p.delay_for(4) == pytest.approx(0.5)  # capped
    assert p.delay_for(10) == pytest.approx(0.5)


def test_full_jitter_within_bounds():
    p = _policy()
    for attempt in range(1, 5):
        for _ in range(20):
            d = p.delay_for(attempt)
            assert 0.0 <= d <= 0.001 * (2 ** (attempt - 1))


def test_handle_event_with_retry_wraps_envelope():
    seen = []

    def handler(env):
        seen.append(env)
        if len(seen) < 2:
            raise DocumentNotFoundError("race")
        return "done"

    env = {"event_type": "ChunksPrepared", "data": {}}
    assert handle_event_with_retry(handler, env, _policy()) == "done"
    assert len(seen) == 2
