# SPMD GPipe pipeline over the pp mesh axis vs the plain forward oracle.
import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu import train
from copilot_for_consensus_tpu.models import decoder
from copilot_for_consensus_tpu.models.configs import decoder_config
from copilot_for_consensus_tpu.parallel import MeshConfig, build_mesh
from copilot_for_consensus_tpu.parallel.pipeline import (
    make_pipeline_train_step,
    pipeline_forward,
    shard_params_for_pipeline,
)


def _setup(n_layers, seed=0, batch=4, seq=32):
    cfg = decoder_config("tiny", n_layers=n_layers)
    params = decoder.init_params(jax.random.PRNGKey(seed), cfg,
                                 dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(seed + 1),
                                (batch, seq), 0, cfg.vocab_size)
    return cfg, params, tokens


@pytest.mark.parametrize("pp,n_layers,m", [(2, 2, 2), (4, 4, 4),
                                           (2, 4, 1), (8, 8, 2)])
def test_pipeline_forward_matches_plain(pp, n_layers, m):
    cfg, params, tokens = _setup(n_layers)
    mesh = build_mesh(MeshConfig(pp=pp, tp=0))
    sharded = shard_params_for_pipeline(params, cfg, mesh)
    ref = decoder.forward(params, tokens, cfg)
    out = jax.jit(
        lambda p, t: pipeline_forward(p, t, cfg, mesh, n_microbatches=m)
    )(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_forward_with_padded_lengths():
    cfg, params, tokens = _setup(2)
    mesh = build_mesh(MeshConfig(pp=2, tp=0))
    sharded = shard_params_for_pipeline(params, cfg, mesh)
    lengths = jnp.asarray([32, 20, 11, 32], jnp.int32)
    ref = decoder.forward(params, tokens, cfg, lengths=lengths)
    out = pipeline_forward(sharded, tokens, cfg, mesh, n_microbatches=2,
                           lengths=lengths)
    # Compare only valid positions: padded tails see different garbage.
    for b in range(4):
        ln = int(lengths[b])
        np.testing.assert_allclose(np.asarray(out)[b, :ln],
                                   np.asarray(ref)[b, :ln],
                                   rtol=2e-4, atol=2e-4)


def test_pipeline_train_step_matches_plain_loss_and_updates():
    """Gradients flow through ppermute: one optimizer step under the
    pipeline must match the unpipelined train step."""
    cfg, params, tokens = _setup(4)
    lengths = jnp.full((4,), 32, jnp.int32)
    mesh = build_mesh(MeshConfig(pp=4, tp=0))
    opt = train.default_optimizer()

    plain_step = jax.jit(train.make_train_step(cfg, opt, attn_impl="xla"))
    p_ref, _, loss_ref = plain_step(params, opt.init(params), tokens,
                                    lengths)

    sharded = shard_params_for_pipeline(params, cfg, mesh)
    pp_step = jax.jit(make_pipeline_train_step(cfg, opt, mesh,
                                               n_microbatches=2))
    p_pp, _, loss_pp = pp_step(sharded, opt.init(sharded), tokens, lengths)

    assert abs(float(loss_pp) - float(loss_ref)) < 1e-4
    # Updated weights agree leaf-by-leaf.
    for ref_leaf, pp_leaf in zip(jax.tree.leaves(p_ref),
                                 jax.tree.leaves(p_pp)):
        np.testing.assert_allclose(np.asarray(pp_leaf),
                                   np.asarray(ref_leaf),
                                   rtol=1e-3, atol=1e-3)


def test_pipeline_rejects_indivisible_shapes():
    cfg, params, tokens = _setup(3)
    mesh = build_mesh(MeshConfig(pp=2, tp=0))
    with pytest.raises(ValueError):
        pipeline_forward(params, tokens, cfg, mesh, n_microbatches=2)
    cfg2, params2, tokens2 = _setup(2)
    with pytest.raises(ValueError):
        pipeline_forward(params2, tokens2, cfg2, mesh, n_microbatches=3)


@pytest.mark.parametrize("pp,tp,n_layers,m,dp", [(2, 2, 2, 2, 2),
                                                 (2, 2, 4, 1, 2),
                                                 (4, 2, 4, 2, 1)])
def test_pipeline_forward_pp_x_tp_matches_plain(pp, tp, n_layers, m, dp):
    """VERDICT r2 item 9: intra-stage tensor parallelism — each stage's
    heads/ffn split over tp with Megatron column/row psums; the pp×tp
    pipeline must equal the plain forward."""
    cfg, params, tokens = _setup(n_layers)
    mesh = build_mesh(MeshConfig(pp=pp, tp=tp, dp=dp))
    sharded = shard_params_for_pipeline(params, cfg, mesh)
    ref = decoder.forward(params, tokens, cfg)
    out = jax.jit(
        lambda p, t: pipeline_forward(p, t, cfg, mesh, n_microbatches=m,
                                      tp_axis="tp")
    )(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_greedy_decode_pp_x_tp():
    """Decode THROUGH the pp×tp pipeline: greedy tokens match the
    single-device naive loop."""
    from copilot_for_consensus_tpu.parallel.pipeline import (
        pipeline_greedy_decode,
    )

    cfg, params, _ = _setup(2, batch=2, seq=8)
    mesh = build_mesh(MeshConfig(pp=2, tp=2, dp=2))
    sharded = shard_params_for_pipeline(params, cfg, mesh)
    prompt = jax.random.randint(jax.random.PRNGKey(9), (2, 8), 3,
                                cfg.vocab_size)
    out = pipeline_greedy_decode(sharded, prompt, cfg, mesh,
                                 n_new_tokens=6, tp_axis="tp")
    # naive oracle
    want = []
    for b in range(2):
        seq = list(np.asarray(prompt[b]))
        row = []
        for _ in range(6):
            logits = decoder.forward(params,
                                     jnp.asarray([seq], jnp.int32), cfg)
            nxt = int(jnp.argmax(logits[0, -1]))
            row.append(nxt)
            seq.append(nxt)
        want.append(row)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(want))


def test_pipeline_tp_rejects_indivisible_heads():
    cfg, params, tokens = _setup(2)
    mesh = build_mesh(MeshConfig(pp=2, tp=4))
    with pytest.raises(ValueError, match="n_kv_heads"):
        pipeline_forward(params, tokens, decoder_config(
            "tiny", n_layers=2, n_kv_heads=2), mesh,
            n_microbatches=1, tp_axis="tp")
