# Embedding engine: batching/bucketing must preserve order and numerics.
import jax
import jax.numpy as jnp
import numpy as np

from copilot_for_consensus_tpu.engine.embedding import EmbeddingEngine
from copilot_for_consensus_tpu.engine.tokenizer import HashWordTokenizer
from copilot_for_consensus_tpu.models.configs import encoder_config

import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")

CFG = encoder_config("tiny")


def _engine(**kw):
    kw.setdefault("batch_size", 4)
    kw.setdefault("buckets", (8, 16, 32))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    return EmbeddingEngine(CFG, **kw)


def test_embed_batch_shape_and_norms():
    eng = _engine()
    texts = ["hello world", "consensus reached on the draft",
             "short", " ".join(["w"] * 100)]
    out = eng.embed_batch(texts)
    assert out.shape == (4, CFG.d_model)
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-4)


def test_batched_equals_individual():
    # Mixed lengths land in different buckets; order must be preserved and
    # each row must equal its solo embedding.
    eng = _engine()
    texts = [f"word{i} " * (i + 1) for i in range(9)]
    batched = eng.embed_batch(texts)
    for i, t in enumerate(texts):
        solo = eng.embed_batch([t])[0]
        np.testing.assert_allclose(batched[i], solo, rtol=1e-4, atol=1e-5)


def test_embed_single_parity():
    eng = _engine()
    v = eng.embed("the working group agrees")
    assert isinstance(v, list) and len(v) == CFG.d_model


def test_empty_and_degenerate_inputs():
    eng = _engine()
    assert eng.embed_batch([]).shape == (0, CFG.d_model)
    out = eng.embed_batch(["", "   "])
    assert out.shape == (2, CFG.d_model)
    assert np.all(np.isfinite(out))


def test_same_text_same_vector_different_text_different_vector():
    eng = _engine()
    a, b, c = eng.embed_batch(["alpha beta gamma", "alpha beta gamma",
                               "totally different text here"])
    np.testing.assert_allclose(a, b, rtol=1e-6)
    assert np.linalg.norm(a - c) > 1e-3


def test_tokenizer_vocab_guard():
    import pytest
    with pytest.raises(ValueError):
        _engine(tokenizer=HashWordTokenizer(10 * CFG.vocab_size))
