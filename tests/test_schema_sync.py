"""Policy check: the committed schema files must match their generators.

Editing an event dataclass or config spec without re-running the generator
would silently diverge the runtime validation contract (generated schemas use
``additionalProperties: false`` + full ``required`` lists, so divergence means
every publish of that event fails validation).
"""

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
SCHEMAS = REPO / "copilot_for_consensus_tpu" / "schemas"


def _regenerate_and_compare(script: str, subdir: str, tmp_path,
                            glob: str = "*.json"):
    # Run the generator against a copied repo-layout so committed files are
    # untouched, then diff the schema trees.
    tmp_repo = tmp_path / "repo"
    (tmp_repo / "scripts").mkdir(parents=True)
    (tmp_repo / "scripts" / script).write_text(
        (REPO / "scripts" / script).read_text())
    pkg = tmp_repo / "copilot_for_consensus_tpu"
    pkg.mkdir()
    env = {"PYTHONPATH": str(REPO), "PATH": "/usr/bin:/bin"}
    subprocess.run([sys.executable, str(tmp_repo / "scripts" / script)],
                   check=True, env=env, capture_output=True)
    generated_root = pkg / "schemas" / subdir
    committed_root = SCHEMAS / subdir
    gen = {str(p.relative_to(generated_root)): json.loads(p.read_text())
           for p in generated_root.rglob(glob)}
    com = {str(p.relative_to(committed_root)): json.loads(p.read_text())
           for p in committed_root.rglob("*.schema.json")}
    assert set(gen) == set(com), (
        f"schema file set drift in {subdir}: generated-only="
        f"{sorted(set(gen) - set(com))} committed-only="
        f"{sorted(set(com) - set(gen))}; re-run scripts/{script}")
    for name, payload in gen.items():
        assert payload == com[name], f"schema drift in {subdir}/{name}: re-run scripts/{script}"


def test_event_schemas_in_sync(tmp_path):
    _regenerate_and_compare("generate_event_schemas.py", "events", tmp_path)


def test_config_schemas_in_sync(tmp_path):
    # Covers both trees the generator owns: configs/services and
    # configs/adapters/<kind>/<driver>.
    _regenerate_and_compare("generate_config_schemas.py", "configs", tmp_path)


def test_every_registered_driver_has_schema():
    """Registry ↔ schema coverage: each driver registered via
    core.factory for each adapter kind must ship a driver schema
    (the reference's per-driver config contract,
    docs/schemas/configs/adapters/drivers/*/*.json)."""
    from copilot_for_consensus_tpu.core import factory

    missing = []
    for kind in factory._KIND_MODULES:
        for driver in factory.available_drivers(kind):
            f = SCHEMAS / "configs" / "adapters" / kind / f"{driver}.schema.json"
            if not f.exists():
                missing.append(f"{kind}/{driver}")
    assert not missing, (
        f"drivers without schemas: {missing}; add to DRIVERS in "
        "scripts/generate_config_schemas.py and regenerate")
