"""Policy check: the committed schema files must match their generators.

Editing an event dataclass or config spec without re-running the generator
would silently diverge the runtime validation contract (generated schemas use
``additionalProperties: false`` + full ``required`` lists, so divergence means
every publish of that event fails validation).
"""

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
SCHEMAS = REPO / "copilot_for_consensus_tpu" / "schemas"


def _regenerate_and_compare(script: str, subdir: str, tmp_path):
    # Run the generator against a copied repo-layout so committed files are
    # untouched, then diff the schema trees.
    tmp_repo = tmp_path / "repo"
    (tmp_repo / "scripts").mkdir(parents=True)
    (tmp_repo / "scripts" / script).write_text(
        (REPO / "scripts" / script).read_text())
    pkg = tmp_repo / "copilot_for_consensus_tpu"
    pkg.mkdir()
    env = {"PYTHONPATH": str(REPO), "PATH": "/usr/bin:/bin"}
    subprocess.run([sys.executable, str(tmp_repo / "scripts" / script)],
                   check=True, env=env, capture_output=True)
    generated_root = pkg / "schemas" / subdir
    committed_root = SCHEMAS / subdir
    gen = {p.name: json.loads(p.read_text())
           for p in generated_root.glob("*.json")}
    com = {p.name: json.loads(p.read_text())
           for p in committed_root.glob("*.schema.json")}
    assert set(gen) == set(com), (
        f"schema file set drift in {subdir}: generated-only="
        f"{sorted(set(gen) - set(com))} committed-only="
        f"{sorted(set(com) - set(gen))}; re-run scripts/{script}")
    for name, payload in gen.items():
        assert payload == com[name], f"schema drift in {subdir}/{name}: re-run scripts/{script}"


def test_event_schemas_in_sync(tmp_path):
    _regenerate_and_compare("generate_event_schemas.py", "events", tmp_path)


def test_config_schemas_in_sync(tmp_path):
    _regenerate_and_compare("generate_config_schemas.py", "configs/services", tmp_path)
