"""Gateway config generation: spec → nginx/Azure/AWS/GCP edge configs.

Covers the role of the reference's ``infra/gateway/`` adapter layer:
one OpenAPI doc drives every provider, the auth boundary is projected
consistently, and the committed artifacts cannot go stale.
"""

from __future__ import annotations

import json
import pathlib

import pytest

from copilot_for_consensus_tpu.gateway import (
    create_gateway_adapter,
    routes_from_spec,
)
from copilot_for_consensus_tpu.gateway.providers import all_providers

REPO = pathlib.Path(__file__).resolve().parent.parent
SPEC_PATH = REPO / "copilot_for_consensus_tpu" / "schemas" / "openapi.json"


@pytest.fixture(scope="module")
def spec():
    return json.loads(SPEC_PATH.read_text())


def test_routes_from_spec_distills_auth_boundary(spec):
    routes = routes_from_spec(spec)
    assert len(routes) == len(spec["paths"])
    by_path = {r.path: r for r in routes}
    # The JWKS endpoint must be public (every provider fetches it to
    # validate tokens) and the reports API must be guarded.
    assert not by_path["/.well-known/jwks.json"].auth_required
    assert by_path["/api/reports"].auth_required
    assert "GET" in by_path["/api/reports"].methods


def test_unknown_provider_rejected():
    with pytest.raises(ValueError, match="unknown gateway provider"):
        create_gateway_adapter("heroku")


def test_nginx_config_routes_and_protects(spec):
    adapter = create_gateway_adapter("nginx")
    conf = adapter.generate(spec)["nginx.conf"]
    assert "upstream copilot_pipeline" in conf
    assert "proxy_pass http://copilot_pipeline;" in conf
    assert "listen 443 ssl" in conf
    # Probe/scrape endpoints must not be exposed at the public edge.
    for path in ("/metrics", "/health", "/readyz"):
        assert f"location = {path} {{ return 403; }}" in conf
    # Every edge route appears in the embedded route table.
    for route in adapter.edge_routes(spec):
        assert route.path in conf
    assert "limit_req_zone" in conf


def test_internal_paths_absent_from_cloud_edges(spec):
    """Cloud adapters must not forward /metrics, /health, /readyz."""
    aws = json.loads(create_gateway_adapter("aws").generate(spec)
                     ["cloudformation.json"])
    route_keys = {r["Properties"]["RouteKey"]
                  for r in aws["Resources"].values()
                  if r["Type"] == "AWS::ApiGatewayV2::Route"}
    gcp = json.loads(create_gateway_adapter("gcp").generate(spec)
                     ["api_gateway.json"])
    for path in ("/metrics", "/health", "/readyz"):
        assert not any(key.endswith(f" {path}") for key in route_keys)
        assert path not in gcp["paths"]


def test_edge_issuer_matches_app_default(spec):
    """The generated configs must validate the issuer the app actually
    mints (services/bootstrap.py: JWTManager issuer='copilot')."""
    policy = create_gateway_adapter("azure").generate(spec)["apim_policy.xml"]
    assert "<issuer>copilot</issuer>" in policy
    # AWS JWT authorizers require an HTTPS URL issuer (discovery-based),
    # so the issuer is a deploy-time parameter, not the bare app issuer.
    aws = json.loads(create_gateway_adapter("aws").generate(spec)
                     ["cloudformation.json"])
    auth = aws["Resources"]["JwtAuthorizer"]["Properties"]
    assert auth["JwtConfiguration"]["Issuer"] == {"Ref": "IssuerUrl"}
    assert "IssuerUrl" in aws["Parameters"]
    gcp = json.loads(create_gateway_adapter("gcp").generate(spec)
                     ["api_gateway.json"])
    assert gcp["securityDefinitions"]["copilot_jwt"][
        "x-google-issuer"] == "copilot"


def test_apim_public_allowlist_matches_templated_paths(spec):
    """The policy's public-path check is a regex, so templated public
    routes (/ui/{asset}) admit real asset requests (/ui/app.js)."""
    import re as _re

    policy = create_gateway_adapter("azure").generate(spec)["apim_policy.xml"]
    m = _re.search(r'IsMatch\(\s*context\.Request\.OriginalUrl\.Path,\s*'
                   r'@?&quot;(.+?)&quot;\)', policy, _re.S)
    assert m, "policy must embed a regex allowlist"
    pattern = _re.compile(m.group(1))
    assert pattern.match("/ui/app.js")
    assert pattern.match("/.well-known/jwks.json")
    assert not pattern.match("/api/reports")
    # Literal '.' is escaped: lookalike paths must NOT skip validation.
    assert not pattern.match("/Xwell-known/jwksXjson")
    # Discovery URL comes from the deploy-time named value, not a
    # baked-in compose hostname APIM could never resolve.
    assert ("{{copilot-backend-url}}/.well-known/openid-configuration"
            in policy)


def test_azure_template_embeds_spec_and_policy(spec):
    adapter = create_gateway_adapter("azure")
    files = adapter.generate(spec)
    template = json.loads(files["apim_template.json"])
    api = next(r for r in template["resources"]
               if r["type"] == "Microsoft.ApiManagement/service/apis")
    embedded = json.loads(api["properties"]["value"])
    # Only edge routes are imported — an APIM operation for /metrics
    # would let any valid-JWT holder scrape internals at the edge.
    assert embedded["paths"].keys() == {r.path
                                       for r in adapter.edge_routes(spec)}
    for path in ("/metrics", "/health", "/readyz"):
        assert path not in embedded["paths"]
    assert "validate-jwt" in files["apim_policy.xml"]


def test_aws_template_one_route_per_method(spec):
    adapter = create_gateway_adapter("aws")
    template = json.loads(adapter.generate(spec)["cloudformation.json"])
    route_resources = [r for r in template["Resources"].values()
                       if r["Type"] == "AWS::ApiGatewayV2::Route"]
    expected = sum(len(r.methods) for r in adapter.edge_routes(spec))
    assert len(route_resources) == expected
    # Guarded routes carry the JWT authorizer; public routes do not.
    keys_with_auth = {r["Properties"]["RouteKey"] for r in route_resources
                      if r["Properties"].get("AuthorizationType") == "JWT"}
    for route in adapter.edge_routes(spec):
        for method in route.methods:
            key = f"{method} {route.path}"
            assert (key in keys_with_auth) == route.auth_required


def test_gcp_swagger_is_valid_and_guarded(spec):
    adapter = create_gateway_adapter("gcp")
    swagger = json.loads(adapter.generate(spec)["api_gateway.json"])
    assert swagger["swagger"] == "2.0"
    assert "x-google-backend" in swagger
    assert "copilot_jwt" in swagger["securityDefinitions"]
    for route in adapter.edge_routes(spec):
        ops = swagger["paths"][route.path]
        for method in route.methods:
            op = ops[method.lower()]
            assert (op.get("security") == [{"copilot_jwt": []}]) \
                == route.auth_required
        # Path params must be declared for swagger 2.0 validity.
        if "{" in route.path:
            declared = {p["name"] for p in ops["parameters"]}
            templated = {seg[1:-1] for seg in route.path.split("/")
                         if seg.startswith("{")}
            assert declared == templated


def test_committed_configs_are_fresh(spec):
    """The files under infra/gateway/ must match regeneration output."""
    for provider in all_providers():
        adapter = create_gateway_adapter(provider)
        for rel, content in adapter.generate(spec).items():
            committed = REPO / "infra" / "gateway" / provider / rel
            assert committed.exists(), (
                f"missing {committed}; run scripts/generate_gateway_config.py")
            assert committed.read_text() == content, (
                f"{committed} is stale; run scripts/generate_gateway_config.py")
