# int8 weight-only quantization: exactness of the scale algebra, forward
# closeness, engine integration, sharding-axes transform.
import jax
import jax.numpy as jnp
import numpy as np

from copilot_for_consensus_tpu.engine.generation import GenerationEngine
from copilot_for_consensus_tpu.models import decoder, quant
from copilot_for_consensus_tpu.models.configs import decoder_config
from copilot_for_consensus_tpu.models.layers import qmatmul
from copilot_for_consensus_tpu.parallel import MeshConfig, build_mesh
from copilot_for_consensus_tpu.parallel.sharding import spec_tree


def test_qmatmul_equals_dequantized_matmul():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 48))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 64))
    qw = quant.quantize_tensor(w)
    ref = x @ (qw["q"].astype(jnp.float32) * qw["scale"])
    out = qmatmul(x, qw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)


def test_quantization_error_is_small():
    w = jax.random.normal(jax.random.PRNGKey(2), (128, 96)) * 0.05
    qw = quant.quantize_tensor(w)
    deq = qw["q"].astype(jnp.float32) * qw["scale"]
    err = np.abs(np.asarray(deq - w))
    assert err.max() <= np.abs(np.asarray(w)).max() / 127 + 1e-7


def test_quantized_forward_close_to_full_precision():
    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(3), cfg,
                                 dtype=jnp.float32)
    qparams = quant.quantize_params(params)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0,
                                cfg.vocab_size)
    ref = decoder.forward(params, tokens, cfg, attn_impl="xla")
    out = decoder.forward(qparams, tokens, cfg, attn_impl="xla")
    # int8 weights: logits agree to ~1e-1 absolute on a tiny model.
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0.35,
                               rtol=0.1)
    # top-1 predictions should essentially all agree
    agree = np.mean(np.argmax(np.asarray(out), -1)
                    == np.argmax(np.asarray(ref), -1))
    assert agree > 0.9


def test_moe_quantized_forward_runs():
    cfg = decoder_config("tiny-moe")
    params = decoder.init_params(jax.random.PRNGKey(5), cfg,
                                 dtype=jnp.float32)
    qparams = quant.quantize_params(params)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0,
                                cfg.vocab_size)
    out = decoder.forward(qparams, tokens, cfg, attn_impl="xla")
    assert bool(jnp.all(jnp.isfinite(out)))


def test_quantized_axes_match_quantized_params():
    cfg = decoder_config("tiny")
    params = quant.quantize_params(
        decoder.init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    axes = quant.quantize_logical_axes(decoder.logical_axes(cfg))
    assert (jax.tree.structure(axes,
                               is_leaf=lambda x: isinstance(x, tuple))
            == jax.tree.structure(params))
    # spec tree builds without unknown-axis errors
    spec_tree(axes)


def test_init_random_quantized_structure_and_engine():
    cfg = decoder_config("tiny")
    params = quant.init_random_quantized(jax.random.PRNGKey(1), cfg,
                                         dtype=jnp.float32)
    assert params["layers"]["wq"]["q"].dtype == jnp.int8
    assert params["layers"]["attn_norm"].dtype == jnp.float32
    eng = GenerationEngine(cfg, num_slots=2, max_len=32,
                           prefill_buckets=(16,), dtype=jnp.float32,
                           attn_impl="xla", quantize=True)
    comps = eng.generate([[5, 6, 7]], max_new_tokens=4)
    assert len(comps[0].tokens) == 4


def test_quantized_engine_on_mesh():
    cfg = decoder_config("tiny")
    mesh = build_mesh(MeshConfig(dp=2, tp=4))
    eng = GenerationEngine(cfg, mesh=mesh, num_slots=2, max_len=32,
                           prefill_buckets=(16,), dtype=jnp.float32,
                           attn_impl="xla", quantize=True)
    comps = eng.generate([[5, 6, 7], [9, 10, 11]], max_new_tokens=4)
    assert all(len(c.tokens) == 4 for c in comps)


def test_moe_int4_forward_runs():
    """int4-quantized MoE experts forward without error and stay close
    to the full-precision logits (the einsum path materializes the
    dequantized experts — group scales don't commute with einsum)."""
    cfg = decoder_config("tiny-moe")
    params = decoder.init_params(jax.random.PRNGKey(5), cfg,
                                 dtype=jnp.float32)
    qparams = quant.quantize_params(params, mode="int4")
    tokens = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0,
                                cfg.vocab_size)
    full = decoder.forward(params, tokens, cfg, attn_impl="xla")
    out = decoder.forward(qparams, tokens, cfg, attn_impl="xla")
    assert bool(jnp.all(jnp.isfinite(out)))
    f = np.asarray(full).reshape(-1, cfg.vocab_size)
    q = np.asarray(out).reshape(-1, cfg.vocab_size)
    cos = (f * q).sum(-1) / (np.linalg.norm(f, axis=-1)
                             * np.linalg.norm(q, axis=-1) + 1e-9)
    assert cos.min() > 0.9


def test_fuse_int4_projections_preserves_forward():
    """The fused wqkv / w_gu leaves must produce the same logits as the
    unfused int4 tree (identical nibbles + scales, split by column)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from copilot_for_consensus_tpu.models import decoder, quant
    from copilot_for_consensus_tpu.models.configs import decoder_config

    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(11), cfg,
                                 dtype=jnp.float32)
    qp = quant.quantize_params(params, mode="int4")
    fused = quant.fuse_int4_projections(qp)
    assert "wqkv" in fused["layers"] and "wq" not in fused["layers"]
    assert "w_gu" in fused["layers"]
    toks = jax.random.randint(jax.random.PRNGKey(12), (2, 9), 3,
                              cfg.vocab_size)
    ref = decoder.forward(qp, toks, cfg, attn_impl="xla")
    out = decoder.forward(fused, toks, cfg, attn_impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # idempotent + validation
    assert quant.fuse_int4_projections(fused) is fused or \
        "wqkv" in quant.fuse_int4_projections(fused)["layers"]


def test_fuse_int4_rejects_moe_leaves():
    """Review repro: MoE expert leaves must not be fused/deleted — the
    per-expert dispatch reads w_gate/w_up by name."""
    import jax
    import jax.numpy as jnp
    import pytest

    from copilot_for_consensus_tpu.models import decoder, quant
    from copilot_for_consensus_tpu.models.configs import decoder_config

    cfg = decoder_config("tiny-moe")
    params = decoder.init_params(jax.random.PRNGKey(1), cfg,
                                 dtype=jnp.float32)
    qp = quant.quantize_params(params, mode="int4")
    with pytest.raises(ValueError, match="dense FFN"):
        quant.fuse_int4_projections(qp)


def test_pallas_override_is_thread_local_and_scoped():
    """pallas_qmatmul_override must shadow the global flag only on the
    holding thread and only inside the block — it is how one engine
    re-routes one program without flipping the route under others."""
    import threading

    from copilot_for_consensus_tpu.models import quant

    prev = quant.pallas_qmatmul_enabled()
    quant.set_pallas_qmatmul(True)
    try:
        seen = {}

        def other_thread():
            seen["other"] = quant.pallas_qmatmul_enabled()

        with quant.pallas_qmatmul_override(False):
            assert not quant.pallas_qmatmul_enabled()
            t = threading.Thread(target=other_thread)
            t.start()
            t.join()
            # nesting restores the outer override, not the global
            with quant.pallas_qmatmul_override(True):
                assert quant.pallas_qmatmul_enabled()
            assert not quant.pallas_qmatmul_enabled()
        assert quant.pallas_qmatmul_enabled()
        assert seen["other"] is True
        # None = no-op passthrough
        with quant.pallas_qmatmul_override(None):
            assert quant.pallas_qmatmul_enabled()
    finally:
        quant.set_pallas_qmatmul(prev)


def test_engine_auto_routes_long_extent_int4_decode():
    """int4 engines past the extent threshold trace their decode
    program with the XLA dequant route (the 136 ms/step @3072 Pallas
    pathology, r4 verdict Weak 3); short-extent engines keep Pallas."""
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.engine.generation import (
        GenerationEngine,
    )
    from copilot_for_consensus_tpu.models import quant
    from copilot_for_consensus_tpu.models.configs import decoder_config

    prev = quant.pallas_qmatmul_enabled()
    # the auto-route only arms when the global Pallas route is on
    # (a sharded-engine test earlier in the session may have cleared it)
    quant.set_pallas_qmatmul(True)
    cfg = decoder_config("tiny", max_seq_len=4096)
    long_eng = GenerationEngine(
        cfg, num_slots=2, max_len=2048, prefill_buckets=(16,),
        dtype=jnp.float32, quantize="int4", decode_window=4)
    assert long_eng._decode_pallas_override is False
    short_eng = GenerationEngine(
        cfg, num_slots=2, max_len=256, prefill_buckets=(16,),
        dtype=jnp.float32, quantize="int4", decode_window=4)
    assert short_eng._decode_pallas_override is None
    off_eng = GenerationEngine(
        cfg, num_slots=2, max_len=2048, prefill_buckets=(16,),
        dtype=jnp.float32, quantize="int4", decode_window=4,
        int4_pallas_max_extent=None)
    assert off_eng._decode_pallas_override is None
    # the routed engine still generates (CPU: both routes are the XLA
    # expression, so this exercises the wrapped dispatch path only)
    try:
        comps = long_eng.generate([[5, 6, 7]], max_new_tokens=4)
        assert comps[0].tokens
    finally:
        quant.set_pallas_qmatmul(prev)
