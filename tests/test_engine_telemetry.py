# Engine flight recorder (engine/telemetry.py): request-lifecycle
# spans, step telemetry, Prometheus export, dump-on-error. Host-side
# unit tests run in the fast lane; engine e2e tests (JAX compiles) are
# slow-marked like the rest of the engine suite.
import json

import pytest

from copilot_for_consensus_tpu.engine.telemetry import (
    METRICS,
    EngineTelemetry,
    FlightRecorder,
    StepRecord,
    resolve_telemetry,
)
from copilot_for_consensus_tpu.obs.metrics import (
    InMemoryMetrics,
    NoopMetrics,
)


# -- host-side units (fast lane) ---------------------------------------


def test_flight_recorder_ring_is_bounded_and_ordered():
    fr = FlightRecorder(capacity=4)
    for _ in range(10):
        fr.record(StepRecord(seq=fr.next_seq(), kind="decode",
                             t_wall=0.0, duration_s=0.001))
    recs = fr.records()
    assert len(recs) == 4
    assert [r.seq for r in recs] == sorted(r.seq for r in recs)
    assert recs[-1].seq == 10              # newest kept, oldest evicted


def test_step_record_occupancy_and_padding_waste():
    r = StepRecord(seq=1, kind="prefill", t_wall=0.0, duration_s=0.1,
                   rows=3, batch=4, tokens=48, padded_tokens=4 * 64)
    assert r.occupancy == 0.75
    assert r.padding_waste == (256 - 48) / 256
    d = r.as_dict()
    assert d["kind"] == "prefill" and "occupancy" in d
    # degenerate records must not divide by zero
    z = StepRecord(seq=2, kind="decode", t_wall=0.0, duration_s=0.0)
    assert z.occupancy == 0.0 and z.padding_waste == 0.0


def test_span_lifecycle_math_and_metrics():
    tele = EngineTelemetry(engine="generation", num_slots=8)
    tr = tele.on_submit(7, prompt_len=100, correlation_id="corr-7")
    tele.on_admit(7, wave_start=tr.enqueued_at + 0.0,
                  admit_kind="seeded", prefix_hit_tokens=64)
    done = tele.on_retire(7, new_tokens=5, finish_reason="eos")
    assert done is tr
    assert tr.ttft_s >= 0 and tr.e2e_s >= tr.ttft_s
    assert tr.queue_wait_s >= 0 and tr.prefix_hit_tokens == 64
    assert tr.finish_reason == "eos" and tr.correlation_id == "corr-7"
    assert not tele.in_flight()
    m = tele.metrics
    assert m.counter_value("engine_requests_total",
                           {"engine": "generation",
                            "finish_reason": "eos"}) == 1
    # prompt tokens split into prefilled vs prefix-cache-seeded
    assert m.counter_value("engine_tokens_total",
                           {"engine": "generation",
                            "kind": "prompt"}) == 36
    assert m.counter_value("engine_tokens_total",
                           {"engine": "generation",
                            "kind": "prompt_cached"}) == 64
    assert m.histogram_stats("engine_ttft_seconds",
                             {"engine": "generation"})["count"] == 1
    # retiring an unknown id is a no-op, not a crash
    assert tele.on_retire(999, new_tokens=0, finish_reason="eos") is None


def test_latency_summary_percentiles_last_n():
    tele = EngineTelemetry(engine="generation", num_slots=4)
    for rid in range(10):
        tele.on_submit(rid, prompt_len=8)
        tele.on_admit(rid, wave_start=0.0)
        tele.on_retire(rid, new_tokens=4, finish_reason="length")
    s = tele.latency_summary(last_n=5)
    assert s["requests"] == 5
    assert s["ttft_p99_s"] >= s["ttft_p95_s"] >= s["ttft_p50_s"] > 0


def test_dump_is_json_serializable_and_names_in_flight_requests(
        tmp_path):
    tele = EngineTelemetry(engine="generation", num_slots=2,
                           dump_dir=str(tmp_path))
    tele.on_submit(1, prompt_len=10, correlation_id="evt-abc")
    tele.on_submit(2, prompt_len=20, correlation_id="evt-def")
    tele.on_admit(1, wave_start=0.0)
    tele.record_step("prefill", 0.01, rows=2, batch=2, tokens=30,
                     padded_tokens=64)
    dump = tele.record_error(RuntimeError("device fell over"),
                             context={"where": "decode"})
    assert dump["error"]["type"] == "RuntimeError"
    assert set(dump["correlation_ids"]) == {"evt-abc", "evt-def"}
    assert dump["where"] == "decode"
    assert dump["steps"] and dump["steps"][0]["kind"] == "prefill"
    # auto-dumped to the configured dir, and the file round-trips
    path = dump["dump_path"]
    on_disk = json.loads(open(path).read())
    assert on_disk["engine"] == "generation"
    assert {t["correlation_id"] for t in on_disk["in_flight"]} == \
        {"evt-abc", "evt-def"}
    assert tele.metrics.counter_value("engine_errors_total",
                                      {"engine": "generation"}) == 1


def test_resolve_telemetry_semantics():
    assert resolve_telemetry(False, engine="x") is None
    assert resolve_telemetry(None, engine="x") is None
    t = resolve_telemetry(True, engine="x", num_slots=3)
    assert isinstance(t, EngineTelemetry) and t.num_slots == 3
    assert resolve_telemetry(t, engine="y") is t
    shared = InMemoryMetrics(namespace="copilot")
    t2 = resolve_telemetry(shared, engine="z")
    assert t2.metrics is shared
    with pytest.raises(ValueError, match="telemetry"):
        resolve_telemetry(object(), engine="x")


def test_registry_labels_are_exhaustive():
    """Every label key the telemetry code attaches must be declared in
    the registry entry — dashboards aggregate by these."""
    tele = EngineTelemetry(engine="g", num_slots=2)
    tele.on_submit(1, 4)
    tele.on_admit(1, wave_start=0.0)
    tele.record_step("decode", 0.01, rows=1, batch=2, tokens=1,
                     padded_tokens=8)
    tele.gauge_queue(0, active=1)
    tele.on_retire(1, new_tokens=3, finish_reason="eos")
    m = tele.metrics
    for store in (m.counters, m.gauges, m.histograms):
        for name, series in store.items():
            declared = set(METRICS[name][1])
            for key in series:
                assert {k for k, _ in key} <= declared, (name, key)


def test_record_step_is_cheap_enough_for_the_hot_loop():
    """Lock-cheap claim: recording must be far below dispatch cost.
    Generous bound (50µs/record) — this is a tripwire against
    accidentally making the recorder do per-step O(ring) work, not a
    microbenchmark."""
    import time

    tele = EngineTelemetry(engine="g", num_slots=8)
    t0 = time.monotonic()
    for _ in range(1000):
        tele.record_step("decode", 0.001, rows=8, batch=8, tokens=64,
                         padded_tokens=256)
    assert time.monotonic() - t0 < 0.05


def test_async_runner_engine_error_dumps_and_reports(tmp_path):
    """A failing dispatch must (1) fail the handles, (2) dump the
    flight recorder, (3) hand the error reporter the in-flight
    correlation ids + dump path — the post-mortem names its victims."""
    import time

    from copilot_for_consensus_tpu.engine.async_runner import (
        AsyncEngineRunner,
    )
    from copilot_for_consensus_tpu.obs.errors import (
        CollectingErrorReporter,
    )

    class ExplodingEngine:
        def __init__(self):
            self.telemetry = EngineTelemetry(engine="generation",
                                             num_slots=2,
                                             dump_dir=str(tmp_path))
            self._active = {}
            self._queue = []
            self._rid = 0

        def submit(self, prompt, max_new_tokens,
                   correlation_id=""):
            rid = self._rid
            self._rid += 1
            self._queue.append(rid)
            self.telemetry.on_submit(rid, len(prompt), correlation_id)
            return rid

        def step(self):
            raise RuntimeError("XLA ate the cache")

    rep = CollectingErrorReporter()
    eng = ExplodingEngine()
    runner = AsyncEngineRunner(eng, error_reporter=rep).start()
    try:
        h = runner.submit([1, 2, 3], 4, correlation_id="evt-123")
        with pytest.raises(RuntimeError, match="ate the cache"):
            h.result(timeout=10.0)
        deadline = time.monotonic() + 10.0
        while not rep.reports and time.monotonic() < deadline:
            time.sleep(0.01)
        assert rep.reports
        _exc, ctx = rep.reports[0]
        assert ctx["correlation_ids"] == ["evt-123"]
        assert "flight_record" in ctx
        on_disk = json.loads(open(ctx["flight_record"]).read())
        assert on_disk["correlation_ids"] == ["evt-123"]
    finally:
        runner.stop()


def test_record_error_abandons_in_flight_spans():
    """A long-lived engine that keeps serving after a dispatch failure
    (the async runner's containment) must not leak dead spans: the
    dump names them, THEN they close with finish_reason="error" and
    stop polluting the next post-mortem."""
    tele = EngineTelemetry(engine="generation", num_slots=2)
    tele.on_submit(1, 8, correlation_id="evt-a")
    dump = tele.record_error(RuntimeError("boom"))
    assert dump["correlation_ids"] == ["evt-a"]     # named in THIS dump
    assert tele.in_flight() == []                   # then closed
    assert tele.completed[-1].finish_reason == "error"
    assert tele.metrics.counter_value(
        "engine_requests_total",
        {"engine": "generation", "finish_reason": "error"}) == 1
    # aborted requests stay OUT of the latency histograms
    assert tele.metrics.histogram_stats(
        "engine_e2e_seconds", {"engine": "generation"}) is None
    # the NEXT dump no longer lists them as in flight
    assert tele.dump()["correlation_ids"] == []


def test_error_dump_file_matches_returned_dict_with_context(tmp_path):
    """record_error must write ONE dump including the caller's context
    — the CI artifact and the in-memory dict must not diverge — and
    must not burn flight-recorder step ids on filenames."""
    tele = EngineTelemetry(engine="generation", num_slots=2,
                           dump_dir=str(tmp_path))
    tele.record_step("decode", 0.01, rows=1, batch=2, tokens=1)
    seq_before = tele.recorder._seq
    dump = tele.record_error(RuntimeError("x"), context={"who": "me"})
    assert tele.recorder._seq == seq_before         # no seq hole
    on_disk = json.loads(open(dump["dump_path"]).read())
    assert on_disk["who"] == "me"
    assert {k: v for k, v in dump.items() if k != "dump_path"} == \
        {k: v for k, v in on_disk.items()}


def test_latency_summary_occupancy_windowed_to_last_n():
    """mean_occupancy must describe the same window as the
    percentiles: steps older than the oldest counted request (warmup)
    are excluded."""
    import time

    tele = EngineTelemetry(engine="generation", num_slots=4)
    tele.record_step("decode", 0.01, rows=1, batch=4)   # "warmup", occ .25
    time.sleep(0.02)
    tele.on_submit(1, 8)
    tele.on_admit(1, wave_start=0.0)
    tele.record_step("decode", 0.01, rows=4, batch=4)   # timed, occ 1.0
    tele.on_retire(1, new_tokens=4, finish_reason="length")
    assert tele.latency_summary(last_n=1)["mean_occupancy"] == 1.0
    # unwindowed view still averages everything
    assert tele.latency_summary()["mean_occupancy"] == 0.625


def test_attach_service_collector_production_wiring():
    """The gap the contract tests cannot see: engine telemetry must be
    re-pointed at the SERVICE's collector (what /metrics serves) or
    every copilot_engine_* panel watches series nobody emits."""
    from copilot_for_consensus_tpu.engine.telemetry import (
        attach_service_collector,
    )

    class Eng:
        telemetry = EngineTelemetry(engine="generation", num_slots=2)

    class Holder:
        engine = Eng()
        long_engine = None

    shared = InMemoryMetrics(namespace="copilot")
    assert attach_service_collector(Holder(), shared) == 1
    Holder.engine.telemetry.on_submit(1, 4)
    Holder.engine.telemetry.on_admit(1, wave_start=0.0)
    assert shared.histogram_stats("engine_ttft_seconds",
                                  {"engine": "generation"})["count"] == 1
    # a Noop collector must NOT replace the engine's renderable copy
    fresh = EngineTelemetry(engine="g2", num_slots=1)

    class H2:
        engine = type("E", (), {"telemetry": fresh})()

    assert attach_service_collector(H2(), NoopMetrics()) == 0
    assert isinstance(fresh.metrics, InMemoryMetrics)


def test_summarization_service_wires_engine_telemetry_and_reporter():
    """End-to-end production wiring: constructing the service
    re-points the summarizer's engine telemetry at the service
    collector and hands the summarizer the error reporter."""
    from copilot_for_consensus_tpu.obs.errors import (
        CollectingErrorReporter,
    )
    from copilot_for_consensus_tpu.services.summarization import (
        SummarizationService,
    )

    class FakeEngine:
        telemetry = EngineTelemetry(engine="generation", num_slots=2)

    class FakeSummarizer:
        engine = FakeEngine()
        long_engine = None
        error_reporter = None

        def summarize(self, context):
            raise NotImplementedError

    shared = InMemoryMetrics(namespace="copilot")
    rep = CollectingErrorReporter()
    summ = FakeSummarizer()
    SummarizationService(publisher=None, store=None, summarizer=summ,
                         metrics=shared, error_reporter=rep)
    assert summ.engine.telemetry.metrics is shared
    assert summ.error_reporter is rep


# -- engine e2e (slow lane: JAX compiles) ------------------------------


@pytest.fixture(scope="module")
def tiny_engine_parts():
    import jax
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.models import decoder
    from copilot_for_consensus_tpu.models.configs import decoder_config

    cfg = decoder_config("tiny")
    params = decoder.init_params(jax.random.PRNGKey(7), cfg,
                                 dtype=jnp.float32)
    return cfg, params


def _engine(cfg, params, **kw):
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.engine.generation import (
        GenerationEngine,
    )

    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", (16, 32))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    return GenerationEngine(cfg, params, **kw)


@pytest.mark.slow
def test_engine_telemetry_default_on_and_exports(tiny_engine_parts):
    cfg, params = tiny_engine_parts
    eng = _engine(cfg, params)
    assert eng.telemetry is not None            # on by default
    eng.submit([5, 6, 7], 6, correlation_id="evt-1")
    eng.submit([8, 9, 10, 11], 6, correlation_id="evt-2")
    for _ in range(30):
        eng.step()
        if not eng._active and not eng._queue:
            break
    tele = eng.telemetry
    comps = [t for t in tele.completed]
    assert {t.correlation_id for t in comps} == {"evt-1", "evt-2"}
    for t in comps:
        assert t.ttft_s > 0 and t.e2e_s >= t.ttft_s
        assert t.admit_kind == "wave"
        assert t.new_tokens > 0
    kinds = {r.kind for r in tele.recorder.records()}
    assert "prefill" in kinds and "decode" in kinds
    body = tele.metrics.render_prometheus()
    assert "copilot_engine_ttft_seconds_bucket" in body
    assert 'copilot_engine_requests_total{engine="generation"' in body
    assert "copilot_engine_queue_depth" in body


@pytest.mark.slow
def test_greedy_bit_identical_with_telemetry_on_vs_off(
        tiny_engine_parts):
    """The acceptance gate: the recorder is pure host-side observation
    — PRNG stream, program count and tokens must be untouched."""
    cfg, params = tiny_engine_parts
    prompts = [[5, 9, 13], [40, 41, 42, 43, 44, 45, 46], [3, 4, 5]]
    on = _engine(cfg, params, telemetry=True).generate(
        prompts, max_new_tokens=8)
    off_eng = _engine(cfg, params, telemetry=False)
    assert off_eng.telemetry is None
    off = off_eng.generate(prompts, max_new_tokens=8)
    assert [c.tokens for c in on] == [c.tokens for c in off]
    assert [c.finish_reason for c in on] == [c.finish_reason
                                             for c in off]


@pytest.mark.slow
def test_prefix_cache_hits_show_in_spans(tiny_engine_parts):
    cfg, params = tiny_engine_parts
    eng = _engine(cfg, params, prefix_cache_blocks=8, prefill_chunk=8)
    common = list(range(40, 56))                 # two full blocks
    p1 = common + [7, 8, 9]
    p2 = common + [10, 11, 12]
    eng.generate([p1], max_new_tokens=4)         # miss: fills the pool
    eng.generate([p2], max_new_tokens=4)         # hit: seeded admit
    tr = list(eng.telemetry.completed)[-1]
    assert tr.admit_kind == "seeded"
    assert tr.prefix_hit_tokens >= 16
    kinds = [r.kind for r in eng.telemetry.recorder.records()]
    assert "prefill_seeded" in kinds
    body = eng.telemetry.metrics.render_prometheus()
    assert "copilot_engine_prefix_hit_rate" in body


@pytest.mark.slow
def test_spec_decode_verify_steps_recorded(tiny_engine_parts):
    cfg, params = tiny_engine_parts
    eng = _engine(cfg, params, spec_decode=True,
                  spec_draft_lens=(0, 4), decode_window=4)
    # copy-cycle prompt: the n-gram index drafts from the repetition
    prompt = [5, 6, 7, 8] * 4
    eng.generate([prompt], max_new_tokens=12)
    recs = eng.telemetry.recorder.records()
    verify = [r for r in recs if r.kind == "verify"]
    if verify:                    # drafts hit on this toy model's output
        assert all(r.draft_tokens >= r.accepted_tokens >= 0
                   for r in verify)
        body = eng.telemetry.metrics.render_prometheus()
        assert "copilot_engine_spec_acceptance_rate" in body
    # the ledger gauges export regardless of hit luck
    assert eng.spec_stats()["enabled"]


@pytest.mark.slow
def test_embedding_engine_records_embed_steps():
    from copilot_for_consensus_tpu.engine.embedding import (
        EmbeddingEngine,
    )
    from copilot_for_consensus_tpu.models.configs import encoder_config

    eng = EmbeddingEngine(encoder_config("tiny"), batch_size=4,
                          buckets=(16, 32))
    eng.embed_batch(["hello world", "a longer text about consensus",
                     "third"])
    recs = eng.telemetry.recorder.records()
    assert recs and all(r.kind == "embed" for r in recs)
    assert recs[0].rows == 3 and recs[0].batch == 4
    assert "copilot_engine_step_seconds_bucket" in \
        eng.telemetry.metrics.render_prometheus()


@pytest.mark.slow
def test_generate_failure_dumps_flight_record(tmp_path,
                                              tiny_engine_parts):
    cfg, params = tiny_engine_parts
    eng = _engine(cfg, params)
    eng.telemetry.dump_dir = str(tmp_path)
    # sabotage the decode dispatch so the error path fires mid-generate
    def boom(*a, **k):
        raise RuntimeError("dispatch exploded")

    eng._decode_fn = boom
    with pytest.raises(RuntimeError, match="dispatch exploded"):
        eng.generate([[5, 6, 7]], max_new_tokens=8)
    dumps = list(tmp_path.glob("error-*.json"))
    assert dumps, "engine error did not auto-dump the flight recorder"
    data = json.loads(dumps[0].read_text())
    assert data["error"]["message"] == "dispatch exploded"
    assert data["in_flight"], "dump must name the requests in flight"
