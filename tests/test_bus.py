import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu.bus.base import PublishError
from copilot_for_consensus_tpu.bus.factory import create_publisher, create_subscriber
from copilot_for_consensus_tpu.bus.inproc import InProcBroker, InProcPublisher, InProcSubscriber
from copilot_for_consensus_tpu.core.events import ArchiveIngested, JSONParsed


@pytest.fixture
def broker():
    return InProcBroker("test.exchange")


def test_publish_routes_by_event_type(broker):
    pub = InProcPublisher(broker=broker)
    pub.publish(ArchiveIngested(archive_id="a1"))
    assert broker.queue_depth("archive.ingested") == 1
    assert broker.queue_depth("json.parsed") == 0


def test_subscribe_and_drain(broker):
    pub = InProcPublisher(broker=broker)
    sub = InProcSubscriber(broker=broker)
    seen = []
    sub.subscribe(["archive.ingested"], lambda env: seen.append(env))
    pub.publish(ArchiveIngested(archive_id="a1"))
    pub.publish(ArchiveIngested(archive_id="a2"))
    assert sub.drain() == 2
    assert [e["data"]["archive_id"] for e in seen] == ["a1", "a2"]


def test_cascade_drains_to_quiescence(broker):
    """A handler that publishes downstream events: drain() runs the cascade."""
    pub = InProcPublisher(broker=broker)
    sub = InProcSubscriber(broker=broker)
    order = []

    def on_archive(env):
        order.append("archive")
        pub.publish(JSONParsed(message_doc_id="m1"))

    sub.subscribe(["archive.ingested"], on_archive)
    sub.subscribe(["json.parsed"], lambda env: order.append("parsed"))
    pub.publish(ArchiveIngested(archive_id="a1"))
    assert sub.drain() == 2
    assert order == ["archive", "parsed"]


def test_nack_requeue_then_dead_letter(broker):
    pub = InProcPublisher(broker=broker)
    sub = InProcSubscriber(broker=broker)
    attempts = []
    sub.subscribe(["archive.ingested"],
                  lambda env: (_ for _ in ()).throw(RuntimeError("boom")))
    sub.subscribe(["archive.ingested.dlq"], lambda env: attempts.append("dlq"))
    pub.publish(ArchiveIngested(archive_id="bad"))
    sub.drain()
    assert len(broker.dead_lettered) == 1
    assert broker.dead_lettered[0][0] == "archive.ingested"
    assert attempts == ["dlq"]


def test_competing_consumers_share_work(broker):
    pub = InProcPublisher(broker=broker)
    sub = InProcSubscriber(broker=broker)
    a, b = [], []
    sub.subscribe(["archive.ingested"], lambda env: a.append(1))
    sub.subscribe(["archive.ingested"], lambda env: b.append(1))
    for i in range(10):
        pub.publish(ArchiveIngested(archive_id=f"a{i}"))
    sub.drain()
    assert len(a) + len(b) == 10
    assert len(a) == 5 and len(b) == 5  # round-robin


def test_validating_publisher_rejects_garbage():
    pub = create_publisher({"driver": "inproc", "exchange": "val.test"})
    with pytest.raises(PublishError):
        pub.publish_envelope({"event_type": "ArchiveIngested"}, "archive.ingested")


def test_validating_subscriber_quarantines_invalid():
    exchange = "val.test.2"
    invalid = []
    pub = create_publisher({"driver": "inproc", "exchange": exchange},
                           validate=False)
    sub = create_subscriber({"driver": "inproc", "exchange": exchange},
                            on_invalid=lambda env, exc: invalid.append(env))
    seen = []
    sub.subscribe(["archive.ingested"], lambda env: seen.append(env))
    pub.publish_envelope({"event_type": "ArchiveIngested"}, "archive.ingested")
    pub.publish(ArchiveIngested(archive_id="ok"))
    sub.drain()
    assert len(seen) == 1 and seen[0]["data"]["archive_id"] == "ok"
    assert len(invalid) == 1
    assert sub.invalid_count == 1


# ---- broker (inter-process tier) ----------------------------------------

broker_mod = pytest.importorskip("copilot_for_consensus_tpu.bus.broker")


@pytest.fixture
def live_broker():
    if not broker_mod.HAS_ZMQ:
        pytest.skip("pyzmq missing")
    b = broker_mod.Broker(port=0).start()
    yield b
    b.stop()


def test_broker_roundtrip_via_factory(live_broker):
    pub = create_publisher({"driver": "broker",
                            "address": live_broker.address})
    sub = create_subscriber({"driver": "broker",
                             "address": live_broker.address})
    seen = []
    sub.subscribe(["archive.ingested"], lambda env: seen.append(env))
    pub.publish(ArchiveIngested(archive_id="z1"))
    sub.drain(max_messages=10)
    pub.close()
    sub.close()
    assert seen and seen[0]["data"]["archive_id"] == "z1"


def test_broker_all_routing_keys_concurrently(live_broker):
    """Every routing key in the contract multiplexes over ONE broker socket
    with publishers in multiple threads — the round-1 port-hash design
    collided keys onto shared ports; this is its regression test."""
    import threading

    from copilot_for_consensus_tpu.core.events import EVENT_TYPES

    keys = sorted({cls.routing_key for cls in EVENT_TYPES.values()})
    assert len(keys) >= 17
    pub = broker_mod.BrokerPublisher({"address": live_broker.address})
    sub = broker_mod.BrokerSubscriber({"address": live_broker.address})
    seen: dict[str, list] = {k: [] for k in keys}
    for k in keys:
        sub.subscribe([k], lambda env, k=k: seen[k].append(env))

    def blast(key):
        for i in range(5):
            pub.publish_envelope({"event_type": key, "n": i},
                                 routing_key=key)

    threads = [threading.Thread(target=blast, args=(k,)) for k in keys]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    sub.drain()
    pub.close()
    sub.close()
    assert all(len(v) == 5 for v in seen.values()), {
        k: len(v) for k, v in seen.items() if len(v) != 5}


def test_broker_nack_requeues_then_dead_letters(live_broker):
    """TRANSIENT handler failures (RetryableError) ride the redelivery
    budget; past it the message parks dead with a structured reason.
    (Deterministic failures skip the budget — poison quarantine,
    tests/test_bus_resilience.py.)"""
    from copilot_for_consensus_tpu.core.retry import RetryableError

    pub = broker_mod.BrokerPublisher({"address": live_broker.address})
    sub = broker_mod.BrokerSubscriber({"address": live_broker.address})
    attempts = []

    def explode(env):
        attempts.append(env)
        raise RetryableError("boom")

    sub.subscribe(["archive.ingested"], explode)
    pub.publish_envelope({"event_type": "archive.ingested"},
                         routing_key="archive.ingested")
    for _ in range(5):
        sub.drain()
    assert len(attempts) == 3  # max_redeliveries
    dead = live_broker.store.dead_letters("archive.ingested")
    assert len(dead) == 1
    assert dead[0][4] == "redelivery budget exhausted"
    # Operator requeue (the failed-queues CLI path) revives it.
    assert live_broker.store.requeue_dead("archive.ingested") == 1
    sub.close()
    pub.close()


def test_broker_lease_expiry_redelivers_crashed_consumer_work(live_broker):
    """A consumer that fetches then dies mid-message must not strand it."""
    live_broker.lease_s = 0.05
    pub = broker_mod.BrokerPublisher({"address": live_broker.address})
    pub.publish_envelope({"event_type": "archive.ingested"},
                         routing_key="archive.ingested")
    crashed = broker_mod.BrokerSubscriber({"address": live_broker.address})
    crashed.subscribe(["archive.ingested"], lambda env: None)
    # Simulate the crash: fetch (message goes inflight) but never ack.
    reply = crashed._client.request(
        {"op": "fetch", "rks": ["archive.ingested"], "max": 1})
    assert len(reply["msgs"]) == 1
    crashed.close()
    import time
    time.sleep(0.1)  # lease expires
    survivor = broker_mod.BrokerSubscriber({"address": live_broker.address})
    seen = []
    survivor.subscribe(["archive.ingested"], lambda env: seen.append(env))
    survivor.drain()
    survivor.close()
    pub.close()
    assert len(seen) == 1


def test_broker_kill_and_resume_no_message_loss(tmp_path):
    """VERDICT r1 item 4's 'kill-and-resume' case: the broker process is
    killed with messages queued and in flight; a restart on the same sqlite
    file delivers every message."""
    if not broker_mod.HAS_ZMQ:
        pytest.skip("pyzmq missing")
    import subprocess
    import sys
    import time

    db = str(tmp_path / "queues.sqlite3")
    port = 5741
    cmd = [sys.executable, "-m", "copilot_for_consensus_tpu.bus.broker",
           "--port", str(port), "--db", db]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE)
    try:
        proc.stdout.readline()  # "broker listening" → bound
        addr = f"tcp://127.0.0.1:{port}"
        pub = broker_mod.BrokerPublisher({"address": addr})
        for i in range(20):
            pub.publish_envelope({"event_type": "archive.ingested", "n": i},
                                 routing_key="archive.ingested")
        # One message inflight (fetched, never acked) at kill time.
        probe = broker_mod.BrokerSubscriber({"address": addr})
        probe.subscribe(["archive.ingested"], lambda env: None)
        probe._client.request(
            {"op": "fetch", "rks": ["archive.ingested"], "max": 1})
        probe.close()
        proc.kill()
        proc.wait(timeout=10)
        # Restart on the same durable db (inflight requeues on open).
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE)
        proc.stdout.readline()
        sub = broker_mod.BrokerSubscriber({"address": addr})
        seen = []
        sub.subscribe(["archive.ingested"], lambda env: seen.append(env))
        deadline = time.time() + 10
        while len(seen) < 20 and time.time() < deadline:
            sub.drain()
        sub.close()
        pub.close()
        assert sorted(e["n"] for e in seen) == list(range(20))
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_pipeline_over_external_broker(live_broker, fixtures_dir):
    """Full end-to-end through the durable inter-process broker: with
    cfg["bus"] set, services publish to AND consume from the external
    broker directly (one group per service) — the deployment topology of
    deploy/docker-compose.yml (pipeline + broker + retry-job)."""
    from copilot_for_consensus_tpu.services.runner import build_pipeline

    p = build_pipeline({"bus": {"driver": "broker",
                                "address": live_broker.address}})
    assert len(p.ext_subscribers) == len(p.services)
    p.ingestion.create_source({
        "source_id": "ietf-test", "name": "ietf-test",
        "fetcher": "local",
        "location": str(fixtures_dir / "ietf-sample.mbox"),
    })
    stats = p.ingest_and_run("ietf-test")
    assert stats["archives"] == 1 and stats["messages"] > 0
    assert stats["reports"] == stats["threads"] > 0
    # Gauges source from the external broker in this mode: consumed
    # keys are gone (acked rows delete). The unbound terminal key stays
    # parked — visible as retention in bus_counts(), but NOT as queue
    # depth: nothing consumes it, so it is not backlog and must not
    # trip the depth alerts or the watermark backpressure.
    depths = p.routing_key_depths()
    assert depths.get("report.published", 0) == 0
    assert depths.get("archive.ingested", 0) == 0
    assert (p.bus_counts()["report.published"]["parked"]
            == stats["reports"])
    for sub in p.ext_subscribers:
        sub.close()


def test_external_publisher_reaches_broker_backed_pipeline(live_broker):
    """A foreign process (the retry job) publishing into the broker is
    consumed by the broker-backed pipeline — the hop the retry-job
    container depends on. Ack happens only after the service handler
    returns (durable at-least-once; no ack-then-crash window)."""
    from copilot_for_consensus_tpu.services.runner import build_pipeline

    p = build_pipeline({"bus": {"driver": "broker",
                                "address": live_broker.address}})
    foreign = create_publisher({"driver": "broker",
                                "address": live_broker.address})
    foreign.publish(ArchiveIngested(archive_id="ghost"))
    p.drain()
    # The unknown archive lands in parsing's failure path, proving the
    # event crossed broker -> service group -> handler; nothing remains
    # queued or inflight broker-side.
    counts = live_broker.store.counts().get("archive.ingested", {})
    assert counts.get("pending", 0) == 0, counts
    assert counts.get("inflight", 0) == 0, counts
    foreign.close()
    for sub in p.ext_subscribers:
        sub.close()


def test_broker_group_fanout_and_competition(live_broker):
    """Distinct groups each see every message; same group competes."""
    pub = broker_mod.BrokerPublisher({"address": live_broker.address})
    svc_a = broker_mod.BrokerSubscriber({"address": live_broker.address},
                                        group="svc-a")
    svc_b = broker_mod.BrokerSubscriber({"address": live_broker.address},
                                        group="svc-b")
    a_replica = broker_mod.BrokerSubscriber(
        {"address": live_broker.address}, group="svc-a")
    seen = {"a": [], "b": [], "a2": []}
    svc_a.subscribe(["source.deletion.requested"],
                    lambda env: seen["a"].append(env))
    svc_b.subscribe(["source.deletion.requested"],
                    lambda env: seen["b"].append(env))
    a_replica.subscribe(["source.deletion.requested"],
                        lambda env: seen["a2"].append(env))
    for i in range(6):
        pub.publish_envelope({"event_type": "source.deletion.requested",
                              "n": i},
                             routing_key="source.deletion.requested")
    # Interleave replica fetches so the competing pair shares work.
    for _ in range(6):
        svc_a.drain(max_messages=1)
        a_replica.drain(max_messages=1)
        svc_b.drain()
    assert len(seen["b"]) == 6                       # fan-out to svc-b
    assert len(seen["a"]) + len(seen["a2"]) == 6     # competition in svc-a
    assert seen["a"] and seen["a2"]
    for s in (svc_a, svc_b, a_replica):
        s.close()
    pub.close()


def test_parked_unroutable_messages_expire():
    """Messages published to a key nothing binds are parked briefly for
    the startup race, then dropped (AMQP drops unroutable outright) —
    the durable db must not grow forever on unconsumed terminal keys."""
    store = broker_mod._QueueStore(":memory:")
    store.enqueue("report.published", "{}")
    # Retention surfaces as 'parked', not 'pending': no consumer group
    # owes this work, so backpressure and depth gauges must not see it
    # as backlog (a stage publishing to an unconsumed terminal key
    # would otherwise pace forever against a queue nothing drains).
    assert store.counts()["report.published"] == {"parked": 1}
    assert store.depth("report.published") == 0
    store.expire_leases(parked_ttl_s=0.0)
    assert "report.published" not in store.counts()
    # Bound-group rows are untouched by the parked TTL.
    store.bind(["summary.complete"], "svc")
    store.enqueue("summary.complete", "{}")
    store.expire_leases(parked_ttl_s=0.0)
    assert store.counts()["summary.complete"]["pending"] == 1
    store.close()


def test_gauge_depths_reset_after_drain(live_broker, fixtures_dir):
    """A key that backed up then fully drained must re-report 0, not
    stick at its last value (acked rows delete broker-side)."""
    from copilot_for_consensus_tpu.services.runner import build_pipeline

    p = build_pipeline({"bus": {"driver": "broker",
                                "address": live_broker.address}})
    foreign = create_publisher({"driver": "broker",
                                "address": live_broker.address})
    foreign.publish(ArchiveIngested(archive_id="ghost"))
    assert p.routing_key_depths().get("archive.ingested") == 1
    p.drain()
    assert p.routing_key_depths().get("archive.ingested") == 0
    foreign.close()
    for sub in p.ext_subscribers:
        sub.close()


def test_role_split_processes_complete_pipeline(live_broker, fixtures_dir):
    """Two role-scoped pipelines over one broker — host stages in one,
    'TPU' stages in the other — jointly complete the pipeline: the
    reference's service-per-container split plus SURVEY §7's host/engine
    split, on the durable bus."""
    from copilot_for_consensus_tpu.services.runner import build_pipeline

    bus = {"driver": "broker", "address": live_broker.address}
    host = build_pipeline({
        "bus": bus,
        "roles": ["ingestion", "parsing", "chunking", "reporting"],
        "unsafe_private_stores": True})
    engine = build_pipeline({
        "bus": bus,
        "roles": ["embedding", "orchestrator", "summarization"],
        "document_store": {"driver": "memory"},
        "unsafe_private_stores": True})
    # Shared store across "processes" for this in-test split: point the
    # engine's services at the host's store objects.
    for svc in engine.services:
        svc.store = host.store
    engine.embedding.vector_store = host.vector_store
    engine.orchestrator.vector_store = host.vector_store

    host.ingestion.create_source({
        "source_id": "s", "name": "s", "fetcher": "local",
        "location": str(fixtures_dir / "ietf-sample.mbox")})
    host.ingestion.trigger_source("s")
    # Alternate draining the two processes until both go quiet.
    for _ in range(40):
        moved = host.drain() + engine.drain()
        if not moved:
            break
    stats = host.reporting.stats()
    assert stats["reports"] == stats["threads"] > 0
    assert stats["messages"] > 0
    for p in (host, engine):
        for sub in p.ext_subscribers:
            sub.close()


def test_unknown_role_rejected():
    from copilot_for_consensus_tpu.services.runner import build_pipeline

    with pytest.raises(ValueError, match="unknown roles"):
        build_pipeline({"roles": ["ingestion", "nonsense"]})


def test_role_split_with_private_store_rejected(live_broker, tmp_path):
    """A role-scoped process with a defaulted in-memory store would
    silently read empty state while its peer writes elsewhere — that
    misconfiguration must fail at build time, not DLQ every event."""
    from copilot_for_consensus_tpu.services.runner import build_pipeline

    bus = {"driver": "broker", "address": live_broker.address}
    with pytest.raises(ValueError, match="shared document_store"):
        build_pipeline({"bus": bus, "roles": ["ingestion", "parsing"]})
    # sqlite ":memory:" is just as private as the memory driver.
    with pytest.raises(ValueError, match="shared document_store"):
        build_pipeline({
            "bus": bus, "roles": ["ingestion", "parsing"],
            "document_store": {"driver": "sqlite", "path": ":memory:"}})
    with pytest.raises(ValueError, match="shared vector_store"):
        build_pipeline({
            "bus": bus, "roles": ["ingestion", "parsing"],
            "document_store": {"driver": "sqlite",
                               "path": str(tmp_path / "docs.sqlite3")}})
