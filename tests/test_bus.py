import pytest

from copilot_for_consensus_tpu.bus.base import PublishError
from copilot_for_consensus_tpu.bus.factory import create_publisher, create_subscriber
from copilot_for_consensus_tpu.bus.inproc import InProcBroker, InProcPublisher, InProcSubscriber
from copilot_for_consensus_tpu.core.events import ArchiveIngested, JSONParsed


@pytest.fixture
def broker():
    return InProcBroker("test.exchange")


def test_publish_routes_by_event_type(broker):
    pub = InProcPublisher(broker=broker)
    pub.publish(ArchiveIngested(archive_id="a1"))
    assert broker.queue_depth("archive.ingested") == 1
    assert broker.queue_depth("json.parsed") == 0


def test_subscribe_and_drain(broker):
    pub = InProcPublisher(broker=broker)
    sub = InProcSubscriber(broker=broker)
    seen = []
    sub.subscribe(["archive.ingested"], lambda env: seen.append(env))
    pub.publish(ArchiveIngested(archive_id="a1"))
    pub.publish(ArchiveIngested(archive_id="a2"))
    assert sub.drain() == 2
    assert [e["data"]["archive_id"] for e in seen] == ["a1", "a2"]


def test_cascade_drains_to_quiescence(broker):
    """A handler that publishes downstream events: drain() runs the cascade."""
    pub = InProcPublisher(broker=broker)
    sub = InProcSubscriber(broker=broker)
    order = []

    def on_archive(env):
        order.append("archive")
        pub.publish(JSONParsed(message_doc_id="m1"))

    sub.subscribe(["archive.ingested"], on_archive)
    sub.subscribe(["json.parsed"], lambda env: order.append("parsed"))
    pub.publish(ArchiveIngested(archive_id="a1"))
    assert sub.drain() == 2
    assert order == ["archive", "parsed"]


def test_nack_requeue_then_dead_letter(broker):
    pub = InProcPublisher(broker=broker)
    sub = InProcSubscriber(broker=broker)
    attempts = []
    sub.subscribe(["archive.ingested"],
                  lambda env: (_ for _ in ()).throw(RuntimeError("boom")))
    sub.subscribe(["archive.ingested.dlq"], lambda env: attempts.append("dlq"))
    pub.publish(ArchiveIngested(archive_id="bad"))
    sub.drain()
    assert len(broker.dead_lettered) == 1
    assert broker.dead_lettered[0][0] == "archive.ingested"
    assert attempts == ["dlq"]


def test_competing_consumers_share_work(broker):
    pub = InProcPublisher(broker=broker)
    sub = InProcSubscriber(broker=broker)
    a, b = [], []
    sub.subscribe(["archive.ingested"], lambda env: a.append(1))
    sub.subscribe(["archive.ingested"], lambda env: b.append(1))
    for i in range(10):
        pub.publish(ArchiveIngested(archive_id=f"a{i}"))
    sub.drain()
    assert len(a) + len(b) == 10
    assert len(a) == 5 and len(b) == 5  # round-robin


def test_validating_publisher_rejects_garbage():
    pub = create_publisher({"driver": "inproc", "exchange": "val.test"})
    with pytest.raises(PublishError):
        pub.publish_envelope({"event_type": "ArchiveIngested"}, "archive.ingested")


def test_validating_subscriber_quarantines_invalid():
    exchange = "val.test.2"
    invalid = []
    pub = create_publisher({"driver": "inproc", "exchange": exchange},
                           validate=False)
    sub = create_subscriber({"driver": "inproc", "exchange": exchange},
                            on_invalid=lambda env, exc: invalid.append(env))
    seen = []
    sub.subscribe(["archive.ingested"], lambda env: seen.append(env))
    pub.publish_envelope({"event_type": "ArchiveIngested"}, "archive.ingested")
    pub.publish(ArchiveIngested(archive_id="ok"))
    sub.drain()
    assert len(seen) == 1 and seen[0]["data"]["archive_id"] == "ok"
    assert len(invalid) == 1
    assert sub.invalid_count == 1


def test_zmq_roundtrip_if_available():
    zmq_bus = pytest.importorskip("copilot_for_consensus_tpu.bus.zmq_bus")
    if not zmq_bus.HAS_ZMQ:
        pytest.skip("pyzmq missing")
    pub = zmq_bus.ZmqPublisher({"base_port": 5810})
    sub = zmq_bus.ZmqSubscriber({"base_port": 5810})
    seen = []
    sub.subscribe(["archive.ingested"], lambda env: seen.append(env))
    import time
    time.sleep(0.2)  # let PULL connect
    pub.publish(ArchiveIngested(archive_id="z1"))
    deadline = time.time() + 5
    while not seen and time.time() < deadline:
        sub.drain(max_messages=10)
    pub.close()
    sub.close()
    assert seen and seen[0]["data"]["archive_id"] == "z1"
