# Multi-chip paged serving (ISSUE 15): the mesh-sharded block pool and
# engine dispatches, and the disaggregated prefill/decode role split.
#
# Gates, all on the 8-virtual-device CPU mesh (tests/conftest.py):
# greedy f32 SHARDED-paged output bit-identical to the single-device
# paged engine across plain / prefix-hit (zero-copy) / spec-decode /
# chunked-prefill paths; per-shard allocator locality (a slot's blocks
# never leave its dp shard); DisaggregatedEngine bit-identity with
# real block-granular KV handoffs; role-aware scheduler shedding.
# The fast (host-only) tests run in tier-1; the compile-heavy engine
# oracles are @slow and enforced by the CI multichip arm.
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from copilot_for_consensus_tpu.engine.generation import GenerationEngine
from copilot_for_consensus_tpu.engine.kv_pool import BlockPool
from copilot_for_consensus_tpu.engine.roles import (
    DisaggregatedEngine,
    RoleConfig,
)
from copilot_for_consensus_tpu.engine.scheduler import (
    Scheduler,
    SchedulerConfig,
)
from copilot_for_consensus_tpu.models import decoder
from copilot_for_consensus_tpu.models.configs import decoder_config
from copilot_for_consensus_tpu.parallel import MeshConfig, build_mesh

CFG = decoder_config("tiny")
PARAMS = decoder.init_params(jax.random.PRNGKey(7), CFG,
                             dtype=jnp.float32)


def _engine(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", (16, 32))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("prefill_chunk", 8)
    return GenerationEngine(CFG, kw.pop("params", PARAMS), **kw)


def _mesh():
    return build_mesh(MeshConfig(dp=2, tp=4))


PROMPTS = [[5, 9, 13], [40, 41, 42, 43, 44, 45, 46],
           [7, 8, 9, 10], [20, 21, 22], [11, 12, 13, 14, 15]]


# ---------------------------------------------------------------------------
# sharded-paged bit-identity oracles (slow: XLA compiles on the mesh)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_sharded_paged_plain_bit_identity():
    want = [c.tokens for c in _engine(kv_pool_blocks=20).generate(
        PROMPTS, max_new_tokens=6)]
    eng = _engine(mesh=_mesh(), kv_pool_blocks=24)
    got = [c.tokens for c in eng.generate(PROMPTS, max_new_tokens=6)]
    assert got == want
    assert eng.kv_pool_stats()["dp_shards"] == 2
    # every block was returned: nothing leaked across the run
    assert eng._pool.free_blocks == eng._pool.num_blocks


@pytest.mark.slow
def test_sharded_paged_prefix_hit_zero_copy_bit_identity():
    rng = np.random.default_rng(0)
    common = rng.integers(3, CFG.vocab_size, size=16).tolist()
    prompts = [common + rng.integers(3, CFG.vocab_size, size=6).tolist()
               for _ in range(4)]
    ref = _engine(kv_pool_blocks=20, prefix_cache_blocks=8)
    want = [[c.tokens for c in ref.generate(prompts, max_new_tokens=5)]
            for _ in range(2)]
    eng = _engine(mesh=_mesh(), kv_pool_blocks=32,
                  prefix_cache_blocks=8)
    got = [[c.tokens for c in eng.generate(prompts, max_new_tokens=5)]
           for _ in range(2)]
    assert got == want
    st = eng.kv_pool_stats()
    assert st["zero_copy_admits"] > 0       # pointer admissions fired
    ps = eng.prefix_stats()
    assert ps["hits"] > 0
    # the per-shard tries hold shard-local blocks only
    for shard, pc in enumerate(eng._prefixes):
        for node in pc._nodes:
            assert eng._pool.shard_of(node.block_id) == shard


@pytest.mark.slow
def test_sharded_paged_spec_decode_bit_identity():
    # copy-cycle weights (test_engine_spec_decode.py): greedy
    # generation is a deterministic token cycle, so prompt-lookup
    # drafts always hit and the verify dispatch really runs sharded
    period = 7
    params = decoder.init_params(jax.random.PRNGKey(7), CFG,
                                 dtype=jnp.float32)
    params["layers"]["wo"] = jnp.zeros_like(params["layers"]["wo"])
    params["layers"]["w_down"] = jnp.zeros_like(
        params["layers"]["w_down"])
    emb = np.zeros((CFG.vocab_size, CFG.d_model), np.float32)
    head = np.zeros((CFG.d_model, CFG.vocab_size), np.float32)
    for i in range(period):
        emb[3 + i, i] = 1.0
        head[i, 3 + (i + 1) % period] = 1.0
    params["tok_emb"] = jnp.asarray(emb)
    params["lm_head"] = jnp.asarray(head)
    prompt = [3 + (i % period) for i in range(2 * period)]
    kw = dict(params=params, decode_window=4, spec_decode=True,
              spec_draft_lens=(0, 2, 4))
    want = _engine(kv_pool_blocks=20, **kw).generate(
        [prompt], max_new_tokens=24)[0]
    eng = _engine(mesh=_mesh(), kv_pool_blocks=24, **kw)
    got = eng.generate([prompt], max_new_tokens=24)[0]
    assert got.tokens == want.tokens
    assert eng.spec_dispatches > 0          # the sharded verify ran
    assert eng.spec_stats()["accepted_tokens"] > 0


@pytest.mark.slow
def test_sharded_paged_chunked_prefill_bit_identity():
    rng = np.random.default_rng(1)
    prompts = [rng.integers(3, CFG.vocab_size, size=30).tolist()
               for _ in range(3)]
    sched = SchedulerConfig(chunk_tokens=8, prefill_wave_tokens=64)
    want = [c.tokens for c in _engine(
        kv_pool_blocks=20, scheduler=sched).generate(
        prompts, max_new_tokens=5)]
    eng = _engine(mesh=_mesh(), kv_pool_blocks=32, scheduler=sched)
    got = [c.tokens for c in eng.generate(prompts, max_new_tokens=5)]
    assert got == want
    assert eng.chunk_dispatches > 0         # the sharded chunk ran


@pytest.mark.slow
def test_sharded_paged_blocks_stay_in_slot_shard():
    eng = _engine(mesh=_mesh(), kv_pool_blocks=24)
    for p in PROMPTS[:4]:
        eng.submit(p, max_new_tokens=40)
    for _ in range(2):
        eng.step()
    assert eng._active, "nothing admitted"
    for slot in eng._active:
        shard = eng._slot_shard(slot)
        for bid in eng._tables[slot]:
            assert eng._pool.shard_of(bid) == shard, (slot, bid)
    # drain so the pool balance check stays meaningful
    for _ in range(40):
        if not eng._active and not eng.queue_depth:
            break
        eng.step()
    assert eng._pool.free_blocks == eng._pool.num_blocks


# ---------------------------------------------------------------------------
# disaggregated prefill/decode roles (slow: two meshes, two engines)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_disaggregated_roles_bit_identity_with_real_handoffs():
    kw = dict(num_slots=4, max_len=64, prefill_buckets=(16, 32),
              dtype=jnp.float32, attn_impl="xla", prefill_chunk=8,
              kv_pool_blocks=24)
    want = [c.tokens for c in GenerationEngine(
        CFG, PARAMS, **{**kw, "kv_pool_blocks": 20}).generate(
        PROMPTS, max_new_tokens=6)]
    dis = DisaggregatedEngine(CFG, PARAMS,
                              roles=RoleConfig(prefill_dp=2, tp=2),
                              engine_kw=kw)
    got = [c.tokens for c in dis.generate(PROMPTS, max_new_tokens=6)]
    assert got == want
    st = dis.stats()
    assert st["handoffs"] == len(PROMPTS)
    assert st["handoff_blocks"] >= len(PROMPTS)
    assert st["pending_handoffs"] == 0
    # both role pools returned every block
    assert dis.prefill._pool.free_blocks == dis.prefill._pool.num_blocks
    assert dis.decode._pool.free_blocks == dis.decode._pool.num_blocks
    # the handoff telemetry series moved on the prefill instance
    rendered = dis.prefill.telemetry.metrics.render_prometheus()
    assert "copilot_engine_role_handoff_blocks_total" in rendered
    assert "copilot_engine_role_handoff_wait_seconds" in rendered
    assert "copilot_engine_role_occupancy" in rendered


@pytest.mark.slow
def test_disaggregated_backpressure_reparks_when_decode_full():
    kw = dict(num_slots=4, max_len=64, prefill_buckets=(16, 32),
              dtype=jnp.float32, attn_impl="xla", prefill_chunk=8,
              kv_pool_blocks=24)
    # decode side gets only 2 slots: at most 2 streams decode at once,
    # the rest of the handoffs re-park until capacity frees
    dis = DisaggregatedEngine(
        CFG, PARAMS, roles=RoleConfig(prefill_dp=2, tp=2),
        engine_kw=kw, decode_kw={"num_slots": 2,
                                 "kv_pool_blocks": 20})
    comps = dis.generate(PROMPTS, max_new_tokens=6)
    assert len(comps) == len(PROMPTS)
    assert all(c.finish_reason in ("eos", "length") for c in comps)
    assert dis.handoffs == len(PROMPTS)


# ---------------------------------------------------------------------------
# fast host-only contracts (tier-1)
# ---------------------------------------------------------------------------


def test_role_requires_paged_engine():
    with pytest.raises(ValueError, match="kv_pool_blocks"):
        _engine(role="prefill")


def test_sharded_pool_requires_divisible_geometry():
    mesh = _mesh()
    with pytest.raises(ValueError, match="divisible by dp"):
        _engine(mesh=mesh, kv_pool_blocks=24, num_slots=3)
    with pytest.raises(ValueError, match="divide evenly"):
        BlockPool(CFG, num_blocks=25, block_size=8, mesh=mesh)


def test_sharded_allocator_per_shard_ranges_and_exhaustion():
    mesh = _mesh()
    pool = BlockPool(CFG, num_blocks=24, block_size=8, mesh=mesh)
    assert pool.num_shards == 2 and pool.blocks_per_shard == 12
    a = pool.alloc(3, shard=0)
    b = pool.alloc(3, shard=1)
    assert all(pool.shard_of(x) == 0 for x in a)
    assert all(pool.shard_of(x) == 1 for x in b)
    assert all(pool.local_id(x) < 12 for x in a + b)
    assert pool.free_blocks_shard(0) == 9
    # per-shard exhaustion: shard 0 running dry must not borrow from 1
    from copilot_for_consensus_tpu.engine.kv_pool import KVPoolExhausted

    with pytest.raises(KVPoolExhausted):
        pool.alloc(10, shard=0)
    assert pool.free_blocks_shard(1) == 9
    # frees route home by global id
    pool.free(a)
    assert pool.free_blocks_shard(0) == 12
    pool.free(b)
    assert pool.free_blocks == pool.num_blocks


def test_scheduler_handoff_backlog_raises_shed_levels():
    cfg = SchedulerConfig(handoff_shed_depth=8)
    s = Scheduler(cfg)
    sig = s.observe(queued=0, active=0, num_slots=4,
                    handoff_backlog=2)
    assert s.overload_level == 0
    assert sig["handoff_backlog"] == 2
    s.observe(queued=0, active=0, num_slots=4, handoff_backlog=8)
    assert s.overload_level == 1           # batch lane sheds
    s.observe(queued=0, active=0, num_slots=4, handoff_backlog=16)
    assert s.overload_level == 2           # everything sheds
    s.observe(queued=0, active=0, num_slots=4, handoff_backlog=0)
    assert s.overload_level == 0           # decode caught up


def test_role_config_resolve():
    rc = RoleConfig(prefill_dp=2, tp=2).resolve(8)
    assert (rc.prefill_dp, rc.decode_dp, rc.tp) == (2, 2, 2)
    with pytest.raises(ValueError, match="nothing left"):
        RoleConfig(prefill_dp=4, tp=2).resolve(8)
    with pytest.raises(ValueError, match="do not divide"):
        RoleConfig(prefill_dp=1, tp=3).resolve(8)


def test_handoff_deadline_and_backpressure_plumbing():
    """Code-review regressions: a handed-off deadline must arm the
    decode engine's expiry sweep (submit() never runs on that path),
    and the prefill hold threshold must be REACHABLE (parked handoffs
    are slot-keyed, so the old 2x-slots default could never fire)."""
    pre = _engine(kv_pool_blocks=20, role="prefill")
    assert pre._handoff_high == pre.num_slots // 2
    dec = _engine(kv_pool_blocks=20, role="decode")
    pre.submit([5, 9, 13], max_new_tokens=8, deadline_s=60.0)
    handoffs = []
    for _ in range(10):
        pre.step()
        handoffs = pre.take_prefilled()
        if handoffs:
            break
    assert len(handoffs) == 1
    assert not dec._deadlines_in_use
    rid = dec.admit_prefilled(handoffs[0])
    assert rid is not None
    assert dec._deadlines_in_use     # the expiry sweep is armed
    # the external-backlog report feeds the release hold's comparison
    pre.set_handoff_external(7)
    assert pre._handoff_external == 7
    pre.set_handoff_external(-3)
    assert pre._handoff_external == 0
