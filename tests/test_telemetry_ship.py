# Cross-process telemetry plane (obs/ship.py, ISSUE 20): crash-safe
# spooling, shipper lifecycle, and the aggregator's merge semantics —
# counters sum, gauges LWW, histogram buckets merge, reserved
# proc/role stamping, type-conflict refusal, (proc, seq) dedup — plus
# the real-SIGKILL recovery and cross-OS-process trace-join contracts
# the pipeline_chaos kill phase gates on.
import json
import os
import pathlib
import signal
import subprocess
import sys
import time

import pytest

from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics
from copilot_for_consensus_tpu.obs.ship import (
    SPOOL_SUFFIX,
    TelemetryAggregator,
    TelemetryShipper,
    TelemetrySpool,
    list_spools,
    read_spool,
    spool_path,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


# -- spool ----------------------------------------------------------------


def test_spool_round_trip(tmp_path):
    path = tmp_path / f"p1{SPOOL_SUFFIX}"
    spool = TelemetrySpool(path, proc="p1", role="engine")
    n = spool.append([("metrics", {"counters": []}),
                      ("span", {"span_id": "s1"})])
    assert n == 2
    spool.close()
    back = read_spool(path)
    assert back["proc"] == "p1" and back["role"] == "engine"
    assert back["lost"] == 0
    assert [(seq, kind) for seq, kind, _p in back["rows"]] == \
        [(1, "metrics"), (2, "span")]
    assert back["rows"][1][2] == {"span_id": "s1"}


def test_spool_append_is_one_transaction(tmp_path):
    """A failing row aborts the WHOLE batch — no torn flushes."""
    spool = TelemetrySpool(tmp_path / f"p{SPOOL_SUFFIX}", proc="p")
    spool.append([("metrics", {"a": 1})])

    class Unserializable:
        pass

    with pytest.raises(TypeError):
        spool.append([("metrics", {"b": 2}),
                      ("span", {"x": Unserializable()})])
    spool.close()
    back = read_spool(spool.path)
    assert len(back["rows"]) == 1 and back["lost"] == 0


def test_list_spools_filters_suffix(tmp_path):
    TelemetrySpool(spool_path(tmp_path, "a"), proc="a").close()
    TelemetrySpool(spool_path(tmp_path, "b"), proc="b").close()
    (tmp_path / "other.json").write_text("{}")
    found = list_spools(tmp_path)
    assert len(found) == 2
    assert all(p.endswith(SPOOL_SUFFIX) for p in found)


def test_spool_path_sanitizes_proc_name(tmp_path):
    assert "/" not in pathlib.Path(
        spool_path(tmp_path, "a/b c")).name.replace(SPOOL_SUFFIX, "")


# -- shipper --------------------------------------------------------------


def _shipper(tmp_path, metrics, **kw):
    return TelemetryShipper(
        tmp_path / f"proc{SPOOL_SUFFIX}", proc="proc", role="engine",
        metrics=metrics, **kw)


def test_shipper_ships_metric_deltas(tmp_path):
    m = InMemoryMetrics(namespace="copilot")
    m.increment("jobs_total", 3.0, {"q": "a"})
    ship = _shipper(tmp_path, m)
    ship.flush()
    m.increment("jobs_total", 2.0, {"q": "a"})
    m.observe("wait_seconds", 0.3)
    ship.close()

    agg = TelemetryAggregator()
    stats = agg.ingest_spool(ship.path)
    assert stats["lost"] == 0 and stats["applied"] > 0
    body = agg.render_prometheus()
    # deltas re-sum to the true total, stamped with proc/role
    assert ('copilot_jobs_total{proc="proc",q="a",role="engine"} 5'
            in body)
    assert 'copilot_wait_seconds_count{proc="proc",role="engine"} 1' \
        in body


def test_idle_shipper_appends_nothing(tmp_path):
    """A sourceless flush appends no rows (the pump runs every
    interval; an idle process must not grow its spool)."""
    ship = TelemetryShipper(tmp_path / f"idle{SPOOL_SUFFIX}",
                            proc="idle")
    assert ship.flush() == 0
    assert ship.flush() == 0
    assert ship.stats()["committed_rows"] == 0
    ship.close()


def test_repeated_flushes_never_double_count(tmp_path):
    """Deltas, not snapshots: N flushes of the same registry re-sum to
    the true total on the aggregator side."""
    m = InMemoryMetrics(namespace="copilot")
    ship = _shipper(tmp_path, m)
    for _ in range(5):
        m.increment("jobs_total", 1.0)
        ship.flush()
    ship.close()
    agg = TelemetryAggregator()
    agg.ingest_spool(ship.path)
    assert agg.metrics.counter_value(
        "jobs_total", {"proc": "proc", "role": "engine"}) == 5.0


def test_shipper_mark_baselines_out_warmup(tmp_path):
    """mark() snapshots the registry without shipping: only
    observations AFTER it land in the spool (bench children call it
    post-warmup so compile time never pollutes the histograms)."""
    m = InMemoryMetrics(namespace="copilot")
    m.observe("ttft_seconds", 30.0)              # "warmup compile"
    ship = _shipper(tmp_path, m)
    ship.mark()
    m.observe("ttft_seconds", 0.02)              # "timed run"
    ship.close()
    agg = TelemetryAggregator()
    agg.ingest_spool(ship.path)
    entry = agg.metrics.histograms["ttft_seconds"]
    (key, (total, count, _buckets)), = entry.items()
    assert count == 1 and total == pytest.approx(0.02)


def test_shipper_pump_thread_lifecycle(tmp_path):
    m = InMemoryMetrics(namespace="copilot")
    ship = _shipper(tmp_path, m, interval_s=0.01)
    ship.start()
    assert ship._thread is not None
    m.increment("jobs_total", 1.0)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if ship.stats()["committed_rows"] > 0:
            break
        time.sleep(0.01)
    assert ship.stats()["committed_rows"] > 0, "pump never flushed"
    ship.stop()
    assert ship._thread is None                 # joined, not abandoned
    ship.close()


def test_shipper_ships_spans_and_steps_once(tmp_path):
    from copilot_for_consensus_tpu.obs.trace import Span, TraceCollector

    collector = TraceCollector(capacity=64)
    collector.record(Span(trace_id="t1", span_id="s1",
                          parent_span_id="", name="stage", kind="stage",
                          service="svc", start_wall=time.time()))
    m = InMemoryMetrics(namespace="copilot")
    ship = _shipper(tmp_path, m, collector=collector)
    ship.flush()
    ship.flush()                                 # dedup by span_id
    ship.close()
    rows = read_spool(ship.path)["rows"]
    assert sum(1 for _s, kind, _p in rows if kind == "span") == 1


# -- aggregator merge semantics ------------------------------------------


def _spool_from(tmp_path, proc, role, fill):
    m = InMemoryMetrics(namespace="copilot")
    ship = TelemetryShipper(
        spool_path(tmp_path, proc), proc=proc, role=role, metrics=m)
    fill(m)
    ship.close()
    return ship.path


def test_counters_sum_and_gauges_lww_across_processes(tmp_path):
    p1 = _spool_from(tmp_path, "p1", "serve",
                     lambda m: (m.increment("jobs_total", 3.0),
                                m.gauge("depth", 7.0)))
    p2 = _spool_from(tmp_path, "p2", "serve",
                     lambda m: (m.increment("jobs_total", 2.0),
                                m.gauge("depth", 1.0)))
    agg = TelemetryAggregator()
    agg.ingest_dir(tmp_path)
    body = agg.render_prometheus()
    assert 'copilot_jobs_total{proc="p1",role="serve"} 3' in body
    assert 'copilot_jobs_total{proc="p2",role="serve"} 2' in body
    assert 'copilot_depth{proc="p1",role="serve"} 7' in body
    assert 'copilot_depth{proc="p2",role="serve"} 1' in body
    assert body.count("# TYPE copilot_jobs_total counter") == 1
    del p1, p2


def test_histogram_buckets_merge_elementwise(tmp_path):
    for proc in ("p1", "p2"):
        _spool_from(tmp_path, proc, "serve",
                    lambda m: m.observe("lat_seconds", 0.03))
    agg = TelemetryAggregator()
    agg.ingest_dir(tmp_path)
    series = agg.metrics.histograms["lat_seconds"]
    assert len(series) == 2                     # one per proc
    total = sum(entry[1] for entry in series.values())
    assert total == 2


def test_reingest_is_deduped_by_proc_seq(tmp_path):
    path = _spool_from(tmp_path, "p1", "serve",
                       lambda m: m.increment("jobs_total", 3.0))
    agg = TelemetryAggregator()
    first = agg.ingest_spool(path)
    again = agg.ingest_spool(path)
    assert first["applied"] > 0
    assert again["applied"] == 0 and again["skipped"] == first["applied"]
    assert ('copilot_jobs_total{proc="p1",role="serve"} 3'
            in agg.render_prometheus())


def test_cross_process_type_conflict_raises(tmp_path):
    _spool_from(tmp_path, "p1", "serve",
                lambda m: m.increment("jobs_total", 1.0))
    _spool_from(tmp_path, "p2", "serve",
                lambda m: m.gauge("jobs_total", 1.0))
    agg = TelemetryAggregator()
    with pytest.raises(ValueError, match="type conflict"):
        agg.ingest_dir(tmp_path)


def test_reserved_labels_in_shipped_series_rejected(tmp_path):
    path = _spool_from(
        tmp_path, "p1", "serve",
        lambda m: m.increment("jobs_total", 1.0, {"proc": "liar"}))
    agg = TelemetryAggregator()
    with pytest.raises(ValueError, match="reserved"):
        agg.ingest_spool(path)


# -- SIGKILL survival (real process death) --------------------------------


_KILL_CHILD = r"""
import os, signal, sys
from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics
from copilot_for_consensus_tpu.obs.ship import TelemetryShipper

m = InMemoryMetrics(namespace="copilot")
ship = TelemetryShipper(sys.argv[1], proc="victim", role="serve",
                        metrics=m)
m.increment("committed_total", 1.0)
ship.flush()                                   # committed: must survive
m.increment("committed_total", 41.0)           # never flushed: may die
os.kill(os.getpid(), signal.SIGKILL)
"""


def test_committed_spool_rows_survive_sigkill(tmp_path):
    path = tmp_path / f"victim{SPOOL_SUFFIX}"
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_CHILD, str(path)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode in (-signal.SIGKILL, 128 + signal.SIGKILL), \
        (proc.returncode, proc.stderr)
    back = read_spool(path)
    assert back["lost"] == 0
    assert len(back["rows"]) >= 1
    agg = TelemetryAggregator()
    agg.ingest_spool(path)
    assert ('copilot_committed_total{proc="victim",role="serve"} 1'
            in agg.render_prometheus())


# -- cross-OS-process trace join -----------------------------------------


def test_trace_joins_across_two_process_spools(tmp_path):
    """A ≥5-stage trace whose spans live in TWO spools (the kill/resume
    shape journal_storm ships) must reconstruct with zero orphans once
    merged — and show orphans from either spool alone."""
    from copilot_for_consensus_tpu.obs.trace import Span, TraceCollector
    from copilot_for_consensus_tpu.tools import tracepath

    def spool_with_spans(proc, role, spans):
        collector = TraceCollector(capacity=64)
        for s in spans:
            collector.record(s)
        ship = TelemetryShipper(spool_path(tmp_path, proc), proc=proc,
                                role=role, collector=collector)
        ship.close()
        return ship.path

    def span(sid, parent, name, kind="stage"):
        return Span(trace_id="t" * 32, span_id=sid,
                    parent_span_id=parent, name=name, kind=kind,
                    service=name, start_wall=time.time(),
                    correlation_id="cid-1")

    # process A: the first three stages; process B: two more stages
    # parented onto A's spans (the cross-process edges)
    spool_with_spans("proc-a", "serve", [
        span("a1", "", "ingest"), span("a2", "a1", "parse"),
        span("a3", "a2", "chunk")])
    spool_with_spans("proc-b", "resume", [
        span("b1", "a3", "embed"), span("b2", "b1", "report")])

    merged = TelemetryAggregator()
    merged.ingest_dir(tmp_path)
    audit = tracepath.analyze(merged.spans())
    assert audit["orphan_spans"] == 0, audit
    assert audit["cross_proc_edges"] >= 1
    assert set(audit["procs"]) == {"proc-a", "proc-b"}
    # count the stages on the reconstructed trace
    spans = merged.spans_by_trace()["t" * 32]
    assert len(spans) == 5
    # either spool alone: b1's parent a3 is missing → orphan
    alone = TelemetryAggregator()
    alone.ingest_spool(spool_path(tmp_path, "proc-b"))
    assert tracepath.analyze(alone.spans())["orphan_spans"] > 0


def test_tracepath_collect_sources_reads_spool_dirs(tmp_path):
    from copilot_for_consensus_tpu.obs.trace import Span, TraceCollector
    from copilot_for_consensus_tpu.tools import tracepath

    collector = TraceCollector(capacity=8)
    collector.record(Span(trace_id="t1", span_id="s1",
                          parent_span_id="", name="x", kind="stage",
                          service="x", start_wall=time.time()))
    ship = TelemetryShipper(spool_path(tmp_path, "p1"), proc="p1",
                            role="serve", collector=collector)
    ship.close()
    spans = tracepath.collect_sources([str(tmp_path)])
    assert len(spans) == 1
    assert spans[0]["proc"] == "p1"              # proc-stamped
    spans = tracepath.collect_sources([ship.path])
    assert len(spans) == 1


# -- conftest bundle hook -------------------------------------------------


def test_dump_all_flushes_live_shippers(tmp_path):
    from copilot_for_consensus_tpu.obs import ship as ship_mod

    m = InMemoryMetrics(namespace="copilot")
    shipper = TelemetryShipper(spool_path(tmp_path, "live"),
                               proc="live", role="serve", metrics=m)
    m.increment("jobs_total", 1.0)
    ship_mod.dump_all(tmp_path, tag="unit")
    manifest = json.loads(
        (tmp_path / "unit-spools.json").read_text())
    assert any(s["proc"] == "live" for s in manifest["spools"])
    assert read_spool(shipper.path)["rows"], "dump_all did not flush"
    shipper.close()
