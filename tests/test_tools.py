# Operator tools: failed-queue CLI manager + retry-stuck-documents job.
import json
import time

from copilot_for_consensus_tpu.core import events as ev
from copilot_for_consensus_tpu.services.runner import build_pipeline
from copilot_for_consensus_tpu.tools.failed_queues import FailedQueueManager
from copilot_for_consensus_tpu.tools.retry_job import (
    RetryStuckDocumentsJob,
)


def _broken_pipeline(fixtures_dir):
    p = build_pipeline()
    p.ingestion.create_source({
        "source_id": "s", "name": "s", "fetcher": "local",
        "location": str(fixtures_dir / "ietf-sample.mbox")})
    return p


def test_failed_queue_list_inspect_requeue(fixtures_dir):
    p = _broken_pipeline(fixtures_dir)
    # Break parsing: event references an archive that never lands.
    p.parsing.publisher.publish(ev.ArchiveIngested(archive_id="ghost"))
    p.drain()
    mgr = FailedQueueManager(p.broker, p.parsing.publisher)
    queues = mgr.list_queues()
    assert queues.get("parsing.failed") == 1
    inspected = mgr.inspect("parsing.failed")
    assert inspected[0]["data"]["archive_id"] == "ghost"
    # requeue converts it back into an ArchiveIngested trigger
    n = mgr.requeue("parsing.failed")
    assert n == 1
    assert mgr.list_queues().get("parsing.failed") is None
    # the re-published trigger fails again (archive still missing) —
    # proving the requeued event actually flowed
    p.drain()
    assert mgr.list_queues().get("parsing.failed") == 1
    assert mgr.purge("parsing.failed") == 1


def test_retry_job_requeues_stuck_chunks(fixtures_dir):
    p = _broken_pipeline(fixtures_dir)
    p.ingest_and_run("s")
    chunk = p.store.query_documents("chunks", {}, limit=1)[0]
    p.store.update_document("chunks", chunk["chunk_id"],
                            {"embedding_generated": False})
    p.vector_store.delete([chunk["chunk_id"]])
    job = RetryStuckDocumentsJob(p.store, p.embedding.publisher,
                                 min_stuck_seconds=0.0)
    # First sweep: no last_attempt_at/ingested_at on chunks → eligible.
    counts = job.run_once(now=time.time() + 10_000)
    assert counts["chunks"] == 1
    p.drain()
    doc = p.store.get_document("chunks", chunk["chunk_id"])
    assert doc["embedding_generated"]
    assert doc["attempt_count"] == 1


def test_retry_job_respects_backoff_and_max_attempts(fixtures_dir):
    p = _broken_pipeline(fixtures_dir)
    p.store.insert_or_ignore("archives", {
        "archive_id": "stuck-archive", "sha256": "0" * 64,
        "parsed": False, "source_id": "s",
    })
    job = RetryStuckDocumentsJob(p.store, p.ingestion.publisher,
                                 min_stuck_seconds=0.0)
    far_future = time.time() + 1e6
    assert job.run_once(now=far_future)["archives"] == 1
    # immediately after an attempt: backoff blocks the next sweep
    assert job.run_once(now=time.time())["archives"] == 0
    # attempts bounded
    for i in range(10):
        job.run_once(now=far_future + i * 1e6)
    doc = p.store.get_document("archives", "stuck-archive")
    assert doc["attempt_count"] == 3     # archives rule max_attempts


def test_retry_job_pushes_sweep_metrics(fixtures_dir):
    """Each sweep records requeue counters + exhaustion gauges and
    pushes them (the reference's retry job is a pushgateway client —
    batch jobs can't be scraped)."""
    from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics

    class PushCounting(InMemoryMetrics):
        pushes = 0

        def safe_push(self):
            self.pushes += 1

    p = _broken_pipeline(fixtures_dir)
    p.store.insert_or_ignore("archives", {
        "archive_id": "stuck-a", "sha256": "3" * 64,
        "parsed": False, "source_id": "s",
    })
    p.store.insert_or_ignore("archives", {
        "archive_id": "dead-a", "sha256": "4" * 64,
        "parsed": False, "source_id": "s", "attempt_count": 99,
    })
    metrics = PushCounting()
    job = RetryStuckDocumentsJob(p.store, p.ingestion.publisher,
                                 min_stuck_seconds=0.0, metrics=metrics)
    job.run_once(now=time.time() + 1e6)
    assert metrics.counter_value("retry_requeued_total",
                                 {"collection": "archives"}) == 1
    assert metrics.gauge_value("retry_exhausted_documents",
                               {"collection": "archives"}) == 1
    assert metrics.gauge_value("retry_last_sweep_timestamp") > 0
    assert metrics.pushes == 1


def test_data_export_import_roundtrip(fixtures_dir, tmp_path):
    """Data portability (reference scripts/data-migration-export.py):
    run the pipeline, dump everything, import into a fresh store pair,
    and the read surface is identical — including the vector index."""
    from copilot_for_consensus_tpu.services.runner import build_pipeline
    from copilot_for_consensus_tpu.tools.data_migration import (
        export_data,
        import_data,
    )
    from copilot_for_consensus_tpu.storage.factory import (
        create_document_store,
    )
    from copilot_for_consensus_tpu.vectorstore.factory import (
        create_vector_store,
    )

    p = build_pipeline()
    p.ingestion.create_source({
        "source_id": "s", "name": "s", "fetcher": "local",
        "location": str(fixtures_dir / "ietf-sample.mbox")})
    stats = p.ingest_and_run("s")
    counts = export_data(p.store, tmp_path / "dump",
                         vector_store=p.vector_store)
    assert counts["messages"] == stats["messages"]
    assert counts["vectors"] == p.vector_store.count()

    store2 = create_document_store({"driver": "memory"})
    store2.connect()
    vs2 = create_vector_store({"driver": "memory"})
    got = import_data(store2, tmp_path / "dump", vector_store=vs2)
    assert got["reports"] == stats["reports"]
    assert vs2.count() == p.vector_store.count()
    for coll in ("messages", "threads", "chunks", "summaries", "reports"):
        a = sorted(json.dumps(d, sort_keys=True)
                   for d in p.store.query_documents(coll, {}))
        b = sorted(json.dumps(d, sort_keys=True)
                   for d in store2.query_documents(coll, {}))
        assert a == b, coll
    # Idempotent: importing again changes nothing.
    import_data(store2, tmp_path / "dump", vector_store=vs2)
    assert store2.count_documents("messages", {}) == stats["messages"]
