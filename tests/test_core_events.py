import pytest

from copilot_for_consensus_tpu.core import events
from copilot_for_consensus_tpu.core.validation import (
    SchemaValidationError,
    validate_envelope,
)


def test_seventeen_event_types_registered():
    assert len(events.EVENT_TYPES) == 17


def test_envelope_roundtrip():
    ev = events.ArchiveIngested(archive_id="abc", source_id="s1",
                                sha256="0" * 64, size_bytes=10)
    env = ev.to_envelope()
    assert env["event_type"] == "ArchiveIngested"
    assert env["version"] == events.ENVELOPE_VERSION
    back = events.Event.from_envelope(env)
    assert isinstance(back, events.ArchiveIngested)
    assert back.archive_id == "abc"
    assert back.size_bytes == 10


@pytest.mark.parametrize("name", sorted(events.EVENT_TYPES))
def test_every_event_envelope_validates_against_its_schema(name):
    ev = events.EVENT_TYPES[name]()
    validate_envelope(ev.to_envelope())


def test_envelope_missing_field_rejected():
    env = events.JSONParsed(message_doc_id="m").to_envelope()
    del env["timestamp"]
    with pytest.raises(SchemaValidationError):
        validate_envelope(env)


def test_event_data_wrong_type_rejected():
    env = events.ArchiveIngested().to_envelope()
    env["data"]["size_bytes"] = "not-an-int"
    with pytest.raises(SchemaValidationError):
        validate_envelope(env)


def test_unknown_event_type_rejected():
    env = events.ArchiveIngested().to_envelope()
    env["event_type"] = "NoSuchEvent"
    with pytest.raises(SchemaValidationError):
        validate_envelope(env)
    with pytest.raises(ValueError):
        events.Event.from_envelope(env)


def test_wire_event_type_cannot_traverse_paths():
    env = events.ArchiveIngested().to_envelope()
    env["event_type"] = "../documents/chunks"
    with pytest.raises(SchemaValidationError):
        validate_envelope(env)


def test_failure_events_share_dlq_shape():
    for name in events.FAILURE_EVENT_TYPES:
        ev = events.EVENT_TYPES[name](error="boom", error_type="X", attempts=3)
        data = ev.to_envelope()["data"]
        assert data["error"] == "boom"
        assert data["attempts"] == 3


def test_make_event_by_name():
    ev = events.make_event("SummaryComplete", summary_id="s", thread_id="t")
    assert isinstance(ev, events.SummaryComplete)
