# Continuous-batching engine vs naive full-forward greedy decoding.
import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu.engine.generation import GenerationEngine
from copilot_for_consensus_tpu.engine.sampling import SamplingConfig
from copilot_for_consensus_tpu.engine.tokenizer import ByteTokenizer
from copilot_for_consensus_tpu.models import decoder
from copilot_for_consensus_tpu.models.configs import decoder_config
from copilot_for_consensus_tpu.parallel import MeshConfig, build_mesh

CFG = decoder_config("tiny")
PARAMS = decoder.init_params(jax.random.PRNGKey(7), CFG, dtype=jnp.float32)


def _naive_greedy(prompt, n_new):
    """Oracle: re-run the full forward for every generated token."""
    toks = list(prompt)
    out = []
    for _ in range(n_new):
        logits = decoder.forward(PARAMS, jnp.asarray([toks]), CFG,
                                 attn_impl="xla")
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
    return out


def _engine(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", (16, 32))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    return GenerationEngine(CFG, PARAMS, **kw)


@pytest.mark.parametrize("window,n_windows", [(1, 1), (4, 1),
                                              (4, 2), (2, 3)])
def test_greedy_matches_naive_forward(window, n_windows):
    eng = _engine(decode_window=window, windows_per_dispatch=n_windows)
    prompts = [[5, 9, 13], [40, 41, 42, 43, 44, 45, 46]]
    comps = eng.generate(prompts, max_new_tokens=6)
    for p, c in zip(prompts, comps):
        want = _naive_greedy(p, 6)
        got = c.tokens
        # Engine stops early on eos; compare up to what it produced.
        assert got == want[:len(got)]
        assert len(got) == 6 or want[len(got)] != got[-1]


def test_more_requests_than_slots_all_complete():
    eng = _engine(num_slots=2)
    prompts = [[i + 3, i + 4, i + 5] for i in range(7)]
    comps = eng.generate(prompts, max_new_tokens=4)
    assert len(comps) == 7
    for p, c in zip(prompts, comps):
        assert c.tokens == _naive_greedy(p, 4)[:len(c.tokens)]


def test_mid_stream_join_does_not_disturb_running_slot():
    # Request B joins while A is mid-decode; A's output must be identical
    # to solo decoding — the continuous-batching invariant.
    solo = _engine().generate([[11, 12, 13]], max_new_tokens=8)[0].tokens

    eng = _engine(decode_window=2)
    done = {}
    a = eng.submit([11, 12, 13], max_new_tokens=8)
    for _ in range(3):
        for c in eng.step():
            done[c.request_id] = c
    b = eng.submit([30, 31, 32, 33], max_new_tokens=3)
    for _ in range(30):
        for c in eng.step():
            done[c.request_id] = c
        if len(done) == 2:
            break
    assert done[a].tokens == solo
    assert done[b].tokens == _naive_greedy([30, 31, 32, 33], 3)[
        :len(done[b].tokens)]


def test_slot_reuse_after_retirement():
    eng = _engine(num_slots=1)
    c1 = eng.generate([[9, 8, 7]], max_new_tokens=3)[0]
    c2 = eng.generate([[21, 22, 23]], max_new_tokens=3)[0]
    assert c1.tokens == _naive_greedy([9, 8, 7], 3)[:len(c1.tokens)]
    assert c2.tokens == _naive_greedy([21, 22, 23], 3)[:len(c2.tokens)]


def test_long_prompt_truncates_to_tail():
    eng = _engine(max_len=32, prefill_buckets=(32,), decode_window=1)
    prompt = list(np.arange(100) % 200 + 3)
    c = eng.generate([prompt], max_new_tokens=2)[0]
    assert c.prompt_len == 31          # max_len - decode_window
    assert len(c.tokens) <= 2


def test_sampled_generation_is_reproducible_and_in_vocab():
    eng1 = _engine(sampling=SamplingConfig(temperature=0.8, top_k=20),
                   seed=3)
    eng2 = _engine(sampling=SamplingConfig(temperature=0.8, top_k=20),
                   seed=3)
    t1 = eng1.generate([[4, 5, 6]], max_new_tokens=8)[0].tokens
    t2 = eng2.generate([[4, 5, 6]], max_new_tokens=8)[0].tokens
    assert t1 == t2
    assert all(0 <= t < CFG.vocab_size for t in t1)


def test_generate_text_roundtrip():
    eng = _engine()
    tok = ByteTokenizer(CFG.vocab_size)
    outs = eng.generate_text(["hi", "ok"], tok, max_new_tokens=4)
    assert len(outs) == 2
    assert all(isinstance(o, str) for o in outs)


def test_engine_on_mesh_matches_single_device():
    want = _engine().generate([[5, 9, 13]], max_new_tokens=5)[0].tokens
    mesh = build_mesh(MeshConfig(dp=2, tp=4))
    eng = _engine(mesh=mesh)
    got = eng.generate([[5, 9, 13]], max_new_tokens=5)[0].tokens
    assert got == want


def test_fp8_kv_cache_close_to_full_precision():
    """float8_e4m3 KV halves cache HBM (the slot-count ceiling). Random
    weights make long token-exactness meaningless (near-tie argmax), so
    the acceptance bar is: the first decode steps agree, and the whole
    generated distribution stays close — logit cosine vs the f32 cache
    well above what a broken cache would give."""
    import jax.numpy as jnp
    import numpy as np

    from copilot_for_consensus_tpu.engine.generation import GenerationEngine
    from copilot_for_consensus_tpu.models import decoder, decoder_config

    cfg = decoder_config("tiny")
    prompts = [list(range(1, 20)), list(range(5, 40))]
    outs, engines = {}, {}
    for name, kv in (("f32", None), ("fp8", jnp.float8_e4m3fn)):
        eng = GenerationEngine(cfg, num_slots=4, max_len=128, seed=3,
                               kv_dtype=kv, dtype=jnp.float32)
        engines[name] = eng
        outs[name] = [c.tokens for c in eng.generate(prompts,
                                                     max_new_tokens=12)]
    for a, b in zip(outs["f32"], outs["fp8"]):
        assert a[:3] == b[:3], (a, b)

    # Distributional closeness where the cache is actually READ: prefill
    # fills each dtype's cache, then a decode_step attends over it — its
    # logits carry the full quantization error of every cached position.
    logits = {}
    for name, eng in engines.items():
        tokens = jnp.asarray([prompts[0]], dtype=jnp.int32)
        n = len(prompts[0])
        lengths = jnp.asarray([n], dtype=jnp.int32)
        cache = decoder.init_cache(cfg, 1, 64, dtype=eng.kv_dtype)
        _, cache = decoder.prefill(eng.params, tokens, lengths, cfg,
                                   cache, attn_impl="xla")
        lg, _ = decoder.decode_step(
            eng.params, jnp.asarray([7], dtype=jnp.int32),
            jnp.asarray([n], dtype=jnp.int32), cfg, cache)
        logits[name] = np.asarray(lg[0], dtype=np.float64)
    x, y = logits["f32"], logits["fp8"]
    assert not np.array_equal(x, y), "fp8 cache read should perturb logits"
    cos = float(x @ y / (np.linalg.norm(x) * np.linalg.norm(y)))
    assert cos > 0.99, cos


@pytest.mark.parametrize("n_windows", [1, 3])
def test_sliding_window_greedy_multi_window(n_windows):
    """tiny-swa through the chained-window dispatch: the done-piece
    masking (completed windows held OUT of the cache until the single
    end-of-dispatch merge) must respect the sliding window exactly —
    greedy tokens match the naive forward oracle."""
    cfg = decoder_config("tiny-swa")
    assert cfg.sliding_window > 0
    params = decoder.init_params(jax.random.PRNGKey(9), cfg,
                                 dtype=jnp.float32)
    eng = GenerationEngine(cfg, params, num_slots=2, max_len=64,
                           prefill_buckets=(16,), dtype=jnp.float32,
                           attn_impl="xla", decode_window=4,
                           windows_per_dispatch=n_windows)
    prompt = list(range(5, 17))
    comp = eng.generate([prompt], max_new_tokens=16)[0]
    toks, want = list(prompt), []
    for _ in range(16):
        logits = decoder.forward(params, jnp.asarray([toks]), cfg,
                                 attn_impl="xla")
        nxt = int(jnp.argmax(logits[0, -1]))
        want.append(nxt)
        toks.append(nxt)
    assert comp.tokens == want[:len(comp.tokens)]
    assert len(comp.tokens) >= 8


# ---------------------------------------------------------------------------
# Chunked-prefill piggybacking (prefill chunks riding decode dispatches)
# ---------------------------------------------------------------------------


def _piggy_engine(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 128)
    kw.setdefault("prefill_buckets", (16, 32, 96))
    kw.setdefault("decode_window", 8)
    kw.setdefault("prefill_chunk", 8)      # capacity W*C = 64 per lane
    kw.setdefault("prefill_rows", 2)
    kw.setdefault("piggyback_min_prompt", 20)
    return _engine(**kw)


def _wave_engine(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 128)
    kw.setdefault("prefill_buckets", (16, 32, 96))
    kw.setdefault("decode_window", 8)
    kw.setdefault("piggyback_min_prompt", 10**9)   # never piggyback
    return _engine(**kw)


def _prompt(seed, n):
    rng = np.random.default_rng(seed)
    return rng.integers(3, CFG.vocab_size, size=n).tolist()


def test_piggyback_matches_wave_path_exactly():
    """Oracle: the piggybacked engine (float32 end to end, greedy) must
    produce token-identical completions to the monolithic-wave engine —
    chunked prefill is a scheduling change, not a numerics change."""
    prompts = [_prompt(1, 40), _prompt(2, 25), _prompt(3, 5),
               _prompt(4, 33)]                     # mixed: 3 piggy, 1 wave
    want = _wave_engine().generate(prompts, max_new_tokens=6)
    got = _piggy_engine().generate(prompts, max_new_tokens=6)
    for w, g in zip(want, got):
        assert g.tokens == w.tokens
        assert g.prompt_len == w.prompt_len


def test_piggyback_lane_packing_many_short_prompts():
    """Several short prompts pack back-to-back into the same lane's
    dispatch buffer (the packed-lane path short-prompt Poisson needs);
    partial final chunks mask correctly; rows never see a packed
    neighbor's kv."""
    prompts = [_prompt(10 + i, 20 + i) for i in range(6)]  # 3 rows/lane
    want = _wave_engine().generate(prompts, max_new_tokens=5)
    got = _piggy_engine().generate(prompts, max_new_tokens=5)
    for w, g in zip(want, got):
        assert g.tokens == w.tokens


def test_piggyback_oversize_prompts_fall_back_to_wave():
    """Prompts beyond one dispatch's lane capacity (W*C = 64) must take
    the monolithic wave and still interleave correctly with piggybacked
    ones."""
    prompts = [_prompt(20, 90), _prompt(21, 30), _prompt(22, 70),
               _prompt(23, 64)]
    want = _wave_engine().generate(prompts, max_new_tokens=5)
    got = _piggy_engine().generate(prompts, max_new_tokens=5)
    for w, g in zip(want, got):
        assert g.tokens == w.tokens


def test_piggyback_more_rows_than_capacity_and_slot_reuse():
    """More prompts than lanes and slots: staged admission across
    dispatches, slot reuse after retirement, everything still exact."""
    prompts = [_prompt(30 + i, 24 + i) for i in range(6)]
    want = _wave_engine(num_slots=2).generate(prompts, max_new_tokens=4)
    got = _piggy_engine(num_slots=2).generate(prompts, max_new_tokens=4)
    for w, g in zip(want, got):
        assert g.tokens == w.tokens


def test_piggyback_staggered_joins_do_not_disturb_decoding():
    """A prompt joining mid-decode must not perturb tokens already
    streaming from active slots (the freed/prefilling slots' garbage
    decode lanes must drop, not overwrite live timelines)."""
    eng = _piggy_engine()
    first = _prompt(40, 28)
    rid1 = eng.submit(first, max_new_tokens=10)
    done = {}
    for _ in range(2):
        for c in eng.step():
            done[c.request_id] = c
    rid2 = eng.submit(_prompt(41, 45), max_new_tokens=10)
    while len(done) < 2:
        for c in eng.step():
            done[c.request_id] = c
    want = _wave_engine().generate([first, _prompt(41, 45)],
                                   max_new_tokens=10)
    assert done[rid1].tokens == want[0].tokens
    assert done[rid2].tokens == want[1].tokens
