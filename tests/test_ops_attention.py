# Flash-attention kernel vs XLA reference oracle (interpret mode on CPU).
import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu.ops.attention import (
    attention_xla,
    decode_attention,
)
from copilot_for_consensus_tpu.ops.flash_attention import flash_attention


def _rand_qkv(rng, b=2, hq=4, hkv=2, s=96, d=32, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, hq, s, d), dtype)
    k = jax.random.normal(kk, (b, hkv, s, d), dtype)
    v = jax.random.normal(kv, (b, hkv, s, d), dtype)
    return q, k, v


@pytest.mark.parametrize("window", [0, 24])
def test_flash_matches_xla_causal(window):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0))
    ref = attention_xla(q, k, v, causal=True, window=window)
    out = flash_attention(q, k, v, causal=True, window=window,
                          block_q=32, block_kv=32, interpret=True)
    # Pallas interpret mode emulates MXU bf16 input rounding → bf16-level
    # agreement with the fp32 XLA oracle is the expected numerics.
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=1e-2)


def test_flash_matches_xla_bidirectional_padded():
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), s=80)
    lengths = jnp.array([80, 37])
    ref = attention_xla(q, k, v, causal=False, kv_lengths=lengths)
    out = flash_attention(q, k, v, causal=False, kv_lengths=lengths,
                          block_q=32, block_kv=32, interpret=True)
    # Only positions < length are meaningful for padded rows.
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(ref[0]),
                               rtol=2e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(out[1, :, :37]),
                               np.asarray(ref[1, :, :37]),
                               rtol=2e-2, atol=1e-2)


def test_flash_non_divisible_seq_is_padded():
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), s=50)
    ref = attention_xla(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_kv=32,
                          interpret=True)
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=1e-2)


def test_decode_matches_full_attention():
    # Decoding the final token against the cache must equal the last row of
    # full causal attention.
    rng = jax.random.PRNGKey(3)
    q, k, v = _rand_qkv(rng, b=2, s=33)
    full = attention_xla(q, k, v, causal=True)
    s_max = 64
    k_cache = jnp.zeros((2, 2, s_max, 32)).at[:, :, :33].set(k)
    v_cache = jnp.zeros((2, 2, s_max, 32)).at[:, :, :33].set(v)
    lengths = jnp.array([33, 33])
    out = decode_attention(q[:, :, -1], k_cache, v_cache, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full[:, :, -1]),
                               rtol=1e-5, atol=1e-5)


def test_decode_sliding_window_matches_windowed_attention():
    rng = jax.random.PRNGKey(4)
    q, k, v = _rand_qkv(rng, b=1, s=40)
    full = attention_xla(q, k, v, causal=True, window=16)
    k_cache = jnp.zeros((1, 2, 64, 32)).at[:, :, :40].set(k)
    v_cache = jnp.zeros((1, 2, 64, 32)).at[:, :, :40].set(v)
    out = decode_attention(q[:, :, -1], k_cache, v_cache,
                           jnp.array([40]), window=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full[:, :, -1]),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("window", [0, 8])
def test_prefix_window_decode_matches_contiguous(window):
    """decode_attention_prefix_window over (prefix ⊕ window-buffer ⊕
    self) must equal decode_attention over one contiguous cache holding
    the same tokens — including when the sliding window is SMALLER than
    the decode window, where buffer columns must fall out of range
    exactly like prefix columns."""
    from copilot_for_consensus_tpu.ops.attention import (
        decode_attention_prefix_window,
    )

    rng = jax.random.PRNGKey(9)
    b, hkv, d, s = 2, 2, 32, 28          # 28 total tokens per slot
    q, k, v = _rand_qkv(rng, b=b, s=s)
    prefix_len, w = 16, 11               # window step 11 (12th token)
    # contiguous reference: all 28 tokens in one cache
    s_max = 32
    k_cache = jnp.zeros((b, hkv, s_max, d)).at[:, :, :s].set(k)
    v_cache = jnp.zeros((b, hkv, s_max, d)).at[:, :, :s].set(v)
    ref = decode_attention(q[:, :, -1], k_cache, v_cache,
                           jnp.array([s, s]), window=window)
    # split view: prefix [0,16), window buffer holds [16, 27), self = 27
    n_win = 16
    k_win = jnp.zeros((b, hkv, n_win, d)).at[:, :, :w].set(
        k[:, :, prefix_len:prefix_len + w])
    v_win = jnp.zeros((b, hkv, n_win, d)).at[:, :, :w].set(
        v[:, :, prefix_len:prefix_len + w])
    out = decode_attention_prefix_window(
        q[:, :, -1], k_cache, v_cache, k_win, v_win,
        k[:, :, -1], v[:, :, -1],
        prefix_lengths=jnp.array([prefix_len, prefix_len]),
        w=jnp.int32(w), window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_q_offsets_match_xla_oracle():
    """Distinct Sq/Skv with per-row dynamic query offsets (the chunked-
    prefill shape) must match the XLA reference with q_offset."""
    import numpy as np

    from copilot_for_consensus_tpu.ops.attention import attention_xla
    from copilot_for_consensus_tpu.ops.flash_attention import flash_attention

    rng = jax.random.PRNGKey(3)
    kq, kk, kv = jax.random.split(rng, 3)
    b, hq, hkv, c, s_kv, d = 3, 4, 2, 8, 64, 16
    q = jax.random.normal(kq, (b, hq, c, d), jnp.float32)
    k = jax.random.normal(kk, (b, hkv, s_kv, d), jnp.float32)
    v = jax.random.normal(kv, (b, hkv, s_kv, d), jnp.float32)
    offset = 24                      # queries sit at positions 24..31
    lengths = jnp.asarray([32, 29, 25])
    got = flash_attention(q, k, v, causal=True, kv_lengths=lengths,
                          q_offsets=jnp.full((b,), offset),
                          block_q=8, block_kv=16)
    want = attention_xla(q, k, v, causal=True, q_offset=offset,
                         kv_lengths=lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)
