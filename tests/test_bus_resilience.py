"""Pipeline fault plane (ISSUE 8): publish-outbox ride-through,
depth-watermark backpressure, poison quarantine, durable-broker crash
recovery, and the seeded pipeline storm.

Fast lane: stub-broker units (no zmq, no subprocess) for the outbox /
backpressure / quarantine / classification machinery. @slow: the
real-broker regressions (restart ride-through, kill-and-recover,
backpressure e2e) and the multi-phase storm the bench preset
(``BENCH_PRESET=pipeline_chaos``) scales up.
"""

from __future__ import annotations

import threading
import time

import pytest

from copilot_for_consensus_tpu.bus import broker as broker_mod
from copilot_for_consensus_tpu.bus.base import (
    BusSaturated,
    PoisonEnvelope,
    PublishError,
)
from copilot_for_consensus_tpu.bus.faults import (
    FaultBoundary,
    FaultPlan,
    FaultSpec,
    FaultingArchiveStore,
    FaultingDocumentStore,
    PipelineFaultError,
    TransientPipelineFault,
    resolve_boundary,
)
from copilot_for_consensus_tpu.bus.inproc import (
    InProcBroker,
    InProcPublisher,
    InProcSubscriber,
)
from copilot_for_consensus_tpu.bus.validating import ValidatingSubscriber
from copilot_for_consensus_tpu.core.events import ArchiveIngested
from copilot_for_consensus_tpu.core.retry import RetryableError
from copilot_for_consensus_tpu.obs.metrics import InMemoryMetrics


# -- stub broker client ---------------------------------------------------


class StubClient:
    """Scriptable ``_Client`` stand-in: records every request; raises
    ``PublishError`` while ``down``; replies confirms with a scripted
    per-key depth."""

    def __init__(self):
        self.down = False
        self.requests: list[dict] = []
        self.depths: dict[str, int] = {}
        self.lock = threading.Lock()

    def request(self, req: dict) -> dict:
        with self.lock:
            if self.down:
                raise PublishError("stub broker unreachable")
            self.requests.append(dict(req))
            if req["op"] == "pub":
                return {"ok": True, "id": len(self.requests),
                        "depth": self.depths.get(req["rk"], 0)}
            if req["op"] == "depth":
                return {"ok": True,
                        "depth": self.depths.get(req["rk"], 0)}
            if req["op"] == "counts":
                return {"ok": True, "counts": {
                    rk: {"pending": d} for rk, d in self.depths.items()}}
            return {"ok": True}

    def published(self) -> list[tuple[str, dict]]:
        with self.lock:
            return [(r["rk"], r["envelope"]) for r in self.requests
                    if r["op"] == "pub"]

    def close(self):
        pass


def make_publisher(stub, **cfg):
    pub = broker_mod.BrokerPublisher(
        {"address": "tcp://stub", **cfg}, client=stub)
    pub._depth_client = stub     # pacing polls ride the stub too
    return pub


def await_cond(fn, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(0.01)
    return fn()


# -- publish outbox: broker-outage ride-through ---------------------------


def test_outbox_parks_during_outage_and_replays_in_order():
    stub = StubClient()
    pub = make_publisher(stub)
    pub.publish_envelope({"event_type": "e", "n": 0}, routing_key="k")
    stub.down = True
    for n in (1, 2, 3):
        pub.publish_envelope({"event_type": "e", "n": n},
                             routing_key="k")   # parks, no raise
    assert pub.outbox.depth() == 3
    stats = pub.outbox_stats()
    assert stats["confirmed"] == 1 and stats["parked"] == 3
    stub.down = False
    assert await_cond(lambda: pub.outbox.depth() == 0)
    # replay order == publish order (rows leave only after confirm)
    assert [env["n"] for _rk, env in stub.published()] == [0, 1, 2, 3]
    assert pub.outbox_stats()["replayed"] == 3
    pub.close()


def test_publishes_during_replay_park_behind_the_backlog():
    """While anything is parked, new publishes queue BEHIND it — a
    half-replayed outbox must not let fresh traffic overtake parked
    work and scramble per-publisher order."""
    import json

    stub = StubClient()
    pub = make_publisher(stub)
    # A parked row with no replayer running: the state right after an
    # outage began (or a publisher-process restart on a durable
    # outbox_path file).
    pub.outbox.append("k", json.dumps({"event_type": "e", "n": 0}))
    # Broker is UP, but the backlog must drain first: the new publish
    # parks behind it instead of overtaking.
    pub.publish_envelope({"event_type": "e", "n": 1}, routing_key="k")
    assert await_cond(lambda: pub.outbox.depth() == 0)
    assert [env["n"] for _rk, env in stub.published()] == [0, 1]
    assert pub.outbox_stats()["parked"] == 1      # n=1 parked, n=0 manual
    pub.close()


def test_outbox_overflow_raises_structured_bus_saturated():
    stub = StubClient()
    pub = make_publisher(stub, outbox_cap=2)
    stub.down = True
    pub.publish_envelope({"event_type": "e"}, routing_key="k")
    pub.publish_envelope({"event_type": "e"}, routing_key="k")
    with pytest.raises(BusSaturated) as ei:
        pub.publish_envelope({"event_type": "e"}, routing_key="k")
    err = ei.value
    assert err.reason == "outbox-full"
    assert err.routing_key == "k" and err.limit == 2
    assert isinstance(err, PublishError)    # services nack-transient it
    assert pub.outbox_stats()["overflow"] == 1
    # nothing was dropped silently: both parked envelopes still there
    assert pub.outbox.depth() == 2
    pub.close()


def test_injected_publish_fault_takes_the_outage_path():
    """A scripted ``publish`` fault parks the envelope exactly like a
    real outage — the chaos harness's determinism contract."""
    stub = StubClient()
    boundary = resolve_boundary(
        FaultPlan(specs=[FaultSpec(kind="publish", at=1, count=2)]))
    pub = broker_mod.BrokerPublisher({"address": "tcp://stub"},
                                     client=stub, faults=boundary)
    pub._depth_client = stub
    pub.publish_envelope({"event_type": "e", "n": 0}, routing_key="k")
    assert pub.outbox.depth() == 1          # fault == outage == park
    # replay's own publish boundary check burns occurrence 2; after
    # that the replay drains
    assert await_cond(lambda: pub.outbox.depth() == 0)
    assert [env["n"] for _rk, env in stub.published()] == [0]
    pub.close()


# -- depth-watermark backpressure -----------------------------------------


def test_publisher_paces_at_high_watermark_until_drain():
    stub = StubClient()
    pub = make_publisher(stub, high_watermark=10, low_watermark=4,
                         saturation_poll_s=0.01,
                         saturation_max_wait_s=5.0)
    stub.depths["k"] = 12        # confirm reports saturated depth

    drained = threading.Event()

    def drain_later():
        time.sleep(0.05)
        with stub.lock:
            stub.depths["k"] = 3
        drained.set()

    t = threading.Thread(target=drain_later)
    t.start()
    t0 = time.monotonic()
    pub.publish_envelope({"event_type": "e"}, routing_key="k")
    waited = time.monotonic() - t0
    t.join()
    assert drained.is_set() and waited >= 0.04   # actually paced
    assert pub.outbox_stats()["throttle_waits"] == 1
    assert pub.saturation() == {}                 # drained below high
    pub.close()


def test_saturation_surfaces_hot_keys_and_close_releases_pace():
    stub = StubClient()
    pub = make_publisher(stub, high_watermark=5, saturation_poll_s=0.01,
                         saturation_max_wait_s=30.0)
    stub.depths["k"] = 9
    done = threading.Event()

    def blocked_publish():
        pub.publish_envelope({"event_type": "e"}, routing_key="k")
        done.set()

    t = threading.Thread(target=blocked_publish)
    t.start()
    assert await_cond(lambda: pub.saturation() == {"k": 9})
    pub.close()                  # stop event releases the pace wait
    assert done.wait(5.0)
    t.join()


def test_validating_publisher_delegates_depth_feedback():
    """EventPublisher defines concrete {} defaults for saturation()/
    pending_depths(), so the validating wrapper needs EXPLICIT
    delegation — __getattr__ never fires for inherited class attributes.
    Without it every assembled pipeline (all service publishers are
    validating-wrapped) silently loses the consumption throttle and the
    ingestion pacer."""
    from copilot_for_consensus_tpu.bus.validating import (
        ValidatingPublisher,
    )

    broker = InProcBroker("sat.wrap.test")
    pub = ValidatingPublisher(
        InProcPublisher(config={"high_watermark": 2}, broker=broker))
    sub = InProcSubscriber(broker=broker)
    sub.subscribe(["archive.ingested"], lambda env: None)
    for i in range(3):
        pub.publish(ArchiveIngested(archive_id=f"w{i}"))
    assert pub.saturation() == {"archive.ingested": 3}
    assert pub.pending_depths()["archive.ingested"] == 3
    sub.drain()
    assert pub.saturation() == {}


def test_stale_hot_snapshot_repolls_and_clears():
    """A key hot at its last confirm must not read saturated forever
    once the producer goes quiet: past ``saturation_refresh_s`` the
    snapshot re-polls the broker, so a drained queue stops throttling
    consumers (and an unreachable broker reads as not-hot — outages
    are the outbox's problem, not the throttle's)."""
    stub = StubClient()
    pub = make_publisher(stub, high_watermark=10,
                         saturation_poll_s=0.01,
                         saturation_max_wait_s=0.05,
                         saturation_refresh_s=0.0)
    stub.depths["k"] = 12
    pub.publish_envelope({"event_type": "e"}, routing_key="k")
    assert pub.saturation() == {"k": 12}      # re-poll: still hot
    with stub.lock:
        stub.depths["k"] = 0                  # producer quiet, queue drains
    assert pub.saturation() == {}             # stale snapshot re-polled
    with stub.lock:
        stub.depths["k"] = 12
    pub.publish_envelope({"event_type": "e"}, routing_key="k")
    assert pub.saturation() == {"k": 12}      # hot again
    stub.down = True
    assert pub.saturation() == {}             # broker away: not-hot
    pub.close()


def test_inproc_publisher_saturation_parity():
    broker = InProcBroker("sat.test")
    pub = InProcPublisher(config={"high_watermark": 2}, broker=broker)
    sub = InProcSubscriber(broker=broker)
    sub.subscribe(["archive.ingested"], lambda env: None)
    for i in range(3):
        pub.publish(ArchiveIngested(archive_id=f"a{i}"))
    assert pub.saturation() == {"archive.ingested": 3}
    assert pub.pending_depths()["archive.ingested"] == 3
    sub.drain()
    assert pub.saturation() == {}


def test_base_service_throttles_consumption_while_saturated():
    from copilot_for_consensus_tpu.services.base import BaseService

    class HotPublisher:
        def __init__(self):
            self.hot = {"json.parsed": 50}

        def saturation(self):
            return self.hot

        def pending_depths(self):
            return dict(self.hot)

        def publish(self, event, routing_key=None):
            pass

        def publish_envelope(self, envelope, routing_key=None):
            pass

    class Svc(BaseService):
        name = "probe"
        consumes = ()

        def on_ArchiveIngested(self, event):
            pass

    metrics = InMemoryMetrics()
    svc = Svc(HotPublisher(), store=None, metrics=metrics,
              throttle_pause_s=0.03)
    env = ArchiveIngested(archive_id="a1").to_envelope()
    t0 = time.monotonic()
    svc.handle_envelope(env)
    assert time.monotonic() - t0 >= 0.02        # paused once
    assert metrics.counter_value(
        "bus_throttle_total", {"service": "probe"}) == 1
    # stop_throttling releases current and future pauses (shutdown
    # must never wait out a watermark)
    svc.stop_throttling()
    t0 = time.monotonic()
    svc.handle_envelope(env)
    assert time.monotonic() - t0 < 0.02


def test_ingestion_pacing_waits_for_queues_below_watermark():
    from copilot_for_consensus_tpu.services.ingestion import (
        IngestionService,
    )

    class DepthPublisher:
        def __init__(self):
            self.depths = {"json.parsed": 100,
                           "parsing.failed": 10**6}   # failure keys skip

        def saturation(self):
            return {}

        def pending_depths(self):
            return dict(self.depths)

        def publish(self, event, routing_key=None):
            pass

        def publish_envelope(self, envelope, routing_key=None):
            pass

    pub = DepthPublisher()
    svc = IngestionService(pub, store=None, archive_store=None,
                           fetchers={}, bus_watermark=50,
                           bus_poll_s=0.01, bus_pause_max_s=5.0)

    def drain_later():
        time.sleep(0.05)
        pub.depths["json.parsed"] = 5

    t = threading.Thread(target=drain_later)
    t.start()
    waited = svc._await_bus_capacity()
    t.join()
    assert waited >= 0.04                     # held until below SLO
    assert svc._await_bus_capacity() < 0.01   # healthy: no pause
    # unconfigured watermark is a strict no-op
    svc.bus_watermark = 0
    assert svc._await_bus_capacity() == 0.0


# -- poison quarantine ----------------------------------------------------


class StubVerdictClient(StubClient):
    """Records ack/nack verdicts for dispatch-classification tests."""

    def fetch_reply(self, msg):
        return {"ok": True, "msgs": [msg]}


def _dispatch_with(exc, metrics=None):
    stub = StubVerdictClient()
    sub = broker_mod.BrokerSubscriber({"address": "tcp://stub"},
                                      client=stub)
    sub.metrics = metrics or InMemoryMetrics()

    def handler(env):
        if exc is not None:
            raise exc

    sub.subscribe(["archive.ingested"], handler)
    sub._dispatch({"id": 7, "rk": "archive.ingested", "attempts": 0,
                   "envelope": {"event_type": "ArchiveIngested",
                                "event_id": "e-1"}})
    verdicts = [r for r in stub.requests if r["op"] in ("ack", "nack")]
    assert len(verdicts) == 1
    return verdicts[0], sub.metrics


def test_dispatch_classification_transient_vs_poison():
    ack, _ = _dispatch_with(None)
    assert ack["op"] == "ack"

    # RetryableError / bus-level PublishError → plain nack (lease/
    # redelivery budget applies)
    for exc in (RetryableError("flaky"), PublishError("bus away")):
        nack, m = _dispatch_with(exc)
        assert nack["op"] == "nack" and not nack.get("poison")
        assert m.counter_value(
            "bus_dispatch_failures_total",
            {"queue": "archive.ingested", "kind": "transient"}) == 1

    # deterministic failures → poison nack with a structured reason
    for exc, reason_part in (
            (PoisonEnvelope("schema validation failed: no data"),
             "schema validation failed"),
            (ValueError("bad id"), "ValueError: bad id"),
            (PipelineFaultError("injected terminal", kind="store_write"),
             "injected terminal")):
        nack, m = _dispatch_with(exc)
        assert nack["op"] == "nack" and nack["poison"] is True
        assert reason_part in nack["reason"]
        assert m.counter_value("bus_poison_total",
                               {"queue": "archive.ingested"}) == 1
        assert m.counter_value(
            "bus_dispatch_failures_total",
            {"queue": "archive.ingested", "kind": "poison"}) == 1

    # a scripted TRANSIENT pipeline fault is a RetryableError
    nack, _ = _dispatch_with(TransientPipelineFault("hiccup",
                                                    kind="store_write"))
    assert nack["op"] == "nack" and not nack.get("poison")


def test_queuestore_poison_nack_skips_redelivery_budget():
    store = broker_mod._QueueStore(":memory:")
    store.bind(["k"], "g")
    store.enqueue("k", "{}")
    (mid, _rk, _env, _at), = store.fetch(["k"], "g", 1, 30.0)
    store.nack([mid], max_redeliveries=3, poison=True,
               reason="schema validation failed: boom")
    dead = store.dead_letters("k")
    assert len(dead) == 1
    assert dead[0][3] == 0       # attempts untouched: never cycled
    assert dead[0][4] == "schema validation failed: boom"
    # operator requeue resets budget AND reason
    assert store.requeue_dead("k") == 1
    assert store.counts()["k"]["pending"] == 1
    (mid, _rk, _env, _at), = store.fetch(["k"], "g", 1, 30.0)
    for _ in range(3):           # transient path still budgets
        store.nack([mid], max_redeliveries=3)
        got = store.fetch(["k"], "g", 1, 30.0)
        if got:
            (mid, _rk, _env, _at), = got
    dead = store.dead_letters("k")
    assert len(dead) == 1 and dead[0][4] == "redelivery budget exhausted"
    store.close()


def test_inproc_poison_quarantines_without_redelivery():
    broker = InProcBroker("poison.test")
    pub = InProcPublisher(broker=broker)
    sub = InProcSubscriber(broker=broker)
    calls = []

    def poison_handler(env):
        calls.append(env)
        raise PoisonEnvelope("deterministic failure")

    sub.subscribe(["archive.ingested"], poison_handler)
    pub.publish(ArchiveIngested(archive_id="bad"))
    sub.drain()
    assert len(calls) == 1                    # no redelivery cycles
    assert len(broker.dead_lettered) == 1
    assert broker.dead_lettered[0][0] == "archive.ingested"


def test_validating_subscriber_raises_poison_on_schema_failure():
    broker = InProcBroker("val.poison")
    pub = InProcPublisher(broker=broker)
    invalid = []
    sub = ValidatingSubscriber(InProcSubscriber(broker=broker),
                               on_invalid=lambda e, x: invalid.append(e))
    seen = []
    sub.subscribe(["archive.ingested"], lambda env: seen.append(env))
    pub.publish_envelope({"event_type": "ArchiveIngested"},
                         "archive.ingested")           # schema-invalid
    pub.publish(ArchiveIngested(archive_id="ok"))
    sub.drain()
    assert [e["data"]["archive_id"] for e in seen] == ["ok"]
    assert len(invalid) == 1 and sub.invalid_count == 1
    # quarantined (dead-lettered once), not silently acked away
    assert len(broker.dead_lettered) == 1


def test_base_service_unexpected_error_publishes_failure_then_poisons():
    from copilot_for_consensus_tpu.services.base import BaseService

    published = []

    class Pub:
        def publish(self, event, routing_key=None):
            published.append(event)

        def publish_envelope(self, envelope, routing_key=None):
            published.append(envelope)

    class Svc(BaseService):
        name = "probe"
        consumes = ()

        def on_ArchiveIngested(self, event):
            raise KeyError("missing doc")

        def failure_event(self, envelope, error, attempts):
            return ("probe.failed", str(error))

    svc = Svc(Pub(), store=None)
    env = ArchiveIngested(archive_id="a1").to_envelope()
    with pytest.raises(PoisonEnvelope) as ei:
        svc.handle_envelope(env)
    assert "KeyError" in str(ei.value)
    assert len(published) == 1                # the *Failed event record

    class BusDownSvc(Svc):
        def on_ArchiveIngested(self, event):
            raise PublishError("broker away and outbox full")

    # bus-level trouble is transient: propagate for nack/redelivery,
    # do NOT mint a failure event the same broker couldn't carry
    published.clear()
    with pytest.raises(PublishError):
        BusDownSvc(Pub(), store=None).handle_envelope(env)
    assert published == []


# -- zombie-redelivery idempotency ----------------------------------------


_ZOMBIE_MBOX = b"""From a@example.org Mon Jan  1 00:00:00 2024
Message-ID: <m1@example.org>
Subject: consensus call
From: A <a@example.org>
Date: Mon, 1 Jan 2024 00:00:00 +0000

first message

From b@example.org Mon Jan  1 00:00:01 2024
Message-ID: <m2@example.org>
In-Reply-To: <m1@example.org>
Subject: Re: consensus call
From: B <b@example.org>
Date: Mon, 1 Jan 2024 00:00:01 +0000

second message
"""


def test_zombie_reparse_preserves_summary_link_written_mid_parse():
    """At-least-once means a ZOMBIE parse (lease expired mid-parse; the
    redelivery already completed elsewhere) can write thread docs
    minutes late — its writes must not clobber fields other writers
    own. Regression: the old read-carry-replace (get prev → copy
    summary_id → upsert) lost a summary link that landed between its
    stale read and its replace, un-summarizing a whole archive's
    threads AFTER the pipeline looked quiescent (seen as lost=19 in a
    pipeline_chaos storm under CPU contention). The parse write is now
    a field-merge update, so a summary_id landing at ANY point survives
    without ever being read."""
    from copilot_for_consensus_tpu.archive.base import (
        InMemoryArchiveStore,
    )
    from copilot_for_consensus_tpu.services.parsing import ParsingService
    from copilot_for_consensus_tpu.storage.memory import (
        InMemoryDocumentStore,
    )

    class SummaryLandsMidParse(InMemoryDocumentStore):
        """Simulates the summarizer winning the race: the instant the
        zombie parse writes a thread doc, the summary link for that
        thread has JUST been set by the concurrent (completed)
        pipeline."""

        def update_document(self, collection, doc_id, updates):
            if (collection == "threads"
                    and "summary_id" not in updates
                    and not (self.get_document("threads", doc_id)
                             or {}).get("summary_id")):
                super().update_document("threads", doc_id,
                                        {"summary_id": "sum-live"})
            return super().update_document(collection, doc_id, updates)

    store = SummaryLandsMidParse()
    store.connect()
    archive_store = InMemoryArchiveStore()
    archive_store.save("arch-z", _ZOMBIE_MBOX)
    store.upsert_document("archives", {
        "archive_id": "arch-z", "source_id": "s1", "parsed": False})
    broker = InProcBroker("zombie.test")
    svc = ParsingService(InProcPublisher(broker=broker), store,
                         archive_store)

    svc.process_archive("arch-z")           # first parse (creates docs)
    svc.process_archive("arch-z")           # zombie re-parse
    threads = store.query_documents("threads", {})
    assert threads, "fixture produced no threads"
    for th in threads:
        assert th.get("summary_id") == "sum-live", th
        assert th.get("message_count") == 2     # parse fields did land
        assert th.get("parsed_at")              # first-parse stamp kept


# -- fault plane (bus/faults.py) ------------------------------------------


def test_fault_boundary_transient_vs_terminal_kinds():
    boundary = FaultBoundary(
        FaultPlan(specs=[FaultSpec(kind="store_write", at=1, count=1),
                         FaultSpec(kind="archive_read", at=1, count=1)]),
        terminal_kinds=("archive_read",))
    with pytest.raises(TransientPipelineFault) as ti:
        boundary.check("store_write")
    assert isinstance(ti.value, RetryableError)
    assert ti.value.kind == "store_write" and ti.value.occurrence == 1
    with pytest.raises(PipelineFaultError) as pe:
        boundary.check("archive_read")
    assert not isinstance(pe.value, RetryableError)
    boundary.check("store_write")       # spec spent: no fire
    assert boundary.stats()["fired"] == 2


def test_faulting_store_wrappers_fire_and_delegate():
    class Store:
        def __init__(self):
            self.writes = []

        def upsert_document(self, collection, doc):
            self.writes.append((collection, doc))
            return "id-1"

        def find_document(self, collection, doc_id):
            return {"_id": doc_id}

    class Archive:
        def load(self, archive_id):
            return b"bytes"

    plan = FaultPlan(specs=[FaultSpec(kind="store_write", at=1, count=1),
                            FaultSpec(kind="archive_read", at=1,
                                      count=1)])
    boundary = resolve_boundary(plan)
    store = FaultingDocumentStore(Store(), boundary)
    with pytest.raises(TransientPipelineFault):
        store.upsert_document("c", {"a": 1})
    assert store.upsert_document("c", {"a": 1}) == "id-1"   # recovered
    assert store.find_document("c", "x") == {"_id": "x"}    # reads pass
    archive = FaultingArchiveStore(Archive(), boundary)     # SHARED plan
    with pytest.raises(TransientPipelineFault):
        archive.load("a1")
    assert archive.load("a1") == b"bytes"


def test_build_pipeline_wires_fault_plan_end_to_end(fixtures_dir):
    from copilot_for_consensus_tpu.services.runner import build_pipeline

    plan = FaultPlan(seed=3, specs=[
        FaultSpec(kind="store_write", at=1, count=1)]).to_dict()
    p = build_pipeline({"faults": {"plan": plan,
                                   "terminal_kinds": ["archive_read"]}})
    assert p.fault_boundary is not None
    assert p.fault_boundary.terminal_kinds == {"archive_read"}
    # the wrapped store fires the shared boundary
    with pytest.raises(TransientPipelineFault):
        p.store.upsert_document("sources", {"source_id": "s"})
    # spec spent: pipeline runs clean end-to-end afterwards — the
    # transient service-retry spine absorbs nothing here, the plan is
    # simply exhausted
    p.ingestion.create_source({
        "source_id": "m", "name": "m", "fetcher": "local",
        "location": str(fixtures_dir / "ietf-sample.mbox")})
    p.ingestion.trigger_source("m")
    p.drain()
    stats = p.reporting.stats()
    assert stats["reports"] == stats["threads"] > 0


def test_pipeline_bus_counts_and_publisher_stats_inproc():
    from copilot_for_consensus_tpu.services.runner import build_pipeline

    p = build_pipeline({})
    p.broker.publish({"event_type": "report.published"},
                     "report.published")
    counts = p.bus_counts()
    assert counts["report.published"]["pending"] == 1
    assert counts["report.published"]["dead"] == 0
    # drained keys re-report zero, not stick
    p.broker._pending.clear()
    assert p.bus_counts()["report.published"]["pending"] == 0
    # in-proc publishers have no outbox: stats aggregate to zeros
    assert p.publisher_stats()["outbox_depth"] == 0


# -- real broker (zmq): restart ride-through + crash recovery -------------

pytestmark_slow = pytest.mark.slow


@pytest.fixture
def live_broker(tmp_path):
    if not broker_mod.HAS_ZMQ:
        pytest.skip("pyzmq missing")
    b = broker_mod.Broker(port=0,
                          db_path=str(tmp_path / "q.sqlite3")).start()
    yield b
    b.stop()


@pytest.mark.slow
def test_broker_restart_costs_latency_not_work(tmp_path):
    """THE ride-through regression (acceptance bullet 4): the broker
    dies mid-run with a publisher still producing; once it returns on
    the same durable db, the outbox replays in publish order and every
    message is consumed — zero dead letters, zero loss."""
    if not broker_mod.HAS_ZMQ:
        pytest.skip("pyzmq missing")
    db = str(tmp_path / "q.sqlite3")
    port = broker_mod.Broker(port=0).start()  # steal a free port
    addr, pnum = port.address, port.port
    port.stop()
    b = broker_mod.Broker(port=pnum, db_path=db).start()
    pub = broker_mod.BrokerPublisher({"address": addr, "timeout_ms": 300,
                                      "retries": 1})
    sub = broker_mod.BrokerSubscriber({"address": addr})
    seen = []
    sub.subscribe(["archive.ingested"], lambda env: seen.append(env))
    for n in range(3):
        pub.publish_envelope({"event_type": "archive.ingested", "n": n},
                             routing_key="archive.ingested")
    b.stop()                                  # broker restart begins
    for n in range(3, 8):
        pub.publish_envelope({"event_type": "archive.ingested", "n": n},
                             routing_key="archive.ingested")   # parks
    assert pub.outbox.depth() == 5
    assert pub.outbox_stats()["parked"] == 5
    b2 = broker_mod.Broker(port=pnum, db_path=db).start()
    try:
        assert await_cond(lambda: pub.outbox.depth() == 0, timeout=15.0)
        deadline = time.monotonic() + 10
        while len(seen) < 8 and time.monotonic() < deadline:
            sub.drain()
        assert sorted(e["n"] for e in seen) == list(range(8))
        # in order per publisher: the parked tail replayed 3..7 after
        # the confirmed head 0..2
        assert [e["n"] for e in seen] == list(range(8))
        assert b2.store.dead_letters() == []
    finally:
        sub.close()
        pub.close()
        b2.stop()


@pytest.mark.slow
def test_durable_broker_crash_recovery_with_leased_messages(tmp_path):
    """Satellite: broker on a real sqlite db killed mid-run with
    messages pending AND leased; restart → pending survive, expired
    leases redeliver, consumers resume via start_consuming's backoff,
    nothing lost, nothing double-acked."""
    if not broker_mod.HAS_ZMQ:
        pytest.skip("pyzmq missing")
    import subprocess
    import sys

    db = str(tmp_path / "queues.sqlite3")
    port = 5743
    cmd = [sys.executable, "-m", "copilot_for_consensus_tpu.bus.broker",
           "--port", str(port), "--db", db, "--lease-s", "0.5"]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE)
    seen: list[dict] = []
    consumer = None
    consume_thread = None
    try:
        proc.stdout.readline()                # bound
        addr = f"tcp://127.0.0.1:{port}"
        pub = broker_mod.BrokerPublisher({"address": addr,
                                          "timeout_ms": 500})
        for i in range(12):
            pub.publish_envelope({"event_type": "archive.ingested",
                                  "n": i},
                                 routing_key="archive.ingested")
        # a consumer loop that survives the outage via backoff
        consumer = broker_mod.BrokerSubscriber(
            {"address": addr, "timeout_ms": 300, "retries": 1,
             "poll_interval_s": 0.02})
        lock = threading.Lock()

        def handle(env):
            with lock:
                seen.append(env)

        consumer.subscribe(["archive.ingested"], handle)
        consume_thread = threading.Thread(
            target=consumer.start_consuming, daemon=True)
        consume_thread.start()
        assert await_cond(lambda: len(seen) >= 2, timeout=10.0)
        # strand one message INFLIGHT: fetch on a separate group-
        # sharing client and never ack, then kill the broker
        zombie = broker_mod.BrokerSubscriber({"address": addr,
                                              "timeout_ms": 500})
        zombie.subscribe(["archive.ingested"], lambda env: None)
        zombie._client.request({"op": "fetch",
                                "rks": ["archive.ingested"], "max": 1})
        zombie.close()
        proc.kill()
        proc.wait(timeout=10)
        time.sleep(0.6)        # consumer loop rides the outage backoff
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE)
        proc.stdout.readline()
        # everything delivers: pending survived the crash, the stranded
        # lease expired and redelivered, the loop reconnected by itself
        assert await_cond(
            lambda: len({e["n"] for e in seen}) == 12, timeout=20.0)
        time.sleep(0.7)        # one more lease window: no double-acks
        counts = {}
        c = broker_mod._Client(f"tcp://127.0.0.1:{port}",
                               timeout_ms=1000)
        counts = c.request({"op": "counts"})["counts"]
        c.close()
        assert counts.get("archive.ingested", {}).get("pending", 0) == 0
        assert counts.get("archive.ingested", {}).get("inflight", 0) == 0
        # at-least-once: duplicates allowed, loss is not
        assert {e["n"] for e in seen} == set(range(12))
        pub.close()
    finally:
        if consumer is not None:
            consumer.stop()
        if consume_thread is not None:
            consume_thread.join(timeout=5)
        if consumer is not None:
            consumer.close()
        proc.kill()
        proc.wait(timeout=10)


@pytest.mark.slow
def test_poison_quarantine_and_dlq_ops_on_durable_broker(live_broker):
    """Poison goes straight to the dead-letter table with its reason;
    the failed-queues CLI surface (DeadLetterManager) triages,
    requeues, and purges it."""
    from copilot_for_consensus_tpu.tools.failed_queues import (
        DeadLetterManager,
    )

    pub = broker_mod.BrokerPublisher({"address": live_broker.address})
    sub = broker_mod.BrokerSubscriber({"address": live_broker.address})
    calls = []

    def poison(env):
        calls.append(env)
        raise ValueError("deterministic: unknown archive")

    sub.subscribe(["archive.ingested"], poison)
    pub.publish_envelope({"event_type": "archive.ingested", "n": 1},
                         routing_key="archive.ingested")
    for _ in range(3):
        sub.drain()
    assert len(calls) == 1                    # skipped the budget
    dlq = DeadLetterManager(live_broker.address)
    dead = dlq.list_dead("archive.ingested")
    assert len(dead) == 1
    assert "ValueError: deterministic" in dead[0]["reason"]
    assert dead[0]["attempts"] == 0
    summary = dlq.summarize_dead()
    assert list(summary) == ["archive.ingested"]
    # requeue → redelivers (and re-quarantines, cause unfixed)
    assert dlq.requeue_dead("archive.ingested") == 1
    sub.drain()
    assert len(calls) == 2
    assert dlq.purge_dead("archive.ingested") == 1
    assert dlq.list_dead() == []
    dlq.close()
    sub.close()
    pub.close()


@pytest.mark.slow
def test_backpressure_bounds_broker_depth_under_overload(live_broker):
    """Sustained overload with the watermark configured: broker depth
    converges under the watermark instead of growing unboundedly."""
    hw = 20
    pub = broker_mod.BrokerPublisher(
        {"address": live_broker.address, "high_watermark": hw,
         "low_watermark": 5, "saturation_poll_s": 0.01,
         "saturation_max_wait_s": 10.0})
    sub = broker_mod.BrokerSubscriber({"address": live_broker.address,
                                       "batch": 4})
    sub.subscribe(["archive.ingested"], lambda env: time.sleep(0.001))
    stop = threading.Event()
    max_depth = 0

    def consume():
        while not stop.is_set():
            sub.drain(max_messages=4)
            time.sleep(0.002)

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    for n in range(200):
        pub.publish_envelope({"event_type": "archive.ingested", "n": n},
                             routing_key="archive.ingested")
        max_depth = max(max_depth,
                        live_broker.store.depth("archive.ingested"))
    stop.set()
    t.join(timeout=5)
    sub.close()
    assert pub.outbox_stats()["throttle_waits"] >= 1
    # pacing holds the flood at the watermark (+ batch slack)
    assert max_depth <= hw + 5, max_depth
    pub.close()


@pytest.mark.slow
def test_pipeline_chaos_storm_gate():
    """THE tentpole gate at test scale: the same three-arm harness
    BENCH_PRESET=pipeline_chaos runs (overload with backpressure
    off/on, then the seeded storm — broker restart, store/vector/
    archive faults, consumer crash-after-work, consume-loop outages,
    scripted publish faults, poison envelopes) over a scaled-down
    corpus. Zero threads without a summary, zero duplicate terminal
    artifacts, exactly the injected poison quarantined, parked
    publishes replayed, final depths inside the scaled SLO."""
    if not broker_mod.HAS_ZMQ:
        pytest.skip("pyzmq missing")
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    knobs = {"BENCH_PIPE_MESSAGES": "160", "BENCH_PIPE_ARCHIVES": "4",
             "BENCH_PIPE_FLOOD_MESSAGES": "120",
             "BENCH_PIPE_FLOOD_ARCHIVES": "2",
             "BENCH_PIPE_WARN_SLO": "16",
             "BENCH_PIPE_DRAG_S": "0.015",
             "BENCH_PIPE_POISON": "3",
             "BENCH_PIPE_BUDGET_S": "240"}
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    try:
        out = bench.pipeline_chaos_headline()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert out["lost"] == 0, out
    assert out["duplicated"] == 0, out
    assert out["quarantined"] == 3, out
    assert out["replayed_publishes"] >= 1, out
    assert out["redelivered"] >= 1, out
    assert out["final_depth_max"] < 16, out
    # both overload arms in the artifact: pacing held depth under the
    # scaled warn SLO; the unpaced arm flooded well past it
    assert out["max_depth_backpressure_on"] < 16, out
    assert out["max_depth_backpressure_off"] >= 32, out
    # tracing tentpole: zero orphan spans under the storm (redelivery,
    # outbox replay and the broker restart all yield annotated retries)
    # and the dragged chunking handler is the NAMED bottleneck stage
    assert out["orphan_spans"] == 0, out
    assert out["bottleneck_stage"] == "chunking", out
    assert out["stage_p95_s"].get("chunking", 0) > 0, out
    assert "chunking" in out["queue_wait_p95_s"], out
    assert out["backpressure_ok"] and out["storm_ok"], out
    assert out["pipeline_chaos_ok"] is True, out


# -- stage scale-out (ISSUE 11): competing consumers + batched dispatch ---


def test_queuestore_competing_consumers_never_double_dispatch():
    """Two fetchers in ONE group over the durable queue store must
    split the backlog disjointly: fetch atomically moves rows to
    inflight, so a message can never be leased twice while a lease is
    live."""
    store = broker_mod._QueueStore(":memory:")
    store.bind(["k"], "g")
    for i in range(30):
        store.enqueue("k", "{}")
    a = store.fetch(["k"], "g", 16, 30.0)
    b = store.fetch(["k"], "g", 16, 30.0)
    ids_a = {r[0] for r in a}
    ids_b = {r[0] for r in b}
    assert not ids_a & ids_b
    assert len(ids_a | ids_b) == 30
    store.ack(sorted(ids_a | ids_b))
    assert store.counts() == {}
    store.close()


def test_queuestore_expired_lease_redelivers_exactly_once():
    store = broker_mod._QueueStore(":memory:")
    store.bind(["k"], "g")
    store.enqueue("k", "{}")
    (mid, _rk, _env, at0), = store.fetch(["k"], "g", 4, 0.01)
    assert store.fetch(["k"], "g", 4, 0.01) == []   # leased: invisible
    time.sleep(0.05)
    store.expire_leases()
    redelivered = store.fetch(["k"], "g", 4, 30.0)
    assert [r[0] for r in redelivered] == [mid]     # same row, once
    assert store.fetch(["k"], "g", 4, 30.0) == []
    store.ack([mid])
    assert store.dead_letters() == []
    store.close()


def test_queuestore_dlq_counts_exact_under_concurrent_nacks():
    """N worker threads nacking concurrently (half poison, half budget
    exhaustion) must leave EXACTLY one dead row per message, reasons
    and attempt counters intact — the competing-consumer quarantine
    contract."""
    store = broker_mod._QueueStore(":memory:")
    store.bind(["k"], "g")
    for _ in range(12):
        store.enqueue("k", "{}")
    rows = store.fetch(["k"], "g", 12, 30.0)
    assert len(rows) == 12
    ids = [r[0] for r in rows]

    def poison_nack(batch):
        store.nack(batch, max_redeliveries=1, poison=True,
                   reason="schema validation failed: x")

    def budget_nack(batch):
        store.nack(batch, max_redeliveries=1)

    threads = [threading.Thread(target=poison_nack, args=(ids[i::4],))
               for i in range(2)]
    threads += [threading.Thread(target=budget_nack, args=(ids[2 + i::4],))
                for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dead = store.dead_letters("k")
    assert len(dead) == 12
    assert sorted(d[0] for d in dead) == sorted(ids)   # no dup, no loss
    poisoned = [d for d in dead if d[4].startswith("schema validation")]
    budgeted = [d for d in dead if d[4] == "redelivery budget exhausted"]
    assert len(poisoned) == 6 and len(budgeted) == 6
    assert all(d[3] == 0 for d in poisoned)    # attempts untouched
    assert all(d[3] == 1 for d in budgeted)
    store.close()


def test_broker_subscriber_prefetch_config_knob():
    """`bus.prefetch` sizes the per-fetch lease batch (the old
    hardcoded 16); the legacy `batch` key stays as an alias and
    prefetch wins when both are set."""
    stub = StubClient()
    assert broker_mod.BrokerSubscriber(
        {"address": "tcp://stub"}, client=stub).batch == 16
    assert broker_mod.BrokerSubscriber(
        {"address": "tcp://stub", "prefetch": 48},
        client=stub).batch == 48
    assert broker_mod.BrokerSubscriber(
        {"address": "tcp://stub", "batch": 9}, client=stub).batch == 9
    assert broker_mod.BrokerSubscriber(
        {"address": "tcp://stub", "prefetch": 48, "batch": 9},
        client=stub).batch == 48


class StubWaveClient(StubClient):
    """StubClient whose fetches serve scripted message waves."""

    def __init__(self, waves):
        super().__init__()
        self.waves = list(waves)

    def request(self, req):
        if req.get("op") == "fetch":
            with self.lock:
                self.requests.append(dict(req))
            return {"ok": True,
                    "msgs": self.waves.pop(0) if self.waves else []}
        return super().request(req)


def _wave_msgs(rk, n, start=1):
    return [{"id": start + i, "rk": rk, "attempts": 0,
             "envelope": {"event_type": "JSONParsed", "event_id": f"e{i}",
                          "data": {"message_doc_id": f"m{i}"}}}
            for i in range(n)]


def test_broker_batch_dispatch_groups_verdicts_per_outcome():
    """A registered batch route dispatches one fetch wave as ONE
    callback call; per-envelope outcomes map to grouped verdicts —
    one ack for the successes, one transient nack, poison nacks with
    their structured reasons."""
    from copilot_for_consensus_tpu.core.retry import RetryableError

    stub = StubWaveClient([_wave_msgs("json.parsed", 4)])
    sub = broker_mod.BrokerSubscriber({"address": "tcp://stub"},
                                      client=stub)
    sub.metrics = InMemoryMetrics()
    waves = []

    def batch_cb(envelopes):
        waves.append(list(envelopes))
        return [None, RetryableError("store busy"), None,
                PoisonEnvelope("schema validation failed: nope")]

    sub.subscribe(["json.parsed"], lambda env: None)
    assert sub.subscribe_batch(["json.parsed"], batch_cb) is True
    assert sub.drain(4) == 4
    assert len(waves) == 1 and len(waves[0]) == 4
    verdicts = [r for r in stub.requests if r["op"] in ("ack", "nack")]
    acks = [v for v in verdicts if v["op"] == "ack"]
    nacks = [v for v in verdicts if v["op"] == "nack"]
    assert len(acks) == 1 and sorted(acks[0]["ids"]) == [1, 3]
    transient = [v for v in nacks if not v.get("poison")]
    poison = [v for v in nacks if v.get("poison")]
    assert len(transient) == 1 and transient[0]["ids"] == [2]
    assert len(poison) == 1 and poison[0]["ids"] == [4]
    assert "schema validation failed" in poison[0]["reason"]


def test_broker_batch_callback_raise_falls_back_to_single_dispatch():
    """A wave-level callback failure degrades to the exact per-envelope
    path: every message dispatched individually, individually acked."""
    stub = StubWaveClient([_wave_msgs("json.parsed", 3)])
    sub = broker_mod.BrokerSubscriber({"address": "tcp://stub"},
                                      client=stub)
    sub.metrics = InMemoryMetrics()
    singles = []
    sub.subscribe(["json.parsed"], lambda env: singles.append(env))

    def bad_batch(envelopes):
        raise RuntimeError("whole wave exploded")

    sub.subscribe_batch(["json.parsed"], bad_batch)
    assert sub.drain(3) == 3
    assert len(singles) == 3
    acks = [r for r in stub.requests if r["op"] == "ack"]
    assert sorted(i for a in acks for i in a["ids"]) == [1, 2, 3]
    assert not [r for r in stub.requests if r["op"] == "nack"]


def test_broker_batch_dispatch_only_groups_registered_keys():
    """Keys without a batch route keep per-envelope dispatch even when
    fetched in the same wave as batched keys."""
    wave = _wave_msgs("json.parsed", 2) + [
        {"id": 9, "rk": "source.deletion", "attempts": 0,
         "envelope": {"event_type": "SourceDeletionRequested",
                      "event_id": "d1", "data": {}}}]
    stub = StubWaveClient([wave])
    sub = broker_mod.BrokerSubscriber({"address": "tcp://stub"},
                                      client=stub)
    sub.metrics = InMemoryMetrics()
    singles, batches = [], []
    sub.subscribe(["json.parsed", "source.deletion"],
                  lambda env: singles.append(env))
    sub.subscribe_batch(["json.parsed"],
                        lambda envs: batches.append(list(envs)))
    assert sub.drain(3) == 3
    assert len(batches) == 1 and len(batches[0]) == 2
    assert len(singles) == 1
    assert singles[0]["event_type"] == "SourceDeletionRequested"


def test_validating_subscriber_batch_quarantines_invalid_per_envelope():
    """The validating wrapper's batch path must (a) exist explicitly —
    the base class's concrete `return False` default would otherwise
    shadow delegation and silently disable batching — and (b) validate
    per envelope: invalid ones become PoisonEnvelope outcomes without
    ever reaching the service wave."""
    captured = {}

    class FakeInner:
        def subscribe_batch(self, rks, cb):
            captured["cb"] = cb
            return True

    invalid_seen = []
    vsub = ValidatingSubscriber(FakeInner(),
                                on_invalid=lambda e, x:
                                invalid_seen.append(e))
    inner_waves = []

    def service_wave(envelopes):
        inner_waves.append(list(envelopes))
        return [None] * len(envelopes)

    assert vsub.subscribe_batch(["archive.ingested"],
                                service_wave) is True
    good = ArchiveIngested(archive_id="a1").to_envelope()
    bad = {"event_type": "ArchiveIngested", "nope": 1}
    outcomes = captured["cb"]([bad, good, dict(bad)])
    assert isinstance(outcomes[0], PoisonEnvelope)
    assert outcomes[1] is None
    assert isinstance(outcomes[2], PoisonEnvelope)
    assert len(inner_waves) == 1 and len(inner_waves[0]) == 1
    assert vsub.invalid_count == 2 and len(invalid_seen) == 2


@pytest.mark.slow
def test_competing_subscribers_on_durable_broker_split_work(tmp_path):
    """Two real subscribers in one group over the live broker: every
    message dispatched exactly once across the pool, nothing
    double-dispatched, nothing lost — the StageWorkerPool's delivery
    contract."""
    if not broker_mod.HAS_ZMQ:
        pytest.skip("pyzmq missing")
    b = broker_mod.Broker(port=0,
                          db_path=str(tmp_path / "q.sqlite3")).start()
    try:
        pub = broker_mod.BrokerPublisher({"address": b.address})
        seen: dict[str, list[str]] = {"a": [], "b": []}
        subs = {}
        for name in ("a", "b"):
            sub = broker_mod.BrokerSubscriber(
                {"address": b.address, "prefetch": 4}, group="svc")
            sub.subscribe(
                ["archive.ingested"],
                lambda env, n=name: seen[n].append(
                    env["data"]["archive_id"]))
            subs[name] = sub
        for i in range(40):
            pub.publish(ArchiveIngested(archive_id=f"m{i}"))
        threads = [threading.Thread(target=s.start_consuming)
                   for s in subs.values()]
        for t in threads:
            t.start()
        assert await_cond(
            lambda: len(seen["a"]) + len(seen["b"]) >= 40, timeout=20)
        time.sleep(0.3)          # would-be double dispatches land now
        for s in subs.values():
            s.stop()
        for t in threads:
            t.join(timeout=5)
        got = seen["a"] + seen["b"]
        assert sorted(got) == sorted({f"m{i}" for i in range(40)})
        assert len(got) == 40                      # exactly once
        counts = subs["a"].counts(timeout_ms=2000)
        assert counts.get("archive.ingested", {}).get("pending", 0) == 0
        for s in subs.values():
            s.close()
        pub.close()
    finally:
        b.stop()


def test_publish_window_groups_wave_publishes_into_one_request():
    """Grouped publishes: N publish() calls inside a window reach the
    broker as ONE pub_batch request, in order; depths piggyback."""
    stub = StubClient()
    pub = make_publisher(stub)
    with pub.publish_window():
        for i in range(5):
            pub.publish(ArchiveIngested(archive_id=f"a{i}"))
        # nested window joins the outer one (no premature flush)
        with pub.publish_window():
            pub.publish(ArchiveIngested(archive_id="a5"))
    batches = [r for r in stub.requests if r["op"] == "pub_batch"]
    singles = [r for r in stub.requests if r["op"] == "pub"]
    assert len(batches) == 1 and not singles
    ids = [it["envelope"]["data"]["archive_id"]
           for it in batches[0]["items"]]
    assert ids == [f"a{i}" for i in range(6)]
    assert pub.outbox_stats()["confirmed"] == 6
    # outside the window, publishes go back to per-event confirms
    pub.publish(ArchiveIngested(archive_id="solo"))
    assert [r["op"] for r in stub.requests][-1] == "pub"
    pub.close()


def test_publish_window_outage_parks_whole_window_in_order():
    stub = StubClient()
    pub = make_publisher(stub)
    stub.down = True
    with pub.publish_window():
        for i in range(3):
            pub.publish(ArchiveIngested(archive_id=f"a{i}"))
    assert pub.outbox.depth() == 3
    stub.down = False
    assert await_cond(lambda: pub.outbox.depth() == 0)
    # replayed oldest-first as singles: order preserved
    ids = [env["data"]["archive_id"] for _rk, env in stub.published()]
    assert ids == ["a0", "a1", "a2"]
    assert pub.outbox_stats()["replayed"] == 3
    pub.close()


def test_queuestore_enqueue_many_one_transaction_depths():
    store = broker_mod._QueueStore(":memory:")
    store.bind(["k1"], "g")
    store.bind(["k2"], "g")
    depths = store.enqueue_many([("k1", "{}"), ("k2", "{}"),
                                 ("k1", "{}")])
    assert depths == {"k1": 2, "k2": 1}
    counts = store.counts()
    assert counts["k1"]["pending"] == 2
    assert counts["k2"]["pending"] == 1
    store.close()
