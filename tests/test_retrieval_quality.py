# Retrieval quality: BERT-family encoder checkpoint import (golden
# embedding parity vs transformers), WordPiece tokenizer serving, and the
# recall@k eval proving a contrastively-tuned encoder beats the
# hashed-BoW baseline — the measurement VERDICT r1 found missing (the
# reference's quality rests on sentence-transformers weights,
# sentence_transformer_provider.py:19-51, and is never evaluated).
#
# Only the parity tests need torch/transformers (as the oracle); the
# recall@k quality gate and eval-script tests are pure JAX and run in
# a torch-free install — hence per-test `torch_oracle` skips, not a
# module-level importorskip.
import json
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")


from copilot_for_consensus_tpu import checkpoint
from copilot_for_consensus_tpu.embedding.eval import (
    recall_at_k,
    synthetic_fixture,
    train_encoder_on_fixture,
)
from copilot_for_consensus_tpu.engine.embedding import EmbeddingEngine
from copilot_for_consensus_tpu.engine.tokenizer import HashWordTokenizer
from copilot_for_consensus_tpu.models import encoder
from copilot_for_consensus_tpu.models.configs import EncoderConfig

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture()
def torch_oracle():
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")
    return transformers, torch


def _tiny_bert_dir(torch_oracle, tmp_path, with_tokenizer=True):
    transformers, torch = torch_oracle
    torch.manual_seed(0)
    cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2)
    model = transformers.BertModel(cfg).to(torch.float32).eval()
    out = tmp_path / "hf-bert"
    model.save_pretrained(out, safe_serialization=True)
    if with_tokenizer:
        _write_wordpiece_tokenizer(out)
    return out, model


def _write_wordpiece_tokenizer(out_dir):
    """A real (tiny) WordPiece tokenizer.json with the BERT post-processor
    so encode() emits [CLS] ... [SEP] like production MiniLM."""
    from tokenizers import Tokenizer, models, pre_tokenizers, processors

    vocab = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3}
    for w in ("the", "quick", "brown", "fox", "lazy", "dog", "##s", "a"):
        vocab[w] = len(vocab)
    tok = Tokenizer(models.WordPiece(vocab, unk_token="[UNK]"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    tok.post_processor = processors.TemplateProcessing(
        single="[CLS] $A [SEP]", pair="[CLS] $A [SEP] $B [SEP]",
        special_tokens=[("[CLS]", 2), ("[SEP]", 3)])
    tok.save(str(out_dir / "tokenizer.json"))


def _ref_mean_pooled(torch, model, tokens, lengths):
    """sentence-transformers-style masked mean pool + L2 norm over
    BertModel last_hidden_state."""
    with torch.no_grad():
        mask = (torch.arange(tokens.shape[1])[None, :]
                < torch.tensor(lengths)[:, None])
        out = model(torch.from_numpy(tokens).long(),
                    attention_mask=mask.long()).last_hidden_state
        pooled = (out * mask[..., None]).sum(1) / mask.sum(1)[:, None]
        return torch.nn.functional.normalize(pooled, dim=-1).numpy()


def test_encoder_config_mapping(torch_oracle, tmp_path):
    path, _ = _tiny_bert_dir(torch_oracle, tmp_path, with_tokenizer=False)
    cfg = checkpoint.encoder_config_from_hf(checkpoint.read_hf_config(path))
    assert cfg.d_model == 32 and cfg.n_layers == 2 and cfg.n_heads == 4
    assert cfg.vocab_size == 128 and cfg.max_positions == 64


def test_relative_position_bert_rejected():
    with pytest.raises(checkpoint.CheckpointError, match="position"):
        checkpoint.encoder_config_from_hf({
            "model_type": "bert", "vocab_size": 128, "hidden_size": 32,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "intermediate_size": 64,
            "position_embedding_type": "relative_key"})


def test_golden_embeddings_bert(torch_oracle, tmp_path):
    _, torch = torch_oracle
    path, model = _tiny_bert_dir(torch_oracle, tmp_path,
                                 with_tokenizer=False)
    cfg, params = checkpoint.load_hf_encoder_checkpoint(path,
                                                        dtype="float32")
    tokens = np.array([[2, 9, 17, 42, 3, 0, 0, 0],
                       [2, 100, 5, 3, 0, 0, 0, 0]], dtype=np.int32)
    lengths = [5, 4]
    ref = _ref_mean_pooled(torch, model, tokens, lengths)
    got = np.asarray(encoder.encode(
        jax.tree.map(jnp.asarray, params), jnp.asarray(tokens),
        jnp.asarray(lengths, dtype=jnp.int32), cfg, attn_impl="xla"))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=1e-3)


def test_engine_from_checkpoint_wordpiece(torch_oracle, tmp_path):
    _, torch = torch_oracle
    path, model = _tiny_bert_dir(torch_oracle, tmp_path)
    eng = EmbeddingEngine.from_checkpoint(str(path))
    assert eng.dimension == 32
    assert eng.tokenizer.pad_id == 0
    # WordPiece + post-processor: [CLS] the quick [SEP]
    assert eng.tokenizer.encode("the quick") == [2, 4, 5, 3]
    vecs = eng.embed_batch(["the quick brown fox", "a lazy dogs"])
    assert vecs.shape == (2, 32)
    np.testing.assert_allclose(np.linalg.norm(vecs, axis=1), 1.0,
                               atol=1e-5)
    # Parity through the full engine path (tokenize → pad → encode).
    ids = eng.tokenizer.encode("the quick brown fox")
    tokens = np.zeros((1, 32), dtype=np.int32)
    tokens[0, :len(ids)] = ids
    ref = _ref_mean_pooled(torch, model, tokens, [len(ids)])
    np.testing.assert_allclose(vecs[0], ref[0], atol=2e-4, rtol=1e-3)


def test_engine_from_checkpoint_requires_tokenizer(torch_oracle, tmp_path):
    path, _ = _tiny_bert_dir(torch_oracle, tmp_path, with_tokenizer=False)
    with pytest.raises(ValueError, match="tokenizer"):
        EmbeddingEngine.from_checkpoint(str(path))


def test_trained_encoder_beats_hash_baseline():
    """The VERDICT r1 'Done' bar: recall@10 of a real (trained) encoder
    ≫ the hashed-BoW baseline, measured through the production ANN path."""
    fixture = synthetic_fixture(n_topics=4, docs_per_topic=6,
                                queries_per_topic=3, seed=0)
    base_cfg = EncoderConfig(name="hash-baseline", vocab_size=1024,
                             d_model=32, n_layers=1, n_heads=4, d_ff=64,
                             max_positions=32)
    baseline = EmbeddingEngine(
        base_cfg, tokenizer=HashWordTokenizer(base_cfg.vocab_size),
        dtype=jnp.float32)
    base = recall_at_k(baseline.embed_batch, fixture, ks=(10,))

    cfg, params, tok, loss = train_encoder_on_fixture(
        fixture, steps=40, batch=12,
        cfg=EncoderConfig(name="tiny", vocab_size=1024, d_model=32,
                          n_layers=1, n_heads=4, d_ff=64,
                          max_positions=16))
    trained_eng = EmbeddingEngine(cfg, params, tokenizer=tok,
                                  dtype=jnp.float32)
    trained = recall_at_k(trained_eng.embed_batch, fixture, ks=(10,))
    # Doc/query vocabularies are disjoint per topic: hash overlap is
    # noise (~1/n_topics), a trained encoder should be near-perfect.
    assert trained["recall@10"] > base["recall@10"] + 0.3, (base, trained)
    assert trained["recall@10"] > 0.8, trained


def test_eval_script_shape():
    """scripts/eval_retrieval.py prints one valid JSON line per backend."""
    res = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "eval_retrieval.py"),
         "--backend", "hash", "--topics", "2", "--docs-per-topic", "3",
         "--queries-per-topic", "2"],
        capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stderr
    rec = json.loads(res.stdout.strip().splitlines()[-1])
    assert rec["backend"] == "hash" and "recall@10" in rec
