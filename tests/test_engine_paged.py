# Paged KV cache (ISSUE 14): the block-pool allocator's invariants
# (property tests over random alloc/free/pin/release sequences), the
# paged attention op's parity with the contiguous reference, and the
# engine-level greedy f32 CPU bit-identity gates — paged-on vs
# paged-off across the plain, prefix-cache (zero-copy pointer
# admission), spec-decode, chunked-prefill, chaos-replay, and
# journal-warm-restart paths — plus the capacity claim: a pool smaller
# than slots x max_len still serves every stream.
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from copilot_for_consensus_tpu.engine.kv_pool import (
    BLOCK_TABLE_DTYPE,
    BlockPool,
    KVPoolExhausted,
)
from copilot_for_consensus_tpu.engine.prefix_cache import PrefixCache
from copilot_for_consensus_tpu.models.configs import decoder_config

CFG = decoder_config("tiny")


def _params():
    from copilot_for_consensus_tpu.models import decoder

    return decoder.init_params(jax.random.PRNGKey(7), CFG,
                               dtype=jnp.float32)


def _engine(params, paged_blocks=0, **kw):
    from copilot_for_consensus_tpu.engine.generation import (
        GenerationEngine,
    )

    kw.setdefault("num_slots", 4)
    kw.setdefault("max_len", 256)
    kw.setdefault("prefill_buckets", (64, 128, 192))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("kv_dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("decode_window", 4)
    kw.setdefault("prefill_chunk", 64)
    return GenerationEngine(CFG, params, kv_pool_blocks=paged_blocks,
                            **kw)


# ---------------------------------------------------------------------------
# BlockPool allocator invariants (property tests)
# ---------------------------------------------------------------------------


def _pool(n=16, blk=4):
    return BlockPool(CFG, num_blocks=n, block_size=blk,
                     kv_dtype=jnp.float32)


def test_alloc_is_exclusive_and_free_returns():
    p = _pool(8)
    a = p.alloc(3)
    b = p.alloc(2)
    assert len(set(a) | set(b)) == 5          # never double-assigned
    assert p.free_blocks == 3
    p.free(a)
    assert p.free_blocks == 6
    c = p.alloc(6)
    assert len(set(c) | set(b)) == 8


def test_double_free_and_oob_free_raise():
    p = _pool(4)
    a = p.alloc(2)
    p.free(a)
    with pytest.raises(ValueError, match="double free"):
        p.free([a[0]])
    with pytest.raises(ValueError, match="out-of-range"):
        p.free([99])


def test_pinned_blocks_cannot_be_freed_and_pins_are_counted():
    p = _pool(4)
    a = p.alloc(1)
    p.pin(a)
    p.pin(a)
    assert p.pinned_blocks == 1
    assert p.pins(a[0]) == 2
    with pytest.raises(ValueError, match="pinned"):
        p.free(a)
    p.release(a)
    with pytest.raises(ValueError, match="pinned"):
        p.free(a)
    p.release(a)
    p.free(a)
    with pytest.raises(ValueError, match="underflow"):
        p.release(a)


def test_pin_of_free_block_raises():
    p = _pool(4)
    with pytest.raises(ValueError, match="pin of free"):
        p.pin([0])


def test_exhaustion_is_all_or_nothing_and_classified():
    from copilot_for_consensus_tpu.engine.supervisor import (
        is_resource_exhaustion,
    )

    p = _pool(4)
    p.alloc(3)
    with pytest.raises(KVPoolExhausted) as ei:
        p.alloc(2)
    assert p.free_blocks == 1                 # nothing partially taken
    assert is_resource_exhaustion(ei.value)


def test_random_sequences_never_leak_or_alias():
    """Property: under arbitrary interleavings of alloc/free/pin/
    release, every block is in exactly one place and the count books
    balance."""
    rng = np.random.default_rng(0)
    p = _pool(12)
    held: list[int] = []
    pinned: list[int] = []
    for _ in range(2000):
        op = rng.integers(0, 4)
        if op == 0 and p.free_blocks:
            n = int(rng.integers(1, p.free_blocks + 1))
            got = p.alloc(n)
            assert not (set(got) & set(held))
            held += got
        elif op == 1 and held:
            i = int(rng.integers(0, len(held)))
            bid = held[i]
            if bid not in pinned:
                held.pop(i)
                p.free([bid])
        elif op == 2 and held:
            bid = held[int(rng.integers(0, len(held)))]
            p.pin([bid])
            pinned.append(bid)
        elif op == 3 and pinned:
            i = int(rng.integers(0, len(pinned)))
            p.release([pinned.pop(i)])
        assert p.free_blocks + len(held) == p.num_blocks
        assert p.pinned_blocks == len(set(pinned))


def test_rebuild_free_list_reclaims_unowned_blocks():
    p = _pool(8)
    a = p.alloc(4)
    p.pin(a[:1])
    changed = p.rebuild_free_list(owned=set(a[:2]))
    assert sorted(changed) == sorted(a[2:])
    assert p.free_blocks == 6
    assert p.pins(a[0]) == 1                  # owned keeps its pin


# ---------------------------------------------------------------------------
# shared-pool PrefixCache: refcounted adopt handoff
# ---------------------------------------------------------------------------


def _shared_prefix(pool):
    return PrefixCache(CFG, num_blocks=1, block_size=pool.block,
                       shared=pool)


def test_adopt_blocks_hands_off_without_copy_and_pins():
    pool = _pool(8)
    pc = _shared_prefix(pool)
    tokens = list(range(10, 26))                       # 4 blocks of 4
    table = pool.alloc(4)
    adopted = pc.adopt_blocks(tokens, table, owned_from=0)
    assert adopted == set(table)
    assert pool.pinned_blocks == 4                     # trie pins
    # the adopted blocks are NOT freeable (pinned) — "refcounted
    # publish keeps pinned blocks out of the free list"
    with pytest.raises(ValueError, match="pinned"):
        pool.free(table)
    # dedup: a second slot retiring the same prefix adopts nothing
    table2 = pool.alloc(4)
    adopted2 = pc.adopt_blocks(tokens, table2, owned_from=0)
    assert adopted2 == set()
    pool.free(table2)                                  # caller frees
    # a match pins nodes; eviction cannot touch them
    m = pc.lookup(tokens + [1])
    assert m.tokens == 16
    assert pc.evictable_blocks == 0
    pc.release(m)
    assert pc.evictable_blocks == 4


def test_shared_eviction_returns_blocks_to_the_pool():
    pool = _pool(8)
    pc = _shared_prefix(pool)
    tokens = list(range(10, 26))
    pc.adopt_blocks(tokens, pool.alloc(4), owned_from=0)
    assert pool.free_blocks == 4
    got = pc.reclaim(2)
    assert got == 2
    assert pool.free_blocks == 6
    assert pc.node_count == 2
    # flush returns the rest
    pc.flush()
    assert pool.free_blocks == 8
    assert pool.pinned_blocks == 0


def test_adopt_blocks_is_transactional_on_corrupt_tables():
    """A corrupted table entry (a free block id where an owned one
    should be) must adopt NOTHING and pin nothing — the caller frees
    the slot's owned blocks right after, so a partial adoption would
    turn _retire's publish-failure containment into an uncontained
    free-of-pinned-block error."""
    pool = _pool(8)
    pc = _shared_prefix(pool)
    tokens = list(range(10, 26))                       # 4 blocks of 4
    table = pool.alloc(4)
    bad = list(table)
    bad[2] = pool.alloc(1)[0]
    pool.free([bad[2]])                                # free mid-table
    adopted = pc.adopt_blocks(tokens, bad, owned_from=0)
    assert adopted == set()
    assert pool.pinned_blocks == 0                     # nothing pinned
    assert pc.node_count == 0                          # nothing created
    assert pc.stats.publish_skips == 1
    pool.free(table)                                   # caller-safe


def test_shared_mode_guards_copy_publish_and_alloc():
    pool = _pool(8)
    pc = _shared_prefix(pool)
    with pytest.raises(RuntimeError, match="adopt_blocks"):
        pc.publish([1, 2, 3, 4], {"k": None, "v": None}, 0)
    owned = PrefixCache(CFG, num_blocks=4, block_size=4,
                        kv_dtype=jnp.float32)
    with pytest.raises(RuntimeError, match="publish"):
        owned.adopt_blocks([1, 2, 3, 4], [0], 0)


# ---------------------------------------------------------------------------
# paged attention op: reference parity
# ---------------------------------------------------------------------------


def test_paged_xla_route_is_bitwise_the_gathered_reference():
    from copilot_for_consensus_tpu.ops.attention import decode_attention
    from copilot_for_consensus_tpu.ops.paged_attention import (
        paged_decode_attention,
        paged_gather_layer,
    )

    rng = np.random.default_rng(0)
    b, hq, hkv, d, blk, nbtot, nb = 3, 8, 2, 16, 8, 10, 4
    q = jnp.asarray(rng.standard_normal((b, hq, d)), jnp.float32)
    pk = jnp.asarray(rng.standard_normal((nbtot, hkv, blk, d)),
                     jnp.float32)
    pv = jnp.asarray(rng.standard_normal((nbtot, hkv, blk, d)),
                     jnp.float32)
    tables = jnp.asarray(rng.integers(0, nbtot, (b, nb)),
                         BLOCK_TABLE_DTYPE)
    lengths = jnp.asarray([5, 0, 29], jnp.int32)
    for window in (0, 7):
        k, v = paged_gather_layer(pk, pv, tables)
        ref = decode_attention(q, k, v, lengths, window=window)
        got = paged_decode_attention(q, pk, pv, tables, lengths,
                                     window=window, impl="xla")
        assert bool(jnp.all(ref == got))
    # fully-masked row (length 0) emits exact zeros
    got = paged_decode_attention(q, pk, pv, tables, lengths,
                                 impl="xla")
    assert bool(jnp.all(got[1] == 0.0))


def test_paged_pallas_kernel_matches_reference_in_interpret_mode():
    """The TPU kernel route, run through the Pallas interpreter on
    CPU: GQA + sliding window + fp8 dequant parity against the
    bit-exact XLA reference (online-softmax reassociation keeps this
    approximate, not bitwise)."""
    from copilot_for_consensus_tpu.ops.attention import decode_attention
    from copilot_for_consensus_tpu.ops.paged_attention import (
        paged_decode_attention_pallas,
        paged_gather_layer,
    )

    rng = np.random.default_rng(1)
    b, hq, hkv, d, blk, nbtot, nb = 4, 8, 2, 16, 8, 12, 4
    q = jnp.asarray(rng.standard_normal((b, hq, d)), jnp.float32)
    pk = jnp.asarray(rng.standard_normal((nbtot, hkv, blk, d)),
                     jnp.float32)
    pv = jnp.asarray(rng.standard_normal((nbtot, hkv, blk, d)),
                     jnp.float32)
    tables = jnp.asarray(rng.integers(0, nbtot, (b, nb)),
                         BLOCK_TABLE_DTYPE)
    lengths = jnp.asarray([1, 9, 0, 31], jnp.int32)
    for kp, vp in ((pk, pv),
                   (pk.astype(jnp.float8_e4m3fn),
                    pv.astype(jnp.float8_e4m3fn))):
        for window in (0, 5):
            k, v = paged_gather_layer(kp, vp, tables)
            ref = decode_attention(q, k, v, lengths, window=window)
            got = paged_decode_attention_pallas(
                q, kp, vp, tables, lengths, window=window,
                interpret=True)
            np.testing.assert_allclose(np.asarray(ref),
                                       np.asarray(got), atol=1e-5)


# ---------------------------------------------------------------------------
# engine construction guards
# ---------------------------------------------------------------------------


def test_paged_constructor_guards():
    params = _params()
    with pytest.raises(ValueError, match="divide 128"):
        _engine(params, paged_blocks=16, prefill_chunk=48,
                max_len=192, prefill_buckets=(48,))
    with pytest.raises(ValueError, match="max_len"):
        _engine(params, paged_blocks=16, max_len=200,
                prefill_buckets=(64,))
    with pytest.raises(ValueError, match="cannot hold"):
        _engine(params, paged_blocks=3, max_len=256)


# ---------------------------------------------------------------------------
# engine e2e: greedy f32 CPU bit-identity, paged-on vs paged-off
# ---------------------------------------------------------------------------


def test_paged_plain_decode_bit_identical_and_books_balance():
    params = _params()
    plain = _engine(params)
    paged = _engine(params, paged_blocks=12)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(3, CFG.vocab_size, size=70).tolist()
               for _ in range(6)]
    want = plain.generate(prompts, max_new_tokens=10)
    got = paged.generate(prompts, max_new_tokens=10)
    for w, g in zip(want, got):
        assert w.tokens == g.tokens
        assert w.finish_reason == g.finish_reason
    st = paged.kv_pool_stats()
    assert st["free_blocks"] == st["num_blocks"]   # all blocks returned
    assert st["paged_admits"] == 6
    assert st["peak_active"] == 4                  # num_slots bound


def test_paged_prefix_cache_zero_copy_bit_identical():
    """The tentpole's hit path: admission appends the matched block
    ids (pinned) — no pool→slot gather, no publish copy — and greedy
    outputs stay bit-identical to the contiguous engine."""
    params = _params()
    plain = _engine(params)
    paged = _engine(params, paged_blocks=16, prefix_cache_blocks=8)
    rng = np.random.default_rng(1)
    shared = rng.integers(3, CFG.vocab_size, size=128).tolist()
    prompts = [shared + rng.integers(3, CFG.vocab_size,
                                     size=30).tolist()
               for _ in range(6)]
    for _round in range(2):
        want = plain.generate(prompts, max_new_tokens=6)
        got = paged.generate(prompts, max_new_tokens=6)
        for w, g in zip(want, got):
            assert w.tokens == g.tokens
    st = paged.kv_pool_stats()
    ps = paged.prefix_stats()
    assert st["zero_copy_admits"] > 0
    assert st["zero_copy_hit_rate"] > 0
    assert ps["prefill_tokens_saved"] >= 6 * 128   # second round all hits
    # the published prefix stays resident (pinned by the trie), the
    # rest of the pool drained back to the allocator
    assert st["pinned_blocks"] == 2                # 128 tokens / 64
    assert st["free_blocks"] == st["num_blocks"] - 2


def test_paged_capacity_exceeds_contiguous_equivalent_ceiling():
    """The capacity claim: a pool holding 8 blocks x 64 = 512 cache
    positions is the contiguous equivalent of TWO max_len=256 slots —
    yet the paged engine runs SIX short streams concurrently on it,
    because slots stop reserving max_len each."""
    params = _params()
    eng = _engine(params, paged_blocks=8, num_slots=6)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(3, CFG.vocab_size, size=20).tolist()
               for _ in range(6)]
    comps = eng.generate(prompts, max_new_tokens=6)
    assert len(comps) == 6
    st = eng.kv_pool_stats()
    contiguous_equiv_slots = (st["num_blocks"] * st["block_size"]
                              // eng.max_len)
    assert contiguous_equiv_slots == 2
    assert st["peak_active"] == 6 > contiguous_equiv_slots
    assert st["free_blocks"] == st["num_blocks"]


def test_paged_admission_blocks_on_pool_pressure_not_slots():
    """Free-BLOCK accounting: with worst-case footprints that cannot
    all fit, admission holds requests back (no KVPoolExhausted ever
    reaches the dispatch path) and serves them as blocks free."""
    params = _params()
    eng = _engine(params, paged_blocks=10, num_slots=4)
    rng = np.random.default_rng(3)
    # each request's worst case: 128 prompt + 100 new + margin ≈ 4
    # blocks; 10 blocks admit at most 2 at once
    prompts = [rng.integers(3, CFG.vocab_size, size=128).tolist()
               for _ in range(4)]
    rids = [eng.submit(list(p), 100) for p in prompts]
    eng.step()
    assert 0 < len(eng._active) <= 2
    results = {}
    for _ in range(400):
        for c in eng.step():
            results[c.request_id] = c
        if len(results) == len(rids):
            break
    assert len(results) == len(rids)
    st = eng.kv_pool_stats()
    assert st["free_blocks"] == st["num_blocks"]


def test_write_maps_drop_columns_past_max_len():
    """A verify dispatch's global width can overhang max_len for
    near-cap rows; those columns are dead padding (the contiguous
    merge drops them OOB) and must map to the OOB block id instead of
    indexing past the slot's table or allocating a block beyond the
    admission-time reservation."""
    params = _params()
    eng = _engine(params, paged_blocks=8, num_slots=2)   # max_len 256
    eng._tables[0] = eng._pool.alloc(4)                  # full table
    bids, offs = eng._write_maps([(0, eng._tables[0], 250, 9)], 9, 2)
    assert (bids[0, :6] != eng._pool.num_blocks).all()   # 250..255
    assert (bids[0, 6:] == eng._pool.num_blocks).all()   # >= max_len
    assert (bids[1] == eng._pool.num_blocks).all()       # no row: OOB
    eng._pool.free(eng._tables[0])
    eng._tables[0] = []


@pytest.mark.slow
def test_paged_spec_decode_bit_identical():
    params = _params()
    rng = np.random.default_rng(0)   # a seed whose drafts actually hit
    half = 60

    def copy_prompt():
        head = rng.integers(3, CFG.vocab_size, size=half).tolist()
        tail = []
        while len(tail) < half:
            s0 = int(rng.integers(0, max(1, half - 16)))
            tail.extend(head[s0:s0 + 16])
        return head + tail[:half]

    prompts = [copy_prompt() for _ in range(4)]
    plain = _engine(params, spec_decode=True)
    paged = _engine(params, paged_blocks=16, spec_decode=True)
    want = plain.generate(prompts, max_new_tokens=16)
    got = paged.generate(prompts, max_new_tokens=16)
    for w, g in zip(want, got):
        assert w.tokens == g.tokens
    assert paged.spec_stats()["verify_dispatches"] > 0
    st = paged.kv_pool_stats()
    assert st["free_blocks"] == st["num_blocks"]


@pytest.mark.slow
def test_paged_chunked_prefill_bit_identical():
    from copilot_for_consensus_tpu.engine.scheduler import (
        Scheduler,
        SchedulerConfig,
    )

    params = _params()
    rng = np.random.default_rng(5)
    prompts = [rng.integers(3, CFG.vocab_size, size=180).tolist()
               for _ in range(3)]
    plain = _engine(params,
                    scheduler=Scheduler(SchedulerConfig(
                        chunk_tokens=64)))
    paged = _engine(params, paged_blocks=16,
                    scheduler=Scheduler(SchedulerConfig(
                        chunk_tokens=64)))
    want = plain.generate(prompts, max_new_tokens=8)
    got = paged.generate(prompts, max_new_tokens=8)
    for w, g in zip(want, got):
        assert w.tokens == g.tokens
    assert paged.chunk_dispatches > 0
    st = paged.kv_pool_stats()
    assert st["free_blocks"] == st["num_blocks"]


@pytest.mark.slow
def test_paged_chaos_replay_bit_identical_and_pool_repaired():
    """PR-7 containment over the paged layout: injected dispatch
    faults evacuate slots (owned blocks freed), the runner replays,
    survivors are bit-identical, and the allocator's books balance
    after the storm."""
    from copilot_for_consensus_tpu.engine.async_runner import (
        AsyncEngineRunner,
    )
    from copilot_for_consensus_tpu.engine.faults import (
        FaultPlan,
        FaultSpec,
    )
    from copilot_for_consensus_tpu.engine.supervisor import (
        SupervisorConfig,
    )

    params = _params()
    rng = np.random.default_rng(6)
    prompts = [rng.integers(3, CFG.vocab_size, size=40).tolist()
               for _ in range(6)]
    base = _engine(params).generate(prompts, max_new_tokens=8)
    plan = FaultPlan(specs=[FaultSpec(kind="prefill", at=2, count=1),
                            FaultSpec(kind="decode", at=3, count=2)])
    eng = _engine(params, paged_blocks=16, faults=plan)
    runner = AsyncEngineRunner(
        eng, supervisor=SupervisorConfig(replay_budget=4)).start()
    try:
        handles = [runner.submit(list(p), 8) for p in prompts]
        outs = [h.result(timeout=120.0).tokens for h in handles]
        for w, g in zip(base, outs):
            assert w.tokens == g
        rec = runner.recovery_stats()
        assert rec["replayed"] >= 1
        assert rec["failed"] == 0
    finally:
        runner.stop()
    st = eng.kv_pool_stats()
    assert st["free_blocks"] + st["blocks_in_use"] == st["num_blocks"]
    assert st["free_blocks"] == st["num_blocks"]


def test_paged_journal_warm_restart_rebuilds_block_tables(tmp_path):
    """PR-12 journal replay over the paged layout: a process 'crash'
    mid-decode warm-restarts, continuations rebuild their block
    tables through normal admission, and the stitched outputs are
    bit-identical to the uninterrupted run."""
    params = _params()
    rng = np.random.default_rng(7)
    prompts = [rng.integers(3, CFG.vocab_size, size=40).tolist()
               for _ in range(4)]
    base = _engine(params).generate(prompts, max_new_tokens=24)
    jp = str(tmp_path / "journal.sqlite")
    e1 = _engine(params, paged_blocks=16, journal=jp)
    for p in prompts:
        e1.submit(list(p), 24)
    e1.step()                                  # admit + first window
    del e1                                     # SIGKILL stand-in
    e2 = _engine(params, paged_blocks=16, journal=jp)
    assert e2.journal_replayed == len(prompts)
    results = {}
    for _ in range(200):
        for c in e2.step():
            results[c.request_id] = c
        if len(results) == len(prompts):
            break
    got = [results[r].tokens for r in sorted(results)]
    for w, g in zip(base, got):
        assert w.tokens == g
    # every continuation's table was rebuilt and released at retire
    st = e2.kv_pool_stats()
    assert st["free_blocks"] == st["num_blocks"]
    assert all(not t for t in e2._tables)


# ---------------------------------------------------------------------------
# supervisor: block-table audit + containment
# ---------------------------------------------------------------------------


def test_audit_repairs_block_table_overlap_and_freelist_drift():
    from copilot_for_consensus_tpu.engine.supervisor import (
        EngineSupervisor,
    )

    params = _params()
    eng = _engine(params, paged_blocks=12, num_slots=4)
    rng = np.random.default_rng(8)
    prompts = [rng.integers(3, CFG.vocab_size, size=40).tolist()
               for _ in range(2)]
    for p in prompts:
        eng.submit(list(p), 32)
    eng.step()
    assert len(eng._active) == 2
    sup = EngineSupervisor(eng)
    assert sup.audit(repair=False) == {}        # healthy: no findings
    # corrupt: both slots claim the same owned block
    slots = sorted(eng._active)
    eng._tables[slots[1]][0] = eng._tables[slots[0]][0]
    findings = sup.audit(repair=True)
    assert set(findings["block_table_overlap"]) == set(slots)
    # both conflicted slots quarantined, allocator rebuilt: every
    # block accounted for exactly once
    assert set(sup.quarantined) == set(slots)
    assert eng._pool.free_blocks == eng._pool.num_blocks
    assert all(not t for t in eng._tables)


def test_contain_releases_paged_state_and_replays_clean():
    """contain() on a real failure: evacuate frees slot-owned blocks
    BEFORE the prefix flush frees the trie's — the pool ends fully
    free with zero pins."""
    from copilot_for_consensus_tpu.engine.supervisor import (
        EngineSupervisor,
    )

    params = _params()
    eng = _engine(params, paged_blocks=16, prefix_cache_blocks=8)
    rng = np.random.default_rng(9)
    shared = rng.integers(3, CFG.vocab_size, size=128).tolist()
    prompts = [shared + rng.integers(3, CFG.vocab_size,
                                     size=20).tolist()
               for _ in range(3)]
    eng.generate(prompts, max_new_tokens=4)    # publish the prefix
    for p in prompts:
        eng.submit(list(p), 32)
    eng.step()                                 # seeded actives (borrow)
    assert eng.kv_pool_stats()["pinned_blocks"] > 0
    sup = EngineSupervisor(eng)
    plan = sup.contain(RuntimeError("device fell over"))
    assert plan.evacuated
    st = eng.kv_pool_stats()
    assert st["free_blocks"] == st["num_blocks"]
    assert st["pinned_blocks"] == 0
    assert eng._prefix.node_count == 0


# ---------------------------------------------------------------------------
# scheduler: free-block accounting signal
# ---------------------------------------------------------------------------


def test_scheduler_sheds_on_kv_pool_pressure():
    from copilot_for_consensus_tpu.engine.scheduler import Scheduler

    s = Scheduler()
    sig = s.observe(queued=0, active=2, num_slots=4,
                    free_blocks=100, total_blocks=1000)
    assert s.overload_level == 0
    assert sig["kv_headroom_ratio"] == 0.1
    s.observe(queued=0, active=2, num_slots=4,
              free_blocks=50, total_blocks=1000)
    assert s.overload_level == 1               # under kv_low_ratio
    s.observe(queued=0, active=2, num_slots=4,
              free_blocks=10, total_blocks=1000)
    assert s.overload_level == 2               # under kv_critical_ratio
    s.observe(queued=0, active=2, num_slots=4)
    assert s.overload_level == 0               # non-paged engines: off
