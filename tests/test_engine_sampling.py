# Sampling invariants (engine/sampling.py): the filtered distribution
# every decode path draws from, plus exact speculative verification —
# greedy acceptance must reproduce the argmax chain bit for bit, and
# the rejection rule must leave the emitted distribution unchanged.
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from copilot_for_consensus_tpu.engine.sampling import (
    SamplingConfig,
    _filter_logits,
    sample,
    verify_draft,
)


def _logits(seed, b=4, v=32):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(b, v)).astype(np.float32))


# ---------------------------------------------------------------------------
# sample() properties
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("temp", [0.3, 0.7, 1.0, 2.5])
def test_top_k_one_matches_greedy_at_any_temperature(temp):
    for seed in range(5):
        lg = _logits(seed)
        greedy = sample(lg, jax.random.PRNGKey(0), SamplingConfig())
        got = sample(lg, jax.random.PRNGKey(seed),
                     SamplingConfig(temperature=temp, top_k=1))
        assert (np.asarray(got) == np.asarray(greedy)).all()


@pytest.mark.parametrize("top_p", [0.01, 0.1, 0.5, 0.9, 0.999])
def test_top_p_never_masks_the_argmax_token(top_p):
    for seed in range(5):
        lg = _logits(seed)
        f = _filter_logits(lg, SamplingConfig(temperature=1.0,
                                              top_p=top_p))
        kept = jnp.take_along_axis(f, jnp.argmax(lg, -1)[:, None], -1)
        assert bool(jnp.all(jnp.isfinite(kept))), (top_p, seed)


def test_top_k_beyond_vocab_degrades_to_plain_sampling():
    """top_k > vocab must behave as top_k disabled (keep everything),
    not mis-index the sorted logits."""
    lg = _logits(0, v=16)
    key = jax.random.PRNGKey(1)
    cfg_plain = SamplingConfig(temperature=0.8)
    cfg_huge = SamplingConfig(temperature=0.8, top_k=99)
    f = _filter_logits(lg, cfg_huge)
    assert bool(jnp.all(jnp.isfinite(f)))          # nothing masked
    want = sample(lg, key, cfg_plain)
    got = sample(lg, key, cfg_huge)
    assert (np.asarray(got) == np.asarray(want)).all()


def test_top_k_exactly_vocab_keeps_everything():
    lg = _logits(3, v=16)
    f = _filter_logits(lg, SamplingConfig(temperature=1.0, top_k=16))
    assert bool(jnp.all(jnp.isfinite(f)))


def test_sample_greedy_is_argmax():
    lg = _logits(2)
    got = sample(lg, jax.random.PRNGKey(0), SamplingConfig())
    assert (np.asarray(got) == np.asarray(jnp.argmax(lg, -1))).all()


# ---------------------------------------------------------------------------
# verify_draft: greedy acceptance
# ---------------------------------------------------------------------------


def test_verify_draft_greedy_accepts_matching_prefix():
    lg = jnp.concatenate([_logits(i, b=5, v=32)[None] for i in range(3)])
    # lg: [3, 5, 32]; argmax chain per row
    am = np.asarray(jnp.argmax(lg, -1))            # [3, 5]
    draft = np.zeros((3, 4), np.int32)
    draft[0] = am[0, :4]                           # full match
    draft[1] = am[1, :4]
    draft[1, 2] = (am[1, 2] + 1) % 32              # diverge at j=2
    draft[2] = am[2, :4]                           # match, but len 0
    lens = np.asarray([4, 4, 0], np.int32)
    out, acc = verify_draft(jnp.asarray(lg), jnp.asarray(draft),
                            jnp.asarray(lens), jax.random.PRNGKey(0),
                            SamplingConfig())
    out, acc = np.asarray(out), np.asarray(acc)
    assert (out == am).all()           # greedy emits the argmax chain
    assert list(acc) == [4, 2, 0]
    # emitted tokens = accepted draft + one correction/bonus token
    assert list(out[0, :5]) == list(am[0, :5])
    assert list(out[1, :3]) == list(am[1, :3])
    assert list(out[2, :1]) == list(am[2, :1])


def test_verify_draft_greedy_never_accepts_past_draft_len():
    lg = _logits(9, b=5, v=16)[None]               # [1, 5, 16]
    am = np.asarray(jnp.argmax(lg, -1))
    draft = am[:, :4].astype(np.int32)             # would all match
    out, acc = verify_draft(lg, jnp.asarray(draft),
                            jnp.asarray([2], np.int32),
                            jax.random.PRNGKey(0), SamplingConfig())
    assert int(acc[0]) == 2


# ---------------------------------------------------------------------------
# verify_draft: the rejection rule preserves the sampling distribution
# ---------------------------------------------------------------------------


def _empirical_first_token(lg, draft, lens, cfg, n=20000):
    keys = jax.random.split(jax.random.PRNGKey(42), n)
    fn = jax.jit(jax.vmap(
        lambda k: verify_draft(lg, draft, lens, k, cfg)))
    out, _ = fn(keys)
    first = np.asarray(out)[:, 0, 0]
    v = lg.shape[-1]
    return np.bincount(first, minlength=v) / n


@pytest.mark.parametrize("draft_tok", [0, 3])
def test_verify_draft_rejection_preserves_distribution(draft_tok):
    """The first emitted token's marginal must equal the serving
    distribution p regardless of what the draft proposed — the whole
    point of the rejection rule. draft_tok 3 is p's mode (high accept
    rate), 0 a tail token (high rejection rate): both must come out
    distribution-exact."""
    v = 8
    rng = np.random.default_rng(7)
    lg = jnp.asarray(rng.normal(size=(1, 3, v)).astype(np.float32))
    lg = lg.at[0, 0, 3].add(2.0)                   # make 3 the mode
    cfg = SamplingConfig(temperature=1.0)
    p = np.asarray(jax.nn.softmax(lg[0, 0] / cfg.temperature))
    draft = jnp.full((1, 2), draft_tok, dtype=jnp.int32)
    lens = jnp.asarray([2], dtype=jnp.int32)
    emp = _empirical_first_token(lg, draft, lens, cfg)
    assert np.abs(emp - p).max() < 0.02, (emp, p)


def test_verify_draft_accepts_sure_tokens():
    """A drafted token carrying ~all filtered probability mass is
    always accepted (p(d) = 1 → the rejection branch is dead)."""
    v = 8
    lg = jnp.full((1, 3, v), -30.0)
    lg = lg.at[0, :, 5].set(30.0)                  # token 5 is certain
    draft = jnp.full((1, 2), 5, dtype=jnp.int32)
    lens = jnp.asarray([2], dtype=jnp.int32)
    cfg = SamplingConfig(temperature=1.0)
    for seed in range(16):
        out, acc = verify_draft(lg, draft, lens,
                                jax.random.PRNGKey(seed), cfg)
        assert int(acc[0]) == 2
        assert np.asarray(out)[0, :3].tolist() == [5, 5, 5]


def test_verify_draft_zero_len_rows_emit_one_plain_sample():
    """A 0-draft row (the k=0 lane of a mixed verify wave) must emit a
    token from the plain serving distribution."""
    v = 8
    rng = np.random.default_rng(3)
    lg = jnp.asarray(rng.normal(size=(1, 3, v)).astype(np.float32))
    cfg = SamplingConfig(temperature=1.0)
    p = np.asarray(jax.nn.softmax(lg[0, 0] / cfg.temperature))
    draft = jnp.zeros((1, 2), dtype=jnp.int32)
    lens = jnp.zeros((1,), dtype=jnp.int32)
    keys = jax.random.split(jax.random.PRNGKey(11), 20000)
    out, acc = jax.jit(jax.vmap(
        lambda k: verify_draft(lg, draft, lens, k, cfg)))(keys)
    assert int(np.asarray(acc).max()) == 0
    first = np.asarray(out)[:, 0, 0]
    emp = np.bincount(first, minlength=v) / len(keys)
    assert np.abs(emp - p).max() < 0.02, (emp, p)
