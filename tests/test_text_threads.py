import pathlib

import pytest

from copilot_for_consensus_tpu.text.mbox import parse_mbox_file
from copilot_for_consensus_tpu.text.threads import (
    ThreadBuilder,
    normalize_subject,
)

FIXTURE = pathlib.Path(__file__).parent / "fixtures" / "ietf-sample.mbox"


@pytest.fixture(scope="module")
def threads():
    messages = [m for m, _ in parse_mbox_file(FIXTURE)]
    return ThreadBuilder().build_threads(messages), messages


def test_normalize_subject():
    assert normalize_subject("Re: Re: Foo bar") == "foo bar"
    assert normalize_subject("RE[2]: Foo") == "foo"
    assert normalize_subject("Fwd: Re:  Foo   bar ") == "foo bar"
    assert normalize_subject("AW: Antwort") == "antwort"


def test_three_threads_built(threads):
    built, _ = threads
    assert len(built) == 3


def test_reply_chain_groups_with_orphan(threads):
    built, messages = threads
    quic = [t for t in built.values()
            if "retransmission" in t.subject.lower()]
    assert len(quic) == 1
    t = quic[0]
    # root + 2 chained replies + 1 orphan (subject fallback) = 4
    assert len(t.message_indices) == 4
    assert t.root_message_id == "qr-root-1@example.org"
    assert t.participants == ["alice@example.org", "bob@example.net",
                              "carol@example.com", "dave@example.io"]
    assert t.first_date and t.first_date.startswith("2026-01-05")
    assert t.last_date and t.last_date.startswith("2026-01-06")


def test_subject_prefix_variants_group(threads):
    built, _ = threads
    h3 = [t for t in built.values() if "priority" in t.subject.lower()]
    assert len(h3) == 1
    assert len(h3[0].message_indices) == 2


def test_lone_message_thread(threads):
    built, _ = threads
    lone = [t for t in built.values() if "interim" in t.subject.lower()]
    assert len(lone) == 1
    assert len(lone[0].message_indices) == 1


def test_thread_ids_deterministic(threads):
    built, messages = threads
    rebuilt = ThreadBuilder().build_threads(messages)
    assert set(rebuilt) == set(built)


def test_cycle_guard():
    from copilot_for_consensus_tpu.text.mbox import ParsedMessage
    a = ParsedMessage(index=0, message_id="a@x", in_reply_to="b@x",
                      subject="loop")
    b = ParsedMessage(index=1, message_id="b@x", in_reply_to="a@x",
                      subject="Re: loop")
    built = ThreadBuilder().build_threads([a, b])
    assert sum(len(t.message_indices) for t in built.values()) == 2
