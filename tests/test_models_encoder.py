# Encoder: shapes, normalization, padding invariance, batching invariance.
import jax
import jax.numpy as jnp
import numpy as np

from copilot_for_consensus_tpu.models import encoder
from copilot_for_consensus_tpu.models.configs import encoder_config

import pytest
pytestmark = pytest.mark.slow   # JAX compiles / multi-process:
# excluded from the CI fast lane (pytest -m "not slow")

CFG = encoder_config("tiny")
PARAMS = encoder.init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


def test_encode_shape_and_normalized():
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                CFG.vocab_size)
    lengths = jnp.array([32, 20, 5, 1])
    out = encoder.encode(PARAMS, tokens, lengths, CFG, attn_impl="xla")
    assert out.shape == (4, CFG.d_model)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(out), axis=-1),
                               np.ones(4), rtol=1e-5)


def test_padding_does_not_change_embedding():
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 10), 0,
                                CFG.vocab_size)
    a = encoder.encode(PARAMS, tokens, jnp.array([10]), CFG, attn_impl="xla")
    padded = jnp.pad(tokens, ((0, 0), (0, 22)), constant_values=3)
    b = encoder.encode(PARAMS, padded, jnp.array([10]), CFG,
                       attn_impl="xla")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                               atol=1e-5)


def test_cross_text_batching_matches_single():
    # The whole point vs the reference's per-text embed() loop
    # (embedding/app/service.py:393): batched == sequential numerics.
    t1 = jax.random.randint(jax.random.PRNGKey(3), (1, 16), 0,
                            CFG.vocab_size)
    t2 = jax.random.randint(jax.random.PRNGKey(4), (1, 16), 0,
                            CFG.vocab_size)
    batched = encoder.encode(PARAMS, jnp.concatenate([t1, t2]),
                             jnp.array([16, 16]), CFG, attn_impl="xla")
    s1 = encoder.encode(PARAMS, t1, jnp.array([16]), CFG, attn_impl="xla")
    s2 = encoder.encode(PARAMS, t2, jnp.array([16]), CFG, attn_impl="xla")
    np.testing.assert_allclose(np.asarray(batched),
                               np.concatenate([s1, s2]), rtol=1e-4,
                               atol=1e-5)
