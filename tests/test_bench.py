# bench.py host-side plumbing: the backend probe must fail FAST within
# its wall-clock budget (r05 burned ~8.5 min of snapshot time proving a
# down tunnel four times over) and record per-attempt outcomes and
# durations for the artifact detail.
import time

import bench


def test_probe_budget_short_circuits_remaining_attempts(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "import time; time.sleep(60)")
    t0 = time.monotonic()
    ok, detail = bench.probe_backend(
        attempts=4, probe_timeout=30.0, waits=(0.0, 30.0, 30.0, 30.0),
        budget=4.0)
    elapsed = time.monotonic() - t0
    assert not ok
    assert elapsed < 20.0, elapsed          # not 4 x 30s + backoff
    assert "budget" in detail["summary"]
    assert detail["budget_s"] == 4.0
    outcomes = [a["outcome"] for a in detail["attempts"]]
    assert any("budget exhausted" in o for o in outcomes)
    assert all("duration_s" in a for a in detail["attempts"])


def test_probe_attempt_timeout_clamped_to_remaining_budget(monkeypatch):
    """With 3s of budget left, a 120s probe timeout must become a ~3s
    one — a single attempt can't blow the budget either."""
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "import time; time.sleep(60)")
    t0 = time.monotonic()
    ok, detail = bench.probe_backend(
        attempts=1, probe_timeout=120.0, waits=(0.0,), budget=3.0)
    assert not ok
    assert time.monotonic() - t0 < 15.0
    assert "timed out" in detail["attempts"][0]["outcome"]


def test_probe_failure_records_every_attempt(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "raise SystemExit('tunnel down')")
    ok, detail = bench.probe_backend(
        attempts=2, probe_timeout=30.0, waits=(0.0, 0.1), budget=60.0)
    assert not ok
    assert len(detail["attempts"]) == 2
    assert all(a["duration_s"] >= 0 for a in detail["attempts"])
    assert detail["summary"]                # last error surfaced


def test_probe_success_reports_ok_attempt(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "print('PROBE_OK fake cpu', flush=True)")
    ok, detail = bench.probe_backend(
        attempts=2, probe_timeout=30.0, budget=60.0)
    assert ok
    assert "PROBE_OK" in detail["summary"]
    assert detail["attempts"][-1]["outcome"] == "ok"


def test_spec_decode_preset_registered():
    assert "spec_decode" in bench.PRESETS
    assert bench.PRESETS["spec_decode"]["BENCH_SPEC_DECODE"] == "1"
    # the shardcheck preflight must trace the engine whose _verify
    # entrypoint the preset exercises
    assert "copilot_for_consensus_tpu.engine.generation" in \
        bench.PRESET_CONTRACT_MODULES["spec_decode"]
