# bench.py host-side plumbing: the backend probe must fail FAST within
# its wall-clock budget (r05 burned ~8.5 min of snapshot time proving a
# down tunnel four times over) and record per-attempt outcomes and
# durations for the artifact detail.
import time

import bench


def test_probe_budget_short_circuits_remaining_attempts(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "import time; time.sleep(60)")
    t0 = time.monotonic()
    ok, detail = bench.probe_backend(
        attempts=4, probe_timeout=30.0, waits=(0.0, 30.0, 30.0, 30.0),
        budget=4.0)
    elapsed = time.monotonic() - t0
    assert not ok
    assert elapsed < 20.0, elapsed          # not 4 x 30s + backoff
    assert "budget" in detail["summary"]
    assert detail["budget_s"] == 4.0
    outcomes = [a["outcome"] for a in detail["attempts"]]
    assert any("budget exhausted" in o for o in outcomes)
    assert all("duration_s" in a for a in detail["attempts"])


def test_probe_attempt_timeout_clamped_to_remaining_budget(monkeypatch):
    """With 3s of budget left, a 120s probe timeout must become a ~3s
    one — a single attempt can't blow the budget either."""
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "import time; time.sleep(60)")
    t0 = time.monotonic()
    ok, detail = bench.probe_backend(
        attempts=1, probe_timeout=120.0, waits=(0.0,), budget=3.0)
    assert not ok
    assert time.monotonic() - t0 < 15.0
    assert "timed out" in detail["attempts"][0]["outcome"]


def test_probe_failure_records_every_attempt(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "raise SystemExit('tunnel down')")
    ok, detail = bench.probe_backend(
        attempts=2, probe_timeout=30.0, waits=(0.0, 0.1), budget=60.0)
    assert not ok
    assert len(detail["attempts"]) == 2
    assert all(a["duration_s"] >= 0 for a in detail["attempts"])
    assert detail["summary"]                # last error surfaced


def test_probe_success_reports_ok_attempt(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_SRC",
                        "print('PROBE_OK fake cpu', flush=True)")
    ok, detail = bench.probe_backend(
        attempts=2, probe_timeout=30.0, budget=60.0)
    assert ok
    assert "PROBE_OK" in detail["summary"]
    assert detail["attempts"][-1]["outcome"] == "ok"


def test_spec_decode_preset_registered():
    assert "spec_decode" in bench.PRESETS
    assert bench.PRESETS["spec_decode"]["BENCH_SPEC_DECODE"] == "1"
    # the shardcheck preflight must trace the engine whose _verify
    # entrypoint the preset exercises
    assert "copilot_for_consensus_tpu.engine.generation" in \
        bench.PRESET_CONTRACT_MODULES["spec_decode"]


def test_decode_heavy_preset_registered():
    """The telemetry-overhead gate's preset: decode-dominated shape,
    contract-traced like every other preset."""
    assert "decode_heavy" in bench.PRESETS
    p = bench.PRESETS["decode_heavy"]
    # decode-dominated: generated tokens dominate prompt tokens
    assert int(p["BENCH_NEW_TOKENS"]) >= 4 * int(p["BENCH_PROMPT_LEN"])
    assert "copilot_for_consensus_tpu.engine.generation" in \
        bench.PRESET_CONTRACT_MODULES["decode_heavy"]


def test_preset_artifact_columns_unchanged():
    """The artifact column sets are a cross-round contract: the
    telemetry tentpole must not rename the columns earlier rounds'
    presets established, and its own columns are now part of it."""
    ps0 = {"lookups": 0, "hits": 0, "prefill_tokens": 0,
           "prefill_tokens_saved": 0}
    ps1 = {"lookups": 10, "hits": 9, "prefill_tokens": 1280,
           "prefill_tokens_saved": 3840}
    cols = bench.prefix_columns(ps0, ps1)
    assert set(cols) == {"prefix_hit_rate", "prefill_tokens_saved",
                         "prefill_tokens"}
    assert cols["prefix_hit_rate"] == 0.9
    assert cols["prefill_tokens_saved"] == 3840

    ss0 = {"lookups": 0, "hits": 0, "accepted_tokens": 0,
           "verify_rows": 0, "weight_row_tokens": 0,
           "weight_row_passes": 0}
    ss1 = {"lookups": 8, "hits": 4, "accepted_tokens": 12,
           "verify_rows": 4, "weight_row_tokens": 40,
           "weight_row_passes": 10}
    cols = bench.spec_columns(ss0, ss1)
    assert set(cols) == {"draft_hit_rate", "mean_accepted_per_step",
                         "tokens_per_weight_pass"}
    assert cols["draft_hit_rate"] == 0.5
    assert cols["tokens_per_weight_pass"] == 4.0
    # zero-delta denominators must not divide by zero
    assert bench.prefix_columns(ps0, ps0)["prefix_hit_rate"] == 0.0
    assert bench.spec_columns(ss0, ss0)["tokens_per_weight_pass"] == 0.0


def test_paged_capacity_preset_registered():
    """ISSUE 14: the paged-KV capacity gate — paged engine ON, pool
    sized at the contiguous 128-slot HBM budget (1024 x 64-token
    blocks == 128 slots x max_len 512), slot count ABOVE the 128
    ceiling, and a shared prefix so the zero-copy hit path exercises.
    The shardcheck preflight must trace the paged dispatch family."""
    assert "paged_capacity" in bench.PRESETS
    p = bench.PRESETS["paged_capacity"]
    assert p["BENCH_PAGED"] == "1"
    assert int(p["BENCH_SLOTS"]) > 128
    assert int(p["BENCH_KV_POOL_BLOCKS"]) * 64 \
        == 128 * int(p["BENCH_MAX_LEN"])
    assert int(p["BENCH_SHARED_PREFIX"]) > 0
    assert int(p["BENCH_PREFIX_BLOCKS"]) > 0
    assert "copilot_for_consensus_tpu.engine.generation" in \
        bench.PRESET_CONTRACT_MODULES["paged_capacity"]


def test_paged_columns_contract():
    """paged_capacity's artifact columns are a cross-round contract:
    max_concurrent_streams / kv_pool_fragmentation /
    zero_copy_hit_rate (timed-run delta, zero-delta safe)."""
    kv0 = {"paged_admits": 4, "zero_copy_admits": 0,
           "peak_active": 3, "fragmentation_ratio": 0.5}
    kv1 = {"paged_admits": 14, "zero_copy_admits": 8,
           "peak_active": 170, "fragmentation_ratio": 0.12}
    cols = bench.paged_columns(kv0, kv1)
    assert set(cols) == {"max_concurrent_streams",
                         "kv_pool_fragmentation", "zero_copy_hit_rate"}
    assert cols["max_concurrent_streams"] == 170
    assert cols["kv_pool_fragmentation"] == 0.12
    assert cols["zero_copy_hit_rate"] == 0.8
    assert bench.paged_columns(kv0, kv0)["zero_copy_hit_rate"] == 0.0


def test_mixed_traffic_preset_registered():
    """The scheduler gate's preset (ISSUE 6): adversarial mix with at
    least two tenants, contract-traced through BOTH the generation
    engine and the scheduler module (the chunked-prefill dispatch)."""
    assert "mixed_traffic" in bench.PRESETS
    p = bench.PRESETS["mixed_traffic"]
    assert int(p["BENCH_MIX_CHAT"]) > 0 and int(p["BENCH_MIX_LONG"]) > 0
    # adversarial: the long prompts must actually be long enough to
    # need chunking at the preset's chunk size
    assert int(p["BENCH_MIX_LONG_LEN"]) > int(p["BENCH_CHUNK_TOKENS"])
    mods = bench.PRESET_CONTRACT_MODULES["mixed_traffic"]
    assert "copilot_for_consensus_tpu.engine.generation" in mods
    assert "copilot_for_consensus_tpu.engine.scheduler" in mods


def test_sched_columns_contract():
    """The mixed_traffic artifact columns are a cross-round contract:
    ttft_p99_s / itl_p95_s / shed_rate / fairness_jain_index."""
    summary = {"ttft_p99_s": 1.25, "itl_p95_s": 0.08,
               "ttft_p50_s": 0.2}
    stats = {"shed_rate": 0.125, "fairness_jain_index": 0.96,
             "chunk_dispatches": 7}
    cols = bench.sched_columns(summary, stats)
    assert set(cols) == {"ttft_p99_s", "itl_p95_s", "shed_rate",
                         "fairness_jain_index"}
    assert cols["ttft_p99_s"] == 1.25
    assert cols["shed_rate"] == 0.125
    assert cols["fairness_jain_index"] == 0.96
    # empty stats degrade to the no-scheduler defaults, not KeyErrors
    empty = bench.sched_columns({}, {})
    assert empty["shed_rate"] == 0.0
    assert empty["fairness_jain_index"] == 1.0


def test_chaos_preset_registered():
    """The resilience gate's preset (ISSUE 7): spec decode ON (the
    persistent verify fault needs a verify dispatch to hit), compute
    dtype pinned to float32 (the replay bit-identity requirement:
    prefill and decode logits only agree exactly at f32), a hang
    longer than the watchdog deadline, contract-traced through the
    generation engine."""
    assert "chaos" in bench.PRESETS
    p = bench.PRESETS["chaos"]
    assert p["BENCH_SPEC_DECODE"] == "1"
    assert p["BENCH_CHAOS_DTYPE"] == "float32"
    assert float(p["BENCH_CHAOS_HANG_S"]) > \
        float(p["BENCH_CHAOS_DECODE_DEADLINE_S"])
    assert int(p["BENCH_CHAOS_CHAT"]) > 0 and \
        int(p["BENCH_CHAOS_LONG"]) > 0
    assert "copilot_for_consensus_tpu.engine.generation" in \
        bench.PRESET_CONTRACT_MODULES["chaos"]


def test_chaos_columns_contract():
    """The chaos artifact columns are a cross-round contract:
    recovered / replayed / failed / breaker_trips / watchdog_trips
    (plus the chaos_ok verdict assembled in chaos_headline)."""
    rec = {"recovered": 5, "replayed": 7, "failed": 1,
           "breaker_trips": 2, "watchdog_trips": 1,
           "containments": 9, "suspect_failures": 3}
    cols = bench.chaos_columns(rec)
    assert set(cols) == {"recovered", "replayed", "failed",
                         "breaker_trips", "watchdog_trips"}
    assert cols["recovered"] == 5 and cols["failed"] == 1
    # empty stats degrade to zeros, not KeyErrors
    empty = bench.chaos_columns({})
    assert empty == {"recovered": 0, "replayed": 0, "failed": 0,
                     "breaker_trips": 0, "watchdog_trips": 0}


def test_pipeline_chaos_preset_registered():
    """The pipeline fault gate's preset (ISSUE 8): a host-only storm —
    no jitted entrypoints for the shardcheck preflight to trace — with
    a watermark strictly inside the scaled warn SLO (pacing must hold
    depth UNDER the SLO with headroom, not ride its edge), poison
    envelopes to quarantine, and an overload drag so the OFF arm
    reproduces the SCALE_BROKER flood deterministically."""
    assert "pipeline_chaos" in bench.PRESETS
    p = bench.PRESETS["pipeline_chaos"]
    assert int(p["BENCH_PIPE_MESSAGES"]) > 0
    assert int(p["BENCH_PIPE_POISON"]) > 0
    assert float(p["BENCH_PIPE_DRAG_S"]) > 0
    slo = int(p["BENCH_PIPE_WARN_SLO"])
    assert 0 < slo // 2 < slo          # the watermark the harness uses
    # host-only: the preflight must SKIP, not trace the default engine
    # set a pipeline storm never dispatches to
    assert bench.PRESET_CONTRACT_MODULES["pipeline_chaos"] == []


def test_pipeline_chaos_columns_contract():
    """The pipeline_chaos artifact columns are a cross-round contract:
    lost / duplicated / quarantined / replayed_publishes plus the
    redelivery, sweep-recovery and two-arm depth evidence (the
    pipeline_chaos_ok verdict is assembled in
    pipeline_chaos_headline)."""
    audit = {"lost": 0, "duplicated": 0, "quarantined": 5,
             "replayed_publishes": 104, "redelivered": 3,
             "recovered_by_sweep": 2, "max_depth_backpressure_on": 8,
             "max_depth_backpressure_off": 88, "final_depth_max": 0,
             "stage_p95_s": {"chunking": 0.4},
             "queue_wait_p95_s": {"chunking": 1.2},
             "bottleneck_stage": "chunking", "orphan_spans": 0,
             "journal_replayed": 7, "shutdown_redeliveries": 0,
             "telemetry_recovered_ok": True, "spool_rows": 30,
             "spool_lost": 0, "extra_key_ignored": 1}
    cols = bench.pipeline_chaos_columns(audit)
    assert set(cols) == {"lost", "duplicated", "quarantined",
                         "replayed_publishes", "redelivered",
                         "recovered_by_sweep",
                         "max_depth_backpressure_on",
                         "max_depth_backpressure_off",
                         "final_depth_max",
                         # distributed-tracing columns (obs/trace.py +
                         # tools/tracepath.py, PR-10 tentpole)
                         "stage_p95_s", "queue_wait_p95_s",
                         "bottleneck_stage", "orphan_spans",
                         # process-lifecycle columns (engine/journal
                         # + services/lifecycle, ISSUE 12): the kill
                         # phase's warm-restart replays and the
                         # graceful-drain arm's shutdown-caused
                         # redeliveries (zero is the gate)
                         "journal_replayed", "shutdown_redeliveries",
                         # cross-process telemetry columns (obs/ship,
                         # ISSUE 20): the SIGKILLed child's committed
                         # spool survived and merged with zero orphans
                         "telemetry_recovered_ok", "spool_rows",
                         "spool_lost"}
    assert cols["quarantined"] == 5
    assert cols["replayed_publishes"] == 104
    assert cols["max_depth_backpressure_off"] == 88
    assert cols["bottleneck_stage"] == "chunking"
    assert cols["stage_p95_s"] == {"chunking": 0.4}
    assert cols["orphan_spans"] == 0
    assert cols["journal_replayed"] == 7
    assert cols["shutdown_redeliveries"] == 0
    assert cols["telemetry_recovered_ok"] is True
    assert cols["spool_rows"] == 30 and cols["spool_lost"] == 0
    # empty audit degrades to zeros/empties, not KeyErrors — and the
    # telemetry verdict degrades to False / -1 lost (unknown), never a
    # vacuous pass
    empty = bench.pipeline_chaos_columns({})
    assert empty["bottleneck_stage"] == ""
    assert empty["stage_p95_s"] == {}
    assert empty["queue_wait_p95_s"] == {}
    assert empty["telemetry_recovered_ok"] is False
    assert empty["spool_lost"] == -1
    assert all(v == 0 for k, v in empty.items()
               if k not in ("bottleneck_stage", "stage_p95_s",
                            "queue_wait_p95_s", "spool_lost"))


def test_telemetry_columns_contract():
    """Flight-recorder columns come from the engine's own telemetry;
    a telemetry-disabled engine (BENCH_TELEMETRY=0 overhead arm)
    yields NO columns rather than zeros that would look like a
    regression."""
    from copilot_for_consensus_tpu.engine.telemetry import (
        EngineTelemetry,
    )

    class FakeEngine:
        telemetry = EngineTelemetry(engine="generation", num_slots=4)

    tele = FakeEngine.telemetry
    for rid in range(3):
        tele.on_submit(rid, prompt_len=8)
        tele.on_admit(rid, wave_start=0.0)
    tele.record_step("decode", 0.01, rows=3, batch=4, tokens=12,
                     padded_tokens=32)
    for rid in range(3):
        tele.on_retire(rid, new_tokens=4, finish_reason="length")
    cols = bench.telemetry_columns(FakeEngine(), last_n=3)
    assert set(cols) == {"ttft_p50_s", "ttft_p95_s", "ttft_p99_s",
                         "itl_mean_s", "itl_p95_s", "mean_occupancy"}
    assert cols["ttft_p50_s"] > 0
    assert cols["mean_occupancy"] == 0.75

    class Disabled:
        telemetry = None

    assert bench.telemetry_columns(Disabled()) == {}


def test_pipeline_chaos_preset_enables_worker_pools():
    """ISSUE 11: the chaos gate must prove its delivery contracts
    UNDER stage scale-out — competing consumer pools on the host-bound
    stages, not the old one-consumer-per-service wiring."""
    assert int(bench.PRESETS["pipeline_chaos"]["BENCH_PIPE_WORKERS"]) >= 2


def test_pipeline_chaos_preset_has_kill_and_drain_knobs():
    """ISSUE 12: the chaos gate grew a process-kill phase (journaled
    engine storm SIGKILLed in a child process, warm-restarted from the
    journal) and a graceful-drain arm — both must stay in the preset."""
    p = bench.PRESETS["pipeline_chaos"]
    assert int(p["BENCH_KILL_REQUESTS"]) > 0
    assert int(p["BENCH_KILL_STEP"]) > 0
    assert int(p["BENCH_KILL_NEW_TOKENS"]) > 0
    assert int(p["BENCH_PIPE_DRAIN_MESSAGES"]) > 0
    assert int(p["BENCH_PIPE_DRAIN_ARCHIVES"]) > 0


def _scale_bench():
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(bench.__file__).parent
                           / "scripts"))
    import scale_bench
    return scale_bench


def test_scale_bench_workers_spec_parsing():
    sb = _scale_bench()
    assert sb.parse_workers_spec("") == {}
    assert sb.parse_workers_spec("1") == {}      # 1 = pre-scale-out
    assert sb.parse_workers_spec("4") == {
        "parsing": 4, "chunking": 4, "embedding": 4}
    assert sb.parse_workers_spec("parsing=2,chunking=6") == {
        "parsing": 2, "chunking": 6}
    # prefetch rides the services config next to the pools
    cfg = sb.services_config({"chunking": 3}, prefetch=32)
    assert cfg["chunking"] == {"workers": 3, "prefetch": 32}
    assert cfg["parsing"]["prefetch"] == 32


def test_scale_bench_artifact_columns_contract():
    """The SCALE_BROKER.json columns are a cross-round contract; the
    scale-out round adds speedup_vs_baseline (vs the 59.6 msg/s
    single-consumer baseline), per-stage worker counts and the
    prefetch knob, without renaming the established columns."""
    sb = _scale_bench()
    out = sb.broker_artifact(
        messages=100_000, gen_s=5.0, run_s=167.8, events=337_600,
        max_depth={"json.parsed": 900}, workers={"chunking": 6},
        prefetch=64, failure_audit={"events": 0}, stats={"reports": 1},
        ok=True)
    assert {"stage", "messages", "generate_s", "pipeline_s",
            "messages_per_s", "baseline_messages_per_s",
            "speedup_vs_baseline", "workers", "prefetch",
            "broker_events", "broker_events_per_s", "max_queue_depth",
            "queue_depth_slo", "failure_audit", "stats",
            "ok"} <= set(out)
    assert out["messages_per_s"] == 595.9
    assert out["speedup_vs_baseline"] == 10.0
    assert out["baseline_messages_per_s"] == 59.6
    # every scalable stage reports a worker count, configured or not
    assert out["workers"] == {"parsing": 1, "chunking": 6,
                              "embedding": 1}
    assert out["prefetch"] == 64
    assert out["queue_depth_slo"]["worst"] == 900
    # unconfigured knobs degrade to the pre-scale-out shape
    base = sb.broker_artifact(
        messages=10, gen_s=0.0, run_s=1.0, events=30, max_depth={},
        workers={}, prefetch=0, failure_audit={}, stats={}, ok=False)
    assert base["workers"] == {"parsing": 1, "chunking": 1,
                               "embedding": 1}
    assert base["prefetch"] == 16
    assert base["queue_depth_slo"]["worst"] == 0


import pytest


@pytest.mark.slow
def test_scale_bench_smoke_arm_runs_green():
    """The CI-runnable small-N arm: broker mode, pools + batching on,
    toy corpus — asserts the artifact contract end-to-end without
    touching SCALE_BROKER.json."""
    import json
    import pathlib
    import subprocess
    import sys

    pytest.importorskip("zmq")
    root = pathlib.Path(bench.__file__).parent
    out = subprocess.run(
        [sys.executable, str(root / "scripts" / "scale_bench.py"),
         "--smoke", "--messages", "240"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    artifact = json.loads(out.stdout.strip().splitlines()[-1])
    assert artifact["ok"] is True
    assert artifact["workers"]["chunking"] >= 2
    assert artifact["speedup_vs_baseline"] > 0
    assert artifact["stats"]["messages"] == 240


def test_multichip_serving_preset_registered():
    """ISSUE 15: the multi-chip sharded-paged serving gate — paged
    pool sized so every dp degree in the chip sweep gets equal shards
    with per-slot headroom, and the preflight traces the MESH-sharded
    dispatch family plus the serving mesh/rules contracts."""
    assert "multichip_serving" in bench.PRESETS
    p = bench.PRESETS["multichip_serving"]
    chips = [int(c) for c in p["BENCH_MC_CHIPS"].split(",")]
    assert chips[0] == 1 and chips[-1] == 8
    tp = int(p["BENCH_MC_TP"])
    blocks = int(p["BENCH_KV_POOL_BLOCKS"])
    slots = int(p["BENCH_SLOTS"])
    max_blocks = int(p["BENCH_MAX_LEN"]) // int(p["BENCH_PREFILL_CHUNK"])
    for c in chips:
        dp = c // tp if c > tp else 1
        assert blocks % dp == 0
        assert slots % dp == 0
        assert blocks // dp >= max_blocks + 1
    assert float(p["BENCH_MC_ITL_TOL"]) >= 1.0
    mods = bench.PRESET_CONTRACT_MODULES["multichip_serving"]
    assert "copilot_for_consensus_tpu.engine.generation" in mods
    assert "copilot_for_consensus_tpu.parallel.mesh" in mods
    assert "copilot_for_consensus_tpu.parallel.sharding" in mods


def test_multichip_columns_contract():
    """multichip_serving's artifact columns are a cross-round
    contract: chips / tok_s_per_chip / scaling_efficiency /
    ttft_p99_s / handoff_ms plus the two-arm ITL comparison."""
    scaling = {1: {"tok_s": 100.0, "ttft_p99_s": 0.01},
               2: {"tok_s": 180.0, "ttft_p99_s": 0.012},
               4: {"tok_s": 320.0, "ttft_p99_s": 0.015},
               8: {"tok_s": 560.0, "ttft_p99_s": 0.02}}
    disagg = {"itl_p95_coloc_s": 0.3, "itl_p95_disagg_s": 0.05,
              "handoff_ms": 12.5, "handoffs": 9}
    cols = bench.multichip_columns(scaling, disagg)
    assert cols["chips"] == 8
    assert cols["tok_s_per_chip"] == 70.0
    assert cols["scaling_efficiency"] == 0.7
    assert cols["ttft_p99_s"] == 0.02
    assert cols["handoff_ms"] == 12.5
    assert cols["itl_p95_disagg_s"] == 0.05
    assert set(cols["scaling"]) == {"1", "2", "4", "8"}
    # no spool merge: the spool columns degrade to unknown, never to a
    # vacuous pass
    assert cols["slo_ok"] is None
    assert cols["spool_rows"] == 0 and cols["spool_lost"] == -1
    assert all(row["ttft_p99_spool_s"] is None
               for row in cols["scaling"].values())
    # degenerate single-chip sweep stays well-formed
    one = bench.multichip_columns({1: {"tok_s": 0.0}}, {})
    assert one["scaling_efficiency"] == 0.0


def test_multichip_columns_spool_merge():
    """ISSUE 20: the parent merges every child's telemetry spool and
    publishes spool-derived TTFT per chip count, fleet ITL p95, row
    accounting and the declarative SLO verdict next to the measured
    columns."""
    scaling = {1: {"tok_s": 100.0, "ttft_p99_s": 0.01},
               2: {"tok_s": 180.0, "ttft_p99_s": 0.012}}
    spool = {"ttft_p99_by_chips": {"1": 0.011, "2": 0.013},
             "itl_p95_s": 0.04, "spool_rows": 21, "spool_lost": 0,
             "slo_ok": True,
             "slo": {"interactive-ttft-p99": True}}
    cols = bench.multichip_columns(scaling, {}, spool)
    assert cols["scaling"]["1"]["ttft_p99_spool_s"] == 0.011
    assert cols["scaling"]["2"]["ttft_p99_spool_s"] == 0.013
    assert cols["itl_p95_s"] == 0.04
    assert cols["spool_rows"] == 21 and cols["spool_lost"] == 0
    assert cols["slo_ok"] is True
    assert cols["slo"] == {"interactive-ttft-p99": True}


def test_kv_kernel_route_preset_keys():
    """ISSUE 16: the paged presets carry the dispatch-route knob —
    paged_capacity auto-selects its headline arm and pins a Pallas
    kernel-route arm next to it; multichip_serving auto-selects its
    scale children (the parent adds the pinned kernel child itself)."""
    p = bench.PRESETS["paged_capacity"]
    assert p["BENCH_KV_KERNEL"] == "auto"
    assert p["BENCH_KV_KERNEL_ARM"] == "1"
    assert bench.PRESETS["multichip_serving"]["BENCH_KV_KERNEL"] \
        == "auto"


def test_kernel_route_columns_contract():
    """The kernel-route arm's artifact columns are a cross-round
    contract: the RESOLVED route (kernel proves the Pallas path
    compiled), its tok/s, and the zero-safe ratio against the
    headline arm."""
    cols = bench.kernel_route_columns("kernel", 100.0, 117.0)
    assert set(cols) == {"kv_route", "kernel_tok_s",
                         "kernel_tok_s_delta"}
    assert cols["kv_route"] == "kernel"
    assert cols["kernel_tok_s"] == 117.0
    assert cols["kernel_tok_s_delta"] == 1.17
    # a failed headline arm must not divide by zero
    assert bench.kernel_route_columns("kernel", 0.0,
                                      50.0)["kernel_tok_s_delta"] == 0.0


def test_unknown_kv_kernel_fails_loudly():
    """ISSUE 16: a typo'd BENCH_KV_KERNEL must fail rc-2/ok:false the
    same way a typo'd BENCH_PRESET does — silently running (and
    mislabeling) the default route would poison the next round's
    artifact comparison. The check runs before the jax import, so the
    subprocess exits fast."""
    import json
    import os
    import subprocess
    import sys

    env = {**os.environ, "BENCH_KV_KERNEL": "pallass",
           "BENCH_PRESET": "", "BENCH_MC_CHILD": ""}
    out = subprocess.run(
        [sys.executable, bench.__file__],
        env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 2, out.stdout + out.stderr
    artifact = json.loads(out.stdout.strip().splitlines()[-1])
    assert artifact["ok"] is False
    assert "BENCH_KV_KERNEL" in artifact["reason"]
    assert "pallass" in artifact["reason"]


def test_ann_retrieval_preset_registered():
    """ISSUE 19: the ANN retrieval gate — million-vector default
    corpus, auto-sized index (nlist=0), a probe budget that keeps
    lists_scanned_frac well under the 0.15 ceiling, and preflights
    that trace + compile the vectorstore contract family (the fused
    search dispatch carries an hlo peak/collective budget)."""
    assert "ann_retrieval" in bench.PRESETS
    p = bench.PRESETS["ann_retrieval"]
    assert int(p["BENCH_ANN_N"]) == 1_000_000
    assert int(p["BENCH_ANN_TOPK"]) == 10
    assert int(p["BENCH_ANN_NLIST"]) == 0        # auto: ~sqrt(n)
    # at auto nlist for 1M (1024 lists), the preset's nprobe must sit
    # under the 15% scanned-lists ceiling the artifact gates on
    assert int(p["BENCH_ANN_NPROBE"]) / 1024 <= 0.15
    mods = bench.PRESET_CONTRACT_MODULES["ann_retrieval"]
    assert "copilot_for_consensus_tpu.vectorstore.tpu" in mods
    # the ivf search dispatch declares compiled-artifact budgets, so
    # the preset must run the hlocheck preflight, not just shardcheck
    assert "ann_retrieval" in bench.HLO_PREFLIGHT_PRESETS
    from copilot_for_consensus_tpu.analysis.contracts import (
        HLO_CONTRACT_MODULES,
    )
    assert "copilot_for_consensus_tpu.vectorstore.tpu" in (
        HLO_CONTRACT_MODULES)


def test_ann_columns_contract():
    """The ann_retrieval artifact columns are a cross-round contract:
    recall/QPS/latency per route plus the scanned-lists fraction, and
    the ann_ok gate = recall >= 0.95 AND frac <= 0.15 AND ivf faster."""
    flat = {"qps": 120.0, "p50_ms": 8.0, "p95_ms": 11.0}
    ivf = {"qps": 900.0, "p50_ms": 1.1, "p95_ms": 1.9,
           "lists_scanned_frac": 0.0156, "spill_fraction": 0.01,
           "nlist": 1024, "nprobe": 16}
    cols = bench.ann_columns(1_000_000, 0.973, flat, ivf)
    assert set(cols) >= {"corpus_size", "recall_at_10", "flat_qps",
                         "ivf_qps", "flat_query_p50_ms",
                         "flat_query_p95_ms", "ivf_query_p50_ms",
                         "ivf_query_p95_ms", "lists_scanned_frac",
                         "spill_fraction", "nlist", "nprobe", "ann_ok"}
    assert cols["recall_at_10"] == 0.973
    assert cols["ivf_qps"] == 900.0
    assert cols["lists_scanned_frac"] == 0.0156
    assert cols["ann_ok"] is True
    # each gate leg flips it independently
    assert not bench.ann_columns(10, 0.90, flat, ivf)["ann_ok"]
    assert not bench.ann_columns(
        10, 0.99, flat, {**ivf, "lists_scanned_frac": 0.5})["ann_ok"]
    assert not bench.ann_columns(
        10, 0.99, flat, {**ivf, "qps": 50.0})["ann_ok"]
    # degenerate empty dicts stay well-formed (failed arm)
    empty = bench.ann_columns(0, 0.0, {}, {})
    assert empty["ann_ok"] is False and empty["nlist"] == 0
