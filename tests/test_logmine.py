"""Log template mining (tools/logmine.py) — the Drain3 log-mining role
(reference ``scripts/log_mining/mining.py``)."""

from __future__ import annotations

import json

from copilot_for_consensus_tpu.tools.logmine import LogMiner, main


def _json_line(message: str, level: str = "info") -> str:
    return json.dumps({"ts": "2026-07-30T00:00:00+0000", "level": level,
                       "service": "parsing", "message": message})


def test_id_bearing_messages_collapse_to_one_template():
    miner = LogMiner()
    for i in range(50):
        miner.add_line(_json_line(f"processed archive {i:08x} with {i} messages"))
    clusters = miner.clusters
    assert len(clusters) == 1
    assert clusters[0].count == 50
    assert "<*>" in clusters[0].text
    assert clusters[0].text.startswith("processed archive")


def test_distinct_shapes_stay_separate():
    miner = LogMiner()
    for _ in range(5):
        miner.add_line(_json_line("subscriber connected to broker"))
        miner.add_line(_json_line("fetch failed after 3 attempts", "error"))
    texts = {c.text for c in miner.clusters}
    assert "subscriber connected to broker" in texts
    assert any(t.startswith("fetch failed") for t in texts)
    assert len(texts) == 2


def test_levels_counted_and_error_shortlist():
    miner = LogMiner()
    miner.add_line(_json_line("upsert ok for chunk 11"))
    for i in range(3):
        miner.add_line(_json_line(f"embed failed for chunk {i}", "error"))
    report = miner.report()
    err = next(t for t in report["templates"] if t["errors"])
    assert err["by_level"] == {"error": 3}
    assert report["top_error_templates"] == [err["template"]]


def test_plain_text_and_garbage_lines_tolerated():
    miner = LogMiner()
    miner.add_line("not json at all")
    miner.add_line("{broken json")
    miner.add_line("")
    assert miner.total == 1          # plain text mined, garbage skipped
    assert miner.skipped == 1


def test_rare_templates_surface():
    miner = LogMiner()
    for i in range(10):
        miner.add_line(_json_line(f"heartbeat tick {i}"))
    miner.add_line(_json_line("unexpected wedge in scheduler state"))
    report = miner.report()
    assert "unexpected wedge in scheduler state" in report["rare_templates"]
    # min_count hides rare lines from the main table but must NOT
    # empty the rare shortlist — one-offs are its whole point.
    filtered = miner.report(min_count=5)
    assert all(t["count"] >= 5 for t in filtered["templates"])
    assert ("unexpected wedge in scheduler state"
            in filtered["rare_templates"])


def test_error_shortlist_survives_min_count():
    """An error template seen fewer than min_count times still appears
    on the error shortlist — hiding it is how incidents get missed."""
    miner = LogMiner()
    for i in range(10):
        miner.add_line(_json_line(f"heartbeat tick {i}"))
    for i in range(3):
        miner.add_line(_json_line(f"bus write failed attempt {i}", "error"))
    report = miner.report(min_count=5)
    assert all(t["count"] >= 5 for t in report["templates"])
    assert any(t.startswith("bus write failed")
               for t in report["top_error_templates"])


def test_adversarial_token_soup_bounded():
    """Unique-token floods route into a catch-all leaf, not an unbounded
    tree (max_children cap)."""
    miner = LogMiner(max_children=8)
    for i in range(200):
        miner.add_line(_json_line(f"xk{i}q zz{i} blorp{i}"))
    leaves = miner._tree[3]
    assert len(leaves) <= 9  # 8 distinct + the catch-all


def test_cli_json_report(tmp_path, capsys):
    log = tmp_path / "svc.log"
    log.write_text("\n".join(
        _json_line(f"stored message {i:04d}") for i in range(7)) + "\n")
    rc = main([str(log), "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["total_lines"] == 7
    assert report["n_templates"] == 1


def test_cli_text_report_min_count(tmp_path, capsys):
    log = tmp_path / "svc.log"
    lines = [_json_line("common event 1")] * 5 + [_json_line("one-off oddity")]
    log.write_text("\n".join(lines) + "\n")
    rc = main([str(log), "--min-count", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "common event" in out
    assert "one-off oddity" not in out
