# Azure Blob archive store against an in-process mock implementing the
# Blob REST wire contract (PUT/GET/HEAD/DELETE + SharedKey signature
# verification) — the driver speaks raw REST, no SDK, so the same code
# path serves real Azure / Azurite wherever egress exists.
import base64

import pytest

from copilot_for_consensus_tpu.archive.azure_blob import (
    AzureBlobArchiveStore,
    _shared_key_signature,
)
from copilot_for_consensus_tpu.archive.base import (
    ArchiveStoreError,
    create_archive_store,
)
from copilot_for_consensus_tpu.services.http import (
    HTTPServer,
    Response,
    Router,
)

KEY = base64.b64encode(b"contract-test-account-key").decode()


@pytest.fixture()
def mock_blob():
    """Blob-service mock: verifies the SharedKey signature of every
    request by recomputing it from the same canonicalization."""
    router = Router()
    blobs: dict[str, tuple[bytes, dict]] = {}
    state = {"auth_failures": 0}

    def _check_sig(req, method, length):
        url = f"http://host{req.path}"
        sign_headers = {k.lower(): v for k, v in req.headers.items()
                        if k.lower().startswith("x-ms-")}
        if "Content-Type" in req.headers:
            sign_headers["Content-Type"] = req.headers["Content-Type"]
        expect = _shared_key_signature(
            "testacct", KEY, method, url, sign_headers, length)
        got = req.headers.get("Authorization", "")
        if got != expect:
            state["auth_failures"] += 1
            return Response({"error": "auth"}, status=403)
        return None

    @router.route("PUT", "/archives/{name}")
    def put(req):
        bad = _check_sig(req, "PUT", len(req.body))
        if bad:
            return bad
        meta = {k.lower()[len("x-ms-meta-"):]: v
                for k, v in req.headers.items()
                if k.lower().startswith("x-ms-meta-")}
        blobs[req.params["name"]] = (req.body, meta)
        return Response("", status=201, content_type="text/plain")

    @router.get("/archives/{name}")
    def get(req):
        bad = _check_sig(req, "GET", 0)
        if bad:
            return bad
        if req.params["name"] not in blobs:
            return Response({"error": "BlobNotFound"}, status=404)
        return Response(blobs[req.params["name"]][0],
                        content_type="application/octet-stream")

    @router.route("HEAD", "/archives/{name}")
    def head(req):
        bad = _check_sig(req, "HEAD", 0)
        if bad:
            return bad
        if req.params["name"] not in blobs:
            return Response({"error": "BlobNotFound"}, status=404)
        return Response("", content_type="text/plain")

    @router.delete("/archives/{name}")
    def delete(req):
        bad = _check_sig(req, "DELETE", 0)
        if bad:
            return bad
        if req.params["name"] not in blobs:
            return Response({"error": "BlobNotFound"}, status=404)
        del blobs[req.params["name"]]
        return Response("", status=202, content_type="text/plain")

    srv = HTTPServer(router)
    srv.start()
    yield srv, blobs, state
    srv.stop()


def _store(srv):
    return create_archive_store({
        "driver": "azure_blob", "account": "testacct",
        "container": "archives", "account_key": KEY,
        "endpoint": f"http://127.0.0.1:{srv.port}"})


def test_blob_roundtrip_with_shared_key(mock_blob):
    srv, blobs, state = mock_blob
    store = _store(srv)
    uri = store.save("arch-1", b"From a@b\n\nhello\n",
                     metadata={"source id": "ietf"})
    assert uri.endswith("/archives/arch-1.mbox")
    assert store.exists("arch-1") and not store.exists("nope")
    assert store.load("arch-1") == b"From a@b\n\nhello\n"
    # metadata keys sanitized to identifier-safe form
    assert blobs["arch-1.mbox"][1].get("source_id") == "ietf"
    assert store.delete("arch-1") is True
    assert store.delete("arch-1") is False
    assert state["auth_failures"] == 0


def test_blob_bad_key_rejected(mock_blob):
    srv, _, state = mock_blob
    bad = AzureBlobArchiveStore(
        "testacct", "archives",
        account_key=base64.b64encode(b"wrong").decode(),
        endpoint=f"http://127.0.0.1:{srv.port}")
    with pytest.raises(ArchiveStoreError, match="403"):
        bad.save("arch-2", b"x")
    assert state["auth_failures"] == 1


def test_blob_missing_archive_and_hostile_ids(mock_blob):
    srv, _, _ = mock_blob
    store = _store(srv)
    with pytest.raises(ArchiveStoreError, match="not found"):
        store.load("absent")
    with pytest.raises(ArchiveStoreError, match="invalid archive id"):
        store.save("../escape", b"x")


def test_blob_unreachable_endpoint():
    store = AzureBlobArchiveStore("a", "c", account_key=KEY,
                                  endpoint="http://127.0.0.1:1")
    with pytest.raises(ArchiveStoreError, match="unreachable"):
        store.load("arch-1")


def test_blob_config_validation():
    with pytest.raises(ValueError, match="account"):
        AzureBlobArchiveStore("", "c", account_key=KEY)
    with pytest.raises(ValueError, match="account_key or sas"):
        AzureBlobArchiveStore("a", "c")


def test_blob_metadata_validation(mock_blob):
    srv, _, _ = mock_blob
    store = _store(srv)
    for bad_meta, pat in [({"subject": "ellipsis…💥"}, "header-safe"),
                          ({"x": "a\r\nInjected: yes"}, "line breaks"),
                          ({"9rank": "v"}, "identifier"),
                          ({"": "v"}, "identifier"),
                          ({"a b": "1", "a.b": "2"}, "collide")]:
        with pytest.raises(ArchiveStoreError, match=pat):
            store.save("meta-case", b"x", metadata=bad_meta)


def test_blob_container_not_found_is_an_error_not_absent(mock_blob):
    """A misconfigured container must surface, not read as
    'archive absent' (review finding: substring matching on 404s)."""
    srv, _, _ = mock_blob
    import urllib.error

    from copilot_for_consensus_tpu.services.http import Response

    router = srv.router
    @router.route("HEAD", "/wrong/{name}")
    def head_missing_container(req):
        return Response("", status=404,
                        headers={"x-ms-error-code": "ContainerNotFound"},
                        content_type="text/plain")
    bad = AzureBlobArchiveStore(
        "testacct", "wrong", account_key=KEY,
        endpoint=f"http://127.0.0.1:{srv.port}")
    with pytest.raises(ArchiveStoreError, match="ContainerNotFound"):
        bad.exists("arch-1")


# ---------------------------------------------------------------------------
# Azure Key Vault secrets (REST + AAD client credentials)
# ---------------------------------------------------------------------------


@pytest.fixture()
def mock_kv():
    """AAD token endpoint + Key Vault secrets endpoint in one mock."""
    import json as _json
    import urllib.parse as up

    router = Router()
    state = {"token_calls": 0, "secret_calls": 0}
    secrets = {"db-password": "s3cr3t!", "api-key": "k-123"}

    @router.post("/tenant-1/oauth2/v2.0/token")
    def token(req):
        form = dict(up.parse_qsl(req.body.decode()))
        state["token_calls"] += 1
        if form.get("client_id") != "app-1" or \
                form.get("client_secret") != "app-secret":
            return Response({"error": "invalid_client"}, status=401)
        assert form["grant_type"] == "client_credentials"
        assert form["scope"].endswith("/.default")
        return {"access_token": "tok-abc", "expires_in": 3600}

    @router.get("/secrets/{name}")
    def secret(req):
        state["secret_calls"] += 1
        if req.headers.get("Authorization") != "Bearer tok-abc":
            return Response({"error": "unauthorized"}, status=401)
        assert req.query.get("api-version")
        name = req.params["name"]
        if name not in secrets:
            return Response({"error": "SecretNotFound"}, status=404)
        return {"value": secrets[name], "id": f"kv/secrets/{name}"}

    srv = HTTPServer(router)
    srv.start()
    yield srv, state
    srv.stop()


def test_keyvault_secret_roundtrip_and_token_cache(mock_kv):
    from copilot_for_consensus_tpu.security.secrets import (
        SecretNotFoundError,
        create_secret_provider,
    )

    srv, state = mock_kv
    base = f"http://127.0.0.1:{srv.port}"
    prov = create_secret_provider({
        "driver": "azure_keyvault", "vault_url": base,
        "tenant_id": "tenant-1", "client_id": "app-1",
        "client_secret": "app-secret", "authority": base})
    assert prov.get_secret("db-password") == "s3cr3t!"
    assert prov.get_secret("api-key") == "k-123"
    assert state["token_calls"] == 1          # cached across reads
    with pytest.raises(SecretNotFoundError):
        prov.get_secret("absent")
    with pytest.raises(SecretNotFoundError):
        prov.get_secret("../../escape")       # KV name charset enforced
    # secret:// resolution path end-to-end via the config layer contract
    assert prov("db-password") == "s3cr3t!"


def test_keyvault_bad_credentials_surface(mock_kv):
    srv, _ = mock_kv
    base = f"http://127.0.0.1:{srv.port}"
    from copilot_for_consensus_tpu.security.secrets import (
        AzureKeyVaultSecretProvider,
    )

    bad = AzureKeyVaultSecretProvider(base, "tenant-1", "app-1",
                                      "wrong", authority=base)
    with pytest.raises(Exception, match="401|Unauthorized"):
        bad.get_secret("db-password")


def test_keyvault_config_validation():
    from copilot_for_consensus_tpu.security.secrets import (
        create_secret_provider,
    )

    with pytest.raises(ValueError, match="vault_url"):
        create_secret_provider({"driver": "azure_keyvault"})


# ---------------------------------------------------------------------------
# Azure Cosmos DB document store (SQL API over REST)
# ---------------------------------------------------------------------------


def _eval_cosmos_sql(sql, params, docs):
    """Evaluate the constrained SQL grammar translate_filter emits —
    enough of the Cosmos SQL surface to round-trip the driver's
    queries; anything else fails loudly."""
    import re

    pvals = {p["name"]: p["value"] for p in params}
    m = re.match(
        r"SELECT (VALUE COUNT\(1\)|\*) FROM c"
        r"(?: WHERE (?P<where>.*?))?"
        r"(?: ORDER BY (?P<order>[^)]+?))?"
        r"(?: OFFSET (?P<off>\d+) LIMIT (?P<lim>\d+))?$", sql)
    assert m, f"mock cannot parse: {sql}"

    def get(doc, dotted):
        cur = doc
        for part in dotted.split(".")[1:]:     # drop leading 'c'
            if not isinstance(cur, dict) or part not in cur:
                return None, False
            cur = cur[part]
        return cur, True

    def _wrapped(t):
        # outer parens strippable only if they MATCH (depth never hits
        # zero before the final char)
        if not (t.startswith("(") and t.endswith(")")):
            return False
        depth = 0
        for i, c in enumerate(t):
            depth += c == "("
            depth -= c == ")"
            if depth == 0 and i < len(t) - 1:
                return False
        return True

    def term(doc, t):
        t = t.strip()
        while _wrapped(t):
            t = t[1:-1].strip()
        if " OR " in t:
            return any(term(doc, s) for s in _split(t, " OR "))
        if " AND " in t:
            return all(term(doc, s) for s in _split(t, " AND "))
        if t == "true":
            return True
        if t == "false":
            return False
        if t.startswith("NOT IS_DEFINED("):
            return not get(doc, t[15:-1])[1]
        if t.startswith("IS_DEFINED("):
            return get(doc, t[11:-1])[1]
        if t.startswith("NOT ARRAY_CONTAINS("):
            arr, f = t[len("NOT ARRAY_CONTAINS("):-1].split(", ")
            v, ex = get(doc, f)
            return not (ex and v in pvals[arr])
        if t.startswith("ARRAY_CONTAINS("):
            arr, f = t[len("ARRAY_CONTAINS("):-1].split(", ")
            v, ex = get(doc, f)
            return ex and v in pvals[arr]
        if t.startswith("RegexMatch("):
            f, pat = t[len("RegexMatch("):-1].split(", ")
            v, ex = get(doc, f)
            return ex and isinstance(v, str) and \
                re.search(pvals[pat], v) is not None
        mm = re.match(r"(c[.\w]+) (=|!=|<=|>=|<|>) (@p\d+)$", t)
        assert mm, f"mock cannot parse term: {t}"
        v, ex = get(doc, mm.group(1))
        arg = pvals[mm.group(3)]
        op = mm.group(2)
        if not ex or v is None:
            # real Cosmos: comparisons on undefined are undefined —
            # the row never matches, INCLUDING for != (the driver's
            # translator wraps $ne with NOT IS_DEFINED to compensate)
            return False
        return {"=": v == arg, "!=": v != arg, "<": v < arg,
                "<=": v <= arg, ">": v > arg, ">=": v >= arg}[op]

    def _split(t, sep):
        # split at depth 0 only
        out, depth, cur = [], 0, ""
        i = 0
        while i < len(t):
            if t[i] == "(":
                depth += 1
            elif t[i] == ")":
                depth -= 1
            if depth == 0 and t[i:i + len(sep)] == sep:
                out.append(cur)
                cur = ""
                i += len(sep)
                continue
            cur += t[i]
            i += 1
        out.append(cur)
        return out

    hits = [d for d in docs.values()
            if term(d, m.group("where") or "true")]
    if m.group("order"):
        for part in reversed(m.group("order").split(", ")):
            f, d = part.rsplit(" ", 1)
            hits.sort(key=lambda x: (get(x, f)[0] is None, get(x, f)[0]),
                      reverse=(d == "DESC"))
    if m.group("off") is not None:
        off, lim = int(m.group("off")), int(m.group("lim"))
        hits = hits[off:off + lim]
    if sql.startswith("SELECT VALUE COUNT"):
        return [len(hits)]
    return hits


@pytest.fixture()
def mock_cosmos():
    import json as _json

    router = Router()
    state = {"colls": {}, "bad_auth": 0}

    def _h(req, name):
        for k, v in req.headers.items():
            if k.lower() == name:
                return v
        return None

    def _authed(req):
        auth = req.headers.get("Authorization", "")
        if "type%3Dmaster" not in auth or "sig%3D" not in auth:
            state["bad_auth"] += 1
            return False
        return True

    @router.post("/dbs")
    def create_db(req):
        return Response({"id": req.json()["id"]}, status=201)

    @router.post("/dbs/{db}/colls")
    def create_coll(req):
        name = req.json()["id"]
        if name in state["colls"]:
            return Response({"error": "Conflict"}, status=409)
        state["colls"][name] = {}
        return Response({"id": name}, status=201)

    @router.post("/dbs/{db}/colls/{coll}/docs")
    def docs_endpoint(req):
        if not _authed(req):
            return Response({"error": "auth"}, status=401)
        coll = state["colls"].setdefault(req.params["coll"], {})
        if _h(req, "x-ms-documentdb-isquery") == "true":
            q = req.json()
            hits = _eval_cosmos_sql(q["query"], q["parameters"], coll)
            page = 3                       # force continuation handling
            start = int(_h(req, "x-ms-continuation") or 0)
            body = {"Documents": hits[start:start + page]}
            headers = {}
            if start + page < len(hits):
                headers["x-ms-continuation"] = str(start + page)
            return Response(body, headers=headers)
        doc = req.json()
        is_upsert = _h(req, "x-ms-documentdb-is-upsert") == "true"
        if doc["id"] in coll and not is_upsert:
            return Response({"error": "Conflict"}, status=409)
        state["etag"] = state.get("etag", 0) + 1
        coll[doc["id"]] = {**doc, "_rid": "rid", "_ts": 1,
                           "_self": "s", "_etag": f"e{state['etag']}",
                           "_attachments": "a"}
        return Response(doc, status=201)

    @router.put("/dbs/{db}/colls/{coll}/docs/{id}")
    def replace_doc(req):
        coll = state["colls"].setdefault(req.params["coll"], {})
        cur = coll.get(req.params["id"])
        if cur is None:
            return Response({"error": "NotFound"}, status=404)
        if_match = _h(req, "if-match")
        if if_match and if_match != cur["_etag"]:
            return Response({"error": "PreconditionFailed"}, status=412)
        state["etag"] = state.get("etag", 0) + 1
        coll[req.params["id"]] = {**req.json(), "_rid": "rid", "_ts": 2,
                                  "_self": "s",
                                  "_etag": f"e{state['etag']}",
                                  "_attachments": "a"}
        return coll[req.params["id"]]

    @router.get("/dbs/{db}/colls/{coll}/docs/{id}")
    def get_doc(req):
        doc = state["colls"].get(req.params["coll"], {}).get(
            req.params["id"])
        if doc is None:
            return Response({"error": "NotFound"}, status=404)
        return doc

    @router.delete("/dbs/{db}/colls/{coll}/docs/{id}")
    def del_doc(req):
        coll = state["colls"].get(req.params["coll"], {})
        if req.params["id"] not in coll:
            return Response({"error": "NotFound"}, status=404)
        del coll[req.params["id"]]
        return Response("", status=204, content_type="text/plain")

    srv = HTTPServer(router)
    srv.start()
    yield srv, state
    srv.stop()


def _cosmos(srv):
    from copilot_for_consensus_tpu.storage.azure_cosmos import (
        AzureCosmosDocumentStore,
    )

    return AzureCosmosDocumentStore(
        "acct", base64.b64encode(b"cosmos-master-key").decode(),
        endpoint=f"http://127.0.0.1:{srv.port}")


def test_cosmos_crud_roundtrip(mock_cosmos):
    srv, state = mock_cosmos
    store = _cosmos(srv)
    store.connect()
    rid = store.upsert_document("reports", {
        "report_id": "r1", "thread_id": "t1", "status": "published",
        "score": 7, "nested": {"k": "v"}})
    assert rid == "r1"
    doc = store.get_document("reports", "r1")
    assert doc == {"report_id": "r1", "thread_id": "t1",
                   "status": "published", "score": 7,
                   "nested": {"k": "v"}}          # system props stripped
    assert store.get_document("reports", "absent") is None
    store.upsert_document("reports", {"report_id": "r1",
                                      "thread_id": "t1",
                                      "status": "draft", "score": 9})
    assert store.update_document("reports", "r1", {"score": 10})
    assert store.get_document("reports", "r1")["score"] == 10
    assert not store.update_document("reports", "nope", {"x": 1})
    assert store.delete_document("reports", "r1") is True
    assert store.delete_document("reports", "r1") is False
    assert state["bad_auth"] == 0


def test_cosmos_insert_conflict(mock_cosmos):
    from copilot_for_consensus_tpu.storage.base import DuplicateKeyError

    srv, _ = mock_cosmos
    store = _cosmos(srv)
    store.insert_document("threads", {"thread_id": "t1", "n": 1})
    with pytest.raises(DuplicateKeyError):
        store.insert_document("threads", {"thread_id": "t1", "n": 2})
    assert store.insert_or_ignore("threads",
                                  {"thread_id": "t1", "n": 3}) is False


def test_cosmos_query_filters_match_memory_store(mock_cosmos):
    """Oracle: every supported filter shape returns the same documents
    through (translate_filter → Cosmos SQL → mock evaluator) as the
    in-memory matcher on identical data."""
    from copilot_for_consensus_tpu.storage.memory import (
        InMemoryDocumentStore,
    )

    srv, _ = mock_cosmos
    store = _cosmos(srv)
    mem = InMemoryDocumentStore()
    mem.connect()
    docs = [
        {"chunk_id": f"c{i}", "thread_id": f"t{i % 3}",
         "status": ["pending", "embedded"][i % 2], "n": i,
         "meta": {"lang": ["en", "de"][i % 2]},
         **({"extra": True} if i == 4 else {})}
        for i in range(9)
    ]
    for d in docs:
        store.upsert_document("chunks", d)
        mem.upsert_document("chunks", d)
    filters = [
        None,
        {"thread_id": "t1"},
        {"status": "embedded", "thread_id": "t0"},
        {"n": {"$gte": 3, "$lt": 7}},
        {"chunk_id": {"$in": ["c1", "c5", "zz"]}},
        {"status": {"$ne": "pending"}},
        {"thread_id": {"$nin": ["t0", "t2"]}},
        {"extra": {"$exists": True}},
        {"extra": {"$exists": False}},
        {"meta.lang": "de"},
        {"chunk_id": {"$regex": "^c[12]$"}},
        {"$or": [{"thread_id": "t0"}, {"n": {"$gt": 7}}]},
        {"$and": [{"status": "pending"}, {"n": {"$lte": 4}}]},
        # degenerate lists: empty $or matches nothing (any([])), empty
        # $and everything (all([])) — must not emit invalid SQL '()'
        {"$or": []},
        {"$and": []},
        {"status": "pending", "$or": []},
        {"status": "pending", "$and": []},
    ]
    for flt in filters:
        got = sorted(d["chunk_id"]
                     for d in store.query_documents("chunks", flt))
        want = sorted(d["chunk_id"]
                      for d in mem.query_documents("chunks", flt))
        assert got == want, (flt, got, want)
        assert store.count_documents("chunks", flt) == len(want), flt
    # sort + limit/skip
    page = store.query_documents("chunks", None, sort=[("n", -1)],
                                 limit=3, skip=2)
    assert [d["n"] for d in page] == [6, 5, 4]
    # delete by filter
    assert store.delete_documents("chunks", {"status": "pending"}) == 5
    assert store.count_documents("chunks") == 4


def test_cosmos_rejects_hostile_field_paths(mock_cosmos):
    from copilot_for_consensus_tpu.storage.base import StorageError

    srv, _ = mock_cosmos
    store = _cosmos(srv)
    with pytest.raises(StorageError, match="field path"):
        store.query_documents("chunks", {"a;DROP": 1})
    with pytest.raises(StorageError, match="operator"):
        store.query_documents("chunks", {"a": {"$where": "1"}})
