# Azure Blob archive store against an in-process mock implementing the
# Blob REST wire contract (PUT/GET/HEAD/DELETE + SharedKey signature
# verification) — the driver speaks raw REST, no SDK, so the same code
# path serves real Azure / Azurite wherever egress exists.
import base64

import pytest

from copilot_for_consensus_tpu.archive.azure_blob import (
    AzureBlobArchiveStore,
    _shared_key_signature,
)
from copilot_for_consensus_tpu.archive.base import (
    ArchiveStoreError,
    create_archive_store,
)
from copilot_for_consensus_tpu.services.http import (
    HTTPServer,
    Response,
    Router,
)

KEY = base64.b64encode(b"contract-test-account-key").decode()


@pytest.fixture()
def mock_blob():
    """Blob-service mock: verifies the SharedKey signature of every
    request by recomputing it from the same canonicalization."""
    router = Router()
    blobs: dict[str, tuple[bytes, dict]] = {}
    state = {"auth_failures": 0}

    def _check_sig(req, method, length):
        url = f"http://host{req.path}"
        sign_headers = {k.lower(): v for k, v in req.headers.items()
                        if k.lower().startswith("x-ms-")}
        if "Content-Type" in req.headers:
            sign_headers["Content-Type"] = req.headers["Content-Type"]
        expect = _shared_key_signature(
            "testacct", KEY, method, url, sign_headers, length)
        got = req.headers.get("Authorization", "")
        if got != expect:
            state["auth_failures"] += 1
            return Response({"error": "auth"}, status=403)
        return None

    @router.route("PUT", "/archives/{name}")
    def put(req):
        bad = _check_sig(req, "PUT", len(req.body))
        if bad:
            return bad
        meta = {k.lower()[len("x-ms-meta-"):]: v
                for k, v in req.headers.items()
                if k.lower().startswith("x-ms-meta-")}
        blobs[req.params["name"]] = (req.body, meta)
        return Response("", status=201, content_type="text/plain")

    @router.get("/archives/{name}")
    def get(req):
        bad = _check_sig(req, "GET", 0)
        if bad:
            return bad
        if req.params["name"] not in blobs:
            return Response({"error": "BlobNotFound"}, status=404)
        return Response(blobs[req.params["name"]][0],
                        content_type="application/octet-stream")

    @router.route("HEAD", "/archives/{name}")
    def head(req):
        bad = _check_sig(req, "HEAD", 0)
        if bad:
            return bad
        if req.params["name"] not in blobs:
            return Response({"error": "BlobNotFound"}, status=404)
        return Response("", content_type="text/plain")

    @router.delete("/archives/{name}")
    def delete(req):
        bad = _check_sig(req, "DELETE", 0)
        if bad:
            return bad
        if req.params["name"] not in blobs:
            return Response({"error": "BlobNotFound"}, status=404)
        del blobs[req.params["name"]]
        return Response("", status=202, content_type="text/plain")

    srv = HTTPServer(router)
    srv.start()
    yield srv, blobs, state
    srv.stop()


def _store(srv):
    return create_archive_store({
        "driver": "azure_blob", "account": "testacct",
        "container": "archives", "account_key": KEY,
        "endpoint": f"http://127.0.0.1:{srv.port}"})


def test_blob_roundtrip_with_shared_key(mock_blob):
    srv, blobs, state = mock_blob
    store = _store(srv)
    uri = store.save("arch-1", b"From a@b\n\nhello\n",
                     metadata={"source id": "ietf"})
    assert uri.endswith("/archives/arch-1.mbox")
    assert store.exists("arch-1") and not store.exists("nope")
    assert store.load("arch-1") == b"From a@b\n\nhello\n"
    # metadata keys sanitized to identifier-safe form
    assert blobs["arch-1.mbox"][1].get("source_id") == "ietf"
    assert store.delete("arch-1") is True
    assert store.delete("arch-1") is False
    assert state["auth_failures"] == 0


def test_blob_bad_key_rejected(mock_blob):
    srv, _, state = mock_blob
    bad = AzureBlobArchiveStore(
        "testacct", "archives",
        account_key=base64.b64encode(b"wrong").decode(),
        endpoint=f"http://127.0.0.1:{srv.port}")
    with pytest.raises(ArchiveStoreError, match="403"):
        bad.save("arch-2", b"x")
    assert state["auth_failures"] == 1


def test_blob_missing_archive_and_hostile_ids(mock_blob):
    srv, _, _ = mock_blob
    store = _store(srv)
    with pytest.raises(ArchiveStoreError, match="not found"):
        store.load("absent")
    with pytest.raises(ArchiveStoreError, match="invalid archive id"):
        store.save("../escape", b"x")


def test_blob_unreachable_endpoint():
    store = AzureBlobArchiveStore("a", "c", account_key=KEY,
                                  endpoint="http://127.0.0.1:1")
    with pytest.raises(ArchiveStoreError, match="unreachable"):
        store.load("arch-1")


def test_blob_config_validation():
    with pytest.raises(ValueError, match="account"):
        AzureBlobArchiveStore("", "c", account_key=KEY)
    with pytest.raises(ValueError, match="account_key or sas"):
        AzureBlobArchiveStore("a", "c")


def test_blob_metadata_validation(mock_blob):
    srv, _, _ = mock_blob
    store = _store(srv)
    for bad_meta, pat in [({"subject": "ellipsis…💥"}, "header-safe"),
                          ({"x": "a\r\nInjected: yes"}, "line breaks"),
                          ({"9rank": "v"}, "identifier"),
                          ({"": "v"}, "identifier"),
                          ({"a b": "1", "a.b": "2"}, "collide")]:
        with pytest.raises(ArchiveStoreError, match=pat):
            store.save("meta-case", b"x", metadata=bad_meta)


def test_blob_container_not_found_is_an_error_not_absent(mock_blob):
    """A misconfigured container must surface, not read as
    'archive absent' (review finding: substring matching on 404s)."""
    srv, _, _ = mock_blob
    import urllib.error

    from copilot_for_consensus_tpu.services.http import Response

    router = srv.router
    @router.route("HEAD", "/wrong/{name}")
    def head_missing_container(req):
        return Response("", status=404,
                        headers={"x-ms-error-code": "ContainerNotFound"},
                        content_type="text/plain")
    bad = AzureBlobArchiveStore(
        "testacct", "wrong", account_key=KEY,
        endpoint=f"http://127.0.0.1:{srv.port}")
    with pytest.raises(ArchiveStoreError, match="ContainerNotFound"):
        bad.exists("arch-1")


# ---------------------------------------------------------------------------
# Azure Key Vault secrets (REST + AAD client credentials)
# ---------------------------------------------------------------------------


@pytest.fixture()
def mock_kv():
    """AAD token endpoint + Key Vault secrets endpoint in one mock."""
    import json as _json
    import urllib.parse as up

    router = Router()
    state = {"token_calls": 0, "secret_calls": 0}
    secrets = {"db-password": "s3cr3t!", "api-key": "k-123"}

    @router.post("/tenant-1/oauth2/v2.0/token")
    def token(req):
        form = dict(up.parse_qsl(req.body.decode()))
        state["token_calls"] += 1
        if form.get("client_id") != "app-1" or \
                form.get("client_secret") != "app-secret":
            return Response({"error": "invalid_client"}, status=401)
        assert form["grant_type"] == "client_credentials"
        assert form["scope"].endswith("/.default")
        return {"access_token": "tok-abc", "expires_in": 3600}

    @router.get("/secrets/{name}")
    def secret(req):
        state["secret_calls"] += 1
        if req.headers.get("Authorization") != "Bearer tok-abc":
            return Response({"error": "unauthorized"}, status=401)
        assert req.query.get("api-version")
        name = req.params["name"]
        if name not in secrets:
            return Response({"error": "SecretNotFound"}, status=404)
        return {"value": secrets[name], "id": f"kv/secrets/{name}"}

    srv = HTTPServer(router)
    srv.start()
    yield srv, state
    srv.stop()


def test_keyvault_secret_roundtrip_and_token_cache(mock_kv):
    from copilot_for_consensus_tpu.security.secrets import (
        SecretNotFoundError,
        create_secret_provider,
    )

    srv, state = mock_kv
    base = f"http://127.0.0.1:{srv.port}"
    prov = create_secret_provider({
        "driver": "azure_keyvault", "vault_url": base,
        "tenant_id": "tenant-1", "client_id": "app-1",
        "client_secret": "app-secret", "authority": base})
    assert prov.get_secret("db-password") == "s3cr3t!"
    assert prov.get_secret("api-key") == "k-123"
    assert state["token_calls"] == 1          # cached across reads
    with pytest.raises(SecretNotFoundError):
        prov.get_secret("absent")
    with pytest.raises(SecretNotFoundError):
        prov.get_secret("../../escape")       # KV name charset enforced
    # secret:// resolution path end-to-end via the config layer contract
    assert prov("db-password") == "s3cr3t!"


def test_keyvault_bad_credentials_surface(mock_kv):
    srv, _ = mock_kv
    base = f"http://127.0.0.1:{srv.port}"
    from copilot_for_consensus_tpu.security.secrets import (
        AzureKeyVaultSecretProvider,
    )

    bad = AzureKeyVaultSecretProvider(base, "tenant-1", "app-1",
                                      "wrong", authority=base)
    with pytest.raises(Exception, match="401|Unauthorized"):
        bad.get_secret("db-password")


def test_keyvault_config_validation():
    from copilot_for_consensus_tpu.security.secrets import (
        create_secret_provider,
    )

    with pytest.raises(ValueError, match="vault_url"):
        create_secret_provider({"driver": "azure_keyvault"})
