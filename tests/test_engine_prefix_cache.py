# Cross-request prefix KV-cache reuse: radix trie matching, refcount
# pinning vs LRU eviction, hit/miss accounting, and end-to-end identity
# of seeded-admission outputs vs the cache-disabled engine.
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from copilot_for_consensus_tpu.engine.prefix_cache import PrefixCache
from copilot_for_consensus_tpu.engine.tokenizer import stable_block_hash
from copilot_for_consensus_tpu.models.configs import decoder_config

CFG = decoder_config("tiny")
BLOCK = 4


def _cache(num_blocks=8):
    return PrefixCache(CFG, num_blocks=num_blocks, block_size=BLOCK,
                       kv_dtype=jnp.float32)


def _slot_cache(num_slots=2, max_len=32, fill=None):
    """A fake engine slot cache with recognizable per-position values."""
    shape = (CFG.n_layers, num_slots, CFG.n_kv_heads, max_len,
             CFG.head_dim)
    if fill is None:
        base = np.arange(max_len, dtype=np.float32)
        arr = np.broadcast_to(
            base[None, None, None, :, None], shape).copy()
    else:
        arr = np.full(shape, fill, dtype=np.float32)
    return {"k": jnp.asarray(arr), "v": jnp.asarray(arr) * 2.0}


# ---------------------------------------------------------------------------
# hashing
# ---------------------------------------------------------------------------


def test_stable_block_hash_is_chained_and_stable():
    a = stable_block_hash(b"", [1, 2, 3, 4])
    assert a == stable_block_hash(b"", [1, 2, 3, 4])   # deterministic
    assert a != stable_block_hash(b"", [1, 2, 3, 5])
    # chaining: same block under a different parent is a different node
    assert stable_block_hash(a, [9, 9]) != stable_block_hash(b"x", [9, 9])
    # not concat-ambiguous with list vs tuple / np ints
    assert a == stable_block_hash(b"", (np.int32(1), 2, 3, 4))


# ---------------------------------------------------------------------------
# trie matching + accounting
# ---------------------------------------------------------------------------


def test_longest_prefix_match_and_accounting():
    pc = _cache()
    cache = _slot_cache()
    prompt = list(range(10, 10 + 3 * BLOCK))           # 3 full blocks
    assert pc.publish(prompt, cache, slot=0) == 3
    assert pc.blocks_in_use == 3

    # full 3-block match — but lookup must leave >= 1 suffix token, so
    # an IDENTICAL prompt matches only 2 blocks (12 of 12 tokens would
    # leave nothing to sample the first generated token from)
    m = pc.lookup(prompt)
    assert m.tokens == 2 * BLOCK
    pc.release(m)

    # one extra token past the blocks: now all 3 blocks match
    m = pc.lookup(prompt + [99])
    assert m.tokens == 3 * BLOCK
    assert len(m.block_ids) == 3
    pc.release(m)

    # diverging second block matches only the first
    div = prompt[:BLOCK] + [0] * (2 * BLOCK)
    m = pc.lookup(div)
    assert m.tokens == BLOCK
    pc.release(m)

    # total miss
    m = pc.lookup([7] * (3 * BLOCK))
    assert m.tokens == 0 and not m.nodes

    s = pc.stats
    assert s.lookups == 4
    assert s.hits == 3 and s.misses == 1
    assert s.tokens_matched == 2 * BLOCK + 3 * BLOCK + BLOCK


def test_publish_dedup_and_extension():
    pc = _cache()
    cache = _slot_cache()
    p = list(range(50, 50 + 2 * BLOCK))
    assert pc.publish(p, cache, 0) == 2
    # re-publishing the same prompt allocates nothing new
    assert pc.publish(p, cache, 1) == 0
    assert pc.blocks_in_use == 2
    # a longer prompt with the same head only adds the tail block
    assert pc.publish(p + list(range(4)), cache, 0) == 1
    assert pc.blocks_in_use == 3


def test_publish_eligibility_cap_is_block_aligned():
    pc = _cache()
    cache = _slot_cache()
    p = list(range(3 * BLOCK))
    # cap mid-block: only the fully-covered blocks publish
    assert pc.publish(p, cache, 0, eligible_tokens=2 * BLOCK + 1) == 2
    assert pc.publish(p, cache, 0, eligible_tokens=0) == 0
    assert pc.blocks_in_use == 2


def test_published_kv_matches_cache_contents():
    """The pool block for positions [B, 2B) must hold slot 1's cache
    values at those positions (k and v, k != v)."""
    pc = _cache()
    cache = _slot_cache(num_slots=3)
    p = list(range(2 * BLOCK))
    pc.publish(p, cache, slot=1)
    m = pc.lookup(p + [1])
    assert m.tokens == 2 * BLOCK
    k2 = np.asarray(pc.pool["k"][:, m.block_ids[1]])   # [L, Hkv, B, Dh]
    v2 = np.asarray(pc.pool["v"][:, m.block_ids[1]])
    want_k = np.asarray(cache["k"][:, 1, :, BLOCK:2 * BLOCK, :])
    want_v = np.asarray(cache["v"][:, 1, :, BLOCK:2 * BLOCK, :])
    np.testing.assert_array_equal(k2, want_k)
    np.testing.assert_array_equal(v2, want_v)
    pc.release(m)


# ---------------------------------------------------------------------------
# refcount pinning vs LRU eviction
# ---------------------------------------------------------------------------


def test_lru_evicts_least_recently_used_leaf():
    pc = _cache(num_blocks=2)
    cache = _slot_cache()
    a = [1] * BLOCK + [1]
    b = [2] * BLOCK + [2]
    c = [3] * BLOCK + [3]
    assert pc.publish(a, cache, 0) == 1
    assert pc.publish(b, cache, 0) == 1
    # touch a so b becomes the LRU leaf
    pc.release(pc.lookup(a))
    assert pc.publish(c, cache, 0) == 1      # evicts b
    assert pc.stats.blocks_evicted == 1
    assert pc.lookup(a).tokens == BLOCK      # survived (leave pinned)
    assert pc.lookup(b).tokens == 0          # evicted
    assert pc.lookup(c).tokens == BLOCK


def test_pinned_blocks_are_not_evicted():
    pc = _cache(num_blocks=1)
    cache = _slot_cache()
    a = [1] * BLOCK + [1]
    assert pc.publish(a, cache, 0) == 1
    m = pc.lookup(a)                         # pins the only block
    assert m.tokens == BLOCK
    # pool full of pinned blocks: the new publish must SKIP, not evict
    assert pc.publish([2] * BLOCK + [2], cache, 0) == 0
    assert pc.stats.publish_skips == 1
    m2 = pc.lookup(a)
    assert m2.tokens == BLOCK                # still resident
    pc.release(m2)
    pc.release(m)                            # fully unpinned now
    assert pc.publish([2] * BLOCK + [2], cache, 0) == 1   # evicts a
    assert pc.lookup(a).tokens == 0


def test_interior_nodes_survive_while_children_exist():
    """Eviction is leaves-only: evicting an interior block would orphan
    descendants that can then never be matched from the root."""
    pc = _cache(num_blocks=3)
    cache = _slot_cache()
    long = list(range(3 * BLOCK))
    assert pc.publish(long, cache, 0) == 3   # chain of 3 nodes
    # pool is full; a new 1-block publish must evict the chain TAIL,
    # not the root block
    assert pc.publish([9] * BLOCK + [9], cache, 0) == 1
    m = pc.lookup(long + [1])
    assert m.tokens == 2 * BLOCK             # head survived, tail gone
    pc.release(m)


def test_shared_template_head_is_thread_independent():
    from copilot_for_consensus_tpu.summarization.tpu_summarizer import (
        DEFAULT_SYSTEM,
        DEFAULT_TEMPLATE,
        build_prompt,
        shared_template_head,
    )
    from copilot_for_consensus_tpu.summarization.base import ThreadContext

    head = shared_template_head(DEFAULT_TEMPLATE, DEFAULT_SYSTEM)
    assert DEFAULT_SYSTEM in head
    assert "{" not in head                       # fully rendered
    for tid in ("t1", "t2"):
        ctx = ThreadContext(thread_id=tid, subject=f"subj-{tid}",
                            participants=[f"{tid}@x"], message_count=2,
                            chunks=[{"chunk_id": "c", "text": tid * 5}])
        assert build_prompt(ctx).startswith(head)


# ---------------------------------------------------------------------------
# end-to-end through the engine (CPU)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestEngineEndToEnd:
    CHUNK = 64
    SHARED = 256            # acceptance: >= 256-token shared prefix

    def _engines(self):
        from copilot_for_consensus_tpu.engine.generation import (
            GenerationEngine,
        )
        from copilot_for_consensus_tpu.models import decoder

        params = decoder.init_params(jax.random.PRNGKey(7), CFG,
                                     dtype=jnp.float32)
        kw = dict(num_slots=4, max_len=384,
                  prefill_buckets=(64, 128, 320),
                  dtype=jnp.float32, kv_dtype=jnp.float32,
                  attn_impl="xla", decode_window=4,
                  prefill_chunk=self.CHUNK)
        return (GenerationEngine(CFG, params, **kw),
                GenerationEngine(CFG, params, prefix_cache_blocks=32,
                                 **kw))

    def test_shared_prefix_batch_identical_outputs_and_savings(self):
        plain, cached = self._engines()
        rng = np.random.default_rng(0)
        shared = rng.integers(3, CFG.vocab_size,
                              size=self.SHARED).tolist()
        prompts = [shared + rng.integers(3, CFG.vocab_size,
                                         size=40).tolist()
                   for _ in range(8)]

        want = plain.generate(prompts, max_new_tokens=8)
        got = cached.generate(prompts, max_new_tokens=8)
        # bit-identical generations (greedy sampling, f32 cache)
        for w, g in zip(want, got):
            assert w.tokens == g.tokens
            assert w.finish_reason == g.finish_reason

        # second pass: every prompt now fully cached
        want2 = plain.generate(prompts, max_new_tokens=8)
        got2 = cached.generate(prompts, max_new_tokens=8)
        for w, g in zip(want2, got2):
            assert w.tokens == g.tokens

        stats = cached.prefix_stats()
        assert stats["enabled"]
        assert stats["hits"] >= 8                 # whole second pass
        # acceptance: accounted prefilled tokens drop >= 50% vs the
        # cache-disabled engine over the same workload
        assert plain.prefill_tokens == 2 * 8 * len(prompts[0])
        assert stats["prefill_tokens"] <= plain.prefill_tokens // 2
        assert stats["prefill_tokens_saved"] >= 8 * self.SHARED

    def test_mixed_hit_miss_wave_and_divergent_prefixes(self):
        plain, cached = self._engines()
        rng = np.random.default_rng(1)
        shared = rng.integers(3, CFG.vocab_size, size=self.SHARED).tolist()
        batch1 = [shared + rng.integers(3, CFG.vocab_size,
                                        size=24).tolist()
                  for _ in range(3)]
        cached.generate(batch1, max_new_tokens=4)   # warm the cache
        # second batch mixes: full hits, a diverging prefix (matches
        # only part of the chain), and a cold miss — one seeded wave
        divergent = shared[:self.CHUNK] + rng.integers(
            3, CFG.vocab_size, size=self.SHARED).tolist()
        cold = rng.integers(3, CFG.vocab_size,
                            size=self.SHARED).tolist()
        batch2 = [batch1[0], divergent, cold]
        plain.generate(batch1, max_new_tokens=4)
        want = plain.generate(batch2, max_new_tokens=4)
        got = cached.generate(batch2, max_new_tokens=4)
        for w, g in zip(want, got):
            assert w.tokens == g.tokens

    def test_async_runner_with_prefix_cache(self):
        from copilot_for_consensus_tpu.engine.async_runner import (
            AsyncEngineRunner,
        )

        plain, cached = self._engines()
        rng = np.random.default_rng(2)
        shared = rng.integers(3, CFG.vocab_size, size=self.SHARED).tolist()
        prompts = [shared + [10 + i] * 16 for i in range(6)]
        want = plain.generate(prompts, max_new_tokens=5)
        runner = AsyncEngineRunner(cached).start()
        try:
            hs = [runner.submit(list(p), 5) for p in prompts]
            for w, h in zip(want, hs):
                assert h.result(timeout=300).tokens == w.tokens
            hs = [runner.submit(list(p), 5,
                                cache_eligible_tokens=len(shared))
                  for p in prompts]
            for w, h in zip(want, hs):
                assert h.result(timeout=300).tokens == w.tokens
        finally:
            runner.stop()
        assert cached.prefix_stats()["hits"] > 0

    def test_summarizer_template_scope_hits_across_threads(self):
        """cache_scope='template' publishes only the shared template
        head; a second thread's prompt still hits on that span."""
        from copilot_for_consensus_tpu.summarization.base import (
            ThreadContext,
        )
        from copilot_for_consensus_tpu.summarization.tpu_summarizer import (
            TPUSummarizer,
        )

        _, cached = self._engines()
        summ = TPUSummarizer(engine=cached, max_new_tokens=4,
                             cache_scope="template")
        assert 0 < summ._cache_eligible
        threads = [
            ThreadContext(thread_id=f"t{i}", subject=f"subject {i}",
                          participants=[f"p{i}@x"], message_count=3,
                          chunks=[{"chunk_id": f"c{i}",
                                   "text": f"body {i} " * 8}])
            for i in range(3)
        ]
        summ.summarize(threads[0])
        summ.summarize_batch(threads[1:])
        stats = cached.prefix_stats()
        # later threads reused the template head published by the first
        assert stats["hits"] >= 1
        assert stats["prefill_tokens_saved"] > 0
        # template scope: nothing beyond the shared span was published
        assert stats["blocks_published"] <= \
            summ._cache_eligible // self.CHUNK + 1
