"""Deployment manifests (deploy/k8s, deploy/docker-compose.yml) stay
consistent with the CLI they invoke and the config files they mount —
the role of the reference's compose/CI manifest checks."""

from __future__ import annotations

import pathlib

import pytest

yaml = pytest.importorskip("yaml")

REPO = pathlib.Path(__file__).resolve().parent.parent
K8S = REPO / "deploy" / "k8s"

# Subcommands the package CLI actually exposes (__main__.py).
CLI_SUBCOMMANDS = {"serve", "broker", "retry-job", "failed-queues",
                   "logmine", "logstore", "exporters", "export-data",
                   "import-data"}


def _is_copilot(container: dict) -> bool:
    """Off-the-shelf observability images (prometheus/grafana/...) have
    their own CLIs; the subcommand/volume contracts apply only to
    containers running the package image."""
    return container.get("image", "").startswith("copilot")


def _docs():
    out = []
    for f in sorted(K8S.glob("*.yaml")):
        if f.name == "kustomization.yaml":
            continue
        for doc in yaml.safe_load_all(f.read_text()):
            if doc:
                out.append((f.name, doc))
    assert out, "no k8s manifests found"
    return out


def _pod_specs():
    for name, doc in _docs():
        kind = doc.get("kind")
        spec = doc.get("spec", {})
        if kind in ("Deployment", "StatefulSet"):
            yield name, doc, spec["template"]["spec"]
        elif kind == "CronJob":
            yield name, doc, (spec["jobTemplate"]["spec"]["template"]
                              ["spec"])


def test_manifests_parse_and_have_core_kinds():
    kinds = {doc["kind"] for _, doc in _docs()}
    assert {"StatefulSet", "Deployment", "CronJob", "Service",
            "PersistentVolumeClaim"} <= kinds


def test_container_args_are_real_cli_subcommands():
    for name, _, pod in _pod_specs():
        for c in pod["containers"]:
            if not _is_copilot(c):
                continue
            sub = c["args"][0]
            assert sub in CLI_SUBCOMMANDS, (name, sub)


def test_mounted_configs_exist_in_repo():
    """Every --config path a container passes must be provided by the
    kustomize configMap, which must map to a real file."""
    kust = yaml.safe_load((K8S / "kustomization.yaml").read_text())
    cm_files = set()
    for gen in kust["configMapGenerator"]:
        for p in gen["files"]:
            # paths are relative to the kustomization dir; each must be
            # a real repo file
            assert (K8S / p).resolve().exists(), p
            cm_files.add(pathlib.Path(p).name)
    for name, _, pod in _pod_specs():
        for c in pod["containers"]:
            args = c.get("args", [])
            if "--config" in args:
                cfg = pathlib.Path(args[args.index("--config") + 1])
                assert cfg.name in cm_files, (name, cfg)


def test_bus_host_resolves_to_a_k8s_service():
    """The bus host the shipped configs dial must be a Service name in
    the manifests, or every non-broker pod fails DNS and the stack
    comes up with zero message flow."""
    import json

    services = {doc["metadata"]["name"] for _, doc in _docs()
                if doc["kind"] == "Service"}
    for cfg_name in ("pipeline.json", "retry-job.json"):
        cfg = json.loads(
            (REPO / "deploy" / "config" / cfg_name).read_text())
        host = cfg.get("bus", {}).get("host")
        if host:
            assert host in services, (cfg_name, host, services)


def test_probes_hit_real_endpoints():
    """Liveness/readiness paths must be routes the server serves
    (/health, /readyz on the pipeline; /health on the exporter)."""
    for name, _, pod in _pod_specs():
        for c in pod["containers"]:
            for probe in ("readinessProbe", "livenessProbe"):
                http = c.get(probe, {}).get("httpGet")
                if http:
                    assert http["path"] in ("/health", "/readyz"), (
                        name, http["path"])


def test_stateful_roles_mount_the_shared_volume():
    """Role-split contract (deploy/README.md): every store-touching role
    (the ones that take --config, i.e. dial the document store) mounts
    the shared data volume. Observability pods keep their own state."""
    for name, doc, pod in _pod_specs():
        store_touching = any(
            _is_copilot(c) and "--config" in c.get("args", [])
            for c in pod["containers"])
        if not store_touching:
            continue
        mounts = {m["mountPath"] for c in pod["containers"]
                  for m in c.get("volumeMounts", [])}
        assert "/data" in mounts, name


def test_rwo_volume_mounters_coschedule_with_pipeline():
    """The shared data PVC is RWO block storage: every OTHER pod that
    mounts it must carry a hard podAffinity to the pipeline pod's node,
    or it deadlocks in Multi-Attach on any multi-node cluster."""
    for name, doc, pod in _pod_specs():
        labels = (doc.get("spec", {}).get("template", {})
                  .get("metadata", {}).get("labels", {})
                  or doc.get("spec", {}).get("jobTemplate", {})
                  .get("spec", {}).get("template", {})
                  .get("metadata", {}).get("labels", {}))
        mounts_data = any(
            v.get("persistentVolumeClaim", {}).get("claimName")
            == "copilot-data" for v in pod.get("volumes", []))
        if not mounts_data or labels.get("role") == "pipeline":
            continue
        rules = (pod.get("affinity", {}).get("podAffinity", {})
                 .get("requiredDuringSchedulingIgnoredDuringExecution"))
        assert rules, f"{name}: missing podAffinity to the pipeline pod"
        assert any(r["labelSelector"]["matchLabels"].get("role")
                   == "pipeline" for r in rules), name


def test_compose_services_restart():
    compose = yaml.safe_load(
        (REPO / "deploy" / "docker-compose.yml").read_text())
    for name, svc in compose["services"].items():
        assert svc.get("restart") == "unless-stopped", name
