#!/usr/bin/env python3
"""Generate config JSON schemas: per-service under
schemas/configs/services/, per-adapter-driver under
schemas/configs/adapters/<kind>/<driver>.schema.json.

Capability parity with the reference's schema-driven config layer
(``docs/schemas/configs/services/*.json``,
``docs/schemas/configs/adapters/drivers/*/*.json`` +
``generate_typed_configs.py``): each service gets a schema whose defaults
make ``get_config(service)`` work with zero config files — every adapter
defaults to its in-process/mock driver, mirroring the reference's
fake-backend test strategy (SURVEY.md §4) — and every registered driver
of every adapter kind gets a driver schema documenting its config keys
(coverage enforced by ``tests/test_schema_sync.py``).

Run: python scripts/generate_config_schemas.py
"""

from __future__ import annotations

import json
import pathlib

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT = REPO / "copilot_for_consensus_tpu" / "schemas" / "configs" / "services"
DRIVER_OUT = REPO / "copilot_for_consensus_tpu" / "schemas" / "configs" / "adapters"


def adapter(default_driver: str, **extra_defaults) -> dict:
    props: dict = {"driver": {"type": "string", "default": default_driver}}
    for key, value in extra_defaults.items():
        tname = {str: "string", int: "integer", float: "number", bool: "boolean",
                 list: "array", dict: "object"}[type(value)]
        props[key] = {"type": tname, "default": value}
    return {"type": "object", "properties": props, "additionalProperties": True}


COMMON = {
    "service_name": {"type": "string", "default": ""},
    "bus": adapter("inproc", exchange="copilot.events"),
    "document_store": adapter("memory"),
    "logger": adapter("stdout", level="info"),
    "metrics": adapter("inmemory", namespace="copilot"),
    "error_reporter": adapter("console"),
    "event_retry": adapter("default", max_attempts=8),
    "auth": {
        "type": "object",
        "properties": {
            "enabled": {"type": "boolean", "default": False},
            "jwks_url": {"type": "string", "default": ""},
            "issuer": {"type": "string", "default": ""},
            "audience": {"type": "string", "default": ""},
        },
        "additionalProperties": True,
    },
    "api": adapter("aiohttp", host="127.0.0.1", port=0),
}


def service_schema(name: str, extra: dict) -> dict:
    props = json.loads(json.dumps(COMMON))  # deep copy
    props.update(extra)
    return {
        "$schema": "https://json-schema.org/draft/2020-12/schema",
        "$id": f"copilot-for-consensus-tpu/schemas/configs/services/{name}.schema.json",
        "title": f"{name} service config",
        "type": "object",
        "properties": props,
        "additionalProperties": True,
    }


SERVICES: dict[str, dict] = {
    "ingestion": {
        "archive_store": adapter("local", root="var/archives"),
        "scheduler": {
            "type": "object",
            "properties": {
                "enabled": {"type": "boolean", "default": False},
                "interval_seconds": {"type": "integer", "default": 3600},
            },
            "additionalProperties": True,
        },
    },
    "parsing": {
        "normalizer": {
            "type": "object",
            "properties": {
                "strip_html": {"type": "boolean", "default": True},
                "strip_signatures": {"type": "boolean", "default": True},
                "strip_quoted_replies": {"type": "boolean", "default": True},
            },
            "additionalProperties": True,
        },
    },
    "chunking": {
        "chunker": adapter(
            "token_window", chunk_size=384, overlap=50,
            min_chunk_tokens=100, max_chunk_tokens=512,
        ),
    },
    "embedding": {
        "vector_store": adapter("memory"),
        "embedding_backend": adapter("mock", model="tpu-minilm-384",
                                     batch_size=128, max_seq_len=256,
                                     dimension=384),
    },
    "orchestrator": {
        "vector_store": adapter("memory"),
        "embedding_backend": adapter("mock", model="tpu-minilm-384",
                                     batch_size=128, max_seq_len=256,
                                     dimension=384),
        "selection": {
            "type": "object",
            "properties": {
                "selector": {"type": "string", "default": "top_k_relevance"},
                "top_k": {"type": "integer", "default": 12},
                "context_window_tokens": {"type": "integer", "default": 3000},
                "candidate_multiplier": {"type": "integer", "default": 2},
                "min_chunks_per_thread": {"type": "integer", "default": 1},
            },
            "additionalProperties": True,
        },
    },
    "summarization": {
        "llm_backend": adapter("mock", model="tpu-mistral-7b",
                               max_new_tokens=512, temperature=0.2,
                               context_window_tokens=4096),
        "consensus_detector": adapter("heuristic"),
        "prompts": {
            "type": "object",
            "properties": {
                "system_file": {"type": "string", "default": ""},
                "user_file": {"type": "string", "default": ""},
            },
            "additionalProperties": True,
        },
        "rate_limit": adapter("default", max_retries=3, base_delay=1.0),
    },
    "reporting": {
        "webhooks": {
            "type": "array",
            "items": {"type": "object"},
            "default": [],
        },
        "page_size": {"type": "integer", "default": 20},
    },
    "auth": {
        "jwt_signer": adapter("local", algorithm="RS256",
                              issuer="copilot-tpu", audience="copilot",
                              token_ttl_seconds=3600),
        "oidc": {
            "type": "object",
            "properties": {
                "providers": {"type": "array", "items": {"type": "object"},
                              "default": []},
            },
            "additionalProperties": True,
        },
    },
    # The resident TPU engine process (no reference analogue — this replaces
    # the Ollama/llama.cpp containers with a first-party serving engine).
    "tpu_engine": {
        "mesh": {
            "type": "object",
            "properties": {
                "dp": {"type": "integer", "default": 1},
                "tp": {"type": "integer", "default": 1},
                "sp": {"type": "integer", "default": 1},
                "ep": {"type": "integer", "default": 1},
            },
            "additionalProperties": True,
        },
        "embedding_backend": adapter("tpu", model="tpu-minilm-384",
                                     batch_size=128, max_seq_len=256,
                                     dimension=384),
        "llm_backend": adapter("tpu", model="tpu-mistral-7b",
                               max_new_tokens=512, temperature=0.2),
        "serving": {
            "type": "object",
            "properties": {
                "max_batch_slots": {"type": "integer", "default": 8},
                "page_size": {"type": "integer", "default": 128},
                "max_pages_per_seq": {"type": "integer", "default": 32},
                "prefill_chunk": {"type": "integer", "default": 512},
            },
            "additionalProperties": True,
        },
    },
}


# ---------------------------------------------------------------------------
# Per-adapter-driver schemas. Keys mirror what each driver's constructor/
# factory actually reads (cited in each driver's source); the sync test
# asserts every driver registered via core.factory has a schema here.
# ---------------------------------------------------------------------------

_BROKER_KEYS = dict(address="", host="127.0.0.1", port=5700,
                    timeout_ms=5000, poll_interval_s=0.05, batch=16,
                    group="")

DRIVERS: dict[str, dict[str, dict]] = {
    "message_bus": {
        "inproc": dict(exchange="copilot.events", group=""),
        "broker": dict(_BROKER_KEYS),
        "zmq": dict(_BROKER_KEYS),          # config alias of broker
        "noop": {},
        "azure_servicebus": dict(namespace="", key_name="", key="",
                                 endpoint="", topic="copilot.events",
                                 group="", lock_duration_s=60,
                                 max_redeliveries=3, peek_timeout_s=1,
                                 poll_interval_s=0.05, timeout_s=30.0,
                                 auto_renew=True, retry_attempts=3,
                                 retry_backoff_s=0.3),
    },
    "document_store": {
        "memory": {},
        "sqlite": dict(path="var/documents.sqlite3"),
        "azure_cosmos": dict(account="", master_key="",
                             database="copilot", endpoint=""),
    },
    "vector_store": {
        "memory": dict(dimension=0, persist_path=""),
        "tpu": dict(dimension=0, dtype="bfloat16", persist_path=""),
        "native": dict(dimension=0, persist_path=""),
        "azure_ai_search": dict(endpoint="", api_key="",
                                index_name="embeddings", dimension=0,
                                filterable_keys=list(
                                    ("thread_id", "archive_id",
                                     "chunk_id", "message_doc_id")),
                                timeout_s=30.0),
    },
    "embedding_backend": {
        "mock": dict(dimension=32),
        "tpu": dict(model="minilm-l6", checkpoint="", batch_size=64),
        "openai": dict(base_url="", api_key="",
                       model="text-embedding-3-small", dimension=1536,
                       batch_size=256, api_version=""),
        "azure_openai": dict(base_url="", api_key="",
                             model="text-embedding-3-small",
                             dimension=1536, batch_size=256,
                             api_version="2024-02-01"),
    },
    "llm_backend": {
        "mock": dict(max_sentences=3),
        "tpu": dict(model="mistral-7b", max_new_tokens=256, num_slots=4,
                    max_len=4096, checkpoint="", long_context=False,
                    kv_dtype="", quantize="int8", profile_dir=""),
        "openai": dict(base_url="", api_key="", model="gpt-4o-mini",
                       temperature=0.2, max_tokens=512, api_version=""),
        "azure_openai": dict(base_url="", api_key="",
                             model="gpt-4o-mini", temperature=0.2,
                             max_tokens=512, api_version="2024-02-01"),
    },
    "chunker": {
        "token_window": dict(chunk_size=384, overlap=50,
                             min_chunk_tokens=100, max_chunk_tokens=512),
        "fixed_size": dict(chunk_chars=1500, overlap_chars=200),
        "semantic": dict(max_chunk_tokens=512, min_chunk_tokens=100),
    },
    "metrics": {
        "noop": {},
        "inmemory": dict(namespace="copilot"),
        "prometheus": dict(namespace="copilot"),
        "pushgateway": dict(gateway_url="http://localhost:9091",
                            job="copilot", namespace="copilot"),
        "azure_monitor": dict(connection_string="",
                              namespace="copilot",
                              export_interval_s=60.0,
                              raise_on_error=False),
    },
    "logger": {
        "stdout": dict(service="", level="info"),
        "memory": dict(service="", level="info"),
        "silent": {},
        "shipping": dict(service="", level="info",
                         host="127.0.0.1", port=5140),
    },
    "error_reporter": {"console": {}, "silent": {}, "collecting": {},
                   "http": dict(endpoint="", release="",
                                environment="production",
                                min_interval_s=60.0)},
    "archive_fetcher": {
        "local": {}, "http": {}, "imap": {}, "rsync": {}, "mock": {},
    },
    "archive_store": {
        "memory": {},
        "azure_blob": dict(account="", container="archives",
                           account_key="", sas_token="", endpoint=""),
        "local": dict(root="var/archives"),
        "document": {},
    },
    "consensus_detector": {
        "heuristic": {}, "mock": {}, "embedding": {},
    },
    "draft_diff_provider": {"mock": {}, "local": {}, "datatracker": {}},
    "secret_provider": {
        "env": {},
        "local": dict(root="secrets"),
        "static": dict(values={}),
        "default": dict(root="secrets"),
        "azure_keyvault": dict(vault_url="", tenant_id="", client_id="",
                               client_secret="",
                               authority="https://login.microsoftonline.com"),
    },
    "jwt_signer": {
        "local_rs256": dict(private_pem=""),
        "hs256": dict(secret=""),
        "azure_keyvault": dict(
            vault_url="", key_name="", key_version="", tenant_id="",
            client_id="", client_secret="",
            authority="https://login.microsoftonline.com"),
    },
    "oidc_provider": {
        name: dict(client_id="", client_secret="", redirect_uri="")
        for name in ("github", "google", "microsoft", "datatracker", "mock")
    },
    "event_retry": {
        "default": dict(max_attempts=8, base_delay=0.05, max_delay=5.0,
                        jitter="full"),
        "noop": {},
    },
}


# Keys the factory hard-requires (construction raises without them) —
# the schema must not promise a config shape the factory rejects.
REQUIRED_KEYS: dict[tuple[str, str], list[str]] = {
    ("error_reporter", "http"): ["endpoint"],
    ("embedding_backend", "openai"): ["base_url"],
    ("embedding_backend", "azure_openai"): ["base_url"],
    ("llm_backend", "openai"): ["base_url"],
    ("llm_backend", "azure_openai"): ["base_url"],
    ("archive_store", "azure_blob"): ["account"],
    ("document_store", "azure_cosmos"): ["account", "master_key"],
    ("message_bus", "azure_servicebus"): ["key"],
    ("vector_store", "azure_ai_search"): ["endpoint", "api_key",
                                          "dimension"],
    ("metrics", "azure_monitor"): ["connection_string"],
    ("jwt_signer", "azure_keyvault"): ["vault_url", "key_name",
                                       "tenant_id", "client_id",
                                       "client_secret"],
    ("secret_provider", "azure_keyvault"): ["vault_url", "tenant_id", "client_id", "client_secret"],
}


def driver_schema(kind: str, name: str, keys: dict) -> dict:
    props: dict = {"driver": {"const": name}}
    required = ["driver"] + REQUIRED_KEYS.get((kind, name), [])
    for key, value in keys.items():
        tname = {str: "string", int: "integer", float: "number",
                 bool: "boolean", list: "array", dict: "object"}[type(value)]
        props[key] = {"type": tname}
        if key not in required:
            props[key]["default"] = value
    return {
        "$schema": "https://json-schema.org/draft/2020-12/schema",
        "$id": ("copilot-for-consensus-tpu/schemas/configs/adapters/"
                f"{kind}/{name}.schema.json"),
        "title": f"{kind} driver: {name}",
        "type": "object",
        "properties": props,
        "required": required,
        "additionalProperties": True,
    }


def main() -> None:
    OUT.mkdir(parents=True, exist_ok=True)
    for name, extra in SERVICES.items():
        path = OUT / f"{name}.schema.json"
        path.write_text(json.dumps(service_schema(name, extra), indent=2) + "\n")
        print(f"wrote {path.relative_to(REPO)}")
    for kind, drivers in DRIVERS.items():
        kind_dir = DRIVER_OUT / kind
        kind_dir.mkdir(parents=True, exist_ok=True)
        for name, keys in drivers.items():
            path = kind_dir / f"{name}.schema.json"
            path.write_text(
                json.dumps(driver_schema(kind, name, keys), indent=2) + "\n")
            print(f"wrote {path.relative_to(REPO)}")


if __name__ == "__main__":
    main()
