#!/usr/bin/env python3
"""Generate per-service config JSON schemas under schemas/configs/services/.

Capability parity with the reference's schema-driven config layer
(``docs/schemas/configs/services/*.json`` + ``generate_typed_configs.py``):
each service gets a schema whose defaults make ``get_config(service)`` work
with zero config files — every adapter defaults to its in-process/mock
driver, mirroring the reference's fake-backend test strategy (SURVEY.md §4).

Run: python scripts/generate_config_schemas.py
"""

from __future__ import annotations

import json
import pathlib

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT = REPO / "copilot_for_consensus_tpu" / "schemas" / "configs" / "services"


def adapter(default_driver: str, **extra_defaults) -> dict:
    props: dict = {"driver": {"type": "string", "default": default_driver}}
    for key, value in extra_defaults.items():
        tname = {str: "string", int: "integer", float: "number", bool: "boolean",
                 list: "array", dict: "object"}[type(value)]
        props[key] = {"type": tname, "default": value}
    return {"type": "object", "properties": props, "additionalProperties": True}


COMMON = {
    "service_name": {"type": "string", "default": ""},
    "bus": adapter("inproc", exchange="copilot.events"),
    "document_store": adapter("memory"),
    "logger": adapter("stdout", level="info"),
    "metrics": adapter("inmemory", namespace="copilot"),
    "error_reporter": adapter("console"),
    "event_retry": adapter("default", max_attempts=8),
    "auth": {
        "type": "object",
        "properties": {
            "enabled": {"type": "boolean", "default": False},
            "jwks_url": {"type": "string", "default": ""},
            "issuer": {"type": "string", "default": ""},
            "audience": {"type": "string", "default": ""},
        },
        "additionalProperties": True,
    },
    "api": adapter("aiohttp", host="127.0.0.1", port=0),
}


def service_schema(name: str, extra: dict) -> dict:
    props = json.loads(json.dumps(COMMON))  # deep copy
    props.update(extra)
    return {
        "$schema": "https://json-schema.org/draft/2020-12/schema",
        "$id": f"copilot-for-consensus-tpu/schemas/configs/services/{name}.schema.json",
        "title": f"{name} service config",
        "type": "object",
        "properties": props,
        "additionalProperties": True,
    }


SERVICES: dict[str, dict] = {
    "ingestion": {
        "archive_store": adapter("local", root="var/archives"),
        "scheduler": {
            "type": "object",
            "properties": {
                "enabled": {"type": "boolean", "default": False},
                "interval_seconds": {"type": "integer", "default": 3600},
            },
            "additionalProperties": True,
        },
    },
    "parsing": {
        "normalizer": {
            "type": "object",
            "properties": {
                "strip_html": {"type": "boolean", "default": True},
                "strip_signatures": {"type": "boolean", "default": True},
                "strip_quoted_replies": {"type": "boolean", "default": True},
            },
            "additionalProperties": True,
        },
    },
    "chunking": {
        "chunker": adapter(
            "token_window", chunk_size=384, overlap=50,
            min_chunk_tokens=100, max_chunk_tokens=512,
        ),
    },
    "embedding": {
        "vector_store": adapter("memory"),
        "embedding_backend": adapter("mock", model="tpu-minilm-384",
                                     batch_size=128, max_seq_len=256,
                                     dimension=384),
    },
    "orchestrator": {
        "vector_store": adapter("memory"),
        "embedding_backend": adapter("mock", model="tpu-minilm-384",
                                     batch_size=128, max_seq_len=256,
                                     dimension=384),
        "selection": {
            "type": "object",
            "properties": {
                "selector": {"type": "string", "default": "top_k_relevance"},
                "top_k": {"type": "integer", "default": 12},
                "context_window_tokens": {"type": "integer", "default": 3000},
                "candidate_multiplier": {"type": "integer", "default": 2},
                "min_chunks_per_thread": {"type": "integer", "default": 1},
            },
            "additionalProperties": True,
        },
    },
    "summarization": {
        "llm_backend": adapter("mock", model="tpu-mistral-7b",
                               max_new_tokens=512, temperature=0.2,
                               context_window_tokens=4096),
        "consensus_detector": adapter("heuristic"),
        "prompts": {
            "type": "object",
            "properties": {
                "system_file": {"type": "string", "default": ""},
                "user_file": {"type": "string", "default": ""},
            },
            "additionalProperties": True,
        },
        "rate_limit": adapter("default", max_retries=3, base_delay=1.0),
    },
    "reporting": {
        "webhooks": {
            "type": "array",
            "items": {"type": "object"},
            "default": [],
        },
        "page_size": {"type": "integer", "default": 20},
    },
    "auth": {
        "jwt_signer": adapter("local", algorithm="RS256",
                              issuer="copilot-tpu", audience="copilot",
                              token_ttl_seconds=3600),
        "oidc": {
            "type": "object",
            "properties": {
                "providers": {"type": "array", "items": {"type": "object"},
                              "default": []},
            },
            "additionalProperties": True,
        },
    },
    # The resident TPU engine process (no reference analogue — this replaces
    # the Ollama/llama.cpp containers with a first-party serving engine).
    "tpu_engine": {
        "mesh": {
            "type": "object",
            "properties": {
                "dp": {"type": "integer", "default": 1},
                "tp": {"type": "integer", "default": 1},
                "sp": {"type": "integer", "default": 1},
                "ep": {"type": "integer", "default": 1},
            },
            "additionalProperties": True,
        },
        "embedding_backend": adapter("tpu", model="tpu-minilm-384",
                                     batch_size=128, max_seq_len=256,
                                     dimension=384),
        "llm_backend": adapter("tpu", model="tpu-mistral-7b",
                               max_new_tokens=512, temperature=0.2),
        "serving": {
            "type": "object",
            "properties": {
                "max_batch_slots": {"type": "integer", "default": 8},
                "page_size": {"type": "integer", "default": 128},
                "max_pages_per_seq": {"type": "integer", "default": 32},
                "prefill_chunk": {"type": "integer", "default": 512},
            },
            "additionalProperties": True,
        },
    },
}


def main() -> None:
    OUT.mkdir(parents=True, exist_ok=True)
    for name, extra in SERVICES.items():
        path = OUT / f"{name}.schema.json"
        path.write_text(json.dumps(service_schema(name, extra), indent=2) + "\n")
        print(f"wrote {path.relative_to(REPO)}")


if __name__ == "__main__":
    main()
