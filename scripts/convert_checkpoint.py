#!/usr/bin/env python
"""Offline checkpoint converter: HF safetensors → native serving format.

Usage:
    python scripts/convert_checkpoint.py SRC_HF_DIR DST_DIR [--no-quantize]
        [--dtype bfloat16|float32|float16]

The native format is mmap-fast and (by default) int8 weight-only
quantized, so serving startup is seconds of reads instead of minutes of
device-side quantization (the role `ollama pull`'s GGUF blobs play for
the reference, `local_llm_summarizer.py:106-115`). Runs entirely on the
host — no accelerator needed.
"""
import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("src", help="HF checkpoint dir (config.json + "
                                "*.safetensors)")
    ap.add_argument("dst", help="output native checkpoint dir")
    ap.add_argument("--no-quantize", action="store_true",
                    help="keep full-precision weights")
    ap.add_argument("--weight-dtype", default="int8",
                    choices=("int8", "int4"),
                    help="quantized serving dtype (int4 = group-wise "
                         "packed nibbles, half the HBM of int8)")
    ap.add_argument("--dtype", default="bfloat16",
                    choices=("bfloat16", "float32", "float16"))
    args = ap.parse_args()

    from copilot_for_consensus_tpu.checkpoint import convert

    meta = convert(args.src, args.dst,
                   quantize=False if args.no_quantize else args.weight_dtype,
                   dtype=args.dtype)
    print(json.dumps(meta, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
