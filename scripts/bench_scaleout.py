"""Multi-process serving efficiency: same global mesh, 1 vs 2 processes.

The serving engine runs SPMD over a dp x tp mesh; with 2 processes the
same programs execute multi-controller and every collective + harvest
crosses the process boundary through the distributed runtime (the DCN
tier on localhost). The ratio

    eff = tok_s(2 procs, 2+2 devices) / tok_s(1 proc, 4 devices)

isolates the multi-controller LOCKSTEP overhead (coordination, cross-
process collectives, allgather harvest) from compute, because compute
is identical. On CPU this is an upper bound on the overhead fraction —
real ICI collectives are faster than localhost gRPC, real TPU compute
is faster than CPU, so the measured overhead seconds here are
pessimistic in absolute terms.

Usage: python scripts/bench_scaleout.py [--model tiny] [--slots 8]
       [--new-tokens 32] [--reps 3]
Prints one JSON line per configuration + a final summary line.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import socket
import subprocess
import sys
import textwrap

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

_WORKER = textwrap.dedent("""
    import json, os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=@LOCAL@"
    sys.path.insert(0, "@REPO@")
    rank, nprocs = int(sys.argv[1]), int(sys.argv[2])
    model, slots, new_tokens, reps = (sys.argv[3], int(sys.argv[4]),
                                      int(sys.argv[5]), int(sys.argv[6]))
    if nprocs > 1:
        from copilot_for_consensus_tpu.parallel.multihost import (
            MultiHostConfig, initialize_multihost)
        initialize_multihost(MultiHostConfig(
            coordinator_address="@COORD@", num_processes=nprocs,
            process_id=rank))
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from copilot_for_consensus_tpu.engine.generation import (
        GenerationEngine)
    from copilot_for_consensus_tpu.models import decoder
    from copilot_for_consensus_tpu.models.configs import decoder_config

    cfg = decoder_config(model)
    params = decoder.init_params(jax.random.PRNGKey(7), cfg,
                                 dtype=jnp.float32)
    devs = np.array(jax.devices())
    mesh = Mesh(devs.reshape(2, len(devs) // 2), ("dp", "tp"))
    eng = GenerationEngine(cfg, params, mesh=mesh, num_slots=slots,
                           max_len=96, prefill_buckets=(16,),
                           dtype=jnp.float32, attn_impl="xla",
                           decode_window=8)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(3, cfg.vocab_size, size=12).tolist()
               for _ in range(slots)]
    eng.generate(prompts, max_new_tokens=new_tokens)      # compile
    best = None
    for _ in range(reps):
        t0 = time.monotonic()
        comps = eng.generate(prompts, max_new_tokens=new_tokens)
        dt = time.monotonic() - t0
        n = sum(len(c.tokens) for c in comps)
        best = max(best or 0.0, n / dt)
    print(json.dumps({"rank": rank, "tok_s": round(best, 1)}),
          flush=True)
""")


def _run(nprocs: int, local_devs: int, args) -> float:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    src = (_WORKER.replace("@REPO@", str(REPO))
           .replace("@COORD@", coord)
           .replace("@LOCAL@", str(local_devs)))
    script = REPO / "scripts" / f"_scaleout_worker_{nprocs}.py"
    script.write_text(src)
    try:
        procs = [subprocess.Popen(
            [sys.executable, str(script), str(rank), str(nprocs),
             args.model, str(args.slots), str(args.new_tokens),
             str(args.reps)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env={"PATH": "/usr/bin:/bin:/usr/local/bin"})
            for rank in range(nprocs)]
        tok_s = 0.0
        for p in procs:
            out, err = p.communicate(timeout=900)
            if p.returncode != 0:
                raise RuntimeError(err[-2000:])
            row = json.loads(out.strip().splitlines()[-1])
            if row["rank"] == 0:
                tok_s = row["tok_s"]
        return tok_s
    finally:
        script.unlink(missing_ok=True)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="tiny")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--new-tokens", type=int, default=32)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    one = _run(1, 4, args)      # 1 process, 4 local devices
    print(json.dumps({"config": "1proc_4dev", "tok_s": one}), flush=True)
    two = _run(2, 2, args)      # 2 processes x 2 local devices
    print(json.dumps({"config": "2proc_2+2dev", "tok_s": two}),
          flush=True)
    print(json.dumps({
        "metric": f"{args.model} serving scale-out efficiency "
                  "(2-process multi-controller vs single-process, "
                  "same 2x2 mesh, CPU)",
        "value": round(two / one, 3) if one else 0.0,
        "unit": "fraction",
        "tok_s_1proc": one, "tok_s_2proc": two,
    }))


if __name__ == "__main__":
    main()
